"""torch.fx frontend: import a PyTorch nn.Module into an FFModel graph.

Reference analog: python/flexflow/torch/model.py (2607 LoC — `PyTorchModel`
at :2408, `_trace_model` at :2427, ~60 per-op Node subclasses with `to_ff()`
emitters and a "; "-delimited string format). This rebuild keeps the public
surface (PyTorchModel / torch_to_ff / torch_to_string / torch_to_file /
file_to_ff) but replaces the node-class hierarchy with dispatch tables over
fx node targets, plus import-time constant folding: values flowing through
the importer are either FFModel Tensors or concrete Python/numpy values
(shapes from .size(), buffers, traced literals), and handlers fold
concrete-only expressions eagerly instead of emitting graph ops.

The serialized format is JSON-lines (one node per line), not the reference's
positional strings; file_to_ff replays it without torch installed.
"""

from __future__ import annotations

import json
import math
import operator
from typing import Any, Callable, Dict, List, Optional

import numpy as np

from flexflow_tpu.core.tensor import Tensor

# ---------------------------------------------------------------------------
# module specs: a call_module fx node is reduced at trace time to a plain
# dict {"cls": ..., **config} so that live import and file replay share one
# handler per module class and file replay needs no torch.
# ---------------------------------------------------------------------------


def _pair(v):
    return (v, v) if isinstance(v, int) else tuple(v)


def module_to_spec(module) -> Dict[str, Any]:
    import torch.nn as nn

    m = module
    if isinstance(m, nn.Linear):
        return {"cls": "Linear", "out_features": m.out_features,
                "bias": m.bias is not None}
    try:
        from transformers.pytorch_utils import Conv1D as HFConv1D
    except Exception:
        HFConv1D = ()
    if HFConv1D and isinstance(m, HFConv1D):
        return {"cls": "HFConv1D", "out_features": m.nf, "bias": True}
    if isinstance(m, nn.Conv2d):
        if _pair(m.dilation) != (1, 1):
            raise NotImplementedError("dilated conv")
        return {"cls": "Conv2d", "out_channels": m.out_channels,
                "kernel_size": _pair(m.kernel_size), "stride": _pair(m.stride),
                "padding": _pair(m.padding), "groups": m.groups,
                "bias": m.bias is not None}
    if isinstance(m, nn.MaxPool2d):
        if m.ceil_mode or _pair(m.dilation) != (1, 1) or m.return_indices:
            raise NotImplementedError(
                "MaxPool2d ceil_mode/dilation/return_indices import unsupported "
                "(would silently change output shapes/values)")
        return {"cls": "Pool2d", "pool_type": "max",
                "kernel_size": _pair(m.kernel_size),
                "stride": _pair(m.stride or m.kernel_size),
                "padding": _pair(m.padding)}
    if isinstance(m, nn.AvgPool2d):
        if m.ceil_mode:
            raise NotImplementedError("AvgPool2d ceil_mode import unsupported")
        return {"cls": "Pool2d", "pool_type": "avg",
                "kernel_size": _pair(m.kernel_size),
                "stride": _pair(m.stride or m.kernel_size),
                "padding": _pair(m.padding)}
    if isinstance(m, nn.AdaptiveAvgPool2d):
        return {"cls": "AdaptiveAvgPool2d", "output_size": _pair(m.output_size)}
    if isinstance(m, nn.BatchNorm2d):
        return {"cls": "BatchNorm2d", "eps": m.eps,
                "momentum": 1.0 - (m.momentum or 0.1)}
    if isinstance(m, nn.LayerNorm):
        return {"cls": "LayerNorm", "eps": m.eps,
                "n_axes": len(m.normalized_shape),
                "affine": m.elementwise_affine}
    if isinstance(m, nn.Embedding):
        return {"cls": "Embedding", "num_embeddings": m.num_embeddings,
                "embedding_dim": m.embedding_dim}
    if isinstance(m, nn.Dropout):
        return {"cls": "Dropout", "p": m.p}
    if isinstance(m, nn.Softmax):
        return {"cls": "Softmax", "dim": m.dim if m.dim is not None else -1}
    if isinstance(m, nn.LogSoftmax):
        return {"cls": "LogSoftmax", "dim": m.dim if m.dim is not None else -1}
    if isinstance(m, nn.Flatten):
        return {"cls": "Flatten", "start_dim": m.start_dim, "end_dim": m.end_dim}
    if isinstance(m, nn.MultiheadAttention):
        return {"cls": "MultiheadAttention", "embed_dim": m.embed_dim,
                "num_heads": m.num_heads, "dropout": m.dropout,
                "bias": m.in_proj_bias is not None,
                "add_bias_kv": m.bias_k is not None,
                "add_zero_attn": m.add_zero_attn,
                "batch_first": m.batch_first}
    for cls, tag in ((nn.ReLU, "ReLU"), (nn.GELU, "GELU"), (nn.SiLU, "SiLU"),
                     (nn.Sigmoid, "Sigmoid"), (nn.Tanh, "Tanh"), (nn.ELU, "ELU"),
                     (nn.Identity, "Identity")):
        if isinstance(m, cls):
            return {"cls": tag}
    raise NotImplementedError(f"no FFModel mapping for module {type(m).__name__}")


def _flatten_dims(ff, x, start, end, name):
    nd = x.ndim
    start %= nd
    end %= nd
    if start == end:
        return x
    shape = (list(x.shape[:start])
             + [int(np.prod(x.shape[start:end + 1]))]
             + list(x.shape[end + 1:]))
    return ff.reshape(x, shape, name=name)


def _h_linear(im, spec, args, name):
    return im.ff.dense(im.as_tensor(args[0]), spec["out_features"],
                       use_bias=spec["bias"], name=name)


def _h_conv2d(im, spec, args, name):
    kh, kw = spec["kernel_size"]
    sh, sw = spec["stride"]
    ph, pw = spec["padding"]
    return im.ff.conv2d(im.as_tensor(args[0]), spec["out_channels"], kh, kw,
                        sh, sw, ph, pw, groups=spec["groups"],
                        use_bias=spec["bias"], name=name)


def _h_pool2d(im, spec, args, name):
    kh, kw = spec["kernel_size"]
    sh, sw = spec["stride"]
    ph, pw = spec["padding"]
    return im.ff.pool2d(im.as_tensor(args[0]), kh, kw, sh, sw, ph, pw,
                        pool_type=spec["pool_type"], name=name)


def _h_adaptive_pool(im, spec, args, name):
    x = im.as_tensor(args[0])
    oh, ow = spec["output_size"]
    h, w = x.shape[2], x.shape[3]
    if h % oh or w % ow:
        raise NotImplementedError(f"adaptive pool {h}x{w} -> {oh}x{ow}")
    return im.ff.pool2d(x, h // oh, w // ow, h // oh, w // ow, 0, 0,
                        pool_type="avg", name=name)


MODULE_HANDLERS: Dict[str, Callable] = {
    "Linear": _h_linear,
    "HFConv1D": _h_linear,  # GPT-2's Conv1D == Linear with (in,out) weight
    "Conv2d": _h_conv2d,
    "Pool2d": _h_pool2d,
    "AdaptiveAvgPool2d": _h_adaptive_pool,
    "BatchNorm2d": lambda im, s, a, name: im.ff.batch_norm(
        im.as_tensor(a[0]), relu=False, momentum=s["momentum"], eps=s["eps"], name=name),
    "LayerNorm": lambda im, s, a, name: im.ff.layer_norm(
        im.as_tensor(a[0]), axes=list(range(-s["n_axes"], 0)),
        elementwise_affine=s["affine"], eps=s["eps"], name=name),
    "Embedding": lambda im, s, a, name: im.ff.embedding(
        im.as_tensor(a[0]), s["num_embeddings"], s["embedding_dim"], name=name),
    "Dropout": lambda im, s, a, name: im.ff.dropout(
        im.as_tensor(a[0]), rate=s["p"], name=name),
    "Softmax": lambda im, s, a, name: im.ff.softmax(
        im.as_tensor(a[0]), axis=s["dim"], name=name),
    "LogSoftmax": lambda im, s, a, name: im.ff.log_softmax(
        im.as_tensor(a[0]), axis=s["dim"], name=name),
    "Flatten": lambda im, s, a, name: _flatten_dims(
        im.ff, im.as_tensor(a[0]), s["start_dim"], s["end_dim"], name),
    "ReLU": lambda im, s, a, name: im.ff.relu(im.as_tensor(a[0]), name=name),
    "GELU": lambda im, s, a, name: im.ff.gelu(im.as_tensor(a[0]), name=name),
    "SiLU": lambda im, s, a, name: im.ff.silu(im.as_tensor(a[0]), name=name),
    "Sigmoid": lambda im, s, a, name: im.ff.sigmoid(im.as_tensor(a[0]), name=name),
    "Tanh": lambda im, s, a, name: im.ff.tanh(im.as_tensor(a[0]), name=name),
    "ELU": lambda im, s, a, name: im.ff.elu(im.as_tensor(a[0]), name=name),
    "Identity": lambda im, s, a, name: im.as_tensor(a[0]),
}


def _h_mha(im, spec, args, kwargs, name):
    # forward(q, k, v, key_padding_mask=None, need_weights=True,
    #         attn_mask=None, average_attn_weights=True, is_causal=False)
    def arg(pos, kw, default=None):
        if len(args) > pos:
            return args[pos]
        return kwargs.get(kw, default)

    # masks would be silently dropped (unmasked attention with wrong
    # numerics) — fail loudly instead, like dilated conv / strided slices
    if arg(3, "key_padding_mask") is not None or arg(5, "attn_mask") is not None:
        raise NotImplementedError(
            "MultiheadAttention attn_mask/key_padding_mask import unsupported; "
            "use is_causal=True or drop the mask")
    is_causal = bool(arg(7, "is_causal", False))
    q, k, v = (im.as_tensor(a) for a in args[:3])
    if not spec["batch_first"]:
        # our MHA is batch-first; transpose in and out
        q = im.ff.transpose(q, (1, 0, 2), name=f"{name}_qT")
        k = im.ff.transpose(k, (1, 0, 2), name=f"{name}_kT")
        v = im.ff.transpose(v, (1, 0, 2), name=f"{name}_vT")
    out = im.ff.multihead_attention(
        q, k, v, spec["embed_dim"], spec["num_heads"], dropout=spec["dropout"],
        bias=spec["bias"], add_bias_kv=spec["add_bias_kv"],
        add_zero_attn=spec["add_zero_attn"], causal=is_causal, name=name)
    if not spec["batch_first"]:
        out = im.ff.transpose(out, (1, 0, 2), name=f"{name}_oT")
    # torch returns (attn_output, attn_weights); weights path unsupported
    return (out, None)


MODULE_HANDLERS["MultiheadAttention"] = _h_mha  # takes kwargs (special-cased)

# ---------------------------------------------------------------------------
# function / method handlers. Values are Tensor or concrete (int/float/tuple/
# np.ndarray). Concrete-only expressions fold eagerly.
# ---------------------------------------------------------------------------


def _is_t(v) -> bool:
    return isinstance(v, Tensor)


def _np(v):
    # avoid importing torch on the replay path (file_to_ff runs torch-less)
    if type(v).__module__.startswith("torch"):
        return v.detach().cpu().numpy()
    return np.asarray(v)


class _Finfo:
    def __init__(self, dtype=None):
        npdt = np.float32
        if dtype is not None:
            s = str(dtype).replace("torch.", "")
            npdt = {"float16": np.float16, "half": np.float16,
                    "float64": np.float64}.get(s, np.float32)
        self.min = float(np.finfo(npdt).min)
        self.max = float(np.finfo(npdt).max)
        self.eps = float(np.finfo(npdt).eps)


def _as_torch_dtype(v):
    """Accept torch.dtype, flexflow DataType, or string."""
    import torch as _torch

    from flexflow_tpu.dtype import DataType as _DT

    if isinstance(v, _torch.dtype):
        return v
    if isinstance(v, _DT):
        return getattr(_torch, _DTYPE_ALIAS.get(v.value, v.value))
    if isinstance(v, str):
        return getattr(_torch, _DTYPE_ALIAS.get(v, v))
    return v


def _binary(im, op_t, op_s, fold, a, b, name):
    """Dispatch tensor/tensor, tensor/scalar, scalar-only binary ops."""
    if _is_t(a) and _is_t(b):
        return op_t(a, b, name=name)
    if _is_t(a) and isinstance(b, (int, float)):
        return op_s(im, a, float(b), False, name)
    if _is_t(b) and isinstance(a, (int, float)):
        return op_s(im, b, float(a), True, name)
    if _is_t(a) or _is_t(b):
        # tensor op ndarray constant: materialize the constant
        ta = a if _is_t(a) else im.ff.constant(_np(a), name=f"{name}_c")
        tb = b if _is_t(b) else im.ff.constant(_np(b), name=f"{name}_c")
        return op_t(ta, tb, name=name)
    return fold(a, b)


def _scalar_add(im, x, s, rev, name):
    return im.ff.scalar_add(x, s, name=name)


def _scalar_sub(im, x, s, rev, name):
    if rev:  # s - x
        neg = im.ff.scalar_multiply(x, -1.0, name=f"{name}_neg")
        return im.ff.scalar_add(neg, s, name=name)
    return im.ff.scalar_sub(x, s, name=name)


def _scalar_mul(im, x, s, rev, name):
    return im.ff.scalar_multiply(x, s, name=name)


def _scalar_div(im, x, s, rev, name):
    if rev:  # s / x
        inv = im.ff.pow(x, -1.0, name=f"{name}_inv")
        return im.ff.scalar_multiply(inv, s, name=name)
    return im.ff.scalar_true_divide(x, s, name=name)


def _h_add(im, args, kwargs, name):
    return _binary(im, im.ff.add, _scalar_add, operator.add, args[0], args[1], name)


def _h_sub(im, args, kwargs, name):
    return _binary(im, im.ff.subtract, _scalar_sub, operator.sub, args[0], args[1], name)


def _h_mul(im, args, kwargs, name):
    return _binary(im, im.ff.multiply, _scalar_mul, operator.mul, args[0], args[1], name)


def _h_div(im, args, kwargs, name):
    return _binary(im, im.ff.divide, _scalar_div, operator.truediv, args[0], args[1], name)


def _h_eq(im, args, kwargs, name):
    a, b = args[0], args[1]
    if not (_is_t(a) or _is_t(b)):
        return a == b
    ta = a if _is_t(a) else im.ff.constant(_np(a), name=f"{name}_c")
    tb = b if _is_t(b) else im.ff.constant(_np(b), name=f"{name}_c")
    return im.ff._binary(im.ff_optype.EW_EQUAL, ta, tb, name=name)


def _h_getitem(im, args, kwargs, name):
    obj, idx = args[0], args[1]
    if not _is_t(obj):
        if isinstance(obj, np.ndarray):
            return obj[idx if not isinstance(idx, list) else tuple(idx)]
        return obj[idx]
    # tensor indexing: ints / slices / None (unsqueeze) / Ellipsis
    # (tuples arrive as lists after serialization)
    if isinstance(idx, list):
        idx = tuple(idx)
    if not isinstance(idx, tuple):
        idx = (idx,)
    if Ellipsis in idx:
        pos = idx.index(Ellipsis)
        n_explicit = sum(1 for i in idx if i is not Ellipsis and i is not None)
        fill = obj.ndim - n_explicit
        idx = idx[:pos] + (slice(None),) * fill + idx[pos + 1:]
    starts, limits, squeeze_dims, unsqueeze_positions = [], [], [], []
    d = 0
    out_pos = 0
    for it in idx:
        if it is None:
            unsqueeze_positions.append(out_pos)
            out_pos += 1
            continue
        if isinstance(it, int):
            lo = it % obj.shape[d]
            starts.append(lo)
            limits.append(lo + 1)
            squeeze_dims.append(d)
            d += 1
            continue
        if isinstance(it, slice):
            lo, hi, step = it.indices(obj.shape[d])
            if step != 1:
                raise NotImplementedError("strided tensor slice")
            starts.append(lo)
            limits.append(hi)
            d += 1
            out_pos += 1
            continue
        raise NotImplementedError(f"tensor getitem index {it!r}")
    while d < obj.ndim:
        starts.append(0)
        limits.append(obj.shape[d])
        d += 1
        out_pos += 1
    x = obj
    if any(lo != 0 for lo in starts) or any(
            hi != s for hi, s in zip(limits, obj.shape)):
        x = im.ff.slice_tensor(x, starts, limits, name=f"{name}_sl")
    final = [d2 for d2 in range(obj.ndim) if d2 not in squeeze_dims]
    shape = [x.shape[d2] for d2 in final]
    for p in unsqueeze_positions:
        shape.insert(p, 1)
    if tuple(shape) != x.shape:
        x = im.ff.reshape(x, shape, name=f"{name}_rs")
    return x


def _h_matmul(im, args, kwargs, name):
    a, b = im.as_tensor(args[0]), im.as_tensor(args[1])
    return im.ff.batch_matmul(a, b, name=name)


def _h_cat(im, args, kwargs, name):
    tensors = [im.as_tensor(t) for t in args[0]]
    axis = args[1] if len(args) > 1 else kwargs.get("dim", 0)
    return im.ff.concat(tensors, axis=axis, name=name)


def _h_split(im, args, kwargs, name):
    x = im.as_tensor(args[0])
    size = args[1]
    axis = args[2] if len(args) > 2 else kwargs.get("dim", 0)
    if isinstance(size, int):
        d = x.shape[axis % x.ndim]
        n = (d + size - 1) // size
        sizes = [size] * (n - 1) + [d - size * (n - 1)]
    else:
        sizes = list(size)
    return tuple(im.ff.split(x, sizes, axis=axis, name=name))


def _h_chunk(im, args, kwargs, name):
    x = im.as_tensor(args[0])
    n = args[1]
    axis = args[2] if len(args) > 2 else kwargs.get("dim", 0)
    d = x.shape[axis % x.ndim]
    if d % n == 0:
        return tuple(im.ff.split(x, n, axis=axis, name=name))
    # torch.chunk semantics for non-divisible dims: ceil-div chunk size,
    # smaller final chunk, possibly fewer than n chunks
    size = -(-d // n)
    sizes = []
    rem = d
    while rem > 0:
        sizes.append(min(size, rem))
        rem -= size
    return tuple(im.ff.split(x, sizes, axis=axis, name=name))


def _h_flatten(im, args, kwargs, name):
    x = im.as_tensor(args[0])
    start = args[1] if len(args) > 1 else kwargs.get("start_dim", 0)
    end = args[2] if len(args) > 2 else kwargs.get("end_dim", -1)
    return _flatten_dims(im.ff, x, start, end, name)


def _h_transpose(im, args, kwargs, name):
    x, d0, d1 = args[0], args[1], args[2]
    if not _is_t(x):
        return np.swapaxes(_np(x), d0, d1)
    perm = list(range(x.ndim))
    perm[d0 % x.ndim], perm[d1 % x.ndim] = perm[d1 % x.ndim], perm[d0 % x.ndim]
    return im.ff.transpose(x, perm, name=name)


def _h_permute(im, args, kwargs, name):
    x = im.as_tensor(args[0])
    perm = args[1] if len(args) == 2 and isinstance(args[1], (list, tuple)) \
        else args[1:]
    return im.ff.transpose(x, tuple(perm), name=name)


def _h_reshape(im, args, kwargs, name):
    x = args[0]
    shape = args[1] if len(args) == 2 and isinstance(args[1], (list, tuple)) \
        else args[1:]
    shape = tuple(int(s) for s in shape)
    if not _is_t(x):
        return _np(x).reshape(shape)
    return im.ff.reshape(x, shape, name=name)


def _h_unsqueeze(im, args, kwargs, name):
    x, dim = args[0], args[1]
    if not _is_t(x):
        return np.expand_dims(_np(x), dim)
    shape = list(x.shape)
    shape.insert(dim % (x.ndim + 1), 1)
    return im.ff.reshape(x, shape, name=name)


def _h_squeeze(im, args, kwargs, name):
    x = args[0]
    dim = args[1] if len(args) > 1 else kwargs.get("dim")
    if not _is_t(x):
        return np.squeeze(_np(x), dim)
    shape = [s for i, s in enumerate(x.shape)
             if not (s == 1 and (dim is None or i == dim % x.ndim))]
    return im.ff.reshape(x, shape, name=name)


def _h_mean(im, args, kwargs, name):
    x = im.as_tensor(args[0])
    dim = args[1] if len(args) > 1 else kwargs.get("dim")
    keep = kwargs.get("keepdim", args[2] if len(args) > 2 else False)
    axes = [dim] if isinstance(dim, int) else list(dim if dim is not None
                                                   else range(x.ndim))
    return im.ff.reduce_mean(x, axes, keepdims=keep, name=name)


def _h_pow(im, args, kwargs, name):
    x, e = args[0], args[1]
    if not _is_t(x):
        return _np(x) ** e
    return im.ff.pow(x, float(e), name=name)


def _h_softmax_f(im, args, kwargs, name):
    x = im.as_tensor(args[0])
    dim = kwargs.get("dim", args[1] if len(args) > 1 else -1)
    return im.ff.softmax(x, axis=dim if dim is not None else -1, name=name)


def _h_dropout_f(im, args, kwargs, name):
    x = im.as_tensor(args[0])
    p = kwargs.get("p", args[1] if len(args) > 1 else 0.5)
    return im.ff.dropout(x, rate=p, name=name)


def _h_sdpa(im, args, kwargs, name):
    q, k, v = (im.as_tensor(a) for a in args[:3])
    mask = kwargs.get("attn_mask", args[3] if len(args) > 3 else None)
    if mask is not None and not _is_t(mask):
        mask = im.ff.constant(_np(mask), name=f"{name}_mask")
    return im.ff.scaled_dot_product_attention(
        q, k, v, attn_mask=mask,
        dropout_p=kwargs.get("dropout_p", 0.0),
        is_causal=kwargs.get("is_causal", False),
        scale=kwargs.get("scale"), name=name)


def _h_where(im, args, kwargs, name):
    """torch.where(cond, a, b): a true SELECT (a blend would let NaN/inf in
    the unselected branch poison the result)."""
    cond = im.as_tensor(args[0])
    a, b = im.as_tensor(args[1]), im.as_tensor(args[2])
    return im.ff.where(cond, a, b, name=name)


def _h_masked_fill(im, args, kwargs, name):
    x, mask, value = args[0], args[1], args[2]
    x = im.as_tensor(x)
    mask = mask if _is_t(mask) else im.ff.constant(_np(mask), name=f"{name}_m")
    return im.ff.masked_fill(x, mask, float(value), name=name)


def _h_expand(im, args, kwargs, name):
    x = args[0]
    sizes = args[1] if len(args) == 2 and isinstance(args[1], (list, tuple)) \
        else args[1:]
    sizes = tuple(int(s) for s in sizes)
    if not _is_t(x):
        v = _np(x)
        shape = [v.shape[i - (len(sizes) - v.ndim)] if s == -1 else s
                 for i, s in enumerate(sizes)]
        return np.broadcast_to(v, shape)
    return im.ff.expand(x, sizes, name=name)


class _DTypeName(str):
    """Marker for dtype names decoded from a .ff file's "$dtype" records —
    distinguishes them from ordinary string args without importing torch."""


def _h_to(im, args, kwargs, name):
    from flexflow_tpu.dtype import DataType as _DT

    x = args[0]
    target = kwargs.get("dtype", args[1] if len(args) > 1 else None)
    dt = None
    if isinstance(target, _DTypeName):
        dt = str(target)
    elif isinstance(target, _DT):
        dt = target.value
    elif target is not None and type(target).__module__.startswith("torch"):
        dt = str(target).replace("torch.", "")
    if dt is None:
        return x  # device / copy moves are no-ops
    if not _is_t(x):
        return _np(x).astype(_TORCH_NP.get(dt, dt))
    return im.ff.cast(x, _DTYPE_ALIAS.get(dt, dt), name=name)


_TORCH_NP = {"float32": np.float32, "float64": np.float32, "float16": np.float16,
             "bfloat16": np.float32, "int64": np.int64, "int32": np.int32,
             "bool": np.bool_, "long": np.int64}
_DTYPE_ALIAS = {"float64": "float32", "long": "int64", "half": "float16"}


def _h_cast_to(dtype):
    def h(im, args, kwargs, name):
        x = args[0]
        if not _is_t(x):
            return _np(x).astype(_TORCH_NP[dtype])
        return im.ff.cast(x, _DTYPE_ALIAS.get(dtype, dtype), name=name)
    return h


def _h_new_tensor(ctor):
    def h(im, args, kwargs, name):
        import torch as _torch

        kwargs = {k: v for k, v in kwargs.items()
                  if k not in ("device", "requires_grad", "pin_memory", "layout")}
        if "dtype" in kwargs:
            kwargs["dtype"] = _as_torch_dtype(kwargs["dtype"])
        return _np(getattr(_torch, ctor)(*args, **kwargs))
    return h


def _unary_h(attr):
    def h(im, args, kwargs, name):
        x = args[0]
        if not _is_t(x):
            return getattr(np, attr if attr != "rsqrt" else "sqrt")(_np(x)) \
                if attr != "rsqrt" else 1.0 / np.sqrt(_np(x))
        return getattr(im.ff, attr)(x, name=name)
    return h


def build_function_handlers() -> Dict[Any, Callable]:
    import torch as _torch
    import torch.nn.functional as F

    h: Dict[Any, Callable] = {
        operator.add: _h_add, _torch.add: _h_add,
        operator.sub: _h_sub, _torch.sub: _h_sub,
        operator.mul: _h_mul, _torch.mul: _h_mul,
        operator.truediv: _h_div, _torch.div: _h_div,
        operator.floordiv: lambda im, a, k, n: a[0] // a[1],
        operator.pow: _h_pow, _torch.pow: _h_pow,
        operator.eq: _h_eq, operator.getitem: _h_getitem,
        operator.neg: lambda im, a, k, n: (
            -a[0] if not _is_t(a[0])
            else im.ff.scalar_multiply(a[0], -1.0, name=n)),
        getattr: lambda im, a, k, n: getattr(a[0], a[1]),
        _torch.matmul: _h_matmul, _torch.bmm: _h_matmul,
        _torch.cat: _h_cat, _torch.split: _h_split, _torch.chunk: _h_chunk,
        _torch.flatten: _h_flatten, _torch.transpose: _h_transpose,
        _torch.permute: _h_permute, _torch.reshape: _h_reshape,
        _torch.unsqueeze: _h_unsqueeze, _torch.squeeze: _h_squeeze,
        _torch.mean: _h_mean, _torch.rsqrt: _unary_h("rsqrt"),
        _torch.tanh: _unary_h("tanh"), _torch.sigmoid: _unary_h("sigmoid"),
        _torch.exp: _unary_h("exp"), _torch.sqrt: _unary_h("sqrt"),
        _torch.relu: _unary_h("relu"),
        _torch.softmax: _h_softmax_f,
        _torch.where: lambda im, a, k, n: im.ff.masked_fill(
            im.as_tensor(a[2]), im.as_tensor(a[0]), float(a[1]))
            if isinstance(a[1], (int, float)) else _h_where(im, a, k, n),
        _torch.finfo: lambda im, a, k, n: _Finfo(*a),
        _torch.tensor: _h_new_tensor("tensor"),
        _torch.ones: _h_new_tensor("ones"), _torch.zeros: _h_new_tensor("zeros"),
        _torch.full: _h_new_tensor("full"), _torch.arange: _h_new_tensor("arange"),
        F.relu: _unary_h("relu"), F.gelu: lambda im, a, k, n: im.ff.gelu(
            im.as_tensor(a[0]), name=n),
        F.silu: _unary_h("silu"), F.sigmoid: _unary_h("sigmoid"),
        F.tanh: _unary_h("tanh"), F.elu: _unary_h("elu"),
        F.softmax: _h_softmax_f, F.log_softmax: lambda im, a, k, n:
            im.ff.log_softmax(im.as_tensor(a[0]),
                              axis=k.get("dim", a[1] if len(a) > 1 else -1), name=n),
        F.dropout: _h_dropout_f,
        F.scaled_dot_product_attention: _h_sdpa,
        math.sqrt: lambda im, a, k, n: math.sqrt(a[0]),
    }
    try:
        h[_torch._C._nn.scaled_dot_product_attention] = _h_sdpa
    except AttributeError:
        pass
    return h


METHOD_HANDLERS: Dict[str, Callable] = {
    "add": _h_add, "sub": _h_sub, "mul": _h_mul, "div": _h_div,
    "pow": _h_pow, "eq": _h_eq, "matmul": _h_matmul, "bmm": _h_matmul,
    "view": _h_reshape, "reshape": _h_reshape, "permute": _h_permute,
    "transpose": _h_transpose, "flatten": _h_flatten,
    "unsqueeze": _h_unsqueeze, "squeeze": _h_squeeze, "expand": _h_expand,
    "split": _h_split, "chunk": _h_chunk, "mean": _h_mean,
    "softmax": _h_softmax_f, "masked_fill": _h_masked_fill,
    "masked_fill_": _h_masked_fill, "to": _h_to,
    "float": _h_cast_to("float32"), "long": _h_cast_to("int64"),
    "int": _h_cast_to("int32"), "bool": _h_cast_to("bool"),
    "half": _h_cast_to("float16"), "rsqrt": _unary_h("rsqrt"),
    "tanh": _unary_h("tanh"), "sigmoid": _unary_h("sigmoid"),
    "exp": _unary_h("exp"), "sqrt": _unary_h("sqrt"),
    "contiguous": lambda im, a, k, n: a[0],
    "clone": lambda im, a, k, n: a[0],
    "detach": lambda im, a, k, n: a[0],
    "type_as": lambda im, a, k, n: a[0],
    "size": lambda im, a, k, n: (tuple(a[0].shape) if len(a) == 1
                                 else a[0].shape[a[1]]),
    "dim": lambda im, a, k, n: a[0].ndim,
    "numel": lambda im, a, k, n: int(np.prod(a[0].shape)),
    "t": lambda im, a, k, n: _h_transpose(im, (a[0], 0, 1), {}, n),
    "expand_as": lambda im, a, k, n: _h_expand(
        im, (a[0], tuple(a[1].shape)), {}, n),
}


# ---------------------------------------------------------------------------
# the importer
# ---------------------------------------------------------------------------


class _Importer:
    """Walks a serialized node list, emitting FFModel ops."""

    def __init__(self, ffmodel, input_tensors: List[Tensor], verbose=False):
        from flexflow_tpu.ops.op_type import OperatorType

        self.ff = ffmodel
        self.ff_optype = OperatorType
        self.inputs = list(input_tensors)
        self.env: Dict[str, Any] = {}
        self.outputs: List[Tensor] = []
        self.verbose = verbose
        self.layer_to_module: Dict[str, str] = {}  # ff layer name -> module path
        self._input_idx = 0
        self._fn_handlers = None

    def as_tensor(self, v) -> Tensor:
        if _is_t(v):
            return v
        return self.ff.constant(_np(v))

    def resolve(self, a):
        if isinstance(a, dict) and "$ref" in a:
            return self.env[a["$ref"]]
        if isinstance(a, dict) and "$nd" in a:
            return np.asarray(a["$nd"], dtype=a["$dt"])
        if isinstance(a, list):
            return [self.resolve(x) for x in a]
        if isinstance(a, tuple):
            return tuple(self.resolve(x) for x in a)
        if isinstance(a, dict) and "$slice" in a:
            lo, hi, st = (self.resolve(x) for x in a["$slice"])
            as_int = lambda v: int(v) if v is not None else None  # noqa: E731
            return slice(as_int(lo), as_int(hi), as_int(st))
        if isinstance(a, dict) and "$ellipsis" in a:
            return Ellipsis
        if isinstance(a, dict) and "$dtype" in a:
            # dtype-name marker string: keeps .ff replay torch-free
            # (_h_to recognizes _DTypeName unambiguously)
            return _DTypeName(a["$dtype"])
        if isinstance(a, dict) and "$dict" in a:
            return {k: self.resolve(v) for k, v in a["$dict"].items()}
        return a

    def run_node(self, rec: Dict[str, Any]):
        op, name = rec["op"], rec["name"]
        args = self.resolve(rec.get("args", []))
        kwargs = {k: self.resolve(v) for k, v in rec.get("kwargs", {}).items()}
        if self.verbose:
            print(json.dumps({k: v for k, v in rec.items() if k != "args"}))
        if op == "placeholder":
            if self._input_idx >= len(self.inputs):
                if rec.get("has_default"):
                    self.env[name] = self.resolve(rec["default"])
                    return
                raise ValueError(f"not enough input tensors for {name}")
            self.env[name] = self.inputs[self._input_idx]
            self._input_idx += 1
            return
        if op == "get_attr":
            self.env[name] = np.asarray(rec["value"], dtype=rec["vdtype"])
            return
        if op == "call_module":
            spec = rec["module"]
            handler = MODULE_HANDLERS[spec["cls"]]
            if spec["cls"] == "MultiheadAttention":
                out = handler(self, spec, args, kwargs, name)
            else:
                out = handler(self, spec, args, name)
            if _is_t(out) or (isinstance(out, tuple) and any(_is_t(o) for o in out)):
                self.layer_to_module[name] = rec["target"]
            self.env[name] = out
            return
        if op == "call_function":
            if self._fn_handlers is None:
                self._fn_handlers = build_function_handlers()
            target = _decode_callable(rec["target"])
            if target not in self._fn_handlers:
                raise NotImplementedError(f"call_function {rec['target']}")
            self.env[name] = self._fn_handlers[target](self, args, kwargs, name)
            return
        if op == "call_method":
            meth = rec["target"]
            if meth not in METHOD_HANDLERS:
                raise NotImplementedError(f"call_method {meth}")
            self.env[name] = METHOD_HANDLERS[meth](self, args, kwargs, name)
            return
        if op == "output":
            self._collect_outputs(args[0])
            return
        raise NotImplementedError(f"fx op {op}")

    def _collect_outputs(self, v):
        if _is_t(v):
            self.outputs.append(v)
        elif isinstance(v, (list, tuple)):
            for x in v:
                self._collect_outputs(x)
        elif isinstance(v, dict):
            for x in v.values():
                self._collect_outputs(x)


# -------------------------------------------------------------- serialization


def _encode_callable(fn) -> str:
    import importlib

    # normalize to a public module path (torch.relu's __qualname__ is a
    # private class attr that does not round-trip)
    name = getattr(fn, "__name__", None)
    if name:
        for modname in ("operator", "torch", "torch.nn.functional", "math",
                        "builtins"):
            try:
                mod = importlib.import_module(modname)
            except ImportError:
                continue
            if getattr(mod, name, None) is fn:
                return f"{modname}:{name}"
    mod = getattr(fn, "__module__", None) or "builtins"
    qual = getattr(fn, "__qualname__", None) or name or str(fn)
    return f"{mod}:{qual}"


_CALLABLE_CACHE: Dict[str, Any] = {}


def _decode_callable(s: str):
    if s in _CALLABLE_CACHE:
        return _CALLABLE_CACHE[s]
    import importlib

    mod, qual = s.split(":", 1)
    if mod == "_operator":
        mod = "operator"
    try:
        obj = importlib.import_module(mod)
    except ImportError:
        obj = importlib.import_module("builtins")
    for part in qual.split("."):
        obj = getattr(obj, part)
    _CALLABLE_CACHE[s] = obj
    return obj


def _encode_arg(a, node_names):
    import torch as _torch
    import torch.fx as fx

    if isinstance(a, fx.Node):
        return {"$ref": a.name}
    if isinstance(a, (list, tuple)):
        return [_encode_arg(x, node_names) for x in a]
    if isinstance(a, slice):
        return {"$slice": [_encode_arg(a.start, node_names),
                           _encode_arg(a.stop, node_names),
                           _encode_arg(a.step, node_names)]}
    if a is Ellipsis:
        return {"$ellipsis": True}
    if isinstance(a, _torch.dtype):
        return {"$dtype": str(a).replace("torch.", "")}
    if isinstance(a, _torch.Tensor):
        v = a.detach().cpu().numpy()
        return {"$nd": v.tolist(), "$dt": str(v.dtype)}
    if isinstance(a, (int, float, bool, str)) or a is None:
        return a
    if isinstance(a, dict):
        return {"$dict": {str(k): _encode_arg(v, node_names)
                          for k, v in a.items()}}
    raise NotImplementedError(f"cannot serialize arg {a!r}")


class PyTorchModel:
    """Mirror of the reference PyTorchModel (torch/model.py:2408): trace a
    torch module with torch.fx (or HF transformers.utils.fx for HF models)
    and emit the graph onto an FFModel."""

    def __init__(self, model, is_hf_model: bool = False,
                 input_names: Optional[List[str]] = None,
                 batch_size: int = 1, seq_length: Optional[int] = None):
        self.model = model
        self.is_hf_model = is_hf_model
        self.input_names = input_names
        self.batch_size = batch_size
        self.seq_length = seq_length
        self._records: Optional[List[Dict[str, Any]]] = None

    # ------------------------------------------------------------- tracing
    def _trace_model(self):
        import torch.fx as fx

        if self.is_hf_model:
            from transformers.utils import fx as hf_fx

            kw = {"input_names": self.input_names}
            traced = hf_fx.symbolic_trace(self.model, **kw)
        else:
            traced = fx.symbolic_trace(self.model)
        return traced

    def _to_records(self) -> List[Dict[str, Any]]:
        """Reduce the fx graph to torch-free JSON records (the IR)."""
        if self._records is not None:
            return self._records
        traced = self._trace_model()
        name_to_module = dict(self.model.named_modules())
        recs = []
        for node in traced.graph.nodes:
            rec: Dict[str, Any] = {"op": node.op, "name": node.name}
            if node.op == "placeholder":
                rec["target"] = str(node.target)
                if node.args:  # default value (optional input)
                    rec["has_default"] = True
                    rec["default"] = _encode_arg(node.args[0], None)
            elif node.op == "get_attr":
                obj = self.model
                for part in str(node.target).split("."):
                    obj = getattr(obj, part)
                v = obj.detach().cpu().numpy()
                rec.update(target=str(node.target), value=v.tolist(),
                           vdtype=str(v.dtype))
            elif node.op == "call_module":
                module = name_to_module[str(node.target)]
                rec.update(target=str(node.target),
                           module=module_to_spec(module),
                           args=_encode_arg(list(node.args), None),
                           kwargs={k: _encode_arg(v, None)
                                   for k, v in node.kwargs.items()})
            elif node.op == "call_function":
                rec.update(target=_encode_callable(node.target),
                           args=_encode_arg(list(node.args), None),
                           kwargs={k: _encode_arg(v, None)
                                   for k, v in node.kwargs.items()})
            elif node.op == "call_method":
                rec.update(target=str(node.target),
                           args=_encode_arg(list(node.args), None),
                           kwargs={k: _encode_arg(v, None)
                                   for k, v in node.kwargs.items()})
            elif node.op == "output":
                rec["args"] = _encode_arg(list(node.args), None)
            recs.append(rec)
        self._records = recs
        return recs

    # ------------------------------------------------------------- emission
    def torch_to_ff(self, ffmodel, input_tensors: List[Tensor],
                    verbose: bool = False) -> List[Tensor]:
        im = _Importer(ffmodel, input_tensors, verbose=verbose)
        for rec in self._to_records():
            im.run_node(rec)
        self.layer_to_module = im.layer_to_module
        return im.outputs

    # --------------------------------------------------------- .ff file flow
    def torch_to_string(self) -> List[str]:
        return [json.dumps(rec) for rec in self._to_records()]

    def torch_to_file(self, filename: str) -> None:
        with open(filename, "w") as f:
            for line in self.torch_to_string():
                f.write(line + "\n")

    @staticmethod
    def file_to_ff(filename: str, ffmodel, input_tensors: List[Tensor],
                   verbose: bool = False) -> List[Tensor]:
        im = _Importer(ffmodel, input_tensors, verbose=verbose)
        with open(filename) as f:
            for line in f:
                line = line.strip()
                if line:
                    im.run_node(json.loads(line))
        return im.outputs

    # ------------------------------------------------------- weight transfer
    def import_weights(self, compiled) -> None:
        """Copy the torch module's weights into a CompiledModel so imported
        models reproduce torch numerics (the tests/align analog)."""
        import torch.nn as nn

        try:
            from transformers.pytorch_utils import Conv1D as HFConv1D
        except Exception:
            HFConv1D = ()
        name_to_module = dict(self.model.named_modules())
        for lname, target in self.layer_to_module.items():
            m = name_to_module[target]
            if lname not in compiled.params:
                continue  # weight-free layers (dropout, softmax, ...)
            if isinstance(m, nn.Linear):
                compiled.set_weight(lname, "kernel",
                                    m.weight.detach().numpy().T)
                if m.bias is not None:
                    compiled.set_weight(lname, "bias", m.bias.detach().numpy())
            elif HFConv1D and isinstance(m, HFConv1D):
                compiled.set_weight(lname, "kernel", m.weight.detach().numpy())
                compiled.set_weight(lname, "bias", m.bias.detach().numpy())
            elif isinstance(m, nn.Conv2d):
                compiled.set_weight(lname, "kernel", m.weight.detach().numpy())
                if m.bias is not None:
                    compiled.set_weight(lname, "bias", m.bias.detach().numpy())
            elif isinstance(m, nn.Embedding):
                compiled.set_weight(lname, "kernel", m.weight.detach().numpy())
            elif isinstance(m, (nn.LayerNorm, nn.BatchNorm2d)):
                if m.weight is not None:
                    compiled.set_weight(lname, "gamma", m.weight.detach().numpy())
                    beta = (m.bias.detach().numpy() if m.bias is not None
                            else np.zeros(m.weight.shape, np.float32))
                    compiled.set_weight(lname, "beta", beta)
                if isinstance(m, nn.BatchNorm2d):
                    compiled.state[f"{lname}/mean"] = \
                        np.asarray(m.running_mean.detach().numpy())
                    compiled.state[f"{lname}/var"] = \
                        np.asarray(m.running_var.detach().numpy())
            elif isinstance(m, nn.MultiheadAttention):
                e = m.embed_dim
                if m.in_proj_weight is not None:
                    w = m.in_proj_weight.detach().numpy()
                    parts = {"wq": w[:e].T, "wk": w[e:2 * e].T, "wv": w[2 * e:].T}
                else:
                    parts = {"wq": m.q_proj_weight.detach().numpy().T,
                             "wk": m.k_proj_weight.detach().numpy().T,
                             "wv": m.v_proj_weight.detach().numpy().T}
                for k, v in parts.items():
                    compiled.set_weight(lname, k, v)
                compiled.set_weight(lname, "wo",
                                    m.out_proj.weight.detach().numpy().T)
                if m.in_proj_bias is not None:
                    b = m.in_proj_bias.detach().numpy()
                    compiled.set_weight(lname, "bq", b[:e])
                    compiled.set_weight(lname, "bk", b[e:2 * e])
                    compiled.set_weight(lname, "bv", b[2 * e:])
                    compiled.set_weight(lname, "bo",
                                        m.out_proj.bias.detach().numpy())


def torch_to_flexflow(model, filename: str, **kw) -> None:
    """Trace `model` and write the serialized graph to `filename`
    (reference fx.torch_to_flexflow flow, README.md:17-24)."""
    PyTorchModel(model, **kw).torch_to_file(filename)


file_to_ff = PyTorchModel.file_to_ff
