"""Pallas TPU kernels.

flash_attention — block-wise online-softmax attention (fwd + custom VJP),
the cuDNN-fused-attention replacement (reference src/ops/attention.cu:35).
fused_ce — blockwise online-logsumexp sparse cross-entropy (fwd + custom
VJP): the loss never materializes an f32 [N, vocab] array.
fused_optim — single-pass Adam/SGD moment update, replacing the optax
tree_map chain while keeping its exact state layout.
collective_matmul — all-gather/matmul overlap on the model axis (ring of
chunked matmuls via ppermute).
dequant_attention — fused int8-dequant + decode attention over the
quantized paged KV cache (serving --kv-cache-dtype int8).
"""

from flexflow_tpu.kernels.collective_matmul import (  # noqa: F401
    collective_matmul,
    collective_matmul_supported,
)
from flexflow_tpu.kernels.dequant_attention import (  # noqa: F401
    dequant_decode_attention,
)
from flexflow_tpu.kernels.flash_attention import (  # noqa: F401
    flash_attention,
    flash_attention_qkv,
)
from flexflow_tpu.kernels.fused_ce import (  # noqa: F401
    fused_ce_supported,
    fused_cross_entropy,
)
from flexflow_tpu.kernels.fused_optim import (  # noqa: F401
    fused_update,
    plan_for,
)
