"""Graph passes over the frontend layer graph.

`fuse_fork_joins` closes the generic half of the reference's nonsequence
splits (C11/P8, src/runtime/graph.cc:187-321): Unity can split ANY parallel
branches of the PCG across machine resources, not just regions the user
marked. Here the analogous generic path is a model transform: detect
fork-join regions (a fork tensor whose independent consumer chains reconverge
at one join op) and rewrite them into the first-class FORK_JOIN composite —
after which the search's `inter:{axis}` candidate can place the branches on
disjoint device subsets like any hand-built fork_join.

The pass is conservative: a region is fused only when every branch is a
linear chain of single-input/single-output layers from the fork tensor to
the join (no external edges in or out), the join is an add or a last-dim
concat consuming exactly the branch ends, and the branches satisfy the
FORK_JOIN contract (batch preserved, shapes agree) — any violation skips
that region. Regions are fused one at a time with re-detection in between,
so cascaded regions (one region's join feeding another's fork) fuse
correctly against the current graph.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

from flexflow_tpu.core.layer import Layer
from flexflow_tpu.core.tensor import Tensor
from flexflow_tpu.ops.op_type import OperatorType
from flexflow_tpu.ops.registry import get_op_def


def _consumer_index(layers) -> Dict[int, List[Tuple[Layer, int]]]:
    idx: Dict[int, List[Tuple[Layer, int]]] = {}
    for l in layers:
        for i, t in enumerate(l.inputs):
            idx.setdefault(t.guid, []).append((l, i))
    return idx


def find_fork_join_regions(model) -> List[dict]:
    """Fork tensors whose every consumer chain reconverges at one join op."""
    regions = []
    layers = model.layers
    cons_of = _consumer_index(layers)
    tensors = list(model.input_tensors) + \
        [o for l in layers for o in l.outputs]
    for t in tensors:
        cons = cons_of.get(t.guid, [])
        if len(cons) < 2:
            continue
        starts = [c for c, _ in cons]
        if any(len(s.inputs) != 1 for s in starts):
            continue
        # each start must begin a clean single-consumer chain; all chains
        # must terminate at the same multi-input join op
        joins = set()
        chains = []
        ok = True
        for s in starts:
            chain = [s]
            cur = s
            term = None
            while True:
                if len(cur.outputs) != 1:
                    ok = False
                    break
                cc = cons_of.get(cur.outputs[0].guid, [])
                if len(cc) != 1:
                    ok = False
                    break
                nxt, _ = cc[0]
                if len(nxt.inputs) > 1:
                    term = nxt
                    break
                chain.append(nxt)
                cur = nxt
            if not ok or term is None:
                ok = False
                break
            joins.add(id(term))
            chains.append((chain, term))
        if not ok or len(joins) != 1:
            continue
        join = chains[0][1]
        if len(join.inputs) != len(chains):
            continue  # the join takes inputs from outside the region
        if join.op_type is OperatorType.EW_ADD:
            jkind = "add"
        elif join.op_type is OperatorType.CONCAT and \
                join.params.get("axis") in (-1, join.inputs[0].spec.ndim - 1):
            jkind = "concat"
        else:
            continue
        regions.append({"fork": t, "join": join, "jkind": jkind,
                        "chains": [c for c, _ in chains]})
    return regions


def _try_fuse(model, region) -> bool:
    fork, join = region["fork"], region["join"]
    chains: List[List[Layer]] = region["chains"]
    # order branches by the join's input order so numerics (concat) hold
    order = []
    for tin in join.inputs:
        for ci, chain in enumerate(chains):
            if chain[-1].outputs[0].guid == tin.guid:
                order.append(ci)
    if sorted(order) != list(range(len(chains))):
        return False
    chains = [chains[i] for i in order]

    subs = []
    for chain in chains:
        bx = Tensor(fork.spec, name=f"_fj_in_{fork.guid}")
        prev = bx
        blayers = []
        for j, l in enumerate(chain):
            # positional rename for auto-generated names: weight keys must
            # not embed process-global guids (matches FFModel.fork_join)
            name = l.name
            if name == f"{l.op_type.value}_{l.guid}":
                name = f"{l.op_type.value}{j}"
            nl = Layer(l.op_type, l.params, [prev], name=name)
            nl.weight_specs = dict(l.weight_specs)
            if hasattr(l, "branches"):  # nested hand-built fork_join
                nl.branches = l.branches
            for i, o in enumerate(l.outputs):
                nl.add_output(o.spec, i)
            prev = nl.outputs[0]
            blayers.append(nl)
        subs.append((blayers, bx, prev))

    fj = Layer(OperatorType.FORK_JOIN,
               {"join": region["jkind"], "n_branches": len(chains)},
               [fork], name=f"fj_{join.name}")
    fj.branches = subs
    try:
        specs = get_op_def(OperatorType.FORK_JOIN).infer(fj)
    except (ValueError, KeyError):
        return False  # contract violation (e.g. batch-changing branch): skip
    for i, spec in enumerate(specs):
        fj.add_output(spec, idx=i)

    # splice: remove the branch layers + join, rewire join consumers
    removed = {id(l) for chain in chains for l in chain} | {id(join)}
    cons_of = _consumer_index(model.layers)
    for cl, ii in cons_of.get(join.outputs[0].guid, []):
        if id(cl) not in removed:
            cl.inputs[ii] = fj.outputs[0]
    insert_at = min(i for i, l in enumerate(model.layers) if id(l) in removed)
    model.layers = [l for l in model.layers if id(l) not in removed]
    model.layers.insert(insert_at, fj)
    # initializer overrides follow the weights under "b{i}.{layer}.{w}"
    over = model._initializer_overrides
    for bi, (blayers, _bx, _o) in enumerate(subs):
        for nl, old in zip(blayers, chains[bi]):
            for (ln, wn), init in list(over.items()):
                if ln == old.name:
                    over[(fj.name, f"b{bi}.{nl.name}.{wn}")] = over.pop((ln, wn))
    return True


def fuse_fork_joins(model) -> int:
    """Rewrite detected fork-join regions into FORK_JOIN composites (in
    place, one at a time with re-detection in between — cascaded regions
    fuse against the current graph). Returns the number fused. Run BEFORE
    compile(); branch weights move under the composite's
    "b{i}.{sublayer}.{w}" names."""
    fused = 0
    skipped_ids = set()
    while True:
        progress = False
        for region in find_fork_join_regions(model):
            key = (region["fork"].guid, id(region["join"]))
            if key in skipped_ids:
                continue
            if _try_fuse(model, region):
                fused += 1
                progress = True
                break  # graph changed: re-detect from scratch
            skipped_ids.add(key)
        if not progress:
            return fused
