"""Round-4 workload evidence: attribute-parallel conv EXECUTES on the mesh
(P3, halo validation), DLRM's searched strategy shards the embedding tables
(the reference ships hand-tuned strategies for exactly this,
examples/cpp/DLRM/strategies/), and recompile-on-condition drives the MoE
cache-trigger use case (reference examples/cpp/mixture_of_experts/
moe.cc:64-97)."""

import numpy as np
import pytest

from flexflow_tpu import FFConfig, FFModel, SGDOptimizer
from flexflow_tpu.compiler.lowering import build_forward
from flexflow_tpu.models import build_dlrm
from flexflow_tpu.parallel.machine import MachineSpec
from flexflow_tpu.search.candidates import layer_candidates
from flexflow_tpu.search.dp import search_graph

MACH = MachineSpec(mesh_axes={"data": 2, "model": 4}, chip="v5p")


# ------------------------------------------------------- attribute parallel
def _conv_model(batch=8):
    cfg = FFConfig(batch_size=batch, mesh_shape={"data": 2, "model": 4},
                   only_data_parallel=True)
    m = FFModel(cfg)
    x = m.create_tensor([batch, 3, 16, 16], name="x")
    h = m.conv2d(x, 8, 3, 3, padding_h=1, padding_w=1, activation="relu",
                 name="c1")
    h = m.pool2d(h, 2, 2, 2, 2, name="p1")
    h = m.flat(h, name="flat")
    m.dense(h, 4, name="head")
    return m


def test_attr_conv_candidate_carries_halo_cost():
    m = _conv_model()
    c1 = m.get_layer_by_name("c1")
    cands = {c.name: c for c in layer_candidates(c1, MACH, {8})}
    attr = cands.get("attr_h:model")
    assert attr is not None, list(cands)
    # halo = (kernel_h - 1) rows exchanged over the spatial axis: priced > 0
    assert attr.extra_comm > 0.0
    # spatially sharded in/out on H
    assert attr.out_dims[0][2] == "model", attr.out_dims


def test_attr_sharded_conv_executes_and_matches(devices):
    """P3 'done' bar (open since round 1): a conv ATTRIBUTE-sharded on its
    spatial dim actually runs on the mesh — GSPMD materializes the halo
    exchange the candidate's cost term models — and matches the replicated
    numerics."""
    m = _conv_model()
    cm = m.compile(SGDOptimizer(lr=0.01),
                   loss_type="sparse_categorical_crossentropy", metrics=[])
    cm.init(seed=0)
    rng = np.random.default_rng(0)
    xv = rng.normal(size=(8, 3, 16, 16)).astype(np.float32)
    yv = rng.integers(0, 4, size=(8,)).astype(np.int32)
    base = np.asarray(cm.forward(xv))

    # re-lower the same weights with conv output sharded over H (attr_h)
    sh = cm.strategy.op_shardings["c1"]
    sh.outputs[0] = ["data", None, "model", None]
    cm.forward_fn = build_forward(m.layers, m.input_tensors, cm.outputs,
                                  cm.mesh, cm.strategy)
    cm._build_steps()
    attr_out = np.asarray(cm.forward(xv))
    np.testing.assert_allclose(attr_out, base, rtol=2e-5, atol=2e-5)
    # the sharding is real: H dim carries the model axis
    pv = cm.parallel_view("c1")
    assert pv.dims[2].axes == ("model",) and pv.dims[2].shard_size == 4

    hist = cm.fit(xv, yv, epochs=2, verbose=False)
    assert np.isfinite(hist[-1]["loss"])


# ------------------------------------------------------------- DLRM search
def test_dlrm_search_shards_embedding_tables():
    """Not just 'cost is finite' (the round-3 smoke): the searched strategy
    must shard the big embedding tables over the model axis — the known-good
    structure of the reference's shipped DLRM strategies."""
    m = FFModel(FFConfig(batch_size=64))
    build_dlrm(m, batch=64, embedding_tables=(1_000_000,) * 4,
               embedding_dim=64)
    r = search_graph(m, MACH)
    sharded = 0
    for ti in range(4):
        cand = r.choices[f"emb_{ti}"]
        w = cand.weight_dims.get("kernel", [])
        if any(a == "model" or (isinstance(a, tuple) and "model" in a)
               for a in w if a):
            sharded += 1
    assert sharded == 4, {f"emb_{t}": r.choices[f"emb_{t}"].name
                          for t in range(4)}
    # and the bottom MLP stays unsharded-on-model at these small dims
    assert r.choices["bot0"].name == "dp"


def test_dlrm_unity_strategy_trains(devices):
    m = FFModel(FFConfig(batch_size=16, mesh_shape={"data": 2, "model": 4},
                         search_budget=8))
    ins, out = build_dlrm(m, batch=16, embedding_tables=(8192,) * 4,
                          embedding_dim=64)
    cm = m.compile(SGDOptimizer(lr=0.01), loss_type="mean_squared_error",
                   metrics=[], outputs=[out])
    cm.init(seed=0)
    rng = np.random.default_rng(0)
    dense = rng.normal(size=(16, 13)).astype(np.float32)
    sparse = [rng.integers(0, 8192, size=(16, 1)).astype(np.int32)
              for _ in range(4)]
    labels = rng.uniform(size=(16, 1)).astype(np.float32)
    h = cm.fit([dense] + sparse, labels, epochs=1, verbose=False)
    assert np.isfinite(h[0]["loss"])


# ------------------------------------------------- recompile-on-condition
def test_recompile_on_condition_moe_cache_trigger(devices):
    """The MoE cache-trigger flow (reference moe.cc:64-97 + RecompileState,
    include/flexflow/recompile.h:26-43): a predicate watched during fit
    fires once, the alter function changes the execution config, and the
    model is re-lowered mid-training without losing weights."""
    cfg = FFConfig(batch_size=16, only_data_parallel=True, epochs=1)
    m = FFModel(cfg)
    x = m.create_tensor([16, 32], name="x")
    h = m.moe(x, num_exp=4, num_select=2, expert_hidden_size=32, name="moe")
    m.dense(h, 4, name="head")
    cm = m.compile(SGDOptimizer(lr=0.01),
                   loss_type="sparse_categorical_crossentropy", metrics=[])
    cm.init(seed=0)

    events = []
    fwd_before = cm.forward_fn

    def trigger(c):
        # the cache-score analog: fire once after 3 optimizer steps
        return c._iteration == 3 and not events

    def alter(c):
        events.append(c._iteration)
        c.cfg.enable_fusion = False  # re-lower with a different exec config

    cm.recompile_on_condition(trigger, alter)
    rng = np.random.default_rng(0)
    xv = rng.normal(size=(96, 32)).astype(np.float32)
    yv = rng.integers(0, 4, size=(96,)).astype(np.int32)
    hist = cm.fit(xv, yv, verbose=False)  # 6 steps of batch 16
    assert events == [3], events
    assert cm.forward_fn is not fwd_before  # genuinely re-lowered
    assert np.isfinite(hist[0]["loss"])
    assert cm._iteration == 6  # training continued after the recompile
