"""Per-op sharding candidates — the substitution-rule generator.

Reference analog: `generate_all_pcg_xfers` (src/runtime/substitution.cc:
1726-1868) + `register_all_machine_views` (src/runtime/graph.cc:2329-2360):
for every divisor degree the reference emits partition/replicate/combine/reduce
rewrites per op family. Here each op family enumerates Candidate layouts over
the mesh axes; the DP (search/dp.py) picks one per op, and reshard costs at
the edges price the implied parallel ops.

Axis convention: the axis named "data" (else the first axis) is the batch
axis and is always used for batch-dim sharding when divisible (pure-DP is the
always-present baseline candidate, reference --only-data-parallel). Other axes
("model", "expert", "seq", ...) are enumerated for tensor/attribute/expert
parallelism, gated by the reference's flags enable_parameter_parallel /
enable_attribute_parallel.
"""

from __future__ import annotations

import dataclasses
from typing import TYPE_CHECKING, Dict, List, Optional

if TYPE_CHECKING:
    from flexflow_tpu.core.layer import Layer

from flexflow_tpu.ops.op_type import (
    BINARY_OPS,
    OperatorType,
    PARALLEL_OPS,
    UNARY_OPS,
)
from flexflow_tpu.ops.registry import get_op_def, io_bytes
from flexflow_tpu.parallel.machine import MachineSpec
from flexflow_tpu.parallel.sharding import DimSharding
from flexflow_tpu.search import cost_model as cm
from flexflow_tpu.search import memo


@dataclasses.dataclass
class Candidate:
    """One way to place an op: wanted input layouts, produced output/weight
    layouts, and the cost terms that don't live on graph edges."""

    name: str
    in_dims: List[List[DimSharding]]
    out_dims: List[List[DimSharding]]
    weight_dims: Dict[str, List[DimSharding]]
    compute_degree: int = 1
    extra_comm: float = 0.0  # collectives inherent to this placement (s)
    eff: float = 1.0  # MXU-tile granularity efficiency (shards < 128 lanes waste MXU)
    # fraction of the (per-device) weight bytes actually STREAMED from HBM
    # each step: < 1 when a device touches only part of the resident weights
    # (fork_join inter placement runs one branch's weights per device)
    weight_stream_frac: float = 1.0
    # passthrough: identity layout op — adopts whatever layout arrives (minus
    # drop_axis) with zero cost. Used by engine-inserted Replicate/Reduction
    # marker nodes so they never force a gather of the batch sharding.
    passthrough: bool = False
    drop_axis: Optional[str] = None
    # forward-only share of extra_comm (s): extra_comm prices the training
    # step (fwd+bwd); serving cost fns run forward-only programs, so ring
    # rotation and flash-infeasibility penalties must not charge the bwd
    # passes there. None = no fwd/bwd split known; use extra_comm.
    extra_comm_fwd: Optional[float] = None

    def memo_key(self) -> tuple:
        """Hashable identity of this placement (tier-2 interning)."""
        return (self.name,
                tuple(memo.freeze_dims(d) for d in self.in_dims),
                tuple(memo.freeze_dims(d) for d in self.out_dims),
                tuple(sorted((w, memo.freeze_dims(d))
                             for w, d in self.weight_dims.items())),
                self.compute_degree, self.extra_comm, self.eff,
                self.weight_stream_frac, self.passthrough, self.drop_axis,
                self.extra_comm_fwd)

    def op_time(self, layer: "Layer", machine: MachineSpec) -> float:
        # interned by (op params key, placement, machine): structural twins
        # (GPT-2 blocks, ResNeXt branches) and repeated DP frontier states
        # share one evaluation. fork_join costs read layer.branches (not in
        # params_key), so composites always take the direct path.
        if memo.enabled() and not hasattr(layer, "branches"):
            key = (layer.params_key(),
                   memo.freeze_weight_specs(layer.weight_specs),
                   self.memo_key(), memo.machine_fingerprint(machine))
            t = memo.get("op_time", key)
            if t is not memo.MISS:
                return t
            return memo.put("op_time", key, self._op_time(layer, machine))
        return self._op_time(layer, machine)

    def flops_bytes(self, layer: "Layer", machine: MachineSpec):
        """(total fwd flops, per-device HBM bytes, effective degree) of this
        placement — the roofline inputs shared by _op_time and the per-op
        attribution layer (flexflow_tpu/attribution.py): activations divide
        by the compute degree, weights stream per replica (each device reads
        its own shard, scaled by weight_stream_frac)."""
        od = get_op_def(layer.op_type)
        act_bytes = (sum(i.spec.size_bytes for i in layer.inputs)
                     + sum(o.spec.size_bytes for o in layer.outputs))
        w_bytes = self.weight_stream_frac * sum(
            cm.shard_bytes(s, self.weight_dims.get(w, []), machine)
            for w, s in layer.weight_specs.items())
        deg = max(1.0, self.compute_degree * self.eff)
        return od.flop_count(layer), act_bytes / deg + w_bytes, deg

    def _op_time(self, layer: "Layer", machine: MachineSpec) -> float:
        flops, hbm, deg = self.flops_bytes(layer, machine)
        t = cm.compute_time(flops, hbm, machine, deg, bytes_predivided=True)
        t += self.extra_comm
        t += cm.grad_sync_time(layer.weight_specs, self.weight_dims, machine,
                               _batch_axes(machine))
        return t

    def weight_mem_bytes(self, layer: "Layer", machine: MachineSpec,
                         opt_mem: "Optional[cm.OptMemSpec]" = None) -> int:
        # per-device, persistent weight state; activation memory is tracked
        # as a live set by the DP (search/dp.py). Legacy accounting
        # (opt_mem=None — direct search_graph callers): weights x4 (param,
        # grad, 2 f32 moments). With an OptMemSpec: param + grad at the
        # weight dtype, plus the optimizer's ACTUAL moments — counted and
        # sized by its state_dtype (bf16 Adam moments were previously
        # charged as f32) and divided by the ZeRO data-axis degree where
        # the runtime shards them (cost_model.zero_divisor mirrors the
        # compile-side placement rule).
        m = 0
        for w, spec in layer.weight_specs.items():
            dims = self.weight_dims.get(w, [])
            sb = cm.shard_bytes(spec, dims, machine)
            if opt_mem is None:
                m += 4 * sb
                continue
            shard_elems = sb // max(1, spec.dtype.itemsize)
            moment_bytes = opt_mem.moments * shard_elems * opt_mem.state_itemsize
            m += 2 * sb + moment_bytes // cm.zero_divisor(
                spec, dims, machine, opt_mem.zero_axes)
        return m


def compiled_candidate(layer: "Layer", strategy, machine: MachineSpec,
                       batch_sizes) -> "Candidate":
    """The sharding candidate matching the COMPILED strategy's weight
    layout + attrs for this layer (falls back to dp when nothing matches).
    Shared by CompiledModel._candidate_for and the pipeline edition of
    op_attribution — attribution rows must describe the placement that
    actually compiled, or the span corpus trains on mislabeled features."""
    cands = layer_candidates(layer, machine, batch_sizes)
    sh = strategy.op_shardings.get(layer.name)

    def norm(dims):
        return [None if d in (None, []) else
                (d if isinstance(d, str) else tuple(d))
                for d in (dims or [])]

    if sh is not None:
        want_w = {w: norm(d) for w, d in sh.weights.items()}
        want_attrs = dict(sh.attrs or {})
        # attrs disambiguate candidates with identical weight layouts
        # (a grouped inter: placement keeps weights replicated like dp);
        # fall back to the first layout-only match in the same scan
        layout_match = None
        for c in cands:
            if c.passthrough or \
                    {w: norm(d) for w, d in c.weight_dims.items()} != want_w:
                continue
            if candidate_attrs(c) == want_attrs:
                return c
            layout_match = layout_match or c
        if layout_match is not None:
            return layout_match
    return cands[0]


def candidate_attrs(cand: "Candidate") -> Dict[str, str]:
    """Strategy attrs a chosen candidate implies (consumed by the lowering
    via LoweringCtx.op_attrs): inter:{axis} -> fork_join branch placement;
    sp_ring:{axis} -> ring-attention sequence parallelism."""
    if cand.name.startswith("inter:"):
        parts = cand.name.split(":")
        attrs = {"placement": parts[1]}
        if len(parts) > 2:  # unequal groups: "inter:model:3-1"
            attrs["placement_groups"] = parts[2]
        return attrs
    if cand.name.startswith("sp_ring:"):
        return {"seq_parallel": cand.name.split(":", 1)[1]}
    return {}


def _batch_axes(machine: MachineSpec) -> List[str]:
    """Axes the batch dim rides: "data" plus the multi-node sample axis
    ("node", --nodes in compile.py) when present — nodes split samples,
    they don't replicate them."""
    axes = [a for a in ("node", "data") if a in machine.mesh_axes]
    if axes:
        return axes
    return [next(iter(machine.mesh_axes))] if machine.mesh_axes else []


def _model_axes(machine: MachineSpec) -> List[str]:
    b = set(_batch_axes(machine))
    return [a for a in machine.mesh_axes if a not in b and machine.mesh_axes[a] > 1]


def _dp_dims(shape, machine: MachineSpec, batch_sizes) -> List[DimSharding]:
    dims: List[DimSharding] = [None] * len(shape)
    if not shape or shape[0] not in batch_sizes:
        return dims
    axes = _batch_axes(machine)
    deg = 1
    for a in axes:
        deg *= machine.mesh_axes[a]
    if len(axes) > 1 and shape[0] % deg == 0:
        dims[0] = tuple(axes)  # batch over node AND data
        return dims
    for ax in axes:
        if shape[0] % machine.mesh_axes[ax] == 0:
            dims[0] = ax
            break
    return dims


def _ddeg(dims, machine):
    return cm.dims_degree(dims, machine)


def _best_groups(costs, n: int, b_local: int):
    """Best division of n axis indices among len(costs) branches minimizing
    max_b(costs[b]/g_b), with each g_b dividing the per-device batch
    (place_branches_grouped row-slices it). Exhaustive over divisor-valued
    compositions — k is small (2-4 branches), n <= mesh axis size. Returns
    (makespan_rel, group_sizes) or None when no valid composition exists."""
    k = len(costs)
    divs = [d for d in range(1, n + 1) if b_local % d == 0]
    if n < k or not divs:
        return None
    best = None

    def rec(i, left, acc):
        nonlocal best
        if i == k - 1:
            if left in divs:
                g = acc + [left]
                mk = max(c / gi for c, gi in zip(costs, g))
                if best is None or mk < best[0]:
                    best = (mk, g)
            return
        for d in divs:
            if d <= left - (k - 1 - i):
                rec(i + 1, left - d, acc + [d])

    rec(0, n, [])
    return best


def cut_boundary_tensor(layers, ci: int, last_use=None):
    """THE tensor that crosses cut ci (cut after topo index ci): the cut
    layer's output still consumed after ci. sequence_cut_indices only
    guarantees the single live tensor is SOME output of the cut layer —
    a multi-output layer whose first output dies early is a valid cut
    point whose boundary is a LATER output, so callers must never assume
    outputs[0]."""
    if last_use is None:
        last_use = {}
        for li, l in enumerate(layers):
            for t in l.inputs:
                last_use[t.guid] = li
    for o in layers[ci].outputs:
        if last_use.get(o.guid, -1) > ci:
            return o
    return layers[ci].outputs[0]  # ci == last layer (not a real cut)


def stage_cut_candidates(model, machine: MachineSpec, num_stages: int,
                         max_candidates: int = 12) -> List[tuple]:
    """Candidate stage partitions for pipeline parallelism: tuples of
    (num_stages - 1) cut indices (cut AFTER topo position i), restricted to
    single-tensor cut points (exactly one live tensor crosses the boundary
    — the same find_split_node rule unity's sequence splitting uses, so a
    stage boundary is always ONE activation transfer). Ranked by predicted
    stage balance under the data-parallel placement (per-layer op_time
    prefix sums on the STAGE machine) with the boundary-transfer bytes as
    tiebreak; the top `max_candidates` go to the cut-point DP
    (search/dp.py search_pipelined) for exact costing."""
    import itertools

    from flexflow_tpu.core.graph import topo_order
    from flexflow_tpu.search.unity import sequence_cut_indices

    layers = topo_order(model.layers)
    cuts = sequence_cut_indices(layers, model.input_tensors)
    if num_stages <= 1:
        return [()]
    if len(cuts) < num_stages - 1:
        return []
    batch_sizes = {t.shape[0] for t in model.input_tensors if t.ndim > 0}
    t_layer = []
    for layer in layers:
        cands = layer_candidates(layer, machine, batch_sizes)
        t_layer.append(cands[0].op_time(layer, machine)
                       if not cands[0].passthrough else 0.0)
    prefix = [0.0]
    for t in t_layer:
        prefix.append(prefix[-1] + t)

    last_use: Dict[int, int] = {}
    for li, l in enumerate(layers):
        for t in l.inputs:
            last_use[t.guid] = li

    # boundary activation bytes per cut point (the single live tensor)
    def _cut_bytes(ci: int) -> int:
        return cut_boundary_tensor(layers, ci, last_use).spec.size_bytes

    # keep the combination count bounded on deep models: thin the cut list
    # to ~24 points evenly spaced in cumulative cost before enumerating
    if len(cuts) > 24:
        want = [prefix[-1] * (k + 1) / 25.0 for k in range(24)]
        thinned, wi = [], 0
        for ci in cuts:
            if wi < len(want) and prefix[ci + 1] >= want[wi]:
                thinned.append(ci)
                wi += 1
        cuts = thinned or cuts[:24]

    def _rank(combo) -> tuple:
        bounds = [-1] + list(combo) + [len(layers) - 1]
        seg = [prefix[bounds[i + 1] + 1] - prefix[bounds[i] + 1]
               for i in range(num_stages)]
        return (max(seg), sum(_cut_bytes(c) for c in combo))

    ranked = sorted(itertools.combinations(cuts, num_stages - 1), key=_rank)
    return [tuple(c) for c in ranked[:max_candidates]]


def layer_candidates(layer: "Layer", machine: MachineSpec, batch_sizes,
                     enable_parameter: bool = True,
                     enable_attribute: bool = True) -> List[Candidate]:
    """Candidate placements for one layer — interned by (op params key,
    machine, knobs) so the substitution loop's repeated DP runs and
    structural twins enumerate each op family once (search/memo.py, tier 2).
    Candidates are immutable after construction; callers get a fresh list
    over the shared objects. fork_join composites key on layer.branches
    (absent from params_key), so they always rebuild."""
    if memo.enabled() and layer.op_type is not OperatorType.FORK_JOIN:
        key = (layer.params_key(),
               memo.freeze_weight_specs(layer.weight_specs),
               frozenset(batch_sizes), enable_parameter, enable_attribute,
               memo.machine_fingerprint(machine))
        cands = memo.get("candidates", key)
        if cands is memo.MISS:
            cands = memo.put("candidates", key, _layer_candidates(
                layer, machine, batch_sizes, enable_parameter,
                enable_attribute))
        return list(cands)
    return _layer_candidates(layer, machine, batch_sizes, enable_parameter,
                             enable_attribute)


def _layer_candidates(layer: "Layer", machine: MachineSpec, batch_sizes,
                      enable_parameter: bool = True,
                      enable_attribute: bool = True) -> List[Candidate]:
    t = layer.op_type
    ispecs = [x.spec for x in layer.inputs]
    ospecs = [o.spec for o in layer.outputs]
    dp_in = [_dp_dims(s.shape, machine, batch_sizes) for s in ispecs]
    dp_out = [_dp_dims(s.shape, machine, batch_sizes) for s in ospecs]
    repl_w = {w: [None] * s.ndim for w, s in layer.weight_specs.items()}
    dp = Candidate("dp", dp_in, dp_out, dict(repl_w),
                   compute_degree=max(_ddeg(dp_out[0], machine) if dp_out else 1, 1))
    cands = [dp]
    maxes = _model_axes(machine) if enable_parameter else []

    if t is OperatorType.LINEAR:
        x, o = ispecs[0], ospecs[0]
        for m in maxes:
            dm = machine.mesh_axes[m]
            base = max(1, dp.compute_degree)
            if o.shape[-1] % dm == 0:
                od = [list(dp_out[0][:-1]) + [m]]
                cands.append(Candidate(
                    f"tp_col:{m}", dp_in, od,
                    {"kernel": [None, m], **({"bias": [m]} if "bias" in repl_w else {})},
                    compute_degree=base * dm,
                    eff=min(1.0, (o.shape[-1] // dm) / machine.mxu_min_dim)))
            if x.shape[-1] % dm == 0:
                ind = [list(dp_in[0][:-1]) + [m]]
                out_bytes = cm.shard_bytes(o, dp_out[0], machine)
                cands.append(Candidate(
                    f"tp_row:{m}", ind, dp_out,
                    {"kernel": [m, None], **({"bias": [None]} if "bias" in repl_w else {})},
                    compute_degree=base * dm,
                    extra_comm=cm.all_reduce_time(out_bytes, (m,), machine),
                    eff=min(1.0, (x.shape[-1] // dm) / machine.mxu_min_dim)))

    elif t is OperatorType.MULTIHEAD_ATTENTION:
        heads = layer.params["num_heads"]
        for m in maxes:
            dm = machine.mesh_axes[m]
            if heads % dm:
                continue
            wd = {w: [None, m] for w in ("wq", "wk", "wv")}
            wd["wo"] = [m, None]
            for b in ("bq", "bk", "bv"):
                if b in repl_w:
                    wd[b] = [m]
            if "bo" in repl_w:
                wd["bo"] = [None]
            out_bytes = cm.shard_bytes(ospecs[0], dp_out[0], machine)
            embed = layer.params["embed_dim"]
            cands.append(Candidate(
                f"tp_heads:{m}", dp_in, dp_out, wd,
                compute_degree=max(1, dp.compute_degree) * dm,
                extra_comm=cm.all_reduce_time(out_bytes, (m,), machine),
                eff=min(1.0, (embed // dm) / machine.mxu_min_dim)))
        # sequence parallelism: ring attention over a mesh axis (SURVEY P10
        # extension; kernels/ring_attention.py). q/k/v/out sharded on the
        # seq dim; k/v shards rotate (P-1) hops around the ring. Scope:
        # self-attention shapes (sq == sk; the ring's causal offsets assume
        # one chunk length) and no forced impl="xla".
        q, kspec = ispecs[0], ispecs[1]
        seq, seq_k = q.shape[1], kspec.shape[1]
        head_d = layer.params["embed_dim"] // max(1, heads)
        if not layer.params.get("add_bias_kv") and \
                not layer.params.get("add_zero_attn") and \
                not layer.params.get("dropout") and \
                layer.params.get("impl", "auto") != "xla" and \
                seq == seq_k == ispecs[2].shape[1]:
            for m in maxes:
                dm = machine.mesh_axes[m]
                if seq % dm:
                    continue
                sdims = [[dp_in[0][0], m, None]] * 3
                sout = [[dp_out[0][0], m, None]]
                kv_chunk = cm.shard_bytes(kspec, sdims[1], machine)
                # fwd: k+v rotate (dm-1) times; bwd (custom VJP second ring
                # pass): k, v, dk, dv rotate dm times each
                ring_fwd = 2.0 * (dm - 1) * kv_chunk / machine.axis_bw(m)
                ring_comm = (ring_fwd
                             + 4.0 * dm * kv_chunk / machine.axis_bw(m))
                cands.append(Candidate(
                    f"sp_ring:{m}", sdims, sout, dict(repl_w),
                    compute_degree=max(1, dp.compute_degree) * dm,
                    extra_comm=ring_comm, extra_comm_fwd=ring_fwd))
        # where the flash kernel can't cover the shape (q OR k/v past the
        # VMEM budget, or causal cross-shapes), non-ring candidates pay the
        # full (sq, sk) logits materialization through HBM (3x for fwd+bwd)
        from flexflow_tpu.kernels.flash_attention import flash_supported

        isz = q.dtype.itemsize
        flash_ok = (flash_supported(seq, head_d, isz)
                    and flash_supported(seq_k, head_d, isz)
                    and (not layer.params.get("causal") or seq == seq_k))
        if not flash_ok:
            logits_bytes = q.shape[0] * heads * seq * seq_k * max(4, isz)
            for c in cands:
                if not c.name.startswith("sp_ring:"):
                    pen_fwd = (1.0 * 2.0 * logits_bytes
                               / max(1, c.compute_degree) / machine.hbm_bw)
                    c.extra_comm_fwd = (c.extra_comm if c.extra_comm_fwd
                                        is None else c.extra_comm_fwd) + pen_fwd
                    c.extra_comm += 3.0 * pen_fwd

    elif t is OperatorType.EMBEDDING:
        tbl = layer.weight_specs["kernel"]
        for m in maxes:
            dm = machine.mesh_axes[m]
            if tbl.shape[0] % dm == 0:
                out_bytes = cm.shard_bytes(ospecs[0], dp_out[0], machine)
                cands.append(Candidate(
                    f"row:{m}", dp_in, dp_out, {"kernel": [m, None]},
                    compute_degree=max(1, dp.compute_degree) * dm,
                    extra_comm=cm.all_reduce_time(out_bytes, (m,), machine)))
            if tbl.shape[1] % dm == 0 and ospecs[0].shape[-1] % dm == 0:
                od = [list(dp_out[0][:-1]) + [m]]
                cands.append(Candidate(
                    f"col:{m}", dp_in, od, {"kernel": [None, m]},
                    compute_degree=max(1, dp.compute_degree) * dm,
                    eff=min(1.0, (tbl.shape[1] // dm) / machine.mxu_min_dim)))

    elif t is OperatorType.EXPERTS:
        e = ispecs[0].shape[0]
        for m in maxes:
            dm = machine.mesh_axes[m]
            if e % dm:
                continue
            ind = [[m, None, None]]
            od = [[m, None, None]]
            wd = {"kernel": [m, None, None]}
            if "bias" in repl_w:
                wd["bias"] = [m, None]
            cands.append(Candidate(f"ep:{m}", ind, od, wd, compute_degree=dm))

    elif t is OperatorType.GROUP_BY:
        e = ospecs[0].shape[0]
        for m in maxes:
            dm = machine.mesh_axes[m]
            if e % dm:
                continue
            od = [[m, None, None], dp_out[1]]
            cands.append(Candidate(
                f"ep:{m}", dp_in, od, {}, compute_degree=1,
                extra_comm=cm.all_to_all_time(
                    cm.shard_bytes(ospecs[0], [m, None, None], machine), (m,), machine)))

    elif t is OperatorType.CONV2D and enable_attribute:
        x, o = ispecs[0], ospecs[0]
        for m in maxes:
            dm = machine.mesh_axes[m]
            # attribute parallel on H (reference P3); halo = (kernel_h-1) rows
            if o.shape[2] % dm == 0 and x.shape[2] % dm == 0:
                ind = [[dp_in[0][0], None, m, None]]
                od = [[dp_out[0][0], None, m, None]]
                batch_shard = x.shape[0] // max(1, _ddeg([dp_in[0][0]], machine))
                halo_bytes = (layer.params["kernel_h"] - 1) * batch_shard \
                    * x.shape[1] * x.shape[3] * x.dtype.itemsize
                cands.append(Candidate(
                    f"attr_h:{m}", ind, od, dict(repl_w),
                    compute_degree=max(1, dp.compute_degree) * dm,
                    extra_comm=halo_bytes / machine.axis_bw(m)))
            # output-channel TP
            if o.shape[1] % dm == 0:
                od = [[dp_out[0][0], m, None, None]]
                wd = {"kernel": [m, None, None, None]}
                if "bias" in repl_w:
                    wd["bias"] = [m]
                cands.append(Candidate(
                    f"tp_oc:{m}", dp_in, od, wd,
                    compute_degree=max(1, dp.compute_degree) * dm))

    elif t is OperatorType.FORK_JOIN:
        # inter-op placement (reference nonsequence splits, graph.cc:187-321):
        # branch i on mesh-axis index i. Compute divides by the axis size
        # (balanced branches run concurrently on disjoint chips); the join
        # collective (psum for add, all_gather for concat) is the price.
        # The dp candidate computes every branch on every device instead.
        k = layer.params["n_branches"]
        join = layer.params["join"]
        # switch-based placement stacks branch outputs: all branch shapes
        # must be equal, and stateful sub-ops (batch_norm running stats,
        # cache) cannot thread state through the shard_map body
        from flexflow_tpu.ops.fork_join import (
            branch_flops,
            branch_weight_bytes,
            congruent_branches,
            grouped_placeable,
            inter_placeable,
        )

        stacked = congruent_branches(layer)
        b_local = (ispecs[0].shape[0] // max(1, _ddeg([dp_in[0][0]], machine))
                   if ispecs and ispecs[0].ndim else 1)
        # ADVICE r5 crash gate: when the batch cannot shard over the batch
        # axes (_dp_dims fell back to replicated — e.g. batch 6 on data=4),
        # place_branches' backward fails at trace time (g_l varies over the
        # batch axes while the replicated primals do not) and the grouped
        # kernel raises outright — a searched inter:/grouped strategy would
        # crash at compile. Mirror interop._batch_pspec's fallback: emit
        # inter candidates only when the batch actually shards.
        batch_shards = (not ispecs or not ispecs[0].ndim
                        or dp_in[0][0] is not None)
        for m in (maxes if batch_shards else ()):
            n = machine.mesh_axes[m]
            out_bytes = cm.shard_bytes(ospecs[0], dp_out[0], machine)
            if n == k and inter_placeable(layer):
                comm = (cm.all_reduce_time(out_bytes, (m,), machine)
                        if join == "add"
                        else cm.all_gather_time(out_bytes, (m,), machine))
                if stacked:
                    # owned-device residency: stacked (k, ...) weights
                    # sharded over the placement axis — memory, streaming
                    # AND grad all-reduce all divide by k (grad_sync sees
                    # the shard)
                    wd = {w: [m] for w in layer.weight_specs}
                    frac = 1.0
                else:
                    # heterogeneous branches: full replication (union
                    # resident everywhere), each device STREAMS only its
                    # branch's share
                    wd = dict(repl_w)
                    frac = 1.0 / k
                cands.append(Candidate(
                    f"inter:{m}", dp_in, dp_out, wd,
                    compute_degree=max(1, dp.compute_degree) * k,
                    extra_comm=comm,
                    weight_stream_frac=frac))
            elif n > k and grouped_placeable(layer):
                # UNEQUAL resource division (reference graph.cc:267-321):
                # branch b owns g_b axis indices, batch-shards g_b ways
                # inside its group; group sizes must divide the per-device
                # batch (the kernel row-slices it). Weights replicate.
                costs = [max(f, 1.0) for f in branch_flops(layer)]
                best = _best_groups(costs, n, b_local)
                if best is None:
                    continue
                makespan_rel, gsz = best
                speedup = sum(costs) / max(makespan_rel, 1e-30)
                wb = branch_weight_bytes(layer)
                frac = (max(wb) / sum(wb)) if sum(wb) else 1.0
                # join rides one psum of the full joined output over the
                # axis (assembles batch slices AND joins in one collective)
                comm = cm.all_reduce_time(out_bytes, (m,), machine)
                cands.append(Candidate(
                    f"inter:{m}:{'-'.join(map(str, gsz))}",
                    dp_in, dp_out, dict(repl_w),
                    compute_degree=max(1, dp.compute_degree) * speedup,
                    extra_comm=comm,
                    weight_stream_frac=frac))

    elif t in UNARY_OPS or t in (OperatorType.DROPOUT, OperatorType.CAST,
                                 OperatorType.SOFTMAX, OperatorType.LOG_SOFTMAX):
        # propagate a feature-dim shard so TP chains stay sharded
        x = ispecs[0]
        for m in maxes:
            dm = machine.mesh_axes[m]
            if x.ndim >= 2 and x.shape[-1] % dm == 0 and t not in (
                    OperatorType.SOFTMAX, OperatorType.LOG_SOFTMAX):
                d = [list(dp_in[0][:-1]) + [m]]
                cands.append(Candidate(f"follow:{m}", d, d, {},
                                       compute_degree=max(1, dp.compute_degree) * dm,
                                       eff=min(1.0, (x.shape[-1] // dm) / machine.mxu_min_dim)))

    elif t in BINARY_OPS:
        x = ospecs[0]
        for m in maxes:
            dm = machine.mesh_axes[m]
            if x.ndim >= 2 and x.shape[-1] % dm == 0:
                d = [list(dp_out[0][:-1]) + [m]]
                cands.append(Candidate(f"follow:{m}", [d[0], d[0]], d, {},
                                       compute_degree=max(1, dp.compute_degree) * dm,
                                       eff=min(1.0, (x.shape[-1] // dm) / machine.mxu_min_dim)))

    elif t in PARALLEL_OPS:
        from flexflow_tpu.ops.parallel_ops import requested_dims

        # Reduction (and engine-inserted axis-scoped Replicate) are layout
        # markers: they adopt the incoming layout (Replicate guarantees the
        # named axis is unused, i.e. replicated-over). The DP handles these
        # as passthrough so they never gather the batch sharding.
        if t is OperatorType.REDUCTION or (
                t is OperatorType.REPLICATE and "axis" in layer.params):
            return [Candidate("passthrough", [], [], {}, passthrough=True,
                              drop_axis=layer.params.get("axis"))]
        # other parallel ops: the requested layout IS the candidate; pricing
        # happens at the incoming edge (reshard incoming→requested), the op
        # itself is free — so in_dims = out_dims = requested.
        dims = requested_dims(layer)
        return [Candidate("requested", [list(dims)], [list(dims)], {},
                          compute_degree=1)]

    return cands
