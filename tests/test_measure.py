"""Measured per-op cost path (search/measure.py) — the
inner_measure_operator_cost analog (/root/reference/src/runtime/model.cu:
38-74): runs, caches, respects dtype/shard shapes, and can FLIP a search
decision the analytic model gets wrong."""

import numpy as np
import pytest

from flexflow_tpu import FFConfig, FFModel
from flexflow_tpu.dtype import DataType
from flexflow_tpu.parallel.machine import MachineSpec
from flexflow_tpu.search.candidates import layer_candidates
from flexflow_tpu.search.dp import search_graph
from flexflow_tpu.search.measure import MeasuredCost, _shard_shape

MACH = MachineSpec(mesh_axes={"data": 2, "model": 4}, chip="v5p")


def _linear_model(batch=32, din=64, dout=128, dtype=DataType.FLOAT):
    m = FFModel(FFConfig(batch_size=batch))
    x = m.create_tensor([batch, din], dtype=dtype, name="x")
    m.dense(x, dout, name="lin")
    return m, m.get_layer_by_name("lin")


def test_measured_cost_runs_and_caches(devices):
    m, lin = _linear_model()
    mc = MeasuredCost(MACH, repeats=3, warmup=1)
    (dp,) = [c for c in layer_candidates(lin, MACH, {32}) if c.name == "dp"]
    t1 = mc.op_time(lin, dp)
    assert np.isfinite(t1) and t1 > 0
    assert len(mc.cache) == 1
    t2 = mc.op_time(lin, dp)  # cache hit: identical, no re-measure
    assert t2 == t1 and len(mc.cache) == 1


def test_measured_cost_shard_shapes_and_dtype(devices):
    """Measurement runs at SHARD-LOCAL shapes for the candidate's layout and
    keys the cache by (params, layout) — so different dtypes and layouts
    measure separately."""
    m, lin = _linear_model()
    cands = {c.name: c for c in layer_candidates(lin, MACH, {32})}
    tp = cands["tp_col:model"]
    # tp_col shards the weight's out dim over model(4)
    assert _shard_shape(lin.weight_specs["kernel"], tp.weight_dims["kernel"],
                        MACH) == (64, 32)
    assert _shard_shape(lin.inputs[0].spec, tp.in_dims[0], MACH) == (16, 64)

    mc = MeasuredCost(MACH, repeats=3, warmup=1)
    t_dp = mc.op_time(lin, cands["dp"])
    t_tp = mc.op_time(lin, tp)
    assert len(mc.cache) == 2  # distinct layouts, distinct keys
    m16, lin16 = _linear_model(dtype=DataType.HALF)
    t_16 = mc.op_time(lin16, cands["dp"])
    assert len(mc.cache) == 3  # dtype is part of the identity
    assert all(np.isfinite(t) and t > 0 for t in (t_dp, t_tp, t_16))


def test_measurement_flips_search_decision(devices):
    """The fidelity case the measured path exists for: the analytic roofline
    credits a row-sharded embedding with 1/8 of the table's HBM streaming,
    but a real gather only touches the looked-up rows — measurement shows
    the sharding buys nothing and the all-reduce penalty decides, flipping
    the search from row:model to dp (margins ≫ CPU timing noise)."""
    mach = MachineSpec(mesh_axes={"data": 1, "model": 8}, chip="v5p",
                       hbm_bw=1e10, ici_bw={"data": 5e8, "model": 5e8})
    m = FFModel(FFConfig(batch_size=4096))
    x = m.create_tensor([4096], dtype=DataType.INT32, name="idx")
    m.embedding(x, 262144, 60, name="emb")  # 60 % 8 != 0: no col candidate

    r_analytic = search_graph(m, mach)
    assert r_analytic.choices["emb"].name == "row:model"

    mc = MeasuredCost(mach, repeats=8, warmup=3)
    r_measured = search_graph(m, mach, cost_fn=mc.op_time)
    assert r_measured.choices["emb"].name == "dp", r_measured.choices["emb"].name


def test_calibration_harness(devices, tmp_path):
    """tools/calibrate.py produces the analytic/measured/whole-step table
    (SURVEY §7 hard part #1 quantified; committed as CALIBRATION.md)."""
    import sys

    sys.path.insert(0, "/root/repo/tools")
    import calibrate

    rows, machine = calibrate.calibrate(names=["mlp"])
    (row,) = rows
    assert row["workload"] == "mlp"
    for k in ("analytic_ms", "measured_ms", "step_ms",
              "analytic_over_step", "measured_over_step"):
        assert np.isfinite(row[k]) and row[k] > 0, (k, row)
    path = calibrate.write_report(rows, machine, str(tmp_path / "CAL.md"))
    text = open(path).read()
    assert "mlp" in text and "analytic/step" in text
