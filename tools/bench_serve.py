"""Open-loop serving benchmark: the ISSUE 10 evidence artifact.

Builds the gpt2 CPU twin, compiles the two searched serving programs
(`compile_serving` — compute-priced prefill, bandwidth-priced decode),
and drives the continuous-batching scheduler with an OPEN-LOOP Poisson
arrival trace (seeded — arrivals don't wait for the server, so queueing
delay shows up in TTFT exactly as it would against a real frontend).
Per arrival-rate leg it reports:

  tokens_per_s_per_chip — generated tokens / wall / device count
  ttft_p50_s/ttft_p99_s — time-to-first-token quantiles (arrival ->
      first prefill logit materialization, queueing included)
  per_token_p50_s/per_token_p99_s — decode-step latency quantiles at
      the scheduler's dispatch-window materialization granularity

plus the serving memory accounting (predicted vs measured params + KV
pool residency per device) through the PR 8 watermark check.

  python tools/bench_serve.py                        # full twin bench
  python tools/bench_serve.py --rates 2,8 --requests 24
  python tools/bench_serve.py --out BENCH_serve.json
  python tools/bench_serve.py --check   # CI smoke (tiny twin): asserts
      every request completes with its full token budget, quantiles are
      finite and ordered, KV bytes are accounted in memory_stats, and
      the measured watermark sits within the predicted envelope.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                           + " --xla_force_host_platform_device_count=8")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def _quantile(xs, q):
    if not xs:
        return None
    return float(np.quantile(np.asarray(xs, np.float64), q))


def _build_engine(check: bool, kv_cache_dtype: str = "auto"):
    import jax

    from flexflow_tpu import FFConfig, FFModel
    from flexflow_tpu.models import GPT2Config, build_gpt2
    from flexflow_tpu.serving import compile_serving

    n_dev = len(jax.devices())
    mesh = ({"data": 2, "model": n_dev // 2} if n_dev % 2 == 0 and n_dev > 1
            else {"data": max(1, n_dev)})
    cfg = FFConfig(search_budget=16, mesh_shape=mesh, log_level="warning",
                   max_batch_slots=4, kv_page_size=4,
                   kv_cache_dtype=kv_cache_dtype)
    gc = (GPT2Config(vocab=256, seq=16, d_model=64, heads=2, layers=1,
                     dropout=0.0) if check else
          GPT2Config(vocab=512, seq=32, d_model=128, heads=4, layers=2,
                     dropout=0.0))
    m = FFModel(cfg)
    build_gpt2(m, gc, batch=8)
    eng = compile_serving(m, max_decode_len=4 if check else 8)
    eng.init(seed=0)
    return eng, gc, n_dev


def _make_trace(rng, n_requests, rate, vocab, prompt_len, max_new):
    """Open-loop Poisson arrivals via tracefmt (ISSUE 20): the generator
    IS the trace format, so every bench leg doubles as a replayable twin
    scenario. Arrival/prompt rng order is the pre-tracefmt one — fixed
    seeds reproduce the identical request sequence (pinned in tests)."""
    from flexflow_tpu.serving import tracefmt

    return tracefmt.records_to_requests(
        tracefmt.poisson_records(rng, n_requests, rate, vocab, prompt_len,
                                 max_new))


def _run_leg(eng, gc, n_dev, rate, n_requests, seed):
    from flexflow_tpu.serving import (ContinuousBatchingScheduler,
                                      gpt2_prompt_inputs, gpt2_step_inputs)

    rng = np.random.default_rng(seed)
    max_new = eng.max_decode_len
    prompt_len = max(2, gc.seq // 4)
    reqs = _make_trace(rng, n_requests, rate, gc.vocab, prompt_len, max_new)
    sched = ContinuousBatchingScheduler(eng, eng.params, gpt2_prompt_inputs,
                                        gpt2_step_inputs, eos_id=None,
                                        dispatch_ahead=4)
    t0 = time.perf_counter()
    done = sched.run(reqs)
    wall = time.perf_counter() - t0
    tokens = sum(len(r.tokens) for r in done)

    # ISSUE 15: quantiles come from the scheduler's live streaming
    # histograms — the SAME series the monitor panel and prometheus
    # export read, so bench and dashboard can never disagree. The
    # timestamp-list recompute survives only as the reqtrace-off
    # fallback.
    def hq(metric, q, fallback):
        h = sched.tracer.hists.get(metric) if sched.tracer else None
        if h is not None and h.count:
            return h.quantile(q)
        return fallback()

    ttfts = [r.ttft_s for r in done if r.ttft_s is not None]
    return {
        "arrival_rate_req_s": rate,
        "requests": len(done),
        "tokens": tokens,
        "wall_s": round(wall, 3),
        "tokens_per_s": round(tokens / wall, 2),
        "tokens_per_s_per_chip": round(tokens / wall / n_dev, 2),
        "ttft_p50_s": hq("ttft", 0.5, lambda: _quantile(ttfts, 0.5)),
        "ttft_p99_s": hq("ttft", 0.99, lambda: _quantile(ttfts, 0.99)),
        "per_token_p50_s": hq("decode_step", 0.5,
                              lambda: _quantile(sched.step_times, 0.5)),
        "per_token_p99_s": hq("decode_step", 0.99,
                              lambda: _quantile(sched.step_times, 0.99)),
        "decode_steps": sched.decode_steps,
        "prefill_batches": sched.prefills,
        "spec_accept_rate": (
            round(sched.stats["spec_accepted_tokens"]
                  / sched.stats["spec_drafted_tokens"], 4)
            if sched.stats.get("spec_drafted_tokens") else None),
        "all_complete": all(len(r.tokens) == r.max_new_tokens for r in done),
    }


def main(argv=None) -> int:
    p = argparse.ArgumentParser("bench_serve")
    p.add_argument("--rates", default="2,8",
                   help="comma-separated open-loop arrival rates (req/s)")
    p.add_argument("--requests", type=int, default=24)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--out", default="", help="also write the JSON here")
    p.add_argument("--kv-cache-dtype", default="auto",
                   choices=("auto", "bf16", "int8"),
                   help="KV-cache storage dtype for the bench engine")
    p.add_argument("--check", action="store_true",
                   help="CI smoke: tiny twin, assert completion + ordered "
                        "finite quantiles + KV memory accounting")
    args = p.parse_args(argv)
    if args.check:
        args.requests = min(args.requests, 8)

    eng, gc, n_dev = _build_engine(args.check, args.kv_cache_dtype)
    ms = eng.memory_stats()
    hr = eng.health_report()["watermarks"]
    legs = []
    for i, r in enumerate(s for s in args.rates.split(",") if s.strip()):
        legs.append(_run_leg(eng, gc, n_dev, float(r), args.requests,
                             args.seed + i))
    report = {
        "model": "gpt2 CPU twin" + (" (check)" if args.check else ""),
        "devices": n_dev,
        "slots": eng.slots,
        "max_decode_len": eng.max_decode_len,
        "kv_page_size": eng.kv_spec.page_size,
        "prefill_vs_decode_strategy_differ": (
            eng.prefill_strategy.op_shardings != eng.decode_strategy.op_shardings),
        "kv_shard_degree": ms["kv_shard_degree"],
        "memory": ms,
        "watermark": hr,
        "legs": legs,
        # ISSUE 13: KV storage + speculation provenance on the artifact
        "kv_cache_dtype": str(eng.kv_dtype),
        "kv_itemsize": eng.kv_spec.itemsize,
        "kv_scale_itemsize": eng.kv_spec.scale_itemsize,
        "spec_tokens": eng.spec_tokens,
        # headline metrics (bench_history "serve" family)
        "tokens_per_s_per_chip": max(l["tokens_per_s_per_chip"] for l in legs),
        "ttft_p99_s": legs[-1]["ttft_p99_s"],
        "per_token_p99_s": legs[-1]["per_token_p99_s"],
        "spec_accept_rate": next(
            (l["spec_accept_rate"] for l in reversed(legs)
             if l["spec_accept_rate"] is not None), None),
    }
    print(json.dumps(report, indent=1))
    if args.out:
        with open(args.out, "w") as f:
            json.dump(report, f, indent=1)

    if args.check:
        ok = True

        def fail(msg):
            nonlocal ok
            ok = False
            print("CHECK FAIL: " + msg, file=sys.stderr)

        for leg in legs:
            if leg["requests"] != args.requests or not leg["all_complete"]:
                fail(f"rate {leg['arrival_rate_req_s']}: "
                     f"{leg['requests']}/{args.requests} requests complete")
            for lo, hi in (("ttft_p50_s", "ttft_p99_s"),
                           ("per_token_p50_s", "per_token_p99_s")):
                if not (leg[lo] is not None and leg[hi] is not None
                        and 0.0 <= leg[lo] <= leg[hi]):
                    fail(f"rate {leg['arrival_rate_req_s']}: quantiles "
                         f"{lo}={leg[lo]} {hi}={leg[hi]} not ordered/finite")
            if leg["tokens_per_s_per_chip"] <= 0:
                fail("zero serving throughput")
        if ms["predicted_kv_cache_bytes"] <= 0 or \
                ms["actual_kv_cache_bytes_per_device"] != \
                ms["predicted_kv_cache_bytes"]:
            fail(f"KV accounting mismatch: predicted "
                 f"{ms['predicted_kv_cache_bytes']} vs actual "
                 f"{ms['actual_kv_cache_bytes_per_device']}")
        if hr["ratio"] > hr["warn_ratio"]:
            fail(f"measured watermark {hr['ratio']:.2f}x predicted "
                 f"(warn at {hr['warn_ratio']}x)")
        print("CHECK " + ("PASS" if ok else "FAIL"))
        return 0 if ok else 1
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
