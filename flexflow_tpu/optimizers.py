"""Optimizers: SGD + Adam.

Reference analog: include/flexflow/optimizer.h:36-110, src/runtime/optimizer.cc
and optimizer_kernel.cu — where the reference fuses an ncclAllReduce of the
gradients into the update task (optimizer_kernel.cu:88,196). On TPU the update
is part of the single jitted SPMD train step: when weights are replicated over
the data axis, XLA inserts the gradient all-reduce (psum over ICI) at the
jax.grad boundary automatically, which is exactly the NCCL-fused-update
semantics. Implementations are optax GradientTransformations (the idiomatic
JAX optimizer algebra), wrapped in classes mirroring the reference Python API
(python/flexflow/core/flexflow_cffi.py SGDOptimizer/AdamOptimizer).
"""

from __future__ import annotations

from typing import Optional

import optax


class Optimizer:
    def to_optax(self) -> optax.GradientTransformation:
        raise NotImplementedError


class SGDOptimizer(Optimizer):
    def __init__(self, ffmodel=None, lr: float = 0.01, momentum: float = 0.0,
                 nesterov: bool = False, weight_decay: float = 0.0):
        self.lr = lr
        self.momentum = momentum
        self.nesterov = nesterov
        self.weight_decay = weight_decay

    def to_optax(self) -> optax.GradientTransformation:
        parts = []
        if self.weight_decay:
            parts.append(optax.add_decayed_weights(self.weight_decay))
        parts.append(optax.sgd(self.lr, momentum=self.momentum or None, nesterov=self.nesterov))
        return optax.chain(*parts)


class AdamOptimizer(Optimizer):
    def __init__(self, ffmodel=None, alpha: float = 0.001, beta1: float = 0.9,
                 beta2: float = 0.999, weight_decay: float = 0.0, epsilon: float = 1e-8):
        self.alpha = alpha
        self.beta1 = beta1
        self.beta2 = beta2
        self.weight_decay = weight_decay
        self.epsilon = epsilon

    def to_optax(self) -> optax.GradientTransformation:
        if self.weight_decay:
            return optax.adamw(self.alpha, b1=self.beta1, b2=self.beta2,
                               eps=self.epsilon, weight_decay=self.weight_decay)
        return optax.adam(self.alpha, b1=self.beta1, b2=self.beta2, eps=self.epsilon)
