"""Auto-parallelization search (the Unity analog, SURVEY.md C10-C14).

Pipeline: candidate generation per op (substitution-rules analog) →
frontier DP with beam pruning over the layer graph (SearchHelper DP analog) →
Strategy. Costs from the analytic TPU model (simulator analog), optionally
calibrated by on-device measurement.
"""

from flexflow_tpu.search.optimize import graph_optimize

__all__ = ["graph_optimize"]
