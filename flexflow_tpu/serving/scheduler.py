"""Continuous-batching scheduler over the two serving programs.

Policy (the vLLM-style loop, on PR 2's async-dispatch discipline):

- ADMISSION: at every sync point, waiting requests are placed into free
  decode slots (page allocation permitting — a short free list is
  backpressure, the request stays queued). Admitted prompts are right-
  padded into the `[slots, S]` prefill batch at their slot's row, run
  through the prefill program once ("prefill-then-join"), their K/V
  committed into the paged cache, and their first token (argmax of the
  last real-position logits) recorded as time-to-first-token.
- DECODE: between sync points the host dispatches up to `dispatch_ahead`
  single-token steps without materializing anything — each step's argmax
  feeds the next step as a device array, the device-resident loop of the
  async runtime (`prefetch_multi`-style overlap: the host is preparing
  admissions while the device chews the dispatched window). The window
  is additionally capped at the smallest remaining token budget across
  active slots, so the loop never speculates past a max-len finish; an
  EOS finish inside a window is masked out of the committed KV advance
  (`sync_after(advances=...)`) and counted as `overdecode_tokens`.
- EVICTION: at sync points, slots whose sequence hit EOS or max-new are
  evicted (pages freed). The decode attention routes any out-of-range
  write to the scratch page, so over-decode can never corrupt a
  neighbour.

SLO-aware admission & graceful degradation (ISSUE 11):

- Requests carry a `priority` class (lower = more urgent; ties broken by
  arrival) and an optional `deadline_s` TTFT deadline. The waiting queue
  is served priority-first.
- SHED-OR-QUEUE at admit: with `--serve-queue-cap` set, an arrival into
  a full queue sheds the lowest-priority waiter (or the arrival itself
  if nothing waiting is less urgent). With `--serve-ttft-budget-ms` set,
  a waiter whose elapsed wait plus the EMA prefill service time can no
  longer make the budget is shed instead of serving a dead-on-arrival
  response. Deadline-expired waiters shed the same way. Prompts longer
  than the prefill window are shed as `prompt_too_long` (they can never
  be admitted), and `KVPoolExhausted` from a lost admission race keeps
  the request queued (backpressure, not an error).
- CHUNKED-PREFILL admission: `prefill_chunk_tokens` caps the summed
  prompt length of one admission wave, so a burst of long prompts
  spreads over several prefill batches instead of monopolizing the
  engine while decode slots starve.
- WATCHDOG: with `--serve-decode-timeout-ms` set, a dispatched window
  whose per-step materialization exceeds the budget evicts the longest-
  resident slot (outcome "timeout") instead of stalling the whole batch.

Fault wrapping (ISSUE 11): prefill dispatch, KV admission, and decode
dispatch run under `run_resilient` with the serving retry policy — a
transient `serve/prefill` / `serve/kv_admit` / `serve/decode_step` fault
costs a retry (telemetry `retry` events); a permanent one fails ONLY the
affected request(s): a kv_admit escalation sheds that request, a prefill
escalation fails the batch being admitted, a decode escalation evicts
the wedged slot — the engine keeps serving in every case.

Hot-swap integration: when the engine `watch()`es a checkpoint root, the
loop calls `engine.poll_swap()` only while the dispatched window is
empty — the swap's pointer flip happens BETWEEN decode steps, with no
in-flight dispatch referencing the retiring param tree.

Fleet integration (ISSUE 18): this class is the REPLICA-LOCAL decode
loop. The admission policy brain (shed-or-queue, queue-cap displacement,
staleness sweeps) lives in `fleet.AdmissionControl` — one instance here
for standalone use, the same class at fleet level for cross-replica
admission — and three hooks let `fleet.ServingFleet` drive N loops:
`self.feed` (a thread-safe arrival feed replacing the static trace),
`self.control` (swap orchestration at the between-windows safe point,
replacing the local `poll_swap` call), and the `handoff` callback
(prefill-only mode: admitted slots are spilled, exported, and handed to
the decode pool right after their TTFT materialization). All three
default to off, and every fleet branch is guarded on them — a standalone
scheduler is bitwise the pre-fleet loop.

Model specifics stay out of the loop: `prompt_inputs_fn` and
`step_inputs_fn` adapt token ids + cache state to the model's input list
(gpt2 adapters below; the generic transformer feeds embeddings directly
and drives the engine without this scheduler).
"""

from __future__ import annotations

import dataclasses
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from flexflow_tpu import telemetry as tel
from flexflow_tpu.runtime.resilience import RetryPolicy, run_resilient
from flexflow_tpu.serving.kv_cache import (KVPoolExhausted, POS_KEY,
                                           derive_prefetch_ahead,
                                           learned_kv_transfer_seconds)
from flexflow_tpu.serving.reqtrace import RequestTracer, terminal_record


@dataclasses.dataclass
class Request:
    rid: int
    prompt: List[int]
    max_new_tokens: int
    arrival_s: float = 0.0        # offset from scheduler start (open loop)
    priority: int = 1             # SLO class, lower = more urgent
    deadline_s: Optional[float] = None  # TTFT deadline (relative to arrival)
    # filled by the scheduler:
    tokens: List[int] = dataclasses.field(default_factory=list)
    ttft_s: Optional[float] = None
    admit_s: Optional[float] = None  # prefill-dispatch time (queue-wait end)
    finish_s: Optional[float] = None
    slot: Optional[int] = None
    outcome: str = ""             # "done" | "shed" | "failed" | "timeout"
    shed_reason: str = ""


def gpt2_prompt_inputs(ids: np.ndarray, lengths: np.ndarray) -> List[np.ndarray]:
    """gpt2 prefill inputs: token ids + positions 0..S-1."""
    pos = np.broadcast_to(np.arange(ids.shape[1], dtype=np.int32), ids.shape)
    return [ids.astype(np.int32), np.ascontiguousarray(pos)]


def gpt2_step_inputs(tokens, state) -> List[Any]:
    """gpt2 decode inputs: next token ids + the device-side positions (the
    index each slot's token is written at — no host sync to build them).
    Generalizes to multi-token steps (`tokens` shaped [slots, s] for the
    speculative-verify pass): token i of a slot sits at position pos+i."""
    pos = state[POS_KEY][:, None]
    s = int(tokens.shape[1])
    if s > 1:
        pos = pos + jnp.arange(s, dtype=state[POS_KEY].dtype)[None, :]
    return [tokens, pos]


def _urgency(r: Request):
    return (r.priority, r.arrival_s, r.rid)


class ContinuousBatchingScheduler:
    def __init__(self, engine, params, prompt_inputs_fn: Callable,
                 step_inputs_fn: Callable, eos_id: Optional[int] = None,
                 dispatch_ahead: int = 4,
                 ttft_budget_ms: Optional[float] = None,
                 queue_cap: Optional[int] = None,
                 decode_timeout_ms: Optional[float] = None,
                 prefill_chunk_tokens: int = 0,
                 retry_policy: Optional[RetryPolicy] = None,
                 reqtrace: Optional[bool] = None,
                 handoff: Optional[Callable] = None):
        self.engine = engine
        self.params = params
        self.prompt_inputs_fn = prompt_inputs_fn
        self.step_inputs_fn = step_inputs_fn
        self.eos_id = eos_id
        self.dispatch_ahead = max(1, int(dispatch_ahead))
        self.kv = engine.kv
        self.slots = engine.slots
        self.seq = int(engine.prefill_model.input_tensors[0].spec.shape[1])
        cfg = engine.cfg
        self.ttft_budget_ms = float(
            ttft_budget_ms if ttft_budget_ms is not None
            else getattr(cfg, "serve_ttft_budget_ms", 0.0))
        self.queue_cap = int(queue_cap if queue_cap is not None
                             else getattr(cfg, "serve_queue_cap", 0))
        self.decode_timeout_ms = float(
            decode_timeout_ms if decode_timeout_ms is not None
            else getattr(cfg, "serve_decode_timeout_ms", 0.0))
        self.prefill_chunk_tokens = max(0, int(prefill_chunk_tokens))
        self.retry_policy = (retry_policy if retry_policy is not None
                             else RetryPolicy.from_config(cfg))
        self.completed: List[Request] = []
        self.shed: List[Request] = []
        self.failed: List[Request] = []
        # speculative decoding: when the engine carries a draft, every
        # round drafts K tokens and verifies them in one target pass; the
        # draft's paged cache mirrors every admit/advance/evict
        self.spec_tokens = int(getattr(engine, "spec_tokens", 0) or 0)
        self.draft = getattr(engine, "draft", None)
        self._spec = self.spec_tokens > 0 and self.draft is not None
        if self._spec and self.draft.params is None:
            raise ValueError(
                "speculative scheduler: draft engine has no params (call "
                "engine.draft.init() or engine.draft.load_params first)")
        self._spec_fused = None
        if self._spec:
            try:
                # one dispatch per round (draft chain + verify fused);
                # requires a jax-traceable step_inputs_fn — probe with an
                # abstract trace so a host-side fn falls back cleanly here
                # instead of blowing up mid-serve
                fn = engine.build_spec_program(step_inputs_fn)
                jax.eval_shape(fn, params, self.draft.params, self.kv.state,
                               self.draft.kv.state,
                               jax.ShapeDtypeStruct((self.slots, 1),
                                                    jnp.int32))
                self._spec_fused = fn
            except Exception:  # noqa: BLE001 — untraceable inputs fn
                self._spec_fused = None
        self._accept_ema = 0.0
        # tiered KV cache (ISSUE 16): parked requests own their slot id
        # while their K/V sits in the host tier; rotation happens only at
        # sync points so a parked slot never has un-materialized window
        # tokens. prefetch_ahead is the hit/stall classifier AND the lead
        # the rotation aims for.
        self.tiered = bool(getattr(self.kv, "host_pages", 0))
        self.prefetch_ahead = max(1, int(
            getattr(cfg, "kv_prefetch_ahead", 2) or 2))
        # autotuned prefetch-ahead (ISSUE 18 satellite): when a learned
        # model resolves a kv_transfer prediction for this cache geometry,
        # the lead is re-derived from it at the first measured decode step
        # — the flag value above is the fallback, not the authority
        self._autotune_transfer_s: Optional[float] = None
        self._autotuned = False
        if self.tiered:
            self._autotune_transfer_s = learned_kv_transfer_seconds(
                cfg, self.kv.spec, quantized=self.kv.quantized,
                machine=self.kv.machine)
        self.max_context = int(getattr(cfg, "serve_max_context", 0) or 0)
        # the admission policy brain is the fleet-level class (ISSUE 18
        # control-plane split); a standalone scheduler owns one instance
        from flexflow_tpu.serving.fleet import AdmissionControl
        self.admission = AdmissionControl(
            seq=self.seq, max_context=self.max_context,
            queue_cap=self.queue_cap, ttft_budget_ms=self.ttft_budget_ms,
            overhead_tokens=self.dispatch_ahead + self.spec_tokens,
            pages_needed=self.kv.pages_needed,
            capacity_pages=self.kv.capacity_pages)
        # fleet hooks (all default-off; see module docstring)
        self.feed = None                    # fleet-injected arrival feed
        self.control = None                 # fleet swap orchestration
        self.handoff = handoff              # prefill-only: route to decode
        # device-execution serialization: standalone, a private (never
        # contended) lock — zero behavior change. Under an in-process
        # fleet this is the fleet-wide RLock and _exec_serialized=True
        # adds run-to-completion barriers, because concurrent collective
        # programs from sibling replicas deadlock the shared XLA runtime
        # (see fleet._SharedRuntimeEngine).
        self.exec_lock: Any = threading.RLock()
        self._exec_serialized = False
        if self.handoff is not None and self._spec:
            raise ValueError("prefill-only handoff does not compose with "
                             "speculative decoding (no draft-cache handoff)")
        self.handoffs = 0
        self._pending_handoffs: List = []   # (Request, payload) to ingest
        self.queue_depth = 0                # live router signals (ints,
        self.active_count = 0               # safe to read cross-thread)
        self.parked: Dict[int, Request] = {}
        self.stats: Dict[str, int] = {
            "shed_queue_full": 0, "shed_ttft_budget": 0, "shed_deadline": 0,
            "shed_prompt_too_long": 0, "shed_over_max_context": 0,
            "failed": 0, "evicted_wedged": 0,
            "decode_timeouts": 0, "overdecode_tokens": 0, "swaps": 0,
            "spec_rounds": 0, "spec_drafted_tokens": 0,
            "spec_accepted_tokens": 0}
        self._ema_serve_ms = 0.0  # EMA of prefill wall (the shed estimator)
        # per-decode-step wall seconds at materialization granularity —
        # the per-token latency samples the bench quantiles
        self.step_times: List[float] = []
        self.decode_steps = 0
        self.prefills = 0
        self.materializations = 0  # host syncs that drained a window
        # request-level tracing (ISSUE 15): zero-sync by construction —
        # the tracer only ever sees timestamps the loop already took at
        # its sync points. With reqtrace off there is NO tracer and the
        # dispatch path is bitwise the PR-13 baseline.
        rt_on = (reqtrace if reqtrace is not None
                 else bool(getattr(cfg, "serve_reqtrace", True)))
        self.tracer: Optional[RequestTracer] = \
            RequestTracer() if rt_on else None
        # SLO classification rides the unified terminal records (cheap
        # host arithmetic, no syncs) so it stays on even without tracing
        self.slo = getattr(engine, "slo", None)
        # --serve-trace-out (ISSUE 20): export the offered load as a
        # replayable tracefmt trace at run() end — recorded traffic and
        # synthetic bench load become interchangeable twin inputs. A
        # fleet clears this per replica and exports ONE pool-wide trace.
        self.trace_out = str(getattr(cfg, "serve_trace_out", "") or "")
        self._trace_arrivals: List[Request] = []
        self._t0 = time.perf_counter()  # run() re-anchors

    # ----------------------------------------------------------- terminal
    def _terminal(self, req: Request, now_s: float, reason: str,
                  kv_pages: int = 0) -> Dict[str, Any]:
        """Every outcome funnels through here: build the UNIFIED terminal
        record (ISSUE 15 satellite — done/shed/failed/timeout all carry
        the same field schema), classify it against the SLO objectives,
        and close the request's trace."""
        rec = terminal_record(req, now_s, kv_pages, reason)
        if self.slo is not None:
            self.slo.observe(rec)
        if self.tracer is not None:
            self.tracer.on_terminal(req, now_s, rec)
        return rec

    # --------------------------------------------------------- degradation
    def _shed(self, req: Request, reason: str, now_s: float) -> None:
        req.outcome = "shed"
        req.shed_reason = reason
        req.finish_s = now_s
        self.shed.append(req)
        self.stats["shed_" + reason] += 1
        rec = self._terminal(req, now_s, reason)
        tel.event("serve/request_shed", cat="serve", reason=reason,
                  waited_s=max(0.0, now_s - req.arrival_s), **rec)

    def _fail(self, req: Request, outcome: str, now_s: float,
              err: Optional[BaseException] = None) -> None:
        req.outcome = outcome
        req.finish_s = now_s
        req.slot = None
        self.failed.append(req)
        self.stats["failed"] += 1
        rec = self._terminal(
            req, now_s,
            "decode_timeout" if outcome == "timeout" else "error")
        tel.event("serve/request_failed", cat="serve",
                  error=repr(err)[:200] if err else "", **rec)

    def _enqueue(self, req: Request, waiting: List[Request],
                 now_s: float) -> None:
        """The shed-or-queue decision for one arrival. The decisions
        themselves live in `fleet.AdmissionControl` (the PR 11 machinery,
        lifted to where the fleet can share it); this wrapper keeps the
        side effects — tracing, shed telemetry, terminal records — on the
        replica that owns the request."""
        if self.tracer is not None:
            self.tracer.on_submit(req, now_s)
        # getattr: admission-probe test doubles duck-type the scheduler
        # without running __init__
        if getattr(self, "trace_out", ""):
            self._trace_arrivals.append(req)
        reason = self.admission.permanent_shed_reason(req)
        if reason is not None:
            self._shed(req, reason, now_s)
            return
        victim = self.admission.queue_or_displace(req, waiting)
        if victim is not None:
            self._shed(victim, "queue_full", now_s)

    def _shed_stale(self, waiting: List[Request], now_s: float) -> None:
        """Deadline/TTFT-budget sweep: shed waiters that can no longer be
        served in time (their elapsed wait plus the EMA prefill service
        time already blows the budget) — serving them would burn slots on
        dead-on-arrival responses."""
        for r, reason in self.admission.stale(waiting, now_s,
                                              self._ema_serve_ms):
            self._shed(r, reason, now_s)

    def _pick_wedged(self, active: Dict[int, Request]) -> int:
        """Deterministic eviction choice for a wedged/faulted decode
        batch: the longest-resident slot (most tokens; ties to the lowest
        slot id)."""
        return max(active.items(),
                   key=lambda it: (len(it[1].tokens), -it[0]))[0]

    def _evict_wedged(self, active: Dict[int, Request], outcome: str,
                      now_s: float, err: Optional[BaseException]) -> None:
        slot = self._pick_wedged(active)
        req = active.pop(slot)
        self.kv.evict(slot)
        self.kv.push()
        if self._spec:
            self.draft.kv.evict(slot)
            self.draft.kv.push()
        self.stats["evicted_wedged"] += 1
        tel.event("serve/slot_evicted", cat="serve", rid=req.rid, slot=slot,
                  outcome=outcome, tokens=len(req.tokens))
        self._fail(req, outcome, now_s, err)

    # ------------------------------------------------------------ admission
    def _admit(self, waiting: List[Request], active: Dict[int, Request],
               next_host: np.ndarray, now_s: float) -> bool:
        """Place as many waiting requests as slots/pages/chunk budget
        allow (priority-first), prefill them as one batch, commit K/V,
        record TTFT. Returns True if any were admitted. Host page tables
        are pushed BEFORE the commit so the scatter sees the new pages."""
        free = self.kv.free_slots()
        batch: List[Request] = []
        chunk_used = 0
        waiting.sort(key=_urgency)
        i = 0
        while i < len(waiting) and free:
            req = waiting[i]
            if self.prefill_chunk_tokens and batch and \
                    chunk_used + len(req.prompt) > self.prefill_chunk_tokens:
                break  # chunked admission: the rest joins the next wave
            # speculation slack: a verify pass caches up to K entries past
            # the committed extent, so the page reservation grows by K —
            # rollback must never need pages the admit didn't grant
            need = (len(req.prompt) + req.max_new_tokens
                    + self.dispatch_ahead + self.spec_tokens)
            if not self.kv.can_admit(need):
                # tiered: spill an active slot's pages to the host tier to
                # make HBM room before conceding backpressure
                if not (self.tiered and self._make_room(need, active)):
                    break  # page backpressure: keep queued
            slot = free[0]
            try:
                run_resilient(
                    "serve/kv_admit",
                    lambda s=slot, r=req, n=need:
                        self.kv.admit(s, len(r.prompt), n),
                    policy=self.retry_policy)
            except KVPoolExhausted:
                break  # lost a race below can_admit: keep queued
            except Exception as e:  # noqa: BLE001 — escalated injected/IO
                waiting.pop(i)
                self._fail(req, "failed", now_s, e)
                continue
            if self._spec:
                try:  # mirror the reservation in the draft's cache
                    self.draft.kv.admit(slot, len(req.prompt), need)
                except KVPoolExhausted:
                    self.kv.evict(slot)
                    break
            free.pop(0)
            req.slot = slot
            chunk_used += len(req.prompt)
            batch.append(waiting.pop(i))
        if not batch:
            return False
        self.kv.push()
        if self._spec:
            self.draft.kv.push()
        ids = np.zeros((self.slots, self.seq), np.int32)
        lengths = np.zeros((self.slots,), np.int32)
        for req in batch:
            n = min(len(req.prompt), self.seq)
            ids[req.slot, :n] = req.prompt[:n]
            lengths[req.slot] = n
        t_pre = time.perf_counter()
        try:
            logits, kv_state = run_resilient(
                "serve/prefill",
                lambda: self.engine.prefill(
                    self.params, self.prompt_inputs_fn(ids, lengths)),
                policy=self.retry_policy)
        except Exception as e:  # noqa: BLE001 — permanent prefill fault:
            for req in batch:   # fail ONLY the batch being admitted
                self.kv.evict(req.slot)
                if self._spec:
                    self.draft.kv.evict(req.slot)
                self._fail(req, "failed", self._now(), e)
            self.kv.push()
            if self._spec:
                self.draft.kv.push()
            return False
        self.kv.commit_prefill(kv_state,
                               np.arange(self.slots, dtype=np.int32), lengths)
        if self._spec:
            # the draft prefills the SAME prompt batch into its own cache;
            # positions stay pairwise consistent with the target from here
            try:
                _dlg, dkv_state = run_resilient(
                    "serve/prefill",
                    lambda: self.draft.prefill(
                        self.draft.params, self.prompt_inputs_fn(ids, lengths)),
                    policy=self.retry_policy)
            except Exception as e:  # noqa: BLE001
                for req in batch:
                    self.kv.evict(req.slot)
                    self.draft.kv.evict(req.slot)
                    self._fail(req, "failed", self._now(), e)
                self.kv.push()
                self.draft.kv.push()
                return False
            self.draft.kv.commit_prefill(
                dkv_state, np.arange(self.slots, dtype=np.int32), lengths)
        self.prefills += 1
        lg = np.asarray(logits)  # sync: TTFT is a real materialization
        t_first = time.perf_counter()
        serve_ms = 1e3 * (t_first - t_pre)
        self._ema_serve_ms = (serve_ms if not self._ema_serve_ms
                              else 0.5 * self._ema_serve_ms + 0.5 * serve_ms)
        t_pre_off = t_pre - self._t0
        t_first_off = t_first - self._t0
        for req in batch:
            first = int(lg[req.slot, lengths[req.slot] - 1].argmax())
            req.tokens.append(first)
            req.ttft_s = t_first_off - req.arrival_s
            req.admit_s = t_pre_off
            next_host[req.slot, 0] = first
            active[req.slot] = req
            if self.tracer is not None:
                # closes the queue stage at prefill dispatch and spans the
                # prefill wave to the TTFT sync — both timestamps already
                # taken above, nothing extra is materialized
                self.tracer.on_admit(req, t_pre_off, t_first_off,
                                     wave=self.prefills)
            tel.event("serve/request_admitted", cat="serve", rid=req.rid,
                      slot=req.slot, prompt_len=int(lengths[req.slot]),
                      priority=req.priority, ttft_s=req.ttft_s,
                      queue_wait_s=max(0.0, t_pre_off - req.arrival_s))
        return True

    # ---------------------------------------------------------- tier rotation
    def _park(self, slot: int, active: Dict[int, Request]) -> None:
        """Spill one active slot to the host tier. Only called at sync
        points (the window was just materialized), so the request's token
        list and the KV position mirrors agree on the committed extent."""
        req = active.pop(slot)
        self.kv.spill(slot, self.decode_steps)
        if self._spec:
            self.draft.kv.spill(slot, self.decode_steps)
        self.parked[slot] = req
        tel.event("serve/slot_parked", cat="serve", rid=req.rid, slot=slot,
                  tokens=len(req.tokens))

    def _make_room(self, need: int, active: Dict[int, Request]) -> bool:
        """Spill active slots (largest remaining decode budget first — the
        fairness heuristic: the request farthest from finishing donates
        its HBM residency) until `need` pages fit. Spills publish their
        table/active updates immediately so a failed admission afterwards
        can never leave a parked slot looking active on device."""
        spilled = False
        while not self.kv.can_admit(need):
            cands = [s for s in active
                     if self.kv.can_spill(s)
                     and (not self._spec or self.draft.kv.can_spill(s))]
            if not cands:
                break
            slot = max(cands, key=lambda s: (
                active[s].max_new_tokens - len(active[s].tokens), -s))
            self._park(slot, active)
            spilled = True
        if spilled:
            self.kv.push()
            if self._spec:
                self.draft.kv.push()
        return self.kv.can_admit(need)

    def _rotate(self, active: Dict[int, Request], next_host: np.ndarray,
                now_s: float) -> bool:
        """One rotation round at a sync point: issue host→HBM prefetches
        for parked slots (FIFO by park order, as far as device pages
        allow), then rejoin slots whose prefetch has had `prefetch_ahead`
        decode steps to land — or immediately when nothing is active (the
        forced join counts as a stall, never a silent block). Returns True
        when device state changed (caller refreshes its local handles)."""
        changed = False
        for slot in list(self.parked):
            if slot in self.kv._inflight:
                continue
            if not self.kv.prefetch(slot, self.decode_steps):
                break  # device pages short: retry next sync point
            if self._spec:
                self.draft.kv.prefetch(slot, self.decode_steps)
            changed = True
        for slot in list(self.parked):
            issued = self.kv._inflight.get(slot)
            if issued is None:
                continue
            lead = self.decode_steps - issued
            if lead < self.prefetch_ahead and active:
                continue  # not ready and decode has other work
            stalled = self.kv.join(slot, self.decode_steps,
                                   self.prefetch_ahead)
            if self._spec:
                self.draft.kv.join(slot, self.decode_steps,
                                   self.prefetch_ahead)
            req = self.parked.pop(slot)
            # re-seed the decode feedback: the next step consumes the last
            # committed token at the preserved position — this is what
            # makes the spill path bitwise-identical to staying resident
            next_host[slot, 0] = req.tokens[-1]
            active[slot] = req
            changed = True
            if self.tracer is not None:
                # the parked interval tiles into the request's timeline as
                # its own stage, charged to the rejoin sync
                self.tracer.stage(req, "kv_prefetch", now_s,
                                  stalled=int(stalled),
                                  pages=len(self.kv._slot_pages.get(slot, ())))
            tel.event("serve/slot_rejoined", cat="serve", rid=req.rid,
                      slot=slot, stalled=int(stalled), lead_steps=int(lead))
        if changed:
            self.kv.push()
            if self._spec:
                self.draft.kv.push()
            self._emit_tier()
        return changed

    def _maybe_autotune(self, decode_step_s: float) -> None:
        """First measured decode step closes the autotune loop: the lead
        becomes ceil(learned kv_transfer seconds / measured step seconds)
        — the number of steps a slot refill actually needs to hide behind
        decode compute on THIS machine, per the refit host-link
        coefficient. No learned model resolved -> `self._autotune_transfer_s`
        is None and the flag value stays authoritative."""
        if self._autotune_transfer_s is None or self._autotuned:
            return
        self._autotuned = True
        tuned = derive_prefetch_ahead(self._autotune_transfer_s,
                                      decode_step_s, self.prefetch_ahead)
        tel.event("serve/kv_prefetch_autotune", cat="serve",
                  learned_transfer_s=float(self._autotune_transfer_s),
                  decode_step_s=float(decode_step_s),
                  prefetch_ahead=int(tuned),
                  fallback=int(self.prefetch_ahead))
        self.prefetch_ahead = tuned

    # -------------------------------------------------- disaggregated handoff
    def _handoff_all(self, active: Dict[int, Request]) -> None:
        """Prefill-only mode (ISSUE 18 `--serve-fleet-topology disagg`):
        right after the TTFT materialization, every admitted slot is
        spilled to the host tier, its committed K/V exported, and the
        request handed to the fleet's decode pool via the `handoff`
        callback. A slot that cannot spill (host pages short) simply stays
        and decodes locally — colocated fallback, never a drop."""
        moved = False
        for slot in list(active):
            if not self.kv.can_spill(slot):
                continue
            req = active.pop(slot)
            self.kv.spill(slot, self.decode_steps)
            payload = self.kv.export_parked(slot)
            self.kv.evict(slot)
            req.slot = None
            moved = True
            self.handoffs += 1
            tel.event("serve/request_handoff", cat="serve", rid=req.rid,
                      pages=int(payload["pages"]), tokens=len(req.tokens))
            self.handoff(req, payload)
        if moved:
            self.kv.push()

    def _ingest_handoffs(self, now_s: float) -> None:
        """Decode-side of the handoff: adopt each pending payload into the
        host tier as a PARKED slot (position preserved), so the ordinary
        rotation prefetches + rejoins it — bitwise the spill path. A short
        host free list keeps the payload pending (backpressure, retried at
        the next sync point)."""
        still: List = []
        for req, payload in self._pending_handoffs:
            free = self.kv.free_slots()
            if not free or not self.kv.can_import(payload):
                still.append((req, payload))
                continue
            slot = free[0]
            self.kv.import_parked(slot, payload)
            req.slot = slot
            self.parked[slot] = req
            if self.tracer is not None:
                self.tracer.on_submit(req, now_s)
            tel.event("serve/request_adopted", cat="serve", rid=req.rid,
                      slot=slot, pages=int(payload["pages"]))
        self._pending_handoffs = still

    def _emit_tier(self) -> None:
        ts = self.kv.tier_stats()
        tel.counter("serve/kv_tier_hot_pages", ts["kv_hot_pages"],
                    cat="serve")
        tel.counter("serve/kv_tier_cold_pages", ts["kv_cold_pages"],
                    cat="serve")
        tel.counter("serve/kv_prefetch_hits", ts["kv_prefetch_hits"],
                    cat="serve")
        tel.counter("serve/kv_prefetch_stalls", ts["kv_prefetch_stalls"],
                    cat="serve")
        tel.counter("serve/kv_spills", ts["kv_spills"], cat="serve")

    # ------------------------------------------------------------- finish
    def _finish(self, req: Request, now_s: float) -> None:
        req.outcome = "done"
        req.finish_s = now_s
        kv_pages = len(self.kv._slot_pages.get(req.slot, ()))
        self.kv.evict(req.slot)
        if self._spec:
            self.draft.kv.evict(req.slot)
        self.completed.append(req)
        reason = ("eos" if self.eos_id is not None
                  and self.eos_id in req.tokens else "max_new_tokens")
        rec = self._terminal(req, now_s, reason, kv_pages=kv_pages)
        tel.event("serve/request_done", cat="serve",
                  tokens=len(req.tokens), **rec)

    def _truncate(self, req: Request) -> bool:
        """Apply EOS/max-len to a request's token list; True = finished."""
        toks = req.tokens
        if self.eos_id is not None and self.eos_id in toks:
            del toks[toks.index(self.eos_id) + 1:]
            return True
        if len(toks) >= req.max_new_tokens:
            del toks[req.max_new_tokens:]
            return True
        return False

    def _window_cap(self, active: Dict[int, Request]) -> int:
        """Dispatch-window length: bounded by `dispatch_ahead` AND the
        smallest remaining token budget across active slots, so the loop
        never speculates past a max-len finish (the `scheduler.py`
        over-decode waste fix of ISSUE 11)."""
        if not active:
            return self.dispatch_ahead
        rem = min(r.max_new_tokens - len(r.tokens) for r in active.values())
        return max(1, min(self.dispatch_ahead, rem))

    def _materialize(self, window_toks: List[Any], state,
                     active: Dict[int, Request], window_t0: float
                     ) -> np.ndarray:
        """Drain a dispatched window: one host sync pulls every step's
        tokens, advances the host KV mirrors (per-slot — an EOS finish
        inside the window is masked out of the committed advance), evicts
        finished slots, and applies the decode watchdog. Returns the last
        step's tokens (the next window's seed)."""
        mats = [np.asarray(t) for t in window_toks]
        steps = len(mats)
        t_now = time.perf_counter()
        self.materializations += 1
        per_step = (t_now - window_t0) / steps
        self.step_times.extend([per_step] * steps)
        self._maybe_autotune(per_step)
        adv = np.zeros((self.slots,), np.int32)
        finished: List[int] = []
        for slot, req in active.items():
            prev = len(req.tokens)
            req.tokens.extend(int(m[slot, 0]) for m in mats)
            if self._truncate(req):
                kept = max(0, len(req.tokens) - prev)
                adv[slot] = kept
                self.stats["overdecode_tokens"] += steps - kept
                finished.append(slot)
            else:
                adv[slot] = steps
        if self.tracer is not None:
            # attribute the drained window to every slot that decoded in
            # it, using the t_now this sync already produced
            self.tracer.on_decode_window(
                list(active.values()), t_now - self._t0, steps, per_step,
                {slot: int(adv[slot]) for slot in active})
        self.kv.adopt(state)
        self.kv.sync_after(steps, advances=adv)
        for slot in finished:
            self._finish(active.pop(slot), self._now())
        if self.stats["overdecode_tokens"]:
            tel.counter("serve/overdecode_tokens",
                        self.stats["overdecode_tokens"], cat="serve")
        if self.decode_timeout_ms and active and \
                per_step * 1e3 > self.decode_timeout_ms:
            # bounded-step watchdog: the window came back slower than the
            # per-step budget — evict the longest-resident slot instead
            # of letting one wedged sequence stall every neighbour
            self.stats["decode_timeouts"] += 1
            tel.event("serve/decode_timeout", cat="serve",
                      per_step_ms=1e3 * per_step,
                      budget_ms=self.decode_timeout_ms)
            self._evict_wedged(active, "timeout", self._now(), None)
        return mats[-1].copy()

    # --------------------------------------------------------- speculation
    def _spec_round(self, active: Dict[int, Request],
                    next_host: np.ndarray) -> np.ndarray:
        """One speculative round: K chained greedy draft steps, ONE
        batched target verify pass over `[last, d1..dK]`, then the
        longest-accepted-prefix commit. Every committed token is the
        verify program's argmax (the mismatch slot commits the target's
        correction token), so greedy streams are bitwise identical to
        non-speculative decode. Full acceptance caps the commit at K —
        the draft never cached d_K's K/V, so committing the K+1'th
        (bonus) token would start the next round with a draft-cache hole.

        Device work and materializations all happen before any host
        mutation, so a retried round (transient decode fault) replays
        cleanly off the unchanged host mirrors."""
        K = self.spec_tokens
        t0 = time.perf_counter()
        with self.exec_lock:
            dstate = self.draft.kv.state
            tstate = self.kv.state
            last = jnp.asarray(next_host)
            if self._spec_fused is not None:
                # the whole round is ONE program launch (see
                # engine.build_spec_program) — the draft chain's argmax
                # feedback never leaves the device
                t_pred_dev, ver_in, tstate, dstate = \
                    self.engine.spec_round_step(
                        self.params, self.draft.params, tstate, dstate,
                        last, self.step_inputs_fn)
            else:
                # unfused fallback (untraceable step_inputs_fn): K+1
                # launches
                cur = last
                drafts = []
                for _ in range(K):
                    dlogits, dstate = self.draft.decode_step(
                        self.draft.params, dstate,
                        self.step_inputs_fn(cur, dstate))
                    cur = jnp.argmax(dlogits[:, -1, :], axis=-1).astype(
                        jnp.int32)[:, None]
                    drafts.append(cur)
                ver_in = jnp.concatenate([last] + drafts, axis=1)
                vlogits, tstate = self.engine.verify_step(
                    self.params, tstate, self.step_inputs_fn(ver_in, tstate))
                t_pred_dev = jnp.argmax(vlogits, axis=-1).astype(jnp.int32)
            t_pred = np.asarray(t_pred_dev)
            drafted = np.asarray(ver_in)[:, 1:]              # [slots, K]
            if self._exec_serialized:
                jax.block_until_ready((tstate, dstate))
        wall = time.perf_counter() - t0
        self.materializations += 1
        t_end_off = (t0 + wall) - self._t0
        # ---- host commit: nothing below touches the device programs ----
        match = drafted == t_pred[:, :-1]                    # [slots, K]
        adv = np.zeros((self.slots,), np.int32)
        out = next_host.copy()
        finished: List[int] = []
        round_accept = 0
        max_commit = 1
        for slot, req in active.items():
            m = match[slot]
            j = K if m.all() else int(m.argmin())  # accepted draft tokens
            ncommit = min(j + 1, K)
            committed = [int(t) for t in t_pred[slot, :ncommit]]
            prev = len(req.tokens)
            req.tokens.extend(committed)
            round_accept += j
            if self._truncate(req):
                kept = max(0, len(req.tokens) - prev)
                adv[slot] = kept
                self.stats["overdecode_tokens"] += ncommit - kept
                finished.append(slot)
            else:
                adv[slot] = ncommit
                out[slot, 0] = committed[-1]
            max_commit = max(max_commit, ncommit)
            if self.tracer is not None:
                # drafted-vs-committed-vs-rejected per slot, timed off the
                # round's already-taken wall timestamp
                self.tracer.on_spec_round(
                    req, t_end_off, drafted=K, committed=ncommit,
                    rejected=K - min(j, K))
        for kv, st in ((self.kv, tstate), (self.draft.kv, dstate)):
            kv.adopt(st)
            kv.sync_after(0, advances=adv)
            kv.push()  # re-publish the COMMITTED extent: the device-side
            #            speculative advance (K for draft, K+1 for the
            #            verify pass) rolls back to what acceptance kept
        for slot in finished:
            self._finish(active.pop(slot), self._now())
        round_drafted = K * max(1, len(finished) + len(active))
        self.stats["spec_rounds"] += 1
        self.stats["spec_drafted_tokens"] += round_drafted
        self.stats["spec_accepted_tokens"] += round_accept
        rate = round_accept / round_drafted
        self._accept_ema = (rate if self.stats["spec_rounds"] == 1
                            else 0.9 * self._accept_ema + 0.1 * rate)
        tel.counter("serve/spec_drafted_tokens",
                    self.stats["spec_drafted_tokens"], cat="serve")
        tel.counter("serve/spec_accepted_tokens",
                    self.stats["spec_accepted_tokens"], cat="serve")
        tel.counter("serve/spec_accept_rate", self._accept_ema, cat="serve")
        per_tok = wall / max_commit
        self.step_times.extend([per_tok] * max_commit)
        self._maybe_autotune(per_tok)
        if self.tracer is not None:
            self.tracer.hists["decode_step"].add(per_tok, n=max_commit)
        self.decode_steps += K + 1
        if self.decode_timeout_ms and active and \
                1e3 * wall / (K + 1) > self.decode_timeout_ms:
            self.stats["decode_timeouts"] += 1
            tel.event("serve/decode_timeout", cat="serve",
                      per_step_ms=1e3 * wall / (K + 1),
                      budget_ms=self.decode_timeout_ms)
            self._evict_wedged(active, "timeout", self._now(), None)
        return out

    def _now(self) -> float:
        return time.perf_counter() - self._t0

    # --------------------------------------------------------------- loop
    def run(self, requests: List[Request]) -> List[Request]:
        """Serve `requests` (arrival_s offsets define the open-loop trace)
        to completion; returns the COMPLETED ones with tokens + latency
        fields filled. Shed and failed requests land in `self.shed` /
        `self.failed` with their outcome + reason stamped."""
        self._t0 = time.perf_counter()
        if self.tracer is not None:
            self.tracer.begin(self._t0)
        queue = deque(sorted(requests, key=lambda r: (r.arrival_s, r.rid)))
        waiting: List[Request] = []
        active: Dict[int, Request] = {}
        next_host = np.zeros((self.slots, 1), np.int32)
        state = self.kv.state
        next_dev = jnp.asarray(next_host)
        window_toks: List[Any] = []  # dispatched, unmaterialized [slots,1]
        window_t0 = time.perf_counter()

        while (queue or waiting or active or self.parked
               or self._pending_handoffs
               or (self.feed is not None and not self.feed.exhausted)):
            now = self._now()
            if self.feed is not None:
                # fleet feed: the router delivers arrivals (and handed-off
                # prefill payloads) while the loop runs
                for item in self.feed.drain():
                    if isinstance(item, tuple):
                        self._pending_handoffs.append(item)
                    else:
                        self._enqueue(item, waiting, now)
            while queue and queue[0].arrival_s <= now:
                self._enqueue(queue.popleft(), waiting, now)
            self.queue_depth = len(waiting)
            self.active_count = len(active)
            tel.counter("serve/queue_depth", len(waiting), cat="serve")
            tel.counter("serve/active_slots", len(active), cat="serve")
            want_sync = (len(window_toks) >= self._window_cap(active)
                         or (waiting and self.kv.free_slots())
                         or bool(self.parked)
                         or bool(self._pending_handoffs)
                         or not active)
            if want_sync and window_toks:
                # materialize the dispatched window: one host sync drains
                # every step's tokens (tiny [slots,1] arrays)
                next_host = self._materialize(window_toks, state, active,
                                              window_t0)
                window_toks = []
                state = self.kv.state
                window_t0 = time.perf_counter()
            if not window_toks and (self.control is not None
                                    or self.engine.watching):
                # safe swap point: nothing dispatched references params.
                # Under a fleet, the rolling controller decides whether
                # THIS replica may advance (or must roll back) here.
                swapped = (self.control.at_safe_point(self)
                           if self.control is not None
                           else self.engine.poll_swap())
                if swapped:
                    self.params = self.engine.params
                    self.stats["swaps"] += 1
                    state = self.kv.state
                    if self.tracer is not None:
                        # the swap landed between windows: mark it inside
                        # every in-flight request's timeline
                        self.tracer.on_swap(
                            list(active.values()), self._now(),
                            getattr(self.engine, "active_version", None))
            if waiting:
                self._shed_stale(waiting, self._now())
            if self._pending_handoffs and not window_toks:
                # disaggregated decode side: adopt handed-off prefills into
                # the host tier; the rotation below carries them to HBM
                self._ingest_handoffs(self._now())
            if self.parked and not window_toks:
                # tier rotation at this sync point: prefetch-ahead issues +
                # ready/forced rejoins (forced = active drained, a counted
                # stall); runs before admission so rejoining slots claim
                # device pages ahead of new arrivals (they are older)
                self._rotate(active, next_host, self._now())
            if waiting and self.kv.free_slots():
                if self._admit(waiting, active, next_host, self._now()):
                    state = self.kv.state
                    next_dev = jnp.asarray(next_host)
                    window_t0 = time.perf_counter()
            if self.handoff is not None and active and not window_toks:
                # prefill replica: everything admitted leaves for the
                # decode pool right after its TTFT materialization
                self._handoff_all(active)
                state = self.kv.state
            if self.tiered and not window_toks:
                # rotation/spill mutate device state outside _admit's
                # refresh; re-anchor at drained-window points only — with
                # steps in flight the local `state` is AHEAD of the pool
                # mirror, and resetting to it would re-dispatch the last
                # materialized token (untiered runs keep the exact pre-PR
                # dispatch sequence)
                state = self.kv.state
                next_dev = jnp.asarray(next_host)
            if not active:
                if queue and not waiting:
                    # open loop: idle until the next arrival (short naps
                    # when watching, so snapshot polls keep happening)
                    wait = max(0.0, queue[0].arrival_s - self._now())
                    time.sleep(min(wait, 0.05)
                               if (self.engine.watching
                                   or self.control is not None)
                               else wait)
                elif self.feed is not None and not waiting \
                        and not self.parked and not self._pending_handoffs:
                    # fed loop with nothing in hand: nap instead of
                    # spinning on the (still open) feed
                    time.sleep(0.002)
                continue
            if self._spec:
                # speculative rounds are self-contained (draft chain +
                # verify + host commit) — no dispatch-ahead window, every
                # round is a sync point, so poll_swap stays safe above
                try:
                    next_host = run_resilient(
                        "serve/decode_step",
                        lambda nh=next_host: self._spec_round(active, nh),
                        policy=self.retry_policy)
                except Exception as e:  # noqa: BLE001 — permanent fault
                    if active:
                        self._evict_wedged(active, "failed", self._now(), e)
                state = self.kv.state
                next_dev = jnp.asarray(next_host)
                continue
            inputs = self.step_inputs_fn(next_dev, state)
            try:
                logits, state = run_resilient(
                    "serve/decode_step",
                    lambda s=state, ins=inputs:
                        self.engine.decode_step(self.params, s, ins),
                    policy=self.retry_policy)
            except Exception as e:  # noqa: BLE001 — permanent decode fault
                # drain what WAS dispatched successfully, then evict the
                # wedged slot; every other slot keeps serving
                if window_toks:
                    next_host = self._materialize(window_toks, state, active,
                                                  window_t0)
                    window_toks = []
                if active:
                    self._evict_wedged(active, "failed", self._now(), e)
                state = self.kv.state
                next_dev = jnp.asarray(next_host)
                window_t0 = time.perf_counter()
                continue
            with self.exec_lock:
                # the argmax over model-sharded logits is its own collective
                # program; under a fleet it must not interleave with a
                # sibling replica's collectives (the engine call above
                # serializes inside the proxy — this is the one launch the
                # scheduler itself owns)
                next_dev = jnp.argmax(
                    logits[:, -1, :], axis=-1).astype(jnp.int32)[:, None]
                if self._exec_serialized:
                    jax.block_until_ready(next_dev)
            window_toks.append(next_dev)
            self.decode_steps += 1
        if self.tiered:
            # final tier ledger: counters into telemetry (monitor/prom) and
            # into stats (the bench + tests read them from here)
            self._emit_tier()
            self.stats.update(self.kv.tier_stats())
        if self.tracer is not None:
            # publish the live histograms + SLO scoreboard into the
            # telemetry stream (monitor/prom read them from here)
            self.tracer.emit_hists()
        if self.slo is not None and tel.enabled():
            tel.event("serve/slo", cat="serve", report=self.slo.report())
        if getattr(self.engine.cfg, "profile_ops", False) and tel.enabled():
            # --profile-ops (ISSUE 14 satellite): featurize this run's
            # prefill + decode placements into op/attr corpus rows, with
            # the run's REAL wall times as the step normalizers — the
            # learned cost model's only window into the bandwidth-bound
            # seq=1 decode regime training fits never exercise
            try:
                self.engine.op_attribution(
                    step_time_s=(float(np.median(self.step_times))
                                 if self.step_times else None),
                    prefill_step_time_s=(self._ema_serve_ms / 1e3
                                         if self._ema_serve_ms else None))
            except Exception:  # noqa: BLE001 — never fail a served batch
                pass
        if getattr(self, "trace_out", "") and self._trace_arrivals:
            from flexflow_tpu.serving import tracefmt
            tracefmt.save_trace(
                self.trace_out,
                tracefmt.requests_to_records(
                    sorted(self._trace_arrivals,
                           key=lambda r: (r.arrival_s, r.rid))),
                meta={"source": "scheduler", "slots": self.slots,
                      "seq": self.seq})
        return self.completed
