"""Capacity twin: a discrete-event replay of the serving control plane.

ROADMAP item 5 (the FlexFlow thesis, 1807.05358, applied to serving):
configuration questions — "what happens to ttft_p99 if we add a replica /
raise spec K / flip kv dtype / shrink the HBM pool" — should be answered
by a CALIBRATED simulator, not a heuristic or a hardware run. The twin
replays any `serving/tracefmt.py` trace (recorded live traffic and bench
generators are interchangeable) through the REAL control-plane classes:

- admission via `AdmissionControl` (the same permanent-shed / queue-cap /
  staleness brain the scheduler and fleet run),
- placement via `FleetRouter` (sim replicas duck-type `ReplicaHandle`'s
  router-visible signals: outstanding, queue depth, EMA service time),
- slot/page accounting via `KVCacheSpec` geometry (device pool + host
  tier, spill/prefetch priced at the host-link rate with the
  `kv_prefetch_ahead` hiding rule),
- spec rounds as expected-commit batching (1 + accept_rate * K tokens
  per verify round),
- prefill/decode disaggregation with the KV handoff priced like the
  PR-18 `kv_transfer` rows.

Durations come from `TwinCosts`, resolved learned-model-first
(`search/learned_cost.py` rows the twin itself emits close the loop via
tools/refit_cost_model.py), then live-measurement calibration, then the
analytic roofline. Outputs are bitwise the live schema: terminal records
through `reqtrace.terminal_record`, the same `StreamingHistogram` metrics,
and an `SLOTracker` scoreboard — so twin-vs-live validation is a plain
report diff, and `health.scaling_signal` reads twin output exactly as it
reads production output.
"""

from __future__ import annotations

import dataclasses
import heapq
import math
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from flexflow_tpu.health import (SLOTracker, parse_slo, scaling_signal)
from flexflow_tpu.search.cost_model import KVCacheSpec
from flexflow_tpu.serving.fleet import AdmissionControl, FleetRouter
from flexflow_tpu.serving.reqtrace import (HIST_METRICS, StreamingHistogram,
                                           terminal_record)
from flexflow_tpu.serving.scheduler import _urgency
from flexflow_tpu.serving.tracefmt import TraceRecord, scale_rate

__all__ = ["TwinSpec", "TwinCosts", "TwinResult", "simulate",
           "capacity_curve", "validate", "emit_residual_rows",
           "signal_timeline", "calibrate_window_overhead"]


class _Len:
    """A length without the storage: terminal_record/admission only ever
    take len() of prompts and token lists, so the twin carries counts."""

    __slots__ = ("n",)

    def __init__(self, n: int):
        self.n = int(n)

    def __len__(self) -> int:
        return self.n


class _SimReq:
    """The twin's request: exactly the fields `AdmissionControl`,
    `_urgency` and `terminal_record` read off a live `Request`, with
    token/prompt lists replaced by counted lengths."""

    __slots__ = ("rid", "prompt", "max_new_tokens", "arrival_s", "priority",
                 "deadline_s", "tokens", "ttft_s", "admit_s", "finish_s",
                 "outcome", "kv_pages", "host_pages", "phase")

    def __init__(self, rec: TraceRecord, rid: int):
        self.rid = rec.rid if rec.rid is not None else rid
        self.prompt = _Len(rec.tokens_in)
        self.max_new_tokens = int(rec.max_tokens)
        self.arrival_s = float(rec.arrival_ts)
        self.priority = int(rec.priority)
        self.deadline_s = rec.deadline
        self.tokens = _Len(0)
        self.ttft_s: Optional[float] = None
        self.admit_s: Optional[float] = None
        self.finish_s: Optional[float] = None
        self.outcome = ""
        self.kv_pages = 0       # device pages held
        self.host_pages = 0     # host-tier pages borrowed (spilled)
        self.phase = "arrive"   # arrive | decode (disagg handoff)


# ------------------------------------------------------------------- spec
@dataclasses.dataclass
class TwinSpec:
    """The structural half of a twin scenario: replica topology + the
    scheduler/KV geometry knobs. Temporal behavior lives in `TwinCosts`,
    so one spec sweeps cleanly across pricing assumptions."""

    replicas: int = 1
    slots: int = 4
    seq: int = 16                 # prefill window (max prompt positions)
    page_size: int = 4
    pages_per_slot: int = 0       # 0 -> derived from seq + decode budget
    max_decode_len: int = 8
    layers: int = 1
    heads: int = 2
    head_dim: int = 32
    itemsize: int = 4
    scale_itemsize: int = 0
    host_pages: int = 0
    device_pages: int = 0
    dispatch_ahead: int = 4
    spec_tokens: int = 0          # draft K (0 = greedy)
    spec_accept_rate: float = 0.6
    queue_cap: int = 0
    ttft_budget_ms: float = 0.0
    max_context: int = 0
    prefetch_ahead: int = 2
    router: str = "least_loaded"
    slo: str = ""
    topology: str = "colocated"   # "colocated" | "disagg"
    prefill_replicas: int = 1

    def __post_init__(self):
        if not self.pages_per_slot:
            total = self.seq + self.max_decode_len
            self.pages_per_slot = max(1, -(-total // self.page_size))

    def kv_spec(self) -> KVCacheSpec:
        return KVCacheSpec(
            layers=self.layers, heads=self.heads, head_dim=self.head_dim,
            slots=self.slots, pages_per_slot=self.pages_per_slot,
            page_size=self.page_size, itemsize=self.itemsize,
            scale_itemsize=self.scale_itemsize,
            host_pages=self.host_pages, device_pages=self.device_pages)

    @classmethod
    def from_engine(cls, engine: Any, replicas: int = 1,
                    dispatch_ahead: int = 4) -> "TwinSpec":
        """Mirror a live engine's configuration — the twin-vs-live
        validation path builds its spec here so structural drift between
        twin and production is impossible by construction."""
        ks: KVCacheSpec = engine.kv.spec
        cfg = getattr(engine, "cfg", None)
        g = (lambda k, d: getattr(cfg, k, d) if cfg is not None else d)
        return cls(
            replicas=replicas, slots=int(engine.slots),
            seq=int(engine.prefill_model.input_tensors[0].spec.shape[1]),
            page_size=ks.page_size, pages_per_slot=ks.pages_per_slot,
            max_decode_len=int(getattr(engine, "max_decode_len", 0) or
                               ks.padded_len),
            layers=ks.layers, heads=ks.heads, head_dim=ks.head_dim,
            itemsize=ks.itemsize, scale_itemsize=ks.scale_itemsize,
            host_pages=ks.host_pages, device_pages=ks.device_pages,
            dispatch_ahead=dispatch_ahead,
            spec_tokens=int(g("serve_spec_tokens", 0)),
            queue_cap=int(g("serve_queue_cap", 0)),
            ttft_budget_ms=float(g("serve_ttft_budget_ms", 0.0)),
            max_context=int(g("serve_max_context", 0)),
            prefetch_ahead=int(g("kv_prefetch_ahead", 2)),
            router=str(g("serve_router", "least_loaded")),
            slo=str(g("serve_slo", "") or ""),
            topology=str(g("serve_fleet_topology", "colocated")),
            prefill_replicas=int(g("serve_prefill_replicas", 1)))


# ------------------------------------------------------------------ costs
def _twin_features(kind: str, spec: KVCacheSpec, slots: int,
                   machine: Any = None) -> Dict[str, Any]:
    """Feature row for the learned model's `twin_*` kinds — built here AND
    emitted here (emit_residual_rows), so a refit-trained coefficient
    prices exactly the query the twin asks."""
    try:
        from flexflow_tpu.search import memo
        fp = memo.machine_fingerprint(machine) if machine is not None else ()
    except ImportError:
        fp = ()
    return {
        "op": kind,
        "in_shapes": [[slots, spec.page_size, spec.heads, spec.head_dim]],
        "out_shapes": [[slots, spec.page_size, spec.heads, spec.head_dim]],
        "weight_shapes": [],
        "dtype": "int8" if spec.scale_itemsize else "float32",
        "params": 0,
        "layout": f"L{spec.layers}",
        "sharding": {"out": [], "weights": []},
        "machine": fp,
    }


@dataclasses.dataclass
class TwinCosts:
    """The temporal half: every duration the event loop charges.
    `source` records which rung of the resolution ladder priced it —
    "learned" > "measured" > "analytic" — so reports say where their
    numbers came from."""

    decode_step_s: float = 1e-3       # one decode step (all slots)
    prefill_base_s: float = 1e-3      # per prefill program launch
    prefill_per_token_s: float = 0.0  # + per prompt token in the batch
    kv_transfer_page_s: float = 1e-5  # host<->HBM, one page, all layers
    spec_round_factor: float = 1.3    # spec verify round vs plain step
    window_overhead_s: float = 0.0    # host work per dispatch window that
    #   no per-op histogram sees (admission, sampling, materialization
    #   sync) — throughput-limiting under overload; calibrate it as
    #   (wall - histogram-accounted busy) / materializations off a
    #   saturated live run
    source: str = "analytic"

    def prefill_s(self, batch_tokens: int) -> float:
        return self.prefill_base_s + self.prefill_per_token_s * batch_tokens

    def commit_per_step(self, spec_tokens: int, accept: float) -> float:
        """Expected tokens a slot commits per priced step."""
        if spec_tokens <= 0:
            return 1.0
        return 1.0 + max(0.0, min(1.0, accept)) * spec_tokens

    def step_s(self, spec_tokens: int) -> float:
        return self.decode_step_s * (self.spec_round_factor
                                     if spec_tokens > 0 else 1.0)

    # ------------------------------------------------------- resolution
    @classmethod
    def analytic(cls, spec: KVCacheSpec, machine: Any = None,
                 param_bytes: int = 0, step_floor_s: float = 0.0,
                 model_degree: int = 1) -> "TwinCosts":
        """Roofline fallback: decode streams weights + live KV per step,
        prefill is one launch of overhead plus compute per token; the
        host link prices tier traffic. A simulated device-step floor
        (bench fleets pace on one) dominates when present."""
        hbm_bw = getattr(machine, "hbm_bw", 0.0) or 8.1e11
        host_bw = getattr(machine, "host_bw", 0.0) or 16e9
        flops = getattr(machine, "flops_per_chip", 0.0) or 1.97e14
        overhead_s = 5e-5  # host dispatch floor per program launch
        step = (param_bytes + spec.step_read_bytes(model_degree)) / hbm_bw \
            + overhead_s
        per_tok = (2.0 * max(0, param_bytes // 4)) / flops
        return cls(decode_step_s=max(step, step_floor_s),
                   prefill_base_s=max(overhead_s, step_floor_s),
                   prefill_per_token_s=per_tok,
                   kv_transfer_page_s=spec.layers * spec.page_bytes()
                   / host_bw,
                   source="analytic")

    @classmethod
    def from_live_report(cls, report: Dict[str, Any],
                         fallback: "TwinCosts") -> "TwinCosts":
        """Calibrate step/prefill means off a live serving report's
        histograms (`scheduler.tracer.hists` objects or the fleet
        report's summary dicts) — the twin-vs-live path: tell the twin
        how fast a step IS, let queueing/latency behavior emerge."""
        def _mean(m: str) -> Optional[float]:
            h = (report.get("hists") or {}).get(m)
            if h is None:
                return None
            if isinstance(h, dict):
                return h.get("mean")
            mean = getattr(h, "mean", None)
            return mean() if callable(mean) else None

        step = _mean("decode_step")
        pre = _mean("prefill")
        return cls(
            decode_step_s=step if step and step > 0
            else fallback.decode_step_s,
            prefill_base_s=pre if pre and pre > 0
            else fallback.prefill_base_s,
            prefill_per_token_s=0.0 if pre and pre > 0
            else fallback.prefill_per_token_s,
            kv_transfer_page_s=fallback.kv_transfer_page_s,
            spec_round_factor=fallback.spec_round_factor,
            window_overhead_s=fallback.window_overhead_s,
            source="measured")

    @classmethod
    def resolve(cls, spec: KVCacheSpec, cfg: Any = None, machine: Any = None,
                live_report: Optional[Dict[str, Any]] = None,
                param_bytes: int = 0, step_floor_s: float = 0.0,
                model_degree: int = 1, slots: int = 0) -> "TwinCosts":
        """The pricing ladder: learned model (kinds the twin's own
        residual rows teach it) > live measurement > analytic roofline.
        Per-field: a learned kind that never fit falls through alone."""
        out = cls.analytic(spec, machine, param_bytes=param_bytes,
                           step_floor_s=step_floor_s,
                           model_degree=model_degree)
        if live_report is not None:
            out = cls.from_live_report(live_report, out)
        learned = _learned_costs(spec, cfg, machine,
                                 slots=slots or spec.slots)
        if learned:
            for field, val in learned.items():
                setattr(out, field, val)
            out.source = "learned" if len(learned) >= 2 else out.source
        # a learned/measured step can't beat a simulated device floor
        out.decode_step_s = max(out.decode_step_s, step_floor_s)
        out.prefill_base_s = max(out.prefill_base_s, step_floor_s)
        return out


def _learned_costs(spec: KVCacheSpec, cfg: Any, machine: Any,
                   slots: int) -> Dict[str, float]:
    """Query the resolved learned cost model for the twin's op kinds.
    Missing model / unknown kinds return {} — the ladder falls through."""
    import os
    try:
        from flexflow_tpu.search.learned_cost import (LearnedCostModel,
                                                      resolve_model_path)
    except ImportError:
        return {}
    path = resolve_model_path(cfg) if cfg is not None else \
        resolve_model_path(type("_C", (), {"cost_model_path": ""})())
    if not path or not os.path.isfile(path):
        return {}
    try:
        model = LearnedCostModel.load(path)
    except Exception:  # noqa: BLE001 — a corrupt model never breaks the twin
        return {}
    out: Dict[str, float] = {}
    for kind, field in (("twin_decode_step", "decode_step_s"),
                        ("twin_prefill", "prefill_base_s")):
        feats = _twin_features(kind, spec, slots, machine)
        try:
            t = model.predict_features(feats, predicted_s=None,
                                       roofline_s=None)
        except Exception:  # noqa: BLE001
            t = None
        if t is not None and t > 0:
            out[field] = float(t)
    return out


# ------------------------------------------------------------- sim replica
class _SimReplica:
    """One replica's state on its own virtual-time axis. Duck-types the
    router-visible surface of `ReplicaHandle` (outstanding / worst_burn /
    index / sched.queue_depth / sched._ema_serve_ms), so the REAL
    `FleetRouter` places twin work."""

    def __init__(self, index: int, spec: TwinSpec, role: str = "mixed"):
        ks = spec.kv_spec()
        self.index = index
        self.role = role
        self.t = 0.0
        self.waiting: List[_SimReq] = []
        self.active: List[_SimReq] = []
        self.free_slots = int(spec.slots)
        self.free_device = ks.pool_pages - 1   # data pages (minus scratch)
        self.free_host = int(ks.host_pages)
        self.assigned = 0
        self.done = 0
        self._ema_serve_s = 0.05
        self.busy_s = 0.0
        self.stepping = False   # a "step" event is in the heap

    # --- the ReplicaHandle surface FleetRouter reads
    @property
    def sched(self) -> "_SimReplica":
        return self

    @property
    def _ema_serve_ms(self) -> float:
        return self._ema_serve_s * 1e3

    @property
    def queue_depth(self) -> int:
        return len(self.waiting)

    @property
    def outstanding(self) -> int:
        return max(0, self.assigned - self.done)

    def worst_burn(self) -> float:
        return 0.0


# ------------------------------------------------------------------ result
@dataclasses.dataclass
class TwinResult:
    """Twin output in the live report's shape: terminal records (the
    live schema via `terminal_record`), merged histograms, an SLOTracker
    scoreboard, and the scaling-signal timeline the replay produced."""

    completed: List[Dict[str, Any]]
    shed: List[Dict[str, Any]]
    hists: Dict[str, StreamingHistogram]
    slo: SLOTracker
    stats: Dict[str, Any]
    signals: List[Dict[str, Any]]
    spec: TwinSpec
    costs: TwinCosts

    def report(self) -> Dict[str, Any]:
        hists = {m: {"count": h.count, "mean": h.mean(),
                     "p50": h.quantile(0.5), "p99": h.quantile(0.99)}
                 for m, h in self.hists.items() if h.count}
        slo_report = self.slo.report(now_s=self.stats.get("wall_s") or None)
        return {"stats": dict(self.stats), "hists": hists,
                "slo": slo_report, "scaling": scaling_signal(slo_report),
                "signals": list(self.signals),
                "priced_by": self.costs.source}


# -------------------------------------------------------------- event loop
def simulate(records: Sequence[TraceRecord], spec: TwinSpec,
             costs: TwinCosts, signal_every_s: float = 5.0
             ) -> TwinResult:
    """Replay a trace through the twin. Deterministic: same records +
    spec + costs => identical result (no wall clock, no rng)."""
    ks = spec.kv_spec()
    pages_needed = (lambda total:
                    -(-min(int(total), ks.padded_len) // ks.page_size))
    admission = AdmissionControl(
        seq=spec.seq, max_context=spec.max_context,
        queue_cap=spec.queue_cap, ttft_budget_ms=spec.ttft_budget_ms,
        overhead_tokens=spec.dispatch_ahead + spec.spec_tokens,
        pages_needed=pages_needed,
        capacity_pages=lambda: (ks.pool_pages - 1) + ks.host_pages)
    router = FleetRouter(spec.router)
    disagg = spec.topology == "disagg" and spec.replicas > 1
    n_pre = max(1, min(spec.prefill_replicas, spec.replicas - 1)) \
        if disagg else 0
    replicas = [
        _SimReplica(i, spec,
                    role=("prefill" if disagg and i < n_pre else
                          "decode" if disagg else "mixed"))
        for i in range(spec.replicas)]
    prefill_pool = replicas[:n_pre] if disagg else replicas
    decode_pool = replicas[n_pre:] if disagg else replicas

    cps = costs.commit_per_step(spec.spec_tokens, spec.spec_accept_rate)
    step_s = costs.step_s(spec.spec_tokens)
    handoff_pages = pages_needed(spec.seq)  # prefill KV payload (disagg)

    hists = {m: StreamingHistogram() for m in HIST_METRICS}
    terminals: List[Tuple[float, Dict[str, Any]]] = []
    completed: List[Dict[str, Any]] = []
    shed: List[Dict[str, Any]] = []
    counters = {"kv_spilled_pages": 0, "prefetch_stall_s": 0.0,
                "handoffs": 0, "tokens_out": 0, "windows": 0}

    def terminal(req: _SimReq, now_s: float, outcome: str,
                 reason: str) -> None:
        req.outcome = outcome
        req.finish_s = now_s
        rec = terminal_record(req, now_s, req.kv_pages + req.host_pages,
                              reason)
        terminals.append((now_s, rec))
        if outcome == "done":
            completed.append(rec)
            counters["tokens_out"] += rec["tokens_out"]
            if rec["ttft_s"] is not None:
                hists["ttft"].add(rec["ttft_s"])
            if rec["per_token_s"] is not None:
                hists["per_token"].add(rec["per_token_s"])
        else:
            shed.append(rec)
        hists["queue_wait"].add(rec["queue_wait_s"])

    # (time, seq, kind, payload) — seq breaks ties deterministically
    events: List[Tuple[float, int, str, Any]] = []
    eseq = 0

    def push(t: float, kind: str, payload: Any) -> None:
        nonlocal eseq
        heapq.heappush(events, (t, eseq, kind, payload))
        eseq += 1

    def wake(rep: _SimReplica, t: float) -> None:
        if not rep.stepping:
            rep.stepping = True
            push(max(t, rep.t), "step", rep)

    def admit_batch(rep: _SimReplica) -> List[_SimReq]:
        """Most-urgent-first head-of-line admission under slot + two-tier
        page occupancy (mirrors the scheduler's pool backpressure: stop
        at the first waiter that doesn't fit, don't skip past it)."""
        batch: List[_SimReq] = []
        rep.waiting.sort(key=_urgency)
        while rep.waiting and rep.free_slots > 0:
            req = rep.waiting[0]
            budget = (req.max_new_tokens if req.phase != "decode"
                      else max(1, req.max_new_tokens - len(req.tokens)))
            need = pages_needed(len(req.prompt) + budget
                                + admission.overhead_tokens)
            dev = min(need, rep.free_device)
            host = need - dev
            if host > rep.free_host:
                break
            rep.waiting.pop(0)
            rep.free_slots -= 1
            rep.free_device -= dev
            rep.free_host -= host
            req.kv_pages, req.host_pages = dev, host
            if host:
                counters["kv_spilled_pages"] += host
            batch.append(req)
        return batch

    def release(rep: _SimReplica, req: _SimReq) -> None:
        rep.free_slots += 1
        rep.free_device += req.kv_pages
        rep.free_host += req.host_pages
        rep.done += 1

    def replica_step(rep: _SimReplica) -> None:
        t0 = rep.t
        # 1) staleness sweep (deadline / TTFT budget)
        for req, reason in admission.stale(rep.waiting, rep.t,
                                           rep._ema_serve_ms):
            terminal(req, rep.t, "shed", reason)
            rep.done += 1
        # 2) admit + prefill (decode-phase handoffs skip the prefill pass)
        batch = admit_batch(rep)
        fresh = [r for r in batch if r.phase != "decode"]
        joins = [r for r in batch if r.phase == "decode"]
        if fresh:
            for req in fresh:
                req.admit_s = rep.t
            dt = costs.prefill_s(sum(len(r.prompt) for r in fresh))
            spill = sum(r.host_pages for r in fresh)
            if spill:
                dt += spill * costs.kv_transfer_page_s
            rep.t += dt
            rep._ema_serve_s = 0.9 * rep._ema_serve_s + 0.1 * dt
            hists["prefill"].add(dt, n=len(fresh))
            for req in fresh:
                req.ttft_s = rep.t - req.arrival_s
                req.tokens = _Len(1)
                if rep.role == "prefill":
                    # disagg: first token came from prefill; the KV pages
                    # travel to the decode pool over the host link
                    release(rep, req)
                    req.kv_pages = req.host_pages = 0
                    req.phase = "decode"
                    counters["handoffs"] += 1
                    push(rep.t + handoff_pages * costs.kv_transfer_page_s,
                         "handoff", req)
                else:
                    rep.active.append(req)
        for req in joins:
            if req.admit_s is None:
                req.admit_s = rep.t
            rep.active.append(req)
        # 3) decode window
        worked = bool(fresh or joins or rep.active)
        if rep.active:
            steps = min(spec.dispatch_ahead,
                        max(int(math.ceil(
                            (r.max_new_tokens - len(r.tokens)) / cps))
                            for r in rep.active))
            steps = max(1, steps)
            dt = steps * step_s
            stall_pages = sum(r.host_pages for r in rep.active)
            if stall_pages:
                stall = max(0.0, stall_pages * costs.kv_transfer_page_s
                            - spec.prefetch_ahead * step_s)
                counters["prefetch_stall_s"] += stall
                dt += stall
            hists["decode_step"].add(dt / steps, n=steps)
            for req in list(rep.active):
                take = min(req.max_new_tokens - len(req.tokens),
                           int(math.ceil(steps * cps)))
                req.tokens = _Len(len(req.tokens) + max(0, take))
                if len(req.tokens) >= req.max_new_tokens:
                    finish_steps = min(steps,
                                       int(math.ceil(max(1, take) / cps)))
                    rep.active.remove(req)
                    terminal(req, rep.t + finish_steps * step_s,
                             "done", "completed")
                    release(rep, req)
            rep.t += dt
        if worked:
            # one outer-loop window's worth of host overhead
            rep.t += costs.window_overhead_s
            counters["windows"] += 1
        rep.busy_s += rep.t - t0
        if rep.active or rep.waiting:
            push(rep.t, "step", rep)
        else:
            rep.stepping = False

    reqs = [_SimReq(rec, i) for i, rec in enumerate(records)]
    for req in reqs:
        push(req.arrival_s, "arrive", req)
    while events:
        t, _, kind, payload = heapq.heappop(events)
        if kind == "arrive":
            reason = admission.permanent_shed_reason(payload)
            if reason is not None:
                terminal(payload, t, "shed", reason)
                continue
            rep = router.pick(prefill_pool)
            rep.assigned += 1
            victim = admission.queue_or_displace(payload, rep.waiting)
            if victim is not None:
                terminal(victim, t, "shed", "queue_full")
                rep.done += 1
            wake(rep, t)
        elif kind == "handoff":
            rep = router.pick(decode_pool)
            rep.assigned += 1
            rep.waiting.append(payload)
            wake(rep, t)
        else:  # step
            payload.t = max(payload.t, t)
            replica_step(payload)

    terminals.sort(key=lambda e: e[0])
    tracker = SLOTracker(parse_slo(spec.slo or ""))
    for t, rec in terminals:
        tracker.observe(rec, now_s=t)
    wall = max([t for t, _ in terminals] + [r.t for r in replicas] + [1e-9])
    stats = {
        "requests": len(reqs), "completed": len(completed),
        "shed": len(shed), "replicas": spec.replicas,
        "topology": spec.topology, "wall_s": wall,
        "tokens_out": counters["tokens_out"],
        "tokens_per_s": counters["tokens_out"] / wall,
        "handoffs": counters["handoffs"],
        "windows": counters["windows"],
        "kv_spilled_pages": counters["kv_spilled_pages"],
        "prefetch_stall_s": counters["prefetch_stall_s"],
        "utilization": [r.busy_s / wall for r in replicas],
    }
    signals = signal_timeline(terminals, parse_slo(spec.slo or ""),
                              interval_s=signal_every_s)
    return TwinResult(completed=completed, shed=shed, hists=hists,
                      slo=tracker, stats=stats, signals=signals,
                      spec=spec, costs=costs)


# -------------------------------------------------------------- signals
def signal_timeline(terminals: Sequence[Tuple[float, Dict[str, Any]]],
                    objectives: Dict[str, Dict[str, Any]],
                    interval_s: float = 5.0) -> List[Dict[str, Any]]:
    """Evaluate `health.scaling_signal` every `interval_s` of virtual
    time over the terminal stream — the timeline an autoscaler polling
    the live scoreboard at that cadence would have seen. Only action
    TRANSITIONS are recorded (the interesting edges)."""
    if not terminals or not objectives:
        return []
    tracker = SLOTracker(objectives)
    timeline: List[Dict[str, Any]] = []
    last_action = None
    next_t = terminals[0][0] + interval_s
    idx = 0
    end = terminals[-1][0]
    while next_t <= end + interval_s:
        while idx < len(terminals) and terminals[idx][0] <= next_t:
            t, rec = terminals[idx]
            tracker.observe(rec, now_s=t)
            idx += 1
        sig = scaling_signal(tracker.report(now_s=min(next_t, end)))
        if sig["action"] != last_action:
            timeline.append({"t": round(min(next_t, end), 6), **sig})
            last_action = sig["action"]
        next_t += interval_s
    return timeline


# --------------------------------------------------------- capacity curve
def capacity_curve(records: Sequence[TraceRecord], spec: TwinSpec,
                   costs: TwinCosts,
                   replicas: Sequence[int] = (1, 2, 4),
                   feasible: Optional[Callable[[TwinResult], bool]] = None,
                   iters: int = 7) -> List[Dict[str, Any]]:
    """Replicas -> max sustainable offered load at SLO, by twin bisection
    over `tracefmt.scale_rate` factors: exponential search brackets the
    feasible/infeasible edge, then `iters` halvings pin it. "Sustainable"
    defaults to: zero sheds, positive error budget on every objective,
    AND the replay drains about as fast as load arrives (wall time within
    ~5% of the arrival span plus one request service time) — without the
    drain term a short finite trace can squeak a 10x overload under a
    loose latency target and the curve goes superlinear."""
    if not records:
        return []
    duration = max(r.arrival_ts for r in records) or 1e-9
    base_rate = len(records) / duration
    mean_prompt = sum(r.tokens_in for r in records) / len(records)
    mean_new = sum(r.max_tokens for r in records) / len(records)
    cps = costs.commit_per_step(spec.spec_tokens, spec.spec_accept_rate)
    svc_s = (costs.prefill_s(mean_prompt)
             + math.ceil(mean_new / cps) * costs.step_s(spec.spec_tokens))

    out: List[Dict[str, Any]] = []
    for n in replicas:
        spec_n = dataclasses.replace(spec, replicas=int(n))

        def ok(factor: float) -> bool:
            # scale_rate(records, f) multiplies the offered RATE by f
            res = simulate(scale_rate(records, factor), spec_n, costs)
            if feasible is not None:
                return feasible(res)
            if res.stats["shed"]:
                return False
            if res.stats["wall_s"] > 1.05 * (duration / factor) \
                    + svc_s:
                return False
            rep = res.slo.report(now_s=res.stats["wall_s"])
            budgets = [o["budget_remaining"]
                       for o in (rep.get("objectives") or {}).values()]
            return all(b > 0 for b in budgets)

        lo, hi = 0.0, 1.0
        if ok(1.0):
            lo = 1.0
            while lo < 4096 and ok(lo * 2):
                lo *= 2
            hi = lo * 2
        for _ in range(iters):
            mid = (lo + hi) / 2
            if mid <= 0:
                break
            if ok(mid):
                lo = mid
            else:
                hi = mid
        out.append({"replicas": int(n), "load_factor": lo,
                    "capacity_rps": base_rate * lo})
    return out


def calibrate_window_overhead(probe_records: Sequence[TraceRecord],
                              spec: TwinSpec, costs: TwinCosts,
                              live_wall_s: float) -> float:
    """Solve for `TwinCosts.window_overhead_s` from a SATURATED live
    probe: replay the probe trace at zero overhead, and spread the wall
    time the live run took beyond the twin's over the windows the twin
    dispatched. Per-op histograms can't see this cost (admission,
    sampling, host-sync bookkeeping between materializations), but under
    overload it limits throughput, so an uncalibrated twin is
    systematically optimistic."""
    base = dataclasses.replace(costs, window_overhead_s=0.0)
    res = simulate(probe_records, spec, base)
    windows = max(1, res.stats["windows"])
    return max(0.0, (live_wall_s - res.stats["wall_s"]) / windows)


# ------------------------------------------------------------- validation
def validate(live: Dict[str, float], twin: Dict[str, float],
             max_rel_err: float = 0.25) -> Dict[str, Any]:
    """Twin-vs-live report diff: per-metric relative error against the
    live value, gated at `max_rel_err`. Metrics are whatever keys the two
    dicts share (tok/s, ttft_p99_s, ...)."""
    metrics: Dict[str, Dict[str, float]] = {}
    worst = 0.0
    for k in sorted(set(live) & set(twin)):
        lv, tv = live[k], twin[k]
        if lv is None or tv is None:
            continue
        err = abs(tv - lv) / max(abs(lv), 1e-12)
        metrics[k] = {"live": float(lv), "twin": float(tv),
                      "rel_err": err}
        worst = max(worst, err)
    return {"metrics": metrics, "max_rel_err": worst,
            "bound": max_rel_err,
            "ok": bool(metrics) and worst <= max_rel_err}


def emit_residual_rows(live_report: Dict[str, Any], costs: TwinCosts,
                       spec: KVCacheSpec, slots: int,
                       machine: Any = None) -> int:
    """Close the calibration loop: emit op/attr telemetry rows pairing the
    twin's priced step/prefill against the live-measured means, shaped
    exactly like `PagedKVCache._transfer_row` — tools/refit_cost_model.py
    folds them into the corpus and the next `TwinCosts.resolve` prices
    from the refit `twin_*` kinds. Returns the number of rows emitted."""
    from flexflow_tpu import telemetry as tel
    from flexflow_tpu.attribution import OP_EVENT, feature_key

    def _mean(m: str) -> Optional[float]:
        h = (live_report.get("hists") or {}).get(m)
        if h is None:
            return None
        if isinstance(h, dict):
            return h.get("mean")
        mean = getattr(h, "mean", None)
        return mean() if callable(mean) else None

    rows = 0
    for kind, predicted, metric in (
            ("twin_decode_step", costs.decode_step_s, "decode_step"),
            ("twin_prefill", costs.prefill_base_s, "prefill")):
        measured = _mean(metric)
        if not measured or measured <= 0:
            continue
        features = _twin_features(kind, spec, slots, machine)
        tel.event(OP_EVENT, cat="op", layer=f"twin/{kind}", op=kind,
                  candidate="twin", predicted_s=predicted,
                  measured_s=measured, attributed_s=measured,
                  roofline_s=predicted, bound="twin", mfu=0.0,
                  mfu_ceiling=0.0, key=feature_key(features),
                  features=features, source="twin", bytes=0)
        rows += 1
    return rows
