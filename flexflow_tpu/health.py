"""Run health: goodput accounting, numerics sentinels, HBM watermarks.

Motivation (ISSUE 9): PR 5's spans and PR 7's per-op attribution answer
"how fast does each op run", but not the production questions a long
elastic run raises — what fraction of wall-clock was USEFUL training
(vs checkpoint snapshots, input stalls, pipeline bubbles, resume and
recompile overhead), is the run numerically healthy (NaN/Inf, exploding
grad norms, loss spikes), and did memory land where the search predicted.
This module is that layer; both fit loops (compiler/compile.py
_fit_epochs and parallel/pipeline.py PipelinedModel.fit) wire into it,
and tools/monitor.py renders its `health/*` telemetry events live.

Three pieces:

  * `GoodputMeter` — classifies fit wall-clock into named buckets with a
    contiguous lap cursor (every perf_counter interval between two lap()
    calls lands in exactly one bucket, so the buckets tile the loop's
    wall and the unattributed residual stays small and explicit).
    Goodput% counts the compute-facing buckets (dispatch + host_sync +
    barrier — in the async dispatch-ahead regime those are precisely the
    periods the host is issuing or waiting on device compute) minus the
    pipeline-bubble carve-out; input stalls, checkpointing, resume /
    recompile overhead, host bookkeeping, and the residual are lost time.
  * `SentinelMonitor` / `SentinelState` — device-resident finite-checks
    and grad-norm/loss spike detectors. The step functions fold
    `health/grad_norm` and `health/nonfinite` scalars into their metric
    outputs (riding the existing deferred-metrics machinery), and the
    monitor only materializes them at the loop's EXISTING sync points —
    zero extra host syncs on the healthy path. A fatal NaN/Inf emits a
    `health/nonfinite` error event and, under --halt-on-nonfinite,
    raises `NonFiniteError` through the checkpoint drain so the last
    durable checkpoint is the recovery point (runtime/resilience.py).
  * `WatermarkTracker` — per-device live/peak memory sampled at compile
    and epoch boundaries (device.memory_stats() where the backend has it,
    summed addressable-shard bytes as the CPU fallback) compared against
    the search's memory_stats() prediction with drift warnings.

Telemetry events (cat "health"): health/goodput (per epoch),
health/grad_spike, health/loss_spike, health/hbm; cat "error":
health/nonfinite, health/halt.
"""

from __future__ import annotations

import time
from collections import deque
from typing import Any, Dict, List, Optional, Sequence, Tuple

from flexflow_tpu import telemetry as tel
from flexflow_tpu.metrics import PerfMetrics

# ------------------------------------------------------------------ goodput
# bucket names (GoodputMeter.lap / add): every second of fit wall-clock
# should land in one of these, with the leftover reported as "residual"
BUCKETS = (
    "dispatch",        # issuing jitted step dispatches (device compute)
    "prefetch_wait",   # blocked on the input pipeline (data stall)
    "host_sync",       # deferred-metric materialization (device wait)
    "barrier",         # dispatch-ahead block_until_ready (device wait)
    "loop",            # host-side bookkeeping between dispatches
    "checkpoint",      # snapshot + drain on the fit thread
    "resume",          # restore_auto / checkpoint load before epoch 0
    "recompile",       # recompile_on_condition rebuilds mid-fit
)
# compute-facing buckets: counted as productive before the bubble carve-out
PRODUCTIVE = ("dispatch", "host_sync", "barrier")


class GoodputMeter:
    """Wall-clock bucket accounting for one fit.

    The lap cursor makes the accounting contiguous: `tick()` arms it,
    and each `lap(bucket)` charges the interval since the previous
    lap/tick to `bucket`. `add(bucket, s)` credits out-of-band time
    (resume before the loop starts). `epoch_end()` closes the epoch:
    derives the pipeline-bubble carve-out from the dispatch bucket,
    computes goodput% and the unattributed residual, emits the
    `health/goodput` event, and resets for the next epoch."""

    def __init__(self) -> None:
        self._acc: Dict[str, float] = {b: 0.0 for b in BUCKETS}
        self._last: Optional[float] = None
        self.epochs: List[Dict[str, Any]] = []

    def tick(self) -> None:
        self._last = time.perf_counter()

    def lap(self, bucket: str) -> None:
        now = time.perf_counter()
        if self._last is not None:
            self._acc[bucket] = self._acc.get(bucket, 0.0) \
                + (now - self._last)
        self._last = now

    def add(self, bucket: str, seconds: float) -> None:
        if seconds > 0.0:
            self._acc[bucket] = self._acc.get(bucket, 0.0) + seconds

    def epoch_end(self, wall_s: float, epoch: int,
                  bubble_frac: Optional[float] = None) -> Dict[str, Any]:
        acc, self._acc = self._acc, {b: 0.0 for b in BUCKETS}
        self._last = None
        bubble_s = (float(bubble_frac) if bubble_frac else 0.0) \
            * acc.get("dispatch", 0.0)
        accounted = sum(acc.values())
        residual = max(0.0, wall_s - accounted)
        productive = sum(acc.get(b, 0.0) for b in PRODUCTIVE) - bubble_s
        rec: Dict[str, Any] = {
            "epoch": int(epoch),
            "wall_s": float(wall_s),
            "buckets": {k: float(v) for k, v in acc.items()},
            "bubble_s": float(bubble_s),
            "residual_s": float(residual),
            "accounted_frac": (accounted / wall_s) if wall_s > 0 else 0.0,
            "goodput": max(0.0, min(1.0, productive / wall_s))
            if wall_s > 0 else 0.0,
        }
        self.epochs.append(rec)
        if tel.enabled():
            args: Dict[str, Any] = {
                "epoch": rec["epoch"], "wall_s": rec["wall_s"],
                "goodput": rec["goodput"],
                "residual_s": rec["residual_s"],
                "bubble_s": rec["bubble_s"],
            }
            for k, v in acc.items():
                if v > 0.0:
                    args[k + "_s"] = v
            tel.event("health/goodput", cat="health", **args)
        return rec

    def report(self) -> Dict[str, Any]:
        """Fit-level aggregate over the closed epochs."""
        wall = sum(e["wall_s"] for e in self.epochs)
        buckets: Dict[str, float] = {b: 0.0 for b in BUCKETS}
        for e in self.epochs:
            for k, v in e["buckets"].items():
                buckets[k] = buckets.get(k, 0.0) + v
        bubble = sum(e["bubble_s"] for e in self.epochs)
        residual = sum(e["residual_s"] for e in self.epochs)
        productive = sum(buckets.get(b, 0.0) for b in PRODUCTIVE) - bubble
        return {
            "epochs": len(self.epochs),
            "wall_s": wall,
            "buckets": buckets,
            "bubble_s": bubble,
            "residual_s": residual,
            "accounted_frac": (sum(buckets.values()) / wall)
            if wall > 0 else 0.0,
            "goodput": max(0.0, min(1.0, productive / wall))
            if wall > 0 else 0.0,
        }


def format_goodput(rep: Dict[str, Any]) -> List[str]:
    """The `[goodput]` report lines (profile_report + bench share this)."""
    if not rep or not rep.get("epochs"):
        return ["[goodput] no closed fit epochs yet (run fit())"]
    wall = rep["wall_s"] or 1e-12
    parts = " ".join(
        f"{k}={100.0 * v / wall:.1f}%" for k, v in
        sorted(rep["buckets"].items(), key=lambda kv: -kv[1]) if v > 0.0)
    lines = [f"[goodput] {100.0 * rep['goodput']:.1f}% of "
             f"{wall:.2f}s wall over {rep['epochs']} epoch(s) "
             f"(accounted {100.0 * rep['accounted_frac']:.1f}%, "
             f"residual {rep['residual_s']:.3f}s)",
             f"[goodput] buckets: {parts or '(none)'}"]
    if rep.get("bubble_s"):
        lines.append(f"[goodput] pipeline bubble carve-out: "
                     f"{rep['bubble_s']:.3f}s "
                     f"({100.0 * rep['bubble_s'] / wall:.1f}% of wall)")
    return lines


# ---------------------------------------------------------------- sentinels
# reserved metric keys the step functions fold into their metric outputs;
# both fit loops pop them off before user-facing metric accounting
GRAD_NORM_KEY = "health/grad_norm"
NONFINITE_KEY = "health/nonfinite"
SENTINEL_KEYS = (GRAD_NORM_KEY, NONFINITE_KEY)

# spike thresholds: a window mean this many times the trailing EMA (grad
# norm) / the previous window mean (loss) emits a health/*_spike warning
GRAD_SPIKE_RATIO = 10.0
LOSS_SPIKE_RATIO = 4.0
_EMA_DECAY = 0.9


def sentinel_metrics(loss, grad_norm) -> Dict[str, Any]:
    """Device-side sentinel scalars for one optimizer update (called
    inside the jitted step functions): the grad global-norm and a 0/1
    non-finite flag over (loss, grad_norm). Means of the flag across
    fused/accumulated steps stay > 0 iff ANY step tripped (NaN also
    propagates through the mean), so deferred accumulation preserves
    detection."""
    import jax.numpy as jnp

    gn = grad_norm.astype(jnp.float32)
    ls = loss.astype(jnp.float32)
    finite = jnp.isfinite(ls) & jnp.isfinite(gn)
    return {GRAD_NORM_KEY: gn,
            NONFINITE_KEY: 1.0 - finite.astype(jnp.float32)}


class NonFiniteError(RuntimeError):
    """Fatal numerics failure (--halt-on-nonfinite): raised through the
    checkpoint drain, carrying the last DURABLE checkpoint path — the
    recovery point a supervisor resumes from (the in-memory state is
    poisoned and deliberately NOT saved)."""

    def __init__(self, step: int, checkpoint: Optional[str],
                 detail: str = ""):
        self.step = int(step)
        self.checkpoint = checkpoint
        msg = (f"non-finite loss/grad detected at step {step}"
               + (f" ({detail})" if detail else ""))
        msg += (f"; last durable checkpoint: {checkpoint}" if checkpoint
                else "; no durable checkpoint available")
        super().__init__(msg)


def halt_nonfinite(step: int, checkpoint_root: Optional[str],
                   detail: str = "") -> "NoReturn":  # noqa: F821
    """The PR-6 drain path for a fatal sentinel: join in-flight async
    checkpoint writes (so a durable save racing the failure lands), look
    up the newest durable checkpoint, emit the health/halt error event,
    and raise NonFiniteError. The poisoned live state is NOT saved."""
    from flexflow_tpu.runtime import checkpoint as ck
    from flexflow_tpu.runtime.resilience import latest_checkpoint

    ck.wait_pending()
    last = latest_checkpoint(checkpoint_root) if checkpoint_root else None
    tel.error("health/halt", step=int(step), checkpoint=last,
              detail=detail or None)
    raise NonFiniteError(step, last, detail)


class SentinelState:
    """Host-side spike/NaN detectors over materialized window means.
    Pure accounting (feed it floats, read `.events`) so tests drive it
    without a device."""

    def __init__(self, grad_ratio: float = GRAD_SPIKE_RATIO,
                 loss_ratio: float = LOSS_SPIKE_RATIO):
        self.grad_ratio = float(grad_ratio)
        self.loss_ratio = float(loss_ratio)
        self.grad_ema: Optional[float] = None
        self.loss_prev: Optional[float] = None
        self.nonfinite_steps = 0
        self.events: List[Dict[str, Any]] = []

    def observe(self, step: int, loss_mean: Optional[float] = None,
                grad_norm: Optional[float] = None,
                nonfinite: float = 0.0) -> Optional[str]:
        """One materialized window. Returns "nonfinite" on a fatal
        window, else None (spikes are warnings, not fatal)."""
        fatal = (nonfinite is not None and nonfinite > 0.0) \
            or (nonfinite != nonfinite)  # NaN count is itself a trip
        if not fatal and grad_norm is not None \
                and grad_norm != grad_norm:
            fatal = True
        if fatal:
            self.nonfinite_steps += 1
            ev = {"kind": "nonfinite", "step": int(step),
                  "grad_norm": grad_norm, "loss": loss_mean}
            self.events.append(ev)
            tel.error("health/nonfinite", step=int(step),
                      grad_norm=grad_norm, loss=loss_mean)
            return "nonfinite"
        if grad_norm is not None:
            if self.grad_ema is not None \
                    and grad_norm > self.grad_ratio * max(self.grad_ema,
                                                          1e-12):
                ev = {"kind": "grad_spike", "step": int(step),
                      "grad_norm": grad_norm, "ema": self.grad_ema}
                self.events.append(ev)
                tel.event("health/grad_spike", cat="health",
                          step=int(step), grad_norm=grad_norm,
                          ema=self.grad_ema)
            self.grad_ema = grad_norm if self.grad_ema is None else \
                _EMA_DECAY * self.grad_ema + (1 - _EMA_DECAY) * grad_norm
        if loss_mean is not None and loss_mean == loss_mean:
            if self.loss_prev is not None \
                    and abs(loss_mean) > self.loss_ratio \
                    * max(abs(self.loss_prev), 1e-12):
                ev = {"kind": "loss_spike", "step": int(step),
                      "loss": loss_mean, "prev": self.loss_prev}
                self.events.append(ev)
                tel.event("health/loss_spike", cat="health",
                          step=int(step), loss=loss_mean,
                          prev=self.loss_prev)
            self.loss_prev = loss_mean
        return None

    def status(self) -> Dict[str, Any]:
        return {
            "nonfinite_steps": self.nonfinite_steps,
            "grad_spikes": sum(1 for e in self.events
                               if e["kind"] == "grad_spike"),
            "loss_spikes": sum(1 for e in self.events
                               if e["kind"] == "loss_spike"),
            "grad_ema": self.grad_ema,
        }


class SentinelMonitor:
    """The fit loop's sentinel harness: `push()` strips the reserved
    health keys off a dispatch's metric dict into a deferred PerfMetrics
    (no host transfer), and `check()` materializes the window ONLY at
    the loop's existing sync points, runs the detectors, and — under
    halt_on_nonfinite — raises via the drain path."""

    def __init__(self, halt: bool = False,
                 checkpoint_root: Optional[str] = None,
                 state: Optional[SentinelState] = None):
        self.halt = bool(halt)
        self.checkpoint_root = checkpoint_root
        self.state = state or SentinelState()
        self._win = PerfMetrics()
        self._loss_sum_prev = 0.0
        self._steps_prev = 0

    def push(self, steps: int, mvals: Dict[str, Any]) -> None:
        """Pop health/* device scalars out of `mvals` (mutates it — the
        user-facing metric accounting must not see reserved keys) and
        queue them deferred."""
        h = {k: mvals.pop(k) for k in SENTINEL_KEYS if k in mvals}
        if h:
            self._win.update_deferred(int(steps), h)

    def check(self, step: int, loss_sum: Optional[float] = None,
              steps_total: Optional[int] = None) -> Optional[str]:
        """Materialize the window (call ONLY where the loop already
        syncs) and run the detectors. `loss_sum`/`steps_total` are the
        loop's running loss accumulator + step count; window means are
        the deltas since the previous check."""
        w, self._win = self._win, PerfMetrics()
        w.materialize()
        n = max(1, w.train_all)
        gsum = w.sums.get(GRAD_NORM_KEY)
        nf = w.sums.get(NONFINITE_KEY, 0.0)
        loss_mean = None
        if loss_sum is not None and steps_total is not None:
            dn = steps_total - self._steps_prev
            if dn > 0:
                loss_mean = (loss_sum - self._loss_sum_prev) / dn
            self._loss_sum_prev = float(loss_sum)
            self._steps_prev = int(steps_total)
        verdict = self.state.observe(
            step, loss_mean=loss_mean,
            grad_norm=(gsum / n) if gsum is not None else None,
            nonfinite=nf)
        if verdict == "nonfinite" and self.halt:
            halt_nonfinite(step, self.checkpoint_root,
                           detail=f"nonfinite window mean {nf / n:g}")
        return verdict


# --------------------------------------------------------------- watermarks
# actual peak memory beyond this multiple of the search's prediction flags
# the memory model as under-predicting (the inverse of OOM headroom)
WATERMARK_WARN_RATIO = 1.5


def device_watermarks(trees: Sequence[Any] = ()) -> Dict[str, Dict[str, int]]:
    """Per-device live/peak byte sample. TPU/GPU backends expose
    device.memory_stats(); the CPU backend doesn't, so the fallback sums
    the addressable-shard bytes of the live trees the caller passes
    (params/opt state — the persistent footprint, matching what
    memory_stats() predicts)."""
    import jax

    out: Dict[str, Dict[str, int]] = {}
    for d in jax.local_devices():
        stats = None
        try:
            stats = d.memory_stats()
        except Exception:
            stats = None
        if stats and stats.get("bytes_in_use") is not None:
            out[str(d.id)] = {
                "live": int(stats["bytes_in_use"]),
                "peak": int(stats.get("peak_bytes_in_use",
                                      stats["bytes_in_use"])),
            }
    if out:
        return out
    totals: Dict[str, int] = {}
    for tree in trees:
        if tree is None:
            continue
        for leaf in jax.tree_util.tree_leaves(tree):
            shards = getattr(leaf, "addressable_shards", None)
            if shards is None:
                continue
            for s in shards:
                k = str(s.device.id)
                totals[k] = totals.get(k, 0) + int(s.data.nbytes)
    return {k: {"live": v, "peak": v} for k, v in totals.items()}


def watermark_drift(peak_bytes: Optional[int],
                    predicted_bytes: Optional[int],
                    warn_ratio: float = WATERMARK_WARN_RATIO
                    ) -> Dict[str, Any]:
    """Pure comparison: measured per-device peak vs the search's
    prediction. warn trips when the model UNDER-predicted by more than
    `warn_ratio` (the direction that OOMs a real machine)."""
    ratio = None
    if peak_bytes and predicted_bytes:
        ratio = float(peak_bytes) / float(predicted_bytes)
    return {
        "peak_bytes": int(peak_bytes) if peak_bytes else None,
        "predicted_bytes": int(predicted_bytes) if predicted_bytes
        else None,
        "ratio": ratio,
        "warn": bool(ratio is not None and ratio > warn_ratio),
        "warn_ratio": float(warn_ratio),
    }


class WatermarkTracker:
    """HBM watermark sampler: `sample()` at compile and epoch boundaries,
    `report(predicted)` compares the peak against the cost model."""

    def __init__(self) -> None:
        self.samples: List[Dict[str, Any]] = []

    def sample(self, tag: str, trees: Sequence[Any] = ()
               ) -> Dict[str, Any]:
        per_dev = device_watermarks(trees)
        peak = max((v["peak"] for v in per_dev.values()), default=0)
        live = max((v["live"] for v in per_dev.values()), default=0)
        rec = {"tag": str(tag), "per_device": per_dev,
               "peak_bytes": peak, "live_bytes": live}
        self.samples.append(rec)
        if tel.enabled() and per_dev:
            tel.event("health/hbm", cat="health", tag=str(tag),
                      peak_bytes=peak, live_bytes=live,
                      devices=len(per_dev))
        return rec

    def peak_bytes(self) -> Optional[int]:
        peaks = [s["peak_bytes"] for s in self.samples if s["per_device"]]
        return max(peaks) if peaks else None

    def report(self, predicted_bytes: Optional[int],
               warn_ratio: float = WATERMARK_WARN_RATIO
               ) -> Dict[str, Any]:
        rep = watermark_drift(self.peak_bytes(), predicted_bytes,
                              warn_ratio)
        rep["samples"] = len(self.samples)
        return rep


# ----------------------------------------------------------- serving swaps
class SwapStats:
    """Hot-swap bookkeeping for the serving engine (ISSUE 11): every
    completed swap/rollback records its wall latency and the version
    (training step) it activated, so `health_report()["serving"]` and the
    monitor can answer "which weights are live, how long do swaps take,
    and has anyone rolled back" without grepping telemetry."""

    def __init__(self) -> None:
        self.active_version: Optional[int] = None
        self.swaps = 0
        self.rollbacks = 0
        self.rejected = 0          # snapshots refused (fingerprint/fault)
        self.latencies_s: List[float] = []
        self.last_swap_s: Optional[float] = None  # wall ts of last swap

    def record_swap(self, version: Optional[int], latency_s: float,
                    rollback: bool = False) -> None:
        self.active_version = version
        self.latencies_s.append(float(latency_s))
        self.last_swap_s = time.time()
        if rollback:
            self.rollbacks += 1
        else:
            self.swaps += 1
        if tel.enabled():
            tel.event("serve/version", cat="serve",
                      version=-1 if version is None else int(version),
                      latency_s=float(latency_s), rollback=bool(rollback))

    def record_rejected(self) -> None:
        self.rejected += 1

    def report(self) -> Dict[str, Any]:
        lats = sorted(self.latencies_s)

        def q(p: float) -> Optional[float]:
            if not lats:
                return None
            return lats[min(len(lats) - 1, int(p * (len(lats) - 1) + 0.5))]

        return {
            "active_version": self.active_version,
            "swaps": self.swaps,
            "rollbacks": self.rollbacks,
            "rejected": self.rejected,
            "swap_p50_s": q(0.5),
            "swap_p99_s": q(0.99),
            "last_swap_unix_s": self.last_swap_s,
        }


# ------------------------------------------------- serving SLOs (ISSUE 15)
# --serve-slo grammar: comma-separated objectives, e.g.
#   "ttft_p99_ms=25,per_token_p99_ms=10,availability=0.999"
# Latency objectives are <metric>_p<PP>_ms over the terminal records'
# ttft_s / per_token_s / queue_wait_s fields; the implied error budget is
# the complement of the percentile (p99 -> 1% of requests may exceed the
# threshold). availability=<frac> budgets non-done outcomes (sheds,
# failures, watchdog timeouts all count against it).
_SLO_LATENCY_METRICS = ("ttft", "per_token", "queue_wait")


def parse_slo(spec: str) -> Dict[str, Dict[str, Any]]:
    """Parse the --serve-slo objective string into objective specs:
    {name: {"kind": "latency", "metric", "pct", "threshold_s"} |
            {"kind": "availability", "target"}}. Empty spec -> {}."""
    out: Dict[str, Dict[str, Any]] = {}
    for part in (spec or "").split(","):
        part = part.strip()
        if not part:
            continue
        if "=" not in part:
            raise ValueError(f"--serve-slo objective {part!r} has no value "
                             "(want name=value)")
        key, _, val = part.partition("=")
        key = key.strip()
        try:
            fval = float(val)
        except ValueError:
            raise ValueError(f"--serve-slo {key}={val!r}: value must be "
                             "numeric") from None
        if key == "availability":
            if not 0.0 < fval <= 1.0:
                raise ValueError(f"--serve-slo availability={fval} must be "
                                 "in (0, 1]")
            out[key] = {"kind": "availability", "target": fval}
            continue
        for metric in _SLO_LATENCY_METRICS:
            prefix = metric + "_p"
            if key.startswith(prefix) and key.endswith("_ms"):
                pct_txt = key[len(prefix):-3]
                try:
                    pct = float(pct_txt) / 100.0
                except ValueError:
                    break
                if not 0.0 < pct < 1.0:
                    raise ValueError(f"--serve-slo {key}: percentile must "
                                     "be in (0, 100)")
                out[key] = {"kind": "latency", "metric": metric, "pct": pct,
                            "threshold_s": fval / 1e3}
                break
        else:
            raise ValueError(
                f"--serve-slo objective {key!r} not understood (want "
                f"availability=<frac> or one of "
                f"{'/'.join(_SLO_LATENCY_METRICS)}_p<PP>_ms=<ms>)")
    return out


class SLOTracker:
    """Windowed SLO error-budget + burn-rate tracker for serving (the
    signal ROADMAP item 1's fleet router consumes). Every terminal
    request record (the unified reqtrace schema) is classified against
    each objective; `report()` answers remaining error budget and
    multi-window burn rates. burn rate 1.0 = consuming budget exactly at
    the sustainable pace; >1 means the budget drains early."""

    WINDOWS_S = (60.0, 300.0)

    def __init__(self, objectives: Optional[Dict[str, Dict[str, Any]]] = None,
                 windows_s: Sequence[float] = WINDOWS_S,
                 max_events: int = 100_000):
        self.objectives = dict(objectives or {})
        self.windows_s = tuple(float(w) for w in windows_s)
        # (ts_s, {objective: bad}) per terminal request; bounded so a
        # long-lived engine can't grow without limit (window math only
        # ever looks back max(windows_s))
        self.events: "deque[Tuple[float, Dict[str, bool]]]" = \
            deque(maxlen=max_events)
        self.totals: Dict[str, List[int]] = {
            name: [0, 0] for name in self.objectives}  # [total, bad]
        self.requests = 0
        self.outcomes: Dict[str, int] = {}

    @staticmethod
    def allowed_frac(spec: Dict[str, Any]) -> float:
        """The objective's error budget as a fraction of requests."""
        if spec["kind"] == "availability":
            return max(1e-9, 1.0 - spec["target"])
        return max(1e-9, 1.0 - spec["pct"])

    def _classify(self, rec: Dict[str, Any],
                  spec: Dict[str, Any]) -> Optional[bool]:
        """True = bad (budget-burning), False = good, None = the record
        doesn't count toward this objective."""
        if spec["kind"] == "availability":
            return rec.get("outcome") != "done"
        if rec.get("outcome") != "done":
            return None  # sheds/failures have no latency sample; the
            #              availability objective is what prices them
        val = rec.get(spec["metric"] + "_s")
        if val is None:
            return None
        return float(val) > spec["threshold_s"]

    def observe(self, rec: Dict[str, Any],
                now_s: Optional[float] = None) -> None:
        """Classify one terminal request record (reqtrace.terminal_record
        schema) against every objective."""
        now = time.monotonic() if now_s is None else float(now_s)
        self.requests += 1
        oc = str(rec.get("outcome") or "unknown")
        self.outcomes[oc] = self.outcomes.get(oc, 0) + 1
        verdicts: Dict[str, bool] = {}
        for name, spec in self.objectives.items():
            bad = self._classify(rec, spec)
            if bad is None:
                continue
            verdicts[name] = bad
            self.totals[name][0] += 1
            self.totals[name][1] += int(bad)
        self.events.append((now, verdicts))

    def _window_frac(self, name: str, window_s: float,
                     now: float) -> Optional[float]:
        total = bad = 0
        for ts, verdicts in reversed(self.events):
            if ts < now - window_s:
                break
            if name in verdicts:
                total += 1
                bad += int(verdicts[name])
        return (bad / total) if total else None

    def report(self, now_s: Optional[float] = None) -> Dict[str, Any]:
        now = time.monotonic() if now_s is None else float(now_s)
        done = self.outcomes.get("done", 0)
        per_obj: Dict[str, Any] = {}
        worst_burn: Optional[float] = None
        for name, spec in self.objectives.items():
            total, bad = self.totals[name]
            allowed = self.allowed_frac(spec)
            bad_frac = (bad / total) if total else 0.0
            entry: Dict[str, Any] = {
                "kind": spec["kind"],
                "target": (spec["target"] if spec["kind"] == "availability"
                           else spec["threshold_s"]),
                "total": total, "bad": bad, "bad_frac": bad_frac,
                "allowed_frac": allowed,
                "budget_remaining": 1.0 - bad_frac / allowed,
            }
            for w in self.windows_s:
                frac = self._window_frac(name, w, now)
                burn = (frac / allowed) if frac is not None else None
                entry[f"burn_rate_{w:g}s"] = burn
                if burn is not None:
                    worst_burn = burn if worst_burn is None \
                        else max(worst_burn, burn)
            per_obj[name] = entry
        return {
            "objectives": per_obj,
            "requests": self.requests,
            "outcomes": dict(self.outcomes),
            "shed_rate": ((self.requests - done) / self.requests
                          if self.requests else 0.0),
            "worst_burn_rate": worst_burn,
            "windows_s": list(self.windows_s),
        }


def merge_slo_trackers(trackers) -> "SLOTracker":
    """Rebuild the SLO scoreboard a single tracker would hold had it
    observed the union of every replica's terminal records: totals and
    outcome tallies add, events interleave by timestamp (the window walk
    needs time order), objectives union across the pool, and — the
    windowed-state fix — the merged event ring inherits the base
    tracker's bound, so window burn rates match a union-fed tracker
    exactly even when the ring has wrapped (pinned in tests). Lives in
    health.py next to SLOTracker; serving/fleet re-exports it."""
    trackers = [t for t in trackers if t is not None]
    if not trackers:
        return SLOTracker({})
    base = trackers[0]
    objectives: Dict[str, Dict[str, Any]] = {}
    for t in trackers:
        objectives.update(t.objectives)
    out = SLOTracker(objectives, windows_s=base.windows_s,
                     max_events=base.events.maxlen)
    events: List[Tuple[float, Dict[str, bool]]] = []
    for t in trackers:
        events.extend(t.events)
        for name, (total, bad) in t.totals.items():
            slot = out.totals.setdefault(name, [0, 0])
            slot[0] += total
            slot[1] += bad
        out.requests += t.requests
        for oc, n in t.outcomes.items():
            out.outcomes[oc] = out.outcomes.get(oc, 0) + n
    events.sort(key=lambda e: e[0])
    out.events.extend(events)
    return out


# ------------------------------------------------- scaling recommendation
# Multi-window burn-rate policy thresholds (the textbook SRE shape: a
# fast-window burn this hot, CONFIRMED by the slow window, exhausts the
# budget long before a human reacts — recommend scale-out while
# budget_remaining is still positive).
SCALE_OUT_FAST_BURN = 6.0    # short-window burn that demands action
SCALE_OUT_SLOW_BURN = 1.0    # long-window burn confirming it's not a blip
SCALE_IN_MAX_BURN = 0.5      # every window this cool -> capacity to spare
SCALE_IN_MIN_BUDGET = 0.9    # ... and nearly all budget intact


def scaling_signal(slo_report: Dict[str, Any],
                   fast_burn: float = SCALE_OUT_FAST_BURN,
                   slow_burn: float = SCALE_OUT_SLOW_BURN
                   ) -> Dict[str, Any]:
    """Turn one SLOTracker.report() into a scaling recommendation —
    the policy half of ROADMAP item 5's autoscaler, shared by
    `health_report()`, the fleet report, the twin's burst replay, and
    the monitor panel. Actions:

      scale_out      — some objective's short-window burn >= fast_burn
                       with the long window confirming (>= slow_burn);
                       fired BEFORE budget_remaining exhausts.
      objective_flip — an error budget already exhausted
                       (budget_remaining <= 0): added capacity can't
                       un-burn history; flip the latency<->throughput
                       objective (or re-tier admission) instead.
      scale_in       — every objective cold (all window burns <=
                       SCALE_IN_MAX_BURN, budgets >= SCALE_IN_MIN_BUDGET).
      steady         — anything else.

    Returns {"action", "objective", "reason", "budget_remaining",
    "worst_burn_rate"} — `objective` names the offender (or None)."""
    objectives = slo_report.get("objectives") or {}
    windows = sorted(float(w) for w in (slo_report.get("windows_s") or
                                        SLOTracker.WINDOWS_S))
    if not objectives:
        return {"action": "steady", "objective": None,
                "reason": "no SLO objectives configured",
                "budget_remaining": None, "worst_burn_rate": None}
    w_fast, w_slow = windows[0], windows[-1]
    min_budget, min_budget_obj = None, None
    flip_obj = None
    out_obj, out_reason = None, None
    all_cold = True
    for name, entry in objectives.items():
        budget = entry.get("budget_remaining")
        if budget is not None and (min_budget is None or
                                   budget < min_budget):
            min_budget, min_budget_obj = budget, name
        bf = entry.get(f"burn_rate_{w_fast:g}s")
        bs = entry.get(f"burn_rate_{w_slow:g}s")
        if budget is not None and budget <= 0.0 and flip_obj is None:
            flip_obj = name
        confirmed = bs is None or bs >= slow_burn
        if bf is not None and bf >= fast_burn and confirmed \
                and out_obj is None:
            out_obj = name
            out_reason = (f"burn_rate_{w_fast:g}s={bf:.2f} >= "
                          f"{fast_burn:g} (slow window "
                          f"{'confirms' if bs is not None else 'empty'})")
        for b in (bf, bs):
            if b is not None and b > SCALE_IN_MAX_BURN:
                all_cold = False
        if budget is not None and budget < SCALE_IN_MIN_BUDGET:
            all_cold = False
    worst = slo_report.get("worst_burn_rate")
    if flip_obj is not None:
        return {"action": "objective_flip", "objective": flip_obj,
                "reason": (f"{flip_obj} error budget exhausted "
                           "(budget_remaining <= 0): capacity alone "
                           "cannot un-burn history"),
                "budget_remaining": min_budget, "worst_burn_rate": worst}
    if out_obj is not None:
        return {"action": "scale_out", "objective": out_obj,
                "reason": out_reason,
                "budget_remaining": min_budget, "worst_burn_rate": worst}
    if all_cold:
        return {"action": "scale_in", "objective": min_budget_obj,
                "reason": (f"all window burns <= {SCALE_IN_MAX_BURN:g} "
                           f"and budgets >= {SCALE_IN_MIN_BUDGET:g}"),
                "budget_remaining": min_budget, "worst_burn_rate": worst}
    return {"action": "steady", "objective": min_budget_obj,
            "reason": "burn within budgeted pace",
            "budget_remaining": min_budget, "worst_burn_rate": worst}


def format_kv_tier(tier_stats: Dict[str, Any]) -> Dict[str, Any]:
    """Normalize a PagedKVCache.tier_stats() snapshot into the health
    report's serving section: occupancy, transfer totals, and the derived
    prefetch hit rate (hits / (hits + stalls); 1.0 with no rejoins — an
    idle tier has missed nothing)."""
    hits = int(tier_stats.get("kv_prefetch_hits", 0))
    stalls = int(tier_stats.get("kv_prefetch_stalls", 0))
    joins = hits + stalls
    return {
        "hot_pages": int(tier_stats.get("kv_hot_pages", 0)),
        "cold_pages": int(tier_stats.get("kv_cold_pages", 0)),
        "host_pages_total": int(tier_stats.get("kv_host_pages_total", 0)),
        "parked_slots": int(tier_stats.get("kv_parked_slots", 0)),
        "spills": int(tier_stats.get("kv_spills", 0)),
        "refills": int(tier_stats.get("kv_refills", 0)),
        "spilled_bytes": int(tier_stats.get("kv_spilled_bytes", 0)),
        "refilled_bytes": int(tier_stats.get("kv_refilled_bytes", 0)),
        "prefetch_hits": hits,
        "prefetch_stalls": stalls,
        "prefetch_hit_rate": (hits / joins) if joins else 1.0,
    }


def format_health(sentinels: Optional[Dict[str, Any]],
                  watermarks: Optional[Dict[str, Any]]) -> List[str]:
    """The `[health]` report lines (profile_report; bench reuses)."""
    lines: List[str] = []
    if sentinels is not None:
        nf = sentinels.get("nonfinite_steps", 0)
        lines.append(
            f"[health] sentinels: nonfinite_windows={nf} "
            f"grad_spikes={sentinels.get('grad_spikes', 0)} "
            f"loss_spikes={sentinels.get('loss_spikes', 0)}"
            + (" — NON-FINITE VALUES DETECTED" if nf else ""))
    if watermarks is not None and watermarks.get("peak_bytes"):
        mb = 1024 * 1024
        pred = watermarks.get("predicted_bytes")
        line = (f"[health] hbm peak/device: "
                f"{watermarks['peak_bytes'] / mb:.2f}MB")
        if pred:
            line += (f" vs predicted {pred / mb:.2f}MB "
                     f"(ratio {watermarks['ratio']:.2f}x)")
        lines.append(line)
        if watermarks.get("warn"):
            lines.append(
                f"[health] WARNING: peak memory "
                f"{watermarks['ratio']:.2f}x the predicted footprint "
                f"(> {watermarks['warn_ratio']:g}x) — the memory model "
                "under-predicts this config; re-check "
                "memory_stats()/OptMemSpec accounting")
    return lines
