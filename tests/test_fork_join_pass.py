"""Generic fork-join detection (the other half of C11: the reference's
nonsequence splits apply to ANY parallel branches, not just user-marked
regions): the fuse_fork_joins pass finds reconverging chains in a plain
layer graph, rewrites them into FORK_JOIN composites, preserves numerics,
and makes them placeable on disjoint chips by the search."""

import numpy as np

from flexflow_tpu import FFConfig, FFModel, SGDOptimizer
from flexflow_tpu.compiler.passes import find_fork_join_regions, fuse_fork_joins
from flexflow_tpu.ops.op_type import OperatorType
from flexflow_tpu.parallel.machine import MachineSpec
from flexflow_tpu.search.dp import search_graph


def _branchy(hidden=64, join="add"):
    m = FFModel(FFConfig(batch_size=16, mesh_shape={"data": 4, "model": 2},
                         only_data_parallel=True))
    x = m.create_tensor([16, 32], name="x")
    a = m.dense(x, hidden, activation="relu", name="a1")
    a = m.dense(a, 48, name="a2")
    b = m.dense(x, hidden, activation="gelu", name="b1")
    b = m.dense(b, 48, name="b2")
    j = m.add(a, b, name="j") if join == "add" else \
        m.concat([a, b], axis=-1, name="j")
    m.dense(j, 4, name="head")
    return m


def test_detects_and_fuses_add_join():
    m = _branchy()
    regions = find_fork_join_regions(m)
    assert len(regions) == 1
    assert [l.name for l in regions[0]["chains"][0]] == ["a1", "a2"]
    assert fuse_fork_joins(m) == 1
    types = [l.op_type for l in m.layers]
    assert OperatorType.FORK_JOIN in types
    assert len(m.layers) == 2  # fj + head
    fj = next(l for l in m.layers if l.op_type is OperatorType.FORK_JOIN)
    assert "b0.a1.kernel" in fj.weight_specs
    assert fj.outputs[0].spec.shape == (16, 48)


def test_no_false_positives():
    # residual (fork feeds the join directly) and diverging-only graphs
    m = FFModel(FFConfig(batch_size=8))
    x = m.create_tensor([8, 32], name="x")
    h = m.dense(x, 32, name="d")
    m.add(h, x, name="res")          # residual: NOT a balanced fork-join
    m2 = FFModel(FFConfig(batch_size=8))
    x2 = m2.create_tensor([8, 32], name="x")
    m2.dense(x2, 16, name="p")       # two heads, never reconverge
    m2.dense(x2, 8, name="q")
    assert fuse_fork_joins(m) == 0
    assert fuse_fork_joins(m2) == 0


def test_cascaded_regions_fuse_and_compile(devices):
    """Region 2's fork is region 1's join output: fusing must re-detect
    against the mutated graph, not splice a deleted tensor (round-4 review
    crash repro)."""
    m = FFModel(FFConfig(batch_size=8, only_data_parallel=True))
    x = m.create_tensor([8, 16], name="x")
    a = m.dense(x, 32, name="r1a")
    b = m.dense(x, 32, name="r1b")
    j1 = m.add(a, b, name="j1")
    c = m.dense(j1, 32, name="r2a")
    d = m.dense(j1, 32, name="r2b")
    j2 = m.add(c, d, name="j2")
    m.dense(j2, 4, name="head")
    assert fuse_fork_joins(m) == 2
    cm = m.compile(SGDOptimizer(lr=0.01), loss_type="mean_squared_error",
                   metrics=[])
    cm.init(seed=0)
    out = cm.forward(np.zeros((8, 16), np.float32))
    assert np.asarray(out).shape == (8, 4)


def test_nested_hand_built_fork_join_survives():
    """A hand-built fork_join inside a detected chain keeps its branches
    attribute through the rebuild (round-4 review crash repro)."""
    m = FFModel(FFConfig(batch_size=8, only_data_parallel=True))
    x = m.create_tensor([8, 16], name="x")
    a = m.fork_join(x, [lambda mm, t: mm.dense(t, 16, name="i1"),
                        lambda mm, t: mm.dense(t, 16, name="i2")],
                    join="add", name="inner")
    a = m.dense(a, 32, name="a2")
    b = m.dense(x, 32, name="b1")
    m.add(a, b, name="j")
    assert fuse_fork_joins(m) == 1
    cm = m.compile(SGDOptimizer(lr=0.01), loss_type="mean_squared_error",
                   metrics=[])
    cm.init(seed=0)  # lowering the nested composite needs .branches
    out = cm.forward(np.zeros((8, 16), np.float32))
    assert np.asarray(out).shape == (8, 32)


def test_contract_violating_region_skipped():
    """Branches that break the fork_join contract (batch-changing reshape)
    are SKIPPED, not crashed on (round-4 review crash repro)."""
    m = FFModel(FFConfig(batch_size=16, only_data_parallel=True))
    x = m.create_tensor([16, 4, 8], name="x")
    a = m.dense(m.reshape(x, [8, 64], name="ra"), 32, name="da")
    b = m.dense(m.reshape(x, [8, 64], name="rb"), 32, name="db")
    m.add(a, b, name="j")
    assert fuse_fork_joins(m) == 0  # no crash, nothing mutated
    assert any(l.name == "ra" for l in m.layers)


def test_auto_named_branch_layers_renamed_positionally():
    def build():
        m = FFModel(FFConfig(batch_size=8))
        x = m.create_tensor([8, 16], name="x")
        a = m.dense(m.dense(x, 32), 16)   # auto names
        b = m.dense(x, 16)
        m.add(a, b, name="j")
        fuse_fork_joins(m)
        fj = next(l for l in m.layers
                  if l.op_type is OperatorType.FORK_JOIN)
        return sorted(fj.weight_specs)

    assert build() == build()  # no process-global guids in the keys


def test_fused_numerics_match_unfused(devices):
    rng = np.random.default_rng(0)
    xv = rng.normal(size=(16, 32)).astype(np.float32)

    m1 = _branchy(join="concat")
    cm1 = m1.compile(SGDOptimizer(lr=0.01), loss_type="mean_squared_error",
                     metrics=[])
    cm1.init(seed=0)
    ref = np.asarray(cm1.forward(xv))

    m2 = _branchy(join="concat")
    assert fuse_fork_joins(m2) == 1
    cm2 = m2.compile(SGDOptimizer(lr=0.01), loss_type="mean_squared_error",
                     metrics=[])
    cm2.init(seed=0)
    fj = next(l for l in m2.layers if l.op_type is OperatorType.FORK_JOIN)
    for bi, branch in enumerate(("a", "b")):
        for li in (1, 2):
            for w in ("kernel", "bias"):
                cm2.set_weight(fj.name, f"b{bi}.{branch}{li}.{w}",
                               cm1.get_weight(f"{branch}{li}", w))
    cm2.set_weight("head", "kernel", cm1.get_weight("head", "kernel"))
    cm2.set_weight("head", "bias", cm1.get_weight("head", "bias"))
    got = np.asarray(cm2.forward(xv))
    np.testing.assert_allclose(got, ref, rtol=2e-5, atol=2e-5)


def test_fused_region_becomes_placeable(devices):
    """After fusion the search can place the branches on disjoint chips —
    the generic nonsequence-split path end to end."""
    m = _branchy(hidden=4096)
    assert fuse_fork_joins(m) == 1
    mach = MachineSpec(mesh_axes={"data": 4, "model": 2}, chip="v5p")
    r = search_graph(m, mach)
    fj = next(l for l in m.layers if l.op_type is OperatorType.FORK_JOIN)
    assert r.choices[fj.name].name == "inter:model", r.choices[fj.name].name

    # and it trains placed
    m.config.only_data_parallel = False
    m.config.search_budget = 8
    cm = m.compile(SGDOptimizer(lr=0.01), loss_type="mean_squared_error",
                   metrics=[])
    assert cm.strategy.sharding_for(fj.name).attrs.get("placement") == "model"
    cm.init(seed=0)
    rng = np.random.default_rng(0)
    xv = rng.normal(size=(16, 32)).astype(np.float32)
    yv = rng.normal(size=(16, 4)).astype(np.float32)
    h = cm.fit(xv, yv, epochs=1, verbose=False)
    assert np.isfinite(h[0]["loss"])
