"""AlexNet / CIFAR-10 via the Keras functional API — BASELINE config #1.

Reference analog: examples/python/keras/func_cifar10_alexnet.py (same layer
stack: 5 conv + 3 pool + 2 fc-4096 + softmax head at 229x229 input). Images
are upsampled from 32x32 to 229x229 like the reference (which used PIL; here
a nearest-neighbor numpy upsample, no PIL dependency).

Run:  python examples/keras/func_cifar10_alexnet.py [--samples N] [--epochs E]
On hosts without the CIFAR-10 npz, deterministic synthetic data is used.
"""

import argparse

import numpy as np

import flexflow_tpu.keras.optimizers as opt
from flexflow_tpu.keras.callbacks import EpochVerifyMetrics, VerifyMetrics
from flexflow_tpu.keras.datasets import cifar10
from flexflow_tpu.keras.layers import (
    Activation,
    Conv2D,
    Dense,
    Flatten,
    Input,
    MaxPooling2D,
)
from flexflow_tpu.keras.models import Model


def build_alexnet(num_classes: int = 10):
    input_tensor = Input(shape=(3, 229, 229), dtype="float32")
    x = Conv2D(filters=64, kernel_size=(11, 11), strides=(4, 4),
               padding=(2, 2), activation="relu")(input_tensor)
    x = MaxPooling2D(pool_size=(3, 3), strides=(2, 2), padding="valid")(x)
    x = Conv2D(filters=192, kernel_size=(5, 5), strides=(1, 1),
               padding=(2, 2), activation="relu")(x)
    x = MaxPooling2D(pool_size=(3, 3), strides=(2, 2), padding="valid")(x)
    x = Conv2D(filters=384, kernel_size=(3, 3), strides=(1, 1),
               padding=(1, 1), activation="relu")(x)
    x = Conv2D(filters=256, kernel_size=(3, 3), strides=(1, 1),
               padding=(1, 1), activation="relu")(x)
    x = Conv2D(filters=256, kernel_size=(3, 3), strides=(1, 1),
               padding=(1, 1), activation="relu")(x)
    x = MaxPooling2D(pool_size=(3, 3), strides=(2, 2), padding="valid")(x)
    x = Flatten()(x)
    x = Dense(4096, activation="relu")(x)
    x = Dense(4096, activation="relu")(x)
    x = Dense(num_classes)(x)
    out = Activation("softmax")(x)
    return Model(input_tensor, out)


def upsample_nearest(x: np.ndarray, size: int) -> np.ndarray:
    """(N, C, 32, 32) uint8 -> (N, C, size, size) float32 nearest-neighbor."""
    n, c, h, w = x.shape
    ih = (np.arange(size) * h // size).astype(np.int32)
    iw = (np.arange(size) * w // size).astype(np.int32)
    return x[:, :, ih[:, None], iw[None, :]].astype(np.float32)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--samples", type=int, default=512)
    ap.add_argument("--epochs", type=int, default=2)
    ap.add_argument("--batch-size", type=int, default=64)
    args = ap.parse_args()

    (x_train, y_train), _ = cifar10.load_data(args.samples)
    full_input = upsample_nearest(x_train, 229) / 255.0
    full_label = y_train.astype("int32").reshape(-1)

    model = build_alexnet()
    model.compile(optimizer=opt.SGD(learning_rate=0.01),
                  loss="sparse_categorical_crossentropy",
                  metrics=["accuracy", "sparse_categorical_crossentropy"])
    print(model.summary())
    model.fit(full_input, full_label, batch_size=args.batch_size,
              epochs=args.epochs, callbacks=[EpochVerifyMetrics(0.0)])


if __name__ == "__main__":
    main()
