"""Auto-parallel inference serving (ISSUE 10).

Two searched programs per decoder model (compute-priced prefill,
bandwidth-priced decode), a paged KV cache laid out by the winning decode
strategy, and a continuous-batching scheduler driving a device-resident
decode loop. Entry point: `compile_serving(model)`.
"""

from flexflow_tpu.serving import tracefmt
from flexflow_tpu.serving.engine import ServingCompiled, compile_serving
from flexflow_tpu.serving.fleet import (AdmissionControl, FleetRouter,
                                        RollingSwapController, ServingFleet,
                                        merge_histograms, merge_slo_trackers)
from flexflow_tpu.serving.kv_cache import (ACTIVE_KEY, KVPoolExhausted,
                                           PAGE_TABLE_KEY, POS_KEY,
                                           PagedKVCache,
                                           derive_prefetch_ahead)
from flexflow_tpu.serving.program import clone_for_serving, serving_optimize
from flexflow_tpu.serving.reqtrace import (RequestTracer, StreamingHistogram,
                                           TERMINAL_FIELDS, terminal_record)
from flexflow_tpu.serving.scheduler import (ContinuousBatchingScheduler,
                                            Request, gpt2_prompt_inputs,
                                            gpt2_step_inputs)
from flexflow_tpu.serving.tracefmt import (Trace, TraceRecord, load_trace,
                                           save_trace)
from flexflow_tpu.serving.twin import (TwinCosts, TwinResult, TwinSpec,
                                       capacity_curve, simulate)

__all__ = [
    "compile_serving", "ServingCompiled", "PagedKVCache", "KVPoolExhausted",
    "ContinuousBatchingScheduler", "Request", "clone_for_serving",
    "serving_optimize", "gpt2_prompt_inputs", "gpt2_step_inputs",
    "PAGE_TABLE_KEY", "POS_KEY", "ACTIVE_KEY",
    "RequestTracer", "StreamingHistogram", "TERMINAL_FIELDS",
    "terminal_record",
    "ServingFleet", "AdmissionControl", "FleetRouter",
    "RollingSwapController", "merge_histograms", "merge_slo_trackers",
    "derive_prefetch_ahead",
    "tracefmt", "Trace", "TraceRecord", "load_trace", "save_trace",
    "TwinSpec", "TwinCosts", "TwinResult", "simulate", "capacity_curve",
]
