"""Machine-agnostic tensors of the frontend graph.

Reference analog: `Tensor`/`TensorBase` (include/flexflow/tensor.h) — the
machine-agnostic values produced by frontends, before parallelization. Here a
`Tensor` is a symbolic handle into the layer graph: it records its spec
(shape/dtype), the producing layer, and its output slot. The *parallel* view of
a tensor (dim degrees / mesh-axis assignment) lives in
flexflow_tpu.parallel.ptensor.ParallelTensor.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Optional, Tuple

from flexflow_tpu.dtype import DataType


@dataclasses.dataclass(frozen=True)
class TensorSpec:
    """Static shape + dtype. Shapes are always fully static (XLA requirement)."""

    shape: Tuple[int, ...]
    dtype: DataType = DataType.FLOAT

    def __post_init__(self):
        object.__setattr__(self, "shape", tuple(int(d) for d in self.shape))
        if any(d <= 0 for d in self.shape):
            raise ValueError(f"non-positive dim in shape {self.shape}")

    @property
    def ndim(self) -> int:
        return len(self.shape)

    @property
    def num_elements(self) -> int:
        return math.prod(self.shape) if self.shape else 1

    @property
    def size_bytes(self) -> int:
        return self.num_elements * self.dtype.itemsize

    def with_shape(self, shape) -> "TensorSpec":
        return TensorSpec(tuple(shape), self.dtype)

    def with_dtype(self, dtype: DataType) -> "TensorSpec":
        return TensorSpec(self.shape, dtype)

    def __repr__(self):
        return f"{self.dtype.value}{list(self.shape)}"


class Tensor:
    """Symbolic value in the layer graph.

    `owner` is the producing Layer (None for graph inputs created via
    FFModel.create_tensor), `owner_idx` the output slot.
    """

    _next_guid = [1000]

    def __init__(self, spec: TensorSpec, owner=None, owner_idx: int = 0, name: Optional[str] = None):
        self.spec = spec
        self.owner = owner
        self.owner_idx = owner_idx
        self.guid = Tensor._next_guid[0]
        Tensor._next_guid[0] += 1
        self.name = name or f"tensor_{self.guid}"

    # Convenience accessors mirroring the reference Python API
    # (python/flexflow/core/flexflow_cffi.py Tensor.dims etc.)
    @property
    def shape(self) -> Tuple[int, ...]:
        return self.spec.shape

    @property
    def dims(self) -> Tuple[int, ...]:
        return self.spec.shape

    @property
    def dtype(self) -> DataType:
        return self.spec.dtype

    @property
    def ndim(self) -> int:
        return self.spec.ndim

    def __repr__(self):
        return f"Tensor({self.name}: {self.spec})"
