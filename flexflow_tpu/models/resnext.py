"""ResNeXt-50 (32x4d) — the last OSDI'22 AE workload
(reference: examples/cpp/resnext50/resnext.cc, scripts/osdi22ae/resnext-50.sh).

The defining feature is the GROUPED 3x3 conv (cardinality 32), which is also
the workload that exercises attribute-parallel conv placement
(tests/test_workloads.py) on a non-toy network: grouped convs shard naturally
over the channel/group dim.

Mirrors the reference builder faithfully, including its quirks
(resnext.cc:12-32): blocks are built with `has_residual=False` by default —
the reference's stack is plain feedforward unless the caller opts in — and
the residual projection applies ReLU on the projected shortcut. One
deliberate deviation: when the caller opts into residuals, shape-preserving
blocks get the standard IDENTITY shortcut (the reference's gate drops the
skip entirely there, which would silently un-residual 12 of the 16 blocks)."""

from __future__ import annotations

from flexflow_tpu.core.model import FFModel


def resnext_block(model: FFModel, t, stride: int, out_c: int, groups: int,
                  name: str, has_residual: bool = False):
    """1x1 (relu) -> 3x3 grouped (relu) -> 1x1 to 2*out_c; optional
    projected residual (reference resnext.cc:12-32)."""
    inp = t
    u = model.conv2d(t, out_c, 1, 1, 1, 1, 0, 0, activation="relu",
                     name=f"{name}_c1")
    u = model.conv2d(u, out_c, 3, 3, stride, stride, 1, 1, activation="relu",
                     groups=groups, name=f"{name}_c2")
    u = model.conv2d(u, 2 * out_c, 1, 1, 1, 1, 0, 0, name=f"{name}_c3")
    if has_residual:
        if stride > 1 or inp.shape[1] != 2 * out_c:
            inp = model.conv2d(inp, 2 * out_c, 1, 1, stride, stride, 0, 0,
                               activation="relu", name=f"{name}_proj")
        u = model.relu(model.add(inp, u, name=f"{name}_addres"),
                       name=f"{name}_relu")
    return u


def build_resnext50(model: FFModel, batch: int = 64, in_hw: int = 224,
                    classes: int = 1000, groups: int = 32, width: int = 128,
                    has_residual: bool = False):
    """Stage plan (reference resnext.cc:62-82): 3/4/6/3 blocks at width
    128/256/512/1024, stride 2 entering each stage after the first.
    `width`/`in_hw` scale down for CPU tests."""
    x = model.create_tensor([batch, 3, in_hw, in_hw], name="image")
    t = model.conv2d(x, 64, 7, 7, 2, 2, 3, 3, activation="relu", name="stem")
    t = model.pool2d(t, 3, 3, 2, 2, 1, 1, name="stem_pool")
    stages = [(width, 3, 1), (2 * width, 4, 2), (4 * width, 6, 2),
              (8 * width, 3, 2)]
    for si, (c, blocks, stride) in enumerate(stages):
        for bi in range(blocks):
            t = resnext_block(model, t, stride if bi == 0 else 1, c, groups,
                              f"s{si}b{bi}", has_residual=has_residual)
    t = model.relu(t, name="final_relu")
    # global average pool over the remaining spatial extent (reference uses
    # pool2d with kernel == spatial dims; mean is the TPU-native reduction)
    t = model.mean(t, axes=[2, 3], name="gap")
    t = model.flat(t, name="flat")
    logits = model.dense(t, classes, name="fc")
    return x, logits
