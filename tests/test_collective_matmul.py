"""Collective matmul (ISSUE 12 tentpole b): the ring all-gather/matmul
overlap vs the plain GSPMD `x @ w` it replaces — forward and gradient
parity on the 8-virtual-device mesh, the output layout contract, and the
shape/mesh precheck."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from flexflow_tpu.kernels.collective_matmul import (
    collective_matmul, collective_matmul_supported)


@pytest.fixture
def mesh(devices):
    return Mesh(np.asarray(devices).reshape(2, 4), ("data", "model"))


def _xw(m=64, k=32, n=64, seed=0):
    rng = np.random.default_rng(seed)
    x = jnp.asarray(rng.normal(size=(m, k)), jnp.float32)
    w = jnp.asarray(rng.normal(size=(k, n)), jnp.float32)
    return x, w


def test_forward_matches_plain_matmul(mesh):
    x, w = _xw()
    y = collective_matmul(x, w, mesh, "model")
    ref = jnp.dot(x, w, preferred_element_type=jnp.float32)
    np.testing.assert_allclose(np.asarray(y), np.asarray(ref),
                               atol=1e-5, rtol=1e-5)
    # layout contract: rows gathered, columns still on the ring axis —
    # what GSPMD would produce for these layouts, minus the blocking gather
    spec = y.sharding.spec if isinstance(y.sharding, NamedSharding) else None
    assert spec == P(None, "model")


def test_gradients_match_plain_matmul(mesh):
    x, w = _xw()

    def f_ring(x, w):
        return jnp.sum(collective_matmul(x, w, mesh, "model") ** 2)

    def f_ref(x, w):
        return jnp.sum(jnp.dot(x, w,
                               preferred_element_type=jnp.float32) ** 2)

    gx, gw = jax.grad(f_ring, argnums=(0, 1))(x, w)
    rx, rw = jax.grad(f_ref, argnums=(0, 1))(x, w)
    np.testing.assert_allclose(np.asarray(gx), np.asarray(rx),
                               atol=2e-4, rtol=2e-4)
    np.testing.assert_allclose(np.asarray(gw), np.asarray(rw),
                               atol=2e-4, rtol=2e-4)


def test_data_axis_ring(mesh):
    """Any mesh axis can carry the ring, not just 'model'."""
    x, w = _xw(m=32, k=16, n=32, seed=1)
    y = collective_matmul(x, w, mesh, "data")
    np.testing.assert_allclose(
        np.asarray(y),
        np.asarray(jnp.dot(x, w, preferred_element_type=jnp.float32)),
        atol=1e-5, rtol=1e-5)


def test_supported_precheck_and_errors(mesh):
    assert collective_matmul_supported(mesh, "model", 64, 64)
    assert not collective_matmul_supported(mesh, "model", 63, 64)  # m % p
    assert not collective_matmul_supported(mesh, "model", 64, 66)  # n % p
    assert not collective_matmul_supported(mesh, "pipe", 64, 64)   # no axis
    assert not collective_matmul_supported(None, "model", 64, 64)
    x, w = _xw()
    with pytest.raises(ValueError):
        collective_matmul(x, w, mesh, "pipe")
    with pytest.raises(ValueError):
        collective_matmul(x[:, :16], w, mesh, "model")  # k mismatch
