"""Native-API MLP (reference analog: examples/python/native/mnist_mlp.py) —
also the launcher demo:

    python -m flexflow_tpu -b 64 -e 2 examples/native/mnist_mlp.py

The launcher parses the FFConfig flags; the script reads them via
flexflow_tpu.get_launch_config() (the flexflow_top pattern: the runtime owns
argv, the script owns the model)."""

import numpy as np

from flexflow_tpu import FFModel, SGDOptimizer, get_launch_config
from flexflow_tpu.keras.datasets import mnist


def main():
    cfg = get_launch_config()
    batch = cfg.batch_size
    (x, y), (xt, yt) = mnist.load_data(num_samples=8192)
    x = (x.reshape(x.shape[0], -1).astype(np.float32) / 255.0) - 0.5
    xt = (xt.reshape(xt.shape[0], -1).astype(np.float32) / 255.0) - 0.5
    y = y.reshape(-1).astype(np.int32)
    yt = yt.reshape(-1).astype(np.int32)

    model = FFModel(cfg)
    inp = model.create_tensor([batch, x.shape[1]], name="pixels")
    h = model.dense(inp, 256, activation="relu", name="fc1")
    h = model.dense(h, 128, activation="relu", name="fc2")
    model.dense(h, 10, name="head")
    model.compile(SGDOptimizer(lr=cfg.learning_rate),
                  loss_type="sparse_categorical_crossentropy",
                  metrics=["accuracy"])
    hist = model.fit(x, y, epochs=cfg.epochs, verbose=True)
    ev = model.eval(xt, yt)
    print(f"FINAL loss={hist[-1]['loss']:.4f} "
          f"test_accuracy={ev.get('accuracy', 0.0):.4f}")
    return hist, ev


if __name__ == "__main__":
    main()
