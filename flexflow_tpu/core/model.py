"""FFModel — the model-builder and training entry point.

Reference analog: `FFModel` (include/flexflow/model.h:326, Python mirror
python/flexflow/core/flexflow_cffi.py:887). The builder methods append Layers
to the frontend graph; `compile()` is the pivot (reference
src/runtime/model.cc:2803): it lowers the layer graph to a PCG, runs the
strategy search (or data-parallel fallback), and builds one jitted SPMD train
step; `fit()` is the training loop (flexflow_cffi.py:2062).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Tuple, Union

import numpy as np

from flexflow_tpu.config import FFConfig
from flexflow_tpu.core.graph import topo_order, to_dot
from flexflow_tpu.core.layer import Layer
from flexflow_tpu.core.tensor import Tensor, TensorSpec
from flexflow_tpu.dtype import DataType
from flexflow_tpu.losses import LossType
from flexflow_tpu.metrics import MetricsType
from flexflow_tpu.ops import get_op_def
from flexflow_tpu.ops.op_type import OperatorType


class FFModel:
    def __init__(self, config: Optional[FFConfig] = None):
        self.config = config or FFConfig()
        self.layers: List[Layer] = []
        self.input_tensors: List[Tensor] = []
        self._dedup: Dict[Tuple, Layer] = {}
        self.label_tensor: Optional[Tensor] = None
        self._compiled = None  # CompiledModel after compile()
        self._initializer_overrides: Dict[Tuple[str, str], Any] = {}
        # (layer, wname) -> [("l1"|"l2", coeff)]: penalty terms the compiled
        # train step adds to the loss (keras kernel_regularizer analog —
        # reference RegularizerMode, python/flexflow/keras/regularizers.py)
        self._weight_regularizers: Dict[Tuple[str, str], List[Tuple[str, float]]] = {}

    def add_weight_regularizer(self, layer_name: str, wname: str,
                               mode: str, coeff: float) -> None:
        """Register an L1/L2 penalty on a weight; differentiated as part of
        the training loss (compiler/compile.py)."""
        if mode not in ("l1", "l2"):
            raise ValueError(f"unknown regularizer mode {mode!r}")
        self._weight_regularizers.setdefault(
            (layer_name, wname), []).append((mode, float(coeff)))

    # ---------------------------------------------------------------- builder
    def create_tensor(self, dims: Sequence[int], dtype=DataType.FLOAT,
                      name: Optional[str] = None) -> Tensor:
        t = Tensor(TensorSpec(tuple(dims), DataType.from_any(dtype)), name=name)
        self.input_tensors.append(t)
        return t

    def _add_layer(self, op_type: OperatorType, params: Dict[str, Any],
                   inputs: Sequence[Tensor], name: Optional[str] = None,
                   initializers: Optional[Dict[str, Any]] = None) -> List[Tensor]:
        layer = Layer(op_type, params, list(inputs), name=name)
        specs = get_op_def(op_type).infer(layer)
        for i, spec in enumerate(specs):
            layer.add_output(spec, idx=i)
        self.layers.append(layer)
        if initializers:
            for wname, init in initializers.items():
                if init is not None:
                    self._initializer_overrides[(layer.name, wname)] = init
        return layer.outputs

    # dense / conv family -------------------------------------------------
    def dense(self, input: Tensor, out_dim: int, activation=None, use_bias: bool = True,
              kernel_initializer=None, bias_initializer=None, name=None) -> Tensor:
        return self._add_layer(
            OperatorType.LINEAR,
            {"out_dim": int(out_dim), "activation": activation, "use_bias": use_bias},
            [input], name,
            {"kernel": kernel_initializer, "bias": bias_initializer},
        )[0]

    def conv2d(self, input: Tensor, out_channels: int, kernel_h: int, kernel_w: int,
               stride_h: int = 1, stride_w: int = 1, padding_h: int = 0, padding_w: int = 0,
               activation=None, groups: int = 1, use_bias: bool = True,
               kernel_initializer=None, bias_initializer=None, name=None) -> Tensor:
        return self._add_layer(
            OperatorType.CONV2D,
            {"out_channels": int(out_channels), "kernel_h": kernel_h, "kernel_w": kernel_w,
             "stride_h": stride_h, "stride_w": stride_w, "padding_h": padding_h,
             "padding_w": padding_w, "activation": activation, "groups": groups,
             "use_bias": use_bias},
            [input], name,
            {"kernel": kernel_initializer, "bias": bias_initializer},
        )[0]

    def pool2d(self, input: Tensor, kernel_h: int, kernel_w: int, stride_h: int = 1,
               stride_w: int = 1, padding_h: int = 0, padding_w: int = 0,
               pool_type: str = "max", activation=None, name=None) -> Tensor:
        return self._add_layer(
            OperatorType.POOL2D,
            {"kernel_h": kernel_h, "kernel_w": kernel_w, "stride_h": stride_h,
             "stride_w": stride_w, "padding_h": padding_h, "padding_w": padding_w,
             "pool_type": pool_type, "activation": activation},
            [input], name)[0]

    def embedding(self, input: Tensor, num_entries: int, out_dim: int, aggr: str = "none",
                  dtype=DataType.FLOAT, kernel_initializer=None, name=None) -> Tensor:
        return self._add_layer(
            OperatorType.EMBEDDING,
            {"num_entries": int(num_entries), "out_dim": int(out_dim), "aggr": aggr,
             "dtype": DataType.from_any(dtype).value},
            [input], name, {"kernel": kernel_initializer})[0]

    def batch_matmul(self, A: Tensor, B: Tensor, a_seq_length_dim: int = -1,
                     b_seq_length_dim: int = -1, name=None) -> Tensor:
        # FFIterationConfig.seq_length analog: captured at BUILD time so
        # shape inference sees the truncated lengths and downstream specs
        # stay consistent (XLA static shapes; the reference truncates at
        # runtime over full-size regions instead)
        return self._add_layer(
            OperatorType.BATCHMATMUL,
            {"a_seq_length_dim": a_seq_length_dim,
             "b_seq_length_dim": b_seq_length_dim,
             "seq_length": int(self.config.seq_length or 0)},
            [A, B], name)[0]

    def multihead_attention(self, query: Tensor, key: Tensor, value: Tensor,
                            embed_dim: int, num_heads: int, kdim: int = 0, vdim: int = 0,
                            dropout: float = 0.0, bias: bool = True, add_bias_kv: bool = False,
                            add_zero_attn: bool = False, causal: bool = False,
                            kernel_initializer=None, impl: str = "auto",
                            decode: bool = False, kv_out: bool = False,
                            name=None) -> Tensor:
        # decode: single-token serving step reading/writing the paged KV
        # cache via lowering state; kv_out: prefill variant that exposes
        # per-head K/V for cache commit (flexflow_tpu/serving)
        return self._add_layer(
            OperatorType.MULTIHEAD_ATTENTION,
            {"embed_dim": int(embed_dim), "num_heads": int(num_heads), "kdim": kdim,
             "vdim": vdim, "dropout": dropout, "bias": bias, "add_bias_kv": add_bias_kv,
             "add_zero_attn": add_zero_attn, "causal": causal, "impl": impl,
             "decode": decode, "kv_out": kv_out},
            [query, key, value], name,
            {"wq": kernel_initializer, "wk": kernel_initializer, "wv": kernel_initializer,
             "wo": kernel_initializer})[0]

    # elementwise ---------------------------------------------------------
    def _unary(self, op, input, name=None, **params) -> Tensor:
        return self._add_layer(op, params, [input], name)[0]

    def _binary(self, op, a, b, name=None) -> Tensor:
        return self._add_layer(op, {}, [a, b], name)[0]

    def add(self, a, b, name=None):
        return self._binary(OperatorType.EW_ADD, a, b, name)

    def subtract(self, a, b, name=None):
        return self._binary(OperatorType.EW_SUB, a, b, name)

    def multiply(self, a, b, name=None):
        return self._binary(OperatorType.EW_MUL, a, b, name)

    def divide(self, a, b, name=None):
        return self._binary(OperatorType.EW_DIV, a, b, name)

    def max(self, a, b, name=None):
        return self._binary(OperatorType.EW_MAX, a, b, name)

    def min(self, a, b, name=None):
        return self._binary(OperatorType.EW_MIN, a, b, name)

    def relu(self, input, name=None):
        return self._unary(OperatorType.RELU, input, name)

    def identity(self, input, name=None):
        return self._unary(OperatorType.IDENTITY, input, name)

    def sigmoid(self, input, name=None):
        return self._unary(OperatorType.SIGMOID, input, name)

    def tanh(self, input, name=None):
        return self._unary(OperatorType.TANH, input, name)

    def elu(self, input, name=None):
        return self._unary(OperatorType.ELU, input, name)

    def gelu(self, input, name=None):
        return self._unary(OperatorType.GELU, input, name)

    def erf(self, input, name=None):
        return self._unary(OperatorType.ERF, input, name)

    def silu(self, input, name=None):
        return self._unary(OperatorType.SILU, input, name)

    def exp(self, input, name=None):
        return self._unary(OperatorType.EXP, input, name)

    def log(self, input, name=None):
        return self._unary(OperatorType.LOG, input, name)

    def sin(self, input, name=None):
        return self._unary(OperatorType.SIN, input, name)

    def cos(self, input, name=None):
        return self._unary(OperatorType.COS, input, name)

    def sqrt(self, input, name=None):
        return self._unary(OperatorType.SQRT, input, name)

    def rsqrt(self, input, name=None):
        return self._unary(OperatorType.RSQRT, input, name)

    def pow(self, input, exponent: float, name=None):
        return self._unary(OperatorType.POW, input, name, exponent=exponent)

    def scalar_multiply(self, input, scalar: float, name=None):
        return self._unary(OperatorType.SCALAR_MULTIPLY, input, name, scalar=scalar)

    def scalar_add(self, input, scalar: float, name=None):
        return self._unary(OperatorType.SCALAR_ADD, input, name, scalar=scalar)

    def scalar_sub(self, input, scalar: float, name=None):
        return self._unary(OperatorType.SCALAR_SUB, input, name, scalar=scalar)

    def scalar_true_divide(self, input, scalar: float, name=None):
        return self._unary(OperatorType.SCALAR_TRUE_DIV, input, name, scalar=scalar)

    # norm / softmax / dropout -------------------------------------------
    def batch_norm(self, input, relu: bool = True, momentum: float = 0.9,
                   eps: float = 1e-5, name=None):
        return self._add_layer(OperatorType.BATCHNORM,
                               {"relu": relu, "momentum": momentum, "eps": eps},
                               [input], name)[0]

    def layer_norm(self, input, axes=None, elementwise_affine: bool = True,
                   eps: float = 1e-5, name=None):
        return self._add_layer(OperatorType.LAYERNORM,
                               {"axes": axes, "elementwise_affine": elementwise_affine,
                                "eps": eps},
                               [input], name)[0]

    def softmax(self, input, axis: int = -1, name=None):
        return self._add_layer(OperatorType.SOFTMAX, {"axis": axis}, [input], name)[0]

    def log_softmax(self, input, axis: int = -1, name=None):
        return self._add_layer(OperatorType.LOG_SOFTMAX, {"axis": axis}, [input], name)[0]

    def dropout(self, input, rate: float = 0.5, seed: int = 0, name=None):
        return self._add_layer(OperatorType.DROPOUT, {"rate": rate, "seed": seed},
                               [input], name)[0]

    # shape ops -----------------------------------------------------------
    def reshape(self, input, shape: Sequence[int], name=None):
        return self._add_layer(OperatorType.RESHAPE, {"shape": tuple(shape)}, [input], name)[0]

    def transpose(self, input, perm: Sequence[int], name=None):
        return self._add_layer(OperatorType.TRANSPOSE, {"perm": tuple(perm)}, [input], name)[0]

    def flat(self, input, name=None):
        return self._add_layer(OperatorType.FLAT, {}, [input], name)[0]

    def concat(self, tensors: Sequence[Tensor], axis: int, name=None):
        return self._add_layer(OperatorType.CONCAT, {"axis": axis}, list(tensors), name)[0]

    def split(self, input, sizes: Union[int, Sequence[int]], axis: int, name=None) -> List[Tensor]:
        if isinstance(sizes, int):
            d = input.shape[axis % input.ndim]
            assert d % sizes == 0
            sizes = [d // sizes] * sizes
        return self._add_layer(OperatorType.SPLIT, {"sizes": tuple(sizes), "axis": axis},
                               [input], name)

    def reverse(self, input, axis: int, name=None):
        return self._add_layer(OperatorType.REVERSE, {"axis": axis}, [input], name)[0]

    def pad(self, input, pads, value=0.0, name=None):
        return self._add_layer(OperatorType.PAD, {"pads": tuple(map(tuple, pads)), "value": value},
                               [input], name)[0]

    def cast(self, input, dtype, name=None):
        return self._add_layer(OperatorType.CAST,
                               {"dtype": DataType.from_any(dtype).value}, [input], name)[0]

    def gather(self, input, index: Tensor, dim: int, name=None):
        return self._add_layer(OperatorType.GATHER, {"dim": dim}, [input, index], name)[0]

    def slice_tensor(self, input, starts, limits, name=None):
        return self._add_layer(OperatorType.SLICE,
                               {"starts": tuple(starts), "limits": tuple(limits)},
                               [input], name)[0]

    def expand(self, input, sizes: Sequence[int], name=None):
        """torch.Tensor.expand semantics (-1 keeps the dim)."""
        return self._add_layer(OperatorType.EXPAND, {"sizes": tuple(sizes)},
                               [input], name)[0]

    def constant(self, value, name=None) -> Tensor:
        """A fixed array baked into the graph (torch registered buffers,
        traced torch.tensor/ones/zeros literals)."""
        return self._add_layer(OperatorType.CONSTANT,
                               {"value": np.asarray(value)}, [], name)[0]

    def masked_fill(self, input, mask: Tensor, value: float, name=None):
        """Where mask is true, replace with value (torch.masked_fill)."""
        return self._add_layer(OperatorType.MASKED_FILL, {"value": float(value)},
                               [input, mask], name)[0]

    def where(self, cond: Tensor, a: Tensor, b: Tensor, name=None):
        """Elementwise select (torch.where): a where cond else b."""
        return self._add_layer(OperatorType.WHERE, {}, [cond, a, b], name)[0]

    def scaled_dot_product_attention(self, query, key, value, attn_mask=None,
                                     dropout_p: float = 0.0, is_causal: bool = False,
                                     scale=None, name=None) -> Tensor:
        """Core attention without projections (torch F.scaled_dot_product_attention)."""
        ins = [query, key, value] + ([attn_mask] if attn_mask is not None else [])
        return self._add_layer(
            OperatorType.SDPA,
            {"dropout_p": dropout_p, "is_causal": is_causal, "scale": scale},
            ins, name)[0]

    # reductions ----------------------------------------------------------
    def reduce_sum(self, input, axes, keepdims: bool = False, name=None):
        return self._add_layer(OperatorType.REDUCE_SUM,
                               {"axes": tuple(axes), "keepdims": keepdims}, [input], name)[0]

    def reduce_mean(self, input, axes, keepdims: bool = False, name=None):
        return self._add_layer(OperatorType.REDUCE_MEAN,
                               {"axes": tuple(axes), "keepdims": keepdims}, [input], name)[0]

    def mean(self, input, axes, keepdims: bool = False, name=None):
        return self._add_layer(OperatorType.MEAN,
                               {"axes": tuple(axes), "keepdims": keepdims}, [input], name)[0]

    def argmax(self, input, axis: int = -1, name=None):
        return self._add_layer(OperatorType.ARGMAX, {"axis": axis}, [input], name)[0]

    def top_k(self, input, k: int, sorted: bool = True, name=None) -> List[Tensor]:
        return self._add_layer(OperatorType.TOPK, {"k": int(k), "sorted": sorted}, [input], name)

    # MoE -----------------------------------------------------------------
    def group_by(self, data: Tensor, assign: Tensor, n_experts: int, alpha: float = 1.0,
                 name=None) -> List[Tensor]:
        return self._add_layer(OperatorType.GROUP_BY,
                               {"n_experts": int(n_experts), "alpha": alpha},
                               [data, assign], name)

    def experts(self, dispatched: Tensor, out_dim: int, activation=None,
                use_bias: bool = True, name=None) -> Tensor:
        return self._add_layer(OperatorType.EXPERTS,
                               {"out_dim": int(out_dim), "activation": activation,
                                "use_bias": use_bias},
                               [dispatched], name)[0]

    def aggregate(self, gates: Tensor, assign: Tensor, positions: Tensor,
                  expert_outputs: Tensor, name=None) -> Tensor:
        return self._add_layer(OperatorType.AGGREGATE, {},
                               [gates, assign, positions, expert_outputs], name)[0]

    def aggregate_spec(self, gates, assign, positions, expert_outputs, name=None) -> Tensor:
        return self._add_layer(OperatorType.AGGREGATE_SPEC, {},
                               [gates, assign, positions, expert_outputs], name)[0]

    def cache(self, input: Tensor, num_batches: int = 1, name=None) -> Tensor:
        return self._add_layer(OperatorType.CACHE, {"num_batches": num_batches}, [input], name)[0]

    def moe(self, input: Tensor, num_exp: int, num_select: int, expert_hidden_size: int,
            alpha: float = 2.0, lambda_bal: float = 0.0, name=None) -> Tensor:
        """Composite MoE block (reference: FFModel::moe include/flexflow/model.h:509-514,
        src/ops/moe.cc): topk gating + group_by + per-expert dense + aggregate."""
        gate_logits = self.dense(input, num_exp, name=f"{name or 'moe'}_gate")
        gate_probs = self.softmax(gate_logits)
        topk_vals, topk_idx = self.top_k(gate_probs, num_select)
        dispatched, positions = self.group_by(input, topk_idx, num_exp, alpha)
        hidden = self.experts(dispatched, expert_hidden_size, activation="relu",
                              name=f"{name or 'moe'}_experts")
        return self.aggregate(topk_vals, topk_idx, positions, hidden, name=f"{name or 'moe'}_agg")

    def fork_join(self, input: Tensor, branches, join: str = "add",
                  name=None) -> Tensor:
        """Inter-op placement composite: parallel branches that the search may
        place on disjoint device subsets (reference: Unity nonsequence splits,
        src/runtime/graph.cc:187-321; here a first-class composite like moe).

        Each branch is a callable f(sub_model: FFModel, x: Tensor) -> Tensor
        building an ordinary layer sub-graph. join: "add" sums branch
        outputs, "concat" concatenates along the last dim. Branch weights
        surface on this layer as "b{i}.{sub_layer}.{wname}"."""
        subs = []
        overrides = []
        for bi, build in enumerate(branches):
            bm = FFModel(self.config)
            bx = bm.create_tensor(list(input.shape), dtype=input.spec.dtype,
                                  name=f"_fj_b{bi}_in")
            out = build(bm, bx)
            # auto-generated sub-layer names embed the process-global Layer
            # guid; rename positionally so identically-built models get
            # identical weight keys (init determinism + name-based transfer)
            rename = {}
            for j, l in enumerate(bm.layers):
                if l.name == f"{l.op_type.value}_{l.guid}":
                    rename[l.name] = f"{l.op_type.value}{j}"
                    l.name = rename[l.name]
            subs.append((bm.layers, bx, out))
            overrides.append({(rename.get(ln, ln), wn): init
                              for (ln, wn), init in bm._initializer_overrides.items()})
        layer = Layer(OperatorType.FORK_JOIN,
                      {"join": join, "n_branches": len(branches)},
                      [input], name=name)
        layer.branches = subs
        for i, spec in enumerate(get_op_def(OperatorType.FORK_JOIN).infer(layer)):
            layer.add_output(spec, idx=i)
        self.layers.append(layer)
        # lift branch initializer overrides onto the prefixed weight names
        for bi, ov in enumerate(overrides):
            for (lname, wname), init in ov.items():
                self._initializer_overrides[
                    (layer.name, f"b{bi}.{lname}.{wname}")] = init
        return layer.outputs[0]

    # parallel ops (reference: src/parallel_ops/) --------------------------
    def repartition(self, input: Tensor, dim: int, axis: str = "data", name=None) -> Tensor:
        return self._add_layer(OperatorType.REPARTITION, {"dim": dim, "axis": axis},
                               [input], name)[0]

    def combine(self, input: Tensor, dim: int, axis: str, name=None) -> Tensor:
        return self._add_layer(OperatorType.COMBINE, {"dim": dim, "axis": axis},
                               [input], name)[0]

    def replicate(self, input: Tensor, name=None) -> Tensor:
        return self._add_layer(OperatorType.REPLICATE, {}, [input], name)[0]

    def reduction(self, input: Tensor, axis: str, name=None) -> Tensor:
        return self._add_layer(OperatorType.REDUCTION, {"axis": axis}, [input], name)[0]

    def all_to_all(self, input: Tensor, src_dim: int, dst_dim: int, axis: str,
                   name=None) -> Tensor:
        return self._add_layer(OperatorType.ALLTOALL,
                               {"src_dim": src_dim, "dst_dim": dst_dim, "axis": axis},
                               [input], name)[0]

    def fused_parallel(self, input: Tensor, dims: Sequence, name=None) -> Tensor:
        return self._add_layer(OperatorType.FUSED_PARALLEL, {"dims": tuple(dims)},
                               [input], name)[0]

    # ------------------------------------------------------------- compile
    def compile(self, optimizer=None, loss_type=LossType.SPARSE_CATEGORICAL_CROSSENTROPY,
                metrics: Sequence = (MetricsType.ACCURACY,), comp_mode=None,
                outputs: Optional[Sequence[Tensor]] = None):
        from flexflow_tpu.compiler.compile import compile_model

        self._compiled = compile_model(self, optimizer, LossType.from_any(loss_type),
                                       [MetricsType.from_any(m) for m in metrics],
                                       outputs=outputs)
        if self.config.export_dot:
            with open(self.config.export_dot, "w") as f:
                f.write(self.dot())
        if self.config.simulator_trace:
            self._compiled.export_sim_trace(self.config.simulator_trace)
        return self._compiled

    @property
    def compiled(self):
        if self._compiled is None:
            raise RuntimeError("call compile() first")
        return self._compiled

    # ------------------------------------------------------------ training
    def fit(self, x, y, batch_size: Optional[int] = None, epochs: Optional[int] = None,
            callbacks=None, verbose: bool = True,
            sync_every: Optional[int] = None,
            steps_per_dispatch: Optional[int] = None):
        """Train. sync_every/steps_per_dispatch override the config's
        async-pipeline knobs for this call (see FFConfig)."""
        return self.compiled.fit(x, y, batch_size=batch_size, epochs=epochs,
                                 callbacks=callbacks, verbose=verbose,
                                 sync_every=sync_every,
                                 steps_per_dispatch=steps_per_dispatch)

    def save_checkpoint(self, path: str, block: Optional[bool] = None) -> str:
        """Full-state checkpoint (async by default — cfg.async_checkpoint);
        see CompiledModel.save_checkpoint."""
        return self.compiled.save_checkpoint(path, block=block)

    def load_checkpoint(self, path: str) -> None:
        self.compiled.load_checkpoint(path)

    def forward(self, *inputs):
        return self.compiled.forward(*inputs)

    def eval(self, x, y, batch_size: Optional[int] = None):
        return self.compiled.evaluate(x, y, batch_size=batch_size)

    # --------------------------------------------------------------- misc
    def get_layers(self) -> List[Layer]:
        return list(self.layers)

    def get_layer_by_name(self, name: str) -> Layer:
        for l in self.layers:
            if l.name == name:
                return l
        raise KeyError(name)

    def get_parameter_by_name(self, layer_name: str, wname: str = "kernel"):
        return self.compiled.get_weight(layer_name, wname)

    def set_parameter_by_name(self, layer_name: str, wname: str, value: np.ndarray):
        self.compiled.set_weight(layer_name, wname, value)

    def dot(self, include_costs: Optional[bool] = None) -> str:
        """Graphviz export with sharding annotations; include_costs (the
        --include-costs-dot-graph flag, reference model.cc:3666-3676) adds
        each op's predicted roofline time on the compiled machine."""
        if include_costs is None:
            include_costs = self.config.include_costs_dot_graph
        ann = {}
        if self._compiled is not None:
            ann = {l: str(self._compiled.strategy.op_shardings.get(l.name, ""))
                   for l in self.layers}
            if include_costs:
                from flexflow_tpu.ops.registry import io_bytes
                from flexflow_tpu.search import cost_model as cm_

                machine = self._compiled.machine
                for l in self.layers:
                    t = cm_.compute_time(get_op_def(l.op_type).flop_count(l),
                                         io_bytes(l), machine)
                    ann[l] = (ann.get(l, "") + f"\\n{t * 1e6:.1f}us").lstrip("\\n")
        return to_dot(topo_order(self.layers), ann)
