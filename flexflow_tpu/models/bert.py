"""BERT encoder (config #3 of BASELINE.md: BERT-base pretraining proxy,
reference: examples/python/native/bert_proxy_native.py — encoder stack at
BERT-base dims driven by synthetic data)."""

from __future__ import annotations

from flexflow_tpu.core.model import FFModel
from flexflow_tpu.dtype import DataType
from flexflow_tpu.models.transformer import transformer_block


def build_bert(model: FFModel, batch: int = 8, seq: int = 512,
               vocab: int = 30522, d_model: int = 768, heads: int = 12,
               layers: int = 12, d_ff: int = 3072):
    ids = model.create_tensor([batch, seq], DataType.INT32, name="input_ids")
    pos = model.create_tensor([batch, seq], DataType.INT32, name="position_ids")
    tok = model.embedding(ids, vocab, d_model, name="tok_emb")
    pe = model.embedding(pos, seq, d_model, name="pos_emb")
    t = model.layer_norm(model.add(tok, pe), name="emb_ln")
    for i in range(layers):
        t = transformer_block(model, t, d_model, heads, d_ff, f"enc{i}")
    logits = model.dense(t, vocab, name="mlm_head")
    return (ids, pos), logits
