"""Keras frontend tests (reference analog: examples/python/keras smoke runs,
tests/python_interface_test.sh). BASELINE config #1 done-criterion: the
func_cifar10_alexnet-equivalent script runs end-to-end."""

import numpy as np

import flexflow_tpu.keras.optimizers as opt
from flexflow_tpu.keras.callbacks import EpochVerifyMetrics
from flexflow_tpu.keras.datasets import cifar10
from flexflow_tpu.keras.layers import (
    Activation,
    Add,
    BatchNormalization,
    Concatenate,
    Conv2D,
    Dense,
    Dropout,
    Flatten,
    Input,
    MaxPooling2D,
    concatenate,
)
from flexflow_tpu.keras.models import Model, Sequential


def test_functional_cnn_trains():
    (x_train, y_train), _ = cifar10.load_data(128)
    x = (x_train / 255.0).astype(np.float32)
    y = y_train.astype(np.int32).reshape(-1)
    inp = Input(shape=(3, 32, 32))
    t = Conv2D(16, (5, 5), padding=(2, 2), activation="relu")(inp)
    t = MaxPooling2D((2, 2), (2, 2))(t)
    t = Flatten()(t)
    t = Dense(32, activation="relu")(t)
    out = Activation("softmax")(Dense(10)(t))
    m = Model(inp, out)
    m.compile(optimizer=opt.SGD(learning_rate=0.05),
              loss="sparse_categorical_crossentropy", metrics=["accuracy"])
    hist = m.fit(x, y, batch_size=32, epochs=2, verbose=False)
    assert np.isfinite(hist[-1]["loss"])
    assert m.predict(x[:32]).shape == (32, 10)
    ev = m.evaluate(x, y)
    assert "accuracy" in ev


def test_alexnet_example_builds_and_runs():
    """The BASELINE #1 script at reduced sample count."""
    import importlib.util
    import os

    spec = importlib.util.spec_from_file_location(
        "alexnet_example",
        os.path.join(os.path.dirname(__file__), os.pardir, "examples",
                     "keras", "func_cifar10_alexnet.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    model = mod.build_alexnet()
    (x_train, y_train), _ = cifar10.load_data(32)
    x = mod.upsample_nearest(x_train, 229) / 255.0
    y = y_train.astype(np.int32).reshape(-1)
    model.compile(optimizer=opt.SGD(learning_rate=0.01),
                  loss="sparse_categorical_crossentropy", metrics=["accuracy"])
    hist = model.fit(x, y, batch_size=16, epochs=1, verbose=False,
                     callbacks=[EpochVerifyMetrics(0.0)])
    assert np.isfinite(hist[-1]["loss"])


def test_sequential_and_merges():
    rng = np.random.default_rng(0)
    xs = rng.normal(size=(64, 16)).astype(np.float32)
    ys = (xs.sum(1) > 0).astype(np.int32)

    sm = Sequential([Dense(32, activation="relu", input_shape=(16,)),
                     Dropout(0.1), Dense(2)])
    sm.compile(optimizer="adam", loss="sparse_categorical_crossentropy",
               metrics=["accuracy"])
    hist = sm.fit(xs, ys, batch_size=32, epochs=2, verbose=False)
    assert np.isfinite(hist[-1]["loss"])

    # functional with merges (concat + residual add)
    inp = Input(shape=(16,))
    a = Dense(16, activation="relu")(inp)
    b = Dense(16, activation="relu")(inp)
    c = concatenate([a, b], axis=-1)
    d = Dense(16)(c)
    e = Add()([d, a])
    out = Dense(2)(e)
    m = Model(inp, out)
    m.compile(optimizer=opt.Adam(learning_rate=1e-3),
              loss="sparse_categorical_crossentropy", metrics=["accuracy"])
    hist = m.fit(xs, ys, batch_size=32, epochs=2, verbose=False)
    assert np.isfinite(hist[-1]["loss"])
