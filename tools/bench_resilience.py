"""Resilience smoke + micro-bench: kill-and-resume trajectory parity.

Drives the ISSUE-6 acceptance scenario end to end with REAL processes and
REAL signals (no mocks): a training run writing durable atomic-commit
checkpoints (runtime/resilience.py) is SIGKILLed mid-epoch, relaunched
with resume="auto", and must finish with the loss trajectory of an
uninterrupted run — on the same mesh AND on a resized mesh (elastic
resume re-shards via the PR 3/4 cross-mesh restore). A fourth leg runs
with a deterministic fault plan (runtime/faults.py) injecting transient
failures at the dataloader-transfer, dispatch and checkpoint-write sites:
retry/backoff must recover every one of them with the trajectory
bit-unperturbed (injected faults fire BEFORE any state mutation).

  python tools/bench_resilience.py            # full run: 2x the epochs,
      prints JSON including the measured durable-checkpoint overhead
      (checkpoint_parity leg seconds vs the no-checkpoint reference)
  python tools/bench_resilience.py --check    # CI smoke (tier-1 safe,
      wired into tests/test_resilience.py): the same legs at the short
      epoch count, no overhead stats; exits nonzero when any leg's
      relaunched trajectory diverges from the uninterrupted reference,
      when the killed run failed to leave a committed snapshot behind, or
      when an injected fault escaped recovery.

The worker (--worker) is this same file: a tiny Adam MLP (moments make
resume correctness observable), fixed seeds, ~8 steps/epoch; it prints
`HISTORY <json losses>` on completion. --step-sleep paces the steps so
the parent's SIGKILL reliably lands mid-epoch.
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import signal
import subprocess
import sys
import tempfile
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                           + " --xla_force_host_platform_device_count=8")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

EPOCHS = 3
BATCH = 16
N_SAMPLES = 128  # 8 steps/epoch
CKPT_EVERY = 3


def _data():
    import numpy as np

    rng = np.random.default_rng(0)
    x = rng.normal(size=(N_SAMPLES, 32)).astype(np.float32)
    w = rng.normal(size=(32, 4)).astype(np.float32)
    y = (x @ w).argmax(axis=1).astype(np.int32)
    return x, y


def _build(mesh: str):
    from flexflow_tpu import AdamOptimizer, FFConfig, FFModel

    mesh_shape = {}
    for part in (mesh or "").split(","):
        if part.strip():
            k, v = part.split("=")
            mesh_shape[k.strip()] = int(v)
    cfg = FFConfig(batch_size=BATCH, only_data_parallel=True, seed=5,
                   log_level="warning", mesh_shape=mesh_shape)
    m = FFModel(cfg)
    x = m.create_tensor([BATCH, 32], name="x")
    h = m.dense(x, 64, activation="relu", name="fc1")
    m.dense(h, 4, name="head")
    return m.compile(AdamOptimizer(alpha=0.01),
                     loss_type="sparse_categorical_crossentropy", metrics=[])


class _Pacer:
    """Per-step sleep so the parent's SIGKILL lands mid-epoch (a per-batch
    callback also pins the fit loop to per-step dispatch — deterministic
    step/checkpoint interleaving across every leg)."""

    def __init__(self, secs: float):
        self.secs = secs

    def on_batch_end(self, it, logs):
        if self.secs:
            time.sleep(self.secs)


def worker(args) -> int:
    from flexflow_tpu.runtime.resilience import Preempted

    cm = _build(args.mesh)
    cm.init(seed=0)
    x, y = _data()
    try:
        hist = cm.fit(x, y, epochs=args.epochs or EPOCHS, verbose=False,
                      checkpoint_dir=args.ckpt_dir or None,
                      checkpoint_every_steps=CKPT_EVERY if args.ckpt_dir
                      else None,
                      resume=args.resume or None,
                      callbacks=[_Pacer(args.step_sleep)])
    except Preempted as e:
        print(f"PREEMPTED {e.checkpoint_path}", flush=True)
        raise
    cm.wait_checkpoints()
    print("HISTORY " + json.dumps([h["loss"] for h in hist]), flush=True)
    return 0


# --------------------------------------------------------------- the parent
def _spawn(extra, env_extra=None):
    env = dict(os.environ)
    env.pop("FF_FAULT_PLAN", None)
    env.update(env_extra or {})
    return subprocess.Popen(
        [sys.executable, os.path.abspath(__file__), "--worker"] + extra,
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        env=env, cwd=os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))


def _finish(proc, timeout=240):
    out, _ = proc.communicate(timeout=timeout)
    return proc.returncode, out


def _history(out: str):
    for line in reversed(out.splitlines()):
        if line.startswith("HISTORY "):
            return json.loads(line[len("HISTORY "):])
    return None


def _wait_for_commit(root: str, proc, timeout=180.0) -> bool:
    """Poll until the running worker commits its first durable snapshot
    (True), or it exits / the deadline passes (False)."""
    from flexflow_tpu.runtime.resilience import committed_snapshots

    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if committed_snapshots(root):
            return True
        if proc.poll() is not None:
            return False
        time.sleep(0.02)
    return False


def main(argv=None) -> int:
    p = argparse.ArgumentParser("bench_resilience")
    p.add_argument("--check", action="store_true")
    p.add_argument("--worker", action="store_true")
    p.add_argument("--ckpt-dir", type=str, default="")
    p.add_argument("--resume", type=str, default="")
    p.add_argument("--mesh", type=str, default="")
    p.add_argument("--step-sleep", type=float, default=0.0)
    p.add_argument("--epochs", type=int, default=0)
    p.add_argument("--out", type=str, default="")
    args = p.parse_args(argv)
    if args.worker:
        return worker(args)

    import numpy as np

    # --check = the fast CI scope; the full bench doubles the epochs and
    # adds the measured durable-checkpoint overhead to the report
    n_epochs = EPOCHS if args.check else 2 * EPOCHS
    base = ["--epochs", str(n_epochs)]
    work = tempfile.mkdtemp(prefix="ff_resilience_")
    report = {"legs": {}, "mode": "check" if args.check else "full",
              "epochs": n_epochs}
    ok = True

    def leg(name, passed, **info):
        nonlocal ok
        ok = ok and passed
        report["legs"][name] = dict(info, passed=bool(passed))
        print(f"[{'ok' if passed else 'FAIL'}] {name}: {info}", flush=True)

    def close(losses, ref, tol=1e-5):
        return (losses is not None and len(losses) == len(ref)
                and bool(np.allclose(losses, ref, rtol=tol, atol=1e-7)))

    try:
        # --- reference: uninterrupted run, no checkpointing ---
        t0 = time.time()
        rc, out = _finish(_spawn(base))
        ref = _history(out)
        leg("reference", rc == 0 and ref is not None,
            seconds=round(time.time() - t0, 2), losses=ref)
        if ref is None:
            print(out[-4000:])
            return 1

        # --- checkpointing overhead: same run writing durable snapshots ---
        root = os.path.join(work, "ck")
        t0 = time.time()
        rc, out = _finish(_spawn(base + ["--ckpt-dir", root]))
        h = _history(out)
        leg("checkpoint_parity", rc == 0 and close(h, ref, 1e-7),
            seconds=round(time.time() - t0, 2),
            note="durable snapshots must not perturb the trajectory")

        # --- kill-and-resume: SIGKILL mid-epoch, relaunch resume=auto ---
        root = os.path.join(work, "kill")
        proc = _spawn(base + ["--ckpt-dir", root, "--step-sleep", "0.08"])
        committed = _wait_for_commit(root, proc)
        time.sleep(0.3)  # let it run past the snapshot before the kill
        killed_mid_run = proc.poll() is None
        proc.kill()
        rc, out = _finish(proc)
        leg("sigkill_landed", committed and killed_mid_run
            and _history(out) is None, returncode=rc,
            note="worker must die mid-run with >=1 committed snapshot")
        # relaunch on the SAME mesh
        elastic_root = os.path.join(work, "kill_elastic")
        shutil.copytree(root, elastic_root)  # pristine copy for the 3rd leg
        rc, out = _finish(_spawn(base + ["--ckpt-dir", root, "--resume", "auto"]))
        h = _history(out)
        leg("kill_resume_same_mesh", rc == 0 and close(h, ref),
            losses=h)
        # relaunch on a RESIZED mesh (elastic resume re-shards)
        rc, out = _finish(_spawn(base + ["--ckpt-dir", elastic_root,
                                  "--resume", "auto",
                                  "--mesh", "data=4,model=2"]))
        h = _history(out)
        leg("kill_resume_resized_mesh", rc == 0 and close(h, ref),
            losses=h)

        # --- injected transient faults: recovered, trajectory untouched ---
        root = os.path.join(work, "faults")
        plan = "dataloader/transfer@2*2,fit/dispatch@3,checkpoint/write@1"
        rc, out = _finish(_spawn(base + ["--ckpt-dir", root],
                                 env_extra={"FF_FAULT_PLAN": plan}))
        h = _history(out)
        leg("injected_fault_recovery", rc == 0 and close(h, ref, 1e-7),
            plan=plan)
    finally:
        shutil.rmtree(work, ignore_errors=True)

    if not args.check:
        # full-bench extra: durable checkpointing's wall-clock overhead
        legs = report["legs"]
        r, c = (legs.get("reference", {}).get("seconds"),
                legs.get("checkpoint_parity", {}).get("seconds"))
        if r and c:
            report["checkpoint_overhead_pct"] = round(100.0 * (c - r) / r, 1)
    report["passed"] = ok
    print(json.dumps(report, indent=2))
    if args.out:
        with open(args.out, "w") as f:
            json.dump(report, f, indent=2)
    return 0 if ok else 1


if __name__ == "__main__":
    raise SystemExit(main())
