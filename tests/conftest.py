"""Test fixtures: run everything on a virtual 8-device CPU mesh.

Reference analog: tests/multinode_helpers/mpi_wrapper (fake multi-node on one
machine, SURVEY.md §4). Force the CPU platform BEFORE any jax backend init —
the axon TPU plugin otherwise claims the platform (env vars are overridden by
the site customization, so jax.config is the reliable lever).
"""

import os

os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
)

import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np
import pytest


@pytest.fixture(scope="session")
def devices():
    devs = jax.devices()
    assert len(devs) == 8, f"expected 8 virtual cpu devices, got {devs}"
    return devs


@pytest.fixture
def rng():
    return np.random.default_rng(0)
