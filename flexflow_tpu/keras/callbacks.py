"""Keras callbacks (reference: python/flexflow/keras/callbacks.py)."""

from __future__ import annotations


class Callback:
    def set_model(self, model):
        self.model = model

    def on_train_begin(self):
        pass

    def on_train_end(self):
        pass

    def on_epoch_end(self, epoch, metrics=None):
        pass


class VerifyMetrics(Callback):
    """Assert final accuracy reaches a floor (reference uses a ModelAccuracy
    enum; any object with a .value in percent, or a float fraction, works)."""

    def __init__(self, accuracy):
        self.target = (accuracy.value / 100.0
                       if hasattr(accuracy, "value") else float(accuracy))
        self.last = None

    def on_epoch_end(self, epoch, metrics=None):
        if metrics:
            self.last = metrics.get("accuracy")

    def on_train_end(self):
        if self.last is not None and self.last < self.target:
            raise AssertionError(
                f"accuracy {self.last:.4f} below target {self.target:.4f}")


class EpochVerifyMetrics(Callback):
    """Track whether any epoch reached the target accuracy."""

    def __init__(self, accuracy):
        self.target = (accuracy.value / 100.0
                       if hasattr(accuracy, "value") else float(accuracy))
        self.reached = False

    def on_epoch_end(self, epoch, metrics=None):
        if metrics and metrics.get("accuracy", 0.0) >= self.target:
            self.reached = True
