"""Persistent strategy cache — tier 1 of the search fast path.

Reference analog: Unity's per-(op, machine-view) cost caching amortizes
search across runs ("Beyond Data and Model Parallelism for DNNs"); the
learned-TPU-cost-model line treats the cost artifact as fingerprinted and
reusable rather than throwaway. Here the whole SEARCHED STRATEGY is the
artifact: `graph_optimize` keys the winning Strategy by

    (canonical graph hash, MachineSpec fingerprint, search-knob tuple,
     calibration fingerprint)

and stores it on disk in the same JSON schema as `--export`, so a warm
`compile()` of an unchanged model skips the substitution search entirely —
zero DP frontier expansions — after validating that the cached strategy
still type-checks against the graph (layer names, output/weight ranks,
mesh axes).

Invalidation is purely key-based: edit the graph, change the mesh or chip
coefficients, turn a search knob, or re-calibrate the measured cost store
(search/measure.py's on-disk microbenchmarks — their content hash IS the
calibration fingerprint) and the key changes, forcing a fresh search. A
stale entry that somehow survives a code drift is caught by the type-check
and reported as `invalidated`, never silently applied.

Layout: one `<key>.json` per entry under the cache dir
(`--strategy-cache-dir` > `$FF_STRATEGY_CACHE_DIR` >
`~/.cache/flexflow_tpu/strategy`), carrying the strategy plus a meta block
(fingerprints, predicted cost, search wall-clock) for `profile_report()`
cache-stats and `tools/bench_search.py`.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os
import time
from typing import Dict, List, Optional

from flexflow_tpu import telemetry as tel
from flexflow_tpu.core.graph import topo_order
from flexflow_tpu.parallel.machine import MachineSpec
from flexflow_tpu.parallel.sharding import Strategy, used_axes
from flexflow_tpu.search import memo

# bump when the cached schema or the search's output semantics change in a
# way old entries must not survive
CACHE_VERSION = 1


@dataclasses.dataclass
class CacheStats:
    hits: int = 0
    misses: int = 0
    stores: int = 0
    invalidated: int = 0  # key hit but the strategy no longer type-checks
    errors: int = 0       # unreadable/unwritable cache dir (degraded, not fatal)

    def as_dict(self) -> Dict[str, int]:
        return dataclasses.asdict(self)


STATS = CacheStats()


def resolve_dir(cfg) -> str:
    """--strategy-cache-dir > $FF_STRATEGY_CACHE_DIR > ~/.cache default."""
    d = getattr(cfg, "strategy_cache_dir", "") or \
        os.environ.get("FF_STRATEGY_CACHE_DIR", "") or \
        os.path.join("~", ".cache", "flexflow_tpu", "strategy")
    return os.path.expanduser(d)


# ------------------------------------------------------------ fingerprints
def graph_fingerprint(model) -> str:
    """Canonical hash of the layer graph INCLUDING names: the cached
    strategy is name-addressed (op_shardings key on layer names), so a
    renamed twin must miss and re-search rather than hit an artifact it
    cannot apply."""
    order = topo_order(model.layers)
    idx = {id(l): i for i, l in enumerate(order)}
    in_idx = {t.guid: i for i, t in enumerate(model.input_tensors)}
    rows = [tuple((t.name, t.spec.shape, str(t.spec.dtype))
                  for t in model.input_tensors)]
    from flexflow_tpu.search.pcg import _freeze as _freeze_params

    for l in order:
        ins = []
        for t in l.inputs:
            if t.owner is not None and id(t.owner) in idx:
                ins.append((idx[id(t.owner)], t.owner_idx))
            else:
                ins.append((-1, in_idx.get(t.guid, -9)))
        rows.append((l.name, l.op_type.value, _freeze_params(l.params),
                     tuple(ins), memo.freeze_weight_specs(l.weight_specs),
                     memo.branches_signature(l), len(l.outputs)))
    return hashlib.sha256(repr(rows).encode()).hexdigest()[:24]


def knob_fingerprint(cfg) -> str:
    """The search-affecting FFConfig knobs (machine-shape knobs are covered
    by the machine fingerprint; --substitution-json by its file content)."""
    sub = ""
    if cfg.substitution_json:
        try:
            with open(cfg.substitution_json, "rb") as f:
                sub = hashlib.sha256(f.read()).hexdigest()[:16]
        except OSError:
            sub = "unreadable:" + cfg.substitution_json
    knobs = (cfg.search_budget, cfg.search_alpha, cfg.only_data_parallel,
             cfg.enable_parameter_parallel, cfg.enable_attribute_parallel,
             cfg.base_optimize_threshold, cfg.memory_search, sub,
             cfg.simulator_mode, cfg.simulator_topk,
             cfg.simulator_segment_size,
             getattr(cfg, "zero_sharding", "off"),
             # the pipeline dimension changes both the searched machine
             # (stage sub-mesh) and the artifact (Strategy.pipeline): a
             # different stage count / schedule / microbatch width must
             # never hit a strategy searched for another
             getattr(cfg, "pipeline_stages", 1),
             getattr(cfg, "pipeline_schedule", "1f1b"),
             # the microbatch count M prices the bubble the cut-point
             # search ranks by — but only the pipelined search reads it, so
             # plain compiles keep their cache hits across accum changes
             (getattr(cfg, "accum_steps", 1)
              if getattr(cfg, "pipeline_stages", 1) > 1 else 1),
             # remat knobs change both the searched space (per-layer policy
             # dimension) and the artifact (Strategy.remat) — a strategy
             # searched without the remat dimension must never serve a
             # compile that asked for it, and vice versa
             getattr(cfg, "remat", False),
             getattr(cfg, "remat_search", False),
             (getattr(cfg, "remat_policies", "none,dots,full")
              if getattr(cfg, "remat_search", False) else ""))
    return hashlib.sha256(repr(knobs).encode()).hexdigest()[:16]


def calibration_fingerprint(measure_cache_path: Optional[str]) -> str:
    """Content hash of the persistent measured-cost store, or "analytic"
    when the analytic model prices the search. Re-running calibration
    rewrites that store, changes this fingerprint, and invalidates every
    strategy it priced — the invalidation rule documented in the README."""
    if not measure_cache_path:
        return "analytic"
    try:
        with open(measure_cache_path, "rb") as f:
            return "measured:" + hashlib.sha256(f.read()).hexdigest()[:16]
    except OSError:
        return "measured:empty"


def learned_fingerprint(model_path: Optional[str]) -> str:
    """Content hash of the learned cost model file (ISSUE 14), or "" when
    the learned tier is off. A refit (tools/refit_cost_model.py) rewrites
    the model file, changes this fingerprint, and invalidates every
    strategy the learned tier priced — same rule as the calibration
    fingerprint above."""
    if not model_path:
        return ""
    try:
        with open(model_path, "rb") as f:
            return "learned:" + hashlib.sha256(f.read()).hexdigest()[:16]
    except OSError:
        return "learned:absent"


def cache_key(model, machine: MachineSpec, cfg,
              calib_fp: str = "analytic", opt_fp: str = "",
              learned_fp: str = "") -> str:
    # opt_fp: the OptMemSpec fingerprint (search/cost_model.py) — the
    # optimizer's moment count/dtype and ZeRO axes change the memory
    # accounting memory-constrained searches rank by
    parts = (CACHE_VERSION, graph_fingerprint(model),
             memo.machine_fingerprint(machine), knob_fingerprint(cfg),
             calib_fp, opt_fp)
    if learned_fp:
        # appended only when the learned tier is active so every
        # pre-existing key (and stored strategy) stays bitwise-identical
        parts = parts + (learned_fp,)
    return hashlib.sha256(repr(parts).encode()).hexdigest()[:32]


# ------------------------------------------------------------- validation
def validate_strategy(strategy: Strategy, model,
                      machine: MachineSpec) -> List[str]:
    """Type-check a cached strategy against the live graph: every named
    layer exists, dim lists match tensor ranks, every axis is on the mesh.
    Returns the list of problems (empty = valid)."""
    problems: List[str] = []
    layers = {l.name: l for l in model.layers}
    inputs = {t.name: t for t in model.input_tensors}
    axes = set(machine.mesh_axes)
    if strategy.mesh_axes and dict(strategy.mesh_axes) != dict(machine.mesh_axes):
        problems.append(f"mesh {dict(strategy.mesh_axes)} != "
                        f"{dict(machine.mesh_axes)}")
    for name, sh in strategy.op_shardings.items():
        l = layers.get(name)
        if l is None:
            problems.append(f"unknown layer {name!r}")
            continue
        for oi, dims in enumerate(sh.outputs):
            if oi >= len(l.outputs) or len(dims) != l.outputs[oi].spec.ndim:
                problems.append(f"{name} output {oi} rank mismatch")
            elif any(a not in axes for a in used_axes(dims)):
                problems.append(f"{name} output {oi} uses unknown axis")
        for w, dims in sh.weights.items():
            spec = l.weight_specs.get(w)
            if spec is None or len(dims) != spec.ndim:
                problems.append(f"{name} weight {w!r} rank mismatch")
            elif any(a not in axes for a in used_axes(dims)):
                problems.append(f"{name} weight {w!r} uses unknown axis")
    for name, dims in strategy.input_shardings.items():
        t = inputs.get(name)
        if t is None or len(dims) != t.spec.ndim:
            problems.append(f"input {name!r} rank mismatch")
        elif any(a not in axes for a in used_axes(dims)):
            problems.append(f"input {name!r} uses unknown axis")
    return problems


# -------------------------------------------------------------------- io
def _path(cache_dir: str, key: str) -> str:
    return os.path.join(cache_dir, f"{key}.json")


def lookup(cache_dir: str, key: str, model,
           machine: MachineSpec) -> Optional[Strategy]:
    """Load + validate; returns the Strategy on a usable hit, else None
    (miss or invalidated — STATS records which)."""
    try:
        with open(_path(cache_dir, key)) as f:
            entry = json.load(f)
    except (OSError, ValueError):
        STATS.misses += 1
        tel.event("search/strategy_cache", cat="compile", event="miss")
        return None
    if entry.get("version") != CACHE_VERSION:
        STATS.misses += 1
        tel.event("search/strategy_cache", cat="compile", event="miss")
        return None
    try:
        st = Strategy.from_json(entry["strategy"])
        problems = validate_strategy(st, model, machine)
    except (KeyError, TypeError, ValueError, AttributeError):
        # readable but malformed (hand-edited / schema drift without a
        # version bump): degrade to a miss, never abort the compile
        STATS.invalidated += 1
        tel.event("search/strategy_cache", cat="compile",
                  event="invalidated")
        return None
    if problems:
        STATS.invalidated += 1
        tel.event("search/strategy_cache", cat="compile",
                  event="invalidated")
        return None
    STATS.hits += 1
    tel.event("search/strategy_cache", cat="compile", event="hit", key=key)
    st._cache_info = {"event": "hit", "key": key, "dir": cache_dir,
                      "meta": entry.get("meta", {})}
    # the stored search's predicted per-step cost rides back out with the
    # strategy — the drift monitor (CompiledModel.drift_stats) compares it
    # against fit-measured step times even on warm compiles
    cost = entry.get("meta", {}).get("cost_s")
    if cost:
        st._predicted_cost = float(cost)
    # ... and the per-op breakdown, so warm compiles keep the per-op drift
    # attribution (flexflow_tpu/attribution.py) the cold search enabled
    op_costs = entry.get("meta", {}).get("op_costs_s")
    if isinstance(op_costs, dict):
        st._predicted_op_costs = {str(k): float(v)
                                  for k, v in op_costs.items()}
    return st


def store(cache_dir: str, key: str, strategy: Strategy,
          meta: Optional[dict] = None) -> None:
    """Write-through (atomic rename); an unwritable dir degrades to a
    per-process no-op rather than failing the compile."""
    entry = {"version": CACHE_VERSION, "strategy": strategy.to_json(),
             "meta": dict(meta or {}, created_unix=time.time())}
    try:
        os.makedirs(cache_dir, exist_ok=True)
        tmp = _path(cache_dir, key) + f".tmp{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(entry, f, indent=1)
        os.replace(tmp, _path(cache_dir, key))
    except OSError:
        STATS.errors += 1
        return
    STATS.stores += 1
    tel.event("search/strategy_cache", cat="compile", event="store", key=key)
    strategy._cache_info = {"event": "store", "key": key, "dir": cache_dir,
                            "meta": entry["meta"]}
