"""Sequence preprocessing — pad_sequences and friends.

Reference analog: python/flexflow/keras/preprocessing/sequence.py, which
re-exports keras_preprocessing.sequence. Implemented natively here (numpy,
no keras_preprocessing dependency), matching the keras API contract."""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import numpy as np


def pad_sequences(sequences, maxlen: Optional[int] = None, dtype="int32",
                  padding: str = "pre", truncating: str = "pre",
                  value=0.0) -> np.ndarray:
    """Pad each sequence to the same length (keras semantics: default
    PRE-padding and PRE-truncation; returns (n, maxlen))."""
    if padding not in ("pre", "post"):
        raise ValueError(f"padding must be 'pre'/'post', got {padding!r}")
    if truncating not in ("pre", "post"):
        raise ValueError(f"truncating must be 'pre'/'post', got {truncating!r}")
    seqs = [list(s) for s in sequences]
    if maxlen is None:
        maxlen = max((len(s) for s in seqs), default=0)
    out = np.full((len(seqs), maxlen), value, dtype=dtype)
    for i, s in enumerate(seqs):
        if not s:
            continue
        trunc = s[-maxlen:] if truncating == "pre" else s[:maxlen]
        if padding == "post":
            out[i, :len(trunc)] = trunc
        else:
            out[i, -len(trunc):] = trunc
    return out


def make_sampling_table(size: int, sampling_factor: float = 1e-5) -> np.ndarray:
    """Word-rank -> keep-probability table for skipgram subsampling
    (Zipf-approximated word frequencies, the word2vec heuristic)."""
    gamma = 0.577
    rank = np.arange(size)
    rank[0] = 1
    inv_fq = rank * (np.log(rank) + gamma) + 0.5 - 1.0 / (12.0 * rank)
    f = sampling_factor * inv_fq
    return np.minimum(1.0, np.sqrt(f) + f)


def skipgrams(sequence: Sequence[int], vocabulary_size: int,
              window_size: int = 4, negative_samples: float = 1.0,
              shuffle: bool = True, categorical: bool = False,
              sampling_table: Optional[np.ndarray] = None,
              seed: Optional[int] = None) -> Tuple[List, List]:
    """(word, context) skipgram pairs with sampled negatives."""
    couples: List = []
    labels: List = []
    for i, wi in enumerate(sequence):
        if not wi:
            continue
        if sampling_table is not None:
            if sampling_table[wi] < np.random.random():
                continue
        window_start = max(0, i - window_size)
        window_end = min(len(sequence), i + window_size + 1)
        for j in range(window_start, window_end):
            if j != i and sequence[j]:
                couples.append([wi, sequence[j]])
                labels.append([0, 1] if categorical else 1)
    if negative_samples > 0 and couples:
        n_neg = int(len(labels) * negative_samples)
        words = [c[0] for c in couples]
        np.random.shuffle(words)
        couples += [[words[i % len(words)],
                     np.random.randint(1, vocabulary_size)]
                    for i in range(n_neg)]
        labels += [[1, 0] if categorical else 0] * n_neg
    if shuffle:
        if seed is None:
            seed = np.random.randint(0, 10 ** 6)
        rng = np.random.RandomState(seed)
        idx = rng.permutation(len(couples))
        couples = [couples[i] for i in idx]
        labels = [labels[i] for i in idx]
    return couples, labels


def _remove_long_seq(maxlen: int, seq, label):
    """Drop (sequence, label) pairs whose sequence exceeds maxlen."""
    new_seq, new_label = [], []
    for x, y in zip(seq, label):
        if len(x) < maxlen:
            new_seq.append(x)
            new_label.append(y)
    return new_seq, new_label
