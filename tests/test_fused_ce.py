"""Fused cross-entropy kernel (ISSUE 12 tentpole b): blockwise online
log-sum-exp loss vs the optax reference — forward and gradient parity at
f32/bf16, the no-f32-[N,vocab]-materialization claim checked on the
jaxpr, the auto/on/off mode gate, and end-to-end loss parity on the
sharded compile path."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from flexflow_tpu import FFConfig, FFModel, SGDOptimizer
from flexflow_tpu.core.layer import Layer
from flexflow_tpu.core.tensor import Tensor
from flexflow_tpu.kernels.fused_ce import (fused_ce_supported,
                                           fused_cross_entropy,
                                           use_fused_ce)
from flexflow_tpu.losses import LossType


def _ref(logits, labels):
    return jnp.mean(optax.softmax_cross_entropy_with_integer_labels(
        logits.astype(jnp.float32), labels))


def _data(n=64, v=640, dtype=jnp.float32, seed=0):
    rng = np.random.default_rng(seed)
    logits = jnp.asarray(rng.normal(size=(n, v)) * 3.0, dtype)
    labels = jnp.asarray(rng.integers(0, v, size=(n,)), jnp.int32)
    return logits, labels


@pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16])
def test_forward_matches_optax(dtype):
    logits, labels = _data(dtype=dtype)
    out = fused_cross_entropy(logits, labels)
    ref = _ref(logits, labels)
    # both paths do the log-sum-exp in f32 from the same inputs
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=1e-5, rtol=1e-5)


@pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16])
def test_gradient_matches_optax(dtype):
    logits, labels = _data(dtype=dtype)
    g_fused = jax.grad(lambda x: fused_cross_entropy(x, labels))(logits)
    g_ref = jax.grad(lambda x: _ref(x, labels))(logits)
    atol = 1e-6 if dtype == jnp.float32 else 2e-4  # bf16 output rounding
    np.testing.assert_allclose(np.asarray(g_fused, jnp.float32),
                               np.asarray(g_ref, jnp.float32),
                               atol=atol, rtol=1e-4)


def test_3d_logits_mean_over_all_leading_dims():
    rng = np.random.default_rng(1)
    logits = jnp.asarray(rng.normal(size=(4, 16, 256)), jnp.float32)
    labels = jnp.asarray(rng.integers(0, 256, size=(4, 16)), jnp.int32)
    out = fused_cross_entropy(logits, labels)
    np.testing.assert_allclose(np.asarray(out), np.asarray(_ref(
        logits, labels)), atol=1e-5, rtol=1e-5)


def test_never_materializes_f32_logits():
    """The headline memory claim: with bf16 logits no f32 [N, vocab]
    intermediate exists anywhere in the traced forward+backward — the
    optax path creates two (the cast + the log-softmax)."""
    logits, labels = _data(dtype=jnp.bfloat16)
    n, v = logits.shape

    def has_f32_nv(fn):
        jaxpr = jax.make_jaxpr(fn)(logits)
        found = []

        def walk(jp):
            for eqn in jp.eqns:
                for var in eqn.outvars:
                    aval = getattr(var, "aval", None)
                    if aval is not None and tuple(aval.shape) == (n, v) \
                            and aval.dtype == jnp.float32:
                        found.append(eqn.primitive.name)
                for val in eqn.params.values():
                    inner = getattr(val, "jaxpr", None)
                    if inner is not None:
                        walk(inner)
        walk(jaxpr.jaxpr)
        return found

    assert not has_f32_nv(
        lambda x: jax.grad(lambda y: fused_cross_entropy(y, labels))(x))
    # the reference path DOES: the assertion above is meaningful
    assert has_f32_nv(lambda x: jax.grad(lambda y: _ref(y, labels))(x))


def test_supported_precheck():
    f32 = jnp.float32
    assert fused_ce_supported((64, 640), f32)
    assert fused_ce_supported((4, 16, 256), jnp.bfloat16)
    assert not fused_ce_supported((64, 130), f32)   # vocab % 128 != 0
    assert not fused_ce_supported((13, 256), f32)   # rows match no block
    assert not fused_ce_supported((64, 640), jnp.int32)
    assert not fused_ce_supported((640,), f32)      # needs >= 2 dims
    with pytest.raises(ValueError):
        fused_cross_entropy(jnp.zeros((64, 130), f32),
                            jnp.zeros((64,), jnp.int32))


def test_use_fused_ce_gate():
    sce = LossType.SPARSE_CATEGORICAL_CROSSENTROPY
    good = jnp.zeros((64, 640), jnp.float32)
    bad = jnp.zeros((64, 130), jnp.float32)
    assert use_fused_ce(sce, good, "auto", enable_fusion=True)
    assert not use_fused_ce(sce, good, "off", enable_fusion=True)
    assert not use_fused_ce(sce, good, "auto", enable_fusion=False)
    assert not use_fused_ce(sce, bad, "auto", enable_fusion=True)
    assert use_fused_ce(sce, good, "on", enable_fusion=False)  # forced
    with pytest.raises(ValueError):
        use_fused_ce(sce, bad, "on")
    with pytest.raises(ValueError):
        use_fused_ce(LossType.MEAN_SQUARED_ERROR, good, "on")
    assert not use_fused_ce(LossType.MEAN_SQUARED_ERROR, good, "auto")


def _fit(devices, fused_loss: str):
    # consecutive builds shift the guid-derived dropout streams: pin them
    Layer._next_guid[0] = 100
    Tensor._next_guid[0] = 1000
    cfg = FFConfig(batch_size=8, mesh_shape={"data": 4, "model": 2},
                   only_data_parallel=False, search_budget=0,
                   fused_loss=fused_loss, seed=3)
    m = FFModel(cfg)
    x = m.create_tensor([8, 32], name="x")
    h = m.dense(x, 64, activation="gelu", name="up")
    m.dense(h, 256, name="head")  # vocab-like: 256 % 128 == 0
    cmod = m.compile(SGDOptimizer(lr=0.05),
                     LossType.SPARSE_CATEGORICAL_CROSSENTROPY, metrics=[])
    cmod.init(seed=0)
    rng = np.random.default_rng(0)
    xs = rng.normal(size=(16, 32)).astype(np.float32)
    ys = rng.integers(0, 256, size=(16,)).astype(np.int32)
    return [h["loss"] for h in cmod.fit([xs], ys, epochs=2, verbose=False)]


def test_e2e_loss_parity_on_sharded_mesh(devices):
    """Acceptance: fused vs reference loss within 1e-5 on the real
    compile path over a 4x2 mesh (the kernel runs under jit+GSPMD with
    sharded logits, interpret mode on CPU)."""
    base = _fit(devices, "off")
    fused = _fit(devices, "on")
    assert np.allclose(base, fused, atol=1e-5, rtol=1e-5)
