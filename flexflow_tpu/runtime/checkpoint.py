"""Checkpoint / resume — full training-state persistence.

Reference gap filled (SURVEY §5d): the reference has NO checkpoint
subsystem — only per-weight numpy get/set (parallel_tensor.h:164-169) and
strategy export. The TPU rebuild keeps those (CompiledModel.get_weight/
set_weight, Strategy.save/load) and adds what the survey prescribes: real
orbax-backed checkpointing of params + optimizer state + non-trainable
state + iteration counter, restored INTO the compiled shardings (orbax
writes per-shard; multi-process runs coordinate through it natively).

Non-blocking saves (copy-then-write): `save_checkpoint(..., block=False)`
copies the trees to host ON THE CALLER THREAD — mandatory for correctness
under donation (donate_state=True consumes the live params/opt_state
buffers at the very next train_step, so a background thread must never
read them) — then hands the host tree to a daemon writer thread that does
the expensive part (orbax serialization, json/npz, fsync). The step loop
only pays for the D2H copy. `wait_pending()` joins writers and re-raises
their errors; `restore_checkpoint` waits for any in-flight write to the
same directory, and saves to a directory with an in-flight write queue
behind it (never two writers interleaving on one path).
"""

from __future__ import annotations

import atexit
import json
import logging
import os
import threading
from typing import Any, Dict, List, Optional

import jax
import numpy as np

from flexflow_tpu import telemetry as tel


def _ckpt_dir(path: str) -> str:
    return os.path.abspath(path)


# ------------------------------------------------------- async write registry
_PENDING: Dict[str, "_AsyncSave"] = {}
_PENDING_LOCK = threading.Lock()
# failed async writes not yet re-raised to a caller: [{"path", "error",
# "handle"}]. result()/wait_pending clears an entry when it REPORTS the
# error; until then failed_writes() keeps it visible (fit-end summary,
# profile_report) so a dropped checkpoint can't go unnoticed.
_FAILED: List[Dict[str, Any]] = []


def failed_writes() -> List[Dict[str, str]]:
    """FAILED async checkpoint writes whose error has not yet been
    re-raised (wait_pending()/result() consume an entry when they report
    it). Surfaced by CompiledModel's fit-end summary and profile_report."""
    with _PENDING_LOCK:
        return [{"path": d["path"], "error": d["error"]} for d in _FAILED]


def warn_failed_writes(verbose: bool) -> None:
    """The fit-end summary warning, shared by CompiledModel and
    PipelinedModel: log (and, verbose, print) any still-unreported failed
    async writes so a dropped checkpoint can't go unnoticed."""
    fw = failed_writes()
    if not fw:
        return
    msg = (f"{len(fw)} async checkpoint write(s) FAILED: "
           + "; ".join(f"{f['path']}: {f['error']}" for f in fw)
           + " — call wait_checkpoints() to re-raise")
    logging.getLogger("flexflow_tpu").warning(msg)
    if verbose:
        print(f"[checkpoint] WARNING: {msg}")


def report_failed_writes() -> List[str]:
    """The profile_report lines for still-unreported failed writes."""
    return [f"[checkpoint] FAILED async write: {f['path']}: {f['error']}"
            for f in failed_writes()]


_EXIT_HOOKED = False


def _wait_pending_at_exit():
    # writer threads are daemons: without this join, a save issued just
    # before interpreter exit would be killed mid-serialize and leave a
    # silently truncated checkpoint directory
    try:
        wait_pending()
    except Exception as e:
        logging.getLogger("flexflow_tpu").error(
            "async checkpoint write failed at exit: %s", e)


def _register_exit_drain():
    """Install the exit drain at FIRST async save. threading._register_atexit
    hooks run LIFO at the start of threading._shutdown — i.e. BEFORE
    concurrent.futures' own hook disables executors — so orbax (which
    schedules futures internally) still works while we join the writer.
    A plain atexit.register would fire too late: by then submit() raises
    'cannot schedule new futures after interpreter shutdown'."""
    global _EXIT_HOOKED
    with _PENDING_LOCK:
        if _EXIT_HOOKED:
            return
        _EXIT_HOOKED = True
    try:
        threading._register_atexit(_wait_pending_at_exit)
    except Exception:  # private API; fall back to best-effort atexit
        atexit.register(_wait_pending_at_exit)


class _AsyncSave:
    """Handle for one background checkpoint write."""

    def __init__(self, path: str):
        self.path = path
        self._exc: Optional[BaseException] = None
        self._thread: Optional[threading.Thread] = None

    def _run(self, write_fn):
        try:
            with tel.span("checkpoint/write", cat="checkpoint",
                          path=self.path):
                write_fn()
            # success: deregister here. A FAILED handle stays in _PENDING
            # until result() reports the error — otherwise a fast-failing
            # write would vanish before wait_pending/restore could see it
            # and the caller would trust a partial checkpoint.
            with _PENDING_LOCK:
                if _PENDING.get(self.path) is self:
                    del _PENDING[self.path]
        except BaseException as e:  # surfaced via result()/wait_pending()
            self._exc = e
            # report the failure THE MOMENT it happens, not only when
            # someone eventually joins: telemetry error event + the
            # failed_writes() registry the fit-end summary reads
            with _PENDING_LOCK:
                _FAILED.append({"path": self.path, "error": repr(e),
                                "handle": self})
            tel.error("checkpoint/write_failed", path=self.path,
                      error=repr(e))
            logging.getLogger("flexflow_tpu").error(
                "async checkpoint write to %s failed: %s", self.path, e)

    def start(self, write_fn):
        self._thread = threading.Thread(
            target=self._run, args=(write_fn,), daemon=True,
            name=f"ff-ckpt-write:{os.path.basename(self.path)}")
        self._thread.start()

    def result(self, timeout: Optional[float] = None) -> str:
        assert self._thread is not None
        self._thread.join(timeout)
        if self._thread.is_alive():
            raise TimeoutError(f"checkpoint write to {self.path} still "
                               f"running after {timeout}s")
        # report the outcome exactly once, then deregister (so one failed
        # save can't wedge every later save/wait on the same path)
        with _PENDING_LOCK:
            if _PENDING.get(self.path) is self:
                del _PENDING[self.path]
        if self._exc is not None:
            with _PENDING_LOCK:  # error reported here: clear the registry
                _FAILED[:] = [d for d in _FAILED
                              if d.get("handle") is not self]
            raise self._exc
        return self.path


def wait_pending(path: Optional[str] = None) -> None:
    """Join in-flight async checkpoint writes (all, or just `path`'s),
    re-raising the first write error."""
    with _PENDING_LOCK:
        if path is None:
            handles: List[_AsyncSave] = list(_PENDING.values())
        else:
            h = _PENDING.get(_ckpt_dir(path))
            handles = [h] if h is not None else []
    if not handles:
        return
    with tel.span("checkpoint/drain", cat="checkpoint",
                  pending=len(handles)):
        for h in handles:
            h.result()


# ------------------------------------------------------------------ save/load
def _write_tree(ckptr, path: str, tree: Dict[str, Any], meta: Dict[str, Any],
                state: Dict[str, np.ndarray]) -> None:
    """The expensive half of a save: orbax serialization + metadata files.
    Runs on the caller thread (block=True) or the writer thread. `ckptr`
    must be constructed on the CALLER thread — orbax registers atexit
    hooks at import/construction, which raises if the writer thread is
    draining during interpreter shutdown (the _wait_pending_at_exit path)."""
    ckptr.save(os.path.join(path, "tree"), tree, force=True)
    ckptr.wait_until_finished()
    # small host-side metadata travels as json (numpy state arrays included)
    if jax.process_index() == 0:
        with open(os.path.join(path, "meta.json"), "w") as f:
            json.dump(meta, f)
        if state:
            np.savez(os.path.join(path, "state.npz"), **state)


def save_checkpoint(cm, path: str, block: bool = True) -> str:
    """Persist a CompiledModel's full training state (params, optimizer
    state, BN/running state, iteration, strategy) under `path`.

    block=False (cfg.async_checkpoint through CompiledModel.save_checkpoint)
    returns as soon as the state is snapshot to host; the write happens on
    a background thread. Multi-process runs always write synchronously —
    the per-process shards aren't host-gatherable, and orbax coordinates
    the processes itself."""
    import orbax.checkpoint as ocp

    path = _ckpt_dir(path)
    wait_pending(path)  # never interleave two writers on one directory
    meta = {
        "iteration": int(cm._iteration),
        "state_keys": sorted(cm.state),
        "strategy": cm.strategy.to_json(),
        # the mesh the (possibly ZeRO-sharded) opt state was laid out on:
        # restore logs a re-shard when the restoring mesh differs (orbax
        # stores GLOBAL arrays, so the re-shard is just a different slicing)
        "mesh_axes": dict(cm.machine.mesh_axes),
        "zero_sharding": getattr(cm.cfg, "zero_sharding", "off"),
    }
    state = {k: np.asarray(v) for k, v in cm.state.items()}
    tree = {"params": cm.params, "opt_state": cm.opt_state}
    ckptr = ocp.StandardCheckpointer()  # caller thread: see _write_tree
    if block or jax.process_count() > 1:
        with tel.span("checkpoint/write", cat="checkpoint", path=path,
                      blocking=True):
            _write_tree(ckptr, path, tree, meta, state)
        return path
    # copy-then-write: D2H snapshot here (donation-safe — the live buffers
    # may be consumed by the next train_step), serialization off-thread
    with tel.span("checkpoint/snapshot", cat="checkpoint", path=path):
        host_tree = jax.tree_util.tree_map(np.asarray, tree)
    _register_exit_drain()
    handle = _AsyncSave(path)
    with _PENDING_LOCK:
        _PENDING[path] = handle
    handle.start(lambda: _write_tree(ckptr, path, host_tree, meta, state))
    return path


def save_pipeline_checkpoint(pm, path: str, block: bool = True) -> str:
    """Checkpoint a PipelinedModel (parallel/pipeline.py): params are saved
    as ONE logical tree keyed by layer name (stage ownership is a placement
    detail, not a schema detail), optimizer state per stage. Restoring onto
    a different stage-internal mesh (e.g. data=4 -> data=2 per stage) is
    the same global-array re-shard the flat path does; the stage COUNT must
    match (the per-stage optax state trees key on it)."""
    import orbax.checkpoint as ocp

    path = _ckpt_dir(path)
    wait_pending(path)
    meta = {
        "iteration": int(pm._iteration),
        "strategy": pm.strategy.to_json(),
        "mesh_axes": dict(pm.stage_machine.mesh_axes),
        "pipeline": {"stages": pm.num_stages, "schedule": pm.schedule,
                     "cuts": list(pm.cuts)},
        "zero_sharding": getattr(pm.cfg, "zero_sharding", "off"),
    }
    tree = {"params": pm.merged_params(),
            "opt_state": {f"stage{s}": pm.stage_opt[s]
                          for s in range(pm.num_stages)}}
    # non-trainable state merges like params: keys are "{layer.name}/..."
    # so restore re-derives stage ownership from the layer-name prefix
    state = {k: np.asarray(v) for d in pm.stage_state for k, v in d.items()}
    ckptr = ocp.StandardCheckpointer()
    if block or jax.process_count() > 1:
        with tel.span("checkpoint/write", cat="checkpoint", path=path,
                      blocking=True):
            _write_tree(ckptr, path, tree, meta, state)
        return path
    with tel.span("checkpoint/snapshot", cat="checkpoint", path=path):
        host_tree = jax.tree_util.tree_map(np.asarray, tree)
    _register_exit_drain()
    handle = _AsyncSave(path)
    with _PENDING_LOCK:
        _PENDING[path] = handle
    handle.start(lambda: _write_tree(ckptr, path, host_tree, meta, state))
    return path


def restore_pipeline_checkpoint(pm, path: str) -> None:
    """Restore a pipeline checkpoint into a PipelinedModel built from the
    same model graph, stage count and cuts. Each param lands on the stage
    owning its layer, in the restoring stage-mesh's sharding — so a
    checkpoint saved under {data: 4} stages restores onto {data: 2} stages
    (cross-mesh re-shard of stage-sharded state). The cuts must match: the
    per-stage optax state trees embed the stage's layer partition."""
    import orbax.checkpoint as ocp
    from jax.sharding import NamedSharding, PartitionSpec

    path = _ckpt_dir(path)
    wait_pending(path)
    if pm.stage_params[0] is None:
        pm.init()
    with open(os.path.join(path, "meta.json")) as f:
        meta = json.load(f)
    saved = meta.get("pipeline", {})
    if saved.get("stages") != pm.num_stages:
        raise ValueError(
            f"checkpoint has {saved.get('stages')} pipeline stages, model "
            f"has {pm.num_stages}: per-stage optimizer state cannot be "
            "re-keyed across stage counts")
    if sorted(saved.get("cuts", [])) != sorted(pm.cuts):
        raise ValueError(
            f"checkpoint stage cuts {saved.get('cuts')} != model cuts "
            f"{list(pm.cuts)}: the per-stage optax state trees embed the "
            "stage's layer partition (orbax would fail on the structure "
            "mismatch anyway — failing cleanly here)")
    if dict(meta.get("mesh_axes", {})) != dict(pm.stage_machine.mesh_axes):
        logging.getLogger("flexflow_tpu").info(
            "pipeline checkpoint %s saved on stage mesh %s, restoring "
            "onto %s (re-shard)", path, meta.get("mesh_axes"),
            dict(pm.stage_machine.mesh_axes))
    ckptr = ocp.StandardCheckpointer()
    target = {"params": pm.merged_params(),
              "opt_state": {f"stage{s}": pm.stage_opt[s]
                            for s in range(pm.num_stages)}}
    restored = ckptr.restore(os.path.join(path, "tree"), target)

    def _placed(r, t, mesh):
        sh = getattr(t, "sharding", None)
        if isinstance(sh, NamedSharding):
            return jax.device_put(r, sh)
        return jax.device_put(r, NamedSharding(mesh, PartitionSpec()))

    for s in range(pm.num_stages):
        live = pm.stage_params[s]
        pm.stage_params[s] = jax.tree_util.tree_map(
            lambda r, t, _m=pm.stage_meshes[s]: _placed(r, t, _m),
            {ln: restored["params"][ln] for ln in live}, live)
        pm.stage_opt[s] = jax.tree_util.tree_map(
            lambda r, t, _m=pm.stage_meshes[s]: _placed(r, t, _m),
            restored["opt_state"][f"stage{s}"], pm.stage_opt[s])
    pm._iteration = int(meta.get("iteration", 0))
    state_file = os.path.join(path, "state.npz")
    if os.path.exists(state_file):
        import jax.numpy as jnp

        loaded = np.load(state_file)
        owner = {l.name: s for s in range(pm.num_stages)
                 for l in pm.stage_layers[s]}
        for s in range(pm.num_stages):
            pm.stage_state[s] = {}
        for k in loaded.files:
            s = owner.get(k.rsplit("/", 1)[0])
            if s is not None:
                pm.stage_state[s][k] = jnp.asarray(loaded[k])


def restore_checkpoint(cm, path: str) -> None:
    """Restore `save_checkpoint` output into a CompiledModel built from the
    same model graph. Arrays land directly in the compiled shardings (the
    live params/opt_state trees are the restore targets); the iteration
    counter resumes, so LR schedules and recompile triggers continue.
    Joins any in-flight async write to `path` first."""
    import orbax.checkpoint as ocp

    path = _ckpt_dir(path)
    wait_pending(path)
    if cm.params is None:
        cm.init()
    with open(os.path.join(path, "meta.json")) as f:
        meta = json.load(f)
    saved_mesh = meta.get("mesh_axes")
    if saved_mesh and dict(saved_mesh) != dict(cm.machine.mesh_axes):
        # mesh changed between save and restore (e.g. ZeRO moments saved
        # under data=4 restored under data=2): the checkpoint holds GLOBAL
        # arrays, and the live target trees below carry the NEW mesh's
        # shardings, so orbax re-shards on read — values are unchanged,
        # only the per-device slicing moves
        logging.getLogger("flexflow_tpu").info(
            "checkpoint %s saved on mesh %s, restoring onto %s (re-shard)",
            path, dict(saved_mesh), dict(cm.machine.mesh_axes))
    ckptr = ocp.StandardCheckpointer()
    target = {"params": cm.params, "opt_state": cm.opt_state}
    restored = ckptr.restore(os.path.join(path, "tree"), target)

    # land every leaf in the LIVE tree's sharding; leaves whose live sharding
    # is single-device (optimizer scalars from tx.init) are replicated over
    # the mesh — orbax restores them committed to one device, which would
    # clash with the mesh-wide arrays at the next train_step
    from jax.sharding import NamedSharding, PartitionSpec

    def _placed(r, t):
        sh = getattr(t, "sharding", None)
        if isinstance(sh, NamedSharding):
            return jax.device_put(r, sh)
        return jax.device_put(r, NamedSharding(cm.mesh, PartitionSpec()))

    cm.params = jax.tree_util.tree_map(_placed, restored["params"], cm.params)
    cm.opt_state = jax.tree_util.tree_map(_placed, restored["opt_state"],
                                          cm.opt_state)
    cm._iteration = int(meta.get("iteration", 0))
    state_file = os.path.join(path, "state.npz")
    if os.path.exists(state_file):
        import jax.numpy as jnp

        loaded = np.load(state_file)
        cm.state = {k: jnp.asarray(loaded[k]) for k in loaded.files}
