"""Checkpoint/resume (SURVEY §5d — the rebuild's improvement over the
reference's get/set-weight-only persistence): full state round-trips across
fresh CompiledModel instances, training resumes bit-exactly, and sharded
weights restore into their shardings."""

import numpy as np
import pytest

from flexflow_tpu import FFConfig, FFModel, AdamOptimizer


def _build(tmpdir_seed=0):
    cfg = FFConfig(batch_size=16, mesh_shape={"data": 4, "model": 2},
                   only_data_parallel=True, seed=5)
    m = FFModel(cfg)
    x = m.create_tensor([16, 32], name="x")
    h = m.dense(x, 64, activation="relu", name="fc1")
    h = m.batch_norm(m.reshape(h, [16, 64, 1, 1]), relu=False, name="bn")
    h = m.flat(h, name="fl")
    m.dense(h, 4, name="head")
    cm = m.compile(AdamOptimizer(alpha=0.01),
                   loss_type="sparse_categorical_crossentropy", metrics=[])
    return m, cm


def _data():
    rng = np.random.default_rng(0)
    x = rng.normal(size=(64, 32)).astype(np.float32)
    y = rng.integers(0, 4, size=(64,)).astype(np.int32)
    return x, y


def test_checkpoint_roundtrip_and_exact_resume(devices, tmp_path):
    x, y = _data()
    m1, cm1 = _build()
    cm1.init(seed=0)
    cm1.fit(x, y, epochs=1, verbose=False)  # 4 steps; BN state populated
    assert cm1.state, "batch_norm should have produced running stats"
    ck = str(tmp_path / "ck")
    cm1.save_checkpoint(ck)
    fc1_at_ck = np.asarray(cm1.get_weight("fc1"))
    # continue the original for 1 more epoch -> the reference trajectory
    h_ref = cm1.fit(x, y, epochs=1, verbose=False)

    # fresh process-state: new model, restore, resume
    m2, cm2 = _build()
    cm2.init(seed=123)  # different init — must be overwritten by restore
    cm2.load_checkpoint(ck)
    assert cm2._iteration == 4
    np.testing.assert_array_equal(np.asarray(cm2.get_weight("fc1")), fc1_at_ck)
    h_res = cm2.fit(x, y, epochs=1, verbose=False)
    # same data order (same seed + iteration) -> bit-identical trajectory
    assert h_res[0]["loss"] == pytest.approx(h_ref[0]["loss"], rel=1e-6), \
        (h_res[0]["loss"], h_ref[0]["loss"])
    np.testing.assert_allclose(np.asarray(cm2.get_weight("head")),
                               np.asarray(cm1.get_weight("head")), rtol=1e-6)


def test_async_checkpoint_nonblocking_and_correct(devices, tmp_path):
    """Non-blocking save (copy-then-write thread): the snapshot is taken at
    call time, training continues immediately — INCLUDING donating steps
    that consume the live buffers — and the restore sees exactly the
    state at the save point. restore waits for the in-flight write."""
    x, y = _data()
    m1, cm1 = _build()
    cm1.init(seed=0)
    cm1.fit(x, y, epochs=1, verbose=False)
    w_at_save = np.asarray(cm1.get_weight("fc1"))
    ck = cm1.save_checkpoint(str(tmp_path / "ck_async"), block=False)
    # keep training while the writer thread persists the snapshot: the
    # params the save captured must not be perturbed by these steps
    cm1.fit(x, y, epochs=1, verbose=False)
    assert not np.array_equal(np.asarray(cm1.get_weight("fc1")), w_at_save)
    cm1.wait_checkpoints()  # joins + re-raises writer errors

    m2, cm2 = _build()
    cm2.init(seed=123)
    cm2.load_checkpoint(ck)
    assert cm2._iteration == 4
    np.testing.assert_array_equal(np.asarray(cm2.get_weight("fc1")),
                                  w_at_save)


def test_async_checkpoint_drains_at_interpreter_exit(tmp_path):
    """A save issued right before process exit must still land: the exit
    drain (threading._register_atexit, runs before concurrent.futures
    disables executors) joins the writer thread instead of letting the
    daemon die mid-serialize."""
    import subprocess
    import sys

    code = f"""
import os
os.environ["XLA_FLAGS"] = os.environ.get("XLA_FLAGS","") + " --xla_force_host_platform_device_count=8"
import jax; jax.config.update("jax_platforms", "cpu")
import numpy as np
from flexflow_tpu import FFModel, FFConfig, SGDOptimizer
m = FFModel(FFConfig(batch_size=16, only_data_parallel=True))
t = m.create_tensor([16, 8], name="x")
m.dense(t, 4, name="fc")
cm = m.compile(SGDOptimizer(lr=0.01), "sparse_categorical_crossentropy", [])
cm.init(seed=0)
cm.save_checkpoint({str(tmp_path / "ck")!r})  # async; exit immediately
"""
    r = subprocess.run([sys.executable, "-c", code], cwd="/root/repo",
                       capture_output=True, text=True, timeout=300)
    assert r.returncode == 0, r.stderr[-3000:]
    assert (tmp_path / "ck" / "meta.json").exists(), r.stderr[-3000:]


def test_checkpoint_restores_into_shardings(devices, tmp_path):
    from flexflow_tpu.parallel.templates import apply_tensor_parallel_linear_pair

    cfg = FFConfig(batch_size=16, mesh_shape={"data": 4, "model": 2},
                   only_data_parallel=True)
    m = FFModel(cfg)
    x = m.create_tensor([16, 64], name="x")
    h = m.dense(x, 256, activation="gelu", name="up")
    m.dense(h, 64, name="down")
    cm = m.compile(AdamOptimizer(alpha=0.01), loss_type="mean_squared_error",
                   metrics=[])
    apply_tensor_parallel_linear_pair(cm.strategy, m.get_layer_by_name("up"),
                                      m.get_layer_by_name("down"), "model")
    cm._build_steps()
    cm.init(seed=0)
    before = np.asarray(cm.get_weight("up"))
    ck = str(tmp_path / "ck")
    cm.save_checkpoint(ck)

    m2 = FFModel(cfg)
    x2 = m2.create_tensor([16, 64], name="x")
    h2 = m2.dense(x2, 256, activation="gelu", name="up")
    m2.dense(h2, 64, name="down")
    cm2 = m2.compile(AdamOptimizer(alpha=0.01), loss_type="mean_squared_error",
                     metrics=[])
    apply_tensor_parallel_linear_pair(cm2.strategy, m2.get_layer_by_name("up"),
                                      m2.get_layer_by_name("down"), "model")
    cm2._build_steps()
    cm2.init(seed=9)
    cm2.load_checkpoint(ck)
    np.testing.assert_array_equal(np.asarray(cm2.get_weight("up")), before)
    # restored INTO the tensor-parallel sharding, not gathered
    k = cm2.params["up"]["kernel"]
    shard = next(iter(k.addressable_shards)).data.shape
    assert shard[1] == k.shape[1] // 2, (shard, k.shape)
