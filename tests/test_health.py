"""Run health (ISSUE 9 — flexflow_tpu/health.py): goodput wall-clock
bucket accounting on both fit loops (buckets + explicit residual tile the
measured wall), numerics sentinels (device-resident finite checks with
zero extra host syncs, fault-injected NaN → telemetry → halt with a
durable recovery checkpoint whose resume reproduces the clean
trajectory), HBM watermarks vs the memory model's prediction, size-based
telemetry rotation read transparently by every reader, the pipelined
loop's session-only resume windows, and the monitor / bench_goodput CI
smokes."""

import os
import sys
import time

import numpy as np
import pytest

from flexflow_tpu import AdamOptimizer, FFConfig, FFModel, SGDOptimizer
from flexflow_tpu import health
from flexflow_tpu import telemetry as tel
from flexflow_tpu.losses import LossType
from flexflow_tpu.runtime import faults
from flexflow_tpu.runtime import resilience as rz

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "tools"))


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.clear()
    yield
    faults.clear()


def _build(seed=5, **cfg_kw):
    cfg = FFConfig(batch_size=16, only_data_parallel=True, seed=seed,
                   log_level="warning", mesh_shape={"data": 4, "model": 2},
                   **cfg_kw)
    m = FFModel(cfg)
    x = m.create_tensor([16, 32], name="x")
    h = m.dense(x, 64, activation="relu", name="fc1")
    m.dense(h, 4, name="head")
    cm = m.compile(AdamOptimizer(alpha=0.01),
                   loss_type="sparse_categorical_crossentropy", metrics=[])
    cm.init(seed=0)
    return cm


def _data(n=96):
    rng = np.random.default_rng(0)
    x = rng.normal(size=(n, 32)).astype(np.float32)
    y = rng.integers(0, 4, size=(n,)).astype(np.int32)
    return x, y


def _losses(hist):
    return [h["loss"] for h in hist]


# ------------------------------------------------------------ goodput meter
def test_goodput_meter_buckets_residual_and_bubble():
    """Pure accounting: add()ed buckets + the explicit residual tile the
    wall; goodput counts the productive buckets minus the bubble
    carve-out (derived from the dispatch bucket)."""
    gm = health.GoodputMeter()
    gm.add("dispatch", 0.8)
    gm.add("checkpoint", 0.1)
    rec = gm.epoch_end(1.0, epoch=0, bubble_frac=0.25)
    assert rec["buckets"]["dispatch"] == pytest.approx(0.8)
    assert rec["bubble_s"] == pytest.approx(0.2)  # 0.25 * dispatch
    assert rec["residual_s"] == pytest.approx(0.1)  # 1.0 - 0.9 accounted
    assert rec["accounted_frac"] == pytest.approx(0.9)
    assert rec["goodput"] == pytest.approx(0.6)  # (0.8 - 0.2) / 1.0
    # the lap cursor: intervals between laps land in the named bucket
    gm.tick()
    time.sleep(0.01)
    gm.lap("dispatch")
    rec2 = gm.epoch_end(0.05, epoch=1)
    assert rec2["buckets"]["dispatch"] >= 0.009
    assert rec2["buckets"]["checkpoint"] == 0.0  # reset between epochs
    rep = gm.report()
    assert rep["epochs"] == 2
    lines = health.format_goodput(rep)
    assert lines[0].startswith("[goodput]") and "residual" in lines[0]
    assert health.format_goodput({})[0].startswith("[goodput] no closed")


def test_goodput_accounts_fit_wall(devices):
    """The acceptance bar on the flat loop: buckets account for >= 95% of
    the measured epoch wall, the residual is explicit, and goodput lands
    in history + the fit-level report."""
    cm = _build()
    x, y = _data()
    hist = cm.fit(x, y, epochs=2, verbose=False)
    assert all("goodput" in h for h in hist)
    assert all(0.0 <= h["goodput"] <= 1.0 for h in hist)
    rep = cm.goodput_report()
    assert rep["epochs"] == 2
    assert rep["accounted_frac"] >= 0.95
    wall = sum(h["epoch_time_s"] for h in hist)
    assert rep["wall_s"] == pytest.approx(wall, rel=1e-6)
    assert sum(rep["buckets"].values()) + rep["residual_s"] >= 0.95 * wall
    assert rep["buckets"]["dispatch"] > 0.0


def test_goodput_drops_under_heavy_checkpointing(devices, tmp_path):
    """--checkpoint-every-steps 1 forces a durable snapshot per step; the
    lost time must land in the checkpoint bucket (not vanish into
    residual) and lower goodput vs the unperturbed twin."""
    x, y = _data()
    cm0 = _build()
    cm0.fit(x, y, epochs=2, verbose=False)
    base = cm0.goodput_report()
    cm1 = _build(checkpoint_dir=str(tmp_path / "ck"))
    cm1.fit(x, y, epochs=2, verbose=False, checkpoint_every_steps=1)
    heavy = cm1.goodput_report()
    assert heavy["buckets"]["checkpoint"] > 0.0
    assert base["buckets"]["checkpoint"] == pytest.approx(0.0)
    assert heavy["goodput"] < base["goodput"]
    assert heavy["accounted_frac"] >= 0.95


# ---------------------------------------------------------------- sentinels
def test_sentinel_state_detectors():
    """Pure host-side detectors: grad-norm spike vs the EMA, loss spike
    vs the previous window, NaN/Inf fatal."""
    st = health.SentinelState()
    assert st.observe(1, loss_mean=1.0, grad_norm=1.0) is None
    assert st.observe(2, loss_mean=1.1, grad_norm=50.0) is None  # warn only
    assert [e["kind"] for e in st.events] == ["grad_spike"]
    st2 = health.SentinelState()
    st2.observe(1, loss_mean=1.0, grad_norm=1.0)
    st2.observe(2, loss_mean=100.0, grad_norm=1.0)
    assert [e["kind"] for e in st2.events] == ["loss_spike"]
    st3 = health.SentinelState()
    assert st3.observe(3, loss_mean=float("nan"), grad_norm=1.0,
                       nonfinite=1.0) == "nonfinite"
    assert st3.observe(4, loss_mean=1.0,
                       grad_norm=float("nan")) == "nonfinite"
    s = st3.status()
    assert s["nonfinite_steps"] == 2 and s["grad_spikes"] == 0


def test_sentinel_metrics_device_flags(devices):
    import jax.numpy as jnp

    m = health.sentinel_metrics(jnp.float32(1.5), jnp.float32(2.0))
    assert float(m[health.NONFINITE_KEY]) == 0.0
    assert float(m[health.GRAD_NORM_KEY]) == pytest.approx(2.0)
    m2 = health.sentinel_metrics(jnp.float32(np.nan), jnp.float32(2.0))
    assert float(m2[health.NONFINITE_KEY]) == 1.0
    m3 = health.sentinel_metrics(jnp.float32(1.0), jnp.float32(np.inf))
    assert float(m3[health.NONFINITE_KEY]) == 1.0


def test_sentinels_on_keep_baseline_counters(devices):
    """Healthy-path overhead bar: with sentinels ON at the default
    sync_every the loop performs exactly the PR-2 baseline dispatch /
    host-sync counts (test_telemetry pins the same numbers), the
    reserved health/* keys never leak into user-facing history, and the
    loss trajectory matches a sentinels-OFF run."""
    def fit(**kw):
        cfg = FFConfig(batch_size=32, only_data_parallel=True,
                       log_level="warning", **kw)
        m = FFModel(cfg)
        x = m.create_tensor([32, 16], name="x")
        h = m.dense(x, 32, activation="relu", name="fc1")
        m.dense(h, 4, name="fc2")
        cm = m.compile(SGDOptimizer(lr=0.05),
                       LossType.SPARSE_CATEGORICAL_CROSSENTROPY,
                       metrics=[])
        cm.init(seed=0)
        rng = np.random.default_rng(0)
        xs = rng.normal(size=(256, 16)).astype(np.float32)
        ys = rng.integers(0, 4, size=(256,)).astype(np.int32)
        return cm, cm.fit(xs, ys, epochs=2, verbose=False)

    cm_on, h_on = fit()  # health_sentinels defaults ON
    assert cm_on.cfg.health_sentinels is True
    assert cm_on.step_stats == {"dispatches": 16, "host_syncs": 0,
                                "barriers": 0, "fused_steps": 0}
    assert not any(k.startswith("health/") for e in h_on for k in e)
    assert cm_on._sentinels is not None
    assert cm_on._sentinels.state.status()["nonfinite_steps"] == 0
    cm_off, h_off = fit(health_sentinels=False)
    assert cm_off.step_stats == cm_on.step_stats
    assert cm_off._sentinels is None
    for eo, en in zip(h_off, h_on):
        assert en["loss"] == pytest.approx(eo["loss"], rel=1e-6)


def test_nan_inject_halts_with_durable_checkpoint_and_resumes(
        devices, tmp_path):
    """The ISSUE 9 acceptance path end-to-end: a fault-plan NaN poison
    (health/nonfinite site) trips the sentinel at the next sync, emits
    the health/nonfinite + health/halt telemetry events, and — under
    halt_on_nonfinite — raises NonFiniteError through the drain carrying
    the last DURABLE (pre-fault) checkpoint; resuming from it reproduces
    the uninterrupted run's loss trajectory."""
    x, y = _data(96)  # 6 steps/epoch
    ref = _losses(_build().fit(x, y, epochs=2, verbose=False))

    root = str(tmp_path / "ck")
    tdir = str(tmp_path / "tel")
    try:
        tel.configure(tdir)
        faults.configure("health/nonfinite@3")
        cm = _build(checkpoint_dir=root, halt_on_nonfinite=True)
        with pytest.raises(health.NonFiniteError) as ei:
            # sync_every=1: the sentinel window closes every step, so the
            # poison at step 3 halts before the step-4 durable snapshot
            # could capture NaN params (checkpoints land at steps 2,4,..)
            cm.fit(x, y, epochs=2, verbose=False, sync_every=1,
                   checkpoint_every_steps=2)
        assert ei.value.step == 3
        assert ei.value.checkpoint  # a durable recovery point exists
        assert ei.value.checkpoint == rz.latest_checkpoint(root)
        man = rz.load_manifest(ei.value.checkpoint)
        assert man["progress"]["epoch"] == 0
        assert man["progress"]["step_in_epoch"] == 2  # pre-fault
        tel.flush()
        evs = tel.read_events(tdir)
        names = [e["name"] for e in evs]
        assert "fault/injected" in names
        nf = [e for e in evs if e["name"] == "health/nonfinite"]
        assert nf and nf[0]["cat"] == "error"
        halt = [e for e in evs if e["name"] == "health/halt"]
        assert halt and halt[0]["args"]["checkpoint"] == ei.value.checkpoint
    finally:
        tel.shutdown()

    faults.clear()
    cm2 = _build(checkpoint_dir=root)
    h2 = cm2.fit(x, y, epochs=2, verbose=False, resume="auto")
    np.testing.assert_allclose(_losses(h2), ref, rtol=1e-6)


# ----------------------------------------------------------------- pipeline
def _pipe_build(**cfg_kw):
    cfg = FFConfig(batch_size=8, only_data_parallel=True, seed=3,
                   pipeline_stages=2, pipeline_schedule="1f1b",
                   accum_steps=2, log_level="warning", **cfg_kw)
    m = FFModel(cfg)
    t = m.create_tensor([8, 64], name="x")
    h = m.dense(t, 256, activation="gelu", name="up")
    h = m.dense(h, 64, name="down")
    h = m.dense(h, 128, activation="relu", name="mid")
    m.dense(h, 8, name="head")
    cm = m.compile(AdamOptimizer(alpha=0.01),
                   LossType.SPARSE_CATEGORICAL_CROSSENTROPY, metrics=[])
    cm.init(seed=0)
    return cm


def _pipe_data(n=96):
    rng = np.random.default_rng(0)
    x = rng.normal(size=(n, 64)).astype(np.float32)
    y = rng.integers(0, 8, size=(n,)).astype(np.int32)
    return x, y


def test_pipeline_goodput_sentinels_and_watermarks(devices):
    """The same health surface on the pipelined executor: goodput in
    history + >= 95% accounting, a clean sentinel state (per-stage
    grad-norm-sq accumulators checked at epoch end), and a watermark
    sample per epoch boundary with no under-prediction warning."""
    cm = _pipe_build()
    x, y = _pipe_data()
    hist = cm.fit([x], y, epochs=2, verbose=False)
    assert all("goodput" in h for h in hist)
    rep = cm.goodput_report()
    assert rep["epochs"] == 2 and rep["accounted_frac"] >= 0.95
    hr = cm.health_report()
    assert hr["sentinels"]["nonfinite_steps"] == 0
    assert hr["sentinels"]["grad_ema"] is not None  # detectors really fed
    wm = hr["watermarks"]
    assert wm["samples"] >= 3  # init + 2 epoch boundaries
    assert wm["ratio"] is not None and not wm["warn"]


def test_pipeline_nan_inject_trips_sentinel(devices, tmp_path):
    """health/nonfinite on the pipelined path: the stage-0 poison
    surfaces as a fatal epoch-end window; with halt_on_nonfinite the fit
    raises through the drain with a durable checkpoint. The sentinel
    window is the EPOCH here, so the fault (update 5) is placed after
    the only due periodic snapshot (update 4, every_steps=4) — that
    checkpoint is deterministically pre-fault and clean."""
    root = str(tmp_path / "ck")
    faults.configure("health/nonfinite@5")
    cm = _pipe_build(halt_on_nonfinite=True, checkpoint_dir=root)
    x, y = _pipe_data()  # 6 updates/epoch
    with pytest.raises(health.NonFiniteError) as ei:
        cm.fit([x], y, epochs=2, verbose=False, checkpoint_every_steps=4)
    assert ei.value.checkpoint  # durable pre-fault recovery point
    man = rz.load_manifest(ei.value.checkpoint)
    assert man["progress"]["epoch"] == 0
    assert man["progress"]["step_in_epoch"] == 4  # pre-fault
    assert cm._sentinel_state.nonfinite_steps == 1


def test_pipeline_resume_windows_count_session_steps_only(
        devices, tmp_path):
    """Satellite (c): on a resumed pipelined run the drift windows and
    samples/sec denominators count only THIS session's updates (the
    re-seeded pre-snapshot steps ran before this wall clock started)."""
    root = str(tmp_path / "ck")
    x, y = _pipe_data(96)  # 6 updates/epoch at batch 8 x M=2
    faults.configure("fit/dispatch@4!")  # permanent: escalates mid-epoch
    cm = _pipe_build(checkpoint_dir=root, retry_base_delay=0.001)
    with pytest.raises(faults.PermanentInjectedFault):
        cm.fit([x], y, epochs=2, verbose=False, checkpoint_every_steps=2)
    from flexflow_tpu.runtime import checkpoint as ck
    ck.wait_pending()  # the update-2 async snapshot commits off-thread
    man = rz.load_manifest(rz.latest_checkpoint(root))
    assert man["progress"] == {**man["progress"], "epoch": 0,
                               "step_in_epoch": 2}

    faults.clear()
    cm2 = _pipe_build(checkpoint_dir=root, retry_base_delay=0.001)
    h2 = cm2.fit([x], y, epochs=2, verbose=False, resume="auto")
    # epoch 0 resumed past 2 of its 6 updates -> 4 session updates;
    # epoch 1 ran in full
    assert [w[0] for w in cm2._drift_windows] == [4, 6]
    e0 = h2[0]
    session_samples = 4 * 2 * 8  # updates x M x batch
    assert e0["samples_per_sec"] == pytest.approx(
        session_samples / e0["epoch_time_s"], rel=1e-6)


# --------------------------------------------------------------- watermarks
def test_watermark_drift_and_tracker(devices):
    d = health.watermark_drift(300, 100)
    assert d["warn"] and d["ratio"] == pytest.approx(3.0)
    assert not health.watermark_drift(120, 100)["warn"]
    assert not health.watermark_drift(None, 100)["warn"]
    assert not health.watermark_drift(100, None)["warn"]

    cm = _build()
    x, y = _data()
    cm.fit(x, y, epochs=2, verbose=False)
    hr = cm.health_report()
    wm = hr["watermarks"]
    assert wm["samples"] >= 3  # init + 2 epoch boundaries
    # CPU fallback measures exactly the persistent trees: prediction in
    # the right ballpark, no drift warning on the honest config
    assert wm["peak_bytes"] and not wm["warn"]
    # an under-predicting memory model must warn (the OOM direction)
    under = cm._watermarks.report(max(1, wm["peak_bytes"] // 4))
    assert under["warn"]
    lines = health.format_health(None, under)
    assert any("WARNING" in ln for ln in lines)
    # and the healthy report renders without warning
    ok_lines = health.format_health(hr["sentinels"], wm)
    assert any(ln.startswith("[health] sentinels") for ln in ok_lines)
    assert not any("WARNING" in ln for ln in ok_lines)


# --------------------------------------------------------- rotation (tele)
def test_telemetry_rotation_and_readers(tmp_path):
    """Satellite (b): a small --telemetry-max-mb cap rotates the sink to
    numbered segments (no renames — concurrent readers never chase a
    moved file) and read_events / trace_report / span_dataset read the
    segment family transparently, ts-sorted."""
    tdir = str(tmp_path / "tele")
    try:
        tel.configure(tdir, max_mb=0.0005)  # ~524-byte segments
        for i in range(200):
            tel.event("rot/ev", cat="test", i=i)
        tel.flush()
        segs = sorted(f for f in os.listdir(tdir)
                      if f.startswith("telemetry-"))
        assert len(segs) > 2  # actually rotated
        assert any(".jsonl" == f[-6:] and f.count(".") == 2 for f in segs)
        evs = [e for e in tel.read_events(tdir) if e["name"] == "rot/ev"]
        assert [e["args"]["i"] for e in evs] == list(range(200))
        import trace_report
        assert len(trace_report.load_events(tdir)) >= 200
    finally:
        tel.shutdown()


def test_telemetry_unbounded_without_cap(tmp_path):
    tdir = str(tmp_path / "tele")
    try:
        tel.configure(tdir)  # no cap
        for i in range(500):
            tel.event("rot/ev", cat="test", i=i)
        tel.flush()
        segs = [f for f in os.listdir(tdir) if f.startswith("telemetry-")]
        assert len(segs) == 1  # never rotates uncapped
    finally:
        tel.shutdown()


# ------------------------------------------------------------- monitor tool
def test_monitor_gather_render_prom(tmp_path):
    """tools/monitor.py unit surface on a synthetic stream: goodput bar,
    sparkline, sentinel status, watermark lines, Prometheus export."""
    import monitor

    events = [
        {"name": "health/goodput", "ph": "i", "ts": 1.0,
         "args": {"epoch": 0, "wall_s": 2.0, "goodput": 0.8,
                  "residual_s": 0.05, "dispatch_s": 1.6,
                  "checkpoint_s": 0.3}},
        {"name": "fit/dispatch", "ph": "X", "ts": 2.0, "dur": 1500.0},
        {"name": "fit/dispatch", "ph": "X", "ts": 3.0, "dur": 2500.0},
        {"name": "health/nonfinite", "ph": "i", "ts": 4.0, "cat": "error",
         "args": {"step": 7, "grad_norm": None, "loss": None}},
        {"name": "health/halt", "ph": "i", "ts": 5.0, "cat": "error",
         "args": {"step": 7, "checkpoint": "/ck/step7"}},
        {"name": "health/hbm", "ph": "i", "ts": 6.0,
         "args": {"tag": "epoch0", "peak_bytes": 4 << 20,
                  "live_bytes": 3 << 20, "devices": 8}},
    ]
    state = monitor.gather(events)
    assert len(state["goodputs"]) == 1
    assert state["steps_ms"] == [1.5, 2.5]
    assert state["sentinels"]["nonfinite"] == 1
    assert len(state["halts"]) == 1 and state["errors"] == 2
    out = "\n".join(monitor.render(state))
    assert "80.0%" in out and "FATAL" in out and "epoch0" in out
    assert "/ck/step7" in out
    assert monitor.sparkline([]) == "(no steps yet)"
    prom = str(tmp_path / "ff.prom")
    monitor.prom_export(state, prom)
    with open(prom) as f:
        txt = f.read()
    assert "flexflow_goodput_ratio 0.8" in txt
    assert "flexflow_nonfinite_windows_total 1" in txt
    assert "flexflow_hbm_peak_bytes" in txt
    assert not os.path.exists(prom + ".tmp")  # atomic rename


def test_monitor_check_smoke(devices, capsys):
    import monitor

    assert monitor.main(["--check"]) == 0
    assert "CHECK PASS" in capsys.readouterr().out


def test_bench_goodput_check_smoke(devices, capsys):
    """tools/bench_goodput.py --check: the goodput acceptance evidence
    (>= 95% accounting, checkpoint-induced goodput drop, loss parity) —
    wired like bench_step/bench_resilience."""
    import bench_goodput

    assert bench_goodput.main(["--check"]) == 0
    assert "CHECK PASS" in capsys.readouterr().out
