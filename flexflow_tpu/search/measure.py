"""Measured per-op costs — the on-device microbenchmark path.

Reference analog: `Op::inner_measure_operator_cost` (src/runtime/model.cu:
38-74): run the op's kernels on a real device with warmup + repeats under
cudaEvent timing, cached by (op params, machine view)
(Simulator::measure_operator_cost, src/runtime/simulator.cc:537-560).

TPU version: jit the op's lowering at **shard-local shapes** for the
candidate's layout on one real chip, block_until_ready-time it, and cache by
(params_key, layout). Forward and backward are timed INDEPENDENTLY (like the
reference's separate fwd/bwd kernel timings): backward is the jitted VJP of
the lowering wrt (weights, float inputs), and its time is the grad-step time
minus the forward time. The known fidelity limit (SURVEY.md §7 hard part #1):
XLA fuses across ops, so isolated measurements over-predict; the analytic
model is the default and this path is opt-in calibration.
"""

from __future__ import annotations

import json
import os
import time
from typing import TYPE_CHECKING, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

if TYPE_CHECKING:
    from flexflow_tpu.core.layer import Layer
    from flexflow_tpu.search.candidates import Candidate

from flexflow_tpu.ops.registry import LoweringCtx, get_op_def
from flexflow_tpu.parallel.machine import MachineSpec
from flexflow_tpu.parallel.ptensor import ParallelTensor
from flexflow_tpu.search import cost_model as cm


def _shard_shape(spec, dims, machine):
    return ParallelTensor.build(spec, list(dims or []), machine).shard_shape


class MeasuredCost:
    def __init__(self, machine: MachineSpec, repeats: int = 5, warmup: int = 2,
                 windows: int = 3, cache_dir: Optional[str] = None):
        self.machine = machine
        self.repeats = repeats
        self.warmup = warmup
        # median-of-windows: each measurement is `windows` independent
        # timed windows of `repeats` runs, reduced by MEDIAN — one window
        # stolen by a concurrent process (the tier-1 test_measure flake)
        # can no longer zero out a bwd = total - fwd difference
        self.windows = max(1, windows)
        self.cache: Dict[Tuple, Tuple[float, float]] = {}
        self._floor: float = -1.0  # lazy: scalar-fetch RTT (tunnel latency)
        # persistent (params_key, layout, machine) -> (fwd, bwd) store (the
        # reference's measure_operator_cost cache made cross-process,
        # simulator.cc:537-560): microbenchmarks are the expensive part of
        # the measured path, so they outlive the process. One file per
        # machine fingerprint; its content hash doubles as the strategy
        # cache's calibration fingerprint (search/strategy_cache.py).
        if cache_dir is None:
            cache_dir = os.environ.get("FF_MEASURE_CACHE_DIR", "")
        self.cache_path: Optional[str] = None
        if cache_dir:
            from flexflow_tpu.search import memo

            self.cache_path = os.path.join(
                os.path.expanduser(cache_dir),
                f"measured-{memo.machine_fingerprint(machine)}.json")
            self._load_disk()

    def _load_disk(self):
        # keys persist as repr() of the in-memory tuple key — enums, shapes
        # and dtypes all repr canonically, so the string is process-stable
        self._disk: Dict[str, list] = {}
        self._dirty: Dict[str, list] = {}  # keys THIS process measured
        self._disk_mtime = 0.0
        try:
            with open(self.cache_path) as f:
                self._disk = dict(json.load(f))
            self._disk_mtime = os.path.getmtime(self.cache_path)
        except (OSError, ValueError):
            pass

    def _persist(self, key, val):
        if not self.cache_path:
            return
        try:
            os.makedirs(os.path.dirname(self.cache_path), exist_ok=True)
            # merge-on-write: overlay ONLY the keys this process measured
            # (the dirty set) onto a re-read of the file, so a concurrent
            # measurer's fresher entries for other keys survive. The mtime
            # gate skips the re-read when nobody else wrote, keeping
            # per-measurement I/O at one O(n) dump.
            self._dirty[repr(key)] = list(val)
            try:
                mtime = os.path.getmtime(self.cache_path)
            except OSError:
                mtime = 0.0
            if mtime != self._disk_mtime:
                try:
                    with open(self.cache_path) as f:
                        current = dict(json.load(f))
                except (OSError, ValueError):
                    current = {}
                current.update(self._dirty)
                self._disk = current
            else:
                self._disk[repr(key)] = list(val)
            tmp = self.cache_path + f".tmp{os.getpid()}"
            with open(tmp, "w") as f:
                json.dump(self._disk, f, indent=0, sort_keys=True)
            os.replace(tmp, self.cache_path)
            self._disk_mtime = os.path.getmtime(self.cache_path)
        except OSError:
            self.cache_path = None  # unwritable dir: degrade to in-memory

    def _fetch_floor(self) -> float:
        """The per-window cost of the synchronizing host fetch itself
        (~75 ms through the axon tunnel, ~0 locally) — harness latency, not
        device work; subtracted from every measured window."""
        if self._floor >= 0.0:
            return self._floor
        if jax.default_backend() == "cpu":
            # no tunnel: the fetch is ~free, and subtracting its noise can
            # zero out sub-ms toy measurements
            self._floor = 0.0
            return 0.0
        f = jax.jit(lambda i: i + 1.0)
        self._host_sync(f(jnp.float32(0.0)))
        ts = []
        for i in range(3):
            t0 = time.perf_counter()
            self._host_sync(f(jnp.float32(float(i))))
            ts.append(time.perf_counter() - t0)
        self._floor = float(np.median(ts))
        return self._floor

    def op_times(self, layer: "Layer", cand: "Candidate") -> Tuple[float, float]:
        """(fwd_seconds, bwd_seconds), measured INDEPENDENTLY — the reference
        times forward and backward as separate kernel launches
        (src/runtime/model.cu:38-74); ops whose bwd/fwd ratio is far from 2
        (embedding scatter-add, attention recompute, layernorm) make the old
        bwd≈2×fwd approximation exactly the error measurement exists to fix."""
        key = (layer.params_key(),
               tuple(tuple(map(str, d)) for d in cand.out_dims),
               tuple(sorted((w, tuple(map(str, d))) for w, d in cand.weight_dims.items())))
        if key in self.cache:
            return self.cache[key]
        if self.cache_path:
            hit = self._disk.get(repr(key))
            if hit is not None:
                self.cache[key] = (float(hit[0]), float(hit[1]))
                return self.cache[key]
        try:
            fwd, bwd = self._measure(layer, cand)
            self._persist(key, (fwd, bwd))
        except Exception:
            # fall back to the analytic COMPUTE-ONLY time: cand.op_time
            # includes extra_comm + grad_sync, which op_time() below adds
            # again — subtract them or collective-heavy candidates would be
            # double-charged exactly when measurement fails
            from flexflow_tpu.search.candidates import _batch_axes

            t = cand.op_time(layer, self.machine)
            t -= cand.extra_comm + cm.grad_sync_time(
                layer.weight_specs, cand.weight_dims, self.machine,
                _batch_axes(self.machine))
            t = max(0.0, t)
            fwd, bwd = t / 3.0, 2.0 * t / 3.0
        self.cache[key] = (fwd, bwd)
        return fwd, bwd

    def op_time(self, layer: "Layer", cand: "Candidate") -> float:
        fwd, bwd = self.op_times(layer, cand)
        from flexflow_tpu.search.candidates import _batch_axes

        return fwd + bwd + cand.extra_comm + cm.grad_sync_time(
            layer.weight_specs, cand.weight_dims, self.machine,
            _batch_axes(self.machine))

    def op_time_fwd(self, layer: "Layer", cand: "Candidate") -> float:
        """Forward-pass-only total (serving attribution — ISSUE 14): the
        measured fwd leg plus the candidate's inherent collectives; no
        backward, no grad sync (inference never runs either)."""
        fwd, _bwd = self.op_times(layer, cand)
        return fwd + cand.extra_comm

    @staticmethod
    def _host_sync(out):
        """block_until_ready alone is NOT a reliable barrier under the axon
        TPU tunnel (bench.py round-1 postmortem: async dispatch produced
        physically impossible timings); fetching one element to the host
        provably waits for the dependent chain. The device executes a single
        stream, so waiting on the LAST call covers all queued repeats.
        Fetch ONE SCALAR, never the full array — device_get of a production
        weight gradient (~200 MB) costs seconds through the tunnel."""
        jax.block_until_ready(out)
        leaf = jax.tree_util.tree_leaves(out)[0]
        scalar = leaf if getattr(leaf, "ndim", 0) == 0 \
            else leaf[(0,) * leaf.ndim]
        np.asarray(jax.device_get(scalar))

    def _time(self, fn, *args) -> float:
        """Median over `windows` timed windows of `repeats` dispatches
        each (floor-corrected per window). The shared timing protocol:
        every consumer — the measured search, tools/calibrate.py,
        profile_report — gets the same robustness to a scheduler hiccup
        landing inside one window, instead of a single wall-clock delta
        the hiccup corrupts outright."""
        out = fn(*args)
        self._host_sync(out)
        for _ in range(self.warmup):
            self._host_sync(fn(*args))
        floor = self._fetch_floor()
        ts = []
        for _ in range(self.windows):
            t0 = time.perf_counter()
            for _ in range(self.repeats):
                out = fn(*args)
            self._host_sync(out)
            ts.append(max(0.0, time.perf_counter() - t0 - floor)
                      / self.repeats)
        return float(np.median(ts))

    def _measure(self, layer: "Layer", cand: "Candidate") -> Tuple[float, float]:
        machine = self.machine
        rng = np.random.default_rng(0)
        ins = []
        for i, tin in enumerate(layer.inputs):
            shp = _shard_shape(tin.spec, cand.in_dims[i] if i < len(cand.in_dims) else None, machine)
            dt = tin.spec.dtype.jnp_dtype
            if jnp.issubdtype(dt, jnp.integer):
                ins.append(jnp.asarray(rng.integers(0, 2, size=shp), dt))
            else:
                ins.append(jnp.asarray(rng.normal(size=shp), dt))
        weights = {}
        for w, spec in layer.weight_specs.items():
            shp = _shard_shape(spec, cand.weight_dims.get(w), machine)
            weights[w] = jnp.asarray(rng.normal(size=shp), spec.dtype.jnp_dtype)

        lower = get_op_def(layer.op_type).lower
        fidx = tuple(i for i, a in enumerate(ins)
                     if jnp.issubdtype(a.dtype, jnp.floating))
        fins = [ins[i] for i in fidx]
        iins = [a for i, a in enumerate(ins) if i not in fidx]

        def apply(weights, fins, iins):
            merged, fi, ii = [], iter(fins), iter(iins)
            for i in range(len(ins)):
                merged.append(next(fi) if i in fidx else next(ii))
            ctx = LoweringCtx(training=False, rng=jax.random.PRNGKey(0))
            return lower(layer, merged, weights, ctx)

        run_fwd = jax.jit(apply)
        fwd = self._time(run_fwd, weights, fins, iins)

        # backward: actual VJP of the lowering wrt (weights, float inputs),
        # timed as a separate jit; bwd = grad-step time minus forward time
        def loss_fn(weights, fins, iins):
            outs = apply(weights, fins, iins)
            return sum(jnp.sum(o.astype(jnp.float32)) for o in outs
                       if jnp.issubdtype(o.dtype, jnp.floating))

        out_shapes = jax.eval_shape(apply, weights, fins, iins)
        has_float_out = any(jnp.issubdtype(o.dtype, jnp.floating)
                            for o in out_shapes)
        has_diff = (bool(weights) or bool(fins)) and has_float_out
        if has_diff:
            run_grad = jax.jit(jax.grad(loss_fn, argnums=(0, 1)))
            total = self._time(run_grad, weights, fins, iins)
            bwd = max(0.0, total - fwd)
        else:
            bwd = 0.0
        return fwd, bwd
