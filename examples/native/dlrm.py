"""DLRM with auto-searched embedding sharding (BASELINE config #4;
reference analog: examples/cpp/DLRM/dlrm.cc + shipped strategies).

    python -m flexflow_tpu -b 256 --budget 16 --mesh data=2,model=4 \
        examples/native/dlrm.py
"""

import numpy as np

from flexflow_tpu import FFModel, SGDOptimizer, get_launch_config
from flexflow_tpu.models import build_dlrm


def main():
    cfg = get_launch_config()
    batch = cfg.batch_size
    tables = (100_000,) * 8
    model = FFModel(cfg)
    ins, out = build_dlrm(model, batch=batch, embedding_tables=tables,
                          embedding_dim=64)
    cm = model.compile(SGDOptimizer(lr=cfg.learning_rate),
                       loss_type="mean_squared_error", metrics=[],
                       outputs=[out])
    print("strategy:", cm.strategy.name)
    for ti in range(0, len(tables), 4):
        print(f"  emb_{ti}:", cm.strategy.sharding_for(f"emb_{ti}"))
    rng = np.random.default_rng(0)
    n = batch * 4
    dense = rng.normal(size=(n, 13)).astype(np.float32)
    sparse = [rng.integers(0, t, size=(n, 1)).astype(np.int32) for t in tables]
    labels = rng.uniform(size=(n, 1)).astype(np.float32)
    hist = cm.fit([dense] + sparse, labels, epochs=cfg.epochs, verbose=True)
    print(f"FINAL loss={hist[-1]['loss']:.4f}")


if __name__ == "__main__":
    main()
