"""MLP (reference: examples/cpp/MLP_Unify/mlp.cc, examples/python/native/
mnist_mlp.py)."""

from __future__ import annotations

from typing import Sequence

from flexflow_tpu.core.model import FFModel


def build_mlp(model: FFModel, batch: int, in_dim: int,
              hidden: Sequence[int] = (512, 512), classes: int = 10):
    x = model.create_tensor([batch, in_dim], name="x")
    h = x
    for i, hdim in enumerate(hidden):
        h = model.dense(h, hdim, activation="relu", name=f"mlp_h{i}")
    out = model.dense(h, classes, name="mlp_out")
    return x, out
