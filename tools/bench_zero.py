"""ZeRO-sharded optimizer-state benchmark: replicated vs zero1 memory + speed.

Trains the gpt2 CPU twin (8-virtual-device data-parallel mesh — the
MULTICHIP twin convention) under the two optimizer-state regimes
(compiler/compile.py):

  replicated — zero_sharding=off: Adam moments replicated over the data
               axis (the reference's fully-replicated NCCL regime)
  zero1      — moments sharded over the data axis; the update runs as
               reduce-scatter(grads) -> sharded moment update ->
               all-gather(updates)

and reports, per mode:

  * PREDICTED per-device optimizer-state bytes (the search cost model's
    OptMemSpec accounting, CompiledModel.memory_stats)
  * ACTUAL per-device optimizer-state bytes measured from the live
    buffers (addressable-shard bytes of the opt_state tree on device 0)
  * steps/sec over the post-compile epochs, and the final loss

Identical seeds/data across modes, so final losses must agree to <= 1e-6
(the update arithmetic is elementwise-identical; only the layout moves).
Results print as JSON; --out writes the report (committed as
BENCH_zero.json in the bench trajectory).

  python tools/bench_zero.py                      # gpt2 CPU twin
  python tools/bench_zero.py --model mlp --accum-steps 4
  python tools/bench_zero.py --check              # CI smoke (tiny twin):
      asserts predicted AND actual per-device optimizer-state bytes shrink
      by ~the data-axis degree under zero1, 1e-6 final-loss parity with the
      replicated baseline, and accum_steps=4 equivalence with a 4x batch —
      exits nonzero on regression (tier-1 safe, CPU backend).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                           + " --xla_force_host_platform_device_count=8")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def _build(name: str, batch: int, zero: str, accum: int = 1,
           state_dtype: str = "float32", n_samples: int = 0):
    """Fresh model + synthetic dataset; identical across modes (fixed
    seeds) so loss trajectories are comparable. `n_samples` pins the
    dataset size (the accum-vs-big-batch check needs IDENTICAL data under
    different graph batch sizes)."""
    from flexflow_tpu import AdamOptimizer, FFConfig, FFModel
    from flexflow_tpu.losses import LossType

    cfg = FFConfig(batch_size=batch, only_data_parallel=True, seed=3,
                   zero_sharding=zero, accum_steps=accum,
                   log_level="warning")
    rng = np.random.default_rng(0)
    if name.startswith("gpt2"):
        from flexflow_tpu.models import GPT2Config, build_gpt2

        # CPU twin of gpt2_small (bench_step's convention): same shape
        # family, scaled to the 8-virtual-device CPU mesh. Dropout off so
        # the rng stream can't perturb the loss comparison.
        gc = GPT2Config(vocab=512, seq=16, d_model=64, heads=2, layers=1,
                        dropout=0.0)
        m = FFModel(cfg)
        build_gpt2(m, gc, batch=batch)
        n = n_samples or (16 if name == "gpt2_check" else 48) * batch
        ids = rng.integers(0, gc.vocab, size=(n, gc.seq)).astype(np.int32)
        pos = np.broadcast_to(np.arange(gc.seq, dtype=np.int32),
                              (n, gc.seq)).copy()
        y = rng.integers(0, gc.vocab, size=(n, gc.seq)).astype(np.int32)
        x = [ids, pos]
    elif name == "mlp":
        m = FFModel(cfg)
        t = m.create_tensor([batch, 64], name="x")
        h = m.dense(t, 256, activation="gelu", name="up")
        h = m.dense(h, 64, name="down")
        m.dense(h, 8, name="head")
        n = n_samples or 32 * batch
        x = [rng.normal(size=(n, 64)).astype(np.float32)]
        y = rng.integers(0, 8, size=(n,)).astype(np.int32)
    else:
        raise SystemExit(f"unknown --model {name!r}")
    cm = m.compile(AdamOptimizer(alpha=0.001, state_dtype=state_dtype),
                   LossType.SPARSE_CATEGORICAL_CROSSENTROPY, metrics=[])
    cm.init(seed=0)
    return cm, x, y


def _run_mode(mode: str, model: str, batch: int, epochs: int, accum: int,
              repeats: int = 1, state_dtype: str = "float32",
              n_samples: int = 0):
    """Train a fresh model under one optimizer-state regime; report the
    memory split and steps/sec. Best-of-`repeats` (ambient-load
    robustness; losses/memory identical across repeats — same seeds)."""
    best = None
    for _ in range(max(1, repeats)):
        r = _run_mode_once(mode, model, batch, epochs, accum, state_dtype,
                           n_samples)
        if best is None or r["steps_per_sec"] > best["steps_per_sec"]:
            best = r
    return best


def _run_mode_once(mode, model, batch, epochs, accum, state_dtype,
                   n_samples=0):
    zero = "off" if mode == "replicated" else mode
    cm, x, y = _build(model, batch, zero, accum, state_dtype, n_samples)
    mem0 = cm.memory_stats()  # at init: sharded-from-birth (jitted tx.init)
    t0 = time.perf_counter()
    hist = cm.fit(x, y, epochs=epochs, verbose=False)
    wall = time.perf_counter() - t0
    mem = cm.memory_stats()
    nb = len(y) // (batch * accum)
    timed = hist[1:] if len(hist) > 1 else hist  # epoch 0 = jit compile
    rates = sorted(nb / e["epoch_time_s"] for e in timed if e["epoch_time_s"])
    sps = rates[len(rates) // 2] if rates else 0.0
    return {
        "mode": mode,
        "zero_sharding": zero,
        "accum_steps": accum,
        "steps_per_sec": round(sps, 2),
        "samples_per_sec": round(batch * accum * sps, 1),
        "final_loss": hist[-1]["loss"],
        "updates_per_epoch": nb,
        "wallclock_s": round(wall, 3),
        "data_axis_degree": mem["data_axis_degree"],
        "predicted_opt_state_bytes": mem["predicted_opt_state_bytes"],
        "actual_opt_state_bytes_per_device":
            mem["actual_opt_state_bytes_per_device"],
        "actual_opt_state_bytes_at_init":
            mem0["actual_opt_state_bytes_per_device"],
        "predicted_weight_state_bytes": mem["predicted_weight_state_bytes"],
        "actual_param_bytes_per_device": mem["actual_param_bytes_per_device"],
    }


def main(argv=None) -> int:
    p = argparse.ArgumentParser("bench_zero")
    p.add_argument("--model", default="gpt2_twin",
                   choices=("gpt2_twin", "gpt2_check", "mlp"))
    p.add_argument("--batch", type=int, default=8)
    p.add_argument("--epochs", type=int, default=3)
    p.add_argument("--accum-steps", type=int, default=1)
    p.add_argument("--state-dtype", default="float32",
                   choices=("float32", "bfloat16"))
    p.add_argument("--repeats", type=int, default=2,
                   help="best-of-N runs per mode (load-spike robustness)")
    p.add_argument("--out", default="", help="also write the JSON here")
    p.add_argument("--check", action="store_true",
                   help="CI smoke: tiny twin, assert the ~data-degree "
                        "opt-state reduction (predicted AND actual), 1e-6 "
                        "loss parity, and accum equivalence")
    args = p.parse_args(argv)
    if args.check:
        args.model, args.epochs, args.repeats = "gpt2_check", 2, 1

    repl = _run_mode("replicated", args.model, args.batch, args.epochs,
                     args.accum_steps, args.repeats, args.state_dtype)
    zero = _run_mode("zero1", args.model, args.batch, args.epochs,
                     args.accum_steps, args.repeats, args.state_dtype)

    def ratio(a, b):
        return round(a / max(1, b), 2)

    report = {
        "model": args.model,
        "model_note": "CPU twin of gpt2_small (8-virtual-device data mesh)"
        if args.model.startswith("gpt2") else args.model,
        "batch": args.batch,
        "epochs": args.epochs,
        "accum_steps": args.accum_steps,
        "state_dtype": args.state_dtype,
        "modes": {"replicated": repl, "zero1": zero},
        "opt_state_reduction_predicted": ratio(
            repl["predicted_opt_state_bytes"],
            zero["predicted_opt_state_bytes"]),
        "opt_state_reduction_actual": ratio(
            repl["actual_opt_state_bytes_per_device"],
            zero["actual_opt_state_bytes_per_device"]),
        "data_axis_degree": zero["data_axis_degree"],
        "loss_zero_minus_replicated":
            zero["final_loss"] - repl["final_loss"],
        "zero_vs_replicated_speed": ratio(
            zero["steps_per_sec"] * 100, repl["steps_per_sec"] * 100),
    }
    print(json.dumps(report, indent=1))
    if args.out:
        with open(args.out, "w") as f:
            json.dump(report, f, indent=1)

    if args.check:
        ok = True
        deg = zero["data_axis_degree"]
        # ~data-axis-degree reduction: the step-count scalar and any
        # non-divisible weight keep a replicated sliver, so accept >= deg/2
        for k in ("opt_state_reduction_predicted",
                  "opt_state_reduction_actual"):
            if report[k] < deg / 2:
                print(f"CHECK FAIL: {k}={report[k]} < {deg / 2} "
                      f"(data degree {deg})", file=sys.stderr)
                ok = False
        # sharded-from-birth: the jitted tx.init must not allocate the
        # replicated worst case even transiently at rest
        if zero["actual_opt_state_bytes_at_init"] > \
                repl["actual_opt_state_bytes_at_init"] / (deg / 2):
            print("CHECK FAIL: zero1 opt state not sharded at init "
                  f"({zero['actual_opt_state_bytes_at_init']}B vs replicated "
                  f"{repl['actual_opt_state_bytes_at_init']}B)",
                  file=sys.stderr)
            ok = False
        tol = 1e-6 * max(1.0, abs(repl["final_loss"]))
        if abs(report["loss_zero_minus_replicated"]) > tol:
            print(f"CHECK FAIL: zero1 final loss {zero['final_loss']!r} != "
                  f"replicated {repl['final_loss']!r} (tol {tol:g})",
                  file=sys.stderr)
            ok = False
        # accumulation equivalence: accum=4 at batch B == one step at 4B
        # on the SAME dataset (n pinned — the default dataset size scales
        # with the graph batch, which would change the data)
        n = 16 * args.batch * 4
        acc = _run_mode("replicated", args.model, args.batch, args.epochs,
                        4, n_samples=n)
        big = _run_mode("replicated", args.model, args.batch * 4,
                        args.epochs, 1, n_samples=n)
        dtol = 1e-5 * max(1.0, abs(big["final_loss"]))
        if abs(acc["final_loss"] - big["final_loss"]) > dtol:
            print(f"CHECK FAIL: accum=4 loss {acc['final_loss']!r} != "
                  f"batch x4 loss {big['final_loss']!r} (tol {dtol:g})",
                  file=sys.stderr)
            ok = False
        print("CHECK " + ("PASS" if ok else "FAIL"))
        return 0 if ok else 1
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
