"""Graph-substitution engine (reference src/runtime/substitution.cc):
matcher, built-in xfers, elimination rules, JSON rule loading, best-first
search, and the discovers-the-expert-template end-to-end property."""

import itertools
import json

import numpy as np
import pytest

from flexflow_tpu import FFConfig, FFModel, SGDOptimizer
from flexflow_tpu.ops.op_type import OperatorType
from flexflow_tpu.parallel.machine import MachineSpec
from flexflow_tpu.search import cost_model as cm
from flexflow_tpu.search.candidates import layer_candidates
from flexflow_tpu.search.dp import search_graph
from flexflow_tpu.search.pcg import PCG
from flexflow_tpu.search.substitution import (
    OpX,
    find_matches,
    generate_pcg_xfers,
    load_substitution_json,
)
from flexflow_tpu.search.unity import (
    sequence_cut_indices,
    substitution_optimize,
    unity_optimize,
)

MACH = MachineSpec(mesh_axes={"data": 2, "model": 4}, chip="v5p")


def build_mlp_pair(batch=32, hidden=8192):
    m = FFModel(FFConfig(batch_size=batch))
    x = m.create_tensor([batch, hidden], name="x")
    h = m.dense(x, 4 * hidden, activation="gelu", name="up")
    h = m.dense(h, hidden, name="down")
    return m


# ------------------------------------------------------------------ matcher
def test_find_matches_linear_pair():
    m = build_mlp_pair()
    pcg = PCG.from_model(m)
    pat = [OpX({OperatorType.LINEAR}, [("ext", 0)]),
           OpX({OperatorType.LINEAR}, [("op", 0, 0)])]
    matches = find_matches(pat, pcg)
    assert len(matches) == 1
    assert [l.name for l in matches[0]] == ["up", "down"]


def test_find_matches_respects_edges():
    # two linears NOT chained: no pair match
    m = FFModel(FFConfig(batch_size=8))
    x = m.create_tensor([8, 64], name="x")
    m.dense(x, 64, name="a")
    m.dense(x, 64, name="b")
    pcg = PCG.from_model(m)
    pat = [OpX({OperatorType.LINEAR}, [("ext", 0)]),
           OpX({OperatorType.LINEAR}, [("op", 0, 0)])]
    assert find_matches(pat, pcg) == []


# ------------------------------------------------------------ built-in xfers
def test_megatron_xfer_inserts_parallel_nodes():
    m = build_mlp_pair()
    pcg = PCG.from_model(m)
    xfers = [x for x in generate_pcg_xfers(MACH) if x.name == "megatron_linear_pair:model"]
    assert xfers, [x.name for x in generate_pcg_xfers(MACH)]
    (xf,) = xfers
    (match,) = find_matches(xf.src, pcg)
    ng = xf.apply(pcg, match)
    assert ng is not None
    assert ng.pins == {"up": "tp_col:model", "down": "tp_row:model"}
    types = [l.op_type for l in ng.layers]
    assert OperatorType.REPLICATE in types and OperatorType.REDUCTION in types
    assert ng.num_parallel_nodes == 2
    # original graph untouched
    assert pcg.num_parallel_nodes == 0 and not pcg.pins


def test_pinned_dp_costs_megatron_cheaper_than_gather():
    """The pinned Megatron pair must not be priced with an intermediate
    gather (the passthrough parallel nodes keep the batch sharding)."""
    m = build_mlp_pair()
    pcg = PCG.from_model(m)
    xf = next(x for x in generate_pcg_xfers(MACH) if x.name == "megatron_linear_pair:model")
    (match,) = find_matches(xf.src, pcg)
    ng = xf.apply(pcg, match)
    r_pair = search_graph(ng, MACH, pins=ng.pins)
    # force the 'gather between the linears' alternative: col then col
    pcg2 = pcg.clone()
    pcg2.pins = {"up": "tp_col:model", "down": "tp_col:model"}
    r_colcol = search_graph(pcg2, MACH, pins=pcg2.pins)
    assert r_pair.cost <= r_colcol.cost


def test_elimination_removes_partition_combine():
    m = FFModel(FFConfig(batch_size=8))
    x = m.create_tensor([8, 64], name="x")
    t = m.repartition(x, dim=1, axis="model", name="part")
    t = m.combine(t, dim=1, axis="model", name="comb")
    m.dense(t, 32, name="head")
    pcg = PCG.from_model(m)
    elim = [x for x in generate_pcg_xfers(MACH) if x.name == "eliminate_partition_combine"]
    (xf,) = elim
    matches = find_matches(xf.src, pcg)
    assert matches
    ng = xf.apply(pcg, matches[0])
    assert ng is not None
    types = [l.op_type for l in ng.layers]
    assert OperatorType.REPARTITION not in types
    assert OperatorType.COMBINE not in types
    # head now consumes the graph input directly
    head = ng.layer_by_name("head")
    assert head.inputs[0].owner is None


# ------------------------------------------------------------- brute force
def test_dp_matches_bruteforce_on_chain():
    """Exhaustive enumeration over all candidate assignments of a 3-linear
    chain equals the frontier DP optimum (reference: small graphs with
    brute-force-checkable optima, SURVEY §7)."""
    m = FFModel(FFConfig(batch_size=16))
    x = m.create_tensor([16, 512], name="x")
    h = m.dense(x, 1024, name="l0")
    h = m.dense(h, 1024, name="l1")
    m.dense(h, 256, name="l2")
    layers = m.layers
    batch_sizes = {16}
    cand_lists = [layer_candidates(l, MACH, batch_sizes) for l in layers]

    from flexflow_tpu.search.candidates import _dp_dims
    from flexflow_tpu.search.dp import _freeze_dims

    from flexflow_tpu.search.candidates import _batch_axes

    baxes = _batch_axes(MACH)
    best = float("inf")
    for combo in itertools.product(*cand_lists):
        cur = _freeze_dims(_dp_dims((16, 512), MACH, batch_sizes))
        cost = 0.0
        for layer, cand in zip(layers, combo):
            want = _freeze_dims(cand.in_dims[0])
            edge = cm.reshard_time(layer.inputs[0].spec, list(cur), list(want), MACH)
            # mirror the DP's overlap-aware accumulation (search/dp.py):
            # collectives hide behind up to overlap_frac of consumer compute
            op_comm = cand.extra_comm + cm.grad_sync_time(
                layer.weight_specs, cand.weight_dims, MACH, baxes)
            comp = max(0.0, cand.op_time(layer, MACH) - op_comm)
            cost += comp + max(0.0, edge + op_comm - MACH.overlap_frac * comp)
            cur = _freeze_dims(cand.out_dims[0])
        best = min(best, cost)
    res = search_graph(m, MACH, beam_width=10_000)
    assert res.cost == pytest.approx(best, rel=1e-9)


# ------------------------------------------------------------- best first
def test_substitution_search_improves_or_matches_baseline():
    m = build_mlp_pair()
    pcg = PCG.from_model(m)
    best, best_r, stats = substitution_optimize(
        pcg, MACH, generate_pcg_xfers(MACH), budget=16, alpha=1.05)
    assert best_r.cost <= stats.baseline_cost
    assert stats.expansions >= 1


def test_unity_discovers_megatron_on_gpt2_block():
    """End-to-end: on a GPT-2 block the engine discovers the rewrite the
    hand template (parallel/templates.py) encodes: attention head-sharded,
    mlp up col-sharded + down row-sharded."""
    from flexflow_tpu.models import GPT2Config, build_gpt2

    cfg = FFConfig(batch_size=8, mesh_shape={"data": 2, "model": 4},
                   search_budget=48)
    model = FFModel(cfg)
    gcfg = GPT2Config(vocab=5120, seq=128, d_model=1024, heads=8, layers=1,
                      dropout=0.0)
    build_gpt2(model, gcfg, batch=8)
    for layer in model.layers:  # infer ran at build; specs present
        assert layer.outputs
    mach = MachineSpec(mesh_axes={"data": 2, "model": 4}, chip="v5p")
    st, stats = unity_optimize(model, mach)
    up = st.op_shardings["h0_mlp_up"]
    down = st.op_shardings["h0_mlp_down"]
    attn = st.op_shardings["h0_attn"]
    assert up.weights.get("kernel") == [None, "model"], up.weights
    assert down.weights.get("kernel") == ["model", None], down.weights
    assert attn.weights.get("wq") == [None, "model"], attn.weights
    assert stats.best_cost <= stats.baseline_cost


def test_unity_compile_and_train(devices):
    """The unity strategy compiles and executes a training step on the mesh."""
    from flexflow_tpu.models import GPT2Config, build_gpt2

    cfg = FFConfig(batch_size=8, mesh_shape={"data": 2, "model": 4},
                   search_budget=24)
    model = FFModel(cfg)
    gcfg = GPT2Config.tiny(seq=64)
    build_gpt2(model, gcfg, batch=8)
    cm_ = model.compile(SGDOptimizer(lr=0.01),
                        loss_type="sparse_categorical_crossentropy")
    assert cm_.strategy.name.startswith("unity"), cm_.strategy.name
    rng = np.random.default_rng(0)
    ids = rng.integers(0, gcfg.vocab, size=(8, gcfg.seq)).astype(np.int32)
    pos = np.tile(np.arange(gcfg.seq, dtype=np.int32), (8, 1))
    lab = rng.integers(0, gcfg.vocab, size=(8, gcfg.seq)).astype(np.int32)
    cm_.init(seed=0)
    hist = cm_.fit([ids, pos], lab, epochs=1, verbose=False)
    assert np.isfinite(hist[0]["loss"])


# ------------------------------------------------------------ sequence split
def test_sequence_cut_indices_chain_vs_residual():
    m = FFModel(FFConfig(batch_size=8))
    x = m.create_tensor([8, 64], name="x")
    a = m.dense(x, 64, name="a")
    b = m.dense(a, 64, name="b")     # chain: cut after a and b
    c = m.add(b, a, name="c")        # residual: no cut between b and c
    cuts = sequence_cut_indices(m.layers, m.input_tensors)
    names = [m.layers[i].name for i in cuts]
    # after a: only a's output is live (b and c both read it) -> cut;
    # after b: both a (still needed by c) and b are live -> NOT a cut;
    # c is the final layer (excluded by construction)
    assert names == ["a"], names


# -------------------------------------------------------------- JSON rules
def test_json_loader_and_apply(tmp_path):
    """Load a rule in the reference schema (partition∘combine with equal
    dim/degree cancels) and apply it."""
    rule = {
        "_t": "RuleCollection",
        "rule": [{
            "_t": "Rule",
            "name": "cancel_partition_combine",
            "srcOp": [
                {"_t": "Operator", "type": "OP_PARTITION",
                 "input": [{"_t": "Tensor", "opId": -1, "tsId": 0}],
                 "para": [{"_t": "Parameter", "key": "PM_PARALLEL_DIM", "value": 0},
                          {"_t": "Parameter", "key": "PM_PARALLEL_DEGREE", "value": 4}]},
                {"_t": "Operator", "type": "OP_COMBINE",
                 "input": [{"_t": "Tensor", "opId": 0, "tsId": 0}],
                 "para": [{"_t": "Parameter", "key": "PM_PARALLEL_DIM", "value": 0},
                          {"_t": "Parameter", "key": "PM_PARALLEL_DEGREE", "value": 4}]},
            ],
            "dstOp": [
                {"_t": "Operator", "type": "OP_REPLICATE",
                 "input": [{"_t": "Tensor", "opId": -1, "tsId": 0}],
                 "para": [{"_t": "Parameter", "key": "PM_PARALLEL_DIM", "value": 0},
                          {"_t": "Parameter", "key": "PM_PARALLEL_DEGREE", "value": 4}]},
            ],
            "mappedOutput": [{"_t": "MapOutput", "srcOpId": 1, "srcTsId": 0,
                              "dstOpId": 0, "dstTsId": 0}],
        }],
    }
    p = tmp_path / "rules.json"
    p.write_text(json.dumps(rule))
    xfers, report = load_substitution_json(str(p), MACH)
    assert report["loaded"] == 1, report

    # graph: x -> partition(dim 1 == legion dim 0 for 2D) -> combine -> head
    m = FFModel(FFConfig(batch_size=8))
    x = m.create_tensor([8, 64], name="x")
    t = m.repartition(x, dim=1, axis="model", name="part")
    t = m.combine(t, dim=1, axis="model", name="comb")
    m.dense(t, 32, name="head")
    pcg = PCG.from_model(m)
    (xf,) = xfers
    matches = find_matches(xf.src, pcg)
    assert matches, "JSON rule pattern should match the partition-combine chain"
    ng = xf.apply(pcg, matches[0])
    assert ng is not None
    types = [l.op_type for l in ng.layers]
    assert OperatorType.REPARTITION not in types
    assert OperatorType.COMBINE not in types
    assert OperatorType.REPLICATE in types


def test_json_loader_skips_unmatched_degree(tmp_path):
    rule = {"rule": [{
        "name": "deg3", "srcOp": [
            {"type": "OP_PARTITION", "input": [{"opId": -1, "tsId": 0}],
             "para": [{"key": "PM_PARALLEL_DIM", "value": 0},
                      {"key": "PM_PARALLEL_DEGREE", "value": 3}]}],
        "dstOp": [], "mappedOutput": []}]}
    p = tmp_path / "r.json"
    p.write_text(json.dumps(rule))
    xfers, report = load_substitution_json(str(p), MACH)
    assert report["loaded"] == 0 and report["degree_unmatched"] == 1


def test_json_degree2_is_wildcard_per_model_axis(tmp_path):
    """PM_PARALLEL_DEGREE==2 is the schema's placeholder degree (reference
    substitution.cc:1487): it must bind to each model mesh axis, not
    literal-match an axis of size 2."""
    rule = {"rule": [{
        "name": "deg2", "srcOp": [
            {"type": "OP_PARTITION", "input": [{"opId": -1, "tsId": 0}],
             "para": [{"key": "PM_PARALLEL_DIM", "value": 0},
                      {"key": "PM_PARALLEL_DEGREE", "value": 2}]},
            {"type": "OP_COMBINE", "input": [{"opId": 0, "tsId": 0}],
             "para": [{"key": "PM_PARALLEL_DIM", "value": 0},
                      {"key": "PM_PARALLEL_DEGREE", "value": 2}]}],
        "dstOp": [
            {"type": "OP_REPLICATE", "input": [{"opId": -1, "tsId": 0}],
             "para": [{"key": "PM_PARALLEL_DIM", "value": 0},
                      {"key": "PM_PARALLEL_DEGREE", "value": 2}]}],
        "mappedOutput": [{"srcOpId": 1, "srcTsId": 0, "dstOpId": 0, "dstTsId": 0}],
    }]}
    p = tmp_path / "r.json"
    p.write_text(json.dumps(rule))
    # no size-2 model axis at all: the wildcard must still instantiate
    mach = MachineSpec(mesh_axes={"data": 2, "model": 4}, chip="v5p")
    xfers, report = load_substitution_json(str(p), mach)
    assert report["loaded"] == 1, report
    # two model axes -> one instantiation per axis
    mach2 = MachineSpec(mesh_axes={"data": 2, "model": 4, "expert": 8}, chip="v5p")
    xfers2, report2 = load_substitution_json(str(p), mach2)
    assert report2["instantiated"] == 2, report2


def test_json_dst_compute_shape_inference(tmp_path):
    """A rule whose dst contains a shape-changing compute op (linear) must
    re-derive that node's output spec via registry shape inference and keep
    the replaced model layer's name/params (round-3 advisor medium finding)."""
    # rule: partition -> linear -> reduce  =>  linear -> (mapped out)
    rule = {"rule": [{
        "name": "lift_linear", "srcOp": [
            {"type": "OP_PARTITION", "input": [{"opId": -1, "tsId": 0}],
             "para": [{"key": "PM_PARALLEL_DIM", "value": 0},
                      {"key": "PM_PARALLEL_DEGREE", "value": 4}]},
            {"type": "OP_LINEAR", "input": [{"opId": 0, "tsId": 0}],
             "para": [{"key": "PM_ACTI", "value": 0}]},
        ],
        "dstOp": [
            {"type": "OP_LINEAR", "input": [{"opId": -1, "tsId": 0}],
             "para": [{"key": "PM_ACTI", "value": 0}]},
        ],
        "mappedOutput": [{"srcOpId": 1, "srcTsId": 0, "dstOpId": 0, "dstTsId": 0}],
    }]}
    p = tmp_path / "r.json"
    p.write_text(json.dumps(rule))
    xfers, report = load_substitution_json(str(p), MACH)
    assert report["loaded"] == 1, report

    m = FFModel(FFConfig(batch_size=8))
    x = m.create_tensor([8, 64], name="x")
    t = m.repartition(x, dim=1, axis="model", name="part")
    m.dense(t, 32, name="proj")  # output (8, 32) != input (8, 64)
    pcg = PCG.from_model(m)
    (xf,) = xfers
    matches = find_matches(xf.src, pcg)
    assert matches
    ng = xf.apply(pcg, matches[0])
    assert ng is not None
    proj = ng.layer_by_name("proj")  # identity preserved from the src op
    assert proj.outputs[0].spec.shape == (8, 32)  # inferred, not copied input
    assert proj.params.get("out_dim", 32) == 32 or proj.params  # params mapped
    # the rewritten graph must still be costable end to end
    r = search_graph(ng, MACH, pins=ng.pins)
    assert np.isfinite(r.cost)


def test_unity_global_budget_and_replay():
    """search_budget bounds TOTAL expansions across segments, and repeated
    GPT-2 blocks are replayed from the memoized winning path (quality
    unchanged: the TP rewrite still lands on every block)."""
    from flexflow_tpu.models import GPT2Config, build_gpt2

    budget = 24
    cfg = FFConfig(batch_size=8, mesh_shape={"data": 2, "model": 4},
                   search_budget=budget, base_optimize_threshold=4)
    model = FFModel(cfg)
    gcfg = GPT2Config(vocab=5120, seq=128, d_model=1024, heads=8, layers=4,
                      dropout=0.0)
    build_gpt2(model, gcfg, batch=8)
    mach = MachineSpec(mesh_axes={"data": 2, "model": 4}, chip="v5p")
    st, stats = unity_optimize(model, mach)
    assert stats.expansions <= budget, (stats.expansions, budget)
    assert stats.segments_replayed >= 1, "identical blocks should be replayed"
    # quality: every block's mlp pair still gets the Megatron rewrite
    for i in range(gcfg.layers):
        up = st.op_shardings.get(f"h{i}_mlp_up")
        assert up is not None and up.weights.get("kernel") == [None, "model"], \
            (i, up and up.weights)
