from flexflow_tpu.compiler.compile import CompiledModel, compile_model

__all__ = ["CompiledModel", "compile_model"]
