"""Checkpoint/resume (SURVEY §5d — the rebuild's improvement over the
reference's get/set-weight-only persistence): full state round-trips across
fresh CompiledModel instances, training resumes bit-exactly, and sharded
weights restore into their shardings."""

import numpy as np
import pytest

from flexflow_tpu import FFConfig, FFModel, AdamOptimizer


def _build(tmpdir_seed=0):
    cfg = FFConfig(batch_size=16, mesh_shape={"data": 4, "model": 2},
                   only_data_parallel=True, seed=5)
    m = FFModel(cfg)
    x = m.create_tensor([16, 32], name="x")
    h = m.dense(x, 64, activation="relu", name="fc1")
    h = m.batch_norm(m.reshape(h, [16, 64, 1, 1]), relu=False, name="bn")
    h = m.flat(h, name="fl")
    m.dense(h, 4, name="head")
    cm = m.compile(AdamOptimizer(alpha=0.01),
                   loss_type="sparse_categorical_crossentropy", metrics=[])
    return m, cm


def _data():
    rng = np.random.default_rng(0)
    x = rng.normal(size=(64, 32)).astype(np.float32)
    y = rng.integers(0, 4, size=(64,)).astype(np.int32)
    return x, y


def test_checkpoint_roundtrip_and_exact_resume(devices, tmp_path):
    x, y = _data()
    m1, cm1 = _build()
    cm1.init(seed=0)
    cm1.fit(x, y, epochs=1, verbose=False)  # 4 steps; BN state populated
    assert cm1.state, "batch_norm should have produced running stats"
    ck = str(tmp_path / "ck")
    cm1.save_checkpoint(ck)
    fc1_at_ck = np.asarray(cm1.get_weight("fc1"))
    # continue the original for 1 more epoch -> the reference trajectory
    h_ref = cm1.fit(x, y, epochs=1, verbose=False)

    # fresh process-state: new model, restore, resume
    m2, cm2 = _build()
    cm2.init(seed=123)  # different init — must be overwritten by restore
    cm2.load_checkpoint(ck)
    assert cm2._iteration == 4
    np.testing.assert_array_equal(np.asarray(cm2.get_weight("fc1")), fc1_at_ck)
    h_res = cm2.fit(x, y, epochs=1, verbose=False)
    # same data order (same seed + iteration) -> bit-identical trajectory
    assert h_res[0]["loss"] == pytest.approx(h_ref[0]["loss"], rel=1e-6), \
        (h_res[0]["loss"], h_ref[0]["loss"])
    np.testing.assert_allclose(np.asarray(cm2.get_weight("head")),
                               np.asarray(cm1.get_weight("head")), rtol=1e-6)


def test_checkpoint_restores_into_shardings(devices, tmp_path):
    from flexflow_tpu.parallel.templates import apply_tensor_parallel_linear_pair

    cfg = FFConfig(batch_size=16, mesh_shape={"data": 4, "model": 2},
                   only_data_parallel=True)
    m = FFModel(cfg)
    x = m.create_tensor([16, 64], name="x")
    h = m.dense(x, 256, activation="gelu", name="up")
    m.dense(h, 64, name="down")
    cm = m.compile(AdamOptimizer(alpha=0.01), loss_type="mean_squared_error",
                   metrics=[])
    apply_tensor_parallel_linear_pair(cm.strategy, m.get_layer_by_name("up"),
                                      m.get_layer_by_name("down"), "model")
    cm._build_steps()
    cm.init(seed=0)
    before = np.asarray(cm.get_weight("up"))
    ck = str(tmp_path / "ck")
    cm.save_checkpoint(ck)

    m2 = FFModel(cfg)
    x2 = m2.create_tensor([16, 64], name="x")
    h2 = m2.dense(x2, 256, activation="gelu", name="up")
    m2.dense(h2, 64, name="down")
    cm2 = m2.compile(AdamOptimizer(alpha=0.01), loss_type="mean_squared_error",
                     metrics=[])
    apply_tensor_parallel_linear_pair(cm2.strategy, m2.get_layer_by_name("up"),
                                      m2.get_layer_by_name("down"), "model")
    cm2._build_steps()
    cm2.init(seed=9)
    cm2.load_checkpoint(ck)
    np.testing.assert_array_equal(np.asarray(cm2.get_weight("up")), before)
    # restored INTO the tensor-parallel sharding, not gathered
    k = cm2.params["up"]["kernel"]
    shard = next(iter(k.addressable_shards)).data.shape
    assert shard[1] == k.shape[1] // 2, (shard, k.shape)
