"""Flash-attention kernel numerics vs the reference einsum path.

Reference capability: fused cuDNN attention (src/ops/attention.cu:35). On the
CPU test mesh the pallas kernels run in interpreter mode; on TPU they compile.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from flexflow_tpu.kernels.flash_attention import flash_attention, flash_attention_qkv


def _reference(q, k, v, causal, scale):
    logits = jnp.einsum("bhqd,bhkd->bhqk", q, k).astype(jnp.float32) * scale
    if causal:
        sq, sk = logits.shape[-2:]
        mask = jnp.tril(jnp.ones((sq, sk), bool))
        logits = jnp.where(mask, logits, jnp.finfo(jnp.float32).min)
    probs = jax.nn.softmax(logits, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", probs.astype(q.dtype), v)


@pytest.mark.parametrize("causal", [False, True])
def test_forward_matches_einsum(causal):
    rng = np.random.default_rng(0)
    b, h, s, d = 2, 3, 256, 64
    q = jnp.asarray(rng.normal(size=(b, h, s, d)), jnp.float32)
    k = jnp.asarray(rng.normal(size=(b, h, s, d)), jnp.float32)
    v = jnp.asarray(rng.normal(size=(b, h, s, d)), jnp.float32)
    out = flash_attention(q, k, v, causal=causal)
    ref = _reference(q, k, v, causal, 1.0 / np.sqrt(d))
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5)


def test_cross_attention_lengths():
    rng = np.random.default_rng(1)
    b, h, d = 2, 2, 32
    q = jnp.asarray(rng.normal(size=(b, h, 128, d)), jnp.float32)
    k = jnp.asarray(rng.normal(size=(b, h, 256, d)), jnp.float32)
    v = jnp.asarray(rng.normal(size=(b, h, 256, d)), jnp.float32)
    out = flash_attention(q, k, v, causal=False)
    ref = _reference(q, k, v, False, 1.0 / np.sqrt(d))
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_gradients_match_einsum(causal):
    rng = np.random.default_rng(2)
    b, h, s, d = 1, 2, 128, 32
    q = jnp.asarray(rng.normal(size=(b, h, s, d)), jnp.float32)
    k = jnp.asarray(rng.normal(size=(b, h, s, d)), jnp.float32)
    v = jnp.asarray(rng.normal(size=(b, h, s, d)), jnp.float32)
    scale = 1.0 / np.sqrt(d)

    def f_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v, causal=causal) ** 2)

    def f_ref(q, k, v):
        return jnp.sum(_reference(q, k, v, causal, scale) ** 2)

    g_flash = jax.grad(f_flash, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(f_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b_ in zip(g_flash, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_), atol=5e-4, rtol=5e-4)


def test_unsupported_shapes_raise():
    q = jnp.zeros((1, 1, 100, 32))  # 100 not divisible by any block
    with pytest.raises(ValueError):
        flash_attention(q, q, q)
    q2 = jnp.zeros((1, 1, 128, 32))
    k2 = jnp.zeros((1, 1, 256, 32))
    with pytest.raises(ValueError):
        flash_attention(q2, k2, k2, causal=True)  # causal needs sq == sk


def test_qkv_layout_wrapper():
    rng = np.random.default_rng(3)
    b, s, h, d = 2, 128, 2, 32
    q = jnp.asarray(rng.normal(size=(b, s, h, d)), jnp.float32)
    out = flash_attention_qkv(q, q, q, causal=True)
    assert out.shape == (b, s, h, d)
    ref = jnp.swapaxes(
        _reference(jnp.swapaxes(q, 1, 2), jnp.swapaxes(q, 1, 2),
                   jnp.swapaxes(q, 1, 2), True, 1.0 / np.sqrt(d)), 1, 2)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5)


def test_mha_layer_uses_flash():
    """FFModel MHA with impl='flash' matches impl='xla' end to end."""
    from flexflow_tpu import FFConfig, FFModel

    rng = np.random.default_rng(4)
    x = rng.normal(size=(2, 128, 64)).astype(np.float32)
    outs = {}
    for impl in ("xla", "flash"):
        cfg = FFConfig(batch_size=2)
        m = FFModel(cfg)
        t = m.create_tensor((2, 128, 64), name="x")
        y = m.multihead_attention(t, t, t, embed_dim=64, num_heads=2,
                                  causal=True, impl=impl, name="attn")
        cm = m.compile(loss_type="mean_squared_error")
        cm.init(seed=0)
        outs[impl] = np.asarray(cm.forward(x))
    np.testing.assert_allclose(outs["flash"], outs["xla"], atol=2e-4, rtol=2e-4)


@pytest.mark.parametrize("causal", [False, True])
def test_head_dim_128_parity(causal):
    """Satellite (round-5 MFU note): the block-shape ceiling was sized for
    head_dim 64 — head_dim 128 must pick a depth-aware block (512-row f32
    blocks would double the per-operand VMEM footprint) and still match
    the einsum reference in fwd AND grads."""
    from flexflow_tpu.kernels.flash_attention import _pick_block

    # f32 head_dim 128 drops the 512 block; bf16 keeps it; d=64 unchanged
    assert _pick_block(512, 64, 4) == 512
    assert _pick_block(512, 128, 4) == 256
    assert _pick_block(512, 128, 2) == 512

    rng = np.random.default_rng(5)
    b, h, s, d = 1, 2, 256, 128
    q = jnp.asarray(rng.normal(size=(b, h, s, d)), jnp.float32)
    k = jnp.asarray(rng.normal(size=(b, h, s, d)), jnp.float32)
    v = jnp.asarray(rng.normal(size=(b, h, s, d)), jnp.float32)
    scale = 1.0 / np.sqrt(d)
    out = flash_attention(q, k, v, causal=causal)
    ref = _reference(q, k, v, causal, scale)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=5e-5, rtol=5e-5)

    def f_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v, causal=causal) ** 2)

    def f_ref(q, k, v):
        return jnp.sum(_reference(q, k, v, causal, scale) ** 2)

    g_flash = jax.grad(f_flash, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(f_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b_ in zip(g_flash, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                   atol=1e-3, rtol=1e-3)


@pytest.mark.parametrize("causal", [False, True])
def test_head_dim_64_retuned_blocks_parity(causal):
    """Satellite (ISSUE 12): narrow heads waste the depth-sized budget —
    head_dim <= 64 gets its own VMEM budget so long sequences keep the
    1024-row block (fewer grid steps, better MXU occupancy). The retune
    must leave every depth>=128 pick and the d=64 short-seq picks alone,
    and match the einsum reference in fwd AND grads at the new block."""
    from flexflow_tpu.kernels.flash_attention import _pick_block

    # the retuned pick: d=64 f32 at seq 1024 now keeps the 1024 block
    assert _pick_block(1024, 64, 4) == 1024
    # the d=128 pins of the round-5 retune still hold
    assert _pick_block(512, 64, 4) == 512
    assert _pick_block(512, 128, 4) == 256
    assert _pick_block(512, 128, 2) == 512

    rng = np.random.default_rng(6)
    b, h, s, d = 1, 2, 1024, 64
    q = jnp.asarray(rng.normal(size=(b, h, s, d)), jnp.float32)
    k = jnp.asarray(rng.normal(size=(b, h, s, d)), jnp.float32)
    v = jnp.asarray(rng.normal(size=(b, h, s, d)), jnp.float32)
    scale = 1.0 / np.sqrt(d)
    out = flash_attention(q, k, v, causal=causal)
    ref = _reference(q, k, v, causal, scale)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=5e-5, rtol=5e-5)

    def f_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v, causal=causal) ** 2)

    def f_ref(q, k, v):
        return jnp.sum(_reference(q, k, v, causal, scale) ** 2)

    g_flash = jax.grad(f_flash, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(f_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b_ in zip(g_flash, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                   atol=2e-3, rtol=2e-3)


def test_vmem_reject_falls_back_to_reference_path():
    """A shape past the VMEM-resident budget raises ValueError at TRACE
    time (the graceful Mosaic-reject precheck), and the MHA auto path
    swallows it — the layer still lowers, via the einsum reference."""
    from flexflow_tpu import FFConfig, FFModel
    from flexflow_tpu.kernels.flash_attention import flash_supported

    # seq * depth past the k/v-resident budget: supported == False and the
    # kernel refuses up front
    assert not flash_supported(8192, 128, 4)
    q = jnp.zeros((1, 1, 8192, 128), jnp.float32)
    with pytest.raises(ValueError):
        flash_attention(q, q, q)

    # auto mode: the same shape inside an MHA layer falls back silently
    cfg = FFConfig(batch_size=1)
    m = FFModel(cfg)
    t = m.create_tensor((1, 8192, 128), name="x")
    m.multihead_attention(t, t, t, embed_dim=128, num_heads=1,
                          causal=True, name="attn")
    cm = m.compile(loss_type="mean_squared_error")
    cm.init(seed=0)
    out = cm.forward(np.zeros((1, 8192, 128), np.float32))
    assert np.asarray(out).shape == (1, 8192, 128)
