"""The remaining runtime-config fields are wired (zero
accepted-and-ignored, extending round-2's bar to every field): num_nodes/
workers_per_node machine description, donate_state, tensor-op math gate,
log_level, seq_length (tested in test_core_graph)."""

import logging

import numpy as np
import pytest

from flexflow_tpu import FFConfig, FFModel, SGDOptimizer


def _tiny(cfg):
    m = FFModel(cfg)
    x = m.create_tensor([16, 8], name="x")
    m.dense(x, 4, name="fc")
    return m


def test_num_nodes_builds_dcn_node_axis(devices):
    cfg = FFConfig(batch_size=16, num_nodes=2, workers_per_node=4,
                   only_data_parallel=True)
    m = _tiny(cfg)
    cm = m.compile(SGDOptimizer(lr=0.01),
                   loss_type="sparse_categorical_crossentropy", metrics=[])
    assert dict(cm.machine.mesh_axes) == {"node": 2, "data": 4}
    assert cm.machine.dcn_axes == ("node",)
    assert cm.machine.axis_bw("node") == cm.machine.dcn_bw  # DCN-priced


def test_donate_state_false_keeps_buffers(devices):
    import jax

    cfg = FFConfig(batch_size=16, only_data_parallel=True, donate_state=False)
    m = _tiny(cfg)
    cm = m.compile(SGDOptimizer(lr=0.01),
                   loss_type="sparse_categorical_crossentropy", metrics=[])
    cm.init(seed=0)
    old_params = cm.params
    x = np.zeros((16, 8), np.float32)
    y = np.zeros((16,), np.int32)
    cm.train_step(cm.params, cm.opt_state, cm.state, [jax.device_put(x)],
                  jax.device_put(y), jax.random.PRNGKey(0))
    # without donation the original buffers remain readable
    _ = float(np.asarray(old_params["fc"]["kernel"]).sum())


def test_tensor_op_math_gate_sets_matmul_precision(devices):
    import jax

    def jaxpr_for(allow):
        cfg = FFConfig(batch_size=16, only_data_parallel=True,
                       allow_tensor_op_math_conversion=allow)
        m = _tiny(cfg)
        cm = m.compile(SGDOptimizer(lr=0.01),
                       loss_type="sparse_categorical_crossentropy",
                       metrics=[])
        cm.init(seed=0)
        x = [np.zeros((16, 8), np.float32)]
        y = np.zeros((16,), np.int32)
        return str(jax.make_jaxpr(
            lambda p, o, s: cm.train_step.__wrapped__(p, o, s, x, y,
                                                      jax.random.PRNGKey(0))
        )(cm.params, cm.opt_state, cm.state))

    assert "Precision.HIGHEST" in jaxpr_for(False)
    assert "Precision.HIGHEST" not in jaxpr_for(True)


def test_log_level_wired(devices, caplog):
    lg = logging.getLogger("flexflow_tpu")
    old = lg.level
    try:
        # pristine logger: cfg.log_level applies
        lg.setLevel(logging.NOTSET)
        m = _tiny(FFConfig(batch_size=16, only_data_parallel=True,
                           log_level="debug"))
        m.compile(SGDOptimizer(lr=0.01),
                  loss_type="sparse_categorical_crossentropy", metrics=[])
        assert lg.level == logging.DEBUG
        # application config wins: an explicit level is never clobbered
        lg.setLevel(logging.WARNING)
        m2 = _tiny(FFConfig(batch_size=16, only_data_parallel=True,
                            log_level="info"))
        m2.compile(SGDOptimizer(lr=0.01),
                   loss_type="sparse_categorical_crossentropy", metrics=[])
        assert lg.level == logging.WARNING
        # invalid names fail loud instead of silently meaning INFO
        with pytest.raises(ValueError):
            _tiny(FFConfig(batch_size=16, only_data_parallel=True,
                           log_level="trace")).compile(
                SGDOptimizer(lr=0.01),
                loss_type="sparse_categorical_crossentropy", metrics=[])
        # the compile log line exists
        with caplog.at_level(logging.INFO, logger="flexflow_tpu"):
            m3 = _tiny(FFConfig(batch_size=16, only_data_parallel=True))
            m3.compile(SGDOptimizer(lr=0.01),
                       loss_type="sparse_categorical_crossentropy", metrics=[])
        assert any("compile: mesh=" in r.getMessage() for r in caplog.records)
    finally:
        lg.setLevel(old)


def test_telemetry_dir_wired(devices, tmp_path):
    """--telemetry-dir flows parse_args -> FFConfig -> compile_model,
    which enables the process-global telemetry stream (ISSUE 5). Added
    via FFConfig.build_parser only, so the launcher's value-flag set
    covers it automatically (test_launcher_accuracy's derived-flags
    regression)."""
    from flexflow_tpu import telemetry as tel
    from flexflow_tpu.config import FFConfig as Cfg

    cfg = Cfg.parse_args(["--telemetry-dir", "/tmp/tele_x"])
    assert cfg.telemetry_dir == "/tmp/tele_x"
    assert Cfg().telemetry_dir == ""  # off by default
    # --telemetry-dir consumes its value token: the launcher must not
    # mistake the dir for the user script
    assert "--telemetry-dir" in Cfg.launcher_value_flags()
    try:
        tdir = str(tmp_path / "tele")
        m = _tiny(FFConfig(batch_size=16, only_data_parallel=True,
                           telemetry_dir=tdir, log_level="warning"))
        m.compile(SGDOptimizer(lr=0.01),
                  loss_type="sparse_categorical_crossentropy", metrics=[])
        assert tel.enabled()
        tel.flush()
        evs = tel.read_events(tdir)
        assert any(e["name"] == "compile/compile_model" for e in evs)
    finally:
        tel.shutdown()


def test_resilience_flags_wired(devices):
    """The ISSUE-6 resilience knobs flow parse_args -> FFConfig, and —
    because they are added via FFConfig.build_parser only — the launcher's
    derived value-flag set covers every value-taking one automatically."""
    from flexflow_tpu.config import FFConfig as Cfg

    cfg = Cfg.parse_args([
        "--checkpoint-dir", "/tmp/ck", "--checkpoint-every-steps", "50",
        "--checkpoint-every-secs", "30.5", "--resume", "auto",
        "--keep-checkpoints", "5", "--retry-attempts", "4",
        "--retry-base-delay", "0.2", "--fault-plan",
        "dataloader/transfer@3*2"])
    assert cfg.checkpoint_dir == "/tmp/ck"
    assert cfg.checkpoint_every_steps == 50
    assert cfg.checkpoint_every_secs == 30.5
    assert cfg.resume == "auto"
    assert cfg.keep_checkpoints == 5
    assert cfg.retry_attempts == 4
    assert cfg.retry_base_delay == 0.2
    assert cfg.fault_plan == "dataloader/transfer@3*2"
    # resilience is fully off by default: fit carries zero extra work
    d = Cfg()
    assert (d.checkpoint_dir, d.resume, d.fault_plan) == ("", "", "")
    assert d.checkpoint_every_steps == 0 and d.checkpoint_every_secs == 0.0
    vf = Cfg.launcher_value_flags()
    for flag in ("--checkpoint-dir", "--checkpoint-every-steps",
                 "--checkpoint-every-secs", "--resume",
                 "--keep-checkpoints", "--retry-attempts",
                 "--retry-base-delay", "--fault-plan"):
        assert flag in vf, flag


def test_serving_flags_wired():
    """The ISSUE-10 serving knobs flow parse_args -> FFConfig via
    build_parser only (the launcher's value-flag set derives from it):
    --serve is a boolean gate, the rest consume a value token, and
    --serve-objective is constrained to the two _score objectives."""
    import pytest

    from flexflow_tpu.config import FFConfig as Cfg

    cfg = Cfg.parse_args(["--serve", "--max-decode-len", "64",
                          "--kv-page-size", "32", "--max-batch-slots", "16",
                          "--serve-objective", "throughput"])
    assert cfg.serve is True
    assert cfg.max_decode_len == 64
    assert cfg.kv_page_size == 32
    assert cfg.max_batch_slots == 16
    assert cfg.serve_objective == "throughput"
    d = Cfg()
    assert d.serve is False           # serving is an explicit opt-in
    assert d.max_decode_len == 0      # 0 = compile_serving's default
    assert d.kv_page_size == 16
    assert d.max_batch_slots == 8
    assert d.serve_objective == "latency"
    with pytest.raises(SystemExit):
        Cfg.parse_args(["--serve-objective", "goodput"])
    vf = Cfg.launcher_value_flags()
    for flag in ("--max-decode-len", "--kv-page-size",
                 "--max-batch-slots", "--serve-objective"):
        assert flag in vf, flag
    assert "--serve" not in vf        # the gate takes no value token


def test_serving_resilience_flags_wired():
    """The ISSUE-11 serving-under-fire knobs flow parse_args -> FFConfig
    via build_parser only: hot-swap watch root, TTFT-budget shedding,
    queue cap, and the decode watchdog. All default OFF — a scheduler
    built without them carries zero admission-control overhead."""
    from flexflow_tpu.config import FFConfig as Cfg

    cfg = Cfg.parse_args(["--serve-watch-dir", "/tmp/ckpts",
                          "--serve-ttft-budget-ms", "250.5",
                          "--serve-queue-cap", "32",
                          "--serve-decode-timeout-ms", "75.0"])
    assert cfg.serve_watch_dir == "/tmp/ckpts"
    assert cfg.serve_ttft_budget_ms == 250.5
    assert cfg.serve_queue_cap == 32
    assert cfg.serve_decode_timeout_ms == 75.0
    d = Cfg()
    assert d.serve_watch_dir == ""          # no watch -> no polling
    assert d.serve_ttft_budget_ms == 0.0    # 0 = shedding off
    assert d.serve_queue_cap == 0           # 0 = unbounded queue
    assert d.serve_decode_timeout_ms == 0.0  # 0 = watchdog off
    vf = Cfg.launcher_value_flags()
    for flag in ("--serve-watch-dir", "--serve-ttft-budget-ms",
                 "--serve-queue-cap", "--serve-decode-timeout-ms"):
        assert flag in vf, flag


def test_spec_kv_flags_wired():
    """The ISSUE-13 decode-throughput knobs flow parse_args -> FFConfig via
    build_parser only: draft-model JSON path, speculation depth, and the
    KV-cache dtype (constrained to the engine's supported set). All default
    OFF/auto — an engine built without them is byte-identical to before."""
    import pytest

    from flexflow_tpu.config import FFConfig as Cfg

    cfg = Cfg.parse_args(["--serve-draft-model", "/tmp/draft.json",
                          "--serve-spec-tokens", "4",
                          "--kv-cache-dtype", "int8"])
    assert cfg.serve_draft_model == "/tmp/draft.json"
    assert cfg.serve_spec_tokens == 4
    assert cfg.kv_cache_dtype == "int8"
    d = Cfg()
    assert d.serve_draft_model == ""     # no draft -> plain decode
    assert d.serve_spec_tokens == 0      # 0 = speculation off
    assert d.kv_cache_dtype == "auto"    # auto = follow compute dtype
    with pytest.raises(SystemExit):
        Cfg.parse_args(["--kv-cache-dtype", "fp4"])
    vf = Cfg.launcher_value_flags()
    for flag in ("--serve-draft-model", "--serve-spec-tokens",
                 "--kv-cache-dtype"):
        assert flag in vf, flag


def test_health_flags_wired():
    """The ISSUE-9 health knobs flow parse_args -> FFConfig via
    build_parser only (launcher value-flag set derives automatically):
    sentinels default ON (BooleanOptionalAction), halt opt-in, and the
    telemetry sink's size-based rotation cap generous by default."""
    from flexflow_tpu.config import FFConfig as Cfg

    cfg = Cfg.parse_args(["--telemetry-max-mb", "64",
                          "--no-health-sentinels", "--halt-on-nonfinite"])
    assert cfg.telemetry_max_mb == 64.0
    assert cfg.health_sentinels is False
    assert cfg.halt_on_nonfinite is True
    d = Cfg()
    assert d.telemetry_max_mb == 512.0  # generous: rotation rarely fires
    assert d.health_sentinels is True   # zero-sync checks ride the defaults
    assert d.halt_on_nonfinite is False  # halting is an explicit opt-in
    assert Cfg.parse_args(["--health-sentinels"]).health_sentinels is True
    # --telemetry-max-mb consumes a value token; the boolean gates don't
    vf = Cfg.launcher_value_flags()
    assert "--telemetry-max-mb" in vf
    assert "--halt-on-nonfinite" not in vf


def test_slo_reqtrace_flags_wired():
    """The ISSUE-15 observability knobs flow parse_args -> FFConfig via
    build_parser only: the SLO objective string (validated by parse_slo at
    construction, so a bad grammar fails loud at startup, not mid-serve)
    and the request-tracer gate (default ON, BooleanOptionalAction)."""
    import pytest

    from flexflow_tpu.config import FFConfig as Cfg

    cfg = Cfg.parse_args(["--serve-slo",
                          "ttft_p99_ms=25,per_token_p99_ms=10,"
                          "availability=0.999",
                          "--no-serve-reqtrace"])
    assert cfg.serve_slo == ("ttft_p99_ms=25,per_token_p99_ms=10,"
                             "availability=0.999")
    assert cfg.serve_reqtrace is False
    d = Cfg()
    assert d.serve_slo == ""          # no objectives -> tracker idles
    assert d.serve_reqtrace is True   # tracing is on by default (zero-sync)
    assert Cfg.parse_args(["--serve-reqtrace"]).serve_reqtrace is True
    with pytest.raises(ValueError):
        Cfg(serve_slo="ttft_p99_ms=nope")
    with pytest.raises(ValueError):
        Cfg(serve_slo="unknown_metric_p99_ms=5")
    # --serve-slo consumes a value token; the boolean gate doesn't
    vf = Cfg.launcher_value_flags()
    assert "--serve-slo" in vf
    assert "--serve-reqtrace" not in vf


def test_twin_trace_flags_wired():
    """The ISSUE-20 capacity-twin knobs flow parse_args -> FFConfig via
    build_parser only: live trace export (--serve-trace-out) and the
    twin CLI's replay inputs (--twin-trace/--twin-replicas/--twin-out).
    All default off — recording and replay are strictly opt-in."""
    from flexflow_tpu.config import FFConfig as Cfg

    cfg = Cfg.parse_args(["--serve-trace-out", "/tmp/live.jsonl",
                          "--twin-trace", "/tmp/replay.jsonl",
                          "--twin-replicas", "4",
                          "--twin-out", "/tmp/twin.json"])
    assert cfg.serve_trace_out == "/tmp/live.jsonl"
    assert cfg.twin_trace == "/tmp/replay.jsonl"
    assert cfg.twin_replicas == 4
    assert cfg.twin_out == "/tmp/twin.json"
    d = Cfg()
    assert d.serve_trace_out == ""   # no export unless asked
    assert d.twin_trace == ""
    assert d.twin_replicas == 0      # 0 = follow --serve-replicas
    assert d.twin_out == ""          # report to stdout
    # all four consume value tokens (launcher passthrough safety)
    vf = Cfg.launcher_value_flags()
    for flag in ("--serve-trace-out", "--twin-trace",
                 "--twin-replicas", "--twin-out"):
        assert flag in vf, flag


def test_fleet_flags_wired():
    """The ISSUE-18 fleet knobs flow parse_args -> FFConfig via
    build_parser only: replica count, colocated/disagg topology split,
    prefill-pool size, router policy (choices-validated), and the rolling
    rollout's rollback burn ceiling. All default to the single-replica
    colocated fleet — behaviorally identical to the pre-fleet scheduler."""
    import pytest

    from flexflow_tpu.config import FFConfig as Cfg

    cfg = Cfg.parse_args(["--serve-replicas", "4",
                          "--serve-fleet-topology", "disagg",
                          "--serve-prefill-replicas", "2",
                          "--serve-router", "round_robin",
                          "--serve-rollout-burn-max", "2.0"])
    assert cfg.serve_replicas == 4
    assert cfg.serve_fleet_topology == "disagg"
    assert cfg.serve_prefill_replicas == 2
    assert cfg.serve_router == "round_robin"
    assert cfg.serve_rollout_burn_max == 2.0
    d = Cfg()
    assert d.serve_replicas == 1                  # one replica = no fleet
    assert d.serve_fleet_topology == "colocated"  # every replica does both
    assert d.serve_prefill_replicas == 1
    assert d.serve_router == "least_loaded"
    assert d.serve_rollout_burn_max == 0.0        # 0 = never roll back
    with pytest.raises(SystemExit):
        Cfg.parse_args(["--serve-fleet-topology", "sharded"])
    with pytest.raises(SystemExit):
        Cfg.parse_args(["--serve-router", "random"])
    vf = Cfg.launcher_value_flags()
    for flag in ("--serve-replicas", "--serve-fleet-topology",
                 "--serve-prefill-replicas", "--serve-router",
                 "--serve-rollout-burn-max"):
        assert flag in vf, flag


def test_fault_plan_flag_arms_injector(devices):
    """--fault-plan reaches runtime/faults.py at compile time (the same
    hook order as --telemetry-dir): a bad plan fails loud at compile, a
    good one arms the named site."""
    from flexflow_tpu.runtime import faults

    try:
        m = _tiny(FFConfig(batch_size=16, only_data_parallel=True,
                           fault_plan="checkpoint/write@2",
                           log_level="warning"))
        m.compile(SGDOptimizer(lr=0.01),
                  loss_type="sparse_categorical_crossentropy", metrics=[])
        assert faults.active()
        with pytest.raises(ValueError, match="unknown fault site"):
            _tiny(FFConfig(batch_size=16, only_data_parallel=True,
                           fault_plan="bogus/site@1",
                           log_level="warning")).compile(
                SGDOptimizer(lr=0.01),
                loss_type="sparse_categorical_crossentropy", metrics=[])
    finally:
        faults.clear()


def test_multi_node_mesh_shards_batch_over_node_axis(devices):
    """--nodes must buy sample parallelism: the batch dim rides BOTH the
    node (DCN) axis and the intra-node data axis (round-4 review fix — a
    replicated node axis would make --nodes 2 a no-op)."""
    cfg = FFConfig(batch_size=16, num_nodes=2, workers_per_node=4,
                   only_data_parallel=True)
    m = _tiny(cfg)
    cm = m.compile(SGDOptimizer(lr=0.01),
                   loss_type="sparse_categorical_crossentropy", metrics=[])
    dims = cm.strategy.input_shardings["x"]
    assert dims[0] in (("node", "data"), ["node", "data"]), dims
    pv = cm.parallel_view("fc")
    assert pv.dims[0].degree == 8  # 2 nodes x 4 workers all split samples
    cm.init(seed=0)
    out = cm.forward(np.zeros((16, 8), np.float32))
    assert np.asarray(out).shape == (16, 4)


def test_remat_and_fused_kernel_flags_wired():
    """The ISSUE-12 MFU knobs flow parse_args -> FFConfig via
    build_parser only (launcher value-flag coverage is derived):
    --remat-search/--remat-policies select the searched-remat dimension,
    --fused-loss/--fused-optimizer gate the pallas fusion suite, and the
    deprecated --remat alias survives but cannot combine with the
    search."""
    from flexflow_tpu.config import FFConfig as Cfg

    cfg = Cfg.parse_args(["--remat-search", "--remat-policies",
                          "none,dots", "--fused-loss", "on",
                          "--fused-optimizer", "off"])
    assert cfg.remat_search is True
    assert cfg.remat_policies == "none,dots"
    assert cfg.remat_policy_list() == ("none", "dots")
    assert cfg.fused_loss == "on"
    assert cfg.fused_optimizer == "off"
    # defaults: remat fully off, fused kernels in auto mode
    d = Cfg()
    assert (d.remat, d.remat_search) == (False, False)
    assert d.remat_policy_list() == ("none", "dots", "full")
    assert (d.fused_loss, d.fused_optimizer) == ("auto", "auto")
    # deprecated alias still parses on its own
    assert Cfg.parse_args(["--remat"]).remat is True
    # ...but contradicts the searched dimension, loudly
    with pytest.raises(ValueError, match="contradicts"):
        Cfg.parse_args(["--remat", "--remat-search"])
    # unknown policy names fail at construction, not deep in the DP
    with pytest.raises(ValueError, match="unknown remat policies"):
        Cfg.parse_args(["--remat-policies", "none,sometimes"])
    # mode flags are choice-constrained
    with pytest.raises(SystemExit):
        Cfg.parse_args(["--fused-loss", "maybe"])
    vf = Cfg.launcher_value_flags()
    for flag in ("--remat-policies", "--fused-loss", "--fused-optimizer"):
        assert flag in vf, flag
