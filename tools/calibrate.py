"""Cost-model calibration harness: analytic vs measured vs whole-step time.

Reference analog: the simulator's fidelity contract — per-op costs come from
real on-device microbenchmarks (Op::inner_measure_operator_cost,
/root/reference/src/runtime/model.cu:38-74) and are trusted to predict the
iteration time. SURVEY §7 hard part #1 is the TPU version of that trap: XLA
fuses across ops, so isolated per-op measurements over-predict the fused
whole step. This harness quantifies that error per workload:

  analytic  = Σ per-layer analytic roofline op_time under the DP strategy
  measured  = Σ per-layer MeasuredCost op_time (isolated jit per op)
  step      = real wall-clock train_step time (fit-path, fwd+bwd+update)

and writes the table to CALIBRATION.md. Run on the CPU mesh (cpu-sim
coefficients) or a real chip:

    python tools/calibrate.py [--out CALIBRATION.md]
"""

from __future__ import annotations

import argparse
import sys
import time


def _workloads():
    import numpy as np

    from flexflow_tpu import FFConfig, FFModel

    def mlp():
        m = FFModel(FFConfig(batch_size=64, only_data_parallel=True))
        x = m.create_tensor([64, 512], name="x")
        h = m.dense(x, 1024, activation="relu", name="fc1")
        h = m.dense(h, 1024, activation="relu", name="fc2")
        m.dense(h, 10, name="head")
        y = np.random.default_rng(0).integers(0, 10, size=(64,)).astype(np.int32)
        return m, np.random.default_rng(1).normal(size=(64, 512)).astype(np.float32), y

    def cnn():
        m = FFModel(FFConfig(batch_size=32, only_data_parallel=True))
        x = m.create_tensor([32, 3, 32, 32], name="x")
        h = m.conv2d(x, 32, 3, 3, padding_h=1, padding_w=1, activation="relu", name="c1")
        h = m.pool2d(h, 2, 2, 2, 2, name="p1")
        h = m.conv2d(h, 64, 3, 3, padding_h=1, padding_w=1, activation="relu", name="c2")
        h = m.pool2d(h, 2, 2, 2, 2, name="p2")
        h = m.flat(h, name="flat")
        m.dense(h, 10, name="head")
        y = np.random.default_rng(0).integers(0, 10, size=(32,)).astype(np.int32)
        return m, np.random.default_rng(1).normal(size=(32, 3, 32, 32)).astype(np.float32), y

    def gpt2_block():
        from flexflow_tpu.models import GPT2Config, build_gpt2

        cfg = GPT2Config(vocab=2048, seq=64, d_model=256, heads=4, layers=1,
                         dropout=0.0)
        m = FFModel(FFConfig(batch_size=4, only_data_parallel=True))
        build_gpt2(m, cfg, batch=4)
        rng = np.random.default_rng(0)
        ids = rng.integers(0, cfg.vocab, size=(4, 64)).astype(np.int32)
        pos = np.tile(np.arange(64, dtype=np.int32), (4, 1))
        lab = rng.integers(0, cfg.vocab, size=(4, 64)).astype(np.int32)
        return m, [ids, pos], lab

    def _gpt2_medium(layers):
        # PRODUCTION shapes (VERDICT r4 weak #2: the toy rows above are in
        # the dispatch-overhead regime; the shapes the search actually ranks
        # are b8/seq1024 at d_model 1024 — the BENCH ~200 ms step)
        from flexflow_tpu.models import GPT2Config, build_gpt2

        cfg = GPT2Config.medium()
        cfg.layers = layers
        cfg.dropout = 0.0
        m = FFModel(FFConfig(batch_size=8, compute_dtype="bfloat16",
                             only_data_parallel=True))
        build_gpt2(m, cfg, batch=8)
        rng = np.random.default_rng(0)
        ids = rng.integers(0, cfg.vocab, size=(8, cfg.seq)).astype(np.int32)
        pos = np.tile(np.arange(cfg.seq, dtype=np.int32), (8, 1))
        lab = rng.integers(0, cfg.vocab, size=(8, cfg.seq)).astype(np.int32)
        return m, [ids, pos], lab

    return [("mlp", mlp), ("cnn", cnn), ("gpt2_block", gpt2_block),
            # one production-width block, and the full ~200ms-step model
            ("gpt2_medium_block", lambda: _gpt2_medium(1)),
            ("gpt2_medium", lambda: _gpt2_medium(24))]


def calibrate(names=None):
    import jax
    import numpy as np

    from flexflow_tpu import SGDOptimizer
    from flexflow_tpu.parallel.machine import MachineSpec
    from flexflow_tpu.search.dp import search_graph
    from flexflow_tpu.search.measure import MeasuredCost

    machine = MachineSpec.detect()
    rows = []
    for name, builder in _workloads():
        if names and name not in names:
            continue
        model, x, y = builder()
        r = search_graph(model, machine, enable_parameter=False,
                         enable_attribute=False)
        analytic = sum(r.choices[l.name].op_time(l, machine)
                       for l in model.layers)
        # event-driven replay of the same strategy (search/simulator.py):
        # same per-op costs scheduled on per-stream timelines + optimizer
        # update tasks — the C12 fidelity layer calibrated here against the
        # real fused step
        from flexflow_tpu.search.simulator import simulate_strategy

        simulated = simulate_strategy(model, r.choices, machine).makespan
        mc = MeasuredCost(machine, repeats=5, warmup=2)
        measured = sum(mc.op_time(l, r.choices[l.name]) for l in model.layers)

        loss_t = ("sparse_categorical_crossentropy"
                  if np.asarray(y).dtype == np.int32 else "mean_squared_error")
        cm = model.compile(SGDOptimizer(lr=0.01), loss_type=loss_t, metrics=[])
        cm.init(seed=0)
        xs = x if isinstance(x, list) else [x]
        dx = [jax.device_put(a) for a in xs]
        dy = jax.device_put(y)
        key = jax.random.PRNGKey(0)
        # warmup/compile, then best-of-3 timed runs of 5 chained steps.
        # float(loss) host fetch: block_until_ready alone is not a reliable
        # barrier under the axon tunnel (bench.py round-1 postmortem)
        p, o, s, loss, _ = cm.train_step(cm.params, cm.opt_state, cm.state,
                                         dx, dy, key)
        jax.block_until_ready((loss, p, o))
        float(loss)
        # subtract the synchronizing fetch's own round trip (mc measured it)
        floor = mc._fetch_floor()
        best = float("inf")
        for rep in range(3):
            t0 = time.perf_counter()
            for i in range(5):
                p, o, s, loss, _ = cm.train_step(p, o, s, dx, dy,
                                                 jax.random.fold_in(key, i))
            jax.block_until_ready((loss, p, o))
            float(loss)
            # clamp: sub-ms toy steps are UNMEASURABLE through the axon
            # tunnel (per-dispatch latency ~20-30 ms dwarfs device work);
            # their rows document the dispatch-bound regime, the
            # production-scale rows are the calibration that matters
            best = min(best, max(1e-6, time.perf_counter() - t0 - floor) / 5)
        # through the tunnel, a 5-step loop whose device work is below the
        # ~75 ms fetch RTT hides entirely inside the final fetch — the
        # floor-subtracted time is then noise (can clamp to ~0 and produce
        # absurd ratios). Mark such rows unreliable instead of publishing
        # junk; the production-scale rows carry the fidelity claim.
        reliable = (jax.default_backend() == "cpu") or (5 * best > 0.5 * floor
                                                        and best > 2e-3)
        rows.append({
            "workload": name,
            "analytic_ms": analytic * 1e3,
            "simulated_ms": simulated * 1e3,
            "measured_ms": measured * 1e3,
            "step_ms": best * 1e3,
            "reliable": reliable,
            "analytic_over_step": analytic / best,
            "simulated_over_step": simulated / best,
            "measured_over_step": measured / best,
        })
    return rows, machine


def measure_overlap():
    """Probe whether an independent VPU reduction hides behind an MXU matmul
    chain in one program. FINDING (r5, after fixing a bf16 overflow that
    corrupted earlier readings): it does NOT — three clean runs measure
    overlap 0.00, t_both = t_mm + t_mem. A TPU core executes compute HLOs
    serially; the VPU reduction is COMPUTE, so this single-chip proxy can
    only ever observe compute/compute serialization. Real collectives are
    ICI/HBM DMAs, which XLA's async scheduler genuinely overlaps with
    compute — but that cannot be observed on one chip with a compute proxy.
    `MachineSpec.overlap_frac = 0.7` therefore rests on (a) XLA's async
    collective-permute/all-reduce DMA architecture and (b) the whole-model
    scheduling calibration (simulated/step ~0.94, the gpt2_medium row),
    not on this probe. Kept as an honest negative control."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from flexflow_tpu.search.measure import MeasuredCost

    rng = np.random.default_rng(0)
    a = jnp.asarray(rng.normal(size=(4096, 4096)), jnp.bfloat16)
    w = jnp.asarray(rng.normal(size=(4096, 4096)), jnp.bfloat16)
    big = jnp.asarray(rng.normal(size=(64 * 1024 * 1024,)), jnp.float32)

    # every fn returns a tensor FED BACK as the next rep's input: the
    # dependency chain forces the device to serialize reps, so total device
    # work can be made >> the ~75 ms tunnel fetch RTT. Without chaining,
    # async dispatch hides all sub-RTT work inside the final fetch and the
    # floor subtraction measures ~0 (the r5 degenerate-overlap postmortem).
    def mm(a, w):
        x = a
        for _ in range(8):
            # rescale INSIDE the loop: each 4096-deep bf16 matmul grows
            # element magnitude ~sqrt(4096)=64x, so a post-loop rescale
            # would overflow the fed-back state to inf within a few reps
            x = (x @ w) * (1.0 / 64.0)
        return x

    def mem(b):
        return b * 1.0001

    f_mm = jax.jit(mm)
    f_mem = jax.jit(mem)
    f_both = jax.jit(lambda a, w, b: (mm(a, w), mem(b)))

    from flexflow_tpu.parallel.machine import MachineSpec

    mc = MeasuredCost(MachineSpec.detect())
    floor = mc._fetch_floor()
    sync = MeasuredCost._host_sync

    def t_chained(step, state, reps):
        state = step(state)
        sync(state)
        t0 = time.perf_counter()
        for _ in range(reps):
            state = step(state)
        sync(state)
        return max(0.0, time.perf_counter() - t0 - floor) / reps

    # reps sized so each loop's device work is ~150-300 ms >> RTT
    t_mm = t_chained(lambda s: f_mm(s, w), a, 30)
    t_mem = t_chained(f_mem, big, 450)
    t_both = t_chained(lambda s: f_both(s[0], w, s[1]), (a, big), 30)
    if t_mm > 1e-4 and t_mem > 1e-4 and t_both > 1e-4:
        frac = (t_mm + t_mem - t_both) / max(1e-9, min(t_mm, t_mem))
        return {"t_mm_ms": t_mm * 1e3, "t_mem_ms": t_mem * 1e3,
                "t_both_ms": t_both * 1e3,
                "overlap_frac": float(np.clip(frac, 0.0, 1.0))}
    # degenerate (a kernel still timed at ~0): report unmeasurable rather
    # than writing a fake 0.0 into the calibration artifact
    return {"t_mm_ms": t_mm * 1e3, "t_mem_ms": t_mem * 1e3,
            "t_both_ms": t_both * 1e3, "overlap_frac": None}


def write_report(rows, machine, path="CALIBRATION.md", overlap=None):
    import jax

    lines = [
        "# Cost-model calibration",
        "",
        f"Backend: `{jax.default_backend()}` ({len(jax.devices())} device(s)); "
        f"machine model chip: `{machine.chip}`. Produced by "
        "`python tools/calibrate.py`.",
        "",
        "Columns: per-layer **analytic** roofline sum and per-layer isolated "
        "**measured** sum vs the real fused whole **step** (fwd+bwd+update), "
        "all under the data-parallel strategy. Ratios are predicted/actual — "
        "1.0 is perfect; the known bias (SURVEY §7 hard part #1) is that "
        "isolated measurement over-predicts what XLA fuses, while the "
        "analytic model targets the chip's steady-state rates and "
        "under-predicts small-shape dispatch overheads on CPU.",
        "",
        "**simulated** is the event-driven task-graph replay of the same "
        "strategy (search/simulator.py): identical per-op costs scheduled "
        "on per-stream timelines plus optimizer-update tasks the additive "
        "sum omits.",
        "",
        "| workload | analytic (ms) | simulated (ms) | measured-sum (ms) | "
        "whole step (ms) | analytic/step | simulated/step | measured/step |",
        "|---|---|---|---|---|---|---|---|",
    ]
    for r in rows:
        if r.get("reliable", True):
            lines.append(
                f"| {r['workload']} | {r['analytic_ms']:.3f} | "
                f"{r['simulated_ms']:.3f} | "
                f"{r['measured_ms']:.3f} | {r['step_ms']:.3f} | "
                f"{r['analytic_over_step']:.3f} | "
                f"{r['simulated_over_step']:.3f} | "
                f"{r['measured_over_step']:.3f} |")
        else:
            lines.append(
                f"| {r['workload']} | {r['analytic_ms']:.3f} | "
                f"{r['simulated_ms']:.3f} | "
                f"{r['measured_ms']:.3f} | sub-RTT | n/m | n/m | n/m |")
    lines.append("")
    if any(not r.get("reliable", True) for r in rows):
        lines.append(
            "`sub-RTT` rows: the 5-step timing loop's device work is below "
            "the ~75 ms tunnel fetch round-trip, so the whole loop hides "
            "inside the final fetch and the floor-subtracted time is noise "
            "— unmeasurable through this transport, not actually free.")
    lines.append("")
    if overlap is not None:
        lines += [
            "## Compute/compute serialization probe (overlap_frac context)",
            "",
            "An 8-matmul MXU chain and an independent 256 MB VPU reduction, "
            "timed separately and fused into one program. Clean-data runs "
            "measure ~0 overlap — a TPU core executes compute HLOs "
            "serially, so this single-chip proxy observes compute/compute "
            "serialization, NOT collective/compute overlap (collectives "
            "are async ICI/HBM DMAs, which DO hide behind compute; "
            "unobservable on one chip). `MachineSpec.overlap_frac = 0.7` "
            "rests on the async-DMA architecture plus the whole-model "
            "scheduling calibration above (simulated/step), with this "
            "probe as the negative control.",
            "",
            f"- t(matmuls) = {overlap['t_mm_ms']:.3f} ms, "
            f"t(reduction) = {overlap['t_mem_ms']:.3f} ms, "
            f"t(both, one jit) = {overlap['t_both_ms']:.3f} ms",
            (f"- **measured overlap_frac = {overlap['overlap_frac']:.2f}** "
             "(search/dp.py hides up to this fraction of a consumer "
             "segment's pure-compute time worth of collective cost)"
             if overlap["overlap_frac"] is not None else
             "- **measurement degenerate this run** (a kernel timed at ~0 "
             "through the tunnel-fetch noise floor); the default "
             "overlap_frac=0.7 stands on its documented rationale"),
            "",
        ]
    with open(path, "w") as f:
        f.write("\n".join(lines))
    return path


if __name__ == "__main__":
    import os

    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default="CALIBRATION.md")
    ap.add_argument("--workloads", default="", help="comma-separated subset")
    args = ap.parse_args()
    names = [w for w in args.workloads.split(",") if w] or None
    rows, machine = calibrate(names)
    overlap = measure_overlap()
    path = write_report(rows, machine, args.out, overlap=overlap)
    for r in rows:
        print(r)
    print(overlap)
    print(f"wrote {path}", file=sys.stderr)
