"""Fused optimizer moment update as a pallas TPU kernel.

Capability replaced: the optax update chain in the train step
(compile.py apply_update -> tx.update). optax expresses Adam as a series of
tree_maps — XLA usually fuses them, but the moment update is memory-bound
either way and perf_probe prices it at ~12 ms of a GPT-2-medium step; one
kernel per param block reads (g, mu, nu, p) and writes (update, mu', nu')
in a single pass over HBM, with all arithmetic in f32 and the moments
stored back in the optimizer's state dtype (f32 or bf16, mirroring
optimizers._scale_by_adam_lowp).

The fused path REPLACES only the arithmetic, never the state structure:
`plan_for(optimizer)` recognizes the repo's Adam/SGD configurations (an
unrecognized optimizer silently falls back to tx.update — the "auto" mode
contract), and `fused_update` locates the ScaleByAdamState / TraceState
node inside the existing optax chain state and rebuilds it in place, so
checkpoints, ZeRO's scattered-moment sharding constraints, and state
inspection all see the exact optax layout. Sharding composes the same way
tx.update does: the caller constrains grads to the moment layout before and
the opt state after (compile.py), and the kernel is purely elementwise, so
under ZeRO each device updates only its moment shard.

Numerics mirror optax exactly: same moment recurrences, same
`1 - beta**count` bias-correction expressions, decoupled weight decay
applied after the Adam term, `scale(-lr)` last.
"""

from __future__ import annotations

import functools
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import optax
from jax.experimental import pallas as pl

_LANES = 128
_BLOCK_ROWS = 256


def _interpret() -> bool:
    return jax.default_backend() == "cpu"


def _params():
    from jax.experimental.pallas import tpu as pltpu

    cls = getattr(pltpu, "CompilerParams", None) or \
        getattr(pltpu, "TPUCompilerParams")
    return cls(dimension_semantics=("parallel",))


# ----------------------------------------------------------------- planning
def plan_for(optimizer) -> Optional[Dict[str, Any]]:
    """Recognize the optimizer's update math, or None (caller falls back to
    tx.update). Import is local to avoid a kernels <-> optimizers cycle."""
    from flexflow_tpu.optimizers import AdamOptimizer, SGDOptimizer

    if type(optimizer) is AdamOptimizer:
        sd = optimizer.state_dtype or "float32"
        if sd not in ("float32", "bfloat16"):
            return None
        return {"kind": "adam", "lr": float(optimizer.alpha),
                "b1": float(optimizer.beta1), "b2": float(optimizer.beta2),
                "eps": float(optimizer.epsilon),
                "wd": float(optimizer.weight_decay),
                "state_dtype": jnp.dtype(sd)}
    if type(optimizer) is SGDOptimizer:
        return {"kind": "sgd", "lr": float(optimizer.lr),
                "momentum": float(optimizer.momentum),
                "nesterov": bool(optimizer.nesterov),
                "wd": float(optimizer.weight_decay)}
    return None


# ----------------------------------------------------- leaf padding helpers
def _pad2d(a):
    """Flatten a leaf to (rows, 128) with rows a multiple of the block."""
    size = a.size
    rows = -(-size // _LANES)
    br = rows if rows <= _BLOCK_ROWS else _BLOCK_ROWS
    rows_p = -(-rows // br) * br
    flat = a.reshape(-1)
    pad = rows_p * _LANES - size
    if pad:
        flat = jnp.pad(flat, (0, pad))
    return flat.reshape(rows_p, _LANES), br


def _unpad(a2, shape, size, dtype=None):
    out = a2.reshape(-1)[:size].reshape(shape)
    return out.astype(dtype) if dtype is not None else out


# ------------------------------------------------------------------ kernels
def _adam_kernel(g_ref, mu_ref, nu_ref, p_ref, sc_ref,
                 upd_ref, mu_o_ref, nu_o_ref, *, b1, b2, eps, lr, wd):
    g = g_ref[...].astype(jnp.float32)
    mu = mu_ref[...].astype(jnp.float32)
    nu = nu_ref[...].astype(jnp.float32)
    bc1 = sc_ref[0, 0]                 # 1 - b1**count (f32, optax's exact
    bc2 = sc_ref[0, 1]                 # bias-correction denominators)
    mu_n = b1 * mu + (1.0 - b1) * g
    nu_n = b2 * nu + (1.0 - b2) * g * g
    u = (mu_n / bc1) / (jnp.sqrt(nu_n / bc2) + eps)
    if wd:
        u = u + wd * p_ref[...].astype(jnp.float32)
    upd_ref[...] = (-lr * u).astype(upd_ref.dtype)
    mu_o_ref[...] = mu_n.astype(mu_o_ref.dtype)
    nu_o_ref[...] = nu_n.astype(nu_o_ref.dtype)


def _sgd_kernel(g_ref, t_ref, p_ref, upd_ref, t_o_ref,
                *, momentum, nesterov, lr, wd):
    g = g_ref[...].astype(jnp.float32)
    if wd:
        g = g + wd * p_ref[...].astype(jnp.float32)
    t_n = g + momentum * t_ref[...].astype(jnp.float32)
    u = g + momentum * t_n if nesterov else t_n
    upd_ref[...] = (-lr * u).astype(upd_ref.dtype)
    t_o_ref[...] = t_n.astype(t_o_ref.dtype)


def _sgd_plain_kernel(g_ref, p_ref, upd_ref, *, lr, wd):
    g = g_ref[...].astype(jnp.float32)
    if wd:
        g = g + wd * p_ref[...].astype(jnp.float32)
    upd_ref[...] = (-lr * g).astype(upd_ref.dtype)


def _row_spec(br):
    return pl.BlockSpec((br, _LANES), lambda i: (i, 0))


def _scalar_spec():
    return pl.BlockSpec((1, _LANES), lambda i: (0, 0))


def _adam_leaf(g, mu, nu, p, sc, plan):
    g2, br = _pad2d(g)
    mu2, _ = _pad2d(mu)
    nu2, _ = _pad2d(nu)
    p2, _ = _pad2d(p)
    sd = plan["state_dtype"]
    kernel = functools.partial(_adam_kernel, b1=plan["b1"], b2=plan["b2"],
                               eps=plan["eps"], lr=plan["lr"], wd=plan["wd"])
    upd2, mu_o2, nu_o2 = pl.pallas_call(
        kernel,
        grid=(g2.shape[0] // br,),
        in_specs=[_row_spec(br)] * 4 + [_scalar_spec()],
        out_specs=[_row_spec(br)] * 3,
        out_shape=[jax.ShapeDtypeStruct(g2.shape, g.dtype),
                   jax.ShapeDtypeStruct(g2.shape, sd),
                   jax.ShapeDtypeStruct(g2.shape, sd)],
        compiler_params=_params(),
        interpret=_interpret(),
    )(g2, mu2, nu2, p2, sc)
    return (_unpad(upd2, g.shape, g.size),
            _unpad(mu_o2, g.shape, g.size),
            _unpad(nu_o2, g.shape, g.size))


def _sgd_leaf(g, t, p, plan):
    g2, br = _pad2d(g)
    p2, _ = _pad2d(p)
    common = dict(compiler_params=_params(), interpret=_interpret())
    if t is None:
        upd2 = pl.pallas_call(
            functools.partial(_sgd_plain_kernel, lr=plan["lr"],
                              wd=plan["wd"]),
            grid=(g2.shape[0] // br,),
            in_specs=[_row_spec(br)] * 2,
            out_specs=_row_spec(br),
            out_shape=jax.ShapeDtypeStruct(g2.shape, g.dtype),
            **common,
        )(g2, p2)
        return _unpad(upd2, g.shape, g.size), None
    t2, _ = _pad2d(t)
    upd2, t_o2 = pl.pallas_call(
        functools.partial(_sgd_kernel, momentum=plan["momentum"],
                          nesterov=plan["nesterov"], lr=plan["lr"],
                          wd=plan["wd"]),
        grid=(g2.shape[0] // br,),
        in_specs=[_row_spec(br)] * 3,
        out_specs=[_row_spec(br)] * 2,
        out_shape=[jax.ShapeDtypeStruct(g2.shape, g.dtype),
                   jax.ShapeDtypeStruct(g2.shape, t.dtype)],
        **common,
    )(g2, t2, p2)
    return _unpad(upd2, g.shape, g.size), _unpad(t_o2, g.shape, g.size)


# ----------------------------------------------- state-structure surgery
def _find_node(state, cls):
    """Depth-first search for the unique `cls` node in an optax chain state.
    Returns the node or None."""
    if isinstance(state, cls):
        return state
    if isinstance(state, (tuple, list)) and not hasattr(state, "_fields"):
        for s in state:
            found = _find_node(s, cls)
            if found is not None:
                return found
    return None


def _replace_node(state, cls, new):
    if isinstance(state, cls):
        return new
    if isinstance(state, (tuple, list)) and not hasattr(state, "_fields"):
        return type(state)(_replace_node(s, cls, new) for s in state)
    return state


def _tree3(out_tree, grads):
    """Transpose a tree-of-3-tuples into 3 trees."""
    outer = jax.tree_util.tree_structure(grads)
    inner = jax.tree_util.tree_structure((0, 0, 0))
    return jax.tree_util.tree_transpose(outer, inner, out_tree)


# ------------------------------------------------------------------ update
def fused_update(plan: Dict[str, Any], grads, opt_state, params
                 ) -> Optional[Tuple[Any, Any]]:
    """tx.update replacement: (updates, new_opt_state), or None when the
    live state doesn't match the plan (caller falls back to tx.update)."""
    tm = jax.tree_util.tree_map
    if plan["kind"] == "adam":
        s = _find_node(opt_state, optax.ScaleByAdamState)
        if s is None:
            return None
        count = s.count + 1
        c32 = count.astype(jnp.float32)
        bc1 = 1.0 - plan["b1"] ** c32
        bc2 = 1.0 - plan["b2"] ** c32
        sc = jnp.zeros((1, _LANES), jnp.float32)
        sc = sc.at[0, 0].set(bc1).at[0, 1].set(bc2)
        out = tm(lambda g, m, n, p: _adam_leaf(g, m, n, p, sc, plan),
                 grads, s.mu, s.nu, params)
        upd, mu, nu = _tree3(out, grads)
        new_s = optax.ScaleByAdamState(count=count, mu=mu, nu=nu)
        return upd, _replace_node(opt_state, optax.ScaleByAdamState, new_s)
    if plan["kind"] == "sgd":
        if plan["momentum"]:
            s = _find_node(opt_state, optax.TraceState)
            if s is None:
                return None
            out = tm(lambda g, t, p: _sgd_leaf(g, t, p, plan),
                     grads, s.trace, params)
            outer = jax.tree_util.tree_structure(grads)
            inner = jax.tree_util.tree_structure((0, 0))
            upd, trace = jax.tree_util.tree_transpose(outer, inner, out)
            new_s = optax.TraceState(trace=trace)
            return upd, _replace_node(opt_state, optax.TraceState, new_s)
        upd = tm(lambda g, p: _sgd_leaf(g, None, p, plan)[0], grads, params)
        return upd, opt_state
    return None
