#!/usr/bin/env python
"""Refit the learned cost model from a telemetry dir (the self-calibration
loop, ISSUE 14).

One command closes the loop the `[drift]` report opens: fold a run's
telemetry through tools/span_dataset.py into the per-op corpus, retrain
flexflow_tpu/search/learned_cost.py's per-op-kind ridge on it, and write the
refreshed model (atomic replace) to the resolved model path. The strategy
cache keys on the model file's content hash (strategy_cache.
learned_fingerprint), so the refit automatically invalidates every strategy
the stale model priced — the next compile re-searches with fresh prices.

`fit(..., verbose)`'s drift summary points here when predictions drift >3x,
and `--auto-refit` makes compile.py call `refit()` at fit end without the
operator in the loop (flexflow_tpu/search/learned_cost.auto_refit).

Usage:
    python tools/refit_cost_model.py <telemetry-dir> [--out model.json]
                                     [--corpus corpus.jsonl]
    python tools/refit_cost_model.py --check   # CI smoke
"""

from __future__ import annotations

import argparse
import os
import sys
from typing import Any, Dict, List, Optional

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import span_dataset  # noqa: E402  (tools/ sibling, not a package)


def default_model_path() -> str:
    from flexflow_tpu.search.learned_cost import resolve_model_path

    class _Cfg:  # resolve with no CLI override: env var or ~/.cache default
        cost_model_path = ""

    return resolve_model_path(_Cfg())


def refit(telemetry_path: str, model_path: Optional[str] = None,
          corpus_path: Optional[str] = None, quiet: bool = True
          ) -> Optional[Dict[str, Any]]:
    """telemetry dir -> (merged) corpus -> trained model file.

    Returns {"rows", "kinds", "fingerprint", "path", "corpus"} on success,
    None when the telemetry yields no usable corpus rows (nothing is
    written — an empty refit must not clobber a working model)."""
    from flexflow_tpu.search import learned_cost as lc

    rows: List[Dict[str, Any]] = span_dataset.collect_rows(telemetry_path)
    if corpus_path:
        rows = span_dataset.merge_rows(
            span_dataset.read_jsonl(corpus_path), rows)
    usable = [r for r in rows
              if (r.get("measured_s") or {}).get("mean")]
    if not usable:
        if not quiet:
            print(f"no measured corpus rows under {telemetry_path}; "
                  "model left unchanged")
        return None
    if corpus_path:
        span_dataset.write_jsonl(rows, corpus_path)
    model = lc.train(rows)
    path = model_path or default_model_path()
    fp = model.save(path)
    info = {
        "rows": len(usable),
        "kinds": list(model.meta.get("kinds_fitted") or []),
        "fingerprint": fp,
        "path": path,
        "corpus": corpus_path,
    }
    if not quiet:
        print(f"refit: {info['rows']} rows -> {len(info['kinds'])} op-kind "
              f"submodels, model {fp} -> {path}")
    return info


# --------------------------------------------------------------- check mode
def _check() -> int:
    """CI smoke: profiled tiny fit -> refit -> loadable model that prices
    a corpus row, and whose fingerprint changes when the corpus changes
    (the cache-invalidation edge)."""
    import tempfile

    import numpy as np

    from flexflow_tpu import FFConfig, FFModel, SGDOptimizer, telemetry
    from flexflow_tpu.search import learned_cost as lc

    with tempfile.TemporaryDirectory() as td:
        tdir = os.path.join(td, "telemetry")
        cfg = FFConfig(batch_size=16, only_data_parallel=True,
                       telemetry_dir=tdir, profile_ops=True,
                       log_level="warning")
        m = FFModel(cfg)
        x = m.create_tensor([16, 8], name="x")
        m.dense(m.dense(x, 16, activation="relu", name="fc1"), 4, name="fc2")
        cm = m.compile(SGDOptimizer(lr=0.01),
                       loss_type="sparse_categorical_crossentropy",
                       metrics=[])
        cm.init(seed=0)
        rng = np.random.default_rng(0)
        xv = rng.normal(size=(64, 8)).astype(np.float32)
        yv = rng.integers(0, 4, size=(64,)).astype(np.int32)
        cm.fit(xv, yv, epochs=2, verbose=False)
        telemetry.flush()
        mpath = os.path.join(td, "model.json")
        cpath = os.path.join(td, "corpus.jsonl")
        info = refit(tdir, model_path=mpath, corpus_path=cpath)
        telemetry.shutdown()
        assert info is not None and info["rows"] > 0, info
        model = lc.LearnedCostModel.load(mpath)
        assert model.fingerprint == info["fingerprint"]
        row = span_dataset.read_jsonl(cpath)[0]
        t = model.predict_row(row)
        assert t is not None and t > 0, (row["key"], t)
        # second refit folds the same telemetry in again -> pooled counts
        # change the corpus -> the content fingerprint must move (this is
        # what invalidates the strategy cache)
        info2 = refit(tdir, model_path=mpath, corpus_path=cpath)
        assert info2 is not None
    print("refit_cost_model --check OK")
    return 0


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        "refit_cost_model", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("path", nargs="?", default=None,
                    help="telemetry dir or one telemetry-*.jsonl file")
    ap.add_argument("--out", default=None,
                    help="model JSON path (default: $FF_COST_MODEL_PATH or "
                         "~/.cache/flexflow_tpu/cost_model.json)")
    ap.add_argument("--corpus", default=None,
                    help="corpus JSONL to fold through and keep updated "
                         "(default <dir>/op_corpus.jsonl)")
    ap.add_argument("--check", action="store_true",
                    help="CI smoke: profiled fit -> refit -> validate")
    args = ap.parse_args(argv)
    if args.check:
        return _check()
    if not args.path:
        ap.error("path required (or --check)")
    corpus = args.corpus
    if corpus is None:
        base = args.path if os.path.isdir(args.path) \
            else os.path.dirname(args.path) or "."
        corpus = os.path.join(base, "op_corpus.jsonl")
    info = refit(args.path, model_path=args.out, corpus_path=corpus,
                 quiet=False)
    return 0 if info is not None else 1


if __name__ == "__main__":
    sys.exit(main())
