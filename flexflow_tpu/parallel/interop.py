"""Inter-operator (branch) placement on disjoint device subsets.

Reference analog: Unity's nonsequence splits — VERTICAL (split nodes) /
HORIZONTAL (split workers) in `find_optimal_nonsequence_graph_time`
(/root/reference/src/runtime/graph.cc:187-321): parallel branches of the PCG
are placed on disjoint subsets of the machine and run concurrently.

TPU-native formulation. GSPMD alone cannot express "op A on chips 0..3, op B
on chips 4..7": an op whose operands are replicated is computed redundantly
on EVERY device of the mesh, so branch placement buys nothing. The disjoint
placement needs runtime control flow over the device id, which is exactly
`shard_map` + `lax.switch(lax.axis_index(axis), ...)`:

  - the mesh axis chosen for inter-op placement has one index per branch;
  - inside the shard_map body each device group executes ONLY its branch
    (switch executes a single arm at runtime — the other branches are
    compiled but not run);
  - the body emits the branch output under a stacked leading dim sharded
    over the axis; the join (sum / feature concat) happens OUTSIDE the
    shard_map, where XLA GSPMD emits the collective;
  - other mesh axes (data) keep sharding the batch dim as usual, so inter-op
    placement composes with data parallelism.

Weight residency — two regimes:

  - CONGRUENT branches (identical sub-layer names + weight shapes, the case
    the search targets): weights are stored STACKED, one (k, ...) array per
    sub-weight, sharded over the placement axis (`place_branches_stacked`).
    Each device holds ONLY its branch's weights — memory, weight streaming
    and gradient all-reduce all divide by k. This is the owned-device
    residency of the reference's resource division (graph.cc:267-321).
  - heterogeneous branches: weights are passed replicated (every chip holds
    every branch's weights — the memory price of switch-based placement;
    the search's memory accounting charges the full union).

Autodiff: jax (≤0.9) mis-transposes a switch-on-axis_index inside shard_map
(the backward collapses onto arm 0), so the VJP is written explicitly: the
backward pass is another primal-mode shard_map whose switch dispatches each
device group to ITS branch's vjp (recompute, flash-attention style), then
psums dx over the placement axis and dweights over the whole mesh. Each
branch weight's gradient is therefore the sum over exactly the devices that
executed that branch — the same all-reduce semantics as data parallelism.
"""

from __future__ import annotations

from typing import Callable, List, Optional, Sequence

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec

try:  # jax >= 0.6 exposes shard_map at top level; experimental is deprecated
    from jax import shard_map
except ImportError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map


def _pvary(x, axes):
    """Mark x as varying over `axes` in the vma type system (pcast on new
    jax; pvary on older; identity on jax predating varying-manual-axes
    entirely — there the promotion is unnecessary because shard_map does
    not type-check cotangent vma)."""
    try:
        return jax.lax.pcast(x, axes, to="varying")
    except (AttributeError, TypeError):
        pass
    try:
        return jax.lax.pvary(x, tuple(axes))
    except AttributeError:  # pragma: no cover
        return x


def _batch_pspec(mesh: Mesh, axis: str, batch_len: int,
                 batch_axes=None):
    """Batch-dim sharding for a placement body — MIRRORS the search's
    _dp_dims convention (search/candidates.py) so the divisibility the
    candidate assumed holds at lowering: node+data jointly when their
    product divides the batch, else the first axis that divides, else
    replicated. Explicit `batch_axes` (tests / manual callers) filters by
    per-axis divisibility as before."""
    if batch_axes is not None:
        db = [a for a in batch_axes if a in mesh.shape and a != axis
              and batch_len % mesh.shape[a] == 0]
    else:
        cand = [a for a in ("node", "data") if a in mesh.shape and a != axis]
        deg = 1
        for a in cand:
            deg *= mesh.shape[a]
        if len(cand) > 1 and batch_len % deg == 0:
            db = cand
        else:
            db = next(([a] for a in cand if batch_len % mesh.shape[a] == 0),
                      [])
    bspec = tuple(db) if len(db) > 1 else (db[0] if db else None)
    b_local = batch_len
    for a in db:
        b_local //= mesh.shape[a]
    return db, bspec, b_local


def place_branches(
    mesh: Mesh,
    axis: str,
    branch_fns: List[Callable],
    x: jax.Array,
    branch_weights: Sequence,
    join: str,
    batch_axes: Optional[Sequence[str]] = None,
):
    """Run branch i of `branch_fns` on mesh-axis index i only.

    branch_fns[i](x_local, branch_weights[i]) -> y_local; all branches must
    produce equal shapes. join == "add" sums branch outputs; join ==
    "concat" concatenates them along the last dim.
    """
    k = len(branch_fns)
    if axis not in mesh.shape:
        raise ValueError(f"mesh has no axis {axis!r} (axes: {dict(mesh.shape)})")
    if mesh.shape[axis] != k:
        raise ValueError(
            f"inter-op placement needs axis size == n_branches "
            f"({axis}={mesh.shape[axis]} vs {k} branches)")
    if join not in ("add", "concat"):
        raise ValueError(f"unsupported join {join!r}")

    # batch dim rides the data axes; everything else is replicated
    _db, bspec, _bl = _batch_pspec(mesh, axis, x.shape[0], batch_axes)
    x_spec = PartitionSpec(bspec, *([None] * (x.ndim - 1)))
    w_specs = jax.tree_util.tree_map(lambda _: PartitionSpec(),
                                     tuple(branch_weights))
    stk_spec = PartitionSpec(axis, *x_spec)  # (k, batch, ..., d)
    all_axes = tuple(mesh.shape.keys())

    def _branch_arm(i):
        def arm(x_l, ws_l):
            return branch_fns[i](x_l, ws_l[i])[None]
        return arm

    def _fwd_body(x_l, *ws_l):
        bi = jax.lax.axis_index(axis)
        return jax.lax.switch(bi, [_branch_arm(i) for i in range(k)], x_l, ws_l)

    fwd_sm = shard_map(_fwd_body, mesh=mesh,
                       in_specs=(x_spec,) + w_specs, out_specs=stk_spec)

    def _bwd_arm(i):
        def arm(x_l, ws_l, g_l):
            _, pull = jax.vjp(lambda xv, wv: branch_fns[i](xv, wv), x_l, ws_l[i])
            dx, dw_i = pull(g_l[0])
            dws = tuple(dw_i if j == i
                        else jax.tree_util.tree_map(jnp.zeros_like, ws_l[j])
                        for j in range(k))
            return dx, dws
        return arm

    def _bwd_body(x_l, g_l, *ws_l):
        bi = jax.lax.axis_index(axis)
        # promote the replicated primals to device-varying (vma) so the
        # inner vjp's cotangent types line up with g (which varies over the
        # placement axis by construction)
        x_l = _pvary(x_l, (axis,))
        ws_l = _pvary(ws_l, all_axes)
        dx, dws = jax.lax.switch(bi, [_bwd_arm(i) for i in range(k)],
                                 x_l, ws_l, g_l)
        # x is replicated over the placement axis -> its grads sum over it;
        # weights are replicated over the WHOLE mesh -> grads sum everywhere
        dx = jax.lax.psum(dx, axis)
        dws = jax.lax.psum(dws, all_axes)
        return dx, dws

    bwd_sm = shard_map(_bwd_body, mesh=mesh,
                       in_specs=(x_spec, stk_spec) + w_specs,
                       out_specs=(x_spec, w_specs))

    @jax.custom_vjp
    def run(x_, ws_):
        return fwd_sm(x_, *ws_)

    def run_fwd(x_, ws_):
        return fwd_sm(x_, *ws_), (x_, ws_)

    def run_bwd(res, g):
        x_, ws_ = res
        dx, dws = bwd_sm(x_, g, *ws_)
        return dx, dws

    run.defvjp(run_fwd, run_bwd)

    stacked = run(x, tuple(branch_weights))  # (k, batch, ..., d)
    if join == "add":
        return stacked.sum(axis=0)
    return jnp.concatenate(list(stacked), axis=-1)


def divide_workers(costs: Sequence[float], n: int) -> List[int]:
    """Optimal division of n workers among branches for the makespan metric
    max_b(costs[b] / g[b]) — the reference enumerates these divisions
    (graph.cc:267-321, "first i of n workers vs the rest"); for the max
    metric the greedy waterfill is exact: give every branch one worker, then
    repeatedly give the next worker to the branch with the largest per-worker
    cost.

    Manual-placement helper for `place_branches_grouped` callers. The SEARCH
    uses the divisor-constrained variant instead
    (search/candidates._best_groups): the kernel row-slices the per-device
    batch, so each g_b must divide it — a constraint under which plain
    waterfill can emit invalid divisions."""
    k = len(costs)
    if n < k:
        raise ValueError(f"need at least one worker per branch ({n} < {k})")
    g = [1] * k
    for _ in range(n - k):
        b = max(range(k), key=lambda i: costs[i] / g[i])
        g[b] += 1
    return g


def place_branches_grouped(
    mesh: Mesh,
    axis: str,
    branch_fns: List[Callable],
    x: jax.Array,
    branch_weights: Sequence,
    join: str,
    group_sizes: Sequence[int],
    out_dims: Sequence[int],
    out_ndim: int,
    batch_axes: Optional[Sequence[str]] = None,
):
    """UNEQUAL resource division: branch b owns a contiguous group of
    `group_sizes[b]` indices of the placement axis (sum == axis size), the
    reference's machine-resource enumeration between branches
    (graph.cc:267-321) rather than one-index-per-branch. Devices inside a
    group split their branch's BATCH g_b ways, so a fat branch with more
    chips runs proportionally faster.

    Mechanism: each device computes only its (branch, batch-slice) share,
    writes it into a zero-padded buffer of the full JOINED output (feature
    offset static per branch, batch offset dynamic in the group index), and
    one psum over the placement axis assembles batch slices AND performs the
    join in the same collective ("add" sums overlapping feature blocks;
    "concat" blocks are disjoint). Weights are passed replicated (the
    stacked owned-device storage needs one axis index per branch; unequal
    groups trade that memory saving for balance — priced by the search).

    `out_dims[b]` = branch b's last-dim width (join=="add": all equal)."""
    k = len(branch_fns)
    n = sum(group_sizes)
    if axis not in mesh.shape:
        raise ValueError(f"mesh has no axis {axis!r} (axes: {dict(mesh.shape)})")
    if mesh.shape[axis] != n:
        raise ValueError(f"group sizes {list(group_sizes)} sum to {n} but "
                         f"axis {axis} has size {mesh.shape[axis]}")
    if join not in ("add", "concat"):
        raise ValueError(f"unsupported join {join!r}")
    starts = [sum(group_sizes[:b]) for b in range(k)]
    d_join = out_dims[0] if join == "add" else sum(out_dims)
    feat_off = [0] * k if join == "add" else \
        [sum(out_dims[:b]) for b in range(k)]

    _db, bspec, b_local = _batch_pspec(mesh, axis, x.shape[0], batch_axes)
    for g in group_sizes:
        if b_local % g:
            raise ValueError(
                f"per-device batch {b_local} not divisible by group size {g} "
                f"(groups {list(group_sizes)})")
    x_spec = PartitionSpec(bspec, *([None] * (x.ndim - 1)))
    o_spec = PartitionSpec(bspec, *([None] * (out_ndim - 1)))
    w_specs = jax.tree_util.tree_map(lambda _: PartitionSpec(),
                                     tuple(branch_weights))
    all_axes = tuple(mesh.shape.keys())

    def _row0(ndim, row):
        return (row,) + (0,) * (ndim - 1)

    def _fwd_arm(b):
        def arm(x_l, ws_l, row):
            m = x_l.shape[0] // group_sizes[b]
            xs = jax.lax.dynamic_slice_in_dim(x_l, row * m, m, axis=0)
            y = branch_fns[b](xs, ws_l[b])
            pad = jnp.zeros(y.shape[:-1] + (d_join,), y.dtype)
            pad = jax.lax.dynamic_update_slice(
                pad, y, (0,) * (y.ndim - 1) + (feat_off[b],))
            buf = jnp.zeros((x_l.shape[0],) + pad.shape[1:], y.dtype)
            return jax.lax.dynamic_update_slice(
                buf, pad, _row0(buf.ndim, row * m))
        return arm

    def _branch_of(bi):
        # static decision tree over the traced axis index
        b = jnp.zeros((), jnp.int32)
        for j in range(1, k):
            b = jnp.where(bi >= starts[j], j, b)
        row = bi - jnp.take(jnp.asarray(starts), b)
        return b, row

    def _fwd_body(x_l, *ws_l):
        b, row = _branch_of(jax.lax.axis_index(axis))
        part = jax.lax.switch(b, [_fwd_arm(i) for i in range(k)],
                              x_l, ws_l, row)
        return jax.lax.psum(part, axis)

    fwd_sm = shard_map(_fwd_body, mesh=mesh,
                       in_specs=(x_spec,) + w_specs, out_specs=o_spec)

    def _bwd_arm(b):
        def arm(x_l, ws_l, g_l, row):
            g = group_sizes[b]
            m = x_l.shape[0] // g
            xs = jax.lax.dynamic_slice_in_dim(x_l, row * m, m, axis=0)
            gs = jax.lax.dynamic_slice_in_dim(g_l, row * m, m, axis=0)
            gb = jax.lax.dynamic_slice(
                gs, (0,) * (gs.ndim - 1) + (feat_off[b],),
                gs.shape[:-1] + (out_dims[b],))
            _, pull = jax.vjp(lambda xv, wv: branch_fns[b](xv, wv),
                              xs, ws_l[b])
            dxs, dw_b = pull(gb)
            dx = jnp.zeros(x_l.shape, dxs.dtype)
            dx = jax.lax.dynamic_update_slice(dx, dxs, _row0(dx.ndim, row * m))
            dws = tuple(dw_b if j == b
                        else jax.tree_util.tree_map(jnp.zeros_like, ws_l[j])
                        for j in range(k))
            return dx, dws
        return arm

    def _bwd_body(x_l, g_l, *ws_l):
        b, row = _branch_of(jax.lax.axis_index(axis))
        x_l = _pvary(x_l, (axis,))
        g_l = _pvary(g_l, (axis,))
        ws_l = _pvary(ws_l, all_axes)
        dx, dws = jax.lax.switch(b, [_bwd_arm(i) for i in range(k)],
                                 x_l, ws_l, g_l, row)
        # every contribution is zero-padded to full shape: one psum over the
        # placement axis assembles dx; weight grads sum over the whole mesh
        # (each branch's arm zeroes the other branches' slots)
        dx = jax.lax.psum(dx, axis)
        dws = jax.lax.psum(dws, all_axes)
        return dx, dws

    bwd_sm = shard_map(_bwd_body, mesh=mesh,
                       in_specs=(x_spec, o_spec) + w_specs,
                       out_specs=(x_spec, w_specs))

    @jax.custom_vjp
    def run(x_, ws_):
        return fwd_sm(x_, *ws_)

    def run_fwd(x_, ws_):
        return fwd_sm(x_, *ws_), (x_, ws_)

    def run_bwd(res, g):
        x_, ws_ = res
        dx, dws = bwd_sm(x_, g, *ws_)
        return dx, dws

    run.defvjp(run_fwd, run_bwd)
    return run(x, tuple(branch_weights))


def place_branches_stacked(
    mesh: Mesh,
    axis: str,
    branch_fns: List[Callable],
    x: jax.Array,
    stacked_weights,
    join: str,
    batch_axes: Optional[Sequence[str]] = None,
):
    """Owned-device variant: `stacked_weights` is one pytree whose leaves are
    (k, ...) arrays — leaf [i] is branch i's weight — sharded over the
    placement axis, so each device group STORES only its branch's slice.
    branch_fns[i](x_local, weights_tree) with weights_tree = the unstacked
    local slice. Gradients for the stacked leaves stay sharded over the
    placement axis (no cross-branch all-reduce at all); they sum only over
    the axes the weights are replicated on (data)."""
    k = len(branch_fns)
    if axis not in mesh.shape:
        raise ValueError(f"mesh has no axis {axis!r} (axes: {dict(mesh.shape)})")
    if mesh.shape[axis] != k:
        raise ValueError(
            f"inter-op placement needs axis size == n_branches "
            f"({axis}={mesh.shape[axis]} vs {k} branches)")
    if join not in ("add", "concat"):
        raise ValueError(f"unsupported join {join!r}")

    _db, bspec, _bl = _batch_pspec(mesh, axis, x.shape[0], batch_axes)
    x_spec = PartitionSpec(bspec, *([None] * (x.ndim - 1)))
    w_spec = jax.tree_util.tree_map(lambda _: PartitionSpec(axis),
                                    stacked_weights)
    stk_spec = PartitionSpec(axis, *x_spec)
    other_axes = tuple(a for a in mesh.shape.keys() if a != axis)

    def _local(ws_l):
        # shard_map hands each device its (1, ...) slice of the stack
        return jax.tree_util.tree_map(lambda a: a[0], ws_l)

    def _arm(i):
        def arm(x_l, ws_l):
            return branch_fns[i](x_l, _local(ws_l))[None]
        return arm

    def _fwd_body(x_l, ws_l):
        bi = jax.lax.axis_index(axis)
        return jax.lax.switch(bi, [_arm(i) for i in range(k)], x_l, ws_l)

    fwd_sm = shard_map(_fwd_body, mesh=mesh, in_specs=(x_spec, w_spec),
                       out_specs=stk_spec)

    def _bwd_arm(i):
        def arm(x_l, ws_l, g_l):
            _, pull = jax.vjp(lambda xv, wv: branch_fns[i](xv, wv),
                              x_l, _local(ws_l))
            dx, dw = pull(g_l[0])
            # re-stack the local slice's gradient: (1, ...) per leaf
            return dx, jax.tree_util.tree_map(lambda a: a[None], dw)
        return arm

    def _bwd_body(x_l, g_l, ws_l):
        bi = jax.lax.axis_index(axis)
        x_l = _pvary(x_l, (axis,))
        if other_axes:
            ws_l = _pvary(ws_l, other_axes)
        dx, dws = jax.lax.switch(bi, [_bwd_arm(i) for i in range(k)],
                                 x_l, ws_l, g_l)
        # x replicated over the placement axis -> psum its grad over it;
        # weights SHARDED over the placement axis -> no psum over it, only
        # over the axes they are replicated on (data)
        dx = jax.lax.psum(dx, axis)
        if other_axes:
            dws = jax.lax.psum(dws, other_axes)
        return dx, dws

    bwd_sm = shard_map(_bwd_body, mesh=mesh,
                       in_specs=(x_spec, stk_spec, w_spec),
                       out_specs=(x_spec, w_spec))

    @jax.custom_vjp
    def run(x_, ws_):
        return fwd_sm(x_, ws_)

    def run_fwd(x_, ws_):
        return fwd_sm(x_, ws_), (x_, ws_)

    def run_bwd(res, g):
        x_, ws_ = res
        dx, dws = bwd_sm(x_, g, ws_)
        return dx, dws

    run.defvjp(run_fwd, run_bwd)

    stacked = run(x, stacked_weights)  # (k, batch, ..., d)
    if join == "add":
        return stacked.sum(axis=0)
    return jnp.concatenate(list(stacked), axis=-1)
