"""Shape/movement ops: reshape, transpose, concat, split, reverse, pad, slice,
gather.

Reference analog: src/ops/{reshape,transpose,concat,split,reverse,gather}.cc
(~2.5k LoC of Legion glue + copy kernels). On TPU all of these are pure layout
transformations XLA schedules for free or as single fused copies.
"""

from __future__ import annotations

import math
from typing import List

import jax.numpy as jnp

from typing import TYPE_CHECKING
if TYPE_CHECKING:
    from flexflow_tpu.core.layer import Layer
from flexflow_tpu.core.tensor import TensorSpec
from flexflow_tpu.ops.op_type import OperatorType
from flexflow_tpu.ops.registry import register_op


def _reshape_infer(layer: Layer):
    x = layer.inputs[0].spec
    shape = list(layer.params["shape"])
    if shape.count(-1) > 1:
        raise ValueError("at most one -1 in reshape")
    if -1 in shape:
        known = math.prod(d for d in shape if d != -1)
        shape[shape.index(-1)] = x.num_elements // known
    if math.prod(shape) != x.num_elements:
        raise ValueError(f"reshape {x.shape} -> {shape} element mismatch")
    layer.params["shape"] = tuple(shape)
    return [x.with_shape(shape)]


register_op(
    OperatorType.RESHAPE,
    _reshape_infer,
    lambda l, i, w, c: [i[0].reshape(l.params["shape"])],
)


def _transpose_infer(layer: Layer):
    x = layer.inputs[0].spec
    perm = tuple(p % x.ndim for p in layer.params["perm"])
    layer.params["perm"] = perm
    return [x.with_shape(tuple(x.shape[p] for p in perm))]


register_op(
    OperatorType.TRANSPOSE,
    _transpose_infer,
    lambda l, i, w, c: [jnp.transpose(i[0], l.params["perm"])],
)


def _concat_infer(layer: Layer):
    specs = [t.spec for t in layer.inputs]
    axis = layer.params["axis"] % specs[0].ndim
    layer.params["axis"] = axis
    shape = list(specs[0].shape)
    shape[axis] = sum(s.shape[axis] for s in specs)
    return [specs[0].with_shape(shape)]


register_op(
    OperatorType.CONCAT,
    _concat_infer,
    lambda l, i, w, c: [jnp.concatenate(i, axis=l.params["axis"])],
)


def _split_infer(layer: Layer):
    x = layer.inputs[0].spec
    axis = layer.params["axis"] % x.ndim
    layer.params["axis"] = axis
    sizes: List[int] = list(layer.params["sizes"])
    if sum(sizes) != x.shape[axis]:
        raise ValueError(f"split sizes {sizes} != dim {x.shape[axis]}")
    out = []
    for s in sizes:
        shape = list(x.shape)
        shape[axis] = s
        out.append(x.with_shape(shape))
    return out


def _split_lower(layer: Layer, inputs, weights, ctx):
    x = inputs[0]
    axis = layer.params["axis"]
    sizes = layer.params["sizes"]
    offsets = [0]
    for s in sizes:
        offsets.append(offsets[-1] + s)
    return [jnp.take(x, jnp.arange(offsets[k], offsets[k + 1]), axis=axis) for k in range(len(sizes))]


register_op(OperatorType.SPLIT, _split_infer, _split_lower)


register_op(
    OperatorType.REVERSE,
    lambda l: [l.inputs[0].spec],
    lambda l, i, w, c: [jnp.flip(i[0], axis=l.params["axis"])],
)


def _pad_infer(layer: Layer):
    x = layer.inputs[0].spec
    pads = layer.params["pads"]  # [(lo, hi)] * ndim
    shape = tuple(d + lo + hi for d, (lo, hi) in zip(x.shape, pads))
    return [x.with_shape(shape)]


register_op(
    OperatorType.PAD,
    _pad_infer,
    lambda l, i, w, c: [jnp.pad(i[0], l.params["pads"], constant_values=l.params.get("value", 0))],
)


def _slice_infer(layer: Layer):
    x = layer.inputs[0].spec
    starts, limits = layer.params["starts"], layer.params["limits"]
    shape = tuple(hi - lo for lo, hi in zip(starts, limits))
    return [x.with_shape(shape)]


register_op(
    OperatorType.SLICE,
    _slice_infer,
    lambda l, i, w, c: [jnp.asarray(i[0])[tuple(slice(lo, hi) for lo, hi in zip(l.params["starts"], l.params["limits"]))]],
)


def _gather_infer(layer: Layer):
    data, index = layer.inputs[0].spec, layer.inputs[1].spec
    # torch.gather semantics along `dim` (reference: src/ops/gather.cc)
    return [data.with_shape(index.shape)]


register_op(
    OperatorType.GATHER,
    _gather_infer,
    lambda l, i, w, c: [jnp.take_along_axis(i[0], i[1], axis=l.params["dim"])],
)


def _expand_infer(layer: Layer):
    """torch.Tensor.expand semantics: -1 keeps the dim; size-1 dims broadcast;
    new leading dims may be added."""
    x = layer.inputs[0].spec
    sizes = list(layer.params["sizes"])
    lead = len(sizes) - x.ndim
    if lead < 0:
        raise ValueError(f"expand to fewer dims: {x.shape} -> {sizes}")
    shape = []
    for i, s in enumerate(sizes):
        if i < lead:
            shape.append(s if s != -1 else 1)
        else:
            d = x.shape[i - lead]
            shape.append(d if s == -1 else s)
    layer.params["sizes"] = tuple(shape)
    return [x.with_shape(shape)]


register_op(
    OperatorType.EXPAND,
    _expand_infer,
    lambda l, i, w, c: [jnp.broadcast_to(i[0], l.params["sizes"])],
)


def _constant_infer(layer: Layer):
    import numpy as np

    from flexflow_tpu.dtype import DataType

    v = np.asarray(layer.params["value"])
    return [TensorSpec(tuple(v.shape), DataType.from_any(v.dtype))]


def _constant_lower(layer: Layer, inputs, weights, ctx):
    v = jnp.asarray(layer.params["value"])
    # honor the mixed-precision policy: float constants follow compute_dtype
    # (int/bool stay) so they don't promote bf16 neighbors back to f32
    if ctx.compute_dtype is not None and jnp.issubdtype(v.dtype, jnp.floating):
        v = v.astype(ctx.compute_dtype)
    return [v]


register_op(OperatorType.CONSTANT, _constant_infer, _constant_lower)


def _where_infer(layer: Layer):
    cond, a, b = [t.spec for t in layer.inputs]
    shape = jnp.broadcast_shapes(cond.shape, a.shape, b.shape)
    return [a.with_shape(shape)]


register_op(
    OperatorType.WHERE,
    _where_infer,
    lambda l, i, w, c: [jnp.where(i[0].astype(bool), i[1], i[2])],
)


def _masked_fill_infer(layer: Layer):
    x, mask = layer.inputs[0].spec, layer.inputs[1].spec
    shape = jnp.broadcast_shapes(x.shape, mask.shape)
    return [x.with_shape(shape)]


register_op(
    OperatorType.MASKED_FILL,
    _masked_fill_infer,
    lambda l, i, w, c: [jnp.where(i[1].astype(bool), jnp.asarray(l.params["value"], i[0].dtype), i[0])],
)
