"""Event-driven task-graph simulator (search/simulator.py).

Reference analog: LogicalTaskgraphBasedSimulator (simulator.h:785-827,
simulator.cc:1251-1480) — the task-graph replay with concurrent device
timelines, segmented transfers, and emergent compute/comm overlap. The tests
pin the behaviors the closed-form additive model cannot express: gradient
all-reduces hiding behind the backward pass, POSITION-dependent comm
exposure (an early layer's grad sync cannot hide — its backward runs last),
transfer segmentation, and the re-rank/MCMC integration."""

import math

import pytest

from flexflow_tpu import FFConfig, FFModel
from flexflow_tpu.core.graph import topo_order
from flexflow_tpu.parallel.machine import MachineSpec
from flexflow_tpu.search import mcmc
from flexflow_tpu.search.candidates import layer_candidates
from flexflow_tpu.search.dp import SearchResult, search_graph
from flexflow_tpu.search.simulator import (
    SimTask,
    build_step_tasks,
    replay,
    rerank,
    simulate_strategy,
)

MESH22 = dict(mesh_axes={"data": 2, "model": 2}, chip="v5e", overlap_frac=0.0)


def chain_model(d=4096, n=8, b=8, s=512):
    m = FFModel(FFConfig(batch_size=b))
    x = m.create_tensor([b, s, d], name="x")
    h = x
    for i in range(n):
        h = m.dense(h, d, activation="relu", name=f"fc{i}")
    return m


def plan(model, machine, shard=()):
    """All-dp assignment, with the named layers flipped to tp_row:model."""
    layers = topo_order(model.layers)
    bs = {t.shape[0] for t in model.input_tensors if t.ndim > 0}
    cls = {l.name: layer_candidates(l, machine, bs) for l in layers}
    a = {l.name: 0 for l in layers}
    for nm in shard:
        a[nm] = [c.name for c in cls[nm]].index("tp_row:model")
    choices = {nm: cls[nm][i] for nm, i in a.items()}
    additive = mcmc.assignment_cost(layers, model.input_tensors, a, cls, machine)
    return choices, additive


def test_single_device_chain_is_serial():
    """No mesh parallelism -> no comm tasks; makespan == sum of compute."""
    mach = MachineSpec(mesh_axes={"data": 1}, chip="v5e")
    m = chain_model(d=512, n=3, b=4, s=64)
    choices, _ = plan(m, mach)
    rep = simulate_strategy(m, choices, mach)
    assert not any(t.kind == "comm" for t in rep.tasks)
    assert rep.makespan == pytest.approx(
        sum(t.duration for t in rep.tasks), rel=1e-9)


def test_gradsync_hides_behind_backward():
    """Compute-bound DP chain: grad all-reduces of late layers ride link:data
    while the MXU runs earlier layers' backward — most comm time hides, and
    the simulated step beats the additive sum even though the simulator
    *additionally* prices optimizer updates the additive model ignores."""
    mach = MachineSpec(**MESH22)
    m = chain_model()
    choices, additive = plan(m, mach)
    rep = simulate_strategy(m, choices, mach)
    assert rep.hidden_frac > 0.8
    assert rep.makespan < additive


def test_position_dependent_exposure():
    """THE fidelity gap vs additive costing: sharding an early layer halves
    an *exposed* grad sync (its backward runs last — nothing left to hide
    behind); sharding a late layer halves a *hidden* one. The additive model
    prices the same candidate multiset identically regardless of position;
    the replay strictly prefers shard-early."""
    mach = MachineSpec(**MESH22)
    m = chain_model()
    ch0, add0 = plan(m, mach, shard=("fc0",))
    ch7, add7 = plan(m, mach, shard=("fc7",))
    assert add0 == pytest.approx(add7, rel=1e-9)  # additive cannot see it
    r0 = simulate_strategy(m, ch0, mach)
    r7 = simulate_strategy(m, ch7, mach)
    assert r0.makespan < r7.makespan * 0.995


def test_rerank_breaks_additive_tie():
    """The taskgraph re-rank (simulator_mode='taskgraph') decides between DP
    finalists the additive model scores identically."""
    mach = MachineSpec(**MESH22)
    m = chain_model()
    ch0, add0 = plan(m, mach, shard=("fc0",))
    ch7, add7 = plan(m, mach, shard=("fc7",))
    finalists = [SearchResult(choices=ch7, cost=add7, mem_bytes=0),
                 SearchResult(choices=ch0, cost=add0, mem_bytes=0)]
    best, reports = rerank(m, mach, finalists)
    assert best.choices is ch0
    assert len(reports) == 2
    assert reports[1].makespan < reports[0].makespan


def test_segmented_transfers():
    """A big grad sync splits into 16MB-chunk tasks chained on the link
    (reference --simulator-segment-size); a short transfer interleaves
    between chunks instead of waiting for the whole thing."""
    mach = MachineSpec(**MESH22)
    m = chain_model(d=4096, n=2)
    choices, _ = plan(m, mach)
    tasks = build_step_tasks(m, choices, mach)
    seg = [t for t in tasks if "[0/" in t.name]
    assert seg, "expected segmented comm tasks for 67MB grad syncs"

    # manual interleave: long 10-seg transfer (no dependents) + short
    # transfer gating a compute task, all ready at t=0 on one link
    def manual(seg_long):
        ts = []
        prev = None
        for i in range(seg_long):
            t = SimTask(f"long[{i}]", "comm", "link:x", 1.0)
            if prev is not None:
                prev.add_next(t)
            ts.append(t)
            prev = t
        short = SimTask("short", "comm", "link:x", 1.0)
        comp = SimTask("comp", "comp", "mxu", 1.0)
        short.add_next(comp)
        return ts + [short, comp], comp

    tasks, comp = manual(10)
    replay(tasks)
    t_seg = comp.end
    tasks, comp = manual(1)  # unsegmented: one 10s task... scaled to 1s x1
    # emulate unsegmented long transfer of the same total duration
    tasks[0].duration = 10.0
    replay(tasks)
    t_unseg = comp.end
    assert t_seg < t_unseg  # short xfer squeezed between segments


def test_replay_deadlock_guard():
    a = SimTask("a", "comp", "mxu", 1.0)
    b = SimTask("b", "comp", "mxu", 1.0)
    a.add_next(b)
    b.add_next(a)
    with pytest.raises(RuntimeError, match="deadlock"):
        replay([a, b])


def test_timeline_resources_disjoint(tmp_path):
    """Each resource's scheduled intervals never overlap; the exported
    chrome trace is valid JSON."""
    mach = MachineSpec(**MESH22)
    m = chain_model(d=1024, n=4)
    choices, _ = plan(m, mach, shard=("fc1",))
    rep = simulate_strategy(m, choices, mach)
    by_res = {}
    for t in rep.tasks:
        by_res.setdefault(t.resource, []).append((t.start, t.end))
    for res, spans in by_res.items():
        spans.sort()
        for (s1, e1), (s2, e2) in zip(spans, spans[1:]):
            assert e1 <= s2 + 1e-12, f"overlap on {res}"
    out = tmp_path / "trace.json"
    rep.export_trace(str(out))
    import json

    data = json.loads(out.read_text())
    assert any(e.get("cat") == "comm" for e in data["traceEvents"])


def test_unity_taskgraph_mode():
    """simulator_mode='taskgraph' runs the DP -> topk -> replay re-rank
    inside unity_optimize and still yields an executable strategy."""
    from flexflow_tpu.search.unity import unity_optimize

    cfg = FFConfig(batch_size=8, search_budget=8,
                   simulator_mode="taskgraph", simulator_topk=3)
    m = FFModel(cfg)
    x = m.create_tensor([8, 256, 1024], name="x")
    h = m.dense(x, 4096, activation="gelu", name="up")
    h = m.dense(h, 1024, name="down")
    mach = MachineSpec(**MESH22)
    st, stats = unity_optimize(m, mach)
    assert st.op_shardings
    assert stats.best_cost > 0


def test_mcmc_taskgraph_evaluator():
    """MCMC with the event-driven evaluator (the reference's MCMC always
    scored through its simulator) finds a strategy at least as good under
    the simulated metric as the all-dp start."""
    mach = MachineSpec(**MESH22)
    m = chain_model(d=1024, n=3, b=8, s=128)
    st, stats = mcmc.mcmc_optimize(m, mach, budget=40, seed=3,
                                   evaluator="taskgraph")
    assert stats.best_cost <= stats.init_cost
    assert st.op_shardings


def test_simulator_trace_export_flag(tmp_path, devices):
    """--simulator-trace: compiling writes a chrome trace of the compiled
    strategy's event-driven replay (the reference simulator's
    export_file_name analog), including comm tasks on link timelines."""
    import json as _json

    import numpy as np

    from flexflow_tpu import FFModel, FFConfig, SGDOptimizer

    out = tmp_path / "step_trace.json"
    cfg = FFConfig(batch_size=16, mesh_shape={"data": 4, "model": 2},
                   search_budget=8, simulator_trace=str(out))
    m = FFModel(cfg)
    x = m.create_tensor([16, 64], name="x")
    h = m.dense(x, 2048, activation="relu", name="up")
    m.dense(h, 64, name="down")
    m.compile(SGDOptimizer(lr=0.01), "mean_squared_error", [])
    data = _json.loads(out.read_text())
    names = {e.get("name", "") for e in data["traceEvents"]}
    assert any(n.startswith("up:fwd") for n in names), names
    assert any(":gradsync" in n for n in names), names
    # flag parse path
    c2 = FFConfig.parse_args(["--simulator-trace", "/tmp/x.json"])
    assert c2.simulator_trace == "/tmp/x.json"


def test_dcn_axis_priced_on_its_own_link():
    """Multi-slice machine: grad syncs over the node+data batch axes bind
    to the SLOWEST involved link (the DCN node axis — _link_of picks the
    stage that dominates the hierarchical collective), while a tp layer's
    all-reduce rides the ICI model link; DCN tasks carry the DCN-priced
    duration."""
    from flexflow_tpu.search import cost_model as cm

    mach = MachineSpec(mesh_axes={"node": 2, "data": 2, "model": 2},
                       chip="v5e", dcn_axes=("node",), overlap_frac=0.0)
    m = chain_model(d=2048, n=4, b=16, s=256)
    choices, _ = plan(m, mach, shard=("fc1",))
    rep = simulate_strategy(m, choices, mach)
    links = {t.resource for t in rep.tasks if t.kind == "comm"}
    assert "link:node" in links, links   # gradsync binds to the DCN stage
    assert "link:model" in links, links  # tp_row's all-reduce rides ICI
    gs = [t for t in rep.tasks if t.resource == "link:node"
          and t.name.startswith("fc0:kernel:gradsync")]
    assert gs, [t.name for t in rep.tasks if t.kind == "comm"]
    w = m.get_layer_by_name("fc0").weight_specs["kernel"]
    expect = cm.all_reduce_time(w.size_bytes, ("node", "data"), mach)
    assert sum(t.duration for t in gs) == pytest.approx(expect, rel=1e-6)
