"""Launcher: `python -m flexflow_tpu user_script.py [flags]`.

Reference analog: the `flexflow_python` binary + flexflow_top.py top-level
task (F5; python/flexflow/flexflow_python, python/flexflow/core/
flexflow_top.py:164): the launcher owns runtime bring-up (flag parsing,
platform/mesh selection, optional multi-process init) and then runs the user
script, which reads its FFConfig from `flexflow_tpu.get_launch_config()`.

Flags before the script path belong to the launcher/FFConfig; everything
after the script path goes to the script's own argv.
"""

from __future__ import annotations

import os
import runpy
import sys

from flexflow_tpu.config import FFConfig

def split_argv(argv, value_flags=None):
    """Split launcher argv at the script path: the script is the first
    STANDALONE token (not a flag and not the value of a value-taking flag —
    e.g. `--machine-model-file mach.py train.py` must pick train.py).
    `value_flags` defaults to the set DERIVED from the FFConfig parser
    (FFConfig.launcher_value_flags), so newly added flags are covered
    without touching this module. Returns (script, launcher_args,
    script_args); script is None when argv holds no standalone token."""
    if value_flags is None:
        value_flags = FFConfig.launcher_value_flags()
    i = 0
    while i < len(argv):
        a = argv[i]
        if a.startswith("-"):
            if "=" not in a and a in value_flags:
                i += 1  # consume the flag's value token
        else:
            return a, argv[:i], argv[i + 1:]
        i += 1
    return None, argv, []


def main(argv=None):
    argv = list(sys.argv[1:] if argv is None else argv)
    script, launcher_args, script_args = split_argv(argv)
    if script is None:
        print("usage: python -m flexflow_tpu [flags] script.py [script args]\n"
              "flags: the FFConfig CLI (-b, --budget, --mesh data=4,model=2, ...)",
              file=sys.stderr)
        return 2
    # expose to the script via flexflow_tpu.get_launch_config()
    import flexflow_tpu

    # the launcher IS a real CLI invocation: honor FF_LAUNCH_ARGS (jupyter
    # kernelspec / wrapper-injected machine config) here, with explicit
    # launcher flags overriding it — parse_args itself only reads the env
    # for argv=None so programmatic configs stay untouched
    import shlex

    env_args = shlex.split(os.environ.get("FF_LAUNCH_ARGS", ""))
    flexflow_tpu._launch_config = FFConfig.parse_args(env_args + launcher_args)
    if os.environ.get("FLEXFLOW_PLATFORM"):
        import jax

        jax.config.update("jax_platforms", os.environ["FLEXFLOW_PLATFORM"])
    sys.argv = [script] + script_args
    runpy.run_path(script, run_name="__main__")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
