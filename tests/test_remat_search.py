"""Searched rematerialization (ISSUE 12 tentpole a): per-layer remat
policies as a frontier-DP search dimension — under a memory cap the DP
trades HBM for recompute FLOPs layer by layer, the winning policy rides
the Strategy into lowering (per-layer jax.checkpoint) and the strategy
cache, and policies=("none",) reproduces the pre-remat DP exactly."""

import numpy as np
import pytest

from flexflow_tpu import FFConfig, FFModel, SGDOptimizer
from flexflow_tpu.core.layer import Layer
from flexflow_tpu.core.tensor import Tensor
from flexflow_tpu.losses import LossType
from flexflow_tpu.parallel.machine import MachineSpec
from flexflow_tpu.search import cost_model as cm
from flexflow_tpu.search.dp import (SEARCH_STATS, _score,
                                    reset_search_stats, search_graph)

V5E8 = MachineSpec(mesh_axes={"data": 2, "model": 4}, chip="v5e")


def _chain(batch=8192, hidden=2048, layers=6):
    """Activation-heavy dense chain: the live frontier dominates the
    footprint, so a tight cap makes remat worth its recompute."""
    m = FFModel(FFConfig(batch_size=batch))
    x = m.create_tensor([batch, hidden], name="x")
    h = x
    for i in range(layers):
        h = m.dense(h, hidden, activation="gelu", name=f"blk{i}")
    m.dense(h, 256, name="head")
    return m


def test_cost_model_remat_helpers():
    # keep fraction scales the live-activation multiplier between 1 (full
    # recompute: forward value dropped) and act_mult (no remat)
    assert cm.remat_act_mult("none", 2) == 2
    assert cm.remat_act_mult("dots", 2) == 1.5
    assert cm.remat_act_mult("full", 2) == 1.0
    # recompute time is the policy's fraction of the op's step cost
    assert cm.remat_recompute_time(3.0, "none") == 0.0
    assert cm.remat_recompute_time(3.0, "full") == pytest.approx(1.0)
    assert 0 < cm.remat_recompute_time(3.0, "dots") < \
        cm.remat_recompute_time(3.0, "full")


def test_dp_selects_per_layer_remat_under_memory_cap():
    """The acceptance shape: under a tight cap the DP assigns remat to
    SOME layers (not all-or-nothing), buys real predicted memory with
    priced recompute, and scores better than the no-remat search."""
    base = search_graph(_chain(), V5E8, beam_width=64)
    assert base.remat == {}  # no policies searched -> none assigned
    cap = base.mem_bytes * 0.4

    r = search_graph(_chain(), V5E8, beam_width=64, mem_budget=cap,
                     remat_policies=("dots", "full"))
    r0 = search_graph(_chain(), V5E8, beam_width=64, mem_budget=cap)

    n_layers = len(_chain().layers)
    assert r.remat, "cap should force at least one layer into remat"
    assert len(r.remat) < n_layers, "per-layer, not all-or-nothing"
    assert set(r.remat.values()) <= {"dots", "full"}
    # the remat trade: less memory, more (priced) compute, better score
    assert r.mem_bytes < r0.mem_bytes
    assert r.cost >= r0.cost
    assert _score(r.cost, r.mem_bytes, cap) < _score(r0.cost, r0.mem_bytes,
                                                     cap)
    # recompute overhead stays within the cost model's own estimate for
    # the chosen policies (nothing extra leaks into the step cost)
    model = _chain()
    layers = {l.name: l for l in model.layers}
    est = sum(cm.remat_recompute_time(
        r.choices[n].op_time(layers[n], V5E8), pol)
        for n, pol in r.remat.items())
    assert r.cost - r0.cost <= est * 1.001 + 1e-12


def test_none_policy_reproduces_baseline_dp_exactly():
    """policies=("none",) IS the pre-remat DP: identical cost, memory,
    choices and expansion count (the search fast path's invariant)."""
    reset_search_stats()
    a = search_graph(_chain(), V5E8, beam_width=32)
    exp_a = SEARCH_STATS["expansions"]
    reset_search_stats()
    b = search_graph(_chain(), V5E8, beam_width=32,
                     remat_policies=("none",))
    exp_b = SEARCH_STATS["expansions"]
    assert exp_a == exp_b
    assert a.cost == b.cost
    assert a.mem_bytes == b.mem_bytes
    assert {n: c.name for n, c in a.choices.items()} == \
        {n: c.name for n, c in b.choices.items()}
    assert b.remat == {}


def test_inference_search_never_remats():
    """A serving program has no backward stash to free: the policy set
    collapses to ("none",) regardless of what the caller asks for."""
    r = search_graph(_chain(batch=512, hidden=512, layers=3), V5E8,
                     beam_width=16, inference=True,
                     remat_policies=("dots", "full"))
    assert r.remat == {}


def test_strategy_remat_json_roundtrip():
    from flexflow_tpu.parallel.sharding import Strategy

    st = Strategy(name="s", mesh_axes={"data": 8},
                  remat={"blk0": "dots", "blk1": "full"})
    st2 = Strategy.from_json(st.to_json())
    assert st2.remat == {"blk0": "dots", "blk1": "full"}
    # absent block stays absent (old cache entries deserialize clean)
    st3 = Strategy(name="s", mesh_axes={"data": 8})
    assert "remat" not in st3.to_json()
    assert Strategy.from_json(st3.to_json()).remat is None


def _guid_reset():
    """Consecutive builds in one process advance the layer/tensor guid
    counters, which shifts every dropout stream (rng_for folds in the
    guid) — parity comparisons must pin them."""
    Layer._next_guid[0] = 100
    Tensor._next_guid[0] = 1000


def _fit_mlp(devices, remat: bool, epochs=2):
    _guid_reset()
    cfg = FFConfig(batch_size=16, only_data_parallel=True, remat=remat,
                   seed=3)
    m = FFModel(cfg)
    x = m.create_tensor([16, 32], name="x")
    h = m.dense(x, 64, activation="gelu", name="up")
    h = m.dropout(h, rate=0.25, name="drop")
    h = m.dense(h, 32, activation="relu", name="down")
    m.dense(h, 8, name="head")
    cmod = m.compile(SGDOptimizer(lr=0.05),
                     LossType.SPARSE_CATEGORICAL_CROSSENTROPY, metrics=[])
    cmod.init(seed=0)
    rng = np.random.default_rng(0)
    xs = rng.normal(size=(32, 32)).astype(np.float32)
    ys = rng.integers(0, 8, size=(32,)).astype(np.int32)
    hist = cmod.fit([xs], ys, epochs=epochs, verbose=False)
    return cmod, [h["loss"] for h in hist]


def test_remat_alias_bit_identical_loss(devices):
    """--remat (deprecated alias) = uniform per-layer "full" policy. The
    lowering wraps each layer in jax.checkpoint; recompute must be
    BIT-identical to the stash — same ops, same dropout stream (rng_for
    folds in the layer guid, deterministic under replay)."""
    cm_base, base = _fit_mlp(devices, remat=False)
    cm_remat, remat = _fit_mlp(devices, remat=True)
    assert cm_base.strategy.remat in (None, {})
    assert cm_remat.strategy.remat  # alias materialized as per-layer map
    assert set(cm_remat.strategy.remat.values()) == {"full"}
    assert "up" in cm_remat.strategy.remat
    assert base == remat  # exact float equality, both epochs


def test_contradictory_remat_flags_rejected():
    with pytest.raises(ValueError, match="contradicts"):
        FFConfig(batch_size=8, remat=True, remat_search=True)
    with pytest.raises(ValueError, match="unknown remat policies"):
        FFConfig(batch_size=8, remat_policies="none,banana")
