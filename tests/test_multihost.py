"""Multi-host (N3) + DCN (N4) exercised.

- 2-process jax.distributed CPU run (the reference's fake-multi-node trick,
  tests/multinode_helpers/mpi_wrapper2.sh:14-15: one machine carved into
  ranks): both processes SPMD-run the same fit over a global 8-device mesh
  and must agree on losses and the final weights.
- DCN-aware search: the cost model must keep bandwidth-hungry collectives
  off dcn axes (config.h:157 control replication is the launch analog; the
  machine model's dcn_axes/dcn_bw are the fabric analog)."""

import os
import socket
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from flexflow_tpu import FFConfig, FFModel
from flexflow_tpu.parallel.machine import MachineSpec
from flexflow_tpu.search.dp import search_graph


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


# a rank that stops heartbeating for this long is hung (coordinator
# deadlock, wedged collective): kill everything and fail fast instead of
# eating the whole outer timeout. Phases that legitimately go silent for
# a while on a loaded machine (XLA compilation, the whole fit, the
# collective orbax checkpoint) get a larger budget — hang detection
# there still beats the 420 s communicate timeout, while the handshake
# phases keep the fast trigger.
_HEARTBEAT_TIMEOUT = 90.0
_SLOW_PHASE_TIMEOUT = 240.0
_SLOW_PHASES = ("compile", "fit", "evaluate", "checkpoint")
_OVERALL_TIMEOUT = 360.0


def _run_workers(procs):
    """Drain worker stdout on reader threads, tracking liveness via the
    workers' phase-tagged HB lines; kill the pack when a rank stops
    making progress or the overall deadline passes. Returns per-process
    output strings. An HB line only counts as liveness when its PHASE
    advanced — the heartbeat thread keeps ticking through a hung main
    thread (wedged collective, coordinator deadlock), so repeated beats
    in the same phase are exactly the hang signature; the budget for
    that signature is per-phase (_SLOW_PHASES above)."""
    outs = [[] for _ in procs]
    last_beat = [time.monotonic() for _ in procs]
    cur_phase = [None for _ in procs]

    def reader(i, p):
        last_phase = None
        for line in p.stdout:
            outs[i].append(line)
            if line.startswith("HB "):
                ph = line.split(" ph=")[1].split()[0] if " ph=" in line \
                    else None
                cur_phase[i] = ph
                if ph == last_phase:
                    continue  # same phase: not progress
                last_phase = ph
            last_beat[i] = time.monotonic()

    threads = [threading.Thread(target=reader, args=(i, p), daemon=True)
               for i, p in enumerate(procs)]
    for t in threads:
        t.start()
    deadline = time.monotonic() + _OVERALL_TIMEOUT

    def _budget(i):
        return _SLOW_PHASE_TIMEOUT if cur_phase[i] in _SLOW_PHASES \
            else _HEARTBEAT_TIMEOUT

    while any(p.poll() is None for p in procs):
        now = time.monotonic()
        stale = [i for i, (p, b) in enumerate(zip(procs, last_beat))
                 if p.poll() is None and now - b > _budget(i)]
        if stale or now > deadline:
            for p in procs:
                if p.poll() is None:
                    p.kill()
            reason = ("worker(s) {} hung: no phase progress for {}s".format(
                      stale, [_budget(i) for i in stale]) if stale
                      else f"workers exceeded {_OVERALL_TIMEOUT}s")
            for t in threads:
                t.join(timeout=5)
            raise AssertionError(
                reason + "\n" + "\n".join(
                    f"--- worker {i} tail ---\n" + "".join(o[-40:])
                    for i, o in enumerate(outs)))
        time.sleep(0.25)
    for t in threads:
        t.join(timeout=10)
    return ["".join(o) for o in outs]


def test_two_process_distributed_fit(tmp_path):
    """The mpi_wrapper analog: 2 processes x 4 virtual CPU devices = one
    8-device world; fit runs control-replicated and converges identically.
    Workers heartbeat every 2s; a hung rank fails the test fast."""
    port = _free_port()
    nproc = 2
    ckdir = str(tmp_path / "mh_ckpt")
    env = dict(os.environ)
    env.pop("FF_FAULT_PLAN", None)  # never inherit an armed fault plan
    procs = [
        subprocess.Popen(
            [sys.executable, "tests/_multihost_worker.py", str(port),
             str(nproc), str(pid), ckdir],
            cwd="/root/repo", stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT, text=True, env=env)
        for pid in range(nproc)
    ]
    outs = _run_workers(procs)
    for p, out in zip(procs, outs):
        assert p.returncode == 0, f"worker failed:\n{out[-4000:]}"
    results = {}
    for out in outs:
        for line in out.splitlines():
            if line.startswith("RESULT"):
                kv = dict(tok.split("=") for tok in line.split()[1:])
                results[kv["pid"]] = (kv["loss"], kv["wsum"])
    assert set(results) == {"0", "1"}, outs
    # SPMD: both ranks observe the same loss and identical global weights
    assert results["0"] == results["1"], results


def _mlp_pair(batch=4096, hidden=1024):
    m = FFModel(FFConfig(batch_size=batch))
    x = m.create_tensor([batch, hidden], name="x")
    h = m.dense(x, 4 * hidden, activation="gelu", name="up")
    m.dense(h, hidden, name="down")
    return m


def test_search_avoids_tensor_parallel_over_dcn():
    """Same 2x4 mesh twice, activation-heavy MLP (big batch): with the model
    axis on ICI the search picks the full Megatron chain (col then row, its
    partial-sum all-reduce riding the fast axis); with that axis crossing
    slices (DCN bandwidth) the reduction becomes ~8x dearer and the search
    must abandon the Megatron chain on it."""
    ici = MachineSpec(mesh_axes={"data": 2, "model": 4}, chip="v5p")
    r_ici = search_graph(_mlp_pair(), ici)
    assert r_ici.choices["up"].name == "tp_col:model", r_ici.choices["up"].name
    assert r_ici.choices["down"].name == "tp_row:model", r_ici.choices["down"].name

    dcn = MachineSpec(mesh_axes={"data": 2, "model": 4}, chip="v5p",
                      dcn_axes=("model",))
    assert dcn.axis_bw("model") < ici.axis_bw("model") / 5
    r_dcn = search_graph(_mlp_pair(), dcn)
    assert r_dcn.choices["up"].name == "dp", r_dcn.choices["up"].name
    assert r_dcn.choices["down"].name != "tp_row:model", r_dcn.choices["down"].name


def test_dcn_data_axis_prices_gradient_allreduce():
    """DCN remains usable for sample parallelism — the search still batch-
    shards over a cross-slice data axis — but the gradient all-reduce (N2)
    must be priced at DCN bandwidth: the predicted step time rises by
    exactly the dearer sync."""
    def _model():
        m = FFModel(FFConfig(batch_size=64))
        x = m.create_tensor([64, 1024], name="x")
        m.dense(x, 1024, name="fc")
        return m

    ici = MachineSpec(mesh_axes={"data": 8}, chip="v5p")
    dcn = MachineSpec(mesh_axes={"data": 8}, chip="v5p", dcn_axes=("data",))
    r_ici = search_graph(_model(), ici)
    r_dcn = search_graph(_model(), dcn)
    assert r_ici.choices["fc"].name == "dp"
    assert r_dcn.choices["fc"].name == "dp"  # still batch-sharded over DCN
    # same compute, dearer sync: cost strictly higher, by roughly bw ratio
    assert r_dcn.cost > r_ici.cost * 1.5, (r_dcn.cost, r_ici.cost)
