"""Fresh-interpreter regression tests.

Round-1 bug: ops/__init__.py did not import parallel_ops, so
FFModel.repartition() raised in any process that had not already run
compile() (registration happened only as an import side effect elsewhere).
These tests run in a clean subprocess so import-order luck cannot mask
registration gaps again.
"""

import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run_fresh(code: str):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "")
                        + " --xla_force_host_platform_device_count=8")
    return subprocess.run([sys.executable, "-c", code], cwd=REPO, env=env,
                          capture_output=True, text=True, timeout=300)


def test_parallel_ops_registered_in_fresh_process():
    r = _run_fresh(
        "from flexflow_tpu import FFConfig, FFModel\n"
        "m = FFModel(FFConfig(batch_size=8, only_data_parallel=True))\n"
        "x = m.create_tensor([8, 16], name='x')\n"
        "p = m.repartition(x, dim=0, axis='data')\n"
        "c = m.combine(p, dim=0, axis='data')\n"
        "r = m.replicate(c)\n"
        "d = m.reduction(r, axis='data')\n"
        "print('ok', d.shape)\n")
    assert r.returncode == 0, r.stderr
    assert "ok" in r.stdout


def test_all_op_builders_available_in_fresh_process():
    r = _run_fresh(
        "from flexflow_tpu.ops import has_op_def\n"
        "from flexflow_tpu.ops.op_type import OperatorType, PARALLEL_OPS\n"
        "missing = [t for t in PARALLEL_OPS if not has_op_def(t)]\n"
        "assert not missing, missing\n"
        "print('ok')\n")
    assert r.returncode == 0, r.stderr
    assert "ok" in r.stdout
