"""InceptionV3 (reference: examples/cpp/InceptionV3/inception.cc — the
multi-branch concat workload that exercises nonsequence splits in the search)."""

from __future__ import annotations

from flexflow_tpu.core.model import FFModel


def _conv_bn(model, t, c, kh, kw, sh=1, sw=1, ph=0, pw=0, name=""):
    t = model.conv2d(t, c, kh, kw, sh, sw, ph, pw, use_bias=False, name=f"{name}_conv")
    return model.batch_norm(t, relu=True, name=f"{name}_bn")


def inception_a(model, t, pool_c, name):
    b1 = _conv_bn(model, t, 64, 1, 1, name=f"{name}_b1")
    b2 = _conv_bn(model, t, 48, 1, 1, name=f"{name}_b2a")
    b2 = _conv_bn(model, b2, 64, 5, 5, 1, 1, 2, 2, name=f"{name}_b2b")
    b3 = _conv_bn(model, t, 64, 1, 1, name=f"{name}_b3a")
    b3 = _conv_bn(model, b3, 96, 3, 3, 1, 1, 1, 1, name=f"{name}_b3b")
    b3 = _conv_bn(model, b3, 96, 3, 3, 1, 1, 1, 1, name=f"{name}_b3c")
    b4 = model.pool2d(t, 3, 3, 1, 1, 1, 1, pool_type="avg", name=f"{name}_b4p")
    b4 = _conv_bn(model, b4, pool_c, 1, 1, name=f"{name}_b4")
    return model.concat([b1, b2, b3, b4], axis=1, name=f"{name}_cat")


def inception_b(model, t, name):
    b1 = _conv_bn(model, t, 384, 3, 3, 2, 2, name=f"{name}_b1")
    b2 = _conv_bn(model, t, 64, 1, 1, name=f"{name}_b2a")
    b2 = _conv_bn(model, b2, 96, 3, 3, 1, 1, 1, 1, name=f"{name}_b2b")
    b2 = _conv_bn(model, b2, 96, 3, 3, 2, 2, name=f"{name}_b2c")
    b3 = model.pool2d(t, 3, 3, 2, 2, name=f"{name}_b3")
    return model.concat([b1, b2, b3], axis=1, name=f"{name}_cat")


def build_inception_v3(model: FFModel, batch: int = 32, classes: int = 1000):
    x = model.create_tensor([batch, 3, 299, 299], name="image")
    t = _conv_bn(model, x, 32, 3, 3, 2, 2, name="stem1")
    t = _conv_bn(model, t, 32, 3, 3, name="stem2")
    t = _conv_bn(model, t, 64, 3, 3, 1, 1, 1, 1, name="stem3")
    t = model.pool2d(t, 3, 3, 2, 2, name="stem_pool1")
    t = _conv_bn(model, t, 80, 1, 1, name="stem4")
    t = _conv_bn(model, t, 192, 3, 3, name="stem5")
    t = model.pool2d(t, 3, 3, 2, 2, name="stem_pool2")
    t = inception_a(model, t, 32, "mixed0")
    t = inception_a(model, t, 64, "mixed1")
    t = inception_a(model, t, 64, "mixed2")
    t = inception_b(model, t, "mixed3")
    t = model.mean(t, axes=[2, 3], name="gap")
    t = model.dropout(t, 0.5)
    out = model.dense(t, classes, name="fc")
    return x, out
