"""Disaggregated serving fleet benchmark: the ISSUE 18 evidence artifact.

Builds N gpt2 CPU serving twins (same compiled graph, independent KV
pools, a host cold tier on every replica so the disagg handoff path is
live) and drives the `ServingFleet` control plane through four legs:

  scaling — weak-scaling throughput: N replicas serve N x `--per-rep`
      requests arriving open-loop at N x `--rate` (offered load grows
      with the fleet). On one host the replicas share a single XLA CPU
      runtime whose collectives would deadlock if interleaved, so the
      fleet serializes program execution and paces each replica on its
      own virtual device timeline (`step_floor_s` of occupancy per
      step — the floor models a real accelerator's per-step latency,
      which the CPU twin's microsecond steps under-represent; host-side
      scheduling overlaps it exactly as on a pipelined device). Gates:
      aggregate decode tokens/s >= 1.8x at 2 replicas and >= 3.2x at 4
      vs the identically-paced single replica, zero drops everywhere.
  mixed_priority — 2 replicas under bursty mixed-class load
      (priorities 0/1/2): every request completes and the urgent
      class's TTFT p99 is no worse than the batch class's.
  disagg — the same trace through colocated (2 mixed replicas) and
      disaggregated (1 prefill + 1 decode) topologies: committed KV
      pages travel prefill -> decode over the host tier, every request
      is handed off exactly once, greedy streams are BITWISE identical
      to colocated, and goodput stays within 2x of colocated (the
      honest price of the transfer on this twin).
  rolling_swap — a fine-tuning sibling commits durable snapshots into
      a watched root; the RollingSwapController advances the fleet one
      replica at a time at each replica's between-windows safe point.
      Gates: every replica swaps, ZERO requests dropped fleet-wide.

  python tools/bench_fleet.py                      # full bench
  python tools/bench_fleet.py --out BENCH_fleet.json
  python tools/bench_fleet.py --check   # CI smoke (2 replicas): asserts
      single-replica identity vs the pre-fleet scheduler, zero drops,
      disagg bitwise parity, and a complete rolling swap

Headline keys (bench_history "fleet" family): scale2_x, scale4_x,
fleet_tokens_per_s, mixed_ttft_p99_s, rolling_swaps,
rolling_dropped_inflight, disagg_goodput_ratio, legs_passed.
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import sys
import tempfile
import threading
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                           + " --xla_force_host_platform_device_count=8")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def _quantile(xs, q):
    xs = [x for x in xs if x is not None]
    if not xs:
        return None
    return float(np.quantile(np.asarray(xs, np.float64), q))


def _gc():
    # The tiny twin in BOTH modes: this bench measures the fleet layer
    # (routing, pacing, handoff, rollout), not model compute, and the
    # small twin maximizes replicas per host.
    from flexflow_tpu.models import GPT2Config
    return GPT2Config(vocab=256, seq=16, d_model=64, heads=2, layers=1,
                      dropout=0.0)


def _build_engine(gc, kv_host_pages=16):
    """One replica twin. Every replica gets a host cold tier so the
    disagg handoff (which travels through it) is live fleet-wide."""
    import jax

    from flexflow_tpu import FFConfig, FFModel
    from flexflow_tpu.models import build_gpt2
    from flexflow_tpu.serving import compile_serving

    n_dev = len(jax.devices())
    mesh = ({"data": 2, "model": n_dev // 2} if n_dev % 2 == 0 and n_dev > 1
            else {"data": max(1, n_dev)})
    cfg = FFConfig(search_budget=16, mesh_shape=mesh, log_level="warning",
                   max_batch_slots=4, kv_page_size=4,
                   kv_host_pages=kv_host_pages)
    m = FFModel(cfg)
    build_gpt2(m, gc, batch=8)
    eng = compile_serving(m, max_decode_len=4)
    eng.init(seed=0)
    return eng, n_dev


def _build_trainer(gc):
    """Training-side sibling of the SAME graph — the rolling leg's
    snapshot producer (fingerprint hangs off names + schemas only)."""
    from flexflow_tpu import FFConfig, FFModel, SGDOptimizer
    from flexflow_tpu.models import build_gpt2

    cfg = FFConfig(search_budget=0, only_data_parallel=True,
                   log_level="warning", max_batch_slots=4, kv_page_size=4,
                   async_checkpoint=False)
    m = FFModel(cfg)
    build_gpt2(m, gc, batch=8)
    cm = m.compile(SGDOptimizer(lr=0.01),
                   loss_type="sparse_categorical_crossentropy", metrics=[])
    cm.init(seed=0)
    return cm


def _snapshot(cm, root, step):
    from flexflow_tpu.runtime.resilience import save_durable
    cm.init(seed=step)
    cm._iteration = step
    return save_durable(cm, root, block=True)


def _trace(rng, n, rate, vocab, prompt_len, max_new, priorities=(1,)):
    # tracefmt-backed (ISSUE 20): same rng draw order as the historical
    # inline generator, so fixed seeds reproduce identical traces — and
    # every fleet leg is save_trace()-able for twin replay.
    from flexflow_tpu.serving import tracefmt
    return tracefmt.records_to_requests(
        tracefmt.poisson_records(rng, n, rate, vocab, prompt_len, max_new,
                                 priorities=priorities))


def _fleet(engines, floor=0.0, **kw):
    from flexflow_tpu.serving import (ServingFleet, gpt2_prompt_inputs,
                                      gpt2_step_inputs)
    kw.setdefault("dispatch_ahead", 4)
    return ServingFleet(engines, gpt2_prompt_inputs, gpt2_step_inputs,
                        eos_id=None, step_floor_s=floor, **kw)


class Checks:
    def __init__(self):
        self.items = []

    def add(self, name, ok, detail=""):
        self.items.append({"check": name, "ok": bool(ok), "detail": detail})
        if not ok:
            print(f"CHECK FAIL: {name}: {detail}", file=sys.stderr)

    def ok(self):
        return all(c["ok"] for c in self.items)


def _run_leg(engines, gc, floor, per_rep, rate_per_rep, seed,
             priorities=(1,), **kw):
    """One fleet leg: fresh trace, fresh fleet, returns (fleet, row)."""
    n_rep = len(engines)
    rng = np.random.default_rng(seed)
    n = per_rep * n_rep
    reqs = _trace(rng, n, rate_per_rep * n_rep, gc.vocab, 4,
                  engines[0].max_decode_len, priorities=priorities)
    fleet = _fleet(engines, floor=floor, **kw)
    t0 = time.perf_counter()
    done = fleet.serve(reqs)
    wall = time.perf_counter() - t0
    toks = sum(len(r.tokens) for r in done)
    row = {"replicas": n_rep, "requests": n, "completed": len(done),
           "shed": len(fleet.shed), "failed": len(fleet.failed),
           "tokens_out": toks, "wall_s": wall,
           "tokens_per_s": toks / wall,
           "prefills": sum(h.sched.prefills for h in fleet.replicas
                           if h.sched is not None),
           "decode_steps": sum(h.sched.decode_steps for h in fleet.replicas
                               if h.sched is not None)}
    return fleet, done, row


# ------------------------------------------------------------------ leg 1
def leg_scaling(engines, gc, floor, per_rep, rate_per_rep, seed, checks,
                sizes=(1, 2, 4)):
    sizes = tuple(n for n in sizes if n <= len(engines))
    # compile-warm every engine (first program execution JITs inside the
    # fleet lock otherwise) + one paced single-replica throwaway
    _run_leg(engines, gc, 0.0, 4, 500.0, seed + 90)
    _run_leg(engines[:1], gc, floor, 8, rate_per_rep, seed + 91)
    rows = {}
    for n_rep in sizes:
        fleet, done, row = _run_leg(engines[:n_rep], gc, floor, per_rep,
                                    rate_per_rep, seed)
        checks.add(f"scaling_{n_rep}r_all_served",
                   row["completed"] == row["requests"]
                   and row["shed"] == 0 and row["failed"] == 0,
                   f"{row['completed']}/{row['requests']} shed={row['shed']}")
        rows[n_rep] = row
    base = rows[sizes[0]]["tokens_per_s"]
    out = {"step_floor_s": floor, "per_replica_requests": per_rep,
           "rate_per_replica": rate_per_rep,
           "legs": {str(k): v for k, v in rows.items()},
           "scale2_x": rows[2]["tokens_per_s"] / base if 2 in rows else None,
           "scale4_x": rows[4]["tokens_per_s"] / base if 4 in rows else None,
           "fleet_tokens_per_s": rows[max(sizes)]["tokens_per_s"]}
    if 2 in rows:
        checks.add("scaling_2x_gate", out["scale2_x"] >= 1.8,
                   f"scale2={out['scale2_x']:.2f} < 1.8")
    if 4 in rows:
        checks.add("scaling_4x_gate", out["scale4_x"] >= 3.2,
                   f"scale4={out['scale4_x']:.2f} < 3.2")
    return out


# ------------------------------------------------------------------ leg 2
def leg_mixed(engines, gc, floor, per_rep, seed, checks):
    # bursty mixed-class load: arrivals faster than the paced service
    # chain so queues form and the priority order actually decides TTFT
    fleet, done, row = _run_leg(engines, gc, floor, per_rep, 20.0, seed,
                                priorities=(0, 1, 1, 2))
    checks.add("mixed_all_served",
               row["completed"] == row["requests"] and row["shed"] == 0,
               f"{row['completed']}/{row['requests']} shed={row['shed']}")
    by_cls = {}
    for r in done:
        by_cls.setdefault(r.priority, []).append(r.ttft_s)
    p99 = {c: _quantile(v, 0.99) for c, v in sorted(by_cls.items())}
    urgent, batch = p99.get(0), p99.get(2)
    if urgent is not None and batch is not None:
        checks.add("mixed_priority_ordering", urgent <= batch,
                   f"urgent p99 {urgent:.3f}s > batch p99 {batch:.3f}s")
    row.update({"ttft_p99_s": _quantile([r.ttft_s for r in done], 0.99),
                "ttft_p99_by_priority":
                    {str(c): v for c, v in p99.items()},
                "ttft_p99_urgent_s": urgent, "ttft_p99_batch_s": batch})
    return row


# ------------------------------------------------------------------ leg 3
def leg_disagg(engines, gc, floor, per_rep, rate_per_rep, seed, checks):
    colo_fleet, colo_done, colo = _run_leg(
        engines, gc, floor, per_rep, rate_per_rep, seed,
        topology="colocated")
    dis_fleet, dis_done, dis = _run_leg(
        engines, gc, floor, per_rep, rate_per_rep, seed,
        topology="disagg", prefill_replicas=1)
    n = colo["requests"]
    checks.add("disagg_all_served",
               dis["completed"] == n and dis["shed"] == 0
               and dis["failed"] == 0,
               f"{dis['completed']}/{n} shed={dis['shed']}")
    handoffs = dis_fleet.stats["handoffs"]
    checks.add("disagg_every_request_handed_off", handoffs == n,
               f"handoffs={handoffs} != {n}")
    colo_toks = {r.rid: list(r.tokens) for r in colo_done}
    dis_toks = {r.rid: list(r.tokens) for r in dis_done}
    checks.add("disagg_bitwise_parity", colo_toks == dis_toks,
               "disagg greedy streams differ from colocated")
    ratio = dis["tokens_per_s"] / max(1e-9, colo["tokens_per_s"])
    checks.add("disagg_goodput_within_2x", ratio >= 0.5,
               f"goodput ratio {ratio:.2f} < 0.5")
    # the import side (decode pool) counts the adopted bytes
    moved = sum(h.engine.kv.tier_counters.get("kv_handoff_bytes", 0)
                for h in dis_fleet.replicas)
    return {"colocated": colo, "disagg": dis, "handoffs": handoffs,
            "kv_handoff_bytes": int(moved), "goodput_ratio": ratio}


# ------------------------------------------------------------------ leg 4
def leg_rolling(engines, gc, cm, root, floor, per_rep, seed, checks,
                second_snapshot=True):
    # stage snapshot 1 before serving: the rollout itself still happens
    # mid-traffic (safe points only exist while the fleet is serving)
    _snapshot(cm, root, 1)
    n_rep = len(engines)
    rng = np.random.default_rng(seed)
    n = per_rep * n_rep
    reqs = _trace(rng, n, 10.0 * n_rep, gc.vocab, 4,
                  engines[0].max_decode_len)
    fleet = _fleet(engines, floor=floor)

    def dropper():
        # a second snapshot once the first finished rolling across the
        # fleet — proves the cursor wraps and keeps rolling under load
        deadline = time.monotonic() + 20.0
        while time.monotonic() < deadline:
            rolling = fleet.rolling
            if rolling is not None and len(rolling.swaps) >= n_rep:
                _snapshot(cm, root, 2)
                return
            time.sleep(0.01)

    th = threading.Thread(target=dropper, daemon=True) \
        if second_snapshot else None
    if th:
        th.start()
    t0 = time.perf_counter()
    done = fleet.serve(reqs, watch_root=root, poll_interval_s=0.01)
    wall = time.perf_counter() - t0
    if th:
        th.join(timeout=5.0)
    dropped = len(fleet.shed) + len(fleet.failed)
    swaps = fleet.stats.get("rollout_swaps", 0)
    checks.add("rolling_zero_dropped",
               len(done) == n and dropped == 0,
               f"completed={len(done)}/{n} dropped={dropped}")
    checks.add("rolling_every_replica_swapped", swaps >= n_rep,
               f"rollout_swaps={swaps} < {n_rep}")
    versions = [getattr(e, "active_version", None) for e in engines]
    if not second_snapshot:
        checks.add("rolling_fleet_on_new_version",
                   all(v == 1 for v in versions), f"versions={versions}")
    toks = sum(len(r.tokens) for r in done)
    return {"replicas": n_rep, "requests": n, "completed": len(done),
            "dropped_inflight": dropped, "rollout_swaps": swaps,
            "rollout_rollbacks": fleet.stats.get("rollout_rollbacks", 0),
            "rollout_halted": fleet.stats.get("rollout_halted", False),
            "versions": versions, "wall_s": wall,
            "tokens_per_s": toks / wall}


# --------------------------------------------------------------- identity
def leg_identity(eng, gc, seed, checks):
    """Single-replica fleet == the pre-fleet scheduler: bitwise token
    streams, identical dispatch/host-sync counters."""
    from flexflow_tpu.serving import (ContinuousBatchingScheduler,
                                      gpt2_prompt_inputs, gpt2_step_inputs)
    def mk():
        return _trace(np.random.default_rng(seed), 8, 500.0, gc.vocab, 4,
                      eng.max_decode_len)
    sched = ContinuousBatchingScheduler(
        eng, eng.params, gpt2_prompt_inputs, gpt2_step_inputs,
        eos_id=None, dispatch_ahead=4)
    direct = sched.run(mk())
    fleet, done, _ = _run_leg([eng], gc, 0.0, 8, 500.0, seed)
    d_toks = {r.rid: list(r.tokens) for r in direct}
    f_toks = {r.rid: list(r.tokens) for r in done}
    checks.add("single_replica_bitwise", d_toks == f_toks,
               "fleet(1) token streams differ from the plain scheduler")
    fs = fleet.replicas[0].sched
    counters = ("prefills", "decode_steps", "materializations")
    same = all(getattr(sched, c) == getattr(fs, c) for c in counters)
    checks.add("single_replica_counters", same,
               "; ".join(f"{c}: {getattr(sched, c)} vs {getattr(fs, c)}"
                         for c in counters))
    return {"counters": {c: getattr(fs, c) for c in counters}}


def main(argv=None) -> int:
    p = argparse.ArgumentParser("bench_fleet")
    p.add_argument("--per-rep", type=int, default=12,
                   help="requests per replica (weak scaling)")
    p.add_argument("--rate", type=float, default=10.0,
                   help="arrival rate per replica (offered load scales "
                        "with the fleet)")
    p.add_argument("--step-floor-ms", type=float, default=100.0,
                   help="simulated per-step device occupancy (the CPU "
                        "twin's microsecond steps under-represent a real "
                        "accelerator; recorded in the artifact)")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--out", default="", help="also write the JSON here")
    p.add_argument("--check", action="store_true",
                   help="CI smoke: 2 replicas, identity/parity/rollout "
                        "invariants only (no timing gates)")
    args = p.parse_args(argv)
    floor = args.step_floor_ms / 1e3
    n_engines = 2 if args.check else 4
    if args.check:
        args.per_rep = min(args.per_rep, 6)
        floor = min(floor, 0.02)

    gc = _gc()
    engines = []
    for _ in range(n_engines):
        eng, n_dev = _build_engine(gc)
        engines.append(eng)
    cm = _build_trainer(gc)
    root = tempfile.mkdtemp(prefix="ff_fleet_bench_")
    checks = Checks()
    try:
        ident = leg_identity(engines[0], gc, args.seed + 1, checks)
        scaling = leg_scaling(engines, gc, floor, args.per_rep, args.rate,
                              args.seed + 2, checks,
                              sizes=(1, 2) if args.check else (1, 2, 4))
        if args.check:
            # no timing gates in CI: drop the scaling-ratio verdicts,
            # keep the zero-drop ones
            checks.items = [c for c in checks.items
                            if not c["check"].endswith("x_gate")]
        mixed = leg_mixed(engines[:2], gc, floor, 16 if not args.check
                          else args.per_rep, args.seed + 3, checks)
        disagg = leg_disagg(engines[:2], gc, floor, args.per_rep,
                            args.rate, args.seed + 4, checks)
        rolling = leg_rolling(engines[:2], gc, cm, root, min(floor, 0.05),
                              args.per_rep, args.seed + 5, checks,
                              second_snapshot=not args.check)
    finally:
        shutil.rmtree(root, ignore_errors=True)

    report = {
        "model": "gpt2 CPU twin" + (" (check)" if args.check else ""),
        "devices": n_dev,
        "replicas_built": n_engines,
        "slots": engines[0].slots,
        "max_decode_len": engines[0].max_decode_len,
        "step_floor_s": floor,
        "legs": {"identity": ident, "scaling": scaling,
                 "mixed_priority": mixed, "disagg": disagg,
                 "rolling_swap": rolling},
        "checks": checks.items,
        # headline metrics (bench_history "fleet" family)
        "scale2_x": scaling["scale2_x"],
        "scale4_x": scaling["scale4_x"],
        "fleet_tokens_per_s": scaling["fleet_tokens_per_s"],
        "mixed_ttft_p99_s": mixed["ttft_p99_s"],
        "rolling_swaps": rolling["rollout_swaps"],
        "rolling_dropped_inflight": rolling["dropped_inflight"],
        "disagg_goodput_ratio": disagg["goodput_ratio"],
        "legs_passed": sum(c["ok"] for c in checks.items),
    }
    print(json.dumps(report, indent=1))
    if args.out:
        with open(args.out, "w") as f:
            json.dump(report, f, indent=1)
    if args.check:
        print("CHECK " + ("PASS" if checks.ok() else "FAIL"))
        return 0 if checks.ok() else 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
