"""Keras-compatible frontend (reference: python/flexflow/keras/).

Usage mirrors the reference examples (examples/python/keras/):

    from flexflow_tpu.keras.models import Model, Sequential
    from flexflow_tpu.keras.layers import Input, Dense, Conv2D, ...
    import flexflow_tpu.keras.optimizers
"""

from flexflow_tpu.keras import (  # noqa: F401
    callbacks,
    datasets,
    initializers,
    layers,
    models,
    optimizers,
    preprocessing,
    regularizers,
)
from flexflow_tpu.losses import LossType as losses  # noqa: F401
from flexflow_tpu.metrics import MetricsType as metrics  # noqa: F401
