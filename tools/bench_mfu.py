"""MFU ceiling bench: searched rematerialization + the pallas fusion suite.

Evidence harness for the ISSUE-12 tentpole, in five legs:

  remat_search — the frontier DP with per-layer remat policies under a
      tight HBM cap: reports the chosen per-layer assignment (must be
      MIXED, not all-or-nothing), the predicted memory reduction vs the
      capped no-remat search, and the recompute overhead — asserted to
      stay within the cost model's own remat_recompute_time estimate.
  remat_live — the --remat lowering (per-layer jax.checkpoint) measured
      on the COMPILED train step via XLA's memory analysis: live temp
      buffer bytes must actually shrink, and the loss stays bit-identical
      (recompute replays the same ops, including guid-folded dropout).
  fused_ce — fused cross-entropy vs the optax reference: fwd/grad
      parity, and the no-f32-[N,vocab]-materialization claim counted on
      the traced jaxpr (reference > 0, fused == 0).
  fused_optim — the single-pass Adam/SGD kernel vs tx.update across
      every recognized plan (adam / adamw / adam-bf16 / sgd / sgd-mom).
  collective_matmul — the ring all-gather/matmul overlap vs plain
      x @ w on the 8-virtual-device mesh: fwd/grad parity.

plus an op_attribution() pass over the gpt2 CPU twin with the fusion
suite off vs on — the roofline/MFU rows land in BENCH_mfu.json so the
fused kernels' movement is inspectable per op (timings on the CPU
interpret backend are structural evidence, not TPU speedups).

  python tools/bench_mfu.py                 # full run, prints JSON
  python tools/bench_mfu.py --out BENCH_mfu.json
  python tools/bench_mfu.py --check         # CI smoke: asserts every
      leg's contract (mixed per-layer remat, predicted AND live memory
      reduction, recompute overhead within the cost-model estimate,
      <= 1e-5 kernel parity on every leg) — exits nonzero on regression.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                           + " --xla_force_host_platform_device_count=8")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def _guid_reset():
    """Pin the layer/tensor guid counters: consecutive builds otherwise
    shift every dropout stream (rng folds in the guid), breaking
    bit-identical comparisons."""
    from flexflow_tpu.core.layer import Layer
    from flexflow_tpu.core.tensor import Tensor

    Layer._next_guid[0] = 100
    Tensor._next_guid[0] = 1000


def _chain_model(cfg, batch, hidden, layers):
    from flexflow_tpu import FFModel

    m = FFModel(cfg)
    x = m.create_tensor([batch, hidden], name="x")
    h = x
    for i in range(layers):
        h = m.dense(h, hidden, activation="gelu", name=f"blk{i}")
    m.dense(h, 64, name="head")
    return m


# ------------------------------------------------------------ leg 1: search
def leg_remat_search() -> dict:
    """DP-level: under a 0.4x cap the search assigns remat to SOME layers,
    buys predicted HBM with recompute priced by the cost model."""
    from flexflow_tpu import FFConfig
    from flexflow_tpu.parallel.machine import MachineSpec
    from flexflow_tpu.search import cost_model as cm
    from flexflow_tpu.search.dp import _score, search_graph

    mach = MachineSpec(mesh_axes={"data": 2, "model": 4}, chip="v5e")

    def build():
        from flexflow_tpu import FFConfig
        return _chain_model(FFConfig(batch_size=8192), 8192, 2048, 6)

    base = search_graph(build(), mach, beam_width=64)
    cap = base.mem_bytes * 0.4
    r = search_graph(build(), mach, beam_width=64, mem_budget=cap,
                     remat_policies=("dots", "full"))
    r0 = search_graph(build(), mach, beam_width=64, mem_budget=cap)
    model = build()
    layers = {l.name: l for l in model.layers}
    est = sum(cm.remat_recompute_time(r.choices[n].op_time(layers[n], mach),
                                      pol) for n, pol in r.remat.items())
    overhead = r.cost - r0.cost
    return {
        "hbm_cap_bytes": cap,
        "remat_assignment": dict(r.remat),
        "n_layers": len(model.layers),
        "pred_mem_no_remat_bytes": int(r0.mem_bytes),
        "pred_mem_remat_bytes": int(r.mem_bytes),
        "pred_mem_reduction": 1.0 - r.mem_bytes / r0.mem_bytes,
        "recompute_overhead_s": overhead,
        "cost_model_overhead_estimate_s": est,
        "overhead_within_estimate": bool(overhead <= est * 1.001 + 1e-12),
        "score_improves": bool(
            _score(r.cost, r.mem_bytes, cap) <
            _score(r0.cost, r0.mem_bytes, cap)),
    }


# -------------------------------------------------------------- leg 2: live
def leg_remat_live(batch=1024, hidden=256, layers=8) -> dict:
    """Compiled-artifact level: per-layer jax.checkpoint must shrink the
    train step's live temp buffers (XLA memory analysis) at bit-identical
    loss."""
    import jax

    from flexflow_tpu import FFConfig, SGDOptimizer
    from flexflow_tpu.losses import LossType

    rng = np.random.default_rng(0)
    xs = rng.normal(size=(2 * batch, hidden)).astype(np.float32)
    ys = rng.integers(0, 64, size=(2 * batch,)).astype(np.int32)
    out = {}
    for key, remat in (("base", False), ("remat", True)):
        _guid_reset()
        cfg = FFConfig(batch_size=batch, only_data_parallel=True,
                       remat=remat, seed=3, log_level="warning")
        m = _chain_model(cfg, batch, hidden, layers)
        cmod = m.compile(SGDOptimizer(lr=0.01),
                         LossType.SPARSE_CATEGORICAL_CROSSENTROPY,
                         metrics=[])
        cmod.init(seed=0)
        lowered = cmod.train_step.lower(
            cmod.params, cmod.opt_state, cmod.state,
            [jax.device_put(xs[:batch])], jax.device_put(ys[:batch]),
            jax.random.PRNGKey(0))
        ma = lowered.compile().memory_analysis()
        hist = cmod.fit([xs], ys, epochs=1, verbose=False)
        out[key] = {"temp_bytes": int(ma.temp_size_in_bytes),
                    "loss": float(hist[0]["loss"])}
    return {
        "live_temp_base_bytes": out["base"]["temp_bytes"],
        "live_temp_remat_bytes": out["remat"]["temp_bytes"],
        "live_temp_reduction": 1.0 - out["remat"]["temp_bytes"]
        / out["base"]["temp_bytes"],
        "loss_base": out["base"]["loss"],
        "loss_remat": out["remat"]["loss"],
        "loss_bit_identical": out["base"]["loss"] == out["remat"]["loss"],
    }


# --------------------------------------------------------------- leg 3: CE
def leg_fused_ce(n=256, v=2048) -> dict:
    import jax
    import jax.numpy as jnp
    import optax

    from flexflow_tpu.kernels.fused_ce import fused_cross_entropy

    rng = np.random.default_rng(0)
    logits = jnp.asarray(rng.normal(size=(n, v)) * 3.0, jnp.bfloat16)
    labels = jnp.asarray(rng.integers(0, v, size=(n,)), jnp.int32)

    def ref(x):
        return jnp.mean(optax.softmax_cross_entropy_with_integer_labels(
            x.astype(jnp.float32), labels))

    def fused(x):
        return fused_cross_entropy(x, labels)

    fwd_diff = abs(float(fused(logits)) - float(ref(logits)))
    gf = jax.grad(fused)(logits).astype(jnp.float32)
    gr = jax.grad(ref)(logits).astype(jnp.float32)
    grad_diff = float(jnp.max(jnp.abs(gf - gr)))

    def count_f32_nv(fn):
        jaxpr = jax.make_jaxpr(lambda x: jax.grad(fn)(x))(logits)
        cnt = 0

        def walk(jp):
            nonlocal cnt
            for eqn in jp.eqns:
                for var in eqn.outvars:
                    aval = getattr(var, "aval", None)
                    if aval is not None and tuple(aval.shape) == (n, v) \
                            and aval.dtype == jnp.float32:
                        cnt += 1
                for val in eqn.params.values():
                    if getattr(val, "jaxpr", None) is not None:
                        walk(val.jaxpr)
        walk(jaxpr.jaxpr)
        return cnt

    t0 = time.perf_counter()
    jax.block_until_ready(jax.jit(jax.grad(fused))(logits))
    t_fused = time.perf_counter() - t0
    t0 = time.perf_counter()
    jax.block_until_ready(jax.jit(jax.grad(ref))(logits))
    t_ref = time.perf_counter() - t0
    return {
        "rows": n, "vocab": v,
        "fwd_max_diff": fwd_diff,
        "grad_max_diff": grad_diff,
        "f32_nv_intermediates_ref": count_f32_nv(ref),
        "f32_nv_intermediates_fused": count_f32_nv(fused),
        "compile_plus_step_s_fused": t_fused,
        "compile_plus_step_s_ref": t_ref,
    }


# ------------------------------------------------------------ leg 4: optim
def leg_fused_optim() -> dict:
    import jax
    import jax.numpy as jnp

    from flexflow_tpu import AdamOptimizer, SGDOptimizer
    from flexflow_tpu.kernels.fused_optim import fused_update, plan_for

    rng = np.random.default_rng(0)
    params = {"k": jnp.asarray(rng.normal(size=(33, 65)), jnp.float32),
              "b": jnp.asarray(rng.normal(size=(7,)), jnp.float32)}
    plans = {
        "adam": AdamOptimizer(alpha=1e-3),
        "adamw": AdamOptimizer(alpha=1e-3, weight_decay=0.01),
        "adam_bf16": AdamOptimizer(alpha=1e-3, state_dtype="bfloat16"),
        "sgd": SGDOptimizer(lr=0.05),
        "sgd_momentum": SGDOptimizer(lr=0.05, momentum=0.9, nesterov=True),
    }
    diffs = {}
    for name, opt in plans.items():
        tx = opt.to_optax()
        state = tx.init(params)
        plan = plan_for(opt)
        worst = 0.0
        ref_state = fused_state = state
        for step in range(2):
            grads = jax.tree_util.tree_map(
                lambda p: jnp.asarray(
                    np.random.default_rng(step + p.size).normal(
                        size=p.shape), jnp.float32), params)
            ref_upd, ref_state = tx.update(grads, ref_state, params)
            upd, fused_state = fused_update(plan, grads, fused_state,
                                            params)
            for a, b in zip(jax.tree_util.tree_leaves((upd, fused_state)),
                            jax.tree_util.tree_leaves((ref_upd,
                                                       ref_state))):
                worst = max(worst, float(jnp.max(jnp.abs(
                    jnp.asarray(a, jnp.float32)
                    - jnp.asarray(b, jnp.float32)))))
        diffs[name] = worst
    return {"per_plan_max_diff": diffs,
            "max_diff": max(diffs.values())}


# ------------------------------------------------------- leg 5: collective
def leg_collective_matmul(m_rows=64, k=32, n_cols=64) -> dict:
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh

    from flexflow_tpu.kernels.collective_matmul import collective_matmul

    mesh = Mesh(np.asarray(jax.devices()[:8]).reshape(2, 4),
                ("data", "model"))
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(m_rows, k)), jnp.float32)
    w = jnp.asarray(rng.normal(size=(k, n_cols)), jnp.float32)
    y = collective_matmul(x, w, mesh, "model")
    ref = jnp.dot(x, w, preferred_element_type=jnp.float32)
    fwd = float(jnp.max(jnp.abs(y - ref)))

    def f_ring(x, w):
        return jnp.sum(collective_matmul(x, w, mesh, "model") ** 2)

    def f_ref(x, w):
        return jnp.sum(jnp.dot(x, w,
                               preferred_element_type=jnp.float32) ** 2)

    g = jax.grad(f_ring, argnums=(0, 1))(x, w)
    gr = jax.grad(f_ref, argnums=(0, 1))(x, w)
    grad = max(float(jnp.max(jnp.abs(a - b))) for a, b in zip(g, gr))
    return {"fwd_max_diff": fwd, "grad_max_diff": grad}


# ------------------------------------------------- op_attribution evidence
def _twin(fused: bool, batch=8):
    from flexflow_tpu import AdamOptimizer, FFConfig, FFModel
    from flexflow_tpu.losses import LossType
    from flexflow_tpu.models import GPT2Config, build_gpt2

    _guid_reset()
    mode = "on" if fused else "off"
    cfg = FFConfig(batch_size=batch, only_data_parallel=True, seed=3,
                   fused_loss=mode, fused_optimizer=mode,
                   log_level="warning")
    gc = GPT2Config(vocab=512, seq=16, d_model=64, heads=2, layers=1,
                    dropout=0.0)
    m = FFModel(cfg)
    build_gpt2(m, gc, batch=batch)
    cm = m.compile(AdamOptimizer(alpha=1e-3),
                   LossType.SPARSE_CATEGORICAL_CROSSENTROPY, metrics=[])
    cm.init(seed=0)
    rng = np.random.default_rng(0)
    n = 16 * batch
    ids = rng.integers(0, gc.vocab, size=(n, gc.seq)).astype(np.int32)
    pos = np.broadcast_to(np.arange(gc.seq, dtype=np.int32),
                          (n, gc.seq)).copy()
    y = rng.integers(0, gc.vocab, size=(n, gc.seq)).astype(np.int32)
    return cm, [ids, pos], y


def leg_attribution(epochs=2) -> dict:
    """gpt2 twin with the fusion suite off vs on: per-op roofline/MFU
    rows + measured step time + live temp bytes (the hbm_peak proxy) —
    the movement of each row under fusion is the BENCH artifact."""
    import jax

    out = {}
    for key, fused in (("baseline", False), ("fused", True)):
        cm, x, y = _twin(fused)
        hist = cm.fit(x, y, epochs=epochs, verbose=False)
        rep = cm.op_attribution(print_table=False)
        rows = [{k: r.get(k) for k in ("layer", "op", "measured_s",
                                       "attributed_s", "roofline_s",
                                       "bound", "mfu", "mfu_ceiling")}
                for r in rep["rows"]]
        lowered = cm.train_step.lower(
            cm.params, cm.opt_state, cm.state,
            [jax.device_put(v[:cm.cfg.batch_size]) for v in x],
            jax.device_put(y[:cm.cfg.batch_size]), jax.random.PRNGKey(0))
        ma = lowered.compile().memory_analysis()
        att = sum(r["attributed_s"] or 0.0 for r in rows)
        mfu_w = (sum((r["attributed_s"] or 0.0) * (r["mfu"] or 0.0)
                     for r in rows) / att) if att > 0 else 0.0
        step = cm.drift_stats().get("measured_step_time_s")
        out[key] = {
            "rows": rows,
            "n_rows": len(rows),
            "step_ms": (step or 0.0) * 1e3,
            "mfu_weighted": mfu_w,
            "hbm_temp_bytes": int(ma.temp_size_in_bytes),
            "final_loss": float(hist[-1]["loss"]),
        }
    out["loss_max_diff"] = abs(out["baseline"]["final_loss"]
                               - out["fused"]["final_loss"])
    return out


# ------------------------------------------------------------------- driver
def run(check: bool = False) -> dict:
    t0 = time.perf_counter()
    rs = leg_remat_search()
    rl = leg_remat_live()
    ce = leg_fused_ce()
    fo = leg_fused_optim()
    cmm = leg_collective_matmul()
    att = leg_attribution()

    legs_passed = 0
    failures = []

    def leg(name, ok):
        nonlocal legs_passed
        if ok:
            legs_passed += 1
        else:
            failures.append(name)

    # per-layer, not all-or-nothing, under the cap — with priced recompute
    leg("remat_search",
        0 < len(rs["remat_assignment"]) < rs["n_layers"]
        and rs["pred_mem_reduction"] > 0
        and rs["overhead_within_estimate"] and rs["score_improves"])
    leg("remat_live",
        rl["live_temp_reduction"] > 0 and rl["loss_bit_identical"])
    leg("fused_ce",
        ce["fwd_max_diff"] <= 1e-5 and ce["grad_max_diff"] <= 1e-4
        and ce["f32_nv_intermediates_fused"] == 0
        and ce["f32_nv_intermediates_ref"] > 0)
    leg("fused_optim", fo["max_diff"] <= 1e-5)
    leg("collective_matmul",
        cmm["fwd_max_diff"] <= 1e-4 and cmm["grad_max_diff"] <= 1e-3)
    leg("attribution",
        att["baseline"]["n_rows"] > 0
        and att["baseline"]["n_rows"] == att["fused"]["n_rows"]
        and att["loss_max_diff"] <= 1e-5)

    result = {
        "remat_search": rs,
        "remat_live": rl,
        "fused_ce": ce,
        "fused_optim": fo,
        "collective_matmul": cmm,
        "op_attribution": att,
        # headline metrics (tools/bench_history.py "mfu" family)
        "remat_pred_mem_reduction": rs["pred_mem_reduction"],
        "remat_live_temp_reduction": rl["live_temp_reduction"],
        "fused_ce_max_diff": max(ce["fwd_max_diff"], ce["grad_max_diff"]),
        "step_ms_fused": att["fused"]["step_ms"],
        "mfu_weighted_fused": att["fused"]["mfu_weighted"],
        "hbm_peak_bytes": float(att["fused"]["hbm_temp_bytes"]),
        "legs_passed": legs_passed,
        "wall_s": time.perf_counter() - t0,
    }
    if failures:
        result["failures"] = failures
    return result


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        "bench_mfu", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("--out", default=None,
                    help="write the report JSON here (e.g. BENCH_mfu.json)")
    ap.add_argument("--check", action="store_true",
                    help="CI smoke: assert every leg's contract, write "
                         "nothing, exit nonzero on regression")
    args = ap.parse_args(argv)
    result = run(check=args.check)
    if args.check:
        if result.get("failures"):
            print(f"bench_mfu --check FAILED: {result['failures']}\n"
                  + json.dumps(result, indent=1, default=str))
            return 1
        print(f"bench_mfu --check OK (6/6 legs: remat "
              f"{result['remat_search']['remat_assignment']}, pred mem "
              f"-{result['remat_pred_mem_reduction']:.1%}, live temp "
              f"-{result['remat_live_temp_reduction']:.1%}, fused-ce diff "
              f"{result['fused_ce_max_diff']:.2g}, "
              f"{result['op_attribution']['baseline']['n_rows']} attr rows)")
        return 0
    print(json.dumps(result, indent=1, default=str))
    if args.out:
        with open(args.out, "w") as f:
            json.dump(result, f, indent=1, default=str)
        print(f"wrote {args.out}", file=sys.stderr)
    return 0 if not result.get("failures") else 1


if __name__ == "__main__":
    sys.exit(main())
