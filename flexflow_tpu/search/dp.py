"""Frontier dynamic program with beam pruning.

Reference analog: `SearchHelper::graph_cost<T>` (src/runtime/graph.cc:1586)
— Unity's memoized DP that splits the PCG at post-dominators (sequence
splits) and over machine resources (nonsequence splits). The TPU formulation
exploits the same structure differently: processing layers in topological
order, the DP state is the layout assignment of the **live frontier**
(tensors still awaited by a future consumer). On a chain the frontier is one
tensor and the DP is exact — exactly the reference's sequence split; at joins
(residual connections) the frontier carries both tensors, which prices the
branch interaction exactly rather than approximating it. Beam pruning bounds
the state count on wide graphs (DLRM's 26-table concat), playing the role of
the reference's best-first budget (substitution.cc:2229-2311).

Memory is tracked per state and a quadratic penalty applies beyond the HBM
budget (the memory-aware lambda search analog, graph.cc:2046-2160).
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Sequence, Tuple

from flexflow_tpu.core.graph import topo_order
from flexflow_tpu.parallel.machine import MachineSpec
from flexflow_tpu.search import cost_model as cm
from flexflow_tpu.search import memo
from flexflow_tpu.search.candidates import Candidate, layer_candidates

# Process-wide search instrumentation (the search fast path's observable):
# calls = search_graph invocations, expansions = (beam entry x candidate)
# inner-loop evaluations (the DP's unit of work — a strategy-cache hit must
# leave this at 0), layers_skipped / prefix_hits = tier-3 prefix reuse,
# cands_pruned / finalists_pruned = the learned pruner's cuts (ISSUE 14:
# per-layer candidates dropped before expansion, layout finalists dropped
# before the event-driven re-rank).
SEARCH_STATS: Dict[str, int] = {}


def reset_search_stats() -> None:
    SEARCH_STATS.update(calls=0, expansions=0, layers_skipped=0,
                        prefix_hits=0, prefix_misses=0,
                        cands_pruned=0, finalists_pruned=0)


reset_search_stats()


# canonical None|str|tuple form per dim — ONE implementation, shared with
# the memo/prefix-cache keys so layout canonicalization can never drift
# between the DP's frontier keys and the tier-2/3 cache keys
_freeze_dims = memo.freeze_dims


def _drop_axis(d, ax):
    if ax is None:
        return d
    if d == ax:
        return None
    if isinstance(d, tuple):
        kept = tuple(a for a in d if a != ax)
        return kept if len(kept) > 1 else (kept[0] if kept else None)
    return d


def _score(cost: float, mem: int, mem_budget: float,
           objective: str = "latency") -> float:
    """Cost scaled by a quadratic over-HBM penalty (memory-aware lambda
    analog). Multiplicative so the penalty has the same units as the cost;
    the small floor keeps the penalty alive even at zero accumulated cost.

    `objective` is the serving-search knob (--serve-objective):
      "latency"    — rank by time alone under the budget (training default,
                     and the decode-latency regime).
      "throughput" — under the budget, memory is not free: every byte a
                     strategy holds is a byte the KV cache can't turn into
                     concurrent sequences, so the score carries a mild
                     linear memory-pressure term. Over budget both
                     objectives fall off the same quadratic cliff."""
    if mem > mem_budget:
        over = (mem - mem_budget) / mem_budget
        return (cost + 1e-9) * (1.0 + 10.0 * over * over)
    if objective == "throughput":
        return cost * (1.0 + 0.25 * mem / mem_budget)
    return cost


@dataclasses.dataclass
class SearchResult:
    choices: Dict[str, Candidate]  # layer name -> chosen candidate
    cost: float                    # predicted step time (s)
    mem_bytes: int                 # predicted per-device memory high-water
    # layer name -> chosen remat policy ("dots"/"full"; "none" omitted) —
    # populated only when the DP searched remat_policies (ISSUE 12)
    remat: Dict[str, str] = dataclasses.field(default_factory=dict)


# ------------------------------------------------- tier-3 incremental DP
class DPPrefixCache:
    """Cross-graph reuse of DP beam states for the substitution loop.

    After a GraphXfer rewrite, every layer BEFORE the rewrite site is
    unchanged — but `search_graph` re-ran the whole frontier DP anyway.
    This cache snapshots the (pruned) beam after each layer, keyed by a
    canonical, name/guid-free identity of the graph prefix plus the set of
    prefix tensors still live at that boundary; a later `search_graph` on a
    rewritten clone resumes from the deepest matching snapshot and only
    re-prices the affected frontier window (the analog of the reference's
    memoized sequence-split sub-results, graph.cc:1586).

    Correctness: two graphs share a snapshot iff (a) their prefix rows
    (op/params/wiring/pins/weight specs + graph-input specs) are identical —
    so per-layer candidates, edge costs and within-prefix liveness coincide
    — and (b) the set of prefix tensors consumed at-or-after the boundary is
    identical (frontier composition depends on suffix consumption). Beam
    frontiers are stored under canonical tensor coordinates (producer topo
    position, output slot) and remapped to the resuming graph's guids.

    One instance is only valid for a fixed (machine, beam_width, mem_budget,
    cost_fn, enable flags, learned pruner) — stored traces index into the
    (possibly learned-pruned) candidate lists — and the substitution loop
    creates one per search.
    """

    def __init__(self, max_entries: int = 100_000):
        self.snaps: Dict[Tuple, Dict] = {}
        self.max_entries = max_entries

    def get(self, key):
        return self.snaps.get(key)

    def put(self, key, beam):
        if len(self.snaps) < self.max_entries:
            self.snaps[key] = beam


def _prefix_identity(layers, input_tensors, pins):
    """Per-layer cumulative canonical keys + guid -> coordinate map. A
    coordinate is ("in", input_idx) or (producer_topo_idx, output_slot).
    Keys are rolling sha256 hexdigests of the canonical rows — O(row) per
    layer and O(1) to hash/compare in the snapshot dict (a nested-tuple
    chain would re-walk the whole prefix on every lookup)."""
    import hashlib

    from flexflow_tpu.search.pcg import _freeze as _freeze_params

    coords: Dict[int, Tuple] = {
        t.guid: ("in", i) for i, t in enumerate(input_tensors)}
    h = hashlib.sha256(repr(tuple(
        (t.spec.shape, t.spec.dtype) for t in input_tensors)).encode())
    keys = []
    for li, layer in enumerate(layers):
        row = (layer.op_type.value, _freeze_params(layer.params),
               tuple(coords.get(t.guid, ("?", t.guid)) for t in layer.inputs),
               pins.get(layer.name) if pins else None,
               memo.freeze_weight_specs(layer.weight_specs),
               memo.branches_signature(layer))
        h.update(repr(row).encode())
        keys.append(h.hexdigest())  # digest-so-far: cumulative prefix id
        for oi, o in enumerate(layer.outputs):
            coords[o.guid] = (li, oi)
    return keys, coords


def _live_coords(li, n_layers, coords, last_use):
    """Canonical coords of tensors in the DP frontier after layer li (the
    exact rule the DP applies: produced at or before li, consumed after li —
    plus the last layer's outputs, which the DP always keeps)."""
    out = set()
    for g, c in coords.items():
        produced = -1 if c[0] == "in" else c[0]
        if produced > li:
            continue
        if last_use.get(g, -1) > li or (li == n_layers - 1 and produced == li):
            out.add(c)
    return frozenset(out)


def search_graph(model, machine, *args, **kwargs):
    """Telemetry shim over the frontier DP (_search_graph_impl keeps the
    real signature): one "search/dp" span per DP run, carrying the
    expansion count this run added to SEARCH_STATS — the per-candidate-
    graph cost the unity loop pays is visible in the trace stream."""
    from flexflow_tpu import telemetry as tel

    if not tel.enabled():
        return _search_graph_impl(model, machine, *args, **kwargs)
    t0 = tel.now_us()
    e0 = SEARCH_STATS.get("expansions", 0)
    r = _search_graph_impl(model, machine, *args, **kwargs)
    tel.record("search/dp", t0, cat="compile",
               layers=len(model.layers),
               expansions=SEARCH_STATS.get("expansions", 0) - e0,
               cost_s=(r.cost if not isinstance(r, list)
                       else (r[0].cost if r else None)))
    return r


def _search_graph_impl(model, machine: MachineSpec, beam_width: int = 64,
                 enable_parameter: bool = True, enable_attribute: bool = True,
                 mem_budget: Optional[float] = None,
                 cost_fn=None,
                 pins: Optional[Dict[str, str]] = None,
                 topk: int = 1,
                 prefix_cache: Optional[DPPrefixCache] = None,
                 opt_mem: "Optional[cm.OptMemSpec]" = None,
                 objective: str = "latency",
                 inference: bool = False,
                 remat_policies: Optional[Sequence[str]] = None,
                 learned=None,
                 ) -> "SearchResult | List[SearchResult]":
    """cost_fn(layer, cand) -> seconds overrides the analytic op time
    (hook for the measured path, search/measure.py).

    `learned` (search/learned_cost.LearnedCost, --simulator-mode learned
    with a trained model on disk) turns on the LEARNED DP PRUNER: before a
    layer's candidates expand against the beam, those whose learned time
    exceeds the layer's best by learned.prune_ratio are dropped
    (passthroughs and the memory-leanest candidate always survive), so the
    cut shows up directly in SEARCH_STATS["expansions"]. Pinned layers are
    never pruned — a pin is an instruction, not a suggestion. None (the
    default, and every mode but "learned") keeps the exact candidate sets
    and expansion counts of today.

    `remat_policies` promotes rematerialization to a PER-LAYER search
    dimension (ISSUE 12): each compute candidate expands once per policy
    in the set (cost_model.REMAT_POLICY_SPECS — none / dots / full), the
    policy's recompute time is added to the step cost and its keep
    fraction scales the layer outputs' live-activation multiplier, so
    under a memory cap the DP trades HBM for FLOPs layer by layer instead
    of being forced into ZeRO or pipelining. None / ("none",) (and any
    inference search — no backward stash exists) reproduces the exact
    pre-remat DP: same expansions, costs and memory.

    `objective` ("latency" | "throughput") selects the _score variant the
    beam ranks by — the serving search's latency-vs-throughput knob.
    `inference` drops the training-only cost terms: no gradient all-reduce
    on the op edges and no backward-pass copy in the live-activation
    accounting (forward values only) — a serving program never holds
    grads, so pricing them would bias the decode search toward
    weight-sharded layouts for the wrong reason.

    `opt_mem` (cost_model.OptMemSpec) is the optimizer's memory model:
    moments counted and sized by the optimizer's actual state_dtype, and
    divided by the ZeRO data-axis degree when zero sharding is on — so a
    memory-constrained search prices data parallelism at what the runtime
    really allocates. None keeps the legacy params-x4 accounting. Under
    ZeRO the grad-sync term is priced as reduce-scatter + all-gather
    (numerically equal to the all-reduce on a ring — see
    cost_model.grad_sync_time).

    `prefix_cache` (tier-3 fast path) resumes the DP from the deepest beam
    snapshot whose canonical graph prefix + boundary liveness match this
    graph, re-pricing only the frontier window a rewrite touched. The
    caller guarantees one cache instance per (machine, beam_width,
    mem_budget, cost_fn, enable flags) combination.

    `model` is anything with .layers / .input_tensors (FFModel or a PCG).
    `pins` restricts named layers to one candidate (by candidate name) — the
    substitution engine's hook: a rewritten PCG is costed with its rewrite
    choices pinned while the DP still lays out every unpinned op.

    `topk > 1` returns the best `topk` finalists (List[SearchResult], one per
    distinct terminal frontier) for the event-driven simulator re-rank.
    Diversity caveat: the beam keeps ONE best trace per frontier layout, so
    chain-shaped models whose strategies converge to the same terminal
    layout yield a single finalist — the re-rank then has nothing to decide
    and taskgraph mode degrades gracefully to the additive choice. Interior
    diversity (e.g. which layer to shard, the position-dependent-exposure
    case) is exercised through the MCMC taskgraph evaluator instead."""
    SEARCH_STATS["calls"] = SEARCH_STATS.get("calls", 0) + 1
    layers = topo_order(model.layers)
    batch_sizes = {t.shape[0] for t in model.input_tensors if t.ndim > 0}
    mem_budget = mem_budget or machine.hbm_bytes
    from flexflow_tpu.search.candidates import _batch_axes

    _batch_axes_cached = _batch_axes(machine)

    # liveness: tensor guid -> index of last consuming layer
    last_use: Dict[int, int] = {}
    for li, layer in enumerate(layers):
        for t in layer.inputs:
            last_use[t.guid] = li

    # initial frontier: graph inputs, data-parallel layout
    from flexflow_tpu.search.candidates import _dp_dims

    init_frontier = tuple(sorted(
        (t.guid, _freeze_dims(_dp_dims(t.shape, machine, batch_sizes)))
        for t in model.input_tensors))
    specs = {t.guid: t.spec for t in model.input_tensors}

    # inference holds no backward copies: forward value only (1x vs 2x)
    act_mult = 1 if inference else 2

    # searched remat: "none" is always present at index 0 (passthrough
    # candidates pin to it, and the search must be able to keep any layer
    # unrematerialized). Inference has no backward stash to free.
    policies: Tuple[str, ...] = tuple(dict.fromkeys(
        ("none",) + tuple(remat_policies or ())))
    if inference:
        policies = ("none",)

    def _live_act_bytes(frontier_map, mults=None) -> int:
        # act_mult x: forward value + gradient held for the backward pass;
        # outputs of remat'd layers carry a reduced per-guid multiplier
        # (cost_model.remat_act_mult)
        if not mults:
            return sum(act_mult * cm.shard_bytes(specs[g], list(d), machine)
                       for g, d in frontier_map.items())
        return int(sum(
            mults.get(g, act_mult) * cm.shard_bytes(specs[g], list(d),
                                                    machine)
            for g, d in frontier_map.items()))

    def score(c: float, m: int) -> float:
        return _score(c, m, mem_budget, objective)

    # beam entries: frontier -> (cost, w_mem, act_high, trace, mults)
    # w_mem = cumulative persistent weight memory (params+grads+opt moments:
    # ALL of it is resident for the whole step, init allocates up front);
    # act_high = max over layers of live activation bytes. The reported
    # high-water is final_w_mem + act_high — weights from layers not yet
    # processed are still counted against an early activation peak.
    # trace elements are (candidate_idx, policy_idx); mults maps a frontier
    # guid to its effective activation multiplier when a remat policy
    # reduced it (absent guid = act_mult).
    init_act = _live_act_bytes(dict(init_frontier))
    beam: Dict[Tuple, Tuple[float, int, int, Tuple, Dict[int, float]]] = {
        init_frontier: (0.0, 0, init_act, (), {})}
    cand_cache: Dict[str, List[Candidate]] = {}

    # tier-3: resume from the deepest matching prefix snapshot
    resume_li = -1
    pc_keys = pc_coords = None
    if prefix_cache is not None:
        pc_keys, pc_coords = _prefix_identity(layers, model.input_tensors,
                                              pins)
        inv = {c: g for g, c in pc_coords.items()}
        for li in range(len(layers) - 1, -1, -1):
            live = _live_coords(li, len(layers), pc_coords, last_use)
            snap = prefix_cache.get((pc_keys[li], live))
            if snap is None:
                continue
            resumed = {}
            for cf, entry in snap.items():
                guids = [(inv.get(c), d) for c, d in cf]
                if any(g is None for g, _ in guids):
                    resumed = None
                    break
                # entry mults were stored under canonical coords too —
                # remap back to this graph's guids (all mult guids are
                # frontier guids, so the same inv map covers them)
                ec, ew, ea, et, emu = entry
                mu = {inv[c]: m for c, m in emu}
                resumed[tuple(sorted(guids))] = (ec, ew, ea, et, mu)
            if resumed:
                beam = resumed
                resume_li = li
                SEARCH_STATS["prefix_hits"] = SEARCH_STATS.get(
                    "prefix_hits", 0) + 1
                SEARCH_STATS["layers_skipped"] = SEARCH_STATS.get(
                    "layers_skipped", 0) + li + 1
                break
        else:
            SEARCH_STATS["prefix_misses"] = SEARCH_STATS.get(
                "prefix_misses", 0) + 1

    for li, layer in enumerate(layers):
        for o in layer.outputs:
            specs[o.guid] = o.spec
        cands = layer_candidates(layer, machine, batch_sizes,
                                 enable_parameter, enable_attribute)
        if pins and layer.name in pins:
            want = pins[layer.name]
            sel = [c for c in cands if c.name == want]
            if not sel:
                raise KeyError(f"pinned candidate {want!r} not available for "
                               f"{layer.name} (have {[c.name for c in cands]})")
            cands = sel
        elif learned is not None:
            cands, dropped = learned.prune_candidates(layer, cands)
            if dropped:
                SEARCH_STATS["cands_pruned"] = SEARCH_STATS.get(
                    "cands_pruned", 0) + dropped
        cand_cache[layer.name] = cands
        if li <= resume_li:
            continue  # beam restored from snapshot; candidates only decode traces
        new_beam: Dict[Tuple, Tuple[float, int, int, Tuple, Dict]] = {}
        for frontier, (cost, w_mem, act_high, trace, mults) in beam.items():
            fmap = dict(frontier)
            fmap_act = _live_act_bytes(fmap, mults)

            def commit(c, wm, out_dims, new_mults, ci, pi):
                # peak while this layer runs: ALL its inputs (even those
                # dying here) are live together with its outputs (out guids
                # are new, so the two contributions are disjoint)
                ah = max(act_high,
                         fmap_act + _live_act_bytes(out_dims, new_mults))
                # new frontier: drop dead tensors, add outputs
                nf = {g: d for g, d in fmap.items()
                      if last_use.get(g, -1) > li}
                for o in layer.outputs:
                    if last_use.get(o.guid, -1) > li or layer is layers[-1]:
                        nf[o.guid] = out_dims[o.guid]
                nm = {g: m for g, m in new_mults.items() if g in nf} \
                    if new_mults else {}
                key = tuple(sorted(nf.items()))
                prev = new_beam.get(key)
                if prev is None or score(c, wm + ah) < score(
                        prev[0], prev[1] + prev[2]):
                    new_beam[key] = (c, wm, ah, trace + ((ci, pi),), nm)

            for ci, cand in enumerate(cands):
                if cand.passthrough:
                    SEARCH_STATS["expansions"] = SEARCH_STATS.get(
                        "expansions", 0) + 1
                    c = cost
                    # identity layout marker: adopt input-0's layout (minus
                    # drop_axis). When dropping the axis actually changes the
                    # layout (the input really was sharded over it), the
                    # implied all-gather is priced — a free drop would let
                    # the search hide a real collective (e.g. a tp_col
                    # output feeding a later rewrite's Replicate).
                    cur0 = fmap.get(layer.inputs[0].guid) if layer.inputs else None
                    if cur0 is None:
                        continue
                    od = tuple(_drop_axis(d, cand.drop_axis) for d in cur0)
                    if od != cur0:
                        c += cm.reshard_time(layer.inputs[0].spec,
                                             list(cur0), list(od), machine)
                    # passthrough outputs alias input-0: they inherit its
                    # multiplier (a remat'd producer's saving propagates
                    # through resharding markers), and "none" (index 0) is
                    # the only policy — there is no compute to re-run
                    nm = mults
                    if mults and layer.inputs:
                        m0 = mults.get(layer.inputs[0].guid)
                        if m0 is not None:
                            nm = dict(mults)
                            for o in layer.outputs:
                                nm[o.guid] = m0
                    commit(c, w_mem, {o.guid: od for o in layer.outputs},
                           nm, ci, 0)
                    continue
                SEARCH_STATS["expansions"] = SEARCH_STATS.get(
                    "expansions", 0) + 1
                # edge costs: reshard each input from its frontier layout
                feasible = True
                edge_comm = 0.0
                for ii, tin in enumerate(layer.inputs):
                    cur = fmap.get(tin.guid)
                    if cur is None:
                        feasible = False
                        break
                    want = _freeze_dims(cand.in_dims[ii] if ii < len(cand.in_dims)
                                        else [None] * tin.spec.ndim)
                    edge_comm += cm.reshard_time(tin.spec, list(cur), list(want), machine)
                if not feasible:
                    continue
                total = cost_fn(layer, cand) if cost_fn else cand.op_time(layer, machine)
                # compute/comm overlap (the event-driven-simulator gap,
                # reference simulator.h:785-827, closed-form): XLA's
                # async collectives hide input-edge + op-inherent
                # collective time behind up to overlap_frac of the
                # consumer's pure compute. Purely additive costing
                # (overlap_frac=0) systematically over-prices strategies
                # whose collectives ride behind the next op's matmuls.
                op_comm = cand.extra_comm
                if not inference:
                    op_comm += cm.grad_sync_time(
                        layer.weight_specs, cand.weight_dims, machine,
                        _batch_axes_cached,
                        zero=bool(opt_mem and opt_mem.zero_axes))
                comp = max(0.0, total - op_comm)
                base_c = cost + cm.overlapped_step_cost(
                    comp, edge_comm + op_comm, machine)
                wm = w_mem + cand.weight_mem_bytes(layer, machine, opt_mem)
                out_dims = {
                    o.guid: _freeze_dims(cand.out_dims[oi] if oi < len(cand.out_dims)
                                         else [None] * o.spec.ndim)
                    for oi, o in enumerate(layer.outputs)}
                # the remat dimension: one expansion per policy — "none"
                # replays the pre-remat DP exactly; "dots"/"full" pay the
                # recompute fraction of THIS op's step cost and shrink the
                # outputs' live multiplier (cost_model REMAT_POLICY_SPECS)
                for pi, pol in enumerate(policies):
                    if pi:  # the "none" expansion was counted above
                        SEARCH_STATS["expansions"] = SEARCH_STATS.get(
                            "expansions", 0) + 1
                    if pol == "none":
                        commit(base_c, wm, out_dims, mults, ci, pi)
                        continue
                    c = base_c + cm.remat_recompute_time(total, pol)
                    pm = cm.remat_act_mult(pol, act_mult)
                    nm = dict(mults)
                    for o in layer.outputs:
                        nm[o.guid] = pm
                    commit(c, wm, out_dims, nm, ci, pi)
        # beam prune (ranked by cost + memory penalty; wm+ah understates the
        # final high-water by weights not yet placed, uniformly across states)
        if len(new_beam) > beam_width:
            ranked = sorted(new_beam.items(),
                            key=lambda kv: score(kv[1][0], kv[1][1] + kv[1][2]))
            new_beam = dict(ranked[:beam_width])
        beam = new_beam
        if not beam:
            raise RuntimeError(f"search dead-ended at layer {layer.name}")
        if prefix_cache is not None:
            # snapshot the pruned beam under canonical coordinates (store
            # key carries the boundary liveness so only suffixes consuming
            # the same prefix tensors resume from it)
            live = _live_coords(li, len(layers), pc_coords, last_use)
            # key=repr: coords mix ("in", i) and (topo_idx, slot) tuples,
            # which plain tuple ordering cannot compare
            snap = {}
            for f, e in beam.items():
                ec, ew, ea, et, emu = e
                cmu = tuple(sorted(((pc_coords[g], m)
                                    for g, m in emu.items()), key=repr))
                snap[tuple(sorted(((pc_coords[g], d) for g, d in f),
                                  key=repr))] = (ec, ew, ea, et, cmu)
            prefix_cache.put((pc_keys[li], live), snap)

    def _to_result(entry) -> SearchResult:
        cost, wm, ah, trace, _mults = entry
        choices: Dict[str, Candidate] = {}
        remat: Dict[str, str] = {}
        for layer, (ci, pi) in zip(layers, trace):
            choices[layer.name] = cand_cache[layer.name][ci]
            if policies[pi] != "none":
                remat[layer.name] = policies[pi]
        return SearchResult(choices=choices, cost=cost, mem_bytes=wm + ah,
                            remat=remat)

    ranked = sorted(beam.values(),
                    key=lambda v: score(v[0], v[1] + v[2]))
    if topk > 1:
        # distinct finalists for the event-driven re-rank (search/simulator
        # .py): the final beam holds the best trace per terminal frontier
        # layout — different layouts are materially different strategies
        return [_to_result(e) for e in ranked[:topk]]
    return _to_result(ranked[0])


# ------------------------------------------------------- pipeline search
@dataclasses.dataclass
class PipelineSearchResult:
    """One costed inter-op (pipeline) strategy: where to cut, how to
    schedule, and what it is predicted to cost — comparable against the
    non-pipelined SearchResult through `score` (same _score rule the
    frontier DP ranks by, so the memory penalty speaks the same units)."""

    stages: int
    cuts: Tuple[int, ...]          # topo indices: cut AFTER layers[i]
    schedule: str                  # "gpipe" | "1f1b" ("none" when stages=1)
    cost: float                    # predicted time for ONE update (M microbatches)
    mem_bytes: int                 # per-device high-water of the WORST stage
    bubble: float                  # predicted bubble fraction of the schedule
    score: float                   # _score(cost, mem_bytes, mem_budget)
    stage_costs: List[float] = dataclasses.field(default_factory=list)
    choices: Optional[Dict[str, Candidate]] = None  # merged per-stage layouts


def stage_machine_for(machine: MachineSpec, num_stages: int) -> MachineSpec:
    """The machine ONE pipeline stage runs on: the full machine with the
    pipe dimension factored out. An explicit "pipe" axis is dropped (its
    degree must equal num_stages); otherwise the batch ("data") axis degree
    divides by num_stages — stages claim whole device groups, the groups
    keep data-parallelism inside."""
    axes = dict(machine.mesh_axes)
    if "pipe" in axes:
        if axes["pipe"] != num_stages:
            raise ValueError(f"mesh pipe={axes['pipe']} != "
                             f"--pipeline-stages {num_stages}")
        axes.pop("pipe")
    else:
        from flexflow_tpu.search.candidates import _batch_axes

        ba = next(iter(_batch_axes(machine)), None)
        if ba is None or axes.get(ba, 1) % num_stages != 0:
            raise ValueError(
                f"cannot split {num_stages} pipeline stages out of mesh "
                f"{axes}: no batch axis with degree divisible by "
                f"{num_stages} (add pipe={num_stages} to --mesh)")
        axes[ba] //= num_stages
        if axes[ba] == 1 and len(axes) > 1:
            axes.pop(ba)
    if not axes:
        axes = {"data": 1}
    return MachineSpec(mesh_axes=axes, chip=machine.chip,
                       flops=machine.flops, hbm_bw=machine.hbm_bw,
                       hbm_bytes=machine.hbm_bytes,
                       ici_bw=dict(machine.ici_bw),
                       dcn_axes=tuple(a for a in machine.dcn_axes
                                      if a in axes),
                       dcn_bw=machine.dcn_bw,
                       mxu_flop_overhead=machine.mxu_flop_overhead,
                       mxu_min_dim=machine.mxu_min_dim,
                       axis_type=dict(machine.axis_type),
                       overlap_frac=machine.overlap_frac)


def search_pipelined(model, machine: MachineSpec, num_stages: int,
                     microbatches: int, schedule: str = "1f1b",
                     mem_budget: Optional[float] = None,
                     beam_width: int = 16, cost_fn=None,
                     enable_parameter: bool = True,
                     enable_attribute: bool = True,
                     opt_mem: "Optional[cm.OptMemSpec]" = None,
                     max_candidates: int = 12,
                     ) -> Optional[PipelineSearchResult]:
    """Search over stage cut points (the reference's sequential inter-op
    splits, graph.cc sequence enumeration; JaxPP's stage assignment): each
    candidate cut tuple (search/candidates.stage_cut_candidates) is costed
    by running the frontier DP per stage SUB-GRAPH on the stage machine
    (layouts inside a stage compose freely with the pipeline split), then
    the schedule's event-driven replay prices the whole update:

      cost  = pipeline_step_time(per-stage fwd/bwd, boundary P2P, M)
      mem   = worst stage's weight high-water + the schedule's in-flight
              boundary stash (M for gpipe, min(S, M) for 1f1b) — per-stage
              weights divide ~S x, which is what lets a memory-capped
              search pick pipelining when pure data parallelism can't fit.

    Returns the best PipelineSearchResult, or None when the graph has too
    few single-tensor cut points for `num_stages`."""
    from flexflow_tpu.search.candidates import stage_cut_candidates
    from flexflow_tpu.search.pcg import PCG

    if num_stages <= 1:
        raise ValueError("search_pipelined needs num_stages > 1")
    smach = stage_machine_for(machine, num_stages)
    mem_budget = mem_budget or machine.hbm_bytes
    layers = topo_order(model.layers)
    combos = stage_cut_candidates(model, smach, num_stages,
                                  max_candidates=max_candidates)
    if not combos:
        return None
    inflight = cm.pipeline_inflight_acts(schedule, num_stages, microbatches)
    best: Optional[PipelineSearchResult] = None
    for cuts in combos:
        bounds = [-1] + list(cuts) + [len(layers) - 1]
        stage_results: List[SearchResult] = []
        boundary_bytes: List[int] = []
        feasible = True
        for si in range(num_stages):
            seg = layers[bounds[si] + 1:bounds[si + 1] + 1]
            internal = {o.guid for l in seg for o in l.outputs}
            ext, seen = [], set()
            for l in seg:
                for t in l.inputs:
                    if t.guid not in internal and t.guid not in seen:
                        seen.add(t.guid)
                        ext.append(t)
            try:
                r = search_graph(PCG.from_layers(seg, ext), smach,
                                 beam_width=beam_width,
                                 mem_budget=mem_budget, cost_fn=cost_fn,
                                 enable_parameter=enable_parameter,
                                 enable_attribute=enable_attribute,
                                 opt_mem=opt_mem)
            except (KeyError, RuntimeError):
                feasible = False
                break
            stage_results.append(r)
        if not feasible:
            continue
        from flexflow_tpu.search.candidates import cut_boundary_tensor

        for ci in cuts:
            bt = cut_boundary_tensor(layers, ci)
            boundary_bytes.append(
                cm.shard_bytes(bt.spec,
                               _dp_dims_for(bt.spec.shape, smach, model),
                               smach))
        # phase split matching the executor (cost_model
        # .pipeline_phase_times): fwd c/3, bwd a FULL c (recompute-based),
        # last stage's fwd fused into its backward
        fwd, bwd = cm.pipeline_phase_times([r.cost for r in stage_results])
        cost = cm.pipeline_step_time(fwd, bwd, boundary_bytes, machine,
                                     schedule, microbatches)
        bubble = cm.pipeline_bubble(schedule, microbatches, fwd, bwd)
        # per-device memory of stage si: its own weights + live acts, plus
        # the schedule's stashed boundary inputs (value + recompute grad)
        mems = []
        for si, r in enumerate(stage_results):
            stash = 0
            if si > 0:
                stash = 2 * inflight * boundary_bytes[si - 1]
            mems.append(r.mem_bytes + stash)
        mem = max(mems)
        score = _score(cost, mem, mem_budget)
        if best is None or score < best.score:
            merged: Dict[str, Candidate] = {}
            for r in stage_results:
                merged.update(r.choices)
            best = PipelineSearchResult(
                stages=num_stages, cuts=tuple(cuts), schedule=schedule,
                cost=cost, mem_bytes=mem, bubble=bubble, score=score,
                stage_costs=[r.cost for r in stage_results],
                choices=merged)
    if best is not None:
        # event-replay validation of the winning schedule: the simulator
        # re-times the tick grid and must agree with the cost above
        from flexflow_tpu.search.simulator import simulate_pipeline

        vf, vb = cm.pipeline_phase_times(best.stage_costs)
        rep = simulate_pipeline(vf, vb, best.schedule, microbatches)
        best.bubble = rep["bubble"]
    return best


def _dp_dims_for(shape, machine: MachineSpec, model):
    from flexflow_tpu.search.candidates import _dp_dims

    batch_sizes = {t.shape[0] for t in model.input_tensors if t.ndim > 0}
    return _dp_dims(shape, machine, batch_sizes)


def choose_pipeline(model, machine: MachineSpec, microbatches: int,
                    stages_options: Sequence[int] = (1, 2, 4),
                    schedule: str = "1f1b",
                    mem_budget: Optional[float] = None,
                    beam_width: int = 16,
                    opt_mem: "Optional[cm.OptMemSpec]" = None,
                    ) -> "PipelineSearchResult":
    """Pick the best of {non-pipelined, pipelined at each S} under the
    SAME _score rule (cost x quadratic over-HBM penalty). The non-pipelined
    entry is the plain frontier DP on the full machine, its cost scaled to
    the same unit (M microbatches = one update); pipelining wins exactly
    when the memory cap makes replicating every stage's weights on every
    device infeasible and the bubble costs less than the penalty — the
    MULTICHIP-style assertion tests/test_pipeline.py makes."""
    mem_budget = mem_budget or machine.hbm_bytes
    results: List[PipelineSearchResult] = []
    for s in stages_options:
        if s <= 1:
            r0 = search_graph(model, machine, beam_width=beam_width,
                              mem_budget=mem_budget, opt_mem=opt_mem)
            results.append(PipelineSearchResult(
                stages=1, cuts=(), schedule="none",
                cost=microbatches * r0.cost, mem_bytes=r0.mem_bytes,
                bubble=0.0,
                score=_score(microbatches * r0.cost, r0.mem_bytes,
                             mem_budget),
                stage_costs=[r0.cost], choices=r0.choices))
            continue
        try:
            r = search_pipelined(model, machine, s, microbatches,
                                 schedule=schedule, mem_budget=mem_budget,
                                 beam_width=beam_width, opt_mem=opt_mem)
        except ValueError:
            r = None
        if r is not None:
            results.append(r)
    if not results:
        raise RuntimeError("no feasible parallelization found")
    return min(results, key=lambda r: r.score)
