"""Learned per-op cost model — self-calibrating pricing for the search.

ROADMAP item 2 / ISSUE 14 tentpole. The reference line: FlexFlow's thesis
("Beyond Data and Model Parallelism for DNNs", arXiv 1807.05358) is that a
better-priced search picks measurably better strategies, and "A Learned
Performance Model for TPUs" (arXiv 2008.01040) showed a small learned model
over (opcode, shapes, dtype, layout) features beats the analytic roofline at
exactly that pricing job. Every input already exists in this repo: profiled
fits emit one featurized `op/attr` event per placed op (attribution.py),
tools/span_dataset.py folds them into a deduplicated per-feature-key corpus,
and the `--simulator-mode` knob selects the pricing tier.

This module is deliberately dependency-free (numpy only — no sklearn, no
new packages): per-op-kind RIDGE REGRESSION in log-space over a small
numeric featurization of the 2008.01040 feature dict, fronted by an
EXACT-KEY table (a corpus row whose feature key matches the queried op is a
measurement, not a prediction — return its pooled mean directly). The model
serializes to JSON with a content-hash fingerprint; the strategy cache keys
on that fingerprint so a refit invalidates every strategy the stale model
priced (strategy_cache.learned_fingerprint).

Three mounts (all gated on `--simulator-mode learned` AND a model file
resolving — with either absent, behavior is bitwise-identical to today):

1. the PRICING TIER (search/optimize.py): `LearnedCost.op_time` has the
   exact `cost_fn(layer, cand) -> total seconds` contract of
   MeasuredCost.op_time, so learned per-op times feed the SAME frontier-DP
   cost hook and the same `sim.rerank` task times. An op whose kind the
   model never saw falls back per-op to the analytic price
   (`cand.op_time`) and counts as a coverage miss — the coverage fraction
   rides the `search/learned_cost` telemetry event and the strategy-cache
   meta.
2. the LEARNED DP PRUNER (search/dp.py + unity.py): per-layer, candidates
   whose learned time exceeds the layer's best by DP_PRUNE_RATIO are
   dropped before frontier expansion (the memory-leanest candidate and all
   passthroughs always survive — a memory-capped search keeps its escape
   hatch); per-segment, layout finalists whose learned strategy score
   exceeds the best by FINALIST_MARGIN skip the expensive event-driven
   re-rank (`search/sim_rerank`). Both cuts are pinned winner-safe by
   tools/bench_learned.py on the gpt2 twin.
3. the SELF-CALIBRATING REFIT LOOP (tools/refit_cost_model.py): a drift
   warning now points at (and `--auto-refit` triggers) a refit from the
   run's own telemetry instead of a hand-run calibration sweep —
   `auto_refit()` below is the fit-end hook compile.py calls.
"""

from __future__ import annotations

import hashlib
import json
import math
import os
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

# model file schema (bump when the payload layout changes incompatibly)
MODEL_SCHEMA_VERSION = 1

# per-layer candidate pruning: drop candidates whose learned op time exceeds
# ratio x the layer's best learned time (None disables — bench_learned.py
# toggles this for the pruning on/off leg). Generous on purpose: per-op
# times ignore the resharding edge costs the DP prices, so a tight ratio
# could prune a candidate that wins on cheaper edges.
DP_PRUNE_RATIO: Optional[float] = 2.0

# finalist pruning before the event-driven re-rank: drop finalists whose
# learned strategy score exceeds (1 + margin) x the best finalist's.
FINALIST_MARGIN: Optional[float] = 0.25

# ridge regularization (standardized features, log-space target)
RIDGE_L2 = 1e-2

# a kind needs this many corpus rows before it gets a fitted submodel
# (fewer rows are still served by the exact-key table)
MIN_ROWS_PER_KIND = 3


# ------------------------------------------------------------ featurization
def _dtype_bytes(dtype: str) -> float:
    for width, nbytes in (("64", 8.0), ("32", 4.0), ("16", 2.0), ("8", 1.0)):
        if width in dtype:
            return nbytes
    return 4.0


def _elements(shapes) -> List[float]:
    out = []
    for s in shapes or []:
        n = 1.0
        for d in s or []:
            n *= max(1.0, float(d))
        out.append(n)
    return out


def feature_vector(features: Dict[str, Any],
                   predicted_s: Optional[float] = None,
                   roofline_s: Optional[float] = None) -> List[float]:
    """Numeric vector from one 2008.01040 feature dict (attribution.
    op_features / a corpus row's "features"). The analytic predicted and
    roofline times ride along as features — the ridge then learns a
    RESIDUAL CORRECTION on top of the analytic model rather than raw
    physics from scratch, which is what makes tiny corpora workable."""
    ins = _elements(features.get("in_shapes"))
    outs = _elements(features.get("out_shapes"))
    ws = _elements(list((features.get("weight_shapes") or {}).values()))
    sh = features.get("sharding") or {}
    out_ax = sum(1 for d in (sh.get("out") or []) for a in (d or []) if a)
    w_ax = sum(1 for d in (sh.get("weights") or {}).values()
               for a in (d or []) if a)
    return [
        math.log1p(sum(ins)),
        math.log1p(max(ins) if ins else 0.0),
        math.log1p(sum(outs)),
        math.log1p(sum(ws)),
        float(len(ins)),
        float(out_ax),
        float(w_ax),
        _dtype_bytes(str(features.get("dtype") or "")),
        math.log1p(max(0.0, float(predicted_s or 0.0)) * 1e9),
        math.log1p(max(0.0, float(roofline_s or 0.0)) * 1e9),
    ]


N_FEATURES = 10


# ------------------------------------------------------------------- model
class LearnedCostModel:
    """Per-op-kind ridge over feature_vector + an exact-key measurement
    table. JSON-serializable; `fingerprint` is a content hash of the
    payload, so identical training data reproduces an identical
    fingerprint and any refit that changes a coefficient changes it."""

    def __init__(self, kinds: Dict[str, Dict[str, Any]],
                 exact: Dict[str, float], meta: Dict[str, Any]):
        self.kinds = kinds
        self.exact = exact
        self.meta = meta

    # ------------------------------------------------------------- predict
    def predict_features(self, features: Dict[str, Any],
                         predicted_s: Optional[float] = None,
                         roofline_s: Optional[float] = None,
                         key: Optional[str] = None) -> Optional[float]:
        """Predicted total seconds for one featurized op, or None when the
        op kind is out-of-distribution (caller falls back to analytic)."""
        if key is None:
            from flexflow_tpu.attribution import feature_key

            key = feature_key(features)
        hit = self.exact.get(key)
        if hit is not None:
            return float(hit)
        k = self.kinds.get(str(features.get("op")))
        if k is None:
            return None
        x = np.asarray(feature_vector(features, predicted_s, roofline_s))
        mean = np.asarray(k["mean"])
        std = np.asarray(k["std"])
        z = (x - mean) / std
        log_t = float(np.dot(z, np.asarray(k["coef"])) + k["intercept"])
        return float(min(max(math.exp(min(log_t, 40.0)), 1e-12), 1e6))

    def predict_row(self, row: Dict[str, Any]) -> Optional[float]:
        """Prediction for one span_dataset corpus row (bench MAPE path)."""
        return self.predict_features(row.get("features") or {},
                                     predicted_s=row.get("predicted_s"),
                                     roofline_s=row.get("roofline_s"),
                                     key=row.get("key"))

    # ----------------------------------------------------------------- io
    def to_json(self) -> Dict[str, Any]:
        payload = {
            "schema_version": MODEL_SCHEMA_VERSION,
            "kinds": self.kinds,
            "exact": self.exact,
            "meta": self.meta,
        }
        payload["fingerprint"] = _payload_fingerprint(payload)
        return payload

    @property
    def fingerprint(self) -> str:
        return _payload_fingerprint({
            "schema_version": MODEL_SCHEMA_VERSION,
            "kinds": self.kinds, "exact": self.exact, "meta": self.meta})

    @classmethod
    def from_json(cls, payload: Dict[str, Any]) -> "LearnedCostModel":
        if payload.get("schema_version") != MODEL_SCHEMA_VERSION:
            raise ValueError(
                f"cost model schema {payload.get('schema_version')!r} != "
                f"{MODEL_SCHEMA_VERSION} (re-run tools/refit_cost_model.py)")
        return cls(dict(payload.get("kinds") or {}),
                   {str(k): float(v)
                    for k, v in (payload.get("exact") or {}).items()},
                   dict(payload.get("meta") or {}))

    def save(self, path: str) -> str:
        os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
        tmp = path + f".tmp{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(self.to_json(), f, indent=1, sort_keys=True)
        os.replace(tmp, path)
        return self.fingerprint

    @classmethod
    def load(cls, path: str) -> "LearnedCostModel":
        with open(path) as f:
            return cls.from_json(json.load(f))


def _payload_fingerprint(payload: Dict[str, Any]) -> str:
    blob = json.dumps(payload, sort_keys=True, separators=(",", ":"))
    return hashlib.sha256(blob.encode()).hexdigest()[:16]


# ------------------------------------------------------------------- train
def train(rows: Sequence[Dict[str, Any]], l2: float = RIDGE_L2,
          min_rows: int = MIN_ROWS_PER_KIND) -> LearnedCostModel:
    """Fit the model from span_dataset corpus rows. Rows without a positive
    measured mean are skipped; op kinds with < min_rows measured rows get
    no submodel (their exact keys still serve, unseen keys are OOD)."""
    usable = []
    for r in rows:
        m = (r.get("measured_s") or {}).get("mean")
        if m is not None and m > 0 and isinstance(r.get("features"), dict):
            usable.append((r, float(m)))
    by_kind: Dict[str, List[Tuple[Dict[str, Any], float]]] = {}
    exact: Dict[str, float] = {}
    machines = set()
    for r, m in usable:
        kind = str((r.get("features") or {}).get("op"))
        by_kind.setdefault(kind, []).append((r, m))
        if r.get("key"):
            exact[str(r["key"])] = m
        mfp = r.get("machine")
        if mfp:
            machines.add(str(mfp))
    kinds: Dict[str, Dict[str, Any]] = {}
    for kind in sorted(by_kind):
        group = by_kind[kind]
        if len(group) < max(2, min_rows):
            continue
        X = np.asarray([feature_vector(r.get("features") or {},
                                       r.get("predicted_s"),
                                       r.get("roofline_s"))
                        for r, _m in group])
        y = np.log(np.asarray([m for _r, m in group]))
        mean = X.mean(axis=0)
        std = X.std(axis=0)
        std[std < 1e-9] = 1.0
        Z = (X - mean) / std
        # closed-form ridge; the intercept is the target mean (unpenalized
        # because Z is centered)
        y0 = float(y.mean())
        A = Z.T @ Z + l2 * len(group) * np.eye(Z.shape[1])
        coef = np.linalg.solve(A, Z.T @ (y - y0))
        kinds[kind] = {
            "coef": [round(float(c), 12) for c in coef],
            "mean": [round(float(c), 12) for c in mean],
            "std": [round(float(c), 12) for c in std],
            "intercept": round(y0, 12),
            "rows": len(group),
        }
    return LearnedCostModel(kinds, exact, {
        "rows": len(usable),
        "kinds_fitted": sorted(kinds),
        "machines": sorted(machines),
        "l2": l2,
    })


def mape(pairs: Sequence[Tuple[float, float]]) -> Optional[float]:
    """Mean absolute percentage error over (predicted, measured) pairs."""
    errs = [abs(p - m) / m for p, m in pairs if m > 0 and p is not None]
    return (sum(errs) / len(errs)) if errs else None


# --------------------------------------------------------- runtime adapter
class LearnedCost:
    """The search-time cost function: same `op_time(layer, cand) -> total
    seconds` contract as MeasuredCost.op_time (the total includes the
    candidate's inherent collectives + grad sync, because the corpus's
    measured targets do), with a per-op analytic fallback when the model
    has never seen the op kind. Tracks coverage: hits = learned-priced
    ops, misses = analytic fallbacks."""

    def __init__(self, model: LearnedCostModel, machine,
                 path: Optional[str] = None):
        self.model = model
        self.machine = machine
        self.path = path
        self.hits = 0
        self.misses = 0
        self.prune_ratio = DP_PRUNE_RATIO
        self.finalist_margin = FINALIST_MARGIN
        self._memo: Dict[Tuple, Tuple[float, bool]] = {}

    def _predict(self, layer, cand) -> Tuple[float, bool]:
        key = (layer.params_key(),
               tuple(tuple(map(str, d)) for d in cand.out_dims),
               tuple(sorted((w, tuple(map(str, d)))
                            for w, d in cand.weight_dims.items())))
        hit = self._memo.get(key)
        if hit is not None:
            return hit
        from flexflow_tpu import attribution
        from flexflow_tpu.search import cost_model as cm

        analytic = cand.op_time(layer, self.machine)
        try:
            feats = attribution.op_features(layer, cand, self.machine)
            roof = cm.op_roofline(layer, cand, self.machine)["roofline_s"]
            t = self.model.predict_features(feats, predicted_s=analytic,
                                            roofline_s=roof)
        except Exception:
            t = None
        out = (analytic, False) if t is None else (float(t), True)
        self._memo[key] = out
        return out

    def op_time(self, layer, cand) -> float:
        t, learned = self._predict(layer, cand)
        if learned:
            self.hits += 1
        else:
            self.misses += 1
        return t

    def coverage(self) -> Optional[float]:
        n = self.hits + self.misses
        return (self.hits / n) if n else None

    # ----------------------------------------------------------- pruning
    def prune_candidates(self, layer, cands) -> Tuple[list, int]:
        """Learned per-layer DP pruning: drop candidates whose learned time
        exceeds prune_ratio x the layer's best. Passthroughs and the
        memory-leanest candidate always survive (a memory-capped search
        must keep its escape hatch even when it is slow)."""
        if self.prune_ratio is None or len(cands) <= 2:
            return cands, 0
        timed = []
        for c in cands:
            if c.passthrough:
                continue
            try:
                timed.append((self._predict(layer, c)[0], c))
            except Exception:
                return cands, 0
        if len(timed) <= 1:
            return cands, 0
        best = min(t for t, _c in timed)
        try:
            lean = min(timed, key=lambda tc: tc[1].weight_mem_bytes(
                layer, self.machine, None))[1]
        except Exception:
            lean = None
        cut = best * self.prune_ratio
        by_id = {id(c): t for t, c in timed}
        keep = [c for c in cands
                if c.passthrough or c is lean or by_id[id(c)] <= cut]
        return keep, len(cands) - len(keep)

    def score_result(self, g, result) -> float:
        """Learned total of one SearchResult's per-op choices (the finalist
        pruning score — edge resharding is layout-shared across finalists
        of the same segment, so per-op sums rank them fairly)."""
        from flexflow_tpu.core.graph import topo_order

        total = 0.0
        for layer in topo_order(g.layers):
            cand = result.choices.get(layer.name)
            if cand is None or cand.passthrough:
                continue
            total += self._predict(layer, cand)[0]
        return total

    def prune_finalists(self, g, finalists) -> Tuple[list, int]:
        """Drop layout finalists whose learned score exceeds the best by
        finalist_margin before the expensive event-replay re-rank."""
        if self.finalist_margin is None or not isinstance(finalists, list) \
                or len(finalists) <= 1:
            return finalists, 0
        scored = [(self.score_result(g, r), r) for r in finalists]
        best = min(s for s, _r in scored)
        keep = [r for s, r in scored if s <= best * (1.0 + self.finalist_margin)]
        if not keep:  # defensive: best always qualifies, but never rerank []
            keep = [min(scored, key=lambda sr: sr[0])[1]]
        return keep, len(finalists) - len(keep)


# ------------------------------------------------------------- resolution
def resolve_model_path(cfg) -> str:
    """--cost-model-path > $FF_COST_MODEL_PATH > the ~/.cache default
    (sibling of the strategy cache, so one `rm -r` clears both tiers)."""
    return os.path.expanduser(
        getattr(cfg, "cost_model_path", "") or
        os.environ.get("FF_COST_MODEL_PATH", "") or
        os.path.join("~", ".cache", "flexflow_tpu", "cost_model.json"))


def load_for_config(cfg, machine) -> Optional[LearnedCost]:
    """The learned tier's gate: a LearnedCost only exists when
    `--simulator-mode learned` is on AND a readable model file resolves —
    otherwise None, and every search path is bitwise-identical to today."""
    if getattr(cfg, "simulator_mode", "additive") != "learned":
        return None
    path = resolve_model_path(cfg)
    try:
        model = LearnedCostModel.load(path)
    except (OSError, ValueError):
        return None
    return LearnedCost(model, machine, path=path)


# -------------------------------------------------------------- auto-refit
def _refit_tool():
    """Load tools/refit_cost_model.py (repo-root tools/ is not a package;
    the importlib detour keeps the tool runnable standalone AND callable
    from the fit-end hook without a packaging change)."""
    import importlib.util

    path = os.path.abspath(os.path.join(
        os.path.dirname(__file__), "..", "..", "tools",
        "refit_cost_model.py"))
    if not os.path.exists(path):
        return None
    spec = importlib.util.spec_from_file_location("ff_refit_cost_model", path)
    if spec is None or spec.loader is None:
        return None
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def auto_refit(cfg) -> Optional[Dict[str, Any]]:
    """The drift monitor's self-calibration hook (`--auto-refit`): fold the
    run's telemetry dir through span_dataset into a refreshed model at the
    resolved model path. Returns the refit info dict, or None when the
    loop cannot run (no telemetry dir / no tool / no corpus rows)."""
    tdir = getattr(cfg, "telemetry_dir", "")
    if not tdir or not getattr(cfg, "auto_refit", False):
        return None
    tool = _refit_tool()
    if tool is None:
        return None
    try:
        from flexflow_tpu import telemetry as tel

        tel.flush()
        return tool.refit(tdir, model_path=resolve_model_path(cfg),
                          corpus_path=os.path.join(tdir, "op_corpus.jsonl"),
                          quiet=True)
    except Exception:
        return None
