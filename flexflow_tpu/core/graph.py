"""Layer-graph utilities: topological order, dot export, simple analyses.

Reference analog: graph algorithms in include/flexflow/{basic_graph.h,
dominators.h} and dot export in src/utils/dot/. Heavy algorithms (dominators,
DP-order enumeration) are accelerated by the native C++ core when built
(flexflow_tpu/native); this module keeps pure-Python versions as both the
reference implementation and the fallback.
"""

from __future__ import annotations

from collections import defaultdict
from typing import Dict, Iterable, List, Sequence, Set

from flexflow_tpu.core.layer import Layer


def topo_order(layers: Sequence[Layer]) -> List[Layer]:
    """Kahn topological order over layer dependencies (input-tensor owners).
    Large graphs take the native C++ path (flexflow_tpu/native, same stable
    traversal); this Python body is the reference implementation and the
    fallback."""
    layers = list(layers)
    if len(layers) >= 32:
        native_order = _native_topo(layers)
        if native_order is not None:
            return native_order
    index = {l: i for i, l in enumerate(layers)}
    indeg = {l: 0 for l in layers}
    succs: Dict[Layer, List[Layer]] = defaultdict(list)
    for l in layers:
        for t in l.inputs:
            if t.owner is not None and t.owner in index:
                succs[t.owner].append(l)
                indeg[l] += 1
    # stable: seed queue in original order
    queue = [l for l in layers if indeg[l] == 0]
    out: List[Layer] = []
    while queue:
        l = queue.pop(0)
        out.append(l)
        for s in succs[l]:
            indeg[s] -= 1
            if indeg[s] == 0:
                queue.append(s)
    if len(out) != len(layers):
        raise ValueError("cycle detected in layer graph")
    return out


def _native_topo(layers: List[Layer]):
    try:
        from flexflow_tpu import native
    except Exception:  # pragma: no cover
        return None
    if not native.available():
        return None
    index = {l: i for i, l in enumerate(layers)}
    edges = [(index[t.owner], li)
             for li, l in enumerate(layers) for t in l.inputs
             if t.owner is not None and t.owner in index]
    order = native.topo_order_indices(len(layers), edges)  # raises on cycle
    if order is None:
        return None
    return [layers[i] for i in order]


def predecessors(layer: Layer, universe: Set[Layer]) -> List[Layer]:
    return [t.owner for t in layer.inputs if t.owner is not None and t.owner in universe]


def dominators(layers: Sequence[Layer]) -> Dict[Layer, Set[Layer]]:
    """Forward dominator sets (reference: include/flexflow/dominators.h).

    dom(n) = {n} ∪ ⋂ dom(p) over predecessors p. Sources dominate themselves.
    Used by the search to find sequence-split bottleneck nodes.
    """
    order = topo_order(layers)
    universe = set(order)
    dom: Dict[Layer, Set[Layer]] = {}
    for l in order:
        preds = predecessors(l, universe)
        if not preds:
            dom[l] = {l}
        else:
            inter = set(dom[preds[0]])
            for p in preds[1:]:
                inter &= dom[p]
            inter.add(l)
            dom[l] = inter
    return dom


def post_dominators(layers: Sequence[Layer]) -> Dict[Layer, Set[Layer]]:
    """Post-dominator sets computed over the reversed graph."""
    order = topo_order(layers)
    universe = set(order)
    succs: Dict[Layer, List[Layer]] = defaultdict(list)
    for l in order:
        for p in predecessors(l, universe):
            succs[p].append(l)
    pdom: Dict[Layer, Set[Layer]] = {}
    for l in reversed(order):
        ss = succs[l]
        if not ss:
            pdom[l] = {l}
        else:
            inter = set(pdom[ss[0]])
            for s in ss[1:]:
                inter &= pdom[s]
            inter.add(l)
            pdom[l] = inter
    return pdom


def to_dot(layers: Sequence[Layer], annotations: Dict[Layer, str] | None = None) -> str:
    """Graphviz export (reference: Graph::export_strategy_computation_graph,
    include/flexflow/graph.h:337-344)."""
    annotations = annotations or {}
    lines = ["digraph PCG {", "  rankdir=TB;", '  node [shape=record, fontsize=10];']
    ids = {l: f"n{l.guid}" for l in layers}
    for l in layers:
        extra = annotations.get(l, "")
        outspecs = "/".join(repr(o.spec) for o in l.outputs)
        label = f"{l.name}|{outspecs}"
        if extra:
            label += f"|{extra}"
        label = label.replace("[", "(").replace("]", ")")
        lines.append(f'  {ids[l]} [label="{{{label}}}"];')
    for l in layers:
        for t in l.inputs:
            if t.owner is not None and t.owner in ids:
                lines.append(f"  {ids[t.owner]} -> {ids[l]};")
    lines.append("}")
    return "\n".join(lines)
