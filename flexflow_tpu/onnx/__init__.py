"""ONNX frontend (reference analog: python/flexflow/onnx/)."""

from flexflow_tpu.onnx.model import ONNXModel  # noqa: F401
