"""Replayable request-trace format — the capacity twin's common tongue.

ROADMAP item 5's unlocking refactor: ONE versioned JSONL schema of
request arrivals shared by (a) live serving (`--serve-trace-out` exports
the traffic a scheduler/fleet actually saw), (b) the open-loop Poisson
generators in tools/bench_serve.py and tools/bench_fleet.py (every bench
leg doubles as a replayable planning scenario), and (c) the twin's
loader (`serving/twin.py` replays any trace offline). Recorded
production traffic and synthetic load are interchangeable inputs.

File layout: line 1 is a HEADER object carrying `schema_version` (and a
free-form `meta` dict — generator seed/rate, recording engine config);
every following line is one request record:

    {"arrival_ts": 0.012, "tokens_in": 8, "max_tokens": 4,
     "priority": 1, "deadline": null, "rid": 0, "prompt": [17, 3, ...]}

`arrival_ts` is seconds relative to the trace start (the open-loop
clock every scheduler/fleet/twin run re-anchors), `tokens_in` the prompt
length, `max_tokens` the decode budget, `deadline` seconds-from-arrival
or null, `prompt` the optional token ids (present on synthetic traces so
replay through a LIVE engine is bitwise; a trace without prompts still
replays through the twin, which only prices lengths).

Versioning contract (pinned in tests/test_tracefmt.py):
- an unknown `schema_version` is REJECTED with a clear error (a twin
  quietly mispricing a future trace is worse than refusing it);
- v1 records load forward-compatibly — unknown record fields are
  ignored, never fatal;
- malformed lines are SKIPPED with a counted warning (`Trace.skipped`),
  never a crash: one corrupt line in an hour of recorded traffic must
  not void the other 3.6M.
"""

from __future__ import annotations

import dataclasses
import json
import logging
import os
from typing import Any, Dict, Iterable, List, Optional, Sequence

import numpy as np

log = logging.getLogger("flexflow_tpu")

SCHEMA_VERSION = 1
TRACE_KIND = "flexflow_request_trace"

# required per-record fields (the twin prices these; everything else is
# optional provenance)
REQUIRED_FIELDS = ("arrival_ts", "tokens_in", "max_tokens")


@dataclasses.dataclass
class TraceRecord:
    """One request arrival. `prompt` rides along on synthetic/recorded
    traces that need bitwise live replay; the twin ignores it."""

    arrival_ts: float
    tokens_in: int
    max_tokens: int
    priority: int = 1
    deadline: Optional[float] = None
    rid: Optional[int] = None
    prompt: Optional[List[int]] = None

    def to_json(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {
            "arrival_ts": self.arrival_ts,
            "tokens_in": self.tokens_in,
            "max_tokens": self.max_tokens,
            "priority": self.priority,
            "deadline": self.deadline,
        }
        if self.rid is not None:
            out["rid"] = self.rid
        if self.prompt is not None:
            out["prompt"] = list(self.prompt)
        return out

    @classmethod
    def from_json(cls, d: Dict[str, Any]) -> "TraceRecord":
        # forward-compatible: unknown fields are ignored, never fatal
        prompt = d.get("prompt")
        return cls(
            arrival_ts=float(d["arrival_ts"]),
            tokens_in=int(d["tokens_in"]),
            max_tokens=int(d["max_tokens"]),
            priority=int(d.get("priority", 1)),
            deadline=(None if d.get("deadline") is None
                      else float(d["deadline"])),
            rid=(None if d.get("rid") is None else int(d["rid"])),
            prompt=(None if prompt is None else [int(t) for t in prompt]),
        )


@dataclasses.dataclass
class Trace:
    records: List[TraceRecord]
    meta: Dict[str, Any] = dataclasses.field(default_factory=dict)
    skipped: int = 0  # malformed lines dropped by the loader

    def __len__(self) -> int:
        return len(self.records)


# ------------------------------------------------------------------- io
def save_trace(path: str, records: Sequence[TraceRecord],
               meta: Optional[Dict[str, Any]] = None) -> str:
    """Write a trace atomically (tmp + rename). Serialization is
    deterministic (sorted keys, no whitespace variance), so identical
    records round-trip to identical bytes — the bitwise
    generate -> save -> load -> save pin in tests."""
    d = os.path.dirname(os.path.abspath(path))
    if d:
        os.makedirs(d, exist_ok=True)
    header = {"schema_version": SCHEMA_VERSION, "kind": TRACE_KIND,
              "meta": dict(meta or {})}
    tmp = path + f".tmp{os.getpid()}"
    with open(tmp, "w") as f:
        f.write(json.dumps(header, sort_keys=True,
                           separators=(",", ":")) + "\n")
        for r in records:
            f.write(json.dumps(r.to_json(), sort_keys=True,
                               separators=(",", ":")) + "\n")
    os.replace(tmp, path)
    return path


def load_trace(path: str) -> Trace:
    """Load a trace file. Raises ValueError on a missing/alien header or
    an unknown schema_version; skips (and counts) malformed record
    lines."""
    with open(path) as f:
        first = f.readline()
        try:
            header = json.loads(first)
            if not isinstance(header, dict):
                raise ValueError("header is not an object")
        except ValueError:
            raise ValueError(
                f"{path}: not a {TRACE_KIND} (line 1 must be a JSON header "
                "with schema_version)") from None
        ver = header.get("schema_version")
        if ver != SCHEMA_VERSION:
            raise ValueError(
                f"{path}: unknown trace schema_version {ver!r} (this build "
                f"reads version {SCHEMA_VERSION}; re-record the trace or "
                "upgrade flexflow_tpu)")
        records: List[TraceRecord] = []
        skipped = 0
        for lineno, line in enumerate(f, start=2):
            line = line.strip()
            if not line:
                continue
            try:
                d = json.loads(line)
                if not isinstance(d, dict):
                    raise ValueError("record is not an object")
                for k in REQUIRED_FIELDS:
                    if k not in d:
                        raise ValueError(f"missing field {k!r}")
                records.append(TraceRecord.from_json(d))
            except (ValueError, TypeError) as e:
                skipped += 1
                log.warning("%s:%d: skipping malformed trace line (%s)",
                            path, lineno, e)
    return Trace(records=records, meta=dict(header.get("meta") or {}),
                 skipped=skipped)


# ----------------------------------------------------------- generators
def poisson_records(rng: np.random.Generator, n: int, rate: float,
                    vocab: int, prompt_len: int, max_new: int,
                    priorities: Sequence[int] = (1,),
                    deadline_s: Optional[float] = None,
                    t0: float = 0.0) -> List[TraceRecord]:
    """The open-loop Poisson generator both benches historically inlined,
    lifted here so synthetic load IS a trace. The rng draw order is
    exactly the legacy order — one exponential gap vector, then one
    prompt per request — so a fixed seed reproduces the identical arrival
    sequence the pre-tracefmt benches produced (pinned in tests)."""
    arrivals = t0 + np.cumsum(rng.exponential(1.0 / rate, size=n))
    return [TraceRecord(arrival_ts=float(arrivals[i]),
                        tokens_in=prompt_len,
                        max_tokens=max_new,
                        priority=int(priorities[i % len(priorities)]),
                        deadline=deadline_s,
                        rid=i,
                        prompt=[int(t) for t in
                                rng.integers(1, vocab, size=prompt_len)])
            for i in range(n)]


def burst_records(rng: np.random.Generator, n_base: int, base_rate: float,
                  burst_factor: float, burst_frac: float, vocab: int,
                  prompt_len: int, max_new: int) -> List[TraceRecord]:
    """A steady-state segment followed by a `burst_factor` x arrival-rate
    burst covering the last `burst_frac` of requests — the autoscale
    leg's 10x-burst scenario, as a plain trace."""
    n_burst = max(1, int(n_base * burst_frac))
    steady = poisson_records(rng, n_base, base_rate, vocab, prompt_len,
                             max_new)
    t_end = steady[-1].arrival_ts if steady else 0.0
    burst = poisson_records(rng, n_burst, base_rate * burst_factor, vocab,
                            prompt_len, max_new, t0=t_end)
    for i, r in enumerate(burst):
        r.rid = n_base + i
    return steady + burst


def scale_rate(records: Sequence[TraceRecord],
               factor: float) -> List[TraceRecord]:
    """The same arrival PROCESS at `factor` x the offered load: divide
    every arrival timestamp by the factor (inter-arrival gaps shrink,
    ordering and request shapes stay identical). The capacity-curve
    bisection sweeps this knob."""
    if factor <= 0:
        raise ValueError(f"scale_rate: factor must be > 0, got {factor}")
    return [dataclasses.replace(r, arrival_ts=r.arrival_ts / factor)
            for r in records]


# ---------------------------------------------------------- conversions
def records_to_requests(records: Sequence[TraceRecord],
                        vocab: Optional[int] = None,
                        seed: int = 0) -> List[Any]:
    """Serving `Request`s from trace records — the live-replay direction.
    Records without a stored prompt get a deterministic filler prompt
    (seeded per record) of the recorded length; `vocab` is required then."""
    from flexflow_tpu.serving.scheduler import Request

    out = []
    for i, r in enumerate(records):
        if r.prompt is not None:
            prompt = list(r.prompt)
        else:
            if not vocab:
                raise ValueError(
                    "records_to_requests: trace has no stored prompts; "
                    "pass vocab= to synthesize filler tokens")
            prng = np.random.default_rng(
                seed + (r.rid if r.rid is not None else i))
            prompt = [int(t) for t in
                      prng.integers(1, vocab, size=r.tokens_in)]
        out.append(Request(rid=(r.rid if r.rid is not None else i),
                           prompt=prompt,
                           max_new_tokens=r.max_tokens,
                           arrival_s=r.arrival_ts,
                           priority=r.priority,
                           deadline_s=r.deadline))
    return out


def requests_to_records(requests: Iterable[Any],
                        include_prompts: bool = True) -> List[TraceRecord]:
    """Trace records from serving `Request`s — the live-export direction
    (`--serve-trace-out`). Captures arrival-time/shape/class, optionally
    the prompt ids (so the recorded trace replays bitwise through a live
    engine, not just the twin)."""
    return [TraceRecord(arrival_ts=float(r.arrival_s),
                        tokens_in=len(r.prompt),
                        max_tokens=int(r.max_new_tokens),
                        priority=int(r.priority),
                        deadline=(None if r.deadline_s is None
                                  else float(r.deadline_s)),
                        rid=int(r.rid),
                        prompt=(list(r.prompt) if include_prompts else None))
            for r in requests]
