"""DLRM (config #4 of BASELINE.md; reference: examples/cpp/DLRM/dlrm.cc —
sparse embedding tables + bottom/top MLPs + pairwise feature interaction).

The embedding tables are the attribute-parallel stress case (reference ships
hand-tuned 8/16-GPU strategies for them, examples/cpp/DLRM/strategies/)."""

from __future__ import annotations

from typing import List, Sequence

from flexflow_tpu.core.model import FFModel
from flexflow_tpu.dtype import DataType


def build_dlrm(model: FFModel, batch: int = 64,
               embedding_tables: Sequence[int] = (int(1e5),) * 8,
               embedding_dim: int = 64, dense_dim: int = 13,
               bottom_mlp: Sequence[int] = (512, 256, 64),
               top_mlp: Sequence[int] = (512, 256, 1),
               indices_per_table: int = 1):
    dense = model.create_tensor([batch, dense_dim], name="dense_features")
    sparse_ins = []
    embs = []
    for ti, entries in enumerate(embedding_tables):
        ids = model.create_tensor([batch, indices_per_table], DataType.INT32,
                                  name=f"sparse_{ti}")
        sparse_ins.append(ids)
        embs.append(model.embedding(ids, entries, embedding_dim, aggr="sum",
                                    name=f"emb_{ti}"))
    t = dense
    for i, h in enumerate(bottom_mlp):
        t = model.dense(t, h, activation="relu", name=f"bot{i}")
    # pairwise dot interaction (reference: dlrm.cc interact_features):
    # concat features, batched outer product, flatten upper entries
    feats = [t] + embs  # each (batch, embedding_dim)
    n = len(feats)
    stacked = model.concat([model.reshape(f, [batch, 1, embedding_dim]) for f in feats],
                           axis=1, name="stack")  # (b, n, d)
    inter = model.batch_matmul(stacked, model.transpose(stacked, [0, 2, 1]),
                               name="interact")  # (b, n, n)
    flat = model.reshape(inter, [batch, n * n], name="inter_flat")
    t = model.concat([t, flat], axis=1, name="combine")
    for i, h in enumerate(top_mlp[:-1]):
        t = model.dense(t, h, activation="relu", name=f"top{i}")
    out = model.dense(t, top_mlp[-1], activation="sigmoid", name="click")
    return [dense] + sparse_ins, out
