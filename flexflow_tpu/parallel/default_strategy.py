"""Baseline strategies built without search.

Reference analog: the `--only-data-parallel` short-circuit
(src/runtime/model.cc:2638-2642), which inserts a batch-dim Repartition of
degree #devices before every op. Here the same thing is a Strategy that shards
every batch-carrying dim over the "data" axis and replicates weights; gradient
all-reduce falls out of jax.grad + GSPMD (the NCCL analog, SURVEY.md N2→N4).
"""

from __future__ import annotations

from typing import Dict, List

from flexflow_tpu.core.graph import topo_order
from flexflow_tpu.parallel.machine import MachineSpec
from flexflow_tpu.parallel.sharding import OpSharding, Strategy


def data_parallel_strategy(model, machine: MachineSpec, axis: str = "data") -> Strategy:
    """Shard dim 0 of every batch-sized tensor over the batch axes,
    replicate weights.

    Batch identification is by size: a leading dim equal to the global batch
    (graph-input dim 0). The batch rides ALL sample axes — on a
    {node, data} multi-node mesh (--nodes, compile.py) both axes shard the
    batch, so nodes split samples instead of replicating them. Sharding
    constraints never change semantics, so a miss here only costs layout,
    never correctness.
    """
    from flexflow_tpu.search.candidates import _batch_axes

    axes = _batch_axes(machine) or [axis]
    if not all(a in machine.mesh_axes for a in axes):
        axes = [next(iter(machine.mesh_axes))]
    spec = tuple(axes) if len(axes) > 1 else axes[0]
    degree = 1
    for a in axes:
        degree *= machine.mesh_axes[a]
    batch_sizes = {t.shape[0] for t in model.input_tensors if t.ndim > 0}

    def dims_for(shape) -> List:
        dims: List = [None] * len(shape)
        if shape and shape[0] in batch_sizes and shape[0] % degree == 0:
            dims[0] = spec
        return dims

    st = Strategy(mesh_axes=dict(machine.mesh_axes), name="data_parallel")
    for t in model.input_tensors:
        st.input_shardings[t.name] = dims_for(t.shape)
    for layer in topo_order(model.layers):
        st.op_shardings[layer.name] = OpSharding(
            outputs=[dims_for(o.spec.shape) for o in layer.outputs],
            weights={w: [None] * len(s.shape) for w, s in layer.weight_specs.items()},
        )
    return st
