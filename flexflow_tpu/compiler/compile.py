"""compile_model — the pivot of the framework.

Reference analog: FFModel::compile (src/runtime/model.cc:2803): lower layers
→ operators, run the strategy search, materialize tensors onto the machine,
create the label tensor, init optimizer + NCCL. The TPU-native pipeline:

  1. build/machine-detect the logical Mesh            (mapper analog)
  2. pick a Strategy: imported file > search > data-parallel
     (graph_optimize_task analog)
  3. trace the layer graph into one SPMD train step jitted over the mesh
     (IndexLauncher-per-op → one XLA computation; collectives via GSPMD)
  4. init weights directly into their target shardings
     (region materialization analog)
"""

from __future__ import annotations

import logging
import time
from functools import partial
from typing import Any, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from flexflow_tpu import health
from flexflow_tpu import telemetry as tel
from flexflow_tpu.core.graph import topo_order
from flexflow_tpu.core.tensor import Tensor
from flexflow_tpu.compiler.lowering import build_forward, constrainable
from flexflow_tpu.dtype import DataType
from flexflow_tpu.initializers import default_initializer
from flexflow_tpu.losses import LossType, compute_loss
from flexflow_tpu.metrics import MetricsType, PerfMetrics, compute_metrics
from flexflow_tpu.optimizers import Optimizer, SGDOptimizer
from flexflow_tpu.parallel.default_strategy import data_parallel_strategy
from flexflow_tpu.parallel.machine import MachineSpec, build_mesh
from flexflow_tpu.parallel.sharding import Strategy
from flexflow_tpu.runtime.dataloader import (SingleDataLoader,
                                             group_microbatches,
                                             prefetch_multi,
                                             prefetch_to_device)


def _search_machine(cfg, machine: MachineSpec) -> MachineSpec:
    """--search-num-nodes/--search-num-workers (reference config.h:154-155):
    search strategies for a machine LARGER than the real one (typically with
    --export, so big-machine strategies can be found on a small host). Nodes
    map to a DCN-crossing data axis, workers to the intra-node model axis."""
    if not cfg.search_num_nodes and not cfg.search_num_workers:
        return machine
    nodes = max(1, cfg.search_num_nodes)
    workers = max(1, cfg.search_num_workers)
    axes = {"data": nodes, "model": workers}
    return MachineSpec(mesh_axes=axes, chip=machine.chip,
                       dcn_axes=("data",) if nodes > 1 else ())


def _pick_strategy(model, machine: MachineSpec, optimizer=None) -> Strategy:
    cfg = model.config
    if cfg.import_strategy_file:
        return Strategy.load(cfg.import_strategy_file)
    sm = _search_machine(cfg, machine)
    if sm is not machine and sm.mesh_axes != machine.mesh_axes \
            and not cfg.export_strategy_file:
        import warnings

        warnings.warn(
            f"searching for machine {sm.mesh_axes} but executing on "
            f"{machine.mesh_axes}: shardings that don't fit the real mesh "
            "degrade to replicated — --search-num-nodes/--search-num-workers "
            "are meant to be paired with --export")
    if cfg.search_budget > 0 and not cfg.only_data_parallel and sm.num_devices > 1:
        try:
            from flexflow_tpu.search.optimize import graph_optimize
        except ImportError:
            import warnings

            warnings.warn("strategy search unavailable; falling back to data-parallel")
        else:
            # the optimizer rides along so the search's memory model can
            # price its moments (count/state_dtype/ZeRO divisor) honestly
            with tel.span("compile/graph_optimize", cat="compile",
                          mesh=str(dict(sm.mesh_axes))):
                return graph_optimize(model, sm, optimizer=optimizer)
    return data_parallel_strategy(model, machine)


def _overlay_parallel_ops(model, strategy: Strategy):
    """Explicit parallel-op layers override the strategy's layout for their
    outputs (reference: parallel ops ARE PCG nodes; here they are resharding
    requests, see flexflow_tpu/ops/parallel_ops.py)."""
    from flexflow_tpu.ops.op_type import PARALLEL_OPS
    from flexflow_tpu.ops.parallel_ops import requested_dims
    from flexflow_tpu.parallel.sharding import OpSharding

    for layer in model.layers:
        if layer.op_type not in PARALLEL_OPS:
            continue
        src = layer.inputs[0]
        incoming = None
        if src.owner is not None:
            sh = strategy.op_shardings.get(src.owner.name)
            if sh and src.owner_idx < len(sh.outputs):
                incoming = sh.outputs[src.owner_idx]
        elif src.name in strategy.input_shardings:
            incoming = strategy.input_shardings[src.name]
        dims = requested_dims(layer, incoming)
        strategy.op_shardings[layer.name] = OpSharding(outputs=[dims])


def compile_model(model, optimizer, loss_type: LossType, metrics: Sequence[MetricsType],
                  outputs: Optional[Sequence[Tensor]] = None) -> "CompiledModel":
    cfg = model.config
    # --telemetry-dir enables the process-global span stream; "" leaves
    # the current state untouched (disabling is an explicit
    # telemetry.shutdown(), never a side effect of a later compile)
    if getattr(cfg, "telemetry_dir", ""):
        tel.configure(cfg.telemetry_dir,
                      max_mb=getattr(cfg, "telemetry_max_mb", None))
    # --fault-plan arms the deterministic fault injector (FF_FAULT_PLAN is
    # read at faults import; an explicit config plan overrides it)
    if getattr(cfg, "fault_plan", ""):
        from flexflow_tpu.runtime import faults

        faults.configure(cfg.fault_plan)
    with tel.span("compile/compile_model", cat="compile",
                  pipeline_stages=int(cfg.pipeline_stages)):
        return _compile_model(model, optimizer, loss_type, metrics, outputs)


def compile_serving(model, **kwargs):
    """Serving twin of `compile_model` (flexflow_tpu/serving/engine.py):
    lowers the graph twice — compute-priced prefill and bandwidth-priced
    single-token decode — searches a strategy per program, and returns a
    ServingCompiled over a paged KV cache. Lazy import: serving builds on
    this module (build_init_fn / resolve_machine / _overlay_parallel_ops)."""
    from flexflow_tpu.serving.engine import compile_serving as _compile_serving

    return _compile_serving(model, **kwargs)


def resolve_machine(cfg) -> MachineSpec:
    """The machine description every compile entry point shares (training
    `compile_model` and the serving `compile_serving`): an explicit machine
    file wins, then the --nodes DCN description, then mesh-shape detection."""
    if cfg.machine_model_file:
        return MachineSpec.from_file(cfg.machine_model_file)
    if not cfg.mesh_shape and cfg.num_nodes > 1:
        # --nodes/-ll:tpu (reference machine description): nodes form a
        # DCN-crossing axis, per-node workers the intra-node data axis
        workers = cfg.workers_per_node or max(
            1, len(jax.devices()) // cfg.num_nodes)
        return MachineSpec.detect({"node": cfg.num_nodes, "data": workers},
                                  dcn_axes=("node",))
    return MachineSpec.detect(cfg.mesh_shape)


def _compile_model(model, optimizer, loss_type, metrics, outputs):
    cfg = model.config
    machine = resolve_machine(cfg)
    level = getattr(logging, cfg.log_level.upper(), None)
    if level is None:
        raise ValueError(f"unknown log_level {cfg.log_level!r}")
    lg = logging.getLogger("flexflow_tpu")
    if lg.level == logging.NOTSET:  # never clobber application logging config
        lg.setLevel(level)
    optimizer = optimizer or SGDOptimizer(lr=cfg.learning_rate)
    if cfg.pipeline_stages > 1:
        return _compile_pipelined(model, machine, optimizer, loss_type,
                                  metrics, outputs)
    mesh = build_mesh(machine)
    strategy = _pick_strategy(model, machine, optimizer)
    logging.getLogger("flexflow_tpu").info(
        "compile: mesh=%s strategy=%s", dict(machine.mesh_axes), strategy.name)
    _overlay_parallel_ops(model, strategy)
    if cfg.export_strategy_file:
        strategy.save(cfg.export_strategy_file)
    if outputs is None:
        outputs = model.layers[-1].outputs[:1] if model.layers else []
    return CompiledModel(model, machine, mesh, strategy, optimizer,
                         loss_type, list(metrics), list(outputs))


def _compile_pipelined(model, machine: MachineSpec, optimizer,
                       loss_type: LossType, metrics, outputs):
    """--pipeline-stages N: partition the graph into N sequential stages on
    disjoint device groups. The machine description covers the FULL
    cluster; the pipe dimension is carved out of it (an explicit pipe mesh
    axis, else the batch axis degree divides by N — dp.stage_machine_for),
    intra-stage layouts are searched on the STAGE machine (tensor/data
    parallelism inside a stage composes with the pipeline split), and the
    cut points come from the bubble-aware cut search when a search budget
    is set, else from the balance heuristic. The schedule runs M =
    cfg.accum_steps microbatches per optimizer update
    (parallel/pipeline.py).

    Known approximation: the cut search prices stage times under plain
    per-stage frontier-DP layouts, while execution uses the (possibly
    richer, substitution-searched) strategy from _pick_strategy — the
    cuts are optimal for a close under-approximation of the executed
    layouts, not for them exactly. Both searches are cold-compile-only:
    the warm path (cached strategy with its pipeline block) skips both."""
    from flexflow_tpu.parallel.pipeline import PipelinedModel, balanced_cuts
    from flexflow_tpu.search.dp import search_pipelined, stage_machine_for

    cfg = model.config
    S = int(cfg.pipeline_stages)
    stage_machine = stage_machine_for(machine, S)
    strategy = _pick_strategy(model, stage_machine, optimizer)
    if strategy.pipeline and int(strategy.pipeline.get("stages", S)) != S:
        raise ValueError(f"imported strategy pipelines "
                         f"{strategy.pipeline.get('stages')} stages but "
                         f"--pipeline-stages is {S}")
    if not strategy.pipeline:
        # First compile at these knobs: graph_optimize stored the strategy
        # (intra-stage layouts) BEFORE the pipeline block exists, so the
        # cuts are searched here and the entry is re-stored WITH the block
        # below — the warm path then finds strategy.pipeline set and skips
        # the cut search entirely (zero DP expansions, the cache's
        # headline contract; the knob fingerprint already keys on
        # stages/schedule/M).
        micro = max(1, int(cfg.accum_steps))
        cuts = None
        if cfg.search_budget > 0 and not cfg.only_data_parallel:
            from flexflow_tpu.search import cost_model as cmod

            with tel.span("compile/pipeline_cut_search", cat="compile",
                          stages=S, micro=micro):
                r = search_pipelined(
                    model, machine, S, micro,
                    schedule=cfg.pipeline_schedule,
                    mem_budget=machine.hbm_bytes if cfg.memory_search
                    else None,
                    opt_mem=cmod.opt_mem_spec(optimizer, cfg,
                                              stage_machine))
            if r is not None:
                cuts = list(r.cuts)
                logging.getLogger("flexflow_tpu").info(
                    "pipeline cut search: cuts=%s predicted bubble=%.3f "
                    "stage costs=%s", cuts, r.bubble,
                    ["%.3g" % c for c in r.stage_costs])
        if cuts is None:
            cuts = balanced_cuts(model, stage_machine, S)
        strategy.pipeline = {"stages": S, "cuts": cuts,
                             "schedule": cfg.pipeline_schedule}
        info = getattr(strategy, "_cache_info", None)
        if info and info.get("dir") and info.get("key"):
            # write the completed artifact (layouts + cuts) back into the
            # cache entry graph_optimize created / hit
            from flexflow_tpu.search import strategy_cache as sc

            sc.store(info["dir"], info["key"], strategy,
                     meta=dict(info.get("meta", {})))
    _overlay_parallel_ops(model, strategy)
    if cfg.export_strategy_file:
        strategy.save(cfg.export_strategy_file)
    if outputs is None:
        outputs = model.layers[-1].outputs[:1] if model.layers else []
    logging.getLogger("flexflow_tpu").info(
        "compile: pipeline stages=%d schedule=%s stage_mesh=%s cuts=%s",
        S, strategy.pipeline.get("schedule"),
        dict(stage_machine.mesh_axes), strategy.pipeline.get("cuts"))
    return PipelinedModel(model, machine, stage_machine, strategy,
                          optimizer, loss_type, list(metrics),
                          list(outputs))


def _zero_axes_of(mesh: Mesh) -> List[str]:
    """Mesh axes ZeRO shards optimizer moments over: the batch axes
    (candidates._batch_axes convention — "node"/"data", else the first
    axis) with degree > 1. Sharding over the batch axes is what removes
    REDUNDANT state: every other axis already partitions the params."""
    axes = [a for a in ("node", "data") if a in mesh.shape]
    if not axes and mesh.shape:
        axes = [next(iter(mesh.shape))]
    return [a for a in axes if mesh.shape[a] > 1]


def _zero_moment_pspec(pspec: PartitionSpec, shape, mesh: Mesh,
                       zero_axes: Sequence[str]) -> PartitionSpec:
    """Moment layout for one param under ZeRO: the param's own spec plus
    the FULL data-axis degree on the first unsharded dim it divides. A
    param with no such dim keeps its (possibly model-sharded) layout —
    its moments stay replicated over data, exactly what the search's
    cost_model.zero_divisor mirror predicts. Keep the two rules in
    lockstep or --memory-search prices memory the runtime doesn't save."""
    spec = list(pspec) + [None] * (len(shape) - len(pspec))
    used = {a for d in spec if d is not None
            for a in ((d,) if isinstance(d, str) else tuple(d))}
    if used & set(zero_axes):
        return PartitionSpec(*spec)
    deg = 1
    for a in zero_axes:
        deg *= mesh.shape[a]
    if deg <= 1:
        return PartitionSpec(*spec)
    for i, d in enumerate(spec):
        if d is None and shape[i] % deg == 0:
            spec[i] = zero_axes[0] if len(zero_axes) == 1 \
                else tuple(zero_axes)
            break
    return PartitionSpec(*spec)


def build_init_fn(layers, overrides, topo_idx=None):
    """Weight-init closure shared by CompiledModel.init and the pipeline
    runtime (parallel/pipeline.py): params for `layers`, each weight keyed
    by fold_in(fold_in(key, topo_idx[layer]), weight_idx). `topo_idx` maps
    a layer to its position in the FULL model's topo order (default: its
    position in `layers`) — pipeline stages pass GLOBAL indices so a
    stage-partitioned model initializes bitwise-identically to the
    sequential compile of the same graph."""
    from flexflow_tpu.core.tensor import TensorSpec

    if topo_idx is None:
        topo_idx = {id(l): i for i, l in enumerate(layers)}

    def init_fn(key):
        params = {}
        for layer in layers:
            if not layer.weight_specs:
                continue
            li = topo_idx[id(layer)]
            d = {}
            for i, (wname, spec) in enumerate(sorted(layer.weight_specs.items())):
                # fork_join weights are "b{i}.{sublayer}.{wname}" (or
                # "stk.{sublayer}.{wname}" stacked): the default
                # initializer keys off the terminal wname
                # fold by topo position (not guid) so identically-built
                # models init identically across FFModel instances
                k = jax.random.fold_in(jax.random.fold_in(key, li), i)
                if wname.startswith("stk."):
                    # stacked fork_join storage: init each branch slice
                    # independently (fan-in/out from the SLICE shape, and
                    # per-branch initializer overrides still apply)
                    sspec = TensorSpec(spec.shape[1:], spec.dtype)
                    default = default_initializer(wname.rsplit(".", 1)[-1])
                    slices = []
                    for b in range(spec.shape[0]):
                        init = overrides.get(
                            (layer.name, f"b{b}.{wname[4:]}")) or default
                        slices.append(init(jax.random.fold_in(k, b), sspec))
                    d[wname] = jnp.stack(slices)
                else:
                    init = overrides.get((layer.name, wname)) or \
                        default_initializer(wname.rsplit(".", 1)[-1])
                    d[wname] = init(k, spec)
            params[layer.name] = d
        return params

    return init_fn


@partial(jax.jit, donate_argnums=(0,))
def _stacked_slice_set(stack, value, b):
    """Update slice b of a stacked (k, ...) weight in place, preserving its
    sharding (used by set_weight's per-branch alias on owned fork-join
    weights)."""
    return jax.lax.dynamic_update_index_in_dim(stack, value, b, 0)


class CompiledModel:
    def __init__(self, model, machine: MachineSpec, mesh: Mesh, strategy: Strategy,
                 optimizer: Optimizer, loss_type: LossType,
                 metrics: List[MetricsType], outputs: List[Tensor]):
        self.model = model
        self.machine = machine
        self.mesh = mesh
        self.strategy = strategy
        self.optimizer = optimizer
        self.tx = optimizer.to_optax()
        self.loss_type = loss_type
        self.metrics = metrics
        self.outputs = outputs
        self.cfg = model.config
        self._iteration = 0
        self.recompile_state = None  # set via recompile_on_condition
        # strategy-cache event for THIS compile (hit/store), stamped by
        # search/strategy_cache.py on the returned Strategy; None when the
        # search didn't run (imported / data-parallel) or caching is off
        self.search_cache_info = getattr(strategy, "_cache_info", None)
        # async-pipeline observability, rewritten by each fit (_fit_epochs):
        # dispatches / host_syncs / barriers / fused_steps
        self.step_stats: Dict[str, int] = {}
        # drift-monitor windows from the LAST fit: [(steps, wall_seconds)]
        # per epoch — drift_stats() medians these against the strategy's
        # predicted step time
        self._drift_windows: List[tuple] = []
        # run-health layer (flexflow_tpu/health.py, ISSUE 9): goodput
        # meter is per-fit (rebuilt by _fit), the HBM watermark tracker
        # spans the compile's lifetime (init + every epoch boundary), and
        # the sentinel monitor follows cfg.health_sentinels
        self._goodput: Optional[health.GoodputMeter] = None
        self._watermarks = health.WatermarkTracker()
        self._sentinels: Optional[health.SentinelMonitor] = None

        # --remat compat alias (deprecated): uniform "full" per-layer policy.
        # The searched path (--remat-search) arrives here with the DP's
        # per-layer choices already on strategy.remat.
        if self.cfg.remat and not getattr(strategy, "remat", None):
            strategy.remat = {l.name: "full" for l in model.layers}

        self.forward_fn = build_forward(model.layers, model.input_tensors, outputs,
                                        mesh, strategy,
                                        seq_length=self.cfg.seq_length or None,
                                        compute_dtype=self.cfg.compute_dtype,
                                        enable_fusion=self.cfg.enable_fusion)
        # gradient-accumulation width the step functions are built for
        # (cfg default; fit(accum_steps=...) rebuilds on a different value)
        self._accum_steps = max(1, int(self.cfg.accum_steps))
        self._build_steps()
        self.params = None
        self.state: Dict[str, Any] = {}
        self.opt_state = None

    # ------------------------------------------------------------- sharding
    def _weight_sharding(self, layer_name: str, wname: str, shape) -> NamedSharding:
        pspec = self.strategy.sharding_for(layer_name).weight_pspec(wname)
        if not constrainable(pspec, shape, self.mesh):
            pspec = PartitionSpec()
        return NamedSharding(self.mesh, pspec)

    def input_sharding(self, tensor: Tensor) -> NamedSharding:
        pspec = self.strategy.input_pspec(tensor.name)
        if not constrainable(pspec, tensor.shape, self.mesh):
            pspec = PartitionSpec()
        return NamedSharding(self.mesh, pspec)

    def label_sharding(self, label_shape) -> NamedSharding:
        ax = "data" if "data" in self.mesh.shape else list(self.mesh.shape)[0]
        if label_shape and label_shape[0] % self.mesh.shape[ax] == 0:
            return NamedSharding(self.mesh, PartitionSpec(ax))
        return NamedSharding(self.mesh, PartitionSpec())

    def _put(self, arr, sharding):
        """Host→device transfer for EVERY data path (fit/evaluate/forward/
        set_weight). Single-process: plain device_put. Multi-process
        (control-replication analog): every process holds the full host
        array and contributes the rows its addressable shards own."""
        if jax.process_count() == 1:
            return jax.device_put(arr, sharding)
        from flexflow_tpu.runtime.distributed import global_batch_from_full

        return global_batch_from_full(np.asarray(arr), self.mesh, sharding.spec)

    # ------------------------------------------------- zero-redundancy state
    def _zero_mode(self) -> str:
        """Resolved ZeRO regime: cfg.zero_sharding, degraded to "off" when
        the mesh has no batch axis to shard over (1-device runs)."""
        mode = (self.cfg.zero_sharding or "off").lower()
        if mode not in ("off", "zero1", "zero2"):
            raise ValueError(f"zero_sharding={self.cfg.zero_sharding!r} "
                             "(choose from off/zero1/zero2)")
        if mode != "off" and not _zero_axes_of(self.mesh):
            return "off"
        return mode

    def _param_templates(self):
        """params-shaped trees of avals + compiled shardings, WITHOUT
        materializing arrays — mirrors init()'s params structure (one dict
        per weighted layer), so tx.init's state shape can be derived before
        any weight exists."""
        shapes: Dict[str, Dict[str, jax.ShapeDtypeStruct]] = {}
        shards: Dict[str, Dict[str, NamedSharding]] = {}
        for layer in topo_order(self.model.layers):
            if not layer.weight_specs:
                continue
            shapes[layer.name] = {
                w: jax.ShapeDtypeStruct(s.shape, s.dtype.jnp_dtype)
                for w, s in layer.weight_specs.items()}
            shards[layer.name] = {
                w: self._weight_sharding(layer.name, w, s.shape)
                for w, s in layer.weight_specs.items()}
        return shapes, shards

    def _moment_shardings(self, pshapes, pshards):
        """Per-param layout of the optimizer moments: the param's own
        sharding (the replicated regime / zero off), or that plus the
        data-axis degree on the first divisible free dim (ZeRO)."""
        if self._zero_mode() == "off":
            return pshards
        za = _zero_axes_of(self.mesh)
        return jax.tree_util.tree_map(
            lambda sds, sh: NamedSharding(self.mesh, _zero_moment_pspec(
                sh.spec, sds.shape, self.mesh, za)), pshapes, pshards)

    def _opt_state_shardings(self, pshapes, moment_sh):
        """Sharding tree matching tx.init's FULL state structure (for the
        jitted init's out_shardings and the in-step constraints): optax
        states embed params-shaped subtrees for the moments — those get
        `moment_sh` — while everything else (step counts, EmptyState)
        replicates."""
        repl = NamedSharding(self.mesh, PartitionSpec())
        shapes = jax.eval_shape(self.tx.init, pshapes)
        pstruct = jax.tree_util.tree_structure(pshapes)
        if pstruct.num_leaves == 0:
            return jax.tree_util.tree_map(lambda _: repl, shapes)

        def is_params_subtree(x):
            return jax.tree_util.tree_structure(x) == pstruct

        return jax.tree_util.tree_map(
            lambda sub: moment_sh if is_params_subtree(sub) else repl,
            shapes, is_leaf=is_params_subtree)

    # ---------------------------------------------------------------- init
    def init(self, seed: Optional[int] = None):
        """Initialize weights sharded-at-birth (no host round trip)."""
        seed = self.cfg.seed if seed is None else seed
        layers = topo_order(self.model.layers)
        overrides = self.model._initializer_overrides
        shardings = {}
        for layer in layers:
            if not layer.weight_specs:
                continue
            shardings[layer.name] = {
                w: self._weight_sharding(layer.name, w, s.shape)
                for w, s in layer.weight_specs.items()
            }

        init_fn = build_init_fn(layers, overrides)
        self.params = jax.jit(init_fn, out_shardings=shardings)(jax.random.PRNGKey(seed))
        self.state = {}
        # jitted with EXPLICIT out_shardings (vs the old eager tx.init):
        # moments land directly in their target layout — sharded from the
        # first byte under ZeRO, and never paying the transient
        # fully-replicated allocation implicit propagation produced
        self.opt_state = jax.jit(self.tx.init,
                                 out_shardings=self._opt_sh)(self.params)
        self._iteration = 0
        # first HBM watermark: the persistent footprint right after init
        self._watermarks.sample("init", (self.params, self.opt_state))
        return self.params

    # ---------------------------------------------------------------- steps
    def _build_steps(self):
        forward_fn = self.forward_fn
        loss_type, metric_types = self.loss_type, self.metrics
        tx = self.tx
        # --allow-tensor-op-math-conversion (reference config.h / cuBLAS
        # tensor-op gate ≙ the MXU's reduced-precision passes): when off,
        # every dot runs at HIGHEST precision (f32 accumulation passes)
        precision = None if self.cfg.allow_tensor_op_math_conversion else "highest"

        regularizers = dict(self.model._weight_regularizers)
        # numerics sentinels (flexflow_tpu/health.py): fold the grad
        # global-norm + non-finite flag into the step's metric outputs —
        # they ride the deferred-metrics machinery (sums/means across
        # fused and accumulated steps), so the healthy path pays zero
        # extra host syncs; the fit loop pops the reserved keys off
        # before user-facing metric accounting
        sentinels = bool(getattr(self.cfg, "health_sentinels", False))

        # fused cross-entropy (kernels/fused_ce.py): the sparse-CE loss
        # computed blockwise over the vocab axis, so the training step never
        # holds an f32 copy of the [B, S, vocab] logits
        fused_loss_mode = str(getattr(self.cfg, "fused_loss", "auto"))
        fusion_on = bool(self.cfg.enable_fusion)
        from flexflow_tpu.kernels import fused_ce as _fce

        # fused optimizer update (kernels/fused_optim.py): one elementwise
        # kernel per param block instead of the optax tree_map chain —
        # recognized Adam/SGD configs only, silent tx.update fallback in
        # "auto" mode, hard error in "on" mode
        fused_opt_mode = str(getattr(self.cfg, "fused_optimizer", "auto"))
        fopt_plan = None
        if fused_opt_mode != "off" and (fusion_on or fused_opt_mode == "on"):
            from flexflow_tpu.kernels import fused_optim as _fopt

            fopt_plan = _fopt.plan_for(self.optimizer)
            if fused_opt_mode == "on" and fopt_plan is None:
                raise ValueError(
                    f"--fused-optimizer=on but "
                    f"{type(self.optimizer).__name__} is not a recognized "
                    f"Adam/SGD configuration")

        # ZeRO machinery: the moment/opt-state sharding trees are fixed by
        # (strategy, mesh, optimizer), so build them once per compile and
        # share between the jitted tx.init (see init()) and the in-step
        # constraints below
        zero = self._zero_mode()
        accum = max(1, int(self._accum_steps))
        pshapes, pshards = self._param_templates()
        moment_sh = self._moment_sh = self._moment_shardings(pshapes, pshards)
        self._param_sh = pshards
        opt_sh = self._opt_sh = self._opt_state_shardings(pshapes, moment_sh)
        wsc = jax.lax.with_sharding_constraint

        def value_and_grads(params, state, inputs, label, rng):
            def loss_fn(p):
                # rematerialization is per-layer now (strategy.remat applied
                # inside build_forward); --remat aliases to all-layers "full"
                outs, new_state = forward_fn(p, state, inputs, True, rng)
                logits = outs[0]
                if _fce.use_fused_ce(loss_type, logits, fused_loss_mode,
                                     fusion_on):
                    # native-dtype logits: the f32 copy the reference path
                    # takes below is exactly the materialization we avoid
                    loss = _fce.fused_cross_entropy(logits, label)
                else:
                    loss = compute_loss(loss_type,
                                        logits.astype(jnp.float32), label)
                for (ln, wn), terms in regularizers.items():
                    w = p[ln][wn].astype(jnp.float32)
                    for mode, lam in terms:
                        loss = loss + lam * (jnp.sum(jnp.abs(w)) if mode == "l1"
                                             else jnp.sum(w * w))
                return loss, (logits, new_state)

            return jax.value_and_grad(loss_fn, has_aux=True)(params)

        def apply_update(params, opt_state, grads):
            """One optimizer update. Under ZeRO this is the rewritten sync:
            constraining the (all-reduced) grads to the moment layout lets
            GSPMD lower the sync as reduce-scatter, each device updates
            only ITS moment shard, and the param-dtype updates all-gather
            back — same ring volume as the fused all-reduce
            (cost_model.grad_sync_time zero=True), 1/degree the moment
            memory and update flops."""
            if zero != "off":
                grads = wsc(grads, moment_sh)
            done = None
            if fopt_plan is not None:
                from flexflow_tpu.kernels import fused_optim as _fopt

                done = _fopt.fused_update(fopt_plan, grads, opt_state,
                                          params)
                if done is None and fused_opt_mode == "on":
                    raise ValueError(
                        "--fused-optimizer=on but the live optax state does "
                        "not match the recognized optimizer plan")
            if done is not None:
                updates, opt_state = done
            else:
                updates, opt_state = tx.update(grads, opt_state, params)
            if zero != "off":
                updates = wsc(updates, pshards)      # all-gather
                opt_state = wsc(opt_state, opt_sh)   # moments stay sharded
            return optax.apply_updates(params, updates), opt_state

        def train_step(params, opt_state, state, inputs, label, rng):
            (loss, (logits, new_state)), grads = value_and_grads(
                params, state, inputs, label, rng)
            params, opt_state = apply_update(params, opt_state, grads)
            mvals = compute_metrics(metric_types, logits.astype(jnp.float32), label)
            if sentinels:
                mvals = dict(mvals, **health.sentinel_metrics(
                    loss, optax.global_norm(grads)))
            return params, opt_state, new_state, loss, mvals

        def accum_step(params, opt_state, state, inputs, label, rng):
            """accum_steps=N microbatching: inputs/label carry a leading
            (N, ...) microbatch dim (runtime/dataloader.group_microbatches);
            N fwd/bwd passes accumulate a device-resident mean gradient and
            ONE optimizer update applies it — effective batch N x batch.
            Same signature as train_step, so make_multi_step fuses K
            UPDATES per dispatch unchanged. Under zero2 each microbatch's
            gradient is reduce-scattered before accumulation, so the
            accumulator is stored sharded like the moments (zero1 keeps
            full-size accumulators). Loss/metrics are means over the N
            microbatches. Microbatch j uses fold_in(rng, j) — dropout
            streams differ from an equivalent big-batch step by design."""
            def micro(j, state):
                ins = [jax.lax.dynamic_index_in_dim(a, j, keepdims=False)
                       for a in inputs]
                lab = jax.lax.dynamic_index_in_dim(label, j, keepdims=False)
                (loss, (logits, new_state)), grads = value_and_grads(
                    params, state, ins, lab, jax.random.fold_in(rng, j))
                if zero == "zero2":
                    grads = wsc(grads, moment_sh)
                mvals = compute_metrics(metric_types,
                                        logits.astype(jnp.float32), lab)
                return new_state, grads, loss, mvals

            def body(j, carry):
                s, g, lsum, msum = carry
                s, g2, l2, mv2 = micro(j, s)
                tm = jax.tree_util.tree_map
                return (s, tm(jnp.add, g, g2), lsum + l2,
                        tm(jnp.add, msum, mv2))

            # microbatch 0 outside the loop fixes the carry's shapes (the
            # make_multi_step convention)
            s, g, lsum, msum = micro(0, state)
            s, g, lsum, msum = jax.lax.fori_loop(1, accum, body,
                                                 (s, g, lsum, msum))
            inv = 1.0 / accum
            g = jax.tree_util.tree_map(lambda t: t * inv, g)
            params, opt_state = apply_update(params, opt_state, g)
            loss = lsum * inv
            mvals = jax.tree_util.tree_map(lambda x: x * inv, msum)
            if sentinels:
                mvals = dict(mvals, **health.sentinel_metrics(
                    loss, optax.global_norm(g)))
            return params, opt_state, s, loss, mvals

        step_fn = accum_step if accum > 1 else train_step

        def eval_step(params, state, inputs, label):
            outs, _ = forward_fn(params, state, inputs, False, jax.random.PRNGKey(0))
            logits = outs[0].astype(jnp.float32)
            loss = compute_loss(loss_type, logits, label)
            return loss, compute_metrics(metric_types, logits, label)

        def infer(params, state, inputs):
            outs, _ = forward_fn(params, state, inputs, False, jax.random.PRNGKey(0))
            return outs

        def _wrap(fn):
            if precision is None:
                return fn

            def wrapped(*a):
                with jax.default_matmul_precision(precision):
                    return fn(*a)

            return wrapped

        # donate_state=False keeps the previous params/opt/state buffers
        # alive after each step (debugging / external references)
        donate = (0, 1, 2) if self.cfg.donate_state else ()
        self.train_step = jax.jit(_wrap(step_fn), donate_argnums=donate)
        self.eval_step = jax.jit(_wrap(eval_step))
        self.infer_step = jax.jit(_wrap(infer))
        self._train_step_fn = step_fn  # unjitted body for make_multi_step
        self._wrap_precision = _wrap
        self._multi_cache = {}  # steps_per_dispatch -> jitted multi-step

    def _get_multi(self, k: int):
        """Cached make_multi_step(k) — one jit per fused width per compile
        (cleared by _build_steps on recompile)."""
        fn = self._multi_cache.get(k)
        if fn is None:
            fn = self._multi_cache[k] = self.make_multi_step(k)
        return fn

    def make_multi_step(self, n: int, donate: "Optional[bool]" = None):
        """One-dispatch n-step training: fori_loop over n stacked batches
        inside a single jitted program. The reference's analog is the Legion
        trace replay its Python fit loop wraps around each iteration
        (flexflow_cffi.py begin_trace/end_trace) — amortizing per-step
        runtime overhead; here it amortizes per-step DISPATCH, which
        dominates sub-10ms steps on high-latency transports (the axon
        tunnel's ~ms per dispatch).

        Returns jitted fn(params, opt_state, state, stacked_inputs,
        stacked_labels, rng, i0=0) -> (params, opt_state, state, mean_loss,
        mean_metrics); stacked arrays carry a leading n dim. `i0` is the
        global iteration of the first fused step: step i uses
        fold_in(rng, i0 + i), so with rng = fit's base key the fused loop
        consumes the SAME dropout/rng stream as n individually dispatched
        train_steps at iterations i0..i0+n-1 (pass i0 as a jnp scalar to
        avoid retracing per value).

        `donate=None` follows cfg.donate_state. CAUTION (same contract as
        train_step): under donation the INPUT params/opt_state/state
        buffers are consumed — if you pass cm.params etc., write the
        returned trees back (cm.params, cm.opt_state, cm.state = p, o, s)
        before touching any other CompiledModel method, or they will
        dereference deleted arrays."""
        import jax

        if donate is None:
            donate = self.cfg.donate_state
        step = self._train_step_fn

        def multi(params, opt_state, state, inputs, labels, rng, i0=0):
            def at(i, arrs):
                return [jax.lax.dynamic_index_in_dim(a, i, keepdims=False)
                        for a in arrs]

            def body(i, carry):
                p, o, s, loss_sum, msum = carry
                p, o, s, loss, mv = step(
                    p, o, s, at(i, inputs),
                    jax.lax.dynamic_index_in_dim(labels, i, keepdims=False),
                    jax.random.fold_in(rng, i0 + i))
                return (p, o, s, loss_sum + loss,
                        jax.tree_util.tree_map(jnp.add, msum, mv))

            # step 0 outside the loop fixes the carry's loss/metric shapes
            p, o, s, l0, mv0 = step(params, opt_state, state,
                                    [a[0] for a in inputs], labels[0],
                                    jax.random.fold_in(rng, i0))
            p, o, s, lsum, msum = jax.lax.fori_loop(
                1, n, body, (p, o, s, l0, mv0))
            return p, o, s, lsum / n, \
                jax.tree_util.tree_map(lambda x: x / n, msum)

        return jax.jit(self._wrap_precision(multi),
                       donate_argnums=(0, 1, 2) if donate else ())

    def _coerce_batch(self, batch_size: Optional[int]) -> int:
        # batch must match the traced graph-input batch dim (XLA static shapes)
        gb = self.model.input_tensors[0].shape[0]
        if batch_size is not None and batch_size != gb:
            import warnings

            warnings.warn(f"batch_size={batch_size} coerced to graph batch {gb} "
                          "(XLA static shapes; rebuild the model to change it)")
        return gb

    # ------------------------------------------------------------- training
    def fit(self, x, y, batch_size: Optional[int] = None, epochs: Optional[int] = None,
            callbacks=None, verbose: bool = True,
            sync_every: Optional[int] = None,
            steps_per_dispatch: Optional[int] = None,
            accum_steps: Optional[int] = None,
            resume: Optional[str] = None,
            checkpoint_dir: Optional[str] = None,
            checkpoint_every_steps: Optional[int] = None,
            checkpoint_every_secs: Optional[float] = None):
        # per-call overrides of the async-pipeline knobs (see config.py);
        # None = the config's value, threaded through (cfg never mutated)
        if sync_every is None:
            sync_every = self.cfg.sync_every
        if steps_per_dispatch is None:
            steps_per_dispatch = self.cfg.steps_per_dispatch
        if accum_steps is None:
            accum_steps = self.cfg.accum_steps
        if max(1, int(accum_steps)) != self._accum_steps:
            # the accumulation width is baked into the jitted step
            # functions: a different per-call value (or reverting to the
            # config's after an override) rebuilds them (and clears the
            # fused multi-step cache)
            self._accum_steps = max(1, int(accum_steps))
            self._build_steps()
        return self._fit(x, y, batch_size, epochs, callbacks, verbose,
                         sync_every, steps_per_dispatch,
                         resume, checkpoint_dir, checkpoint_every_steps,
                         checkpoint_every_secs)

    def _fit(self, x, y, batch_size, epochs, callbacks, verbose,
             sync_every, steps_per_dispatch, resume=None,
             checkpoint_dir=None, checkpoint_every_steps=None,
             checkpoint_every_secs=None):
        from flexflow_tpu.runtime.resilience import FitResilience

        xs = x if isinstance(x, (list, tuple)) else [x]
        batch_size = batch_size or self.cfg.batch_size
        epochs = epochs or self.cfg.epochs
        if self.params is None:
            self.init()
        batch_size = self._coerce_batch(batch_size)
        # resilience (runtime/resilience.py): durable periodic checkpoints,
        # SIGTERM/SIGINT drain, resume="auto". None when fully off — the
        # loop below then runs exactly the PR-2 async pipeline.
        res = FitResilience.build(self, resume, checkpoint_dir,
                                  checkpoint_every_steps,
                                  checkpoint_every_secs)
        if res is not None:
            # effective (per-call) knobs, not cfg: they define what the
            # manifest's progress counters mean, for save AND resume check
            res.set_effective(batch_size, self._accum_steps)
        # goodput accounting (flexflow_tpu/health.py): one meter per fit;
        # restore-from-checkpoint time is the "resume" bucket (it happens
        # before any epoch wall-clock starts)
        gm = self._goodput = health.GoodputMeter()
        t_res = time.perf_counter()
        progress = res.resume_now(verbose) if res is not None else None
        gm.add("resume", time.perf_counter() - t_res)
        loader = SingleDataLoader(xs, y, batch_size, shuffle=True, seed=self.cfg.seed)
        in_sh = [self.input_sharding(t) for t in self.model.input_tensors]
        lab_sh = self.label_sharding((batch_size,) + tuple(np.asarray(y).shape[1:]))
        base_rng = jax.random.PRNGKey(self.cfg.seed + 17)
        self._drift_windows = []  # this fit's drift-monitor windows
        history = []
        # --profiling (reference config.h:126): capture an xplane trace of
        # the whole fit (the Legion-trace/profiler analog, flexflow_c.cc:1747)
        prof_ctx = None
        if self.cfg.profiling:
            import os

            pdir = self.cfg.profile_dir or "./ff_profile"
            os.makedirs(pdir, exist_ok=True)
            prof_ctx = jax.profiler.trace(pdir)
            prof_ctx.__enter__()
        try:
            history = self._fit_epochs(epochs, loader, in_sh, lab_sh,
                                       base_rng, batch_size, callbacks,
                                       verbose, sync_every,
                                       steps_per_dispatch, res, progress,
                                       gm)
        finally:
            if prof_ctx is not None:
                prof_ctx.__exit__(None, None, None)
                if verbose:
                    print(f"[profiling] trace written to "
                          f"{self.cfg.profile_dir or './ff_profile'}")
        self._fit_end_report(verbose)
        # per-op work only on the success path (it launches measurement
        # jits; on an error path it would mask the real exception).
        # --profile-ops: attribute the fit's REAL measured step time to
        # individual ops (flexflow_tpu/attribution.py) — only when someone
        # consumes the result (printed table or the telemetry corpus), and
        # not when profile_report below runs the same join anyway
        will_report = prof_ctx is not None and verbose
        if self.cfg.profile_ops and (verbose or tel.enabled()) \
                and not will_report:
            self.op_attribution(print_table=verbose)
        if will_report:
            self.profile_report()
        # self-calibration (ISSUE 14): --auto-refit closes the drift loop —
        # fold this run's telemetry through span_dataset into a refreshed
        # learned cost model. Runs AFTER op_attribution so the refit sees
        # THIS fit's op/attr rows, and on every profiled fit (not only a
        # tripped drift warn) so the corpus keeps growing; the model file's
        # content hash re-keys the strategy cache either way.
        if getattr(self.cfg, "auto_refit", False):
            from flexflow_tpu.search.learned_cost import auto_refit

            info = auto_refit(self.cfg)
            if info is not None and verbose:
                print(f"[refit] cost model <- {info['rows']} corpus rows "
                      f"({len(info['kinds'])} op kinds) -> {info['path']} "
                      f"[{info['fingerprint']}]")
        return history

    def _fit_end_report(self, verbose: bool) -> None:
        """Fit-end summary hooks: emit the drift event into the telemetry
        stream, warn when the cost model has drifted past the threshold,
        and surface any FAILED async checkpoint writes (a dropped
        checkpoint must never go unnoticed — satellite of ISSUE 5)."""
        from flexflow_tpu.runtime.checkpoint import warn_failed_writes

        tel.emit_fit_end(self.drift_stats(), verbose)
        warn_failed_writes(verbose)

    def _fit_epochs(self, epochs, loader, in_sh, lab_sh, base_rng,
                    batch_size, callbacks, verbose, sync_every,
                    steps_per_dispatch, res=None, progress=None, gm=None):
        """Asynchronous training pipeline (the Legion async-launch analog):
        the host's only per-step work is folding the rng key and issuing
        the next dispatch — loss/metrics stay device-resident (deferred
        PerfMetrics + a pending-loss list) and are materialized every
        cfg.sync_every steps (0 = epoch end only), K=cfg.steps_per_dispatch
        consecutive steps fuse into one make_multi_step dispatch over
        stacked prefetched batches, and a block_until_ready barrier every
        cfg.dispatch_ahead dispatches bounds how far the host may queue
        ahead of the device. Per-batch callbacks (`on_batch_end`) or a
        recompile trigger need per-step host control: they force K=1 and
        per-step materialization (the synchronous loop).

        `self.step_stats` counts dispatches / host_syncs / barriers /
        fused_steps for the whole fit; each epoch's history entry carries
        its own dispatches/host_syncs (tools/bench_step.py --check asserts
        dispatches <= ceil(num_batches/K) and zero mid-epoch host syncs in
        the default config).

        `res` (runtime/resilience.FitResilience, None = off) adds durable
        periodic checkpoints + SIGTERM/SIGINT drain, and `progress` (from
        a restored snapshot's manifest) resumes MID-RUN on the identical
        trajectory: the loader's shuffle rng fast-forwards past the
        completed epochs, the interrupted epoch skips its already-consumed
        accumulation groups, and the epoch's loss/metric accumulators are
        re-seeded from the snapshot so its summary covers the full epoch."""
        from flexflow_tpu.runtime import faults as _faults
        from flexflow_tpu.runtime.resilience import (RetryPolicy,
                                                     progress_dict,
                                                     run_resilient,
                                                     start_state)

        policy = res.policy if res is not None \
            else RetryPolicy.from_config(self.cfg)
        # run-health layer: the goodput meter buckets the loop's
        # wall-clock via its lap cursor (always on — a handful of
        # perf_counter calls per DISPATCH, not per step), and the
        # sentinel monitor strips the step functions' health/* outputs
        # into its own deferred window, checked only at the loop's
        # existing materialization points
        if gm is None:
            gm = self._goodput = health.GoodputMeter()
        sent = None
        if getattr(self.cfg, "health_sentinels", False):
            sent = health.SentinelMonitor(
                halt=bool(getattr(self.cfg, "halt_on_nonfinite", False)),
                checkpoint_root=res.root if res is not None else None)
        self._sentinels = sent
        start_epoch, skip_steps, history = start_state(progress)
        if progress:
            # the dataloader cursor: epochs 0..start_epoch-1 consumed their
            # shuffles; the resumed epoch below re-draws the SAME one
            loader.advance_epochs(start_epoch)
        per_batch_cbs = [cb for cb in callbacks or []
                         if hasattr(cb, "on_batch_end")]
        ahead = max(1, int(self.cfg.dispatch_ahead))
        # accum_steps=N: the loop's unit becomes an (N, ...)-stacked
        # accumulation group (group_microbatches below) — one dispatch of
        # the accumulating step = one optimizer update over N microbatches.
        # The unit shardings gain a leading unsharded microbatch dim; the
        # K-fused stacking then rides on top ((K, N, ...) transfers).
        accum = max(1, int(self._accum_steps))
        if accum > 1:
            in_sh_u = [NamedSharding(self.mesh, PartitionSpec(None, *s.spec))
                       for s in in_sh]
            lab_sh_u = NamedSharding(self.mesh,
                                     PartitionSpec(None, *lab_sh.spec))
        else:
            in_sh_u, lab_sh_u = in_sh, lab_sh
        in_sh_k = [NamedSharding(self.mesh, PartitionSpec(None, *s.spec))
                   for s in in_sh_u]
        lab_sh_k = NamedSharding(self.mesh,
                                 PartitionSpec(None, *lab_sh_u.spec))
        stats = self.step_stats = {"dispatches": 0, "host_syncs": 0,
                                   "barriers": 0, "fused_steps": 0}
        # telemetry + xplane step labels: `rec` is captured once (a local
        # bool) so the disabled path stays the exact PR-2 loop — same
        # dispatches, same host syncs, no per-step allocations beyond it.
        # Under --profiling each dispatch also runs inside a
        # StepTraceAnnotation, so the xplane trace is step-labeled.
        rec = tel.enabled()
        prof = jax.profiler.StepTraceAnnotation if self.cfg.profiling \
            else None
        faults_on = _faults.active()
        if res is not None:
            res.install_guard()
        try:
            for epoch in range(start_epoch, epochs):
              # fallbacks re-evaluated per epoch: a recompile trigger
              # registered mid-fit (e.g. by on_epoch_end) must drop the loop
              # to 1-step dispatch — and _get_multi must be re-fetched after
              # any recompile rebuilt the step functions
              k = max(1, int(steps_per_dispatch))
              sync = max(0, int(sync_every))
              if per_batch_cbs or self.recompile_state is not None:
                  k, sync = 1, 1  # per-step host control required
              multi = self._get_multi(k) if k > 1 else None
              pm = PerfMetrics()
              t0 = time.perf_counter()
              gm.tick()  # arm the goodput lap cursor at the epoch wall
              # loss rides a second deferred PerfMetrics keyed by STEPS (not
              # samples): device chunk-folding bounds memory on long epochs.
              # Parity with the old `loss_sum += float(loss)` loop is
              # bit-exact below fold_after pending steps, ~1e-7 relative
              # beyond (see PerfMetrics docstring)
              pml = PerfMetrics()
              nb = 0
              # steps/samples re-seeded from a resumed snapshot: the epoch
              # SUMMARY covers the whole epoch, but wall-clock-derived
              # stats (drift windows, samples/sec) must only count work
              # executed in THIS session
              seed_steps = seed_samples = 0
              # resume mid-epoch: the first `skip_steps` accumulation
              # groups were consumed before the snapshot — the loader
              # fast-forwards past their batches WITHOUT gathering them
              # (snapshots land on dispatch boundaries, so the skipped
              # region is whole accum-groups), and the epoch accumulators
              # re-seed from the manifest so this epoch's summary still
              # covers the WHOLE epoch
              resuming = epoch == start_epoch and progress
              grouped = group_microbatches(
                  loader.epoch(skip_batches=skip_steps * accum
                               if resuming else 0), accum)
              if resuming:
                  nb = seed_steps = skip_steps
                  pml.sums["loss"] = float(progress.get("loss_sum", 0.0))
                  pml.train_all = nb
                  pm.sums = {mk: float(mv) for mk, mv in
                             (progress.get("metric_sums") or {}).items()}
                  pm.train_all = seed_samples = int(progress.get("samples", 0))
              if sent is not None:
                  # per-epoch loss-window baseline (re-seeded on resume so
                  # pre-snapshot loss mass can't look like a spike)
                  sent._loss_sum_prev = pml.sums.get("loss", 0.0)
                  sent._steps_prev = nb
              ep_disp = ep_sync = 0
              since_sync = 0
              gen = prefetch_multi(
                  grouped, k,
                  in_sh_u, lab_sh_u, in_sh_k, lab_sh_k,
                  put=self._put, retry_policy=policy)

              def make_progress(_pml=pml, _pm=pm, _epoch=epoch):
                  # durable progress counters for res.maybe_checkpoint
                  # (reads nb/history at call time)
                  _pml.materialize()
                  _pm.materialize()
                  return progress_dict(_epoch, nb,
                                       _pml.sums.get("loss", 0.0),
                                       _pm.sums, _pm.train_all, history)

              while True:
                  # telemetry: the gap between "want next batch" and
                  # "prefetcher delivered" is the data-wait cost the async
                  # loop is supposed to hide
                  if rec:
                      t_w = tel.now_us()
                      item = next(gen, None)
                      tel.record("fit/prefetch_wait", t_w, cat="fit")
                  else:
                      item = next(gen, None)
                  gm.lap("prefetch_wait")
                  if item is None:
                      break
                  kind, dx, dy = item
                  if faults_on:
                      # the fit/dispatch fault site: admission check BEFORE
                      # the jitted call (nothing consumed yet, retry-safe
                      # even under donation). One check per 1-based global
                      # step COVERED by this dispatch — "fail step 3" is
                      # fit/dispatch@3 regardless of how steps batch into
                      # fused dispatches (the faults.py contract)
                      for s in range(self._iteration + 1,
                                     self._iteration + 1
                                     + (k if kind == "k" else 1)):
                          run_resilient("fit/dispatch", lambda: None,
                                        policy, index=s)
                          # health/nonfinite: SILENT corruption — NaN-
                          # poison the first param leaf instead of
                          # raising, so the numerics sentinel (not an
                          # exception) must catch the blow-up
                          if _faults.poison("health/nonfinite", index=s):
                              leaves, tdef = jax.tree_util.tree_flatten(
                                  self.params)
                              if leaves:
                                  leaves[0] = leaves[0] * jnp.float32(
                                      np.nan)
                                  self.params = \
                                      jax.tree_util.tree_unflatten(
                                          tdef, leaves)
                  if rec:
                      t_d = tel.now_us()
                  ann = prof("train", step_num=self._iteration) \
                      if prof is not None else tel.NULL_SPAN
                  with ann:
                      if kind == "k":
                          (self.params, self.opt_state, self.state, loss,
                           mvals) = multi(self.params, self.opt_state,
                                          self.state, dx, dy, base_rng,
                                          jnp.int32(self._iteration))
                          steps = k
                          stats["fused_steps"] += k
                      else:  # single step (k==1, or the fused-epoch tail)
                          rng = jax.random.fold_in(base_rng, self._iteration)
                          (self.params, self.opt_state, self.state, loss,
                           mvals) = self.train_step(self.params,
                                                    self.opt_state,
                                                    self.state, dx, dy, rng)
                          steps = 1
                  gm.lap("dispatch")
                  self._iteration += steps
                  nb += steps
                  since_sync += steps
                  ep_disp += 1
                  stats["dispatches"] += 1
                  if rec:
                      tel.record("fit/dispatch", t_d, cat="fit", kind=kind,
                                 steps=steps, iteration=self._iteration)
                  if sent is not None:
                      sent.push(steps, mvals)  # strips health/* keys
                  pml.update_deferred(steps, {"loss": loss})
                  pm.update_deferred(batch_size * accum * steps, mvals)
                  gm.lap("loop")
                  if sync and since_sync >= sync:
                      if rec:
                          t_s = tel.now_us()
                      pml.materialize()
                      pm.materialize()
                      if sent is not None:
                          # sentinel window check rides the EXISTING sync
                          # (no extra materialization point)
                          sent.check(self._iteration,
                                     loss_sum=pml.sums.get("loss", 0.0),
                                     steps_total=nb)
                      if rec:
                          tel.record("fit/host_sync", t_s, cat="fit",
                                     iteration=self._iteration)
                      stats["host_syncs"] += 1
                      ep_sync += 1
                      since_sync = 0
                      gm.lap("host_sync")
                  elif ep_disp % ahead == 0:
                      # bounded dispatch-ahead: wait for the device to catch
                      # up (no host transfer, just a queue-depth barrier)
                      if rec:
                          t_b = tel.now_us()
                      jax.block_until_ready(loss)
                      if rec:
                          tel.record("fit/barrier_sync", t_b, cat="fit",
                                     iteration=self._iteration)
                      stats["barriers"] += 1
                      gm.lap("barrier")
                  if res is not None:
                      res.maybe_checkpoint(loss, make_progress)
                      gm.lap("checkpoint")
                  for cb in per_batch_cbs:
                      cb.on_batch_end(self._iteration, {"loss": float(loss)})
                  if kind == "1":
                      self._maybe_recompile()
              # epoch end: the one unavoidable materialization (not counted
              # as a mid-epoch host sync)
              if rec:
                  t_s = tel.now_us()
              pml.materialize()
              if sent is not None:
                  sent.check(self._iteration,
                             loss_sum=pml.sums.get("loss", 0.0),
                             steps_total=nb)
              if rec:
                  tel.record("fit/host_sync", t_s, cat="fit",
                             scope="epoch_end")
              gm.lap("host_sync")
              dt = time.perf_counter() - t0
              # drift/throughput count only work executed THIS session: a
              # resumed epoch's re-seeded steps/samples ran before the
              # snapshot, against a wall clock that started at resume
              self._drift_windows.append((nb - seed_steps, dt))
              if rec:
                  tel.record("fit/epoch", tel.now_us() - dt * 1e6,
                             cat="fit", epoch=epoch, steps=nb)
              grec = gm.epoch_end(dt, epoch)
              # HBM watermark at the epoch boundary (outside the epoch
              # wall; memory_stats() on real backends, live-buffer bytes
              # on the CPU twin)
              self._watermarks.sample(f"epoch{epoch}",
                                      (self.params, self.opt_state))
              summ = pm.summary()
              summ["loss"] = pml.sums.get("loss", 0.0) / max(1, nb)
              summ["epoch_time_s"] = dt
              summ["samples_per_sec"] = (pm.train_all - seed_samples) / dt \
                  if dt > 0 else 0.0
              summ["dispatches"] = float(ep_disp)
              summ["host_syncs"] = float(ep_sync)
              summ["goodput"] = grec["goodput"]
              history.append(summ)
              if verbose:
                  ms = " ".join(f"{k_}={v:.4f}" for k_, v in summ.items()
                                if k_ not in ("samples", "dispatches",
                                              "host_syncs"))
                  print(f"[epoch {epoch}] {ms}")
              for cb in callbacks or []:
                  if hasattr(cb, "on_epoch_end"):
                      cb.on_epoch_end(epoch, summ)
              if res is not None:
                  res.epoch_end(epoch, history)
            if res is not None:
                res.final_save(epochs, history)
        finally:
            if res is not None:
                res.guard.uninstall()
        return history

    def evaluate(self, x, y, batch_size: Optional[int] = None):
        # batch is pinned to the traced graph batch; tail samples beyond the
        # last full batch are excluded (drop_remainder, like the reference's
        # shard-sized batches)
        xs = x if isinstance(x, (list, tuple)) else [x]
        batch_size = self._coerce_batch(batch_size)
        loader = SingleDataLoader(xs, y, batch_size, shuffle=False)
        in_sh = [self.input_sharding(t) for t in self.model.input_tensors]
        lab_sh = self.label_sharding((batch_size,) + tuple(np.asarray(y).shape[1:]))
        pm = PerfMetrics()
        pml = PerfMetrics()  # deferred per-batch losses (chunk-folded)
        ahead = max(1, int(self.cfg.dispatch_ahead))
        nb = 0
        for dx, dy in prefetch_to_device(loader.epoch(), in_sh, lab_sh,
                                         put=self._put):
            loss, mvals = self.eval_step(self.params, self.state, dx, dy)
            pm.update_deferred(batch_size, mvals)
            pml.update_deferred(1, {"loss": loss})
            nb += 1
            if nb % ahead == 0:  # bounded dispatch-ahead, as in fit
                jax.block_until_ready(loss)
        pml.materialize()
        out = pm.summary()
        out["loss"] = pml.sums.get("loss", 0.0) / max(1, nb)
        return out

    def forward(self, *inputs):
        if self.params is None:
            self.init()
        arrs = [self._put(np.asarray(a), s)
                for a, s in zip(inputs, [self.input_sharding(t) for t in self.model.input_tensors])]
        outs = self.infer_step(self.params, self.state, arrs)
        return outs[0] if len(outs) == 1 else outs

    # ------------------------------------------------------------ profiling
    def _candidate_for(self, layer):
        """The sharding candidate matching the COMPILED strategy's weight
        layout for this layer (falls back to dp when nothing matches) —
        see candidates.compiled_candidate."""
        from flexflow_tpu.search.candidates import compiled_candidate

        batch_sizes = {t.shape[0] for t in self.model.input_tensors if t.ndim > 0}
        return compiled_candidate(layer, self.strategy, self.machine,
                                  batch_sizes)

    def memory_stats(self) -> dict:
        """Per-device persistent-memory report: what the search-side cost
        model PREDICTS for this compile's strategy + optimizer (params +
        grads + moments under the OptMemSpec accounting, ZeRO divisor
        included) next to what the live buffers ACTUALLY hold (summed
        addressable-shard bytes on device 0). tools/bench_zero.py asserts
        the two agree on the ~data-degree optimizer-state reduction."""
        from flexflow_tpu.search import cost_model as cmod

        opt_mem = cmod.opt_mem_spec(self.optimizer, self.cfg, self.machine)
        pred_w = pred_opt = 0
        for layer in self.model.layers:
            if not layer.weight_specs:
                continue
            cand = self._candidate_for(layer)
            pred_w += cand.weight_mem_bytes(layer, self.machine, opt_mem)
            for w, spec in layer.weight_specs.items():
                dims = cand.weight_dims.get(w, [])
                elems = cmod.shard_bytes(spec, dims, self.machine) \
                    // max(1, spec.dtype.itemsize)
                pred_opt += (opt_mem.moments * elems * opt_mem.state_itemsize
                             // cmod.zero_divisor(spec, dims, self.machine,
                                                  opt_mem.zero_axes))

        def per_device_bytes(tree):
            if tree is None:
                return 0
            dev = jax.devices()[0]
            total = 0
            for leaf in jax.tree_util.tree_leaves(tree):
                shards = getattr(leaf, "addressable_shards", None)
                if shards is None:
                    total += int(getattr(leaf, "nbytes", 0))
                    continue
                total += sum(s.data.nbytes for s in shards
                             if s.device == dev)
            return total

        za = _zero_axes_of(self.mesh)
        deg = 1
        for a in za:
            deg *= self.mesh.shape[a]
        return {
            "zero_sharding": self._zero_mode(),
            "data_axis_degree": deg,
            "predicted_weight_state_bytes": int(pred_w),
            "predicted_opt_state_bytes": int(pred_opt),
            "actual_param_bytes_per_device": per_device_bytes(self.params),
            "actual_opt_state_bytes_per_device":
                per_device_bytes(self.opt_state),
        }

    def search_cache_stats(self) -> dict:
        """Search fast-path observability: this compile's strategy-cache
        event, the process-wide cache counters, the memoized-costing hit
        rates, and the DP work counters (cache-stats of profile_report)."""
        from flexflow_tpu.search import memo
        from flexflow_tpu.search import strategy_cache as sc
        from flexflow_tpu.search.dp import SEARCH_STATS

        return {
            "strategy_cache": dict(sc.STATS.as_dict(),
                                   this_compile=self.search_cache_info),
            "memo": memo.stats(),
            "dp": dict(SEARCH_STATS),
        }

    def predicted_step_time(self) -> Optional[float]:
        """The cost model's per-UPDATE time prediction for this compile:
        the search's own best_cost when the strategy came out of
        graph_optimize (stamped there, and restored from the cache entry's
        meta on warm hits), else the analytic additive sum over the
        compiled candidates (data-parallel / imported strategies). Scaled
        by accum_steps — one fit-loop step is one update over N
        microbatch passes — so it is directly comparable to the drift
        monitor's measured windows."""
        accum = max(1, int(self._accum_steps))
        pc = getattr(self.strategy, "_predicted_cost", None)
        if pc:
            return float(pc) * accum
        try:
            total = 0.0
            for layer in self.model.layers:
                cand = self._candidate_for(layer)
                if not cand.passthrough:
                    total += cand.op_time(layer, self.machine)
            return total * accum if total > 0 else None
        except Exception:
            return None

    def drift_stats(self) -> dict:
        """Cost-model drift monitor: predicted vs measured step time (see
        telemetry.drift_stats; windows are the last fit's per-epoch
        (steps, seconds) pairs)."""
        return tel.drift_stats(self.predicted_step_time(),
                               list(self._drift_windows))

    def goodput_report(self) -> dict:
        """The last fit's wall-clock bucket accounting (see
        health.GoodputMeter.report): per-bucket seconds, goodput%, the
        unattributed residual, and the accounted fraction. Empty dict
        before any fit."""
        return self._goodput.report() if self._goodput is not None else {}

    def health_report(self) -> dict:
        """Run-health summary: sentinel detector status (nonfinite /
        spike counts) and the HBM watermark vs the cost model's
        predicted per-device footprint (health.watermark_drift)."""
        sent = self._sentinels.state.status() \
            if self._sentinels is not None else None
        wm = None
        if self._watermarks.samples:
            pred = self.memory_stats()["predicted_weight_state_bytes"]
            wm = self._watermarks.report(pred)
        return {"sentinels": sent, "watermarks": wm}

    def op_attribution(self, step_time_s: Optional[float] = None,
                       source: str = "auto", top: int = 0,
                       print_table: bool = True) -> dict:
        """Per-op performance attribution (ISSUE 7 tentpole; see
        flexflow_tpu/attribution.py): joins each compiled op's measured
        time — the --profiling trace when one exists, else the partitioned
        re-execution — against the search's stamped per-op predicted cost
        and the roofline bound. step_time_s defaults to the drift
        monitor's measured per-update time from the LAST fit (attributed
        times are rescaled to sum to it); with no fit yet, attributed ==
        isolated measured. Emits op/attr + op/drift_topk telemetry events
        when the sink is on (the span-dataset corpus). Returns the report
        dict ({"rows", "top_drift", "coverage", ...})."""
        from flexflow_tpu import attribution

        if step_time_s is None:
            step_time_s = self.drift_stats().get("measured_step_time_s")
        pred = getattr(self.strategy, "_predicted_op_costs", None) or {}
        items = []
        for layer in topo_order(self.model.layers):
            cand = self._candidate_for(layer)
            if cand.passthrough:
                continue
            items.append({"layer": layer, "cand": cand,
                          "machine": self.machine,
                          "predicted_s": pred.get(layer.name),
                          "stage": None})
        profile_dir = (self.cfg.profile_dir or "./ff_profile") \
            if self.cfg.profiling else None
        report = attribution.build_report(
            items, step_time_s=step_time_s,
            mult=max(1, int(self._accum_steps)),
            profile_dir=profile_dir, source=source)
        if print_table:
            for line in attribution.format_report(report, top=top):
                print(line)
        return report

    def profile_report(self, top: int = 0, print_table: bool = True):
        """Per-op timing table (reference: per-kernel ms prints behind
        --profiling, src/ops/kernels/linear_kernels.cu:98-117): each layer's
        analytic roofline prediction and isolated measured time under the
        candidate matching its COMPILED sharding, plus the search fast-path
        cache stats (strategy cache / memoized costing / DP counters).
        Returns the rows."""
        from flexflow_tpu.search.measure import MeasuredCost

        # deliberately NOT backed by the persistent measured-cost store
        # (cache_dir="" also overrides the FF_MEASURE_CACHE_DIR fallback):
        # these quick repeats=3/warmup=1 numbers are report-quality, and
        # persisting them would silently degrade the calibration data (and
        # fingerprint) the measured SEARCH path relies on
        mc = MeasuredCost(self.machine, repeats=3, warmup=1, cache_dir="")
        rows = []
        for layer in self.model.layers:
            cand = self._candidate_for(layer)
            if cand.passthrough:
                continue
            rows.append({
                "layer": layer.name,
                "op": layer.op_type.value,
                "candidate": cand.name,
                "analytic_us": cand.op_time(layer, self.machine) * 1e6,
                "measured_us": mc.op_time(layer, cand) * 1e6,
            })
        rows.sort(key=lambda x: -x["measured_us"])
        if top:
            rows = rows[:top]
        if print_table:
            total = sum(x["measured_us"] for x in rows) or 1.0
            print(f"{'layer':28} {'op':18} {'analytic':>10} {'measured':>10} {'%':>5}")
            for x in rows:
                print(f"{x['layer'][:28]:28} {x['op'][:18]:18} "
                      f"{x['analytic_us']:9.1f}u {x['measured_us']:9.1f}u "
                      f"{100 * x['measured_us'] / total:4.1f}%")
            from flexflow_tpu.search import memo

            stats = self.search_cache_stats()
            cs, dp = stats["strategy_cache"], stats["dp"]
            info = self.search_cache_info or {}
            print(f"[strategy-cache] this_compile="
                  f"{info.get('event', 'off/skipped')} "
                  f"hits={cs['hits']} misses={cs['misses']} "
                  f"stores={cs['stores']} invalidated={cs['invalidated']}")
            print(f"[search] dp_calls={dp.get('calls', 0)} "
                  f"expansions={dp.get('expansions', 0)} "
                  f"prefix_skipped_layers={dp.get('layers_skipped', 0)}; "
                  f"{memo.stats_line()}")
            mem = self.memory_stats()
            mb = 1024 * 1024
            print(f"[memory] zero={mem['zero_sharding']} "
                  f"data_degree={mem['data_axis_degree']} "
                  f"predicted/device: weight-state "
                  f"{mem['predicted_weight_state_bytes'] / mb:.2f}MB "
                  f"(opt {mem['predicted_opt_state_bytes'] / mb:.2f}MB)")
            print(f"[memory] actual/device:    params "
                  f"{mem['actual_param_bytes_per_device'] / mb:.2f}MB, "
                  f"opt state "
                  f"{mem['actual_opt_state_bytes_per_device'] / mb:.2f}MB")
            for line in tel.format_drift(self.drift_stats()):
                print(line)
            if self._goodput is not None and self._goodput.epochs:
                for line in health.format_goodput(self._goodput.report()):
                    print(line)
            wm = self._watermarks.report(
                mem["predicted_weight_state_bytes"]) \
                if self._watermarks.samples else None
            sent = self._sentinels.state.status() \
                if self._sentinels is not None else None
            for line in health.format_health(sent, wm):
                print(line)
            if self.cfg.profile_ops:
                # --profile-ops: the full attribution join (measured vs
                # predicted vs roofline, MFU, per-op drift top-K)
                self.op_attribution(print_table=True, top=top)
            else:
                print("[drift] per-op attribution: --profile-ops / "
                      "op_attribution() / tools/profile_attribution.py")
            from flexflow_tpu.runtime.checkpoint import \
                report_failed_writes

            for line in report_failed_writes():
                print(line)
        return rows

    def export_sim_trace(self, path: str):
        """Replay the COMPILED strategy through the event-driven simulator
        and write a chrome-trace timeline (load in chrome://tracing /
        perfetto) — the reference taskgraph simulator's export_file_name
        analog. Wired to --simulator-trace. Returns the SimReport."""
        from flexflow_tpu.search.simulator import simulate_strategy

        choices = {l.name: self._candidate_for(l) for l in self.model.layers}
        # same segmentation the search's re-rank used, so the exported
        # timeline matches the simulation that ranked the strategy
        report = simulate_strategy(self.model, choices, self.machine,
                                   segment_bytes=self.cfg.simulator_segment_size)
        report.export_trace(path)
        return report

    # ------------------------------------------------- recompile-on-condition
    def recompile_on_condition(self, trigger_fn, alter_fn):
        """Reference: RecompileState (include/flexflow/recompile.h:26-43),
        FFModel::recompile_on_condition (src/runtime/model.cc:2422)."""
        self.recompile_state = (trigger_fn, alter_fn)

    def _maybe_recompile(self):
        if self.recompile_state is None:
            return
        trigger, alter = self.recompile_state
        if trigger(self):
            alter(self)
            self.forward_fn = build_forward(self.model.layers, self.model.input_tensors,
                                            self.outputs, self.mesh, self.strategy,
                                            seq_length=self.cfg.seq_length or None,
                                            compute_dtype=self.cfg.compute_dtype,
                                            enable_fusion=self.cfg.enable_fusion)
            self._build_steps()
            if self._goodput is not None:
                # charge the rebuild to the recompile goodput bucket
                self._goodput.lap("recompile")

    # ----------------------------------------------------------- checkpoint
    def save_checkpoint(self, path: str, block: Optional[bool] = None) -> str:
        """Full training-state checkpoint (params + optimizer state + BN
        state + iteration) — orbax-backed; see runtime/checkpoint.py.

        With cfg.async_checkpoint (the default), the device→host snapshot
        happens here (donation-safe) and serialization + fsync run on a
        background writer thread, so periodic saves don't stall the step
        loop. `load_checkpoint`/`wait_checkpoints` join pending writes;
        pass block=True to force the old fully synchronous save."""
        from flexflow_tpu.runtime.checkpoint import save_checkpoint

        if block is None:
            block = not self.cfg.async_checkpoint
        return save_checkpoint(self, path, block=block)

    def wait_checkpoints(self) -> None:
        """Join any in-flight async checkpoint writes (surfacing their
        errors here rather than losing them with the writer thread)."""
        from flexflow_tpu.runtime.checkpoint import wait_pending

        wait_pending()

    def load_checkpoint(self, path: str) -> None:
        from flexflow_tpu.runtime.checkpoint import restore_checkpoint

        restore_checkpoint(self, path)

    # ------------------------------------------------------------- weights
    def parallel_view(self, layer_name: str, out_idx: int = 0):
        """The ParallelTensor view of a layer output under the compiled
        strategy: per-dim degrees, shard shape, replica axes (reference
        ParallelTensorBase, include/flexflow/parallel_tensor.h:134-198)."""
        from flexflow_tpu.parallel.ptensor import ParallelTensor

        layer = self.model.get_layer_by_name(layer_name)
        sh = self.strategy.op_shardings.get(layer_name)
        dims = sh.outputs[out_idx] if sh and out_idx < len(sh.outputs) else []
        return ParallelTensor.build(layer.outputs[out_idx].spec, list(dims),
                                    self.machine)

    @staticmethod
    def _stacked_alias(layer, wname):
        """Resolve a per-branch "b{i}.{sub}.{w}" name against stacked
        storage: returns (stacked_key, branch_index) or None. Keeps the
        per-branch weight API stable across the two residency regimes."""
        if wname in layer.weight_specs or not wname.startswith("b"):
            return None
        head, _, rest = wname.partition(".")
        if not rest or not head[1:].isdigit():
            return None
        stk = f"stk.{rest}"
        return (stk, int(head[1:])) if stk in layer.weight_specs else None

    def weight_view(self, layer_name: str, wname: str = "kernel"):
        """ParallelTensor view of a weight under the compiled strategy."""
        from flexflow_tpu.core.tensor import TensorSpec
        from flexflow_tpu.parallel.ptensor import ParallelTensor

        layer = self.model.get_layer_by_name(layer_name)
        sh = self.strategy.op_shardings.get(layer_name)
        alias = self._stacked_alias(layer, wname)
        if alias is not None:
            stk, _b = alias
            spec = layer.weight_specs[stk]
            dims = list(sh.weights.get(stk, []) if sh else [])
            # the branch slice drops the stacked dim (and its sharding)
            return ParallelTensor.build(
                TensorSpec(spec.shape[1:], spec.dtype), list(dims[1:]),
                self.machine)
        dims = (sh.weights.get(wname, []) if sh else [])
        return ParallelTensor.build(layer.weight_specs[wname], list(dims),
                                    self.machine)

    def get_weight(self, layer_name: str, wname: str = "kernel") -> np.ndarray:
        layer = self.model.get_layer_by_name(layer_name)
        alias = self._stacked_alias(layer, wname)
        if alias is not None:
            stk, b = alias
            return np.asarray(self.params[layer_name][stk])[b]
        return np.asarray(self.params[layer_name][wname])

    def set_weight(self, layer_name: str, wname: str, value):
        value = np.asarray(value)
        layer = self.model.get_layer_by_name(layer_name)
        alias = self._stacked_alias(layer, wname)
        if alias is not None:
            stk, b = alias
            target = self.params[layer_name][stk]
            assert value.shape == tuple(target.shape[1:]), \
                (value.shape, target.shape)
            # in-place sharded slice update: only the owning devices' shard
            # moves (gathering the whole stack to host would defeat the
            # owned-device residency); branch index is a traced argument so
            # repeated set_weight calls hit the jit cache
            self.params[layer_name][stk] = _stacked_slice_set(
                target, jnp.asarray(value, target.dtype), jnp.int32(b))
            return
        target = self.params[layer_name][wname]
        assert value.shape == tuple(target.shape), (value.shape, target.shape)
        self.params[layer_name][wname] = self._put(value, target.sharding)
