"""Request-level tracing + streaming latency histograms (ISSUE 15).

The serving stack makes per-request decisions (priority admission,
TTFT-budget shedding, chunked prefill, speculative rounds, watchdog
evictions) but until this module the telemetry stopped at flat
`serve/request_*` instants and aggregate gauges — nobody could answer
"where did request R's 22 ms go" or "how much p99 TTFT budget is left".
Two pieces live here:

  * `StreamingHistogram` — fixed log-spaced buckets (shared edges across
    every instance, so two histograms merge by adding bucket counts:
    multi-process monitor tails stay exact), numpy-only, O(1) memory.
    Exports real Prometheus histogram series (`*_bucket{le=...}` with
    cumulative counts + `_sum` + `_count`) and answers quantiles with
    within-bucket interpolation — the single source of truth for serving
    latency percentiles (bench_serve and monitor both read it, so they
    can no longer disagree).
  * `RequestTracer` — the per-request lifecycle trace. Every request
    carries a stage cursor from submission through queue-wait, its
    prefill wave, each decode-window materialization / speculative round
    (drafted vs committed vs rejected tokens), any param swap landing
    mid-flight, to the terminal outcome. Stages TILE the request's wall
    time (each span starts where the previous one ended), so accounting
    is >=95% by construction; spans are emitted as `serve/req/<stage>`
    through the existing telemetry sink with tid "slot<k>" (the Chrome
    export reads as one timeline row per decode slot) and finished
    traces are retained in a bounded ring for live queries.

Zero-sync contract: the tracer NEVER reads a device value or calls
perf_counter itself — every timestamp it sees is one the scheduler
already took at an existing dispatch-window boundary. With
`--no-serve-reqtrace` the scheduler holds no tracer at all and its
dispatch/host-sync behavior is bitwise the PR-13 baseline (pinned in
tests/test_serving_reqtrace.py).
"""

from __future__ import annotations

import time
from collections import deque
from typing import Any, Dict, Iterable, List, Optional, Sequence

import numpy as np

from flexflow_tpu import telemetry as tel

# ------------------------------------------------------------- histograms
# One fixed bucket layout for every latency histogram in the process:
# log-spaced, 10 buckets per decade from 1us to 100s (~26% resolution per
# bucket). Fixed edges are what make histograms MERGEABLE — counts from
# two processes (or two bench legs) add elementwise with no rebinning.
HIST_LO_S = 1e-6
HIST_HI_S = 1e2
HIST_BUCKETS_PER_DECADE = 10
_N_EDGES = 8 * HIST_BUCKETS_PER_DECADE + 1  # 8 decades inclusive
HIST_EDGES = np.logspace(np.log10(HIST_LO_S), np.log10(HIST_HI_S), _N_EDGES)

# the tracer's five live histogram families (ISSUE 15 tentpole #2)
HIST_METRICS = ("ttft", "per_token", "queue_wait", "prefill", "decode_step")


class StreamingHistogram:
    """Fixed-bucket streaming latency histogram (seconds).

    counts[i] holds samples x with edges[i-1] < x <= edges[i]
    (counts[0] is the underflow <= edges[0], counts[-1] the overflow
    > edges[-1]), matching the Prometheus cumulative-`le` convention."""

    __slots__ = ("edges", "counts", "sum", "count")

    def __init__(self, edges: Optional[np.ndarray] = None):
        self.edges = HIST_EDGES if edges is None else np.asarray(edges, float)
        self.counts = np.zeros(len(self.edges) + 1, dtype=np.int64)
        self.sum = 0.0
        self.count = 0

    def add(self, value_s: float, n: int = 1) -> None:
        """Record `n` occurrences of one latency value."""
        if not np.isfinite(value_s):
            return
        i = int(np.searchsorted(self.edges, value_s, side="left"))
        self.counts[i] += n
        self.sum += float(value_s) * n
        self.count += n

    def add_many(self, values_s: Iterable[float]) -> None:
        vs = np.asarray(list(values_s), float)
        vs = vs[np.isfinite(vs)]
        if vs.size == 0:
            return
        idx = np.searchsorted(self.edges, vs, side="left")
        np.add.at(self.counts, idx, 1)
        self.sum += float(vs.sum())
        self.count += int(vs.size)

    def merge(self, other: "StreamingHistogram") -> "StreamingHistogram":
        """In-place merge; requires identical bucket edges (always true
        for the module's fixed layout)."""
        if len(self.edges) != len(other.edges) or \
                not np.allclose(self.edges, other.edges):
            raise ValueError("cannot merge histograms with different edges")
        self.counts += other.counts
        self.sum += other.sum
        self.count += other.count
        return self

    def quantile(self, q: float) -> Optional[float]:
        """q-quantile estimate (linear interpolation inside the landing
        bucket). Error is bounded by one bucket's width (~26%); tests pin
        this against np.percentile on random draws."""
        if self.count == 0:
            return None
        q = min(1.0, max(0.0, float(q)))
        target = q * (self.count - 1)
        cum = 0
        for i, c in enumerate(self.counts):
            if c == 0:
                continue
            if cum + c > target:
                lo = float(self.edges[i - 1]) if i >= 1 else 0.0
                hi = float(self.edges[i]) if i < len(self.edges) \
                    else float(self.edges[-1])
                frac = (target - cum + 0.5) / c
                return lo + (hi - lo) * min(1.0, max(0.0, frac))
            cum += c
        return float(self.edges[-1])

    def mean(self) -> Optional[float]:
        return self.sum / self.count if self.count else None

    # -------------------------------------------------------- serialization
    def snapshot(self) -> Dict[str, Any]:
        """Compact dict for a telemetry event: nonzero buckets only (the
        JSONL stays small) + enough layout info to reconstruct/merge."""
        nz = np.nonzero(self.counts)[0]
        return {"buckets": {int(i): int(self.counts[i]) for i in nz},
                "sum": float(self.sum), "count": int(self.count),
                "n_edges": len(self.edges)}

    @classmethod
    def from_snapshot(cls, snap: Dict[str, Any]) -> "StreamingHistogram":
        h = cls()
        if int(snap.get("n_edges", len(h.edges))) != len(h.edges):
            raise ValueError("histogram snapshot has a different bucket "
                             "layout than this build")
        for i, c in (snap.get("buckets") or {}).items():
            h.counts[int(i)] = int(c)
        h.sum = float(snap.get("sum", 0.0))
        h.count = int(snap.get("count", 0))
        return h

    def prom_lines(self, name: str, help_: str) -> List[str]:
        """Render as a real Prometheus histogram series: cumulative
        `_bucket{le="..."}` per edge, `+Inf`, `_sum`, `_count`."""
        lines = [f"# HELP {name} {help_}", f"# TYPE {name} histogram"]
        cum = 0
        for i, edge in enumerate(self.edges):
            cum += int(self.counts[i])
            lines.append(f'{name}_bucket{{le="{edge:.6g}"}} {cum}')
        lines.append(f'{name}_bucket{{le="+Inf"}} {self.count}')
        lines.append(f"{name}_sum {self.sum:.9g}")
        lines.append(f"{name}_count {self.count}")
        return lines


# --------------------------------------------------------- terminal schema
# The unified terminal-event field set every serve/request_{done,shed,
# failed} event carries (ISSUE 15 satellite: the SLO tracker and access
# log never special-case an outcome).
TERMINAL_FIELDS = ("rid", "priority", "outcome", "outcome_reason",
                   "queue_wait_s", "ttft_s", "per_token_s", "tokens_in",
                   "tokens_out", "kv_pages", "total_s")


def terminal_record(req, now_s: float, kv_pages: int,
                    reason: str) -> Dict[str, Any]:
    """The unified terminal record for any outcome, derived purely from
    fields the scheduler already fills — no tracer required, so the
    schema holds even under --no-serve-reqtrace. per_token_s is the
    post-first-token decode average (None below 2 tokens)."""
    tokens_out = len(req.tokens)
    total_s = max(0.0, now_s - req.arrival_s)
    queue_wait_s = (req.admit_s - req.arrival_s
                    if getattr(req, "admit_s", None) is not None
                    else total_s)
    per_token_s = None
    if req.ttft_s is not None and tokens_out >= 2:
        per_token_s = max(0.0, total_s - req.ttft_s) / (tokens_out - 1)
    return {"rid": req.rid, "priority": req.priority,
            "outcome": req.outcome, "outcome_reason": reason,
            "queue_wait_s": max(0.0, queue_wait_s),
            "ttft_s": req.ttft_s, "per_token_s": per_token_s,
            "tokens_in": len(req.prompt), "tokens_out": tokens_out,
            "kv_pages": int(kv_pages), "total_s": total_s}


# ---------------------------------------------------------------- tracer
class RequestTracer:
    """Per-request lifecycle tracing for the continuous-batching loop.

    Timestamps are SCHEDULER-relative seconds (offsets from run()'s t0
    perf_counter origin) — exactly the values the scheduler already
    takes at its sync points; `begin()` anchors that domain onto the
    telemetry clock so emitted spans land on the shared timeline."""

    def __init__(self, ring: int = 512):
        self.hists: Dict[str, StreamingHistogram] = {
            m: StreamingHistogram() for m in HIST_METRICS}
        self.ring: "deque[Dict[str, Any]]" = deque(maxlen=max(1, int(ring)))
        self._live: Dict[int, Dict[str, Any]] = {}
        self._base_us: Optional[float] = None

    # ------------------------------------------------------------ plumbing
    def begin(self, t0_perf: float) -> None:
        """Anchor the scheduler's clock (t0 = its perf_counter origin)
        onto the telemetry us domain."""
        self._base_us = tel.now_us() - (time.perf_counter() - t0_perf) * 1e6

    def _to_us(self, offset_s: float) -> float:
        if self._base_us is None:  # direct unit-test use without run()
            self._base_us = tel.now_us() - offset_s * 1e6
        return self._base_us + offset_s * 1e6

    # -------------------------------------------------------------- stages
    def on_submit(self, req, now_s: float) -> None:
        self._live[req.rid] = {
            "rid": req.rid, "priority": req.priority,
            "arrival_s": req.arrival_s, "tokens_in": len(req.prompt),
            "slot": None, "cursor": min(req.arrival_s, now_s),
            "stages": [], "swaps": []}

    def stage(self, req, name: str, end_s: float, **extra: Any) -> None:
        """Close one stage span for `req`: [previous stage end, end_s].
        The cursor discipline makes stages tile the request's wall."""
        tr = self._live.get(req.rid)
        if tr is None:
            return
        start = tr["cursor"]
        end = max(start, end_s)
        tr["stages"].append({"stage": name, "start_s": start, "end_s": end,
                             **extra})
        tr["cursor"] = end
        if tel.enabled():
            slot = tr["slot"]
            tel.record(f"serve/req/{name}", self._to_us(start),
                       self._to_us(end), cat="serve",
                       tid=("queue" if slot is None else f"slot{slot}"),
                       rid=req.rid, **extra)

    def on_admit(self, req, t_pre_s: float, t_first_s: float,
                 wave: int) -> None:
        """Queue stage closes at prefill dispatch; the prefill stage spans
        dispatch -> first-token materialization (the TTFT sync)."""
        tr = self._live.get(req.rid)
        if tr is None:
            return
        self.stage(req, "queue", t_pre_s)
        tr["slot"] = req.slot
        self.stage(req, "prefill", t_first_s, wave=wave,
                   prompt_tokens=len(req.prompt))
        self.hists["queue_wait"].add(max(0.0, t_pre_s - tr["arrival_s"]))
        self.hists["prefill"].add(max(0.0, t_first_s - t_pre_s))
        if req.ttft_s is not None:
            self.hists["ttft"].add(max(0.0, req.ttft_s))

    def on_decode_window(self, active_reqs: Sequence[Any], end_s: float,
                         steps: int, per_step_s: float,
                         tokens_kept: Dict[int, int]) -> None:
        """One materialized dispatch window, attributed to every slot that
        was active in it."""
        self.hists["decode_step"].add(per_step_s, n=max(1, steps))
        for req in active_reqs:
            self.stage(req, "decode", end_s, steps=steps,
                       tokens=tokens_kept.get(req.slot, steps))

    def on_spec_round(self, req, end_s: float, drafted: int, committed: int,
                      rejected: int) -> None:
        self.stage(req, "spec", end_s, drafted=drafted, committed=committed,
                   rejected=rejected)

    def on_swap(self, active_reqs: Sequence[Any], now_s: float,
                version: Optional[int]) -> None:
        """A param swap landed between windows: charge the swap wall to a
        'swap' stage on every in-flight request's timeline."""
        for req in active_reqs:
            tr = self._live.get(req.rid)
            if tr is None:
                continue
            tr["swaps"].append(version)
            self.stage(req, "swap", now_s, version=version)

    # ------------------------------------------------------------ terminal
    def on_terminal(self, req, now_s: float,
                    record: Dict[str, Any]) -> Dict[str, Any]:
        """Finalize a request: close the residual span (host bookkeeping
        between the last sync point and the terminal decision), move the
        trace to the ring, and feed the per-request histograms. Returns
        the finished trace."""
        tr = self._live.pop(req.rid, None)
        if tr is None:
            tr = {"rid": req.rid, "priority": req.priority,
                  "arrival_s": req.arrival_s, "tokens_in": len(req.prompt),
                  "slot": None, "cursor": req.arrival_s, "stages": [],
                  "swaps": []}
        if now_s > tr["cursor"]:
            # sheds spent their whole life queueing; anything slot-bound
            # was in (a failing) decode since the last materialization
            self.stage_tr(tr, req,
                          "queue" if tr["slot"] is None else "decode",
                          now_s)
        wall = max(0.0, now_s - tr["arrival_s"])
        accounted = sum(s["end_s"] - s["start_s"] for s in tr["stages"])
        tr.update(record)
        tr["wall_s"] = wall
        tr["accounted_s"] = accounted
        tr["accounted_frac"] = (accounted / wall) if wall > 0 else 1.0
        self.ring.append(tr)
        if record.get("per_token_s") is not None:
            self.hists["per_token"].add(record["per_token_s"])
        if tr["slot"] is None and record.get("outcome") != "done":
            # shed before admission: its wait still belongs in the
            # queue-wait distribution the SLO shed estimator reads
            self.hists["queue_wait"].add(record.get("queue_wait_s") or 0.0)
        return tr

    def stage_tr(self, tr: Dict[str, Any], req, name: str,
                 end_s: float) -> None:
        """stage() against an already-popped trace dict."""
        self._live[req.rid] = tr
        self.stage(req, name, end_s)
        self._live.pop(req.rid, None)

    # -------------------------------------------------------------- queries
    def get(self, rid: int) -> Optional[Dict[str, Any]]:
        """Live query: an in-flight or recently finished request's trace."""
        if rid in self._live:
            return self._live[rid]
        for tr in reversed(self.ring):
            if tr["rid"] == rid:
                return tr
        return None

    def min_accounted_frac(self) -> Optional[float]:
        fracs = [tr["accounted_frac"] for tr in self.ring
                 if tr.get("wall_s", 0.0) > 0.0]
        return min(fracs) if fracs else None

    def emit_hists(self) -> None:
        """Publish every histogram into the telemetry stream (one
        `serve/hist` event per metric). monitor.gather MERGES these
        across segments/processes — fixed edges make that exact."""
        if not tel.enabled():
            return
        for metric, h in self.hists.items():
            if h.count:
                tel.event("serve/hist", cat="serve", metric=metric,
                          **h.snapshot())
