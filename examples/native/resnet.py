"""ResNet-50 on synthetic ImageNet-shaped data (BASELINE config #2;
reference analog: examples/python/native/resnet.py).

    python -m flexflow_tpu -b 16 -e 1 examples/native/resnet.py
"""

import numpy as np

from flexflow_tpu import FFModel, SGDOptimizer, get_launch_config
from flexflow_tpu.models import build_resnet50


def main():
    cfg = get_launch_config()
    batch = cfg.batch_size
    in_hw = 64  # CPU-friendly default; pass -b and edit for full 224
    model = FFModel(cfg)
    build_resnet50(model, batch=batch, in_hw=in_hw, classes=100)
    model.compile(SGDOptimizer(lr=cfg.learning_rate),
                  loss_type="sparse_categorical_crossentropy",
                  metrics=["accuracy"])
    rng = np.random.default_rng(0)
    n = batch * 4
    x = rng.normal(size=(n, 3, in_hw, in_hw)).astype(np.float32)
    y = rng.integers(0, 100, size=(n,)).astype(np.int32)
    hist = model.fit(x, y, epochs=cfg.epochs, verbose=True)
    print(f"FINAL loss={hist[-1]['loss']:.4f}")


if __name__ == "__main__":
    main()
