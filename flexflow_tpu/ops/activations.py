"""Fused-activation helper shared by linear/conv/pool lowering paths.

Reference analog: the ActiMode argument on dense/conv ops
(include/flexflow/ffconst.h AC_MODE_*), executed fused in the cuDNN/cuBLAS
epilogue; here XLA fuses the jnp call into the matmul/conv automatically.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


_ACTS = {
    None: lambda x: x,
    "none": lambda x: x,
    "relu": jax.nn.relu,
    "sigmoid": jax.nn.sigmoid,
    "tanh": jnp.tanh,
    "gelu": jax.nn.gelu,
    "elu": jax.nn.elu,
    "silu": jax.nn.silu,
    "softmax": jax.nn.softmax,
}


def apply_activation(name, x):
    if callable(name):
        return name(x)
    return _ACTS[name](x)
