"""AlexNet (reference: examples/cpp/AlexNet/alexnet.cc:36-60, bootcamp_demo
keras CNN). CIFAR-10 variant by default (config #1 of BASELINE.md)."""

from __future__ import annotations

from flexflow_tpu.core.model import FFModel


def build_alexnet(model: FFModel, batch: int = 64, in_hw: int = 224,
                  channels: int = 3, classes: int = 1000):
    x = model.create_tensor([batch, channels, in_hw, in_hw], name="image")
    t = model.conv2d(x, 64, 11, 11, 4, 4, 2, 2, activation="relu", name="conv1")
    t = model.pool2d(t, 3, 3, 2, 2, name="pool1")
    t = model.conv2d(t, 192, 5, 5, 1, 1, 2, 2, activation="relu", name="conv2")
    t = model.pool2d(t, 3, 3, 2, 2, name="pool2")
    t = model.conv2d(t, 384, 3, 3, 1, 1, 1, 1, activation="relu", name="conv3")
    t = model.conv2d(t, 256, 3, 3, 1, 1, 1, 1, activation="relu", name="conv4")
    t = model.conv2d(t, 256, 3, 3, 1, 1, 1, 1, activation="relu", name="conv5")
    t = model.pool2d(t, 3, 3, 2, 2, name="pool5")
    t = model.flat(t)
    t = model.dense(t, 4096, activation="relu", name="fc6")
    t = model.dropout(t, 0.5)
    t = model.dense(t, 4096, activation="relu", name="fc7")
    t = model.dropout(t, 0.5)
    out = model.dense(t, classes, name="fc8")
    return x, out


def build_alexnet_cifar10(model: FFModel, batch: int = 64):
    """The bootcamp CIFAR-10 CNN (reference: bootcamp_demo/keras_cnn_cifar10.py)."""
    x = model.create_tensor([batch, 3, 32, 32], name="image")
    t = model.conv2d(x, 32, 3, 3, 1, 1, 1, 1, activation="relu", name="conv1")
    t = model.conv2d(t, 32, 3, 3, 1, 1, 1, 1, activation="relu", name="conv2")
    t = model.pool2d(t, 2, 2, 2, 2, name="pool1")
    t = model.conv2d(t, 64, 3, 3, 1, 1, 1, 1, activation="relu", name="conv3")
    t = model.conv2d(t, 64, 3, 3, 1, 1, 1, 1, activation="relu", name="conv4")
    t = model.pool2d(t, 2, 2, 2, 2, name="pool2")
    t = model.flat(t)
    t = model.dense(t, 512, activation="relu", name="fc1")
    out = model.dense(t, 10, name="fc2")
    return x, out
