"""Unit tier: graph algorithms + shape inference (reference: tests/unit/*.cc)."""

import numpy as np
import pytest

from flexflow_tpu import DataType, FFModel
from flexflow_tpu.core.graph import dominators, post_dominators, to_dot, topo_order


def build_diamond():
    m = FFModel()
    x = m.create_tensor([8, 16], name="x")
    a = m.dense(x, 32, activation="relu", name="a")
    b = m.dense(a, 32, name="b")
    c = m.dense(a, 32, name="c")
    d = m.add(b, c, name="d")
    out = m.dense(d, 10, name="out")
    return m, out


def test_topo_order():
    m, _ = build_diamond()
    order = topo_order(m.layers)
    pos = {l.name: i for i, l in enumerate(order)}
    assert pos["a"] < pos["b"] and pos["a"] < pos["c"]
    assert pos["b"] < pos["d"] and pos["c"] < pos["d"] < pos["out"]


def test_dominators():
    m, _ = build_diamond()
    dom = dominators(m.layers)
    byname = {l.name: l for l in m.layers}
    # 'a' dominates the join 'd'; neither branch does
    assert byname["a"] in dom[byname["d"]]
    assert byname["b"] not in dom[byname["d"]]
    pdom = post_dominators(m.layers)
    assert byname["d"] in pdom[byname["a"]]


def test_shape_inference_dense_conv():
    m = FFModel()
    x = m.create_tensor([4, 3, 32, 32])
    c = m.conv2d(x, 16, 5, 5, 1, 1, 2, 2, activation="relu")
    assert c.shape == (4, 16, 32, 32)
    p = m.pool2d(c, 2, 2, 2, 2)
    assert p.shape == (4, 16, 16, 16)
    f = m.flat(p)
    assert f.shape == (4, 16 * 16 * 16)
    d = m.dense(f, 10)
    assert d.shape == (4, 10)
    lyr = d.owner
    assert lyr.weight_specs["kernel"].shape == (4096, 10)


def test_shape_inference_misc():
    m = FFModel()
    x = m.create_tensor([4, 8, 16])
    t = m.transpose(x, [0, 2, 1])
    assert t.shape == (4, 16, 8)
    r = m.reshape(x, [4, -1])
    assert r.shape == (4, 128)
    parts = m.split(x, 2, axis=1)
    assert len(parts) == 2 and parts[0].shape == (4, 4, 16)
    cc = m.concat(parts, axis=1)
    assert cc.shape == (4, 8, 16)
    s = m.softmax(x)
    assert s.shape == x.shape
    vals, idx = m.top_k(x, 4)
    assert vals.shape == (4, 8, 4) and idx.dtype == DataType.INT32
    e = m.create_tensor([4, 6], DataType.INT32)
    emb = m.embedding(e, 100, 32, aggr="sum")
    assert emb.shape == (4, 32)
    emb2 = m.embedding(e, 100, 32, aggr="none")
    assert emb2.shape == (4, 6, 32)


def test_mha_shapes():
    m = FFModel()
    q = m.create_tensor([2, 10, 64])
    out = m.multihead_attention(q, q, q, 64, 8)
    assert out.shape == (2, 10, 64)
    lyr = out.owner
    assert lyr.weight_specs["wq"].shape == (64, 64)


def test_moe_shapes():
    m = FFModel()
    x = m.create_tensor([32, 16])
    y = m.moe(x, num_exp=4, num_select=2, expert_hidden_size=16, alpha=2.0)
    assert y.shape == (32, 16)


def test_dot_export():
    m, _ = build_diamond()
    dot = to_dot(m.layers)
    assert "digraph" in dot and "->" in dot


def test_reshape_errors():
    m = FFModel()
    x = m.create_tensor([4, 8])
    with pytest.raises(ValueError):
        m.reshape(x, [5, 7])


def test_seq_length_truncates_batch_matmul(devices):
    """FFIterationConfig.seq_length analog (reference config.h:162-167 +
    batch_matmul a/b_seq_length_dim, model.h:481-485): the configured
    truncation reaches the lowering."""
    import numpy as np

    from flexflow_tpu import FFConfig, FFModel

    def build(seq_length):
        cfg = FFConfig(batch_size=2, only_data_parallel=True,
                       seq_length=seq_length)
        m = FFModel(cfg)
        a = m.create_tensor([2, 8, 4], name="a")
        b = m.create_tensor([2, 4, 8], name="b")
        m.batch_matmul(a, b, a_seq_length_dim=1, name="bmm")
        cm = m.compile(loss_type="identity", metrics=[])
        cm.init(seed=0)
        return cm

    rng = np.random.default_rng(0)
    av = rng.normal(size=(2, 8, 4)).astype(np.float32)
    bv = rng.normal(size=(2, 4, 8)).astype(np.float32)
    full = np.asarray(build(0).forward(av, bv))
    trunc = np.asarray(build(3).forward(av, bv))
    assert full.shape == (2, 8, 8)
    assert trunc.shape == (2, 3, 8)
    np.testing.assert_allclose(trunc, full[:, :3], rtol=1e-6)
