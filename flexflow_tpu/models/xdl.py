"""XDL — ads-CTR model (reference workload: examples/cpp/XDL/xdl.cc; an
OSDI'22 Unity benchmark, scripts/osdi22ae/xdl.sh): a bank of large
embedding tables (1M entries x 64) + a dense feature MLP, concatenated into
a top MLP with a 2-way head. Like DLRM, the tables are the
attribute-parallel stress case."""

from __future__ import annotations

from typing import List, Sequence, Tuple

from flexflow_tpu.core.model import FFModel
from flexflow_tpu.dtype import DataType


def build_xdl(model: FFModel, batch: int = 64,
              embedding_size: Sequence[int] = (1_000_000,) * 4,
              sparse_feature_size: int = 64,
              embedding_bag_size: int = 1,
              dense_dim: int = 64,
              mlp_top: Sequence[int] = (256, 256, 256, 2)) -> Tuple[List, object]:
    inputs = []
    embs = []
    for ti, entries in enumerate(embedding_size):
        ids = model.create_tensor([batch, embedding_bag_size], DataType.INT32,
                                  name=f"xdl_sparse_{ti}")
        inputs.append(ids)
        embs.append(model.embedding(ids, entries, sparse_feature_size,
                                    aggr="sum", name=f"xdl_emb_{ti}"))
    dense = model.create_tensor([batch, dense_dim], name="xdl_dense")
    inputs.append(dense)
    t = model.concat(embs + [dense], axis=-1, name="xdl_concat")
    for li, h in enumerate(mlp_top[:-1]):
        t = model.dense(t, h, activation="relu", name=f"xdl_top_{li}")
    out = model.dense(t, mlp_top[-1], name="xdl_head")
    return inputs, out
