"""Conv2D, Pool2D, Flat, BatchNorm — NCHW, matching the reference API.

Reference analog: src/ops/conv_2d.cc (1198 LoC, cuDNN), pool_2d.cc (688),
flat.cc (412), batch_norm.cc (322). Shapes follow the reference (NCHW,
OIHW kernels); XLA relayouts internally for the TPU MXU/VPU, so the API keeps
reference semantics without a layout cost at runtime.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from typing import TYPE_CHECKING
if TYPE_CHECKING:
    from flexflow_tpu.core.layer import Layer
from flexflow_tpu.core.tensor import TensorSpec
from flexflow_tpu.ops.op_type import OperatorType
from flexflow_tpu.ops.registry import register_op, LoweringCtx
from flexflow_tpu.ops.activations import apply_activation


def _out_hw(h, w, p):
    kh, kw = p["kernel_h"], p["kernel_w"]
    sh, sw = p["stride_h"], p["stride_w"]
    ph, pw = p["padding_h"], p["padding_w"]
    oh = (h + 2 * ph - kh) // sh + 1
    ow = (w + 2 * pw - kw) // sw + 1
    if oh <= 0 or ow <= 0:
        raise ValueError(f"conv/pool output collapsed: {(oh, ow)}")
    return oh, ow


def _conv2d_infer(layer: Layer):
    x = layer.inputs[0].spec  # (N, C, H, W)
    p = layer.params
    n, c, h, w = x.shape
    groups = p.get("groups", 1)
    assert c % groups == 0
    oc = p["out_channels"]
    oh, ow = _out_hw(h, w, p)
    layer.weight_specs = {"kernel": TensorSpec((oc, c // groups, p["kernel_h"], p["kernel_w"]), x.dtype)}
    if p.get("use_bias", True):
        layer.weight_specs["bias"] = TensorSpec((oc,), x.dtype)
    return [x.with_shape((n, oc, oh, ow))]


def _conv2d_lower(layer: Layer, inputs, weights, ctx: LoweringCtx):
    x = inputs[0]
    p = layer.params
    y = lax.conv_general_dilated(
        x,
        weights["kernel"].astype(x.dtype),
        window_strides=(p["stride_h"], p["stride_w"]),
        padding=[(p["padding_h"], p["padding_h"]), (p["padding_w"], p["padding_w"])],
        dimension_numbers=("NCHW", "OIHW", "NCHW"),
        feature_group_count=p.get("groups", 1),
    )
    if "bias" in weights:
        y = y + weights["bias"].astype(y.dtype)[None, :, None, None]
    return [apply_activation(p.get("activation"), y)]


def _conv2d_flops(layer: Layer):
    o = layer.outputs[0].spec  # N, OC, OH, OW
    p = layer.params
    cin_per_group = layer.inputs[0].spec.shape[1] // p.get("groups", 1)
    return 2.0 * o.num_elements * cin_per_group * p["kernel_h"] * p["kernel_w"]


register_op(OperatorType.CONV2D, _conv2d_infer, _conv2d_lower, _conv2d_flops)


def _pool2d_infer(layer: Layer):
    x = layer.inputs[0].spec
    n, c, h, w = x.shape
    oh, ow = _out_hw(h, w, layer.params)
    return [x.with_shape((n, c, oh, ow))]


def _pool2d_lower(layer: Layer, inputs, weights, ctx):
    x = inputs[0]
    p = layer.params
    window = (1, 1, p["kernel_h"], p["kernel_w"])
    strides = (1, 1, p["stride_h"], p["stride_w"])
    pads = ((0, 0), (0, 0), (p["padding_h"], p["padding_h"]), (p["padding_w"], p["padding_w"]))
    if p.get("pool_type", "max") == "max":
        init = -jnp.inf if jnp.issubdtype(x.dtype, jnp.floating) else jnp.iinfo(x.dtype).min
        y = lax.reduce_window(x, init, lax.max, window, strides, pads)
    else:
        s = lax.reduce_window(x, 0.0, lax.add, window, strides, pads)
        # count_include_pad=True matches the reference's cuDNN default
        y = s / (p["kernel_h"] * p["kernel_w"])
    return [apply_activation(p.get("activation"), y)]


register_op(OperatorType.POOL2D, _pool2d_infer, _pool2d_lower)


def _flat_infer(layer: Layer):
    x = layer.inputs[0].spec
    n = x.shape[0]
    rest = 1
    for d in x.shape[1:]:
        rest *= d
    return [x.with_shape((n, rest))]


register_op(
    OperatorType.FLAT,
    _flat_infer,
    lambda l, i, w, c: [i[0].reshape(i[0].shape[0], -1)],
)


def _bn_infer(layer: Layer):
    x = layer.inputs[0].spec  # NCHW (or NC for 2-d input)
    c = x.shape[1]
    layer.weight_specs = {
        "gamma": TensorSpec((c,), x.dtype),
        "beta": TensorSpec((c,), x.dtype),
    }
    return [x]


def _bn_lower(layer: Layer, inputs, weights, ctx: LoweringCtx):
    x = inputs[0]
    eps = layer.params.get("eps", 1e-5)
    momentum = layer.params.get("momentum", 0.9)
    axes = tuple(i for i in range(x.ndim) if i != 1)
    bshape = [1] * x.ndim
    bshape[1] = x.shape[1]
    mean_key, var_key = f"{layer.name}/mean", f"{layer.name}/var"
    # statistics + running stats in f32 (cuDNN BN accumulates f32 too);
    # output returns to the activation dtype
    xf = x.astype(jnp.float32)
    if ctx.training:
        mean = jnp.mean(xf, axis=axes)
        var = jnp.var(xf, axis=axes)
        rm = ctx.state.get(mean_key, jnp.zeros_like(mean))
        rv = ctx.state.get(var_key, jnp.ones_like(var))
        ctx.new_state[mean_key] = momentum * rm + (1 - momentum) * mean
        ctx.new_state[var_key] = momentum * rv + (1 - momentum) * var
    else:
        mean = ctx.state.get(mean_key, jnp.zeros((x.shape[1],), jnp.float32))
        var = ctx.state.get(var_key, jnp.ones((x.shape[1],), jnp.float32))
    y = (xf - mean.astype(jnp.float32).reshape(bshape)) * lax.rsqrt(
        var.astype(jnp.float32).reshape(bshape) + eps)
    y = (y * weights["gamma"].astype(jnp.float32).reshape(bshape)
         + weights["beta"].astype(jnp.float32).reshape(bshape))
    if layer.params.get("relu", False):
        y = jax.nn.relu(y)
    return [y.astype(x.dtype)]


register_op(OperatorType.BATCHNORM, _bn_infer, _bn_lower)
