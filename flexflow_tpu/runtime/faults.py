"""Deterministic fault injection — the test harness for every recovery path.

Reference gap (ISSUE 6): the reference leans on Legion's resilient task
runtime; our JAX rebuild has explicit recovery code (runtime/resilience.py
retry/backoff, durable checkpoints, preemption drain) and every one of
those paths must be EXERCISABLE on demand, deterministically, in tests and
in the kill-and-resume smoke (tools/bench_resilience.py). This module is
the switchboard: a `FaultPlan` arms named SITES to raise at chosen
indices, and each instrumented callsite asks `check(site)` before doing
the real work — so an armed fault fires BEFORE any state is mutated
(safe to retry, even under buffer donation).

Sites (the full set is `SITES`; `check` rejects unknown names so a typo'd
plan can't silently arm nothing):

  dataloader/transfer   host->device batch transfer (prefetch worker)
  checkpoint/write      checkpoint serialization (sync or writer thread)
  fit/dispatch          train-step dispatch admission (index = global step)
  distributed/init      jax.distributed initialization
  pipe/boundary_hop     pipeline stage-boundary activation transfer
  health/nonfinite      NaN-poison the parameters before a step (index =
                        1-based global step) — exercises the numerics
                        sentinels in flexflow_tpu/health.py. This site is
                        NON-RAISING: the fit loops query `poison()` and
                        corrupt the params themselves, modeling a silent
                        numerics blow-up rather than a thrown error.
  serve/prefill         serving prefill dispatch (one index per admission
                        batch) — a permanent fault fails the batch being
                        admitted, never the engine
  serve/decode_step     serving decode-step dispatch — a permanent fault
                        makes the scheduler evict the wedged slot and
                        keep serving the rest
  serve/kv_admit        KV-cache page allocation at admission (one index
                        per request) — a permanent fault sheds only that
                        request
  serve/param_swap      the hot-swap's durable-snapshot read — a
                        permanent fault aborts the swap; the engine keeps
                        serving the currently active version

Plan grammar (FF_FAULT_PLAN env var or --fault-plan, comma-separated):

  site@N        fail once at index N (1-based)
  site@N*T      fail T consecutive times starting at index N (transient:
                a retrying caller recovers once the T failures are spent)
  site@N!       fail EVERY time from index N on (permanent: retries burn
                their budget and the caller escalates)

The index is the site's own 1-based call count, except `fit/dispatch`
where the caller passes the 1-based global step — "fail step 3" is
`fit/dispatch@3` regardless of how steps batch into dispatches.
"""

from __future__ import annotations

import os
import re
import threading
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from flexflow_tpu import telemetry as tel

SITES = (
    "dataloader/transfer",
    "checkpoint/write",
    "fit/dispatch",
    "distributed/init",
    "pipe/boundary_hop",
    "health/nonfinite",
    "serve/prefill",
    "serve/decode_step",
    "serve/kv_admit",
    "serve/param_swap",
)


class InjectedFault(RuntimeError):
    """A deterministic injected failure (transient unless Permanent)."""


class PermanentInjectedFault(InjectedFault):
    """An injected failure armed to outlast any retry budget."""


@dataclass
class FaultSpec:
    site: str
    at: int = 1            # first 1-based index that fires
    times: int = 1         # consecutive failures (ignored when permanent)
    permanent: bool = False
    fired: int = field(default=0, compare=False)

    def should_fire(self, idx: int) -> bool:
        if idx < self.at:
            return False
        if self.permanent:
            return True
        return self.fired < self.times


_SPEC_RE = re.compile(r"^(?P<site>[\w/._-]+)@(?P<at>\d+)"
                      r"(?:\*(?P<times>\d+))?(?P<perm>!)?$")


def parse_plan(spec: str) -> List[FaultSpec]:
    """Parse the plan grammar; unknown sites and malformed entries raise
    (a fault plan that silently arms nothing would green-light a broken
    recovery path)."""
    out: List[FaultSpec] = []
    for entry in (spec or "").split(","):
        entry = entry.strip()
        if not entry:
            continue
        m = _SPEC_RE.match(entry)
        if m is None:
            raise ValueError(
                f"bad fault spec {entry!r}: expected site@N, site@N*T or "
                f"site@N! (sites: {', '.join(SITES)})")
        site = m.group("site")
        if site not in SITES:
            raise ValueError(f"unknown fault site {site!r} in {entry!r}; "
                             f"sites: {', '.join(SITES)}")
        out.append(FaultSpec(site=site, at=int(m.group("at")),
                             times=int(m.group("times") or 1),
                             permanent=bool(m.group("perm"))))
    return out


_LOCK = threading.Lock()
_SPECS: List[FaultSpec] = []
_COUNTS: Dict[str, int] = {}
_FIRED: Dict[str, int] = {}

# FF_FAULT_PLAN at import: subprocess harnesses (bench_resilience --check,
# the SIGTERM/SIGKILL smokes) arm the plan via the environment before the
# worker imports anything
if os.environ.get("FF_FAULT_PLAN"):
    _SPECS = parse_plan(os.environ["FF_FAULT_PLAN"])


def configure(spec) -> None:
    """Arm a plan: a grammar string, a list of FaultSpec, or falsy (leave
    the current plan untouched, mirroring telemetry.configure)."""
    global _SPECS
    if not spec:
        return
    specs = parse_plan(spec) if isinstance(spec, str) else list(spec)
    with _LOCK:
        _SPECS = specs
        _COUNTS.clear()
        _FIRED.clear()


def clear() -> None:
    global _SPECS
    with _LOCK:
        _SPECS = []
        _COUNTS.clear()
        _FIRED.clear()


def active() -> bool:
    """One cheap read — hot loops guard their check() call on this."""
    return bool(_SPECS)


def counts() -> Dict[str, int]:
    """Per-site OPERATION counts (test observability) — retries of one
    operation re-check the same index, so they don't advance this."""
    with _LOCK:
        return dict(_COUNTS)


def next_index(site: str) -> int:
    """Allocate the next 1-based index for one REAL operation at `site`.
    run_resilient calls this once per invocation and re-checks the same
    index on every retry attempt — otherwise a retry would advance the
    counter and shift where a later spec on the same site fires (a plan
    author counts operations, not attempts)."""
    if site not in SITES:
        raise ValueError(f"unknown fault site {site!r}")
    with _LOCK:
        _COUNTS[site] = _COUNTS.get(site, 0) + 1
        return _COUNTS[site]


def fired() -> Dict[str, int]:
    """Per-site injected-failure counts (test observability)."""
    with _LOCK:
        return dict(_FIRED)


def check(site: str, index: Optional[int] = None) -> None:
    """Raise the armed fault for `site`, if any. Called BEFORE the real
    work at every instrumented site, so a fired fault never leaves partial
    state behind. `index` is the operation's index — run_resilient
    allocates it via next_index once per operation (or passes the 1-based
    global step for fit/dispatch) and re-checks the SAME index on
    retries; a bare check() allocates its own."""
    if site not in SITES:
        raise ValueError(f"unknown fault site {site!r}")
    if not _SPECS:
        return
    idx = next_index(site) if index is None else int(index)
    with _LOCK:
        for spec in _SPECS:
            if spec.site == site and spec.should_fire(idx):
                spec.fired += 1
                _FIRED[site] = _FIRED.get(site, 0) + 1
                permanent = spec.permanent
                break
        else:
            return
    tel.event("fault/injected", cat="fault", site=site, index=idx,
              permanent=permanent)
    cls = PermanentInjectedFault if permanent else InjectedFault
    raise cls(f"injected fault at {site} (index {idx}"
              + (", permanent)" if permanent else ")"))


def poison(site: str, index: Optional[int] = None) -> bool:
    """Non-raising variant of check(): True when the armed fault for
    `site` fires at `index`. Used by sites that model SILENT corruption
    (health/nonfinite — the fit loop NaN-poisons the params and keeps
    going so the numerics sentinel, not an exception, must catch it).
    Emits the same fault/injected telemetry event as check()."""
    if site not in SITES:
        raise ValueError(f"unknown fault site {site!r}")
    if not _SPECS:
        return False
    idx = next_index(site) if index is None else int(index)
    with _LOCK:
        for spec in _SPECS:
            if spec.site == site and spec.should_fire(idx):
                spec.fired += 1
                _FIRED[site] = _FIRED.get(site, 0) + 1
                permanent = spec.permanent
                break
        else:
            return False
    tel.event("fault/injected", cat="fault", site=site, index=idx,
              permanent=permanent, poison=True)
    return True
