"""Keras frontend tests (reference analog: examples/python/keras smoke runs,
tests/python_interface_test.sh). BASELINE config #1 done-criterion: the
func_cifar10_alexnet-equivalent script runs end-to-end."""

import numpy as np
import pytest

import flexflow_tpu.keras.optimizers as opt
from flexflow_tpu.keras.callbacks import EpochVerifyMetrics
from flexflow_tpu.keras.datasets import cifar10
from flexflow_tpu.keras.layers import (
    Activation,
    Add,
    BatchNormalization,
    Concatenate,
    Conv2D,
    Dense,
    Dropout,
    Flatten,
    Input,
    MaxPooling2D,
    concatenate,
)
from flexflow_tpu.keras.models import Model, Sequential


def test_functional_cnn_trains():
    (x_train, y_train), _ = cifar10.load_data(128)
    x = (x_train / 255.0).astype(np.float32)
    y = y_train.astype(np.int32).reshape(-1)
    inp = Input(shape=(3, 32, 32))
    t = Conv2D(16, (5, 5), padding=(2, 2), activation="relu")(inp)
    t = MaxPooling2D((2, 2), (2, 2))(t)
    t = Flatten()(t)
    t = Dense(32, activation="relu")(t)
    out = Activation("softmax")(Dense(10)(t))
    m = Model(inp, out)
    m.compile(optimizer=opt.SGD(learning_rate=0.05),
              loss="sparse_categorical_crossentropy", metrics=["accuracy"])
    hist = m.fit(x, y, batch_size=32, epochs=2, verbose=False)
    assert np.isfinite(hist[-1]["loss"])
    assert m.predict(x[:32]).shape == (32, 10)
    ev = m.evaluate(x, y)
    assert "accuracy" in ev


@pytest.mark.slow  # ~43s: full AlexNet example; the functional-CNN and
# sequential tests cover the keras frontend in tier-1
def test_alexnet_example_builds_and_runs():
    """The BASELINE #1 script at reduced sample count."""
    import importlib.util
    import os

    spec = importlib.util.spec_from_file_location(
        "alexnet_example",
        os.path.join(os.path.dirname(__file__), os.pardir, "examples",
                     "keras", "func_cifar10_alexnet.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    model = mod.build_alexnet()
    (x_train, y_train), _ = cifar10.load_data(32)
    x = mod.upsample_nearest(x_train, 229) / 255.0
    y = y_train.astype(np.int32).reshape(-1)
    model.compile(optimizer=opt.SGD(learning_rate=0.01),
                  loss="sparse_categorical_crossentropy", metrics=["accuracy"])
    hist = model.fit(x, y, batch_size=16, epochs=1, verbose=False,
                     callbacks=[EpochVerifyMetrics(0.0)])
    assert np.isfinite(hist[-1]["loss"])


def test_sequential_and_merges():
    rng = np.random.default_rng(0)
    xs = rng.normal(size=(64, 16)).astype(np.float32)
    ys = (xs.sum(1) > 0).astype(np.int32)

    sm = Sequential([Dense(32, activation="relu", input_shape=(16,)),
                     Dropout(0.1), Dense(2)])
    sm.compile(optimizer="adam", loss="sparse_categorical_crossentropy",
               metrics=["accuracy"])
    hist = sm.fit(xs, ys, batch_size=32, epochs=2, verbose=False)
    assert np.isfinite(hist[-1]["loss"])

    # functional with merges (concat + residual add)
    inp = Input(shape=(16,))
    a = Dense(16, activation="relu")(inp)
    b = Dense(16, activation="relu")(inp)
    c = concatenate([a, b], axis=-1)
    d = Dense(16)(c)
    e = Add()([d, a])
    out = Dense(2)(e)
    m = Model(inp, out)
    m.compile(optimizer=opt.Adam(learning_rate=1e-3),
              loss="sparse_categorical_crossentropy", metrics=["accuracy"])
    hist = m.fit(xs, ys, batch_size=32, epochs=2, verbose=False)
    assert np.isfinite(hist[-1]["loss"])


def test_pad_sequences_semantics():
    from flexflow_tpu.keras.preprocessing import pad_sequences

    seqs = [[1, 2, 3], [4, 5], [6]]
    # keras defaults: pre-pad, pre-truncate
    np.testing.assert_array_equal(
        pad_sequences(seqs),
        [[1, 2, 3], [0, 4, 5], [0, 0, 6]])
    np.testing.assert_array_equal(
        pad_sequences(seqs, maxlen=2),
        [[2, 3], [4, 5], [0, 6]])
    np.testing.assert_array_equal(
        pad_sequences(seqs, maxlen=2, truncating="post", padding="post"),
        [[1, 2], [4, 5], [6, 0]])
    assert pad_sequences(seqs, maxlen=4, value=9)[0][0] == 9


def test_tokenizer_matrix_modes():
    from flexflow_tpu.keras.preprocessing.text import (
        Tokenizer, text_to_word_sequence, tokenizer_from_json)

    assert text_to_word_sequence("Hello, TPU world! hello") == \
        ["hello", "tpu", "world", "hello"]
    tk = Tokenizer(num_words=10)
    tk.fit_on_texts(["the cat sat", "the cat ran", "the dog"])
    seqs = tk.texts_to_sequences(["the cat", "the dog dog"])
    assert tk.word_index["the"] == 1 and tk.word_index["cat"] == 2
    m = tk.sequences_to_matrix(seqs, mode="binary")
    assert m.shape == (2, 10)
    assert m[0, 1] == 1 and m[0, 2] == 1 and m[0, 3] == 0
    mc = tk.sequences_to_matrix(seqs, mode="count")
    assert mc[1].max() == 2  # "dog dog"
    # round-trip
    tk2 = tokenizer_from_json(tk.to_json())
    np.testing.assert_array_equal(
        tk2.sequences_to_matrix(seqs, mode="binary"), m)


def test_reuters_mlp_pipeline_trains(devices):
    """The reference's seq_reuters_mlp example pipeline
    (examples/python/keras/seq_reuters_mlp.py): reuters -> Tokenizer
    binary matrix -> Dense MLP with an L2-regularized hidden layer;
    accuracy must beat chance on the learnable synthetic corpus."""
    from flexflow_tpu.keras import regularizers
    from flexflow_tpu.keras.datasets import reuters
    from flexflow_tpu.keras.layers import Activation, Dense, Input
    from flexflow_tpu.keras.models import Sequential
    from flexflow_tpu.keras.preprocessing.text import Tokenizer

    max_words = 256
    (x_train, y_train), _ = reuters.load_data(num_words=max_words,
                                              test_split=0.2,
                                              num_samples=640)
    tk = Tokenizer(num_words=max_words)
    x_train = tk.sequences_to_matrix(x_train, mode="binary").astype("float32")
    y_train = np.reshape(np.asarray(y_train, np.int32), (len(y_train), 1))

    model = Sequential()
    model.add(Input(shape=(max_words,)))
    model.add(Dense(128, activation="relu",
                    kernel_regularizer=regularizers.l2(1e-4)))
    model.add(Dense(reuters.classes))
    model.add(Activation("softmax"))
    model.compile(optimizer=opt.Adam(learning_rate=0.01),
                  loss="sparse_categorical_crossentropy",
                  metrics=["accuracy", "sparse_categorical_crossentropy"])
    hist = model.fit(x_train, y_train, batch_size=64, epochs=6, verbose=False)
    acc = hist[-1]["accuracy"]
    assert acc > 3.0 / reuters.classes, hist  # >> 1/46 chance


def test_regularizer_term_applied(devices):
    """L2 regularization must change the training dynamics: with a heavy
    penalty the trained kernel norm shrinks vs the unregularized run, and
    the reported loss includes the penalty term."""
    from flexflow_tpu.keras import regularizers
    from flexflow_tpu.keras.layers import Dense, Input
    from flexflow_tpu.keras.models import Sequential

    rng = np.random.default_rng(0)
    xv = rng.normal(size=(64, 16)).astype(np.float32)
    yv = rng.normal(size=(64, 8)).astype(np.float32)

    def run(reg):
        m = Sequential()
        m.add(Input(shape=(16,)))
        m.add(Dense(8, kernel_regularizer=reg, name="d"))
        m.compile(optimizer=opt.SGD(learning_rate=0.05),
                  loss="mean_squared_error", metrics=[])
        m.fit(xv, yv, batch_size=64, epochs=20, verbose=False)
        ff = m._ffmodel._compiled
        return float(np.linalg.norm(ff.get_weight("d", "kernel")))

    n_plain = run(None)
    n_reg = run(regularizers.l2(0.5))
    assert n_reg < 0.7 * n_plain, (n_plain, n_reg)
