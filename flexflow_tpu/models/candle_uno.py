"""CANDLE Uno — drug-response regression (reference workload:
examples/cpp/candle_uno/candle_uno.cc; an OSDI'22 Unity benchmark,
scripts/osdi22ae/candle_uno.sh).

Structure: per-feature-TYPE towers (several input features share one tower's
weights when they carry the same feature type — dose1/dose2 both run the
"dose" tower), concatenated and fed to a top MLP ending in a single
regression output. The shared towers make it a natural fork-join /
inter-op-placement workload."""

from __future__ import annotations

from typing import Dict, List, Sequence, Tuple

from flexflow_tpu.core.model import FFModel

# reference defaults (candle_uno.cc CandleConfig)
FEATURE_SHAPES: Dict[str, int] = {
    "dose": 1,
    "cell.rnaseq": 942,
    "drug.descriptors": 5270,
    "drug.fingerprints": 2048,
}
INPUT_FEATURES: Dict[str, str] = {
    "dose1": "dose",
    "dose2": "dose",
    "cell.rnaseq": "cell.rnaseq",
    "drug1.descriptors": "drug.descriptors",
    "drug1.fingerprints": "drug.fingerprints",
    "drug2.descriptors": "drug.descriptors",
    "drug2.fingerprints": "drug.fingerprints",
}


def build_candle_uno(model: FFModel, batch: int = 64,
                     dense_layers: Sequence[int] = (4192,) * 4,
                     dense_feature_layers: Sequence[int] = (4192,) * 8,
                     feature_shapes: Dict[str, int] = None,
                     input_features: Dict[str, str] = None) -> Tuple[List, object]:
    feature_shapes = feature_shapes or FEATURE_SHAPES
    input_features = input_features or INPUT_FEATURES
    inputs = []
    towers: List = []
    for name, ftype in input_features.items():
        safe = name.replace(".", "_")
        x = model.create_tensor([batch, feature_shapes[ftype]],
                                name=f"in_{safe}")
        inputs.append(x)
        t = x
        if feature_shapes[ftype] > 1:  # dose skips the feature tower (ref)
            for li, h in enumerate(dense_feature_layers):
                t = model.dense(t, h, activation="relu",
                                name=f"tower_{safe}_{li}")
        towers.append(t)
    t = model.concat(towers, axis=-1, name="concat_features")
    for li, h in enumerate(dense_layers):
        t = model.dense(t, h, activation="relu", name=f"top_{li}")
    out = model.dense(t, 1, name="uno_out")
    return inputs, out
