#!/usr/bin/env python
"""Live run-health monitor: tail a --telemetry-dir into a refreshing
terminal dashboard — goodput bar + bucket breakdown (health/goodput
events from flexflow_tpu/health.py), a step-time sparkline (fit/dispatch
or pipe/update spans), numerics-sentinel status (health/nonfinite,
health/grad_spike, health/loss_spike), HBM watermarks (health/hbm), and
any fault/error events.

Usage:
    python tools/monitor.py <telemetry-dir> [--refresh 2.0] [--once]
                            [--iterations N] [--prom-file node.prom]
    python tools/monitor.py --check     # CI smoke: tiny fit -> dashboard

--prom-file additionally writes a Prometheus textfile-collector export
(atomic rename, so node_exporter never reads a torn file) on every
refresh — the bridge from the local JSONL stream to a real alerting
stack without running a server in the training process.

The monitor is read-only and tail-safe: it re-reads the directory each
refresh (telemetry.read_events merges rotated telemetry-*.jsonl segments
and skips a crashed writer's torn tail), so it can watch a run that is
still writing, already finished, or restarting under the elastic
supervisor.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
from typing import Any, Dict, List, Optional

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

SPARK = "▁▂▃▄▅▆▇█"
STEP_SPAN_NAMES = ("fit/dispatch", "pipe/update")


def load_events(path: str) -> List[Dict[str, Any]]:
    from flexflow_tpu.telemetry import read_events

    return read_events(path)


# ------------------------------------------------------------------- gather
def gather(events: List[Dict[str, Any]]) -> Dict[str, Any]:
    """Fold the raw event stream into the dashboard's state dict (pure —
    tests feed synthetic events)."""
    goodputs: List[Dict[str, Any]] = []
    steps_ms: List[float] = []
    sent = {"nonfinite": 0, "grad_spike": 0, "loss_spike": 0}
    last_nonfinite: Optional[Dict[str, Any]] = None
    hbm: Dict[str, Dict[str, Any]] = {}
    halts: List[Dict[str, Any]] = []
    faults = 0
    errors = 0
    # serving (flexflow_tpu/serving): decode-step span durations, finished
    # requests (tokens + ttft for the panel quantiles), live slot/queue
    # counter samples, and the ts window tokens/s is computed over
    serve = {"decode_ms": [], "done": [], "prefills": 0,
             "active_slots": None, "queue_depth": None,
             "ts_first": None, "ts_last": None,
             # ISSUE 11: hot-swap + degradation stream
             "swap_ms": [], "active_version": None, "rollbacks": 0,
             "shed": 0, "failed": 0, "evicted": 0, "retries": 0,
             # ISSUE 13: speculative decoding + KV quantization stream
             "spec_drafted": 0, "spec_accepted": 0, "spec_accept_ema": None,
             "kv_dtype": None, "spec_tokens": 0,
             # ISSUE 15: streaming latency histograms (serve/hist
             # snapshots — merged across segments/processes by
             # _merged_hists) + the last SLO scoreboard
             "hist_snaps": [], "slo": None,
             # ISSUE 16: tiered KV cache counters (latest sample wins —
             # the scheduler re-emits at every rotation sync point)
             "kv_hot_pages": None, "kv_cold_pages": None,
             "kv_prefetch_hits": 0, "kv_prefetch_stalls": 0, "kv_spills": 0,
             # ISSUE 18: disaggregated fleet — per-replica scoreboard rows
             # (latest serve/fleet_replica per index wins), the fleet-wide
             # summary, and the rolling-rollout action counters
             "fleet_replicas": {}, "fleet": None,
             "fleet_rollout_swaps": 0, "fleet_rollout_rollbacks": 0}
    for ev in events:
        name = ev.get("name", "")
        args = ev.get("args") or {}
        if name.startswith("serve/"):
            serve["ts_first"] = (ev.get("ts") if serve["ts_first"] is None
                                 else serve["ts_first"])
            serve["ts_last"] = ev.get("ts", serve["ts_last"])
        if name == "health/goodput":
            goodputs.append(args)
        elif name in STEP_SPAN_NAMES and ev.get("ph") == "X":
            steps_ms.append(float(ev.get("dur", 0.0)) / 1e3)
        elif name == "serve/decode_step" and ev.get("ph") == "X":
            serve["decode_ms"].append(float(ev.get("dur", 0.0)) / 1e3)
        elif name == "serve/prefill" and ev.get("ph") == "X":
            serve["prefills"] += 1
        elif name == "serve/request_done":
            serve["done"].append(args)
        elif name == "serve/param_swap" and ev.get("ph") == "X":
            serve["swap_ms"].append(float(ev.get("dur", 0.0)) / 1e3)
            if args.get("version") is not None:
                serve["active_version"] = args.get("version")
        elif name == "serve/version":
            # rollbacks counted HERE only: a disk-reload rollback emits
            # both a param_swap span and a version event — one increment
            serve["active_version"] = args.get("version",
                                               serve["active_version"])
            if args.get("rollback"):
                serve["rollbacks"] += 1
        elif name == "serve/request_shed":
            serve["shed"] += 1
        elif name == "serve/request_failed":
            serve["failed"] += 1
        elif name == "serve/slot_evicted":
            serve["evicted"] += 1
        elif name == "retry" and str(args.get("site", "")).startswith("serve/"):
            serve["retries"] += 1
        elif name == "serve/active_slots":
            serve["active_slots"] = args.get("value")
        elif name == "serve/queue_depth":
            serve["queue_depth"] = args.get("value")
        elif name == "serve/spec_drafted_tokens":
            serve["spec_drafted"] = int(args.get("value") or 0)
        elif name == "serve/spec_accepted_tokens":
            serve["spec_accepted"] = int(args.get("value") or 0)
        elif name == "serve/spec_accept_rate":
            serve["spec_accept_ema"] = args.get("value")
        elif name == "serve/engine":
            serve["kv_dtype"] = args.get("kv_dtype", serve["kv_dtype"])
            serve["spec_tokens"] = int(args.get("spec_tokens") or 0)
        elif name == "serve/kv_tier_hot_pages":
            serve["kv_hot_pages"] = int(args.get("value") or 0)
        elif name == "serve/kv_tier_cold_pages":
            serve["kv_cold_pages"] = int(args.get("value") or 0)
        elif name == "serve/kv_prefetch_hits":
            serve["kv_prefetch_hits"] = int(args.get("value") or 0)
        elif name == "serve/kv_prefetch_stalls":
            serve["kv_prefetch_stalls"] = int(args.get("value") or 0)
        elif name == "serve/kv_spills":
            serve["kv_spills"] = int(args.get("value") or 0)
        elif name == "serve/fleet_replica":
            serve["fleet_replicas"][int(args.get("replica") or 0)] = args
        elif name == "serve/fleet":
            serve["fleet"] = args
        elif name == "serve/fleet_rollout":
            if args.get("action") == "rollback":
                serve["fleet_rollout_rollbacks"] += 1
            else:
                serve["fleet_rollout_swaps"] += 1
        elif name == "serve/hist":
            serve["hist_snaps"].append(args)
        elif name == "serve/slo":
            serve["slo"] = args.get("report") or serve["slo"]
        elif name == "health/nonfinite":
            sent["nonfinite"] += 1
            last_nonfinite = args
        elif name == "health/grad_spike":
            sent["grad_spike"] += 1
        elif name == "health/loss_spike":
            sent["loss_spike"] += 1
        elif name == "health/hbm":
            hbm[str(args.get("tag", "?"))] = args
        elif name == "health/halt":
            halts.append(args)
        elif name == "fault/injected":
            faults += 1
        if ev.get("cat") == "error":
            errors += 1
    return {"goodputs": goodputs, "steps_ms": steps_ms,
            "sentinels": sent, "last_nonfinite": last_nonfinite,
            "hbm": hbm, "halts": halts, "faults": faults,
            "errors": errors, "events": len(events), "serve": serve}


# ------------------------------------------------------------------- render
def load_twin(path: Optional[str]) -> Optional[Dict[str, Any]]:
    """Read a tools/twin.py report (--twin-out) for the twin panel;
    tolerant of a missing/partial file (the twin may be re-running)."""
    if not path:
        return None
    try:
        with open(path) as f:
            d = json.load(f)
    except (OSError, ValueError):
        return None
    return d if isinstance(d, dict) and d.get("stats") else None


def _bar(frac: float, width: int = 30) -> str:
    frac = max(0.0, min(1.0, frac))
    n = int(round(frac * width))
    return "[" + "#" * n + "." * (width - n) + "]"


def sparkline(values: List[float], width: int = 48) -> str:
    vals = values[-width:]
    if not vals:
        return "(no steps yet)"
    lo, hi = min(vals), max(vals)
    span = (hi - lo) or 1.0
    return "".join(SPARK[int((v - lo) / span * (len(SPARK) - 1))]
                   for v in vals)


def _pq(xs: List[float], q: float) -> float:
    """Nearest-rank quantile (no numpy dependency in the render path)."""
    s = sorted(xs)
    return s[min(len(s) - 1, int(q * (len(s) - 1) + 0.5))]


def _merged_hists(serve: Dict[str, Any]) -> Dict[str, Any]:
    """Merge every serve/hist snapshot in the stream into one histogram
    per metric (fixed shared buckets make the merge exact across
    segments, processes, and bench legs). Lazy import keeps the pure
    gather path dependency-free for synthetic-stream tests."""
    snaps = serve.get("hist_snaps") or []
    if not snaps:
        return {}
    from flexflow_tpu.serving.reqtrace import StreamingHistogram

    out: Dict[str, Any] = {}
    for s in snaps:
        metric = s.get("metric")
        if not metric:
            continue
        try:
            h = StreamingHistogram.from_snapshot(s)
        except (ValueError, TypeError):
            continue
        if metric in out:
            out[metric].merge(h)
        else:
            out[metric] = h
    return out


def _serve_stats(serve: Dict[str, Any]) -> Optional[Dict[str, Any]]:
    """Fold the gathered serve/* stream into the panel's numbers; None
    when the run has no serving activity (panel stays hidden)."""
    if not (serve["done"] or serve["decode_ms"] or serve["prefills"]
            or serve.get("hist_snaps")):
        return None
    tokens = sum(int(d.get("tokens", 0)) for d in serve["done"])
    span_s = 0.0
    if serve["ts_first"] is not None and serve["ts_last"] is not None:
        span_s = max(0.0, (serve["ts_last"] - serve["ts_first"]) / 1e6)
    ttfts = [float(d["ttft_s"]) for d in serve["done"]
             if d.get("ttft_s") is not None]
    # ISSUE 15: when the stream carries live histograms they are THE
    # source of truth for latency quantiles (bench_serve reads the same
    # histograms, so the two can never disagree); the done-event/span
    # recompute is only the fallback for pre-15 streams
    hists = _merged_hists(serve)
    th, sh = hists.get("ttft"), hists.get("decode_step")
    return {
        "hists": hists,
        "slo": serve.get("slo"),
        "requests_done": len(serve["done"]),
        "tokens": tokens,
        "tokens_per_s": tokens / span_s if span_s > 0 else 0.0,
        "ttft_p50_s": (th.quantile(0.5) if th is not None and th.count
                       else (_pq(ttfts, 0.5) if ttfts else None)),
        "ttft_p99_s": (th.quantile(0.99) if th is not None and th.count
                       else (_pq(ttfts, 0.99) if ttfts else None)),
        "decode_p50_ms": (sh.quantile(0.5) * 1e3
                          if sh is not None and sh.count else
                          (_pq(serve["decode_ms"], 0.5)
                           if serve["decode_ms"] else None)),
        "decode_p99_ms": (sh.quantile(0.99) * 1e3
                          if sh is not None and sh.count else
                          (_pq(serve["decode_ms"], 0.99)
                           if serve["decode_ms"] else None)),
        "active_slots": serve["active_slots"],
        "queue_depth": serve["queue_depth"],
        "shed": serve.get("shed", 0),
        "failed": serve.get("failed", 0),
        "evicted": serve.get("evicted", 0),
        "serve_retries": serve.get("retries", 0),
        "swaps": len(serve.get("swap_ms", [])),
        "swap_p99_ms": (_pq(serve["swap_ms"], 0.99)
                        if serve.get("swap_ms") else None),
        "active_version": serve.get("active_version"),
        "rollbacks": serve.get("rollbacks", 0),
        "spec_drafted": serve.get("spec_drafted", 0),
        "spec_accepted": serve.get("spec_accepted", 0),
        "spec_accept_rate": (
            serve.get("spec_accept_ema") if serve.get("spec_accept_ema")
            is not None else
            (serve.get("spec_accepted", 0) / serve["spec_drafted"]
             if serve.get("spec_drafted") else None)),
        "spec_tokens": serve.get("spec_tokens", 0),
        "kv_dtype": serve.get("kv_dtype"),
        "kv_hot_pages": serve.get("kv_hot_pages"),
        "kv_cold_pages": serve.get("kv_cold_pages"),
        "kv_prefetch_hits": serve.get("kv_prefetch_hits", 0),
        "kv_prefetch_stalls": serve.get("kv_prefetch_stalls", 0),
        "kv_spills": serve.get("kv_spills", 0),
        "kv_prefetch_hit_rate": (
            serve.get("kv_prefetch_hits", 0)
            / (serve.get("kv_prefetch_hits", 0)
               + serve.get("kv_prefetch_stalls", 0))
            if (serve.get("kv_prefetch_hits", 0)
                + serve.get("kv_prefetch_stalls", 0)) else None),
        "fleet": serve.get("fleet"),
        "fleet_replicas": serve.get("fleet_replicas") or {},
        "fleet_rollout_swaps": serve.get("fleet_rollout_swaps", 0),
        "fleet_rollout_rollbacks": serve.get("fleet_rollout_rollbacks", 0),
    }


def render(state: Dict[str, Any]) -> List[str]:
    lines = [f"flexflow_tpu run monitor — {state['events']} events"]
    gps = state["goodputs"]
    if gps:
        last = gps[-1]
        gp = float(last.get("goodput", 0.0))
        lines.append(f"goodput  {_bar(gp)} {100.0 * gp:5.1f}%  "
                     f"(epoch {last.get('epoch')}, "
                     f"wall {float(last.get('wall_s', 0.0)):.2f}s, "
                     f"residual {float(last.get('residual_s', 0.0)):.3f}s)")
        buckets = {k[:-2]: float(v) for k, v in last.items()
                   if k.endswith("_s") and k not in
                   ("wall_s", "residual_s")}
        wall = float(last.get("wall_s", 0.0)) or 1e-12
        parts = " ".join(f"{k}={100.0 * v / wall:.1f}%" for k, v in
                         sorted(buckets.items(), key=lambda kv: -kv[1])
                         if v > 0.0)
        lines.append(f"buckets  {parts or '(none)'}")
        if len(gps) > 1:
            lines.append("epochs   " + " ".join(
                f"{100.0 * float(g.get('goodput', 0.0)):.0f}%"
                for g in gps[-12:]))
    else:
        lines.append("goodput  (no health/goodput events yet — epoch in "
                     "progress or health disabled)")
    steps = state["steps_ms"]
    if steps:
        tail = steps[-48:]
        lines.append(f"steps    {sparkline(steps)}  "
                     f"last={tail[-1]:.1f}ms "
                     f"min={min(tail):.1f} max={max(tail):.1f} "
                     f"(n={len(steps)})")
    sv = _serve_stats(state.get("serve") or
                      {"done": [], "decode_ms": [], "prefills": 0})
    if sv:
        def f(v, fmt):
            return (fmt % v) if v is not None else "-"
        lines.append(
            f"serving  {sv['tokens_per_s']:.1f} tok/s "
            f"({sv['requests_done']} reqs, {sv['tokens']} tokens)  "
            f"ttft p50/p99 {f(sv['ttft_p50_s'], '%.3fs')}/"
            f"{f(sv['ttft_p99_s'], '%.3fs')}  "
            f"step p50/p99 {f(sv['decode_p50_ms'], '%.1fms')}/"
            f"{f(sv['decode_p99_ms'], '%.1fms')}")
        lines.append(
            f"         active_slots={f(sv['active_slots'], '%g')} "
            f"queue={f(sv['queue_depth'], '%g')} "
            f"shed={sv['shed']} failed={sv['failed']} "
            f"evicted={sv['evicted']} retries={sv['serve_retries']}")
        if sv["swaps"] or sv["rollbacks"] or sv["active_version"] is not None:
            lines.append(
                f"         params v{f(sv['active_version'], '%g')}  "
                f"swaps={sv['swaps']} rollbacks={sv['rollbacks']} "
                f"swap p99 {f(sv['swap_p99_ms'], '%.1fms')}")
        if sv["spec_drafted"] or sv["kv_dtype"]:
            rate = sv["spec_accept_rate"]
            lines.append(
                f"         spec K={sv['spec_tokens']} "
                f"drafted={sv['spec_drafted']} "
                f"accepted={sv['spec_accepted']} "
                f"accept_ema={f(rate, '%.2f')}  "
                f"kv_dtype={sv['kv_dtype'] or '-'}")
        if sv["kv_hot_pages"] is not None or sv["kv_spills"]:
            # ISSUE 16: tiered KV cache — occupancy + prefetch efficiency
            lines.append(
                f"kv tier  hot={f(sv['kv_hot_pages'], '%g')} "
                f"cold={f(sv['kv_cold_pages'], '%g')} pages  "
                f"spills={sv['kv_spills']} "
                f"prefetch hit/stall={sv['kv_prefetch_hits']}/"
                f"{sv['kv_prefetch_stalls']} "
                f"(hit rate {f(sv['kv_prefetch_hit_rate'], '%.2f')})")
        slo = sv.get("slo")
        if slo and slo.get("objectives"):
            # ISSUE 15: error-budget scoreboard — one compact line per
            # objective (budget left + the fastest-window burn rate)
            for name, ob in sorted(slo["objectives"].items()):
                burns = {k: v for k, v in ob.items()
                         if k.startswith("burn_rate_")}
                burn_txt = " ".join(
                    f"{k[len('burn_rate_'):]}={v:.2f}x"
                    for k, v in sorted(burns.items()))
                lines.append(
                    f"slo      {name}: budget "
                    f"{100.0 * float(ob.get('budget_remaining', 0.0)):.1f}% "
                    f"left  bad {ob.get('bad', 0)}/{ob.get('total', 0)}  "
                    f"burn {burn_txt or '-'}")
            lines.append(
                f"         requests={slo.get('requests', 0)} "
                f"shed_rate={100.0 * float(slo.get('shed_rate', 0.0)):.1f}% "
                f"worst_burn={float(slo.get('worst_burn_rate', 0.0)):.2f}x")
        fl = sv.get("fleet")
        reps = sv.get("fleet_replicas") or {}
        if fl or reps:
            # ISSUE 18: disaggregated fleet — one summary line + one line
            # per replica (role, throughput, live occupancy, live version)
            if fl:
                lines.append(
                    f"fleet    {fl.get('replicas', len(reps))} replicas "
                    f"({fl.get('topology', '?')})  "
                    f"{float(fl.get('tokens_per_s', 0.0)):.1f} tok/s  "
                    f"done={fl.get('completed', 0)} "
                    f"shed={fl.get('shed', 0)} "
                    f"handoffs={fl.get('handoffs', 0)}  "
                    f"rollout swaps={sv['fleet_rollout_swaps']} "
                    f"rollbacks={sv['fleet_rollout_rollbacks']}")
            for idx in sorted(reps):
                r = reps[idx]
                lines.append(
                    f"         r{idx} [{r.get('role', '?'):>8}] "
                    f"{float(r.get('tokens_per_s', 0.0)):6.1f} tok/s  "
                    f"done={r.get('completed', 0)} "
                    f"assigned={r.get('assigned', 0)} "
                    f"slots={r.get('active_slots', 0)} "
                    f"queue={r.get('queue_depth', 0)} "
                    f"v{r.get('swap_version') if r.get('swap_version') is not None else '-'}")
    tw = state.get("twin")
    if tw:
        # ISSUE 20: capacity-twin panel — what the replayed trace says
        # about this config, plus the burn-driven scaling recommendation
        # and the replicas -> capacity curve from twin bisection
        st = tw.get("stats") or {}
        ttft = ((tw.get("hists") or {}).get("ttft") or {}).get("p99")
        lines.append(
            f"twin     {st.get('replicas', '?')} replicas "
            f"({st.get('topology', '?')}, priced {tw.get('priced_by', '?')})"
            f"  {float(st.get('tokens_per_s', 0.0)):.1f} tok/s"
            + (f"  ttft p99 {ttft:.3f}s" if ttft is not None else ""))
        lines.append(
            f"         replayed {st.get('requests', 0)} reqs: "
            f"done={st.get('completed', 0)} shed={st.get('shed', 0)} "
            f"handoffs={st.get('handoffs', 0)} "
            f"wall {float(st.get('wall_s', 0.0)):.1f}s (virtual)")
        sc = tw.get("scaling") or {}
        if sc.get("action"):
            bud = sc.get("budget_remaining")
            lines.append(
                f"         scaling: {sc['action']}"
                + (f" [{sc.get('objective')}]" if sc.get("objective")
                   else "")
                + (f" budget={100.0 * bud:.1f}%" if bud is not None else "")
                + f" — {sc.get('reason', '')}")
        curve = tw.get("capacity_curve") or []
        if curve:
            lines.append("capacity " + "  ".join(
                f"{c['replicas']}r={float(c['capacity_rps']):.1f}rps"
                for c in curve))
    sent = state["sentinels"]
    bad = sent["nonfinite"] or state["halts"]
    status = "FATAL" if bad else (
        "WARN" if sent["grad_spike"] or sent["loss_spike"] else "OK")
    lines.append(f"numerics {status}: nonfinite={sent['nonfinite']} "
                 f"grad_spikes={sent['grad_spike']} "
                 f"loss_spikes={sent['loss_spike']}")
    if state["last_nonfinite"]:
        lines.append(f"         last nonfinite: {state['last_nonfinite']}")
    for h in state["halts"][-2:]:
        lines.append(f"         HALTED at step {h.get('step')}; recovery "
                     f"checkpoint: {h.get('checkpoint') or '(none)'}")
    mb = 1024 * 1024
    for tag, s in list(state["hbm"].items())[-3:]:
        lines.append(f"hbm      {tag}: peak "
                     f"{float(s.get('peak_bytes', 0)) / mb:.2f}MB/device "
                     f"live {float(s.get('live_bytes', 0)) / mb:.2f}MB "
                     f"({s.get('devices')} devices)")
    if state["faults"] or state["errors"]:
        lines.append(f"faults   injected={state['faults']} "
                     f"error_events={state['errors']}")
    return lines


# --------------------------------------------------------------- prometheus
def prom_export(state: Dict[str, Any], path: str) -> None:
    """Textfile-collector export: write gauges to <path> atomically."""
    g: List[str] = []

    def gauge(name: str, value: float, help_: str) -> None:
        g.append(f"# HELP {name} {help_}")
        g.append(f"# TYPE {name} gauge")
        g.append(f"{name} {value:g}")

    gps = state["goodputs"]
    if gps:
        last = gps[-1]
        gauge("flexflow_goodput_ratio", float(last.get("goodput", 0.0)),
              "Goodput fraction of the last closed epoch")
        gauge("flexflow_goodput_residual_seconds",
              float(last.get("residual_s", 0.0)),
              "Unattributed wall-clock of the last closed epoch")
        gauge("flexflow_epoch_wall_seconds",
              float(last.get("wall_s", 0.0)),
              "Wall-clock of the last closed epoch")
    gauge("flexflow_epochs_total", float(len(gps)),
          "Closed fit epochs observed in the telemetry stream")
    if state["steps_ms"]:
        gauge("flexflow_step_time_seconds",
              state["steps_ms"][-1] / 1e3,
              "Duration of the last observed step dispatch/update span")
    sent = state["sentinels"]
    gauge("flexflow_nonfinite_windows_total", float(sent["nonfinite"]),
          "Sentinel windows with non-finite loss/grad")
    gauge("flexflow_grad_spikes_total", float(sent["grad_spike"]),
          "Grad-norm spike warnings")
    gauge("flexflow_loss_spikes_total", float(sent["loss_spike"]),
          "Loss spike warnings")
    gauge("flexflow_run_halts_total", float(len(state["halts"])),
          "Fatal health halts (health/halt events)")
    peak = max((float(s.get("peak_bytes", 0))
                for s in state["hbm"].values()), default=0.0)
    gauge("flexflow_hbm_peak_bytes", peak,
          "Max per-device peak memory across watermark samples")
    gauge("flexflow_error_events_total", float(state["errors"]),
          "Events in the reserved error category")
    sv = _serve_stats(state.get("serve") or
                      {"done": [], "decode_ms": [], "prefills": 0})
    if sv:
        gauge("flexflow_serve_tokens_per_second", sv["tokens_per_s"],
              "Serving throughput over the telemetry window")
        gauge("flexflow_serve_requests_done_total",
              float(sv["requests_done"]),
              "Completed serving requests in the telemetry stream")
        if sv["ttft_p99_s"] is not None:
            gauge("flexflow_serve_ttft_p99_seconds", sv["ttft_p99_s"],
                  "p99 time-to-first-token of completed requests")
        if sv["decode_p99_ms"] is not None:
            gauge("flexflow_serve_decode_step_p99_seconds",
                  sv["decode_p99_ms"] / 1e3,
                  "p99 decode-step span duration")
        if sv["active_slots"] is not None:
            gauge("flexflow_serve_active_slots",
                  float(sv["active_slots"]),
                  "Occupied decode slots at the last counter sample")
        gauge("flexflow_serve_shed_total", float(sv["shed"]),
              "Requests shed by SLO-aware admission control")
        gauge("flexflow_serve_failed_total", float(sv["failed"]),
              "Requests failed/evicted by faults or watchdog timeouts")
        gauge("flexflow_serve_evictions_total", float(sv["evicted"]),
              "Decode slots force-evicted (wedged or timed out)")
        gauge("flexflow_serve_retries_total", float(sv["serve_retries"]),
              "Transient serve/* faults absorbed by retry")
        gauge("flexflow_serve_swaps_total", float(sv["swaps"]),
              "Live parameter hot-swaps completed")
        gauge("flexflow_serve_rollbacks_total", float(sv["rollbacks"]),
              "Parameter rollbacks to a retained version")
        if sv["active_version"] is not None:
            gauge("flexflow_serve_active_version",
                  float(sv["active_version"]),
                  "Checkpoint step of the live parameter version")
        if sv["swap_p99_ms"] is not None:
            gauge("flexflow_serve_swap_p99_seconds",
                  sv["swap_p99_ms"] / 1e3,
                  "p99 hot-swap latency (read+validate+place+flip)")
        gauge("flexflow_serve_spec_drafted_tokens_total",
              float(sv["spec_drafted"]),
              "Draft tokens proposed by the speculative decoder")
        gauge("flexflow_serve_spec_accepted_tokens_total",
              float(sv["spec_accepted"]),
              "Draft tokens accepted by the target verify pass")
        if sv["spec_accept_rate"] is not None:
            gauge("flexflow_serve_spec_accept_rate",
                  float(sv["spec_accept_rate"]),
                  "EMA of the per-round draft acceptance rate")
        if sv["kv_hot_pages"] is not None or sv["kv_spills"]:
            # ISSUE 16: tiered KV cache gauges
            gauge("flexflow_serve_kv_tier_hot_pages",
                  float(sv["kv_hot_pages"] or 0),
                  "Allocated HBM-tier KV pages (latest sample)")
            gauge("flexflow_serve_kv_tier_cold_pages",
                  float(sv["kv_cold_pages"] or 0),
                  "Allocated host-tier KV pages (latest sample)")
            gauge("flexflow_serve_kv_tier_spills_total",
                  float(sv["kv_spills"]),
                  "Slot spills HBM -> host tier")
            gauge("flexflow_serve_kv_prefetch_stalls_total",
                  float(sv["kv_prefetch_stalls"]),
                  "Slot rejoins whose host->HBM prefetch lacked lead")
            if sv["kv_prefetch_hit_rate"] is not None:
                gauge("flexflow_serve_kv_prefetch_hit_rate",
                      float(sv["kv_prefetch_hit_rate"]),
                      "Prefetch hits / (hits + stalls)")
        if sv["kv_dtype"] is not None:
            # dtype rides as a label on a constant-1 gauge (the textfile
            # collector has no string metrics)
            g.append("# HELP flexflow_serve_kv_cache_dtype_info "
                     "KV-cache storage dtype of the serving engine")
            g.append("# TYPE flexflow_serve_kv_cache_dtype_info gauge")
            g.append('flexflow_serve_kv_cache_dtype_info{dtype="%s"} 1'
                     % sv["kv_dtype"])
        # ISSUE 15: live latency histograms as real Prometheus histogram
        # series (cumulative le buckets, mergeable across scrapes)
        _HIST_HELP = {
            "ttft": "Time to first token of admitted requests",
            "per_token": "Steady-state inter-token latency of completed "
                         "requests",
            "queue_wait": "Queue wait before admission (or until shed)",
            "prefill": "Chunked-prefill wave latency per admission",
            "decode_step": "Per-token decode/verify step latency",
        }
        for metric, h in sorted((sv.get("hists") or {}).items()):
            g.extend(h.prom_lines(
                f"flexflow_serve_{metric}_seconds",
                _HIST_HELP.get(metric, f"Serving {metric} latency")))
        slo = sv.get("slo")
        if slo and slo.get("objectives"):
            # per-objective error budgets as labeled gauges
            g.append("# HELP flexflow_serve_slo_budget_remaining "
                     "Remaining SLO error budget fraction per objective")
            g.append("# TYPE flexflow_serve_slo_budget_remaining gauge")
            for name, ob in sorted(slo["objectives"].items()):
                g.append(
                    'flexflow_serve_slo_budget_remaining{objective="%s"} %g'
                    % (name, float(ob.get("budget_remaining", 0.0))))
            g.append("# HELP flexflow_serve_slo_burn_rate "
                     "SLO error-budget burn rate per objective and window")
            g.append("# TYPE flexflow_serve_slo_burn_rate gauge")
            for name, ob in sorted(slo["objectives"].items()):
                for k, v in sorted(ob.items()):
                    if k.startswith("burn_rate_"):
                        g.append(
                            'flexflow_serve_slo_burn_rate{objective="%s",'
                            'window="%s"} %g'
                            % (name, k[len("burn_rate_"):], float(v)))
            gauge("flexflow_serve_slo_shed_rate",
                  float(slo.get("shed_rate", 0.0)),
                  "Fraction of terminal requests that did not complete")
            gauge("flexflow_serve_slo_worst_burn_rate",
                  float(slo.get("worst_burn_rate", 0.0)),
                  "Max burn rate across objectives and windows")
        fl = sv.get("fleet")
        reps = sv.get("fleet_replicas") or {}
        if fl or reps:
            # ISSUE 18: disaggregated fleet — per-replica series carry the
            # replica index (and role) as labels so one scrape covers the
            # whole fleet
            if fl:
                gauge("flexflow_fleet_replicas",
                      float(fl.get("replicas", len(reps))),
                      "Serving replicas in the fleet")
                gauge("flexflow_fleet_tokens_per_second",
                      float(fl.get("tokens_per_s", 0.0)),
                      "Aggregate fleet serving throughput")
                gauge("flexflow_fleet_handoffs_total",
                      float(fl.get("handoffs", 0)),
                      "Prefill->decode KV handoffs across the fleet")
            gauge("flexflow_fleet_rollout_swaps_total",
                  float(sv["fleet_rollout_swaps"]),
                  "Rolling-rollout replica swaps completed")
            gauge("flexflow_fleet_rollout_rollbacks_total",
                  float(sv["fleet_rollout_rollbacks"]),
                  "Rolling-rollout rollbacks (SLO burn during bake)")
            _FLEET_SERIES = [
                ("flexflow_fleet_replica_tokens_per_second", "tokens_per_s",
                 "Per-replica serving throughput"),
                ("flexflow_fleet_replica_completed_total", "completed",
                 "Per-replica completed requests"),
                ("flexflow_fleet_replica_assigned_total", "assigned",
                 "Per-replica requests routed by the fleet router"),
                ("flexflow_fleet_replica_active_slots", "active_slots",
                 "Per-replica occupied decode slots (last sample)"),
                ("flexflow_fleet_replica_queue_depth", "queue_depth",
                 "Per-replica waiting queue depth (last sample)"),
                ("flexflow_fleet_replica_swap_version", "swap_version",
                 "Per-replica live parameter version"),
            ]
            for name, key, help_ in _FLEET_SERIES:
                rows = [(idx, reps[idx]) for idx in sorted(reps)
                        if reps[idx].get(key) is not None]
                if not rows:
                    continue
                g.append(f"# HELP {name} {help_}")
                g.append(f"# TYPE {name} gauge")
                for idx, r in rows:
                    g.append('%s{replica="%d",role="%s"} %g'
                             % (name, idx, r.get("role", "?"),
                                float(r[key])))
    tw = state.get("twin")
    if tw:
        # ISSUE 20: capacity-twin gauges — the twin's replay verdict and
        # scaling recommendation, scrapeable next to the live series
        st = tw.get("stats") or {}
        gauge("flexflow_twin_replicas", float(st.get("replicas", 0)),
              "Replica count of the replayed twin scenario")
        gauge("flexflow_twin_tokens_per_second",
              float(st.get("tokens_per_s", 0.0)),
              "Twin-predicted serving throughput for the replayed trace")
        gauge("flexflow_twin_completed_total",
              float(st.get("completed", 0)),
              "Requests the twin replay completed")
        gauge("flexflow_twin_shed_total", float(st.get("shed", 0)),
              "Requests the twin replay shed")
        ttft = ((tw.get("hists") or {}).get("ttft") or {}).get("p99")
        if ttft is not None:
            gauge("flexflow_twin_ttft_p99_seconds", float(ttft),
                  "Twin-predicted TTFT p99 for the replayed trace")
        sc = tw.get("scaling") or {}
        if sc.get("budget_remaining") is not None:
            gauge("flexflow_twin_budget_remaining",
                  float(sc["budget_remaining"]),
                  "Worst remaining SLO error budget in the twin replay")
        if sc.get("worst_burn_rate") is not None:
            gauge("flexflow_twin_worst_burn_rate",
                  float(sc["worst_burn_rate"]),
                  "Worst SLO burn rate in the twin replay")
        if sc.get("action"):
            g.append("# HELP flexflow_twin_scaling_info Twin scaling "
                     "recommendation (action as label)")
            g.append("# TYPE flexflow_twin_scaling_info gauge")
            g.append('flexflow_twin_scaling_info{action="%s"} 1'
                     % sc["action"])
        curve = tw.get("capacity_curve") or []
        if curve:
            g.append("# HELP flexflow_twin_capacity_rps Max sustainable "
                     "offered load at SLO by twin bisection, per replica "
                     "count")
            g.append("# TYPE flexflow_twin_capacity_rps gauge")
            for c in curve:
                g.append('flexflow_twin_capacity_rps{replicas="%d"} %g'
                         % (int(c["replicas"]), float(c["capacity_rps"])))
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        f.write("\n".join(g) + "\n")
    os.replace(tmp, path)


# --------------------------------------------------------------------- main
def run_once(telemetry_dir: str, prom_file: Optional[str] = None,
             clear: bool = False,
             twin_report: Optional[str] = None) -> Dict[str, Any]:
    state = gather(load_events(telemetry_dir))
    state["twin"] = load_twin(twin_report)
    out = render(state)
    if clear:
        sys.stdout.write("\x1b[2J\x1b[H")
    print("\n".join(out))
    if prom_file:
        prom_export(state, prom_file)
    return state


def _check() -> int:
    """CI smoke: tiny CPU fit with telemetry -> gather/render/prom."""
    import tempfile

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import numpy as np

    from flexflow_tpu import FFConfig, FFModel, SGDOptimizer
    from flexflow_tpu.losses import LossType

    with tempfile.TemporaryDirectory() as td:
        tdir = os.path.join(td, "tel")
        cfg = FFConfig(batch_size=8, epochs=2, seed=0,
                       telemetry_dir=tdir, log_level="warning")
        m = FFModel(cfg)
        t = m.create_tensor([8, 16], name="x")
        m.dense(t, 4, name="head")
        cm = m.compile(SGDOptimizer(lr=0.05),
                       LossType.SPARSE_CATEGORICAL_CROSSENTROPY,
                       metrics=[])
        cm.init(seed=0)
        rng = np.random.default_rng(0)
        x = rng.normal(size=(32, 16)).astype(np.float32)
        y = rng.integers(0, 4, size=(32,)).astype(np.int32)
        cm.fit(x, y, epochs=2, verbose=False)
        from flexflow_tpu import telemetry as tel

        tel.shutdown()
        # ISSUE 20: a twin report feeds the twin panel + gauges
        from flexflow_tpu.serving import tracefmt
        from flexflow_tpu.serving.twin import TwinCosts, TwinSpec, simulate

        trng = np.random.default_rng(0)
        recs = tracefmt.poisson_records(trng, 16, 10.0, 64, 4, 4)
        tspec = TwinSpec(replicas=2, slots=4, seq=16, page_size=4,
                         max_decode_len=4, slo="ttft_p99_ms=500")
        trep = simulate(recs, tspec,
                        TwinCosts.analytic(tspec.kv_spec())).report()
        trep["capacity_curve"] = [{"replicas": 1, "capacity_rps": 10.0},
                                  {"replicas": 2, "capacity_rps": 20.0}]
        twin_path = os.path.join(td, "twin.json")
        with open(twin_path, "w") as f:
            json.dump(trep, f, default=float)
        prom = os.path.join(td, "flexflow.prom")
        state = run_once(tdir, prom_file=prom, twin_report=twin_path)
        ok = (len(state["goodputs"]) == 2
              and state["sentinels"]["nonfinite"] == 0
              and os.path.exists(prom))
        if ok:
            with open(prom) as f:
                text = f.read()
            ok = ("flexflow_goodput_ratio" in text
                  and "flexflow_twin_tokens_per_second" in text
                  and 'flexflow_twin_capacity_rps{replicas="2"}' in text)
    print("CHECK " + ("PASS" if ok else "FAIL"))
    return 0 if ok else 1


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("telemetry_dir", nargs="?",
                    help="telemetry dir (or one .jsonl file) to tail")
    ap.add_argument("--refresh", type=float, default=2.0,
                    help="seconds between dashboard refreshes")
    ap.add_argument("--once", action="store_true",
                    help="render one frame and exit (no screen clearing)")
    ap.add_argument("--iterations", type=int, default=0,
                    help="stop after N refreshes (0 = until Ctrl-C)")
    ap.add_argument("--prom-file", default=None,
                    help="write a Prometheus textfile export here on "
                    "every refresh")
    ap.add_argument("--twin-report", default=None,
                    help="tools/twin.py report JSON (--twin-out) to "
                    "render as the capacity-twin panel + "
                    "flexflow_twin_* gauges (re-read every refresh)")
    ap.add_argument("--json", action="store_true",
                    help="with --once: dump the gathered state as JSON "
                    "instead of the dashboard")
    ap.add_argument("--check", action="store_true",
                    help="CI smoke: tiny fit -> dashboard -> verify")
    args = ap.parse_args(argv)
    if args.check:
        return _check()
    if not args.telemetry_dir:
        ap.error("telemetry_dir is required (or --check)")
    if args.once:
        if args.json:
            state = gather(load_events(args.telemetry_dir))
            state["twin"] = load_twin(args.twin_report)
            if args.prom_file:
                prom_export(state, args.prom_file)
            print(json.dumps(state, indent=2, default=str))
        else:
            run_once(args.telemetry_dir, args.prom_file,
                     twin_report=args.twin_report)
        return 0
    n = 0
    try:
        while True:
            run_once(args.telemetry_dir, args.prom_file, clear=True,
                     twin_report=args.twin_report)
            n += 1
            if args.iterations and n >= args.iterations:
                break
            time.sleep(max(0.1, args.refresh))
    except KeyboardInterrupt:
        pass
    return 0


if __name__ == "__main__":
    sys.exit(main())
