"""Tiered KV cache long-context bench: the ISSUE 16 evidence artifact.

Three legs, all on the 8-device gpt2 CPU twin:

1. **Context capacity at fixed HBM pages** (the headline). Two engines
   with the SAME device KV pool (24 data pages, 4 slots): the HBM-only
   engine caps each sequence at 24 pages / 4 slots = 6 pages -> 24
   positions of context, while the tiered engine (--kv-host-pages moves
   3/4 of the slots' footprint to host) serves 96 positions per sequence
   through spill/prefetch rotation. Both are PROVEN by serving: the long
   trace completes fully on the tiered engine (every request all tokens)
   and is permanently shed by the HBM-only twin (its two-tier capacity
   IS its device pool). Headline: `context_gain_vs_hbm_only` (gates
   >= 4.0 on the full run).

2. **Spill-path parity.** The same short trace through an HBM-only
   engine and a tiered one whose device pool is HALVED: greedy streams
   must be bitwise identical (the tier moves committed pages; it never
   touches numerics), the run must really spill, and the prefetch
   hit/stall ledger must cover every rejoin. Reports
   `prefetch_hit_rate` (hits / rejoins — stalls are counted, never
   silent).

3. **Ring-vs-flash prefill crossover.** The serving prefill search must
   route a 16k-token prompt to the sequence-parallel ring candidate
   (priced with its forward-only comm) and keep a 512-token prompt on
   flash — the crossover comes out of the DP's pricing, not a hardcoded
   rule.

  python tools/bench_longctx.py                     # full run, gates on
  python tools/bench_longctx.py --out BENCH_longctx.json
  python tools/bench_longctx.py --check             # CI smoke: smaller
      host tier (2x context), capacity gate skipped, parity + ledger +
      crossover still asserted
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                           + " --xla_force_host_platform_device_count=8")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

MESH = {"data": 2, "model": 4}
SLOTS, PAGE = 4, 4


def _build_engine(gc_seq, max_new, host_pages, slots=SLOTS):
    from flexflow_tpu import FFConfig, FFModel
    from flexflow_tpu.models import GPT2Config, build_gpt2
    from flexflow_tpu.serving import compile_serving

    cfg = FFConfig(search_budget=16, mesh_shape=dict(MESH),
                   max_batch_slots=slots, kv_page_size=PAGE,
                   max_decode_len=max_new, log_level="warning",
                   kv_host_pages=host_pages, kv_prefetch_ahead=2,
                   strategy_cache=False)
    m = FFModel(cfg)
    gc = GPT2Config(vocab=256, seq=gc_seq, d_model=64, heads=4, layers=1,
                    dropout=0.0)
    build_gpt2(m, gc, batch=8)
    eng = compile_serving(m)
    eng.init(seed=0)
    return eng


def _serve(eng, n, prompt_len, max_new):
    from flexflow_tpu.serving import (ContinuousBatchingScheduler, Request,
                                      gpt2_prompt_inputs, gpt2_step_inputs)

    rng = np.random.default_rng(0)
    reqs = [Request(rid=i, prompt=list(rng.integers(1, 255, size=prompt_len)),
                    max_new_tokens=max_new, arrival_s=0.0) for i in range(n)]
    sched = ContinuousBatchingScheduler(
        eng, eng.params, gpt2_prompt_inputs, gpt2_step_inputs, eos_id=None,
        dispatch_ahead=2)
    t0 = time.perf_counter()
    done = sched.run(reqs)
    wall = time.perf_counter() - t0
    streams = {r.rid: list(r.tokens) for r in done}
    return streams, sched, wall


def _capacity_leg(check: bool, fails: list):
    """Same 24-page device pool, 4x (2x for --check) the servable context
    via the host tier — proven by serving the long trace to completion on
    the tiered engine and watching the HBM-only twin permanently shed it."""
    base_seq, base_new = 16, 6              # pps 6 -> 24 data pages, ctx 24
    if check:
        long_seq, long_new = 40, 8          # pps 12 -> host 24, ctx 48 (2x)
    else:
        long_seq, long_new = 88, 8          # pps 24 -> host 72, ctx 96 (4x)
    long_pps = -(-(long_seq + long_new) // PAGE)
    base_pps = -(-(base_seq + base_new) // PAGE)
    dev_pages = SLOTS * base_pps
    host = SLOTS * long_pps - dev_pages

    base = _build_engine(base_seq, base_new, 0)
    tier = _build_engine(long_seq, long_new, host)
    if tier.kv_spec.pool_pages != base.kv_spec.pool_pages:
        fails.append(
            f"device pools differ: tiered {tier.kv_spec.pool_pages} vs "
            f"HBM-only {base.kv_spec.pool_pages} — the gain would not be "
            "at fixed HBM pages")
    ctx_base = base.kv_spec.padded_len
    ctx_tier = tier.kv_spec.padded_len
    n = 4 if check else 6
    prompt_len = long_seq - 8
    streams, sched, wall = _serve(tier, n, prompt_len, long_new)
    complete = (len(streams) == n
                and all(len(t) == long_new for t in streams.values()))
    if not complete:
        fails.append(f"long-context trace incomplete on the tiered engine: "
                     f"{ {k: len(v) for k, v in streams.items()} }")
    ts = sched.kv.tier_stats()
    if not ts["kv_spills"]:
        fails.append("long-context leg never spilled — the device pool "
                     "covered everything, the gain is not tier-backed")
    # the HBM-only twin can NEVER hold one long sequence: permanent shed
    from flexflow_tpu.serving import (ContinuousBatchingScheduler, Request,
                                      gpt2_prompt_inputs, gpt2_step_inputs)
    shed_sched = ContinuousBatchingScheduler(
        base, base.params, gpt2_prompt_inputs, gpt2_step_inputs, eos_id=None)
    shed_sched.run([Request(rid=0, prompt=[1] * prompt_len,
                            max_new_tokens=long_new, arrival_s=0.0)])
    if shed_sched.stats["shed_prompt_too_long"] != 1:
        fails.append("HBM-only twin did not shed the long request as "
                     "permanent (capacity check regressed)")
    toks = sum(len(t) for t in streams.values())
    return {
        "device_data_pages": dev_pages,
        "host_pages": host,
        "context_hbm_only": ctx_base,
        "context_tiered": ctx_tier,
        "context_gain_vs_hbm_only": round(ctx_tier / ctx_base, 2),
        "requests": n,
        "prompt_len": prompt_len,
        "all_complete": complete,
        "wall_s": round(wall, 3),
        "tokens_per_s": round(toks / wall, 2),
        "tier": ts,
        "hbm_only_shed": shed_sched.stats["shed_prompt_too_long"],
    }


def _parity_leg(check: bool, fails: list):
    """Bitwise greedy-stream parity across the spill path, plus the
    hit/stall ledger: every rejoin is a hit or a counted stall."""
    n = 4 if check else 6
    base = _build_engine(16, 6, 0)
    tier = _build_engine(16, 6, 12)         # device pool halved: 12 + 12
    base_streams, _s0, _w0 = _serve(base, n, 8, 6)
    tier_streams, sched, _w1 = _serve(tier, n, 8, 6)
    parity = base_streams == tier_streams
    if not parity:
        bad = [rid for rid in base_streams
               if tier_streams.get(rid) != base_streams[rid]]
        fails.append(f"spill-path streams diverged for rids {bad[:4]}")
    ts = sched.kv.tier_stats()
    if not ts["kv_spills"]:
        fails.append("parity leg never spilled — it proved nothing")
    joins = ts["kv_prefetch_hits"] + ts["kv_prefetch_stalls"]
    if joins != ts["kv_refills"]:
        fails.append(f"rejoin ledger leaks: {joins} classified vs "
                     f"{ts['kv_refills']} refills")
    return {
        "requests": n,
        "bitwise_parity": parity,
        "spills": ts["kv_spills"],
        "refills": ts["kv_refills"],
        "prefetch_hits": ts["kv_prefetch_hits"],
        "prefetch_stalls": ts["kv_prefetch_stalls"],
        "prefetch_hit_rate": (round(ts["kv_prefetch_hits"] / joins, 4)
                              if joins else 1.0),
        "spilled_bytes": ts["kv_spilled_bytes"],
    }


def _crossover_leg(fails: list):
    """The serving prefill search finds the ring/flash crossover from its
    own pricing: ring past the flash VMEM budget, flash below it."""
    from flexflow_tpu import FFConfig, FFModel
    from flexflow_tpu.parallel.machine import MachineSpec
    from flexflow_tpu.serving.program import clone_for_serving, serving_optimize

    mach = MachineSpec(mesh_axes=dict(MESH), chip="v5p")

    def probe(seq):
        cfg = FFConfig(search_budget=16, mesh_shape=dict(MESH),
                       log_level="warning", strategy_cache=False)
        m = FFModel(cfg)
        x = m.create_tensor((2, seq, 128), name="x")
        m.multihead_attention(x, x, x, embed_dim=128, num_heads=2,
                              name="attn")
        sm, attn = clone_for_serving(m, "prefill", 2)
        st = serving_optimize(sm, mach, "prefill", attn)
        sh = st.op_shardings.get("attn")
        return (sh.attrs or {}).get("seq_parallel") if sh else None

    ring_long = probe(16384) == "model"
    flash_short = probe(512) is None
    if not ring_long:
        fails.append("prefill search did not pick sp_ring at 16k")
    if not flash_short:
        fails.append("prefill search picked sp_ring at 512 (ring hops "
                     "are pure overhead there)")
    return {"ring_at_16k": ring_long, "flash_at_512": flash_short,
            "crossover_ok": ring_long and flash_short}


def main(argv=None) -> int:
    p = argparse.ArgumentParser("bench_longctx")
    p.add_argument("--min-gain", type=float, default=4.0,
                   help="full-run gate on context_gain_vs_hbm_only")
    p.add_argument("--out", default="", help="also write the JSON here")
    p.add_argument("--check", action="store_true",
                   help="CI smoke: 2x host tier, capacity gate skipped; "
                        "parity, ledger and crossover still asserted")
    args = p.parse_args(argv)

    fails: list = []
    capacity = _capacity_leg(args.check, fails)
    if not args.check and \
            capacity["context_gain_vs_hbm_only"] < args.min_gain:
        fails.append(f"context gain {capacity['context_gain_vs_hbm_only']} "
                     f"< gate {args.min_gain}")
    parity = _parity_leg(args.check, fails)
    crossover = _crossover_leg(fails)

    report = {
        "model": "gpt2 CPU twin" + (" (check)" if args.check else ""),
        "capacity": capacity,
        "parity": parity,
        "crossover": crossover,
        # headline metrics (bench_history "longctx" family)
        "context_gain_vs_hbm_only": capacity["context_gain_vs_hbm_only"],
        "prefetch_hit_rate": parity["prefetch_hit_rate"],
        "spill_parity": int(parity["bitwise_parity"]),
        "ring_crossover": int(crossover["crossover_ok"]),
        "legs_passed": int(not fails),
    }
    print(json.dumps(report, indent=1))
    if args.out:
        with open(args.out, "w") as f:
            json.dump(report, f, indent=1)
    for msg in fails:
        print("CHECK FAIL: " + msg, file=sys.stderr)
    print("CHECK " + ("PASS" if not fails else "FAIL"))
    return 0 if not fails else 1


if __name__ == "__main__":
    raise SystemExit(main())
