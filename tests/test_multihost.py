"""Multi-host (N3) + DCN (N4) exercised.

- 2-process jax.distributed CPU run (the reference's fake-multi-node trick,
  tests/multinode_helpers/mpi_wrapper2.sh:14-15: one machine carved into
  ranks): both processes SPMD-run the same fit over a global 8-device mesh
  and must agree on losses and the final weights.
- DCN-aware search: the cost model must keep bandwidth-hungry collectives
  off dcn axes (config.h:157 control replication is the launch analog; the
  machine model's dcn_axes/dcn_bw are the fabric analog)."""

import socket
import subprocess
import sys

import numpy as np
import pytest

from flexflow_tpu import FFConfig, FFModel
from flexflow_tpu.parallel.machine import MachineSpec
from flexflow_tpu.search.dp import search_graph


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def test_two_process_distributed_fit(tmp_path):
    """The mpi_wrapper analog: 2 processes x 4 virtual CPU devices = one
    8-device world; fit runs control-replicated and converges identically."""
    port = _free_port()
    nproc = 2
    ckdir = str(tmp_path / "mh_ckpt")
    procs = [
        subprocess.Popen(
            [sys.executable, "tests/_multihost_worker.py", str(port),
             str(nproc), str(pid), ckdir],
            cwd="/root/repo", stdout=subprocess.PIPE, stderr=subprocess.PIPE,
            text=True)
        for pid in range(nproc)
    ]
    outs = []
    for p in procs:
        out, err = p.communicate(timeout=420)
        assert p.returncode == 0, f"worker failed:\n{out}\n{err[-3000:]}"
        outs.append(out)
    results = {}
    for out in outs:
        for line in out.splitlines():
            if line.startswith("RESULT"):
                kv = dict(tok.split("=") for tok in line.split()[1:])
                results[kv["pid"]] = (kv["loss"], kv["wsum"])
    assert set(results) == {"0", "1"}, outs
    # SPMD: both ranks observe the same loss and identical global weights
    assert results["0"] == results["1"], results


def _mlp_pair(batch=4096, hidden=1024):
    m = FFModel(FFConfig(batch_size=batch))
    x = m.create_tensor([batch, hidden], name="x")
    h = m.dense(x, 4 * hidden, activation="gelu", name="up")
    m.dense(h, hidden, name="down")
    return m


def test_search_avoids_tensor_parallel_over_dcn():
    """Same 2x4 mesh twice, activation-heavy MLP (big batch): with the model
    axis on ICI the search picks the full Megatron chain (col then row, its
    partial-sum all-reduce riding the fast axis); with that axis crossing
    slices (DCN bandwidth) the reduction becomes ~8x dearer and the search
    must abandon the Megatron chain on it."""
    ici = MachineSpec(mesh_axes={"data": 2, "model": 4}, chip="v5p")
    r_ici = search_graph(_mlp_pair(), ici)
    assert r_ici.choices["up"].name == "tp_col:model", r_ici.choices["up"].name
    assert r_ici.choices["down"].name == "tp_row:model", r_ici.choices["down"].name

    dcn = MachineSpec(mesh_axes={"data": 2, "model": 4}, chip="v5p",
                      dcn_axes=("model",))
    assert dcn.axis_bw("model") < ici.axis_bw("model") / 5
    r_dcn = search_graph(_mlp_pair(), dcn)
    assert r_dcn.choices["up"].name == "dp", r_dcn.choices["up"].name
    assert r_dcn.choices["down"].name != "tp_row:model", r_dcn.choices["down"].name


def test_dcn_data_axis_prices_gradient_allreduce():
    """DCN remains usable for sample parallelism — the search still batch-
    shards over a cross-slice data axis — but the gradient all-reduce (N2)
    must be priced at DCN bandwidth: the predicted step time rises by
    exactly the dearer sync."""
    def _model():
        m = FFModel(FFConfig(batch_size=64))
        x = m.create_tensor([64, 1024], name="x")
        m.dense(x, 1024, name="fc")
        return m

    ici = MachineSpec(mesh_axes={"data": 8}, chip="v5p")
    dcn = MachineSpec(mesh_axes={"data": 8}, chip="v5p", dcn_axes=("data",))
    r_ici = search_graph(_model(), ici)
    r_dcn = search_graph(_model(), dcn)
    assert r_ici.choices["fc"].name == "dp"
    assert r_dcn.choices["fc"].name == "dp"  # still batch-sharded over DCN
    # same compute, dearer sync: cost strictly higher, by roughly bw ratio
    assert r_dcn.cost > r_ici.cost * 1.5, (r_dcn.cost, r_ici.cost)
