"""ISSUE 10 — auto-parallel inference serving.

Covers the acceptance gates: `compile_serving` produces DIFFERENT searched
strategies for the prefill and decode programs on the 8-device gpt2 CPU
twin; incremental decode through the paged, model-axis-sharded KV cache is
numerically bit-close (<= 1e-5) to the full-sequence forward at every
position (gpt2 AND the generic transformer); serving is deterministic by
construction (dropout hard-zeroed in the clones, fixed rng); both serving
programs warm-hit the strategy cache under independent keys; KV-cache
residency is accounted in memory_stats within the watermark envelope; and
the continuous-batching scheduler admits/evicts correctly under EOS,
max-len, and page backpressure. tools/bench_serve.py --check rides along
as the CI smoke of the open-loop bench.
"""

import os
import sys

import jax.numpy as jnp
import numpy as np
import pytest

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "tools"))

from flexflow_tpu import FFConfig, FFModel
from flexflow_tpu.models import GPT2Config, build_gpt2
from flexflow_tpu.models.transformer import build_transformer
from flexflow_tpu.ops.op_type import OperatorType
from flexflow_tpu.serving import (ContinuousBatchingScheduler, Request,
                                  compile_serving, gpt2_prompt_inputs,
                                  gpt2_step_inputs)

MESH = {"data": 2, "model": 4}


def _serve_cfg(**kw):
    kw.setdefault("search_budget", 16)
    kw.setdefault("mesh_shape", dict(MESH))
    kw.setdefault("max_batch_slots", 4)
    kw.setdefault("kv_page_size", 4)
    kw.setdefault("max_decode_len", 6)
    kw.setdefault("log_level", "warning")
    return FFConfig(**kw)


def _gpt2_cfg():
    # dropout INTENTIONALLY nonzero: the serving clones must hard-zero it
    return GPT2Config(vocab=256, seq=16, d_model=64, heads=4, layers=1,
                      dropout=0.1)


@pytest.fixture(scope="module")
def gpt2_serve(devices):
    """One searched serving engine per module — the expensive bit (two
    DP searches + two jit compiles + sharded init) paid once."""
    cfg = _serve_cfg()
    model = FFModel(cfg)
    gc = _gpt2_cfg()
    build_gpt2(model, gc, batch=8)
    eng = compile_serving(model)
    eng.init(seed=0)
    return eng, gc


# --------------------------------------------------------- searched programs
def test_prefill_decode_strategies_differ(gpt2_serve):
    """The acceptance headline: the two programs SEARCHED to different
    strategies on the 8-device twin. The divergence is physical: decode's
    [slots, 1, e] activations make vocab-/row-sharded embeddings nearly
    free to all-reduce, while prefill's [slots, S, e] activations push the
    embedding tables to feature sharding."""
    eng, _ = gpt2_serve
    pre, dec = eng.prefill_strategy, eng.decode_strategy
    assert pre.op_shardings != dec.op_shardings
    diff = [n for n in pre.op_shardings
            if (dict(pre.op_shardings[n].weights),
                pre.op_shardings[n].outputs) !=
               (dict(dec.op_shardings[n].weights),
                dec.op_shardings[n].outputs)]
    assert diff, "strategies compare unequal but no op-level diff found"


def test_serving_clones_zero_dropout(gpt2_serve):
    """Inference determinism is a property of the PROGRAM: every dropout
    in both clones is rate-0 / p=0 even though the training graph trains
    with dropout=0.1, and layer names/topo order are preserved so params
    transfer 1:1."""
    eng, _ = gpt2_serve
    for sm in (eng.prefill_model, eng.decode_model):
        names = [l.name for l in sm.layers]
        assert names == [l.name for l in eng.model.layers]
        for l in sm.layers:
            if l.op_type is OperatorType.DROPOUT:
                assert l.params["rate"] == 0.0
            elif l.op_type is OperatorType.MULTIHEAD_ATTENTION:
                assert l.params["dropout"] == 0.0
    # the training graph really does carry nonzero dropout
    assert any(l.params.get("rate", 0) == 0.1 for l in eng.model.layers
               if l.op_type is OperatorType.DROPOUT)


def test_kv_pools_sharded_on_model_axis(gpt2_serve):
    """The paged pools shard their heads dim along the axis the decode
    search put on the attention weights — cache ops never reshard."""
    eng, _ = gpt2_serve
    assert eng.kv.heads_axis is not None
    assert eng.kv_shard_degree > 1
    k = eng.kv.state[eng.attn_layers[0]]["k"]
    shard0 = k.addressable_shards[0].data
    assert shard0.shape[2] * eng.kv_shard_degree == eng.kv_spec.heads


# ----------------------------------------------------------- decode parity
def _gpt2_parity_errs(eng, toks, prompt_len):
    """Max |decode - full forward| per generated position (teacher-forced:
    the decode path sees the same token stream as the full forward)."""
    slots, seq = eng.slots, int(eng.prefill_model.input_tensors[0].spec.shape[1])
    L = len(toks)
    ids_full = np.zeros((slots, seq), np.int32)
    ids_full[0, :L] = toks
    full, _ = eng.prefill(eng.params, gpt2_prompt_inputs(
        ids_full, np.full((slots,), L, np.int32)))
    full = np.asarray(full)

    ids = np.zeros((slots, seq), np.int32)
    ids[0, :prompt_len] = toks[:prompt_len]
    lengths = np.zeros((slots,), np.int32)
    lengths[0] = prompt_len
    assert eng.kv.admit(0, prompt_len, L + 2)
    eng.kv.push()
    pre, kv_state = eng.prefill(eng.params, gpt2_prompt_inputs(ids, lengths))
    eng.kv.commit_prefill(kv_state, np.arange(slots, dtype=np.int32), lengths)
    errs = [float(np.abs(np.asarray(pre)[0, :prompt_len]
                         - full[0, :prompt_len]).max())]
    state = eng.kv.state
    for t in range(prompt_len, L):
        step = np.zeros((slots, 1), np.int32)
        step[0, 0] = toks[t]
        logits, state = eng.decode_step(
            eng.params, state, gpt2_step_inputs(jnp.asarray(step), state))
        errs.append(float(np.abs(np.asarray(logits)[0, 0] - full[0, t]).max()))
    eng.kv.adopt(state)
    eng.kv.evict(0)
    eng.kv.push()
    return errs


def test_decode_parity_gpt2(gpt2_serve, rng):
    """Incremental decode with the paged sharded cache == full-sequence
    forward, at EVERY position, to 1e-5 — under the searched (model-axis
    sharded) strategies."""
    eng, gc = gpt2_serve
    toks = rng.integers(1, gc.vocab, size=12).astype(np.int32)
    errs = _gpt2_parity_errs(eng, toks, prompt_len=4)
    assert max(errs) <= 1e-5, errs


def test_decode_parity_transformer(devices, rng):
    """Same parity bar for the GENERIC transformer stack (raw embedding
    inputs, no position table) under a searched model-axis mesh."""
    cfg = _serve_cfg(max_batch_slots=2)
    model = FFModel(cfg)
    seq, d_model = 12, 32
    build_transformer(model, batch=8, seq=seq, d_model=d_model, heads=4,
                      d_ff=64, layers=1, classes=0, causal=True, dropout=0.1)
    eng = compile_serving(model, max_decode_len=4)
    eng.init(seed=0)
    assert eng.kv.heads_axis is not None  # sharded pools, not a dp fallback

    slots, L, P = eng.slots, 10, 3
    x = rng.normal(size=(slots, seq, d_model)).astype(np.float32)
    full, _ = eng.prefill(eng.params, [x])
    full = np.asarray(full)

    xp = np.zeros_like(x)
    xp[0, :P] = x[0, :P]
    lengths = np.zeros((slots,), np.int32)
    lengths[0] = P
    assert eng.kv.admit(0, P, L + 2)
    eng.kv.push()
    pre, kv_state = eng.prefill(eng.params, [xp])
    eng.kv.commit_prefill(kv_state, np.arange(slots, dtype=np.int32), lengths)
    errs = [float(np.abs(np.asarray(pre)[0, :P] - full[0, :P]).max())]
    state = eng.kv.state
    for t in range(P, L):
        logits, state = eng.decode_step(eng.params, state,
                                        [jnp.asarray(x[:, t:t + 1])])
        errs.append(float(np.abs(np.asarray(logits)[0, 0] - full[0, t]).max()))
    assert max(errs) <= 1e-5, errs


def test_inference_determinism(gpt2_serve, rng):
    """Two identical serving passes are BITWISE identical — dropout is
    structurally gone and the rng is pinned, with no flag to forget."""
    eng, gc = gpt2_serve
    toks = rng.integers(1, gc.vocab, size=8).astype(np.int32)
    slots = eng.slots
    seq = int(eng.prefill_model.input_tensors[0].spec.shape[1])
    ids = np.zeros((slots, seq), np.int32)
    ids[0, :8] = toks
    lengths = np.full((slots,), 8, np.int32)
    a, _ = eng.prefill(eng.params, gpt2_prompt_inputs(ids, lengths))
    b, _ = eng.prefill(eng.params, gpt2_prompt_inputs(ids, lengths))
    assert (np.asarray(a) == np.asarray(b)).all()
    state = eng.kv.state
    step = np.ones((slots, 1), np.int32)
    s1, _ = eng.decode_step(eng.params, state,
                            gpt2_step_inputs(jnp.asarray(step), state))
    s2, _ = eng.decode_step(eng.params, state,
                            gpt2_step_inputs(jnp.asarray(step), state))
    assert (np.asarray(s1) == np.asarray(s2)).all()


# ----------------------------------------------------------- strategy cache
def test_strategy_cache_warm_hit_both_programs(gpt2_serve):
    """A second compile_serving of the same graph/machine/knobs restores
    BOTH searched strategies from the cache — zero DP expansions — and the
    two programs live under INDEPENDENT cache keys."""
    from flexflow_tpu.search.dp import SEARCH_STATS

    _, gc = gpt2_serve  # fixture's compile populated the hermetic cache
    model = FFModel(_serve_cfg())
    build_gpt2(model, gc, batch=8)
    SEARCH_STATS["expansions"] = 0
    eng = compile_serving(model)
    assert SEARCH_STATS["expansions"] == 0
    pre_info = getattr(eng.prefill_strategy, "_cache_info", None)
    dec_info = getattr(eng.decode_strategy, "_cache_info", None)
    assert pre_info and pre_info["event"] == "hit"
    assert dec_info and dec_info["event"] == "hit"
    assert pre_info["key"] != dec_info["key"]
    assert pre_info["meta"]["kind"] == "prefill"
    assert dec_info["meta"]["kind"] == "decode"


# --------------------------------------------------------- memory accounting
def test_kv_memory_accounted_in_watermarks(gpt2_serve):
    """KV-cache bytes appear in memory_stats, the measured pool residency
    matches the KVCacheSpec prediction exactly (fixed-size pools), and the
    total predicted envelope holds against the measured watermark."""
    eng, _ = gpt2_serve
    ms = eng.memory_stats()
    assert ms["predicted_kv_cache_bytes"] > 0
    assert ms["actual_kv_cache_bytes_per_device"] == \
        ms["predicted_kv_cache_bytes"]
    assert ms["predicted_total_bytes"] == \
        ms["predicted_kv_cache_bytes"] + ms["predicted_param_bytes"]
    spec = eng.kv_spec
    per_dev = spec.total_bytes() // eng.kv_shard_degree
    assert ms["predicted_kv_cache_bytes"] == per_dev
    wm = eng.health_report()["watermarks"]
    assert wm["samples"] >= 1
    assert wm["ratio"] <= wm["warn_ratio"], wm
    assert not wm["warn"]


# -------------------------------------------------------------- scheduler
def test_scheduler_continuous_batching(gpt2_serve, rng):
    """More requests than slots: admission waves, max-len eviction, every
    request completes with exactly its token budget, and all pages return
    to the free list."""
    eng, gc = gpt2_serve
    n = eng.slots + 3
    reqs = [Request(rid=i, prompt=list(rng.integers(1, gc.vocab, size=3)),
                    max_new_tokens=4, arrival_s=0.0) for i in range(n)]
    sched = ContinuousBatchingScheduler(eng, eng.params, gpt2_prompt_inputs,
                                        gpt2_step_inputs, dispatch_ahead=3)
    done = sched.run(reqs)
    assert len(done) == n
    assert sorted(r.rid for r in done) == list(range(n))
    for r in done:
        assert len(r.tokens) == r.max_new_tokens
        assert r.ttft_s is not None and r.ttft_s >= 0.0
        assert r.finish_s is not None
    assert sched.prefills >= 2  # continuous batching: a second wave joined
    assert len(eng.kv.free_slots()) == eng.slots
    assert len(eng.kv.free_pages) == eng.kv_spec.pool_pages - 1


def test_scheduler_eos_eviction(gpt2_serve, rng):
    """EOS evicts early: pick the token the (deterministic) model emits at
    step 2 as the EOS id and re-serve — the sequence truncates right after
    it while the non-matching request still runs to its budget."""
    eng, gc = gpt2_serve
    prompt = list(rng.integers(1, gc.vocab, size=3))
    probe = [Request(rid=0, prompt=list(prompt), max_new_tokens=5)]
    sched = ContinuousBatchingScheduler(eng, eng.params, gpt2_prompt_inputs,
                                        gpt2_step_inputs, dispatch_ahead=2)
    ref = sched.run(probe)[0].tokens
    eos = ref[2]  # _truncate cuts at the FIRST occurrence, so the
    # expected output is ref up to wherever eos first appears
    reqs = [Request(rid=0, prompt=list(prompt), max_new_tokens=5)]
    sched2 = ContinuousBatchingScheduler(eng, eng.params, gpt2_prompt_inputs,
                                         gpt2_step_inputs, eos_id=eos,
                                         dispatch_ahead=2)
    out = sched2.run(reqs)[0]
    assert out.tokens == ref[:ref.index(eos) + 1]
    assert len(eng.kv.free_slots()) == eng.slots


def test_scheduler_page_backpressure(gpt2_serve, rng):
    """Backpressure is the free LIST draining (a single request is always
    capped at its slot's page budget): with every slot holding its full
    budget nothing more admits; eviction restores admissibility, and the
    scheduler serves admissible requests to completion."""
    eng, gc = gpt2_serve
    kv = eng.kv
    for s in range(eng.slots):  # drain: each slot takes its whole budget
        assert kv.admit(s, 1, kv.spec.padded_len)
    assert not kv.free_pages
    assert not kv.can_admit(1)
    for s in range(eng.slots):
        kv.evict(s)
    kv.push()
    assert kv.can_admit(kv.spec.padded_len)
    reqs = [Request(rid=i, prompt=list(rng.integers(1, gc.vocab, size=2)),
                    max_new_tokens=3, arrival_s=0.0) for i in range(2)]
    sched = ContinuousBatchingScheduler(eng, eng.params, gpt2_prompt_inputs,
                                        gpt2_step_inputs, dispatch_ahead=2)
    assert len(sched.run(reqs)) == 2


# ------------------------------------------------------------------ CI smoke
def test_bench_serve_check_smoke(devices, capsys):
    """tools/bench_serve.py --check wired into tier-1: the open-loop bench
    completes, quantiles are ordered, KV memory is accounted."""
    import bench_serve

    assert bench_serve.main(["--check", "--requests", "6"]) == 0
    assert "CHECK PASS" in capsys.readouterr().out


def test_serve_profile_ops_emits_corpus_rows(gpt2_serve, rng, tmp_path):
    """--profile-ops on a serving engine (ISSUE 14 satellite): a served
    batch featurizes its prefill + decode placements into op/attr corpus
    rows priced by the serving search's OWN cost fns — the learned cost
    model's only window into the bandwidth-bound seq=1 decode regime."""
    from flexflow_tpu import telemetry as tel
    from flexflow_tpu.attribution import OP_EVENT

    eng, gc = gpt2_serve
    tdir = str(tmp_path / "tel")
    tel.configure(tdir)
    old = eng.cfg.profile_ops
    eng.cfg.profile_ops = True
    try:
        reqs = [Request(rid=i, prompt=list(rng.integers(1, gc.vocab, size=3)),
                        max_new_tokens=3, arrival_s=0.0) for i in range(2)]
        sched = ContinuousBatchingScheduler(eng, eng.params,
                                            gpt2_prompt_inputs,
                                            gpt2_step_inputs)
        sched.run(reqs)
    finally:
        eng.cfg.profile_ops = old
        tel.shutdown()
    rows = [e.get("args") or {} for e in tel.read_events(tdir)
            if e.get("name") == OP_EVENT]
    srcs = {a.get("source") for a in rows}
    assert {"serve_prefill", "serve_decode"} <= srcs, srcs
    # every row is a full corpus row: featurized, with the serving
    # regime's own predicted price
    assert all(isinstance(a.get("features"), dict) for a in rows)
    dec = [a for a in rows if a.get("source") == "serve_decode"]
    assert any((a.get("predicted_s") or 0) > 0 for a in dec)


def test_serve_telemetry_stream(gpt2_serve, rng, tmp_path):
    """serve/prefill + serve/decode_step spans, queue/slot counters and
    per-request lifecycle events flow through the PR 5 sink and feed the
    monitor's serving panel."""
    import monitor

    from flexflow_tpu import telemetry as tel

    eng, gc = gpt2_serve
    tdir = str(tmp_path / "tel")
    tel.configure(tdir)
    try:
        reqs = [Request(rid=i, prompt=list(rng.integers(1, gc.vocab, size=3)),
                        max_new_tokens=3, arrival_s=0.0) for i in range(2)]
        sched = ContinuousBatchingScheduler(eng, eng.params,
                                            gpt2_prompt_inputs,
                                            gpt2_step_inputs,
                                            dispatch_ahead=2)
        sched.run(reqs)
    finally:
        tel.shutdown()
    evs = tel.read_events(tdir)
    names = {e.get("name") for e in evs}
    for want in ("serve/prefill", "serve/decode_step", "serve/queue_depth",
                 "serve/active_slots", "serve/request_admitted",
                 "serve/request_done"):
        assert want in names, (want, sorted(names))
    state = monitor.gather(evs)
    sv = monitor._serve_stats(state["serve"])
    assert sv["requests_done"] == 2 and sv["tokens"] == 6
    assert sv["ttft_p99_s"] is not None and sv["decode_p99_ms"] is not None
    prom = str(tmp_path / "node.prom")
    monitor.prom_export(state, prom)
    with open(prom) as f:
        txt = f.read()
    assert "flexflow_serve_tokens_per_second" in txt
    assert "flexflow_serve_ttft_p99_seconds" in txt
