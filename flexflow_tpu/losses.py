"""Loss functions.

Reference analog: include/flexflow/loss_functions.h:27-79 and
src/loss_functions/ — a backward-only Legion task seeding output gradients.
On TPU the loss is a scalar jnp expression inside the train step and jax.grad
derives the seeding, so only the forward definition is needed. Scale factors
match the reference (1/batch, and sparse-CE's intra-batch replica scaling is
subsumed by global mean).
"""

from __future__ import annotations

import enum

import jax
import jax.numpy as jnp
import optax


class LossType(enum.Enum):
    CATEGORICAL_CROSSENTROPY = "categorical_crossentropy"
    SPARSE_CATEGORICAL_CROSSENTROPY = "sparse_categorical_crossentropy"
    MEAN_SQUARED_ERROR = "mean_squared_error"
    MEAN_SQUARED_ERROR_AVG_REDUCE = "mean_squared_error_avg_reduce"
    IDENTITY = "identity"

    @staticmethod
    def from_any(x) -> "LossType":
        if isinstance(x, LossType):
            return x
        return LossType(str(x))


def compute_loss(loss_type: LossType, logits: jax.Array, labels: jax.Array,
                 from_logits: bool = True) -> jax.Array:
    """logits: model output; labels: int ids (sparse) or dense targets."""
    lt = LossType.from_any(loss_type)
    if lt is LossType.SPARSE_CATEGORICAL_CROSSENTROPY:
        labels = labels.reshape(logits.shape[:-1]).astype(jnp.int32)
        if from_logits:
            ce = optax.softmax_cross_entropy_with_integer_labels(logits, labels)
        else:
            logp = jnp.log(jnp.clip(logits, 1e-12))
            ce = -jnp.take_along_axis(logp, labels[..., None], axis=-1)[..., 0]
        return jnp.mean(ce)
    if lt is LossType.CATEGORICAL_CROSSENTROPY:
        if from_logits:
            ce = optax.softmax_cross_entropy(logits, labels.astype(logits.dtype))
        else:
            logp = jnp.log(jnp.clip(logits, 1e-12))
            ce = -jnp.sum(labels * logp, axis=-1)
        return jnp.mean(ce)
    if lt in (LossType.MEAN_SQUARED_ERROR, LossType.MEAN_SQUARED_ERROR_AVG_REDUCE):
        return jnp.mean(jnp.square(logits - labels.astype(logits.dtype)))
    if lt is LossType.IDENTITY:
        return jnp.mean(logits)
    raise ValueError(lt)
