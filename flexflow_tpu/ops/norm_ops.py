"""LayerNorm, Softmax, Dropout.

Reference analog: src/ops/layer_norm.cc (601 LoC custom CUDA), softmax.cc
(418, cuDNN), dropout.cc (362, cuDNN dropout states). Dropout keys derive from
the trace rng folded with the layer guid, so every layer and step draws an
independent stream without any device-side state objects.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from typing import TYPE_CHECKING
if TYPE_CHECKING:
    from flexflow_tpu.core.layer import Layer
from flexflow_tpu.core.tensor import TensorSpec
from flexflow_tpu.ops.op_type import OperatorType
from flexflow_tpu.ops.registry import register_op, LoweringCtx


def _ln_infer(layer: Layer):
    x = layer.inputs[0].spec
    axes = layer.params.get("axes")
    if axes is None:
        axes = [x.ndim - 1]
    axes = [a % x.ndim for a in axes]
    layer.params["axes"] = tuple(sorted(axes))
    if layer.params.get("elementwise_affine", True):
        nshape = tuple(x.shape[a] for a in layer.params["axes"])
        layer.weight_specs = {
            "gamma": TensorSpec(nshape, x.dtype),
            "beta": TensorSpec(nshape, x.dtype),
        }
    return [x]


def _ln_lower(layer: Layer, inputs, weights, ctx):
    x = inputs[0]
    axes = layer.params["axes"]
    eps = layer.params.get("eps", 1e-5)
    # statistics in f32 for bf16 stability; output back in the activation dtype
    xf = x.astype(jnp.float32)
    mean = jnp.mean(xf, axis=axes, keepdims=True)
    var = jnp.var(xf, axis=axes, keepdims=True)
    y = (xf - mean) * lax.rsqrt(var + eps)
    if "gamma" in weights:
        bshape = [1] * x.ndim
        for a in axes:
            bshape[a] = x.shape[a]
        y = (y * weights["gamma"].astype(jnp.float32).reshape(bshape)
             + weights["beta"].astype(jnp.float32).reshape(bshape))
    return [y.astype(x.dtype)]


register_op(OperatorType.LAYERNORM, _ln_infer, _ln_lower)


def _softmax_infer(layer: Layer):
    return [layer.inputs[0].spec]


def _softmax_lower(layer: Layer, inputs, weights, ctx):
    axis = layer.params.get("axis", -1)
    fn = jax.nn.log_softmax if layer.op_type is OperatorType.LOG_SOFTMAX else jax.nn.softmax
    return [fn(inputs[0], axis=axis)]


register_op(OperatorType.SOFTMAX, _softmax_infer, _softmax_lower)
register_op(OperatorType.LOG_SOFTMAX, _softmax_infer, _softmax_lower)


def _dropout_infer(layer: Layer):
    return [layer.inputs[0].spec]


def _dropout_lower(layer: Layer, inputs, weights, ctx: LoweringCtx):
    x = inputs[0]
    rate = layer.params.get("rate", 0.5)
    if not ctx.training or rate <= 0.0:
        return [x]
    keep = 1.0 - rate
    mask = jax.random.bernoulli(ctx.rng_for(layer), keep, x.shape)
    return [jnp.where(mask, x / keep, 0.0).astype(x.dtype)]


register_op(OperatorType.DROPOUT, _dropout_infer, _dropout_lower)
