"""FORK_JOIN — the inter-op-placement composite.

Reference analog: Unity's nonsequence splits place parallel PCG branches on
disjoint machine subsets (/root/reference/src/runtime/graph.cc:187-321,
VERTICAL/HORIZONTAL). There the split is implicit graph structure; here the
fork-join region is a first-class op (like the reference's `moe()` composite,
include/flexflow/model.h:509) holding one sub-graph per branch:

  - built via `FFModel.fork_join(x, [branch_builder...], join=...)`;
  - each branch is a sequence of ordinary Layers (built against a sub-model);
  - the search chooses its placement like any other op: the `dp` candidate
    computes every branch on every device (batch-sharded), the `inter:{axis}`
    candidate places branch i on mesh-axis index i (disjoint chips) via
    shard_map + lax.switch (parallel/interop.py) and pays the join collective.

Weight naming: branch i's layer L weight w is exposed as "b{i}.{L}.{w}" on
the fork_join layer, so checkpointing/get_weight/set_weight see one flat op.
"""

from __future__ import annotations

from typing import TYPE_CHECKING, Dict, List

if TYPE_CHECKING:
    from flexflow_tpu.core.layer import Layer
from flexflow_tpu.core.tensor import TensorSpec
from flexflow_tpu.ops.op_type import OperatorType
from flexflow_tpu.ops.registry import LoweringCtx, get_op_def, register_op


def _branch_layers(layer: Layer, bi: int) -> List[Layer]:
    return layer.branches[bi][0]


# ops whose lowering writes LoweringCtx.new_state; their tracers cannot cross
# the shard_map/switch boundary of the placed execution path
_STATEFUL_OPS = frozenset({OperatorType.BATCHNORM, OperatorType.CACHE})


def inter_placeable(layer: "Layer") -> bool:
    """True when this fork_join can execute under inter:{axis} placement:
    equal branch output shapes (lax.switch arms must agree) and no stateful
    sub-ops (their new_state tracers would leak out of the shard_map)."""
    sigs = {(tuple(out.spec.shape), out.spec.dtype)
            for (_l, _b, out) in layer.branches}
    if len(sigs) != 1:
        return False
    return not any(l.op_type in _STATEFUL_OPS
                   for (ls, _b, _o) in layer.branches for l in ls)


def grouped_placeable(layer: "Layer") -> bool:
    """True when this fork_join can execute under UNEQUAL group placement
    (`inter:{axis}:{g0}-{g1}-...`, parallel/interop.place_branches_grouped).
    Branch output shapes need not match (each arm emits a zero-padded buffer
    of the full joined output) — only stateful sub-ops are excluded."""
    return not any(l.op_type in _STATEFUL_OPS
                   for (ls, _b, _o) in layer.branches for l in ls)


def branch_flops(layer: "Layer") -> List[float]:
    """Per-branch flop counts — the load-balance weights the resource
    division (search/candidates._best_groups; interop.divide_workers for
    manual placement) optimizes over (reference graph.cc:267-321 enumerates
    exactly these divisions)."""
    return [sum(get_op_def(l.op_type).flop_count(l) for l in ls)
            for (ls, _b, _o) in layer.branches]


def branch_weight_bytes(layer: "Layer") -> List[int]:
    return [sum(s.size_bytes for l in ls for s in l.weight_specs.values())
            for (ls, _b, _o) in layer.branches]


def congruent_branches(layer: "Layer") -> bool:
    """True when every branch has the SAME sub-layer names and weight
    shapes/dtypes, position by position — the symmetric case whose weights
    can be stored STACKED ((k, ...) arrays sharded over the placement axis:
    owned-device residency, parallel/interop.py). Heterogeneous branches
    keep per-branch replicated weights."""
    def sig(layers):
        return tuple((l.name, tuple(sorted(
            (w, s.shape, s.dtype) for w, s in l.weight_specs.items())))
            for l in layers if l.weight_specs)

    sigs = {sig(ls) for (ls, _b, _o) in layer.branches}
    return len(sigs) == 1 and any(s for s in sigs)


def _fj_infer(layer: Layer) -> List[TensorSpec]:
    if not hasattr(layer, "branches") or not layer.branches:
        raise ValueError("fork_join layer has no branches attached "
                         "(build via FFModel.fork_join)")
    join = layer.params["join"]
    x = layer.inputs[0].spec
    out_specs = [out.spec for (_layers, _bx, out) in layer.branches]
    base = out_specs[0]
    for s in out_specs[1:]:
        if s.ndim != base.ndim or (join == "add" and s.shape != base.shape):
            raise ValueError(f"fork_join branch shapes differ: {out_specs}")
        if join == "concat" and s.shape[:-1] != base.shape[:-1]:
            raise ValueError(f"fork_join concat branches must agree on all "
                             f"dims but the last: {out_specs}")
    if base.shape[0] != x.shape[0]:
        raise ValueError("fork_join branches must preserve the batch dim")
    layer.weight_specs = {}
    if congruent_branches(layer):
        # stacked owned-device storage: one (k, ...) array per sub-weight,
        # shardable over the placement axis (branch i = slice i)
        k = len(layer.branches)
        for l in layer.branches[0][0]:
            for w, spec in l.weight_specs.items():
                layer.weight_specs[f"stk.{l.name}.{w}"] = TensorSpec(
                    (k,) + tuple(spec.shape), spec.dtype)
    else:
        for bi, (layers, _bx, _out) in enumerate(layer.branches):
            for l in layers:
                for w, spec in l.weight_specs.items():
                    layer.weight_specs[f"b{bi}.{l.name}.{w}"] = spec
    if join == "add":
        return [base]
    last = sum(s.shape[-1] for s in out_specs)
    return [base.with_shape(base.shape[:-1] + (last,))]


def _branch_weight_dicts(layer: Layer, weights: Dict) -> List[Dict[str, Dict]]:
    """Split the flat prefixed weight dict back into per-branch
    {sub_layer_name: {wname: array}}. Stacked ("stk.") weights slice
    branch i out of the (k, ...) array."""
    out: List[Dict[str, Dict]] = []
    for bi in range(len(layer.branches)):
        d: Dict[str, Dict] = {}
        for k, v in weights.items():
            if k.startswith("stk."):
                lname, wname = k[4:].split(".", 1)
                d.setdefault(lname, {})[wname] = v[bi]
            elif k.startswith(f"b{bi}."):
                # split at the FIRST dot: the remainder is the sub-layer's
                # own weight name, which itself contains dots when the
                # sub-layer is a nested fork_join ("b0.inner.b0.i1.kernel")
                lname, wname = k[len(f"b{bi}."):].split(".", 1)
                d.setdefault(lname, {})[wname] = v
        out.append(d)
    return out


def stacked_weight_trees(layer: Layer, weights: Dict):
    """{sub_layer: {wname: (k, ...) array}} for the stacked storage case,
    or None when this layer uses per-branch (heterogeneous) weights."""
    stk = {k: v for k, v in weights.items() if k.startswith("stk.")}
    if not stk:
        return None
    tree: Dict[str, Dict] = {}
    for k, v in stk.items():
        lname, wname = k[4:].split(".", 1)
        tree.setdefault(lname, {})[wname] = v
    return tree


def _make_branch_fn(layer: Layer, bi: int, ctx: LoweringCtx):
    layers, bx, bout = layer.branches[bi]

    def run(x, wdict):
        env = {bx.guid: x}
        for l in layers:
            ins = [env[t.guid] for t in l.inputs]
            outs = get_op_def(l.op_type).lower(l, ins, wdict.get(l.name, {}), ctx)
            for t, o in zip(l.outputs, outs):
                env[t.guid] = o
        return env[bout.guid]

    return run


def _fj_lower(layer: Layer, inputs, weights, ctx: LoweringCtx):
    import jax.numpy as jnp

    x = inputs[0]
    join = layer.params["join"]
    wdicts = _branch_weight_dicts(layer, weights)
    fns = [_make_branch_fn(layer, bi, ctx) for bi in range(len(layer.branches))]

    placement = ctx.op_attrs.get(layer.name, {}).get("placement")
    groups = ctx.op_attrs.get(layer.name, {}).get("placement_groups")
    if placement and ctx.mesh is not None and placement in ctx.mesh.shape:
        if groups and grouped_placeable(layer):
            # unequal resource division: branch b owns group_sizes[b]
            # indices of the axis and batch-shards within its group
            from flexflow_tpu.parallel.interop import place_branches_grouped

            gs = tuple(int(s) for s in groups.split("-"))
            out_dims = [out.spec.shape[-1]
                        for (_ls, _bx, out) in layer.branches]
            return [place_branches_grouped(
                ctx.mesh, placement, fns, x, wdicts, join, gs, out_dims,
                layer.outputs[0].spec.ndim)]
        if not groups and inter_placeable(layer):
            stacked = stacked_weight_trees(layer, weights)
            if stacked is not None:
                from flexflow_tpu.parallel.interop import place_branches_stacked

                return [place_branches_stacked(ctx.mesh, placement, fns, x,
                                               stacked, join)]
            from flexflow_tpu.parallel.interop import place_branches

            return [place_branches(ctx.mesh, placement, fns, x, wdicts, join)]
    # replicated execution: every device runs every branch (batch-sharded)
    ys = [fn(x, wd) for fn, wd in zip(fns, wdicts)]
    if join == "add":
        out = ys[0]
        for y in ys[1:]:
            out = out + y
        return [out]
    return [jnp.concatenate(ys, axis=-1)]


def _fj_flops(layer: Layer) -> float:
    total = 0.0
    for bi in range(len(layer.branches)):
        for l in _branch_layers(layer, bi):
            total += get_op_def(l.op_type).flop_count(l)
    return total


register_op(OperatorType.FORK_JOIN, _fj_infer, _fj_lower, _fj_flops)
