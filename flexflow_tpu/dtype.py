"""Data types (reference: include/flexflow/ffconst.h DataType enum)."""

from __future__ import annotations

import enum

import jax.numpy as jnp
import numpy as np


class DataType(enum.Enum):
    BOOL = "bool"
    INT32 = "int32"
    INT64 = "int64"
    HALF = "float16"
    BF16 = "bfloat16"
    FLOAT = "float32"
    DOUBLE = "float64"

    @property
    def jnp_dtype(self):
        return _JNP[self]

    @property
    def np_dtype(self):
        return _NP[self]

    @property
    def itemsize(self) -> int:
        return np.dtype(self.np_dtype).itemsize if self is not DataType.BF16 else 2

    @staticmethod
    def from_any(x) -> "DataType":
        if isinstance(x, DataType):
            return x
        s = str(jnp.dtype(x)) if not isinstance(x, str) else x
        for dt in DataType:
            if dt.value == s:
                return dt
        raise ValueError(f"unknown dtype {x!r}")


_JNP = {
    DataType.BOOL: jnp.bool_,
    DataType.INT32: jnp.int32,
    DataType.INT64: jnp.int64,
    DataType.HALF: jnp.float16,
    DataType.BF16: jnp.bfloat16,
    DataType.FLOAT: jnp.float32,
    DataType.DOUBLE: jnp.float64,
}

_NP = {
    DataType.BOOL: np.bool_,
    DataType.INT32: np.int32,
    DataType.INT64: np.int64,
    DataType.HALF: np.float16,
    DataType.BF16: jnp.bfloat16,  # numpy via ml_dtypes through jnp
    DataType.FLOAT: np.float32,
    DataType.DOUBLE: np.float64,
}
