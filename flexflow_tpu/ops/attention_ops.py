"""Multi-head attention.

Reference analog: src/ops/attention.cc (926) + attention.cu (372), which wrap
cuDNN MultiHeadAttn (cudnnMultiHeadAttnForward, src/ops/attention.cu:35). The
TPU lowering is einsum-based scaled-dot-product attention that XLA maps onto
the MXU; a fused pallas flash-attention kernel
(flexflow_tpu/kernels/flash_attention.py) is used instead when shapes qualify
(seq multiple of block size) and `impl` is not forced to "xla".

Head-parallel tensor parallelism (reference substitutions
create_partition_attention_combine, src/runtime/substitution.cc:1763-1770) is
expressed by sharding the per-head projection weights on a model axis.
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from typing import TYPE_CHECKING
if TYPE_CHECKING:
    from flexflow_tpu.core.layer import Layer
from flexflow_tpu.core.tensor import TensorSpec
from flexflow_tpu.ops.op_type import OperatorType
from flexflow_tpu.ops.registry import register_op, LoweringCtx


def _mha_infer(layer: Layer):
    q, k, v = [t.spec for t in layer.inputs[:3]]
    p = layer.params
    embed = p["embed_dim"]
    heads = p["num_heads"]
    if embed % heads:
        raise ValueError("num_heads must divide embed_dim")
    # kdim/vdim are the key/value input feature dims (torch/reference
    # semantics); they must match the actual inputs if given.
    if p.get("kdim") and p["kdim"] != k.shape[-1]:
        raise ValueError(f"kdim={p['kdim']} != key feature dim {k.shape[-1]}")
    if p.get("vdim") and p["vdim"] != v.shape[-1]:
        raise ValueError(f"vdim={p['vdim']} != value feature dim {v.shape[-1]}")
    layer.weight_specs = {
        "wq": TensorSpec((q.shape[-1], embed), q.dtype),
        "wk": TensorSpec((k.shape[-1], embed), q.dtype),
        "wv": TensorSpec((v.shape[-1], embed), q.dtype),
        "wo": TensorSpec((embed, embed), q.dtype),
    }
    if p.get("bias", True):
        layer.weight_specs.update(
            {
                "bq": TensorSpec((embed,), q.dtype),
                "bk": TensorSpec((embed,), q.dtype),
                "bv": TensorSpec((embed,), q.dtype),
                "bo": TensorSpec((embed,), q.dtype),
            }
        )
    if p.get("add_bias_kv", False):
        layer.weight_specs["bias_k"] = TensorSpec((embed,), q.dtype)
        layer.weight_specs["bias_v"] = TensorSpec((embed,), q.dtype)
    return [q.with_shape(q.shape[:-1] + (embed,))]


def _split_heads(x, heads):
    b, s, e = x.shape
    return x.reshape(b, s, heads, e // heads)


def _mha_decode_lower(layer: Layer, inputs, weights, ctx: LoweringCtx):
    """Decode step(s) against the paged KV cache (serving path).

    Inputs are [slots, s, embed] — s=1 for the plain decode program, s=K+1
    for the speculative-verify program (one batched pass teacher-forcing
    the K drafted tokens). The cache lives in lowering state:
      ctx.state[layer.name]    = {"k": [pages, page, h, d], "v": ...,
                                  optionally "k_scale"/"v_scale" for int8}
      ctx.state["serve/page_table"] = [slots, pages_per_slot] int32 page ids
      ctx.state["serve/pos"]        = [slots] int32 count of cached tokens

    Token i's K/V is scattered into page (pos+i)//page_size at offset
    (pos+i)%page_size (out-of-range positions route to the scratch page,
    mirroring commit_prefill), then attention runs over the gathered
    per-slot pages with the causal extent mask (query i attends cached
    positions <= pos+i). A quantized cache (int8 pools + per-entry-per-head
    scales) quantizes on append and dequantizes in the gather — fused into
    the attention by the pallas dequant kernel when fusion is enabled,
    einsum fallback otherwise. Inactive slots point every page-table entry
    at the reserved scratch page 0 with pos 0, so their writes land in
    scratch and their (garbage but finite) outputs are ignored by the
    scheduler. Everything is a fixed-shape gather/scatter — no resharding,
    no recompilation across steps."""
    q = inputs[0]
    p = layer.params
    heads = p["num_heads"]
    embed = p["embed_dim"]
    hd = embed // heads
    dt = q.dtype

    def proj(x, w, b):
        y = x @ weights[w].astype(dt)
        if b in weights:
            y = y + weights[b].astype(dt)
        return y

    qh = _split_heads(proj(inputs[0], "wq", "bq"), heads)  # (slots, s, h, d)
    kh = _split_heads(proj(inputs[1], "wk", "bk"), heads)
    vh = _split_heads(proj(inputs[2], "wv", "bv"), heads)

    cache = ctx.state[layer.name]
    k_pool, v_pool = cache["k"], cache["v"]
    quantized = "k_scale" in cache
    pt = ctx.state["serve/page_table"]
    pos = ctx.state["serve/pos"]
    page = k_pool.shape[1]
    b, s = q.shape[0], q.shape[1]
    rows = jnp.arange(b)
    t = pos[:, None] + jnp.arange(s)[None, :]      # (slots, s) write positions
    pg = t // page
    in_range = pg < pt.shape[1]
    pageix = jnp.where(in_range,
                       pt[rows[:, None], jnp.minimum(pg, pt.shape[1] - 1)], 0)
    off = t % page
    if quantized:
        from flexflow_tpu.serving.kv_cache import kv_quantize

        qk, ks = kv_quantize(kh)
        qv, vs = kv_quantize(vh)
        k_pool = k_pool.at[pageix, off].set(qk)
        v_pool = v_pool.at[pageix, off].set(qv)
        k_scale = cache["k_scale"].at[pageix, off].set(ks)
        v_scale = cache["v_scale"].at[pageix, off].set(vs)
        ctx.new_state[layer.name] = {"k": k_pool, "v": v_pool,
                                     "k_scale": k_scale, "v_scale": v_scale}
    else:
        k_pool = k_pool.at[pageix, off].set(kh.astype(k_pool.dtype))
        v_pool = v_pool.at[pageix, off].set(vh.astype(v_pool.dtype))
        ctx.new_state[layer.name] = {"k": k_pool, "v": v_pool}

    scale = 1.0 / math.sqrt(hd)
    out = None
    if quantized:
        # gather the int8 context + scales: [slots, L, h, (d)]
        Kq = k_pool[pt].reshape(b, -1, heads, hd)
        Vq = v_pool[pt].reshape(b, -1, heads, hd)
        Ks = k_scale[pt].reshape(b, -1, heads)
        Vs = v_scale[pt].reshape(b, -1, heads)
        if ctx.enable_fusion:
            try:
                from flexflow_tpu.kernels.dequant_attention import (
                    dequant_decode_attention,
                )

                out = dequant_decode_attention(qh, Kq, Ks, Vq, Vs, pos,
                                               scale=scale)
            except Exception:
                out = None  # einsum dequant fallback below
        if out is None:
            K = (Kq.astype(jnp.float32) * Ks[..., None]).astype(dt)
            V = (Vq.astype(jnp.float32) * Vs[..., None]).astype(dt)
    else:
        # gather each slot's pages: [slots, pages_per_slot, page, h, d]
        K = k_pool[pt].reshape(b, -1, heads, hd).astype(dt)
        V = v_pool[pt].reshape(b, -1, heads, hd).astype(dt)
    if out is None:
        logits = jnp.einsum("bqhd,bkhd->bhqk", qh, K) * scale
        # causal-by-construction: query token i (at position pos+i, just
        # written) attends cached positions 0..pos+i inclusive
        keep = (jnp.arange(K.shape[1])[None, None, None, :]
                <= t[:, None, :, None])
        logits = jnp.where(keep, logits, jnp.finfo(logits.dtype).min)
        probs = jax.nn.softmax(logits, axis=-1)
        out = jnp.einsum("bhqk,bkhd->bqhd", probs, V)
    out = out.reshape(b, s, embed)
    y = out @ weights["wo"].astype(dt)
    if "bo" in weights:
        y = y + weights["bo"].astype(dt)
    return [y]


def _mha_lower(layer: Layer, inputs, weights, ctx: LoweringCtx):
    q, k, v = inputs[:3]
    p = layer.params
    if p.get("decode", False) or p.get("kv_out", False):
        if p.get("add_bias_kv", False) or p.get("add_zero_attn", False):
            raise NotImplementedError(
                "KV-cache decode/prefill does not support add_bias_kv/"
                "add_zero_attn (extra key positions would enter the cache)")
    if p.get("decode", False):
        return _mha_decode_lower(layer, inputs, weights, ctx)
    heads = p["num_heads"]
    embed = p["embed_dim"]
    dt = q.dtype

    def proj(x, w, b):
        y = x @ weights[w].astype(dt)
        if b in weights:
            y = y + weights[b].astype(dt)
        return y

    kp = proj(k, "wk", "bk")
    vp = proj(v, "wv", "bv")
    if p.get("kv_out", False):
        # serving prefill: expose the per-head K/V of the prompt tokens so
        # the engine can commit them into the paged cache (captured BEFORE
        # any bias_kv/zero_attn positions could pollute the cache)
        ctx.new_state[layer.name] = {"k": _split_heads(kp, heads),
                                     "v": _split_heads(vp, heads)}
    if "bias_k" in weights:  # add_bias_kv: learned extra kv position
        b_ = k.shape[0]
        kp = jnp.concatenate([kp, jnp.broadcast_to(weights["bias_k"].astype(dt), (b_, 1, embed))], axis=1)
        vp = jnp.concatenate([vp, jnp.broadcast_to(weights["bias_v"].astype(dt), (b_, 1, embed))], axis=1)
    if p.get("add_zero_attn", False):
        b_ = k.shape[0]
        kp = jnp.concatenate([kp, jnp.zeros((b_, 1, embed), dt)], axis=1)
        vp = jnp.concatenate([vp, jnp.zeros((b_, 1, embed), dt)], axis=1)
    qh = _split_heads(proj(q, "wq", "bq"), heads)  # (b, sq, h, d)
    kh = _split_heads(kp, heads)
    vh = _split_heads(vp, heads)

    impl = p.get("impl", "auto")
    causal = p.get("causal", False)
    scale = 1.0 / math.sqrt(embed // heads)
    out = None
    # flash kernel has no probs-dropout path: fall back (or fail under
    # impl="flash") rather than silently dropping the dropout mask
    needs_dropout = ctx.training and p.get("dropout", 0.0) > 0.0
    # sequence parallelism: the searched strategy may place this attention
    # on the ring path (sp_ring candidate -> {"seq_parallel": axis} attr)
    sp_axis = ctx.op_attrs.get(layer.name, {}).get("seq_parallel")
    if sp_axis and ctx.mesh is not None and sp_axis in ctx.mesh.shape \
            and impl != "xla" and qh.shape[1] == kh.shape[1] == vh.shape[1] \
            and qh.shape[1] % ctx.mesh.shape[sp_axis] == 0 \
            and not needs_dropout and "bias_k" not in weights \
            and not p.get("add_zero_attn", False):
        from flexflow_tpu.kernels.ring_attention import ring_attention_qkv

        out = ring_attention_qkv(qh, kh, vh, ctx.mesh, sp_axis,
                                 causal=causal, scale=scale)
    if impl == "flash" and needs_dropout:
        raise NotImplementedError("impl='flash' does not support attention-prob "
                                  "dropout; use dropout=0.0 or impl='xla'")
    # "auto" uses the fused pallas kernel only when fusion is enabled
    # (--fusion, reference FusedOp gate); impl="flash" forces it regardless
    if out is None and not needs_dropout and (
            impl == "flash" or (impl == "auto" and ctx.enable_fusion)):
        try:
            from flexflow_tpu.kernels.flash_attention import flash_attention_qkv

            out = flash_attention_qkv(qh, kh, vh, causal=causal, scale=scale)
        except Exception:
            # auto falls back to the einsum path on ANY flash failure
            # (unsupported shapes raise ValueError; the experimental pallas
            # stack may raise other types at trace time)
            if impl == "flash":
                raise
            out = None
    if out is None:
        logits = jnp.einsum("bqhd,bkhd->bhqk", qh, kh) * scale
        if causal:
            sq, sk = logits.shape[-2], logits.shape[-1]
            # causal band over the ORIGINAL key positions only; positions
            # appended by add_bias_kv/add_zero_attn (indices >= sk_orig, at the
            # end) are always attendable and must not shift the band
            sk_orig = k.shape[1]
            mask = jnp.tril(jnp.ones((sq, sk_orig), bool), k=sk_orig - sq)
            if sk > sk_orig:
                mask = jnp.concatenate(
                    [mask, jnp.ones((sq, sk - sk_orig), bool)], axis=1)
            logits = jnp.where(mask, logits, jnp.finfo(logits.dtype).min)
        probs = jax.nn.softmax(logits, axis=-1)
        if ctx.training and p.get("dropout", 0.0) > 0.0:
            import jax.random as jrandom

            keep = 1.0 - p["dropout"]
            mask = jrandom.bernoulli(ctx.rng_for(layer), keep, probs.shape)
            probs = jnp.where(mask, probs / keep, 0.0).astype(probs.dtype)
        out = jnp.einsum("bhqk,bkhd->bqhd", probs, vh)
    b, sq = q.shape[0], q.shape[1]
    out = out.reshape(b, sq, embed)
    y = out @ weights["wo"].astype(dt)
    if "bo" in weights:
        y = y + weights["bo"].astype(dt)
    return [y]


def _mha_flops(layer: Layer):
    q, k = layer.inputs[0].spec, layer.inputs[1].spec
    b, sq, e = q.shape
    sk = k.shape[1]
    proj = 2.0 * b * (3 * sq + sq) * e * e  # q,k,v,o projections (approx sq≈sk)
    attn = 2.0 * b * sq * sk * e * 2  # qk^T and att@v
    return proj + attn


register_op(OperatorType.MULTIHEAD_ATTENTION, _mha_infer, _mha_lower, _mha_flops)


def _sdpa_infer(layer: Layer):
    """Core scaled-dot-product attention (torch.nn.functional.
    scaled_dot_product_attention semantics): q (..., sq, d), k (..., sk, d),
    v (..., sk, dv) -> (..., sq, dv). Optional 4th input: additive float mask
    or boolean keep-mask, broadcastable to (..., sq, sk)."""
    q, k, v = [t.spec for t in layer.inputs[:3]]
    if q.shape[-1] != k.shape[-1]:
        raise ValueError(f"q/k depth mismatch {q.shape} vs {k.shape}")
    return [q.with_shape(q.shape[:-1] + (v.shape[-1],))]


def _sdpa_lower(layer: Layer, inputs, weights, ctx: LoweringCtx):
    q, k, v = inputs[:3]
    mask = inputs[3] if len(inputs) > 3 else None
    p = layer.params
    scale = p.get("scale")
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    logits = jnp.einsum("...qd,...kd->...qk", q, k) * scale
    neg = jnp.finfo(logits.dtype).min
    if mask is not None:
        if jnp.issubdtype(mask.dtype, jnp.bool_):
            logits = jnp.where(mask, logits, neg)
        else:
            logits = logits + mask.astype(logits.dtype)
    if p.get("is_causal", False):
        # torch semantics: TOP-LEFT aligned causal band (tril diagonal=0),
        # not bottom-right like a decode-step band
        sq, sk = logits.shape[-2], logits.shape[-1]
        cmask = jnp.tril(jnp.ones((sq, sk), bool))
        logits = jnp.where(cmask, logits, neg)
    probs = jax.nn.softmax(logits, axis=-1)
    if ctx.training and p.get("dropout_p", 0.0) > 0.0:
        keep = 1.0 - p["dropout_p"]
        dmask = jax.random.bernoulli(ctx.rng_for(layer), keep, probs.shape)
        probs = jnp.where(dmask, probs / keep, 0.0).astype(probs.dtype)
    return [jnp.einsum("...qk,...kd->...qd", probs, v)]


def _sdpa_flops(layer: Layer):
    q, k = layer.inputs[0].spec, layer.inputs[1].spec
    batch = 1
    for d in q.shape[:-2]:
        batch *= d
    sq, d = q.shape[-2], q.shape[-1]
    sk = k.shape[-2]
    return 2.0 * batch * sq * sk * d * 2


register_op(OperatorType.SDPA, _sdpa_infer, _sdpa_lower, _sdpa_flops)
