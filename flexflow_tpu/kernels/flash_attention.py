"""Block-wise (flash) attention as a pallas TPU kernel.

Capability replaced: the reference's fused cuDNN multi-head attention
(src/ops/attention.cu:35, cudnnMultiHeadAttnForward) — a single kernel that
never materializes the (b, h, sq, sk) logits tensor. The TPU-native
formulation is the standard online-softmax blocked algorithm: k/v live in
VMEM per (b, h) grid step (bounded by _VMEM_SEQ_BYTES) and stream through
the MXU in blocks, with running max/sum statistics kept in f32, so HBM
traffic is O(s*d) instead of O(s^2).

Forward saves the per-row logsumexp; the backward pass is two more pallas
kernels (dq gridded over q blocks; dk/dv gridded over k blocks) recomputing
the probabilities from the saved lse — the flash-attention v2 recipe.

All matmuls accumulate in float32 (preferred_element_type) regardless of the
input dtype; bf16 inputs hit the MXU at full rate.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

_BLOCK_CANDIDATES = (1024, 512, 256, 128)
_NEG_INF = float("-inf")
# k/v (fwd/dq) and q/do (dk/dv) are held fully in VMEM per (b, h) grid step;
# cap their footprint well under the ~16MB VMEM budget so Mosaic never OOMs
# on shapes that pass the divisibility checks. Longer sequences belong to the
# ring-attention path (kernels/ring_attention.py).
_VMEM_SEQ_BYTES = 6 * 1024 * 1024
# per-BLOCK VMEM budget: the block-shape ceiling was implicitly sized for
# head_dim 64 (a 512 x 64 f32 block = 128KB). Wider heads scale the block
# footprint linearly, so the block choice is parametrized by (depth,
# itemsize): head_dim 128 f32 drops 512 -> 256 instead of handing Mosaic a
# 256KB block per operand (q, do, dq accumulators all carry it); bf16 keeps
# the full 512. 160KB leaves the d=64 behavior exactly as before.
_VMEM_BLOCK_BYTES = 160 * 1024
# narrow heads (d <= 64, the 54%-MFU case in BENCH_r05) get a larger
# per-block budget: a 1024 x 64 f32 block is 256KB and three such operands
# are still < 1MB of VMEM, while the doubled rows-per-grid-step halve the
# k/v streaming overhead that starves the MXU at short blocks. Wider heads
# keep the 160KB budget (d=128 behavior unchanged: f32 -> 256, bf16 -> 512).
_VMEM_BLOCK_BYTES_NARROW = 256 * 1024


def _blocks_for(depth: int, itemsize: int):
    budget = _VMEM_BLOCK_BYTES_NARROW if depth <= 64 else _VMEM_BLOCK_BYTES
    ok = tuple(b for b in _BLOCK_CANDIDATES
               if b * max(1, depth) * itemsize <= budget)
    # always leave the smallest block available: a 128-row block at any
    # plausible head_dim fits VMEM; the budget only orders preferences
    return ok or _BLOCK_CANDIDATES[-1:]


def flash_supported(seq: int, depth: int, itemsize: int = 4) -> bool:
    """Whether the fused kernel covers this shape (depth-aware block
    divisibility + the VMEM-resident k/v budget). Beyond it, attention
    either falls back to materializing full logits or goes
    sequence-parallel via the ring path — the search uses this to price
    that choice."""
    if any(seq % b == 0 for b in _blocks_for(depth, itemsize)):
        return 2 * seq * depth * itemsize <= _VMEM_SEQ_BYTES
    return False


def _pick_block(s: int, depth: int = 64, itemsize: int = 4,
                env: str = "FLEXFLOW_FLASH_BLOCK") -> int:
    import os

    cands = _blocks_for(depth, itemsize)
    try:
        forced = int(os.environ.get(env, "0") or "0")
    except ValueError:
        forced = 0
    # tuning override: only known-safe block sizes (the per-block VMEM
    # budget was sized for _blocks_for's output; arbitrary values could
    # OOM Mosaic)
    if forced in cands and s % forced == 0:
        return forced
    if env != "FLEXFLOW_FLASH_BLOCK":
        # bwd knob unset OR invalid: inherit the main block choice (so a
        # typo'd bwd value degrades to the fwd configuration, not to a
        # third configuration nobody asked for)
        return _pick_block(s, depth, itemsize)
    for b in cands:
        if s % b == 0:
            return b
    raise ValueError(f"sequence length {s} not divisible by any of {cands} "
                     f"(head_dim {depth}, itemsize {itemsize})")


def _interpret() -> bool:
    return jax.default_backend() == "cpu"


def _params():
    from jax.experimental.pallas import tpu as pltpu

    # batch/head/q-block grid dims are independent; lets Mosaic pipeline
    # them. The class was renamed across jax releases (TPUCompilerParams
    # -> CompilerParams); accept either spelling.
    cls = getattr(pltpu, "CompilerParams", None) or \
        getattr(pltpu, "TPUCompilerParams")
    return cls(dimension_semantics=("parallel", "parallel", "arbitrary"))


# --------------------------------------------------------------------- forward
def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, *, scale, causal, block_k):
    q = q_ref[0, 0]                                # (bq, d), input dtype (MXU bf16)
    bq, d = q.shape
    sk = k_ref.shape[2]
    qi = pl.program_id(2)
    q_start = qi * bq

    if causal:
        nk_loop = (q_start + bq) // block_k        # blocks at/under the diagonal
    else:
        nk_loop = sk // block_k

    def body(ki, carry):
        m, l, acc = carry
        k = k_ref[0, 0, pl.ds(ki * block_k, block_k), :]
        v = v_ref[0, 0, pl.ds(ki * block_k, block_k), :]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        if causal:
            row = q_start + jax.lax.broadcasted_iota(jnp.int32, (bq, block_k), 0)
            col = ki * block_k + jax.lax.broadcasted_iota(jnp.int32, (bq, block_k), 1)
            s = jnp.where(row >= col, s, _NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m - m_new)
        l = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc = acc * alpha + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return m_new, l, acc

    m0 = jnp.full((bq, 1), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((bq, 1), jnp.float32)
    a0 = jnp.zeros((bq, d), jnp.float32)
    m, l, acc = jax.lax.fori_loop(0, nk_loop, body, (m0, l0, a0))
    o_ref[0, 0] = (acc / l).astype(o_ref.dtype)
    lse_ref[0, 0] = m + jnp.log(l)                 # (bq, 1)


def _fwd(q, k, v, causal, scale):
    """q: (b, h, sq, d); k/v: (b, h, sk, d) -> (o, lse)."""
    b, h, sq, d = q.shape
    sk = k.shape[2]
    bq = _pick_block(sq, d, q.dtype.itemsize)
    bk = _pick_block(sk, d, k.dtype.itemsize)
    kernel = functools.partial(_fwd_kernel, scale=scale, causal=causal, block_k=bk)
    o, lse = pl.pallas_call(
        kernel,
        grid=(b, h, sq // bq),
        in_specs=[
            pl.BlockSpec((1, 1, bq, d), lambda b_, h_, i: (b_, h_, i, 0)),
            pl.BlockSpec((1, 1, sk, d), lambda b_, h_, i: (b_, h_, 0, 0)),
            pl.BlockSpec((1, 1, sk, d), lambda b_, h_, i: (b_, h_, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, bq, d), lambda b_, h_, i: (b_, h_, i, 0)),
            # lse is (b, h, sq, 1): the trailing singleton keeps the block's
            # last-two dims TPU-tileable ((bq, 1) with 1 == full array dim)
            pl.BlockSpec((1, 1, bq, 1), lambda b_, h_, i: (b_, h_, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, h, sq, d), q.dtype),
            jax.ShapeDtypeStruct((b, h, sq, 1), jnp.float32),
        ],
        compiler_params=_params(),
        interpret=_interpret(),
    )(q, k, v)
    return o, lse


# -------------------------------------------------------------------- backward
def _dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref,
               *, scale, causal, block_k):
    q = q_ref[0, 0]                                # input dtype: MXU-rate dots
    do = do_ref[0, 0]
    lse = lse_ref[0, 0]                            # (bq, 1) f32
    delta = delta_ref[0, 0]
    bq, d = q.shape
    sk = k_ref.shape[2]
    qi = pl.program_id(2)
    q_start = qi * bq
    nk_loop = (q_start + bq) // block_k if causal else sk // block_k

    def body(ki, dq_acc):
        k = k_ref[0, 0, pl.ds(ki * block_k, block_k), :]
        v = v_ref[0, 0, pl.ds(ki * block_k, block_k), :]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        p = jnp.exp(s - lse)
        if causal:
            row = q_start + jax.lax.broadcasted_iota(jnp.int32, (bq, block_k), 0)
            col = ki * block_k + jax.lax.broadcasted_iota(jnp.int32, (bq, block_k), 1)
            p = jnp.where(row >= col, p, 0.0)
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = (p * (dp - delta) * scale).astype(k.dtype)
        return dq_acc + jax.lax.dot_general(ds, k, (((1,), (0,)), ((), ())),
                                            preferred_element_type=jnp.float32)

    dq = jax.lax.fori_loop(0, nk_loop, body, jnp.zeros((bq, d), jnp.float32))
    dq_ref[0, 0] = dq.astype(dq_ref.dtype)


def _dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dk_ref, dv_ref,
                *, scale, causal, block_q):
    k = k_ref[0, 0]                                # (bk, d), input dtype
    v = v_ref[0, 0]
    bk, d = k.shape
    sq = q_ref.shape[2]
    ki = pl.program_id(2)
    k_start = ki * bk
    nq = sq // block_q
    qi_start = k_start // block_q if causal else 0

    def body(qi, carry):
        dk_acc, dv_acc = carry
        q = q_ref[0, 0, pl.ds(qi * block_q, block_q), :]
        do = do_ref[0, 0, pl.ds(qi * block_q, block_q), :]
        lse = lse_ref[0, 0, pl.ds(qi * block_q, block_q), :]
        delta = delta_ref[0, 0, pl.ds(qi * block_q, block_q), :]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        p = jnp.exp(s - lse)                        # (bq, bk) f32
        if causal:
            row = qi * block_q + jax.lax.broadcasted_iota(jnp.int32, (block_q, bk), 0)
            col = k_start + jax.lax.broadcasted_iota(jnp.int32, (block_q, bk), 1)
            p = jnp.where(row >= col, p, 0.0)
        pc = p.astype(do.dtype)
        dv_acc = dv_acc + jax.lax.dot_general(pc, do, (((0,), (0,)), ((), ())),
                                              preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = (p * (dp - delta) * scale).astype(q.dtype)
        dk_acc = dk_acc + jax.lax.dot_general(ds, q, (((0,), (0,)), ((), ())),
                                              preferred_element_type=jnp.float32)
        return dk_acc, dv_acc

    z = jnp.zeros((bk, d), jnp.float32)
    dk, dv = jax.lax.fori_loop(qi_start, nq, body, (z, z))
    dk_ref[0, 0] = dk.astype(dk_ref.dtype)
    dv_ref[0, 0] = dv.astype(dv_ref.dtype)


def _bwd(causal, scale, res, g):
    q, k, v, o, lse = res
    b, h, sq, d = q.shape
    sk = k.shape[2]
    # FLEXFLOW_FLASH_BLOCK_BWD tunes the backward independently (the dq /
    # dkv kernels have different VMEM/recompute balance than the forward);
    # unset = inherit FLEXFLOW_FLASH_BLOCK's choice
    bq = _pick_block(sq, d, q.dtype.itemsize, env="FLEXFLOW_FLASH_BLOCK_BWD")
    bk = _pick_block(sk, d, k.dtype.itemsize, env="FLEXFLOW_FLASH_BLOCK_BWD")
    do = g.astype(jnp.float32)
    delta = jnp.sum(do * o.astype(jnp.float32), axis=-1, keepdims=True)  # (b, h, sq, 1)

    q_spec = pl.BlockSpec((1, 1, bq, d), lambda b_, h_, i: (b_, h_, i, 0))
    k_full = pl.BlockSpec((1, 1, sk, d), lambda b_, h_, i: (b_, h_, 0, 0))
    q_full = pl.BlockSpec((1, 1, sq, d), lambda b_, h_, i: (b_, h_, 0, 0))
    k_spec = pl.BlockSpec((1, 1, bk, d), lambda b_, h_, i: (b_, h_, i, 0))
    vec_q = pl.BlockSpec((1, 1, bq, 1), lambda b_, h_, i: (b_, h_, i, 0))
    vec_full = pl.BlockSpec((1, 1, sq, 1), lambda b_, h_, i: (b_, h_, 0, 0))

    dq = pl.pallas_call(
        functools.partial(_dq_kernel, scale=scale, causal=causal, block_k=bk),
        grid=(b, h, sq // bq),
        in_specs=[q_spec, k_full, k_full, q_spec, vec_q, vec_q],
        out_specs=q_spec,
        out_shape=jax.ShapeDtypeStruct((b, h, sq, d), q.dtype),
        compiler_params=_params(),
        interpret=_interpret(),
    )(q, k, v, g, lse, delta)

    dk, dv = pl.pallas_call(
        functools.partial(_dkv_kernel, scale=scale, causal=causal, block_q=bq),
        grid=(b, h, sk // bk),
        in_specs=[q_full, k_spec, k_spec, q_full, vec_full, vec_full],
        out_specs=[k_spec, k_spec],
        out_shape=[jax.ShapeDtypeStruct((b, h, sk, d), k.dtype),
                   jax.ShapeDtypeStruct((b, h, sk, d), v.dtype)],
        compiler_params=_params(),
        interpret=_interpret(),
    )(q, k, v, g, lse, delta)
    return dq, dk, dv


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def _flash(q, k, v, causal, scale):
    return _fwd(q, k, v, causal, scale)[0]


def _flash_fwd(q, k, v, causal, scale):
    o, lse = _fwd(q, k, v, causal, scale)
    return o, (q, k, v, o, lse)


_flash.defvjp(_flash_fwd, _bwd)


# ------------------------------------------------------------------ public API
def flash_attention(q, k, v, causal: bool = False, scale: float | None = None):
    """q: (b, h, sq, d), k/v: (b, h, sk, d) -> (b, h, sq, d).

    Raises ValueError when shapes don't qualify (sequence not divisible by a
    block size, causal with sq != sk) — callers fall back to the einsum path.
    """
    if q.ndim != 4 or k.ndim != 4 or v.ndim != 4:
        raise ValueError(f"expected rank-4 q/k/v, got {q.shape}/{k.shape}/{v.shape}")
    if causal and q.shape[2] != k.shape[2]:
        raise ValueError("causal flash attention requires sq == sk "
                         f"(got {q.shape[2]} vs {k.shape[2]})")
    if k.shape[2] != v.shape[2]:
        raise ValueError(f"k/v length mismatch {k.shape} vs {v.shape}")
    _pick_block(q.shape[2], q.shape[3], q.dtype.itemsize)
    _pick_block(k.shape[2], k.shape[3], k.dtype.itemsize)
    for s_, d_, it in ((q.shape[2], q.shape[3], q.dtype.itemsize),
                      (k.shape[2], k.shape[3], k.dtype.itemsize)):
        if 2 * s_ * d_ * it > _VMEM_SEQ_BYTES:
            # the Mosaic-reject precheck: shapes whose VMEM-resident
            # operands can't fit raise HERE, at trace time, where the
            # attention op's auto path catches ValueError and falls back
            # to the einsum reference path (ops/attention_ops.py) instead
            # of dying inside the backend compiler
            raise ValueError(
                f"sequence {s_} x depth {d_} exceeds the VMEM-resident budget "
                f"({_VMEM_SEQ_BYTES} bytes); use the einsum or ring path")
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    return _flash(q, k, v, causal, float(scale))


def flash_attention_qkv(q, k, v, causal: bool = False, scale: float | None = None):
    """Head-minor layout entry used by ops/attention_ops: q/k/v (b, s, h, d),
    returns (b, sq, h, d). Unsupported shapes raise ValueError."""
    qt = jnp.swapaxes(q, 1, 2)
    kt = jnp.swapaxes(k, 1, 2)
    vt = jnp.swapaxes(v, 1, 2)
    out = flash_attention(qt, kt, vt, causal=causal, scale=scale)
    return jnp.swapaxes(out, 1, 2)
