"""Fused optimizer update kernel (ISSUE 12 tentpole b): the single-pass
Adam/SGD moment kernel vs the optax chain it replaces — update and state
parity across every recognized plan, exact state-tree structure (the
checkpoint/ZeRO contract), multi-step continuation, and the plan gate."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from flexflow_tpu import AdamOptimizer, SGDOptimizer
from flexflow_tpu.kernels.fused_optim import fused_update, plan_for


def _params(seed=0):
    rng = np.random.default_rng(seed)
    # odd sizes on purpose: exercises the pad-to-(rows,128) path
    return {
        "fc": {"kernel": jnp.asarray(rng.normal(size=(33, 65)), jnp.float32),
               "bias": jnp.asarray(rng.normal(size=(65,)), jnp.float32)},
        "head": {"kernel": jnp.asarray(rng.normal(size=(7,)), jnp.float32)},
    }


def _grads(seed):
    return jax.tree_util.tree_map(
        lambda p: jnp.asarray(
            np.random.default_rng(seed + p.size).normal(size=p.shape),
            jnp.float32), _params())


OPTS = [
    pytest.param(AdamOptimizer(alpha=1e-3), id="adam"),
    pytest.param(AdamOptimizer(alpha=1e-3, weight_decay=0.01), id="adamw"),
    pytest.param(AdamOptimizer(alpha=1e-3, state_dtype="bfloat16"),
                 id="adam-bf16"),
    pytest.param(SGDOptimizer(lr=0.05), id="sgd"),
    pytest.param(SGDOptimizer(lr=0.05, momentum=0.9, nesterov=True),
                 id="sgd-nesterov"),
]


@pytest.mark.parametrize("opt", OPTS)
def test_fused_matches_optax_update_and_state(opt):
    tx = opt.to_optax()
    params = _params()
    state = tx.init(params)
    plan = plan_for(opt)
    assert plan is not None

    ref_state, fused_state = state, state
    for step in range(3):  # multi-step: the count/bias-correction advances
        grads = _grads(step)
        ref_upd, ref_state = tx.update(grads, ref_state, params)
        done = fused_update(plan, grads, fused_state, params)
        assert done is not None
        upd, fused_state = done
        # exact optax tree structure: checkpoints and ZeRO sharding
        # constraints address the state by this layout
        assert jax.tree_util.tree_structure(fused_state) == \
            jax.tree_util.tree_structure(ref_state)
        for a, b in zip(jax.tree_util.tree_leaves(upd),
                        jax.tree_util.tree_leaves(ref_upd)):
            np.testing.assert_allclose(np.asarray(a, np.float32),
                                       np.asarray(b, np.float32),
                                       atol=1e-6, rtol=1e-5)
        for a, b in zip(jax.tree_util.tree_leaves(fused_state),
                        jax.tree_util.tree_leaves(ref_state)):
            assert jnp.asarray(a).dtype == jnp.asarray(b).dtype
            np.testing.assert_allclose(np.asarray(a, np.float32),
                                       np.asarray(b, np.float32),
                                       atol=1e-6, rtol=1e-5)


def test_plan_for_rejects_unknown_optimizers():
    class CustomAdam(AdamOptimizer):
        """A subclass may override to_optax: the exact-type check must
        refuse to guess its math."""

    assert plan_for(CustomAdam(alpha=1e-3)) is None
    assert plan_for(object()) is None
    assert plan_for(AdamOptimizer(alpha=1e-3, state_dtype="float16")) is None


def test_fused_update_none_on_foreign_state():
    """A state tree without the expected moment node falls back (None)
    instead of corrupting anything."""
    opt = AdamOptimizer(alpha=1e-3)
    plan = plan_for(opt)
    params = _params()
    foreign = optax.sgd(0.1).init(params)
    assert fused_update(plan, _grads(0), foreign, params) is None
