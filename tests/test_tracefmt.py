"""ISSUE 20 — the replayable trace format's versioning contract.

tracefmt is the capacity twin's common tongue: live export, bench
generators, and the twin loader all speak it, so schema drift here
silently corrupts every downstream consumer. These tests pin the three
contract clauses (unknown version rejected, v1 forward-compatible,
malformed lines skipped + counted), the bitwise save/load round-trip,
and the legacy-rng pin that makes the refactored benches reproduce the
pre-tracefmt arrival sequences under a fixed seed.
"""

import dataclasses
import json

import numpy as np
import pytest

from flexflow_tpu.serving import tracefmt
from flexflow_tpu.serving.tracefmt import (SCHEMA_VERSION, Trace,
                                           TraceRecord, burst_records,
                                           load_trace, poisson_records,
                                           save_trace, scale_rate)


def _records(n=5):
    rng = np.random.default_rng(0)
    return poisson_records(rng, n, rate=10.0, vocab=64, prompt_len=4,
                           max_new=8, deadline_s=2.5)


# ---------------------------------------------------------- versioning
def test_unknown_schema_version_rejected(tmp_path):
    """A twin quietly mispricing a future trace is worse than refusing
    it: an unknown schema_version must raise, and the error must name
    both the alien version and the one this build reads."""
    p = tmp_path / "future.jsonl"
    p.write_text(json.dumps({"schema_version": SCHEMA_VERSION + 1,
                             "meta": {}}) + "\n")
    with pytest.raises(ValueError, match="schema_version"):
        load_trace(str(p))
    with pytest.raises(ValueError, match=str(SCHEMA_VERSION)):
        load_trace(str(p))


def test_missing_or_alien_header_rejected(tmp_path):
    """A file whose first line isn't a JSON header object (a bare
    records file, a CSV, an empty file) is not a trace."""
    for body in ("", "not json\n", "[1,2,3]\n",
                 '{"arrival_ts": 0, "tokens_in": 4, "max_tokens": 2}\n'
                 if False else '"just a string"\n'):
        p = tmp_path / "alien.jsonl"
        p.write_text(body)
        with pytest.raises(ValueError):
            load_trace(str(p))


def test_v1_records_load_forward_compatibly(tmp_path):
    """Unknown record fields from a NEWER minor writer are ignored,
    never fatal — v1 readers keep working as the schema grows."""
    p = tmp_path / "t.jsonl"
    header = {"schema_version": SCHEMA_VERSION, "meta": {"rate": 10.0}}
    rec = {"arrival_ts": 0.5, "tokens_in": 4, "max_tokens": 2,
           "some_future_field": {"nested": True}, "lora_id": 7}
    p.write_text(json.dumps(header) + "\n" + json.dumps(rec) + "\n")
    tr = load_trace(str(p))
    assert tr.skipped == 0
    assert len(tr) == 1
    assert tr.records[0].arrival_ts == 0.5
    assert tr.records[0].tokens_in == 4
    assert tr.meta == {"rate": 10.0}


def test_malformed_lines_skipped_and_counted(tmp_path):
    """One corrupt line in an hour of recorded traffic must not void
    the rest: malformed records are dropped, counted in Trace.skipped,
    and the good records around them still load."""
    p = tmp_path / "t.jsonl"
    good = {"arrival_ts": 1.0, "tokens_in": 8, "max_tokens": 4}
    lines = [
        json.dumps({"schema_version": SCHEMA_VERSION, "meta": {}}),
        json.dumps(good),
        "{truncated json",                       # unparseable
        json.dumps([1, 2, 3]),                   # not an object
        json.dumps({"tokens_in": 8, "max_tokens": 4}),  # missing field
        json.dumps({"arrival_ts": "NaNope", "tokens_in": 1,
                    "max_tokens": 1}),           # uncoercible type
        "",                                      # blank lines are fine
        json.dumps(dict(good, arrival_ts=2.0)),
    ]
    p.write_text("\n".join(lines) + "\n")
    tr = load_trace(str(p))
    assert tr.skipped == 4
    assert [r.arrival_ts for r in tr.records] == [1.0, 2.0]


# ----------------------------------------------------------- round-trip
def test_save_load_save_is_bitwise(tmp_path):
    """Serialization is deterministic (sorted keys, fixed separators):
    generate -> save -> load -> save produces identical bytes, so traces
    diff/hash cleanly as artifacts."""
    recs = _records(8)
    p1, p2 = tmp_path / "a.jsonl", tmp_path / "b.jsonl"
    save_trace(str(p1), recs, meta={"seed": 0, "rate": 10.0})
    tr = load_trace(str(p1))
    assert tr.skipped == 0
    save_trace(str(p2), tr.records, meta=tr.meta)
    assert p1.read_bytes() == p2.read_bytes()
    # and the loaded records are value-identical dataclasses
    assert tr.records == recs


def test_requests_roundtrip_preserves_shapes():
    """records -> Requests -> records is lossless for everything the
    twin prices (arrival, lengths, priority, deadline, rid, prompt)."""
    recs = _records(6)
    reqs = tracefmt.records_to_requests(recs)
    back = tracefmt.requests_to_records(reqs)
    assert back == recs


# ----------------------------------------------------------- generators
def test_poisson_records_match_legacy_inline_generator():
    """The refactored benches must reproduce the pre-tracefmt arrival
    sequences bitwise under a fixed seed: one exponential gap vector
    first, then one prompt draw per request — the exact legacy order."""
    n, rate, vocab, plen, max_new = 11, 20.0, 256, 4, 8
    rng = np.random.default_rng(42)
    arrivals = np.cumsum(rng.exponential(1.0 / rate, size=n))
    legacy = [(float(arrivals[i]),
               [int(t) for t in rng.integers(1, vocab, size=plen)])
              for i in range(n)]
    recs = poisson_records(np.random.default_rng(42), n, rate, vocab,
                           plen, max_new)
    assert [(r.arrival_ts, r.prompt) for r in recs] == legacy
    assert all(r.rid == i for i, r in enumerate(recs))


def test_burst_records_shape():
    """burst_records = steady segment then a burst_factor x tail: the
    burst rides after the steady window and arrives denser."""
    rng = np.random.default_rng(1)
    recs = burst_records(rng, 100, base_rate=2.0, burst_factor=10.0,
                         burst_frac=0.25, vocab=64, prompt_len=4,
                         max_new=4)
    steady, burst = recs[:100], recs[100:]
    assert len(burst) == 25
    assert burst[0].arrival_ts > steady[-1].arrival_ts
    ts = [r.arrival_ts for r in recs]
    assert ts == sorted(ts)
    gap_s = (steady[-1].arrival_ts - steady[0].arrival_ts) / 99
    gap_b = (burst[-1].arrival_ts - burst[0].arrival_ts) / 24
    assert gap_b < gap_s / 3  # ~10x the rate, generously bounded
    assert [r.rid for r in recs] == list(range(125))


def test_scale_rate_scales_offered_load():
    """scale_rate(records, f) is the same arrival PROCESS at f x load:
    timestamps divide by f, shapes and order are untouched. The
    capacity-curve bisection sweeps exactly this knob."""
    recs = _records(5)
    fast = scale_rate(recs, 2.0)
    for a, b in zip(recs, fast):
        assert b.arrival_ts == pytest.approx(a.arrival_ts / 2.0)
        assert (b.tokens_in, b.max_tokens, b.prompt) == \
            (a.tokens_in, a.max_tokens, a.prompt)
    # originals untouched (replace, not mutate)
    assert recs == _records(5)
    with pytest.raises(ValueError):
        scale_rate(recs, 0.0)
