"""Benchmark: GPT-2 medium training throughput on the available TPU chip(s).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...extras}.

Metric: samples/sec/chip training GPT-2 medium (BASELINE.md config #5).
vs_baseline is measured throughput relative to a hand-tuned reference anchor:
40% MFU (a strong expert-tuned single-chip GPT-2 training baseline) at the
chip's bf16 peak — vs_baseline >= 1.0 means we beat the expert anchor.

Sanity gates (round-1 postmortem: an async-dispatch artifact reported 7.4x
chip peak): the implied MFU is computed from first-principles FLOP accounting
(embedding lookups contribute zero matmul FLOPs, the lm_head is counted) and
the benchmark REFUSES to report a physically impossible number — if implied
MFU > 100% it exits non-zero instead of printing garbage. Timing fully
synchronizes on params + opt state, not just the loss scalar.
"""

from __future__ import annotations

import json
import sys
import time

import numpy as np


def _time_steps(cm, inputs, labels, iters: int, key):
    """Run `iters` chained steps, then synchronize via an actual host fetch.

    block_until_ready alone is NOT a reliable barrier under the axon TPU
    tunnel (observed returning early on a deep dispatch queue, which produced
    round 1's impossible 7.4x-peak number); float(loss) provably waits for
    the dependent computation chain."""
    import jax

    for i in range(iters):
        key = jax.random.fold_in(key, i)
        (cm.params, cm.opt_state, cm.state, loss, _) = cm.train_step(
            cm.params, cm.opt_state, cm.state, inputs, labels, key)
    jax.block_until_ready((loss, cm.params, cm.opt_state))
    return float(loss)


def _bench_model(cfg, batch, searched: bool, on_cpu: bool):
    """Build + train-bench GPT-2 under one strategy; returns samples/sec."""
    import jax

    from flexflow_tpu import AdamOptimizer, FFConfig, FFModel
    from flexflow_tpu.models import build_gpt2

    ff_cfg = FFConfig(batch_size=batch, compute_dtype="bfloat16",
                      only_data_parallel=not searched,
                      search_budget=32 if searched else 0)
    model = FFModel(ff_cfg)
    build_gpt2(model, cfg, batch=batch)
    cm = model.compile(AdamOptimizer(alpha=1e-4),
                       loss_type="sparse_categorical_crossentropy", metrics=[])
    cm.init(seed=0)

    rng = np.random.default_rng(0)
    ids = jax.device_put(rng.integers(0, cfg.vocab, size=(batch, cfg.seq)).astype(np.int32))
    pos = jax.device_put(np.tile(np.arange(cfg.seq, dtype=np.int32), (batch, 1)))
    labels = jax.device_put(rng.integers(0, cfg.vocab, size=(batch, cfg.seq)).astype(np.int32))
    key = jax.random.PRNGKey(0)

    # warmup: compile + 2 steps
    loss = _time_steps(cm, [ids, pos], labels, 2, key)
    assert np.isfinite(float(loss)), f"non-finite loss {loss}"

    iters = 3 if on_cpu else 20
    best_dt = float("inf")
    for rep in range(1 if on_cpu else 3):
        t0 = time.perf_counter()
        _time_steps(cm, [ids, pos], labels, iters, jax.random.fold_in(key, rep))
        best_dt = min(best_dt, time.perf_counter() - t0)
    return iters * batch / best_dt, best_dt / iters


def _bench_workload(build_fn, inputs_fn, loss_type, batch, iters, warmup=2):
    """Generic train-throughput bench: build, compile (DP), chained timed
    steps with full (loss, params, opt_state) sync; returns samples/sec."""
    import jax

    from flexflow_tpu import AdamOptimizer, FFConfig, FFModel

    ff_cfg = FFConfig(batch_size=batch, compute_dtype="bfloat16",
                      only_data_parallel=True)
    model = FFModel(ff_cfg)
    out = build_fn(model)
    cm = model.compile(AdamOptimizer(alpha=1e-4), loss_type=loss_type,
                       metrics=[], outputs=[out] if out is not None else None)
    cm.init(seed=0)
    xs, labels = inputs_fn()
    dx = [jax.device_put(a) for a in xs]
    dy = jax.device_put(labels)
    key = jax.random.PRNGKey(0)
    for i in range(warmup):
        cm.params, cm.opt_state, cm.state, loss, _ = cm.train_step(
            cm.params, cm.opt_state, cm.state, dx, dy, jax.random.fold_in(key, i))
    jax.block_until_ready((loss, cm.params, cm.opt_state))
    best = float("inf")
    for rep in range(3):
        t0 = time.perf_counter()
        for i in range(iters):
            cm.params, cm.opt_state, cm.state, loss, _ = cm.train_step(
                cm.params, cm.opt_state, cm.state, dx, dy,
                jax.random.fold_in(key, 100 + rep * iters + i))
        jax.block_until_ready((loss, cm.params, cm.opt_state))
        best = min(best, time.perf_counter() - t0)
    assert np.isfinite(float(loss)), loss
    return iters * batch / best


def _bench_bert(on_cpu: bool) -> float:
    """BASELINE config #3: BERT-base pretraining proxy throughput."""
    from flexflow_tpu.models import build_bert

    if on_cpu:
        batch, seq, kw = 2, 64, dict(vocab=2048, d_model=128, heads=2,
                                     layers=2, d_ff=256)
    else:
        batch, seq, kw = 8, 512, {}

    holder = {}

    def build(m):
        ins, logits = build_bert(m, batch=batch, seq=seq, **kw)
        holder["vocab"] = kw.get("vocab", 30522)
        return logits

    def inputs():
        rng = np.random.default_rng(0)
        ids = rng.integers(0, holder["vocab"], size=(batch, seq)).astype(np.int32)
        pos = np.tile(np.arange(seq, dtype=np.int32), (batch, 1))
        lab = rng.integers(0, holder["vocab"], size=(batch, seq)).astype(np.int32)
        return [ids, pos], lab

    return _bench_workload(build, inputs, "sparse_categorical_crossentropy",
                           batch, iters=2 if on_cpu else 10)


def _bench_dlrm(on_cpu: bool) -> float:
    """BASELINE config #4: DLRM click-through throughput."""
    from flexflow_tpu.models import build_dlrm

    batch = 256 if on_cpu else 4096
    tables = (10_000,) * 4 if on_cpu else (100_000,) * 8

    def build(m):
        ins, out = build_dlrm(m, batch=batch, embedding_tables=tables,
                              embedding_dim=64)
        return out

    def inputs():
        rng = np.random.default_rng(0)
        dense = rng.normal(size=(batch, 13)).astype(np.float32)
        sparse = [rng.integers(0, t, size=(batch, 1)).astype(np.int32)
                  for t in tables]
        lab = rng.uniform(size=(batch, 1)).astype(np.float32)
        return [dense] + sparse, lab

    return _bench_workload(build, inputs, "mean_squared_error", batch,
                           iters=3 if on_cpu else 20)


def _predicted_multichip_ratio():
    """Cost-model-predicted searched-vs-expert ratio for the v5p TARGET mesh
    (8 data x 4 model): both strategies costed by the same frontier DP,
    entirely analytic (no devices needed). This — not the 1-chip wall-clock
    number — is the meaningful multi-chip anchor the single-chip bench can
    produce; MULTICHIP_r04's dryrun measures the executable CPU-mesh twin."""
    from flexflow_tpu import FFConfig, FFModel
    from flexflow_tpu.models import GPT2Config, build_gpt2
    from flexflow_tpu.parallel.machine import MachineSpec
    from flexflow_tpu.search.dp import search_graph

    cfg = GPT2Config.medium()
    cfg.dropout = 0.0
    model = FFModel(FFConfig(batch_size=32))
    build_gpt2(model, cfg, batch=32)
    mach = MachineSpec(mesh_axes={"data": 8, "model": 4}, chip="v5p")
    searched = search_graph(model, mach).cost
    pins = {}
    for i in range(cfg.layers):
        pins[f"h{i}_attn"] = "tp_heads:model"
        pins[f"h{i}_mlp_up"] = "tp_col:model"
        pins[f"h{i}_mlp_down"] = "tp_row:model"
    expert = search_graph(model, mach, pins=pins).cost
    return expert / searched


def main():
    import jax

    from flexflow_tpu.models import GPT2Config
    from flexflow_tpu.parallel.machine import MachineSpec

    machine = MachineSpec.detect()
    on_cpu = jax.devices()[0].platform == "cpu"

    if on_cpu:  # CI / no-TPU fallback keeps runtime sane
        cfg = GPT2Config.tiny(seq=128)
        batch = 4
    else:
        # BASELINE config #5: GPT-2 medium, seq 1024
        cfg = GPT2Config.medium()
        batch = 8
    cfg.dropout = 0.0

    # expert strategy (hand-tuned data-parallel anchor) = the reported metric;
    # the auto-searched strategy on the same mesh gives BASELINE's second
    # north-star: searched_vs_expert (target >= 0.90)
    sps, step_dt = _bench_model(cfg, batch, searched=False, on_cpu=on_cpu)
    searched_sps, _ = _bench_model(cfg, batch, searched=True, on_cpu=on_cpu)
    bert_sps = _bench_bert(on_cpu)
    dlrm_sps = _bench_dlrm(on_cpu)
    predicted_ratio = _predicted_multichip_ratio()

    n_chips = max(1, len(jax.devices()))
    sps_chip = sps / n_chips

    flops_per_sample = cfg.flops_per_token() * cfg.seq
    achieved_flops = sps_chip * flops_per_sample
    mfu = achieved_flops / machine.flops
    if not on_cpu and mfu > 1.0:
        print(json.dumps({
            "metric": "gpt2_medium_train_samples_per_sec_per_chip",
            "value": None, "unit": "samples/s/chip", "vs_baseline": None,
            "error": f"implied MFU {mfu:.2f} > 1.0 is physically impossible; "
                     "refusing to report (timing or FLOP accounting broken)",
        }), file=sys.stderr)
        raise SystemExit(1)

    # expert anchor: 40% MFU at chip bf16 peak
    ref_sps = 0.40 * machine.flops / flops_per_sample
    print(json.dumps({
        "metric": "gpt2_medium_train_samples_per_sec_per_chip",
        "value": round(sps_chip, 3),
        "unit": "samples/s/chip",
        "vs_baseline": round(sps_chip / ref_sps, 4),
        "mfu": round(mfu, 4),
        "step_ms": round(step_dt * 1e3, 2),
        # 1-chip searched-vs-expert: the mesh has ONE device, so the search
        # has nothing to shard — this checks search/jit overhead only. The
        # multi-chip anchor is the PREDICTED ratio below (cost model on the
        # v5p 8x4 target mesh) + the dryrun's executable CPU-mesh ratio.
        "searched_vs_expert": round(searched_sps / sps, 4),
        "searched_vs_expert_note": "1-chip overhead check, not a sharding anchor",
        "predicted_multichip_searched_vs_expert": round(predicted_ratio, 4),
        "bert_samples_per_sec_per_chip": round(bert_sps / n_chips, 3),
        "dlrm_samples_per_sec_per_chip": round(dlrm_sps / n_chips, 3),
        "batch": batch,
        "seq": cfg.seq,
        "chip_peak_tflops": round(machine.flops / 1e12, 1),
        "flops_per_sample_g": round(flops_per_sample / 1e9, 1),
        "params_m": round(cfg.param_count() / 1e6, 1),
    }))


if __name__ == "__main__":
    main()
