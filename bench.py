"""Benchmark: GPT-2 training throughput on the available TPU chip(s).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Metric: samples/sec/chip training GPT-2 (BASELINE.md north star). vs_baseline
is measured throughput relative to a hand-tuned reference estimate: 40% MFU
(a strong expert-tuned single-chip GPT-2 training baseline) at the chip's
bf16 peak — i.e. vs_baseline >= 1.0 means we beat the expert anchor.
"""

from __future__ import annotations

import json
import sys
import time

import numpy as np


def main():
    import jax

    from flexflow_tpu import AdamOptimizer, FFConfig, FFModel
    from flexflow_tpu.models import GPT2Config, build_gpt2
    from flexflow_tpu.parallel.machine import MachineSpec

    machine = MachineSpec.detect()
    on_cpu = jax.devices()[0].platform == "cpu"

    # single-chip GPT-2 benchmark config: small model, seq 512
    cfg = GPT2Config(vocab=50257, seq=512, d_model=768, heads=12,
                     layers=12, dropout=0.0)
    batch = 8
    if on_cpu:  # CI / no-TPU fallback keeps runtime sane
        cfg = GPT2Config.tiny(seq=128)
        batch = 4

    ff_cfg = FFConfig(batch_size=batch, only_data_parallel=True,
                      compute_dtype="bfloat16")
    model = FFModel(ff_cfg)
    (ids_t, pos_t), _ = build_gpt2(model, cfg, batch=batch)
    cm = model.compile(AdamOptimizer(alpha=1e-4),
                       loss_type="sparse_categorical_crossentropy", metrics=[])
    cm.init(seed=0)

    rng = np.random.default_rng(0)
    ids = jax.device_put(rng.integers(0, cfg.vocab, size=(batch, cfg.seq)).astype(np.int32))
    pos = jax.device_put(np.tile(np.arange(cfg.seq, dtype=np.int32), (batch, 1)))
    labels = jax.device_put(rng.integers(0, cfg.vocab, size=(batch, cfg.seq)).astype(np.int32))
    key = jax.random.PRNGKey(0)

    def step():
        nonlocal key
        key = jax.random.fold_in(key, 1)
        (cm.params, cm.opt_state, cm.state, loss, _) = cm.train_step(
            cm.params, cm.opt_state, cm.state, [ids, pos], labels, key)
        return loss

    # warmup (compile)
    loss = step()
    jax.block_until_ready(loss)
    for _ in range(2):
        loss = step()
    jax.block_until_ready(loss)

    iters = 3 if on_cpu else 20
    t0 = time.perf_counter()
    for _ in range(iters):
        loss = step()
    jax.block_until_ready(loss)
    dt = time.perf_counter() - t0
    sps = iters * batch / dt

    n_chips = max(1, len(jax.devices()))
    sps_chip = sps / n_chips

    # expert anchor: 40% MFU at chip bf16 peak
    flops_per_sample = cfg.flops_per_token() * cfg.seq
    ref_sps = 0.40 * machine.flops / flops_per_sample
    print(json.dumps({
        "metric": "gpt2_train_samples_per_sec_per_chip",
        "value": round(sps_chip, 3),
        "unit": "samples/s/chip",
        "vs_baseline": round(sps_chip / ref_sps, 4),
    }))


if __name__ == "__main__":
    main()
