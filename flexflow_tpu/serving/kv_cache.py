"""Paged, sharded KV cache for the decode program.

Layout (per attention layer): one K pool and one V pool of shape
`[pool_pages, page_size, heads, head_dim]`, where `pool_pages =
slots * pages_per_slot + 1` — page 0 is a reserved SCRATCH page that
inactive slots (and any out-of-range write) land in, so every decode step
is a fixed-shape scatter/gather with no branches. The pools are sharded
over the heads dim along the model axis the decode strategy chose for the
attention weights (q/k/v projections write their head shard, attention
reads it — no resharding anywhere in the cache path, the layout-derivation
requirement of ISSUE 10).

Paging: a per-slot page table `[slots, pages_per_slot]` of int32 page ids
maps token position t to `table[slot, t // page_size]` at offset
`t % page_size`. Allocation assigns page ids from a host free list on
admission (only as many pages as the request's prompt + decode budget
needs — unused tail entries stay pointed at scratch) and returns them on
eviction; the device-side table is refreshed by a tiny replicated
device_put at scheduler sync points. Freed pages still hold stale K/V but
are never attended: the per-slot position mask only exposes positions
written by the CURRENT occupant.

The pools + table + per-slot position/active vectors travel through the
decode program as lowering state (`compile.build_forward`'s state →
new_state channel): `state[layer_name] = {"k", "v"}`,
`state["serve/page_table"]`, `state["serve/pos"]`, `state["serve/active"]`.

Host cold tier (--kv-host-pages > 0): causal decode streams a slot's whole
committed working set every step, so pages cannot go cold while their slot
decodes — the tier works at SLOT granularity. `spill` parks an active slot:
its pages' K/V move to pinned host buffers (`jax.device_get`), the device
pages return to the free list, and the slot deactivates with its position
preserved. `prefetch` issues the host→HBM copy for a parked slot (async
`jax.device_put` + pool scatter — dispatch returns immediately, the copy
rides the dataflow edge into the next decode step, never a silent block);
`join` reactivates the slot and classifies the rejoin as a prefetch hit
(issued ≥ prefetch-ahead steps early) or a counted stall. Host pages come
from their own free list, so `admit`/`evict` capacity accounting spans
both tiers.
"""

from __future__ import annotations

from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from flexflow_tpu.search.cost_model import KVCacheSpec

PAGE_TABLE_KEY = "serve/page_table"
POS_KEY = "serve/pos"
ACTIVE_KEY = "serve/active"


def kv_quantize(x):
    """Symmetric per-(position, head) int8 quantization over head_dim:
    `scale = max|x| / 127` along the last axis, values rounded into
    [-127, 127]. Returns (int8 values, f32 scales) with the scales one
    rank lower — the per-page-entry-per-head arrays the quantized pools
    store next to the values. The scale floor keeps all-zero rows (fresh
    pages, padding routed to scratch) exactly representable as zeros."""
    x = x.astype(jnp.float32)
    amax = jnp.max(jnp.abs(x), axis=-1)
    scale = jnp.maximum(amax, 1e-8) / 127.0
    q = jnp.clip(jnp.round(x / scale[..., None]), -127, 127).astype(jnp.int8)
    return q, scale


def kv_dequantize(q, scale):
    """Inverse of kv_quantize: f32 values from int8 + per-row scales."""
    return q.astype(jnp.float32) * scale[..., None]


class KVPoolExhausted(Exception):
    """`admit` could not allocate the requested pages: the free list is
    shorter than the request's prompt + decode budget. Deliberately NOT a
    RuntimeError — pool exhaustion is backpressure, not a transient fault,
    so `run_resilient`'s retry filter must let it surface immediately to
    the scheduler's shed-or-queue path instead of burning backoff sleeps
    on a condition only an eviction can clear."""

    def __init__(self, slot: int, need: int, have: int):
        super().__init__(
            f"KV pool exhausted admitting slot {slot}: need {need} pages, "
            f"{have} free")
        self.slot = slot
        self.need = need
        self.have = have


@jax.jit
def _commit_prefill(cache_state, kv_state, slot_ids, lengths):
    """Scatter prefilled per-head K/V (`[Bp, S, h, d]` per layer, from the
    prefill program's kv_out state) into the pools of the slots in
    `slot_ids`. Positions >= lengths[r] (right padding) and positions past
    the slot's allocated pages are routed to the scratch page."""
    new = dict(cache_state)
    pt = cache_state[PAGE_TABLE_KEY]
    for name, kv in kv_state.items():
        kh, vh = kv["k"], kv["v"]
        pool_k = cache_state[name]["k"]
        page = pool_k.shape[1]
        s = kh.shape[1]
        pages = pt[slot_ids]                      # [Bp, pages_per_slot]
        t = jnp.arange(s)
        pg = t // page                            # [S]
        in_range = pg < pages.shape[1]
        pageix = jnp.where(in_range[None, :],
                           pages[:, jnp.minimum(pg, pages.shape[1] - 1)], 0)
        valid = t[None, :] < lengths[:, None]
        pageix = jnp.where(valid, pageix, 0)      # padding -> scratch
        off = jnp.broadcast_to(t % page, pageix.shape)
        if "k_scale" in cache_state[name]:
            # quantized pools: scatter int8 values + per-(entry, head) scales
            qk, ks = kv_quantize(kh)
            qv, vs = kv_quantize(vh)
            new[name] = {
                "k": pool_k.at[pageix, off].set(qk),
                "v": cache_state[name]["v"].at[pageix, off].set(qv),
                "k_scale": cache_state[name]["k_scale"].at[pageix, off].set(ks),
                "v_scale": cache_state[name]["v_scale"].at[pageix, off].set(vs),
            }
        else:
            new[name] = {
                "k": pool_k.at[pageix, off].set(kh.astype(pool_k.dtype)),
                "v": cache_state[name]["v"].at[pageix, off].set(
                    vh.astype(pool_k.dtype)),
            }
    return new


class PagedKVCache:
    """Device-resident paged KV pools + host-side page accounting."""

    def __init__(self, spec: KVCacheSpec, attn_layers: List[str],
                 mesh: Optional[Mesh] = None, heads_axis=None,
                 dtype=jnp.float32, quantized: bool = False, machine=None):
        self.spec = spec
        self.machine = machine  # host_bw source for transfer pricing rows
        self.attn_layers = list(attn_layers)
        self.mesh = mesh
        self.heads_axis = None
        self.quantized = bool(quantized)
        pool_pspec = PartitionSpec()
        scale_pspec = PartitionSpec()
        if mesh is not None and heads_axis is not None:
            axes = (heads_axis,) if isinstance(heads_axis, str) \
                else tuple(heads_axis)
            deg = 1
            for a in axes:
                deg *= mesh.shape.get(a, 1)
            if all(a in mesh.shape for a in axes) and spec.heads % deg == 0:
                self.heads_axis = heads_axis
                pool_pspec = PartitionSpec(None, None, heads_axis, None)
                scale_pspec = PartitionSpec(None, None, heads_axis)
        self._pool_sharding = (NamedSharding(mesh, pool_pspec)
                               if mesh is not None else None)
        self._scale_sharding = (NamedSharding(mesh, scale_pspec)
                                if mesh is not None else None)
        self._repl = (NamedSharding(mesh, PartitionSpec())
                      if mesh is not None else None)
        shape = (spec.pool_pages, spec.page_size, spec.heads, spec.head_dim)

        def pool():
            z = jnp.zeros(shape, jnp.int8 if self.quantized else dtype)
            return (jax.device_put(z, self._pool_sharding)
                    if self._pool_sharding is not None else z)

        def scales():
            # per-(page entry, head) f32 scales, sharded like the pools'
            # heads dim so the quantized cache needs no resharding either
            z = jnp.zeros(shape[:3], jnp.float32)
            return (jax.device_put(z, self._scale_sharding)
                    if self._scale_sharding is not None else z)

        def layer_state():
            st = {"k": pool(), "v": pool()}
            if self.quantized:
                st["k_scale"] = scales()
                st["v_scale"] = scales()
            return st

        self.state: Dict = {n: layer_state() for n in self.attn_layers}
        # host mirrors (authoritative at scheduler sync points)
        self._table = np.zeros((spec.slots, spec.pages_per_slot), np.int32)
        self._pos = np.zeros((spec.slots,), np.int32)
        self._active = np.zeros((spec.slots,), np.int32)
        self.free_pages: List[int] = list(range(1, spec.pool_pages))
        self._slot_pages: Dict[int, List[int]] = {}
        # host cold tier: per-layer pinned buffers shaped like the pools
        # minus the page dim ([host_pages, page_size, heads, head_dim] for
        # values, [host_pages, page_size, heads] for quantized scales)
        self.host_pages = int(spec.host_pages)
        self._host: Dict[str, Dict[str, np.ndarray]] = {}
        if self.host_pages:
            for n in self.attn_layers:
                self._host[n] = {
                    key: np.zeros((self.host_pages,) + tuple(leaf.shape[1:]),
                                  leaf.dtype)
                    for key, leaf in self.state[n].items()}
        self.free_host_pages: List[int] = list(range(self.host_pages))
        self._cold: Dict[int, List[int]] = {}   # parked slot -> host page ids
        self._inflight: Dict[int, int] = {}     # slot -> prefetch issue step
        self.tier_counters: Dict[str, int] = {
            "kv_spills": 0, "kv_refills": 0, "kv_prefetch_hits": 0,
            "kv_prefetch_stalls": 0, "kv_spilled_bytes": 0,
            "kv_refilled_bytes": 0, "kv_handoffs": 0, "kv_handoff_bytes": 0}
        self._push_tables()

    # ------------------------------------------------------------ host ops
    def _put_repl(self, arr):
        x = jnp.asarray(arr)
        return jax.device_put(x, self._repl) if self._repl is not None else x

    def _push_tables(self) -> None:
        self.state[PAGE_TABLE_KEY] = self._put_repl(self._table)
        self.state[POS_KEY] = self._put_repl(self._pos)
        self.state[ACTIVE_KEY] = self._put_repl(self._active)

    def free_slots(self) -> List[int]:
        # parked (cold/inflight) slots are inactive on device but occupied:
        # their KV lives in the host tier under the same slot id
        return [i for i in range(self.spec.slots)
                if not self._active[i] and i not in self._cold]

    def pages_needed(self, total_tokens: int) -> int:
        cap = min(int(total_tokens), self.spec.padded_len)
        return -(-cap // self.spec.page_size)

    def can_admit(self, total_tokens: int) -> bool:
        return len(self.free_pages) >= self.pages_needed(total_tokens)

    def capacity_pages(self) -> int:
        """Total data pages across BOTH tiers — the figure `prompt_too_long`
        and admission shedding must compare against (ISSUE 16: capacity
        spans HBM + host, not HBM-only)."""
        return (self.spec.pool_pages - 1) + self.host_pages

    def total_free_pages(self) -> int:
        return len(self.free_pages) + len(self.free_host_pages)

    def admit(self, slot: int, prompt_len: int, total_tokens: int) -> bool:
        """Assign pages for a sequence that will hold up to `total_tokens`
        positions (prompt + decode budget + dispatch-ahead headroom); the
        slot's position starts at `prompt_len` (the index the first decode
        step writes). Raises `KVPoolExhausted` when the free list is short
        — the scheduler's shed-or-queue path decides whether the request
        waits (backpressure) or is shed, instead of a bare free-list
        IndexError mid-drain."""
        if self._active[slot] or slot in self._cold:
            raise ValueError(f"slot {slot} is occupied")
        need = self.pages_needed(total_tokens)
        if len(self.free_pages) < need:
            raise KVPoolExhausted(slot, need, len(self.free_pages))
        pages = [self.free_pages.pop() for _ in range(need)]
        self._slot_pages[slot] = pages
        row = np.zeros(self.spec.pages_per_slot, np.int32)
        row[:need] = pages
        self._table[slot] = row
        self._pos[slot] = prompt_len
        self._active[slot] = 1
        return True

    def evict(self, slot: int) -> None:
        """Return the slot's pages to the free list(s); stale pool contents
        are never attended (position mask) and get overwritten on reuse.
        A parked slot's pages live in the host tier — those return to the
        host free list instead."""
        self.free_pages.extend(self._slot_pages.pop(slot, []))
        self.free_host_pages.extend(self._cold.pop(slot, []))
        self._inflight.pop(slot, None)
        self._table[slot] = 0
        self._pos[slot] = 0
        self._active[slot] = 0

    def sync_after(self, decode_steps: int,
                   advances: Optional[np.ndarray] = None) -> None:
        """Host mirror of the device-side position increments: each decode
        step advanced every active slot by one. Called at scheduler sync
        points BEFORE admissions/evictions mutate the mirrors. `advances`
        (per-slot committed step counts) masks finished slots: a request
        that hit EOS mid-window only advances to its finish position, so
        tokens speculatively decoded past the finish line never accrue to
        its committed KV extent."""
        if advances is not None:
            self._pos += np.asarray(advances, np.int32) * self._active
        else:
            self._pos += self._active * int(decode_steps)

    def push(self) -> None:
        """Publish the host mirrors to the device state (after a batch of
        admissions/evictions)."""
        self._push_tables()

    # ------------------------------------------------------- host tier ops
    def parked_slots(self) -> List[int]:
        """Slots whose KV sits in the host tier with no prefetch in flight
        — the scheduler's rotation candidates."""
        return [s for s in self._cold if s not in self._inflight]

    def can_spill(self, slot: int) -> bool:
        return bool(self.host_pages) and bool(self._active[slot]) and \
            len(self.free_host_pages) >= len(self._slot_pages.get(slot, []))

    def _transfer_row(self, direction: str, pages: int, measured_s: float) -> None:
        """Emit one `op/attr` telemetry row for a tier transfer, shaped like
        the per-op attribution rows: the learned cost model refits a
        `kv_transfer` coefficient from these exactly as it refits any op
        kind (features carry the shapes + machine fingerprint; predicted_s
        is the host-link roofline the refit corrects)."""
        from flexflow_tpu import telemetry as tel
        from flexflow_tpu.attribution import OP_EVENT, feature_key
        from flexflow_tpu.search import memo
        moved = self.spec.layers * pages * self.spec.page_bytes()
        host_bw = getattr(self.machine, "host_bw", 0.0) or 16e9
        predicted = moved / host_bw
        features = {
            "op": "kv_transfer",
            "in_shapes": [[pages, self.spec.page_size, self.spec.heads,
                           self.spec.head_dim]],
            "out_shapes": [[pages, self.spec.page_size, self.spec.heads,
                            self.spec.head_dim]],
            "weight_shapes": [],
            "dtype": "int8" if self.quantized else "float32",
            "params": 0,
            "layout": direction,
            "sharding": {"out": [], "weights": []},
            "machine": (memo.machine_fingerprint(self.machine)
                        if self.machine is not None else ()),
        }
        tel.event(OP_EVENT, cat="op", layer=f"kv_cache/{direction}",
                  op="kv_transfer", candidate=direction,
                  predicted_s=predicted, measured_s=measured_s,
                  attributed_s=measured_s, roofline_s=predicted,
                  bound="host_bw", mfu=0.0, mfu_ceiling=0.0,
                  key=feature_key(features), features=features,
                  source="serve", bytes=moved)

    def spill(self, slot: int, decode_step: int) -> None:
        """Park an active slot: gather its pages from every layer's pools
        to the host buffers (one `jax.device_get` per leaf), return the
        device pages, and deactivate the slot keeping its position. The
        caller (scheduler) batches `push()` after a rotation round."""
        import time as _time
        from flexflow_tpu import telemetry as tel
        if not self.can_spill(slot):
            raise ValueError(f"cannot spill slot {slot}")
        pages = self._slot_pages.pop(slot)
        host_ids = [self.free_host_pages.pop() for _ in pages]
        idx = jnp.asarray(np.asarray(pages, np.int32))
        t0 = _time.perf_counter()
        with tel.span("serve/kv_spill", cat="serve", slot=int(slot),
                      pages=len(pages)):
            for n in self.attn_layers:
                for key, leaf in self.state[n].items():
                    rows = jax.device_get(leaf[idx])
                    self._host[n][key][host_ids] = rows
        self.free_pages.extend(pages)
        self._cold[slot] = host_ids
        self._table[slot] = 0
        self._active[slot] = 0
        moved = self.spec.layers * len(pages) * self.spec.page_bytes()
        self.tier_counters["kv_spills"] += 1
        self.tier_counters["kv_spilled_bytes"] += moved
        self._transfer_row("spill", len(pages), _time.perf_counter() - t0)

    def prefetch(self, slot: int, decode_step: int) -> bool:
        """Issue the host→HBM refill for a parked slot: allocate device
        pages, dispatch the async copy + pool scatter (jax returns before
        the transfer lands — the decode step that first reads these pages
        waits on the dataflow edge, never on a host sync), and restore the
        slot's table row. The slot stays INACTIVE until `join` so the hit/
        stall ledger reflects when the scheduler actually needed it.
        Returns False (no-op) when the device free list can't cover it."""
        import time as _time
        from flexflow_tpu import telemetry as tel
        host_ids = self._cold.get(slot)
        if host_ids is None or slot in self._inflight:
            raise ValueError(f"slot {slot} is not parked")
        need = len(host_ids)
        if len(self.free_pages) < need:
            return False
        pages = [self.free_pages.pop() for _ in range(need)]
        idx = jnp.asarray(np.asarray(pages, np.int32))
        t0 = _time.perf_counter()
        with tel.span("serve/kv_prefetch", cat="serve", slot=int(slot),
                      pages=need, step=int(decode_step)):
            for n in self.attn_layers:
                st = dict(self.state[n])
                for key, leaf in st.items():
                    rows = jnp.asarray(self._host[n][key][host_ids])
                    sh = (self._pool_sharding if leaf.ndim == 4
                          else self._scale_sharding)
                    if sh is not None:
                        rows = jax.device_put(rows, sh)
                    st[key] = leaf.at[idx].set(rows.astype(leaf.dtype))
                self.state[n] = st
        row = np.zeros(self.spec.pages_per_slot, np.int32)
        row[:need] = pages
        self._table[slot] = row
        self._slot_pages[slot] = pages
        self._inflight[slot] = int(decode_step)
        moved = self.spec.layers * need * self.spec.page_bytes()
        self.tier_counters["kv_refills"] += 1
        self.tier_counters["kv_refilled_bytes"] += moved
        self._transfer_row("prefetch", need, _time.perf_counter() - t0)
        return True

    def join(self, slot: int, decode_step: int, prefetch_ahead: int) -> bool:
        """Reactivate a slot whose refill was issued by `prefetch`. Returns
        True when the rejoin STALLED: the copy was issued fewer than
        `prefetch_ahead` decode steps ago, so by the tier's own pricing
        model the transfer had not had time to hide behind decode compute.
        Stalls are counted, never silent (ISSUE 16)."""
        issued = self._inflight.pop(slot, None)
        if issued is None:
            raise ValueError(f"slot {slot} has no prefetch in flight")
        self.free_host_pages.extend(self._cold.pop(slot))
        self._active[slot] = 1
        stalled = (int(decode_step) - issued) < max(1, int(prefetch_ahead))
        if stalled:
            self.tier_counters["kv_prefetch_stalls"] += 1
        else:
            self.tier_counters["kv_prefetch_hits"] += 1
        return stalled

    # ------------------------------------------------------ replica handoff
    def export_parked(self, slot: int) -> Dict:
        """Serialize a PARKED slot's host-tier K/V + committed position for
        a cross-replica handoff (prefill/decode disaggregation, ISSUE 18):
        the prefill replica spills the slot after commit, exports it here,
        evicts, and the fleet delivers the payload to a decode replica's
        `import_parked`. Non-destructive — the caller evicts afterwards."""
        host_ids = self._cold.get(slot)
        if host_ids is None:
            raise ValueError(f"slot {slot} is not parked (spill it first)")
        return {
            "pos": int(self._pos[slot]),
            "pages": len(host_ids),
            "layers": {n: {key: buf[host_ids].copy()
                           for key, buf in self._host[n].items()}
                       for n in self.attn_layers},
        }

    def can_import(self, payload: Dict) -> bool:
        return bool(self.host_pages) and \
            len(self.free_host_pages) >= int(payload["pages"])

    def import_parked(self, slot: int, payload: Dict) -> None:
        """Adopt a handed-off slot into this cache's host tier (the decode
        side of the disaggregated handoff). The slot lands PARKED with its
        position preserved, so the ordinary rotation (prefetch + join)
        carries it into HBM — the handoff rides the exact spill/prefetch
        path and stays bitwise-identical to a colocated prefill. The copy
        is priced and emitted as a `kv_transfer` op/attr row (direction
        "handoff") so the learned model refits the DCN/host link like any
        other op. Raises `KVPoolExhausted` when the host free list is
        short — backpressure, the fleet retries the delivery."""
        import time as _time
        if self._active[slot] or slot in self._cold:
            raise ValueError(f"slot {slot} is occupied")
        need = int(payload["pages"])
        if not self.can_import(payload):
            raise KVPoolExhausted(slot, need, len(self.free_host_pages))
        t0 = _time.perf_counter()
        host_ids = [self.free_host_pages.pop() for _ in range(need)]
        for n in self.attn_layers:
            for key, rows in payload["layers"][n].items():
                self._host[n][key][host_ids] = rows
        self._cold[slot] = host_ids
        self._pos[slot] = int(payload["pos"])
        self._table[slot] = 0
        self._active[slot] = 0
        moved = self.spec.layers * need * self.spec.page_bytes()
        self.tier_counters["kv_handoffs"] += 1
        self.tier_counters["kv_handoff_bytes"] += moved
        self._transfer_row("handoff", need, _time.perf_counter() - t0)

    def tier_stats(self) -> Dict[str, int]:
        """Counters + occupancy snapshot for telemetry/monitoring."""
        hot = (self.spec.pool_pages - 1) - len(self.free_pages)
        cold = self.host_pages - len(self.free_host_pages)
        out = dict(self.tier_counters)
        out.update(kv_hot_pages=hot, kv_cold_pages=cold,
                   kv_parked_slots=len(self._cold),
                   kv_host_pages_total=self.host_pages)
        return out

    def host_bytes(self) -> int:
        """Cold-tier buffer bytes actually allocated on the host."""
        return sum(int(buf.nbytes) for layer in self._host.values()
                   for buf in layer.values())

    # ---------------------------------------------------------- device ops
    def commit_prefill(self, kv_state, slot_ids, lengths) -> None:
        """Write the prefill program's captured K/V into the pools."""
        self.state = _commit_prefill(
            self.state, {n: kv_state[n] for n in self.attn_layers},
            self._put_repl(np.asarray(slot_ids, np.int32)),
            self._put_repl(np.asarray(lengths, np.int32)))

    def adopt(self, new_state) -> None:
        """Take ownership of the state returned by a decode step."""
        self.state = new_state

    def device_bytes(self) -> int:
        """Pool bytes resident on device 0 (the measured side of the
        KV-cache watermark accounting)."""
        dev = jax.devices()[0]
        total = 0
        for n in self.attn_layers:
            # every leaf of the layer's cache state — values AND, for a
            # quantized cache, the per-(entry, head) scale arrays
            for leaf in self.state[n].values():
                shards = getattr(leaf, "addressable_shards", None)
                if shards is None:
                    total += int(leaf.nbytes)
                else:
                    total += sum(s.data.nbytes for s in shards
                                 if s.device == dev)
        return total


# -------------------------------------------------- prefetch-ahead autotune
def learned_kv_transfer_seconds(cfg, spec: KVCacheSpec,
                                quantized: bool = False, machine=None,
                                pages: Optional[int] = None
                                ) -> Optional[float]:
    """Learned seconds for one slot-sized host↔HBM transfer, or None when
    no learned model resolves a `kv_transfer` prediction (no model file on
    the resolution chain, or the model never saw the kind). Features are
    built exactly like `PagedKVCache._transfer_row` emits them, so the
    coefficient refit from serving telemetry prices this query."""
    import os
    try:
        from flexflow_tpu.search.learned_cost import (LearnedCostModel,
                                                      resolve_model_path)
        from flexflow_tpu.search import memo
    except ImportError:
        return None
    path = resolve_model_path(cfg)
    if not path or not os.path.isfile(path):
        return None
    try:
        model = LearnedCostModel.load(path)
    except Exception:  # noqa: BLE001 — a corrupt model never breaks serving
        return None
    n_pages = int(pages if pages is not None else spec.pages_per_slot)
    moved = spec.layers * n_pages * spec.page_bytes()
    host_bw = getattr(machine, "host_bw", 0.0) or 16e9
    predicted = moved / host_bw
    features = {
        "op": "kv_transfer",
        "in_shapes": [[n_pages, spec.page_size, spec.heads, spec.head_dim]],
        "out_shapes": [[n_pages, spec.page_size, spec.heads, spec.head_dim]],
        "weight_shapes": [],
        "dtype": "int8" if quantized else "float32",
        "params": 0,
        "layout": "prefetch",
        "sharding": {"out": [], "weights": []},
        "machine": (memo.machine_fingerprint(machine)
                    if machine is not None else ()),
    }
    try:
        return model.predict_features(features, predicted_s=predicted,
                                      roofline_s=predicted)
    except Exception:  # noqa: BLE001
        return None


def derive_prefetch_ahead(transfer_s: Optional[float],
                          decode_step_s: Optional[float],
                          fallback: int) -> int:
    """The rotation lead (in decode steps) that hides one slot refill
    behind decode compute: ceil(learned transfer time / decode step time),
    clamped to [1, 64]. Falls back to the `--kv-prefetch-ahead` flag value
    when either side of the ratio is unavailable — the flag is the
    fallback, not the authority (ISSUE 18 satellite)."""
    if not transfer_s or not decode_step_s or decode_step_s <= 0:
        return max(1, int(fallback))
    return max(1, min(64, -(-int(transfer_s * 1e9)
                            // max(1, int(decode_step_s * 1e9)))))
