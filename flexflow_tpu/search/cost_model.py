"""Analytic TPU cost model.

Reference analog: the Simulator + MachineModel stack (include/flexflow/
simulator.h:212-778, src/runtime/simulator.cc) which replays a task graph of
measured per-op costs over a modeled NVLink/PCIe/NIC topology. The TPU model
is deliberately simpler and closed-form (the scaling-book recipe):

  compute time  = max(flops / MXU rate, HBM bytes / HBM bw)   (roofline)
  all_gather    = (k-1)/k * full_bytes / axis_bw
  all_reduce    = 2 * (k-1)/k * bytes / axis_bw     (reduce-scatter+all-gather)
  all_to_all    = (k-1)/k * shard_bytes / axis_bw
  DCN axes use dcn_bw instead of ICI bw.

Per-op measured calibration (the inner_measure_operator_cost analog,
reference src/runtime/model.cu:38-74) is in flexflow_tpu/search/measure.py and
replaces the roofline term when enabled.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Dict, List, Optional, Sequence, Tuple

from flexflow_tpu.core.tensor import TensorSpec
from flexflow_tpu.parallel.machine import MachineSpec
from flexflow_tpu.parallel.sharding import DimSharding
from flexflow_tpu.search import memo


@dataclasses.dataclass(frozen=True)
class OptMemSpec:
    """What the optimizer REALLY costs per parameter — the search's memory
    model for persistent weight state (params + grads + moments). The
    legacy accounting (opt_mem=None throughout the search) charged every
    weight 4x its own bytes: param + grad + two f32 moments. This spec
    replaces that with the optimizer's actual shape: `moments` moment
    tensors stored at `state_itemsize` bytes/elem (bf16 Adam moments are 2,
    not 4), divided by the ZeRO data-axis degree when `zero_axes` is set
    (compiler/compile.py shards the moments over those axes — see
    _zero_moment_pspec; zero_divisor mirrors its placement rule)."""

    moments: int = 2
    state_itemsize: int = 4
    zero_axes: Tuple[str, ...] = ()

    def fingerprint(self) -> tuple:
        return (self.moments, self.state_itemsize, self.zero_axes)


def opt_mem_spec(optimizer, cfg, machine: MachineSpec) -> Optional[OptMemSpec]:
    """Build the search's optimizer-memory model from the compile-time
    optimizer + config. None (no optimizer known) keeps the legacy 4x
    accounting so direct search_graph callers are unaffected."""
    if optimizer is None:
        return None
    zero_axes: Tuple[str, ...] = ()
    if getattr(cfg, "zero_sharding", "off") != "off":
        from flexflow_tpu.search.candidates import _batch_axes

        zero_axes = tuple(a for a in _batch_axes(machine)
                          if machine.mesh_axes.get(a, 1) > 1)
    return OptMemSpec(moments=optimizer.moment_count(),
                      state_itemsize=optimizer.moment_itemsize(),
                      zero_axes=zero_axes)


@dataclasses.dataclass(frozen=True)
class KVCacheSpec:
    """Paged KV-cache geometry for the serving (decode) search — the memory
    term that does NOT exist at training time. The decode program holds, per
    attention layer, a key pool and a value pool of `slots * pages_per_slot`
    fixed-size pages (+ one scratch page inactive slots write into), each
    page holding `page_size` token positions of (heads, head_dim) vectors.
    The pools are sharded over the heads dim along the model axis the decode
    strategy picked for the attention weights, so `per_device_bytes` divides
    by that degree. The serving search subtracts this from the HBM cap
    (compile_serving) and the runtime reports it in memory_stats() next to
    the measured watermark."""

    layers: int          # attention layers holding a cache
    heads: int
    head_dim: int
    slots: int           # concurrent decode slots (max_batch_slots)
    pages_per_slot: int
    page_size: int       # token positions per page
    itemsize: int = 4
    # quantized pools (--kv-cache-dtype int8): bytes of the per-page-entry
    # per-head scale factor stored NEXT TO each (page_size, heads) row of
    # int8 values — 0 for unquantized caches, 4 (one f32) for int8
    scale_itemsize: int = 0
    # tiered cache (ISSUE 16): a host-memory cold tier of `host_pages`
    # pages per pool next to a device pool of `device_pages` data pages
    # (0 = the untiered default slots * pages_per_slot — bitwise the old
    # geometry). With a host tier the device pool may be SMALLER than
    # slots * pages_per_slot: parked slots' pages live on host and the
    # scheduler rotates a hot subset through HBM, which is how servable
    # context grows at fixed HBM-page budget.
    host_pages: int = 0
    device_pages: int = 0

    @property
    def padded_len(self) -> int:
        """Max cached positions per sequence (page-rounded)."""
        return self.pages_per_slot * self.page_size

    @property
    def pool_pages(self) -> int:
        """Pages in one DEVICE pool: the data pages (device_pages when a
        host tier shrinks HBM, else every slot's worth) plus scratch."""
        return (self.device_pages or self.slots * self.pages_per_slot) + 1

    def page_bytes(self) -> int:
        """K + V bytes of ONE page of ONE layer (the unit the tier moves:
        spill/prefetch copy whole pages, values plus quantized scales)."""
        return (2 * self.page_size * self.heads
                * (self.head_dim * self.itemsize + self.scale_itemsize))

    def layer_bytes(self) -> int:
        """K + V pool bytes for ONE attention layer (unsharded), including
        the per-(page entry, head) scale arrays of a quantized pool."""
        return self.pool_pages * self.page_bytes()

    def total_bytes(self) -> int:
        return self.layers * self.layer_bytes()

    def per_device_bytes(self, model_degree: int = 1) -> int:
        """Resident bytes per device with the heads dim sharded
        `model_degree` ways (1 = replicated pools)."""
        return self.total_bytes() // max(1, model_degree)

    def step_read_bytes(self, model_degree: int = 1) -> int:
        """HBM traffic ONE decode step adds per device: the full live K/V
        working set streams through the attention — the bandwidth term the
        decode cost_fn charges on top of the weight streaming."""
        return self.total_bytes() // max(1, model_degree)

    def slot_bytes(self) -> int:
        """Worst-case K/V bytes of ONE slot across all layers — the
        payload a full spill or refill of a parked slot moves over the
        host link."""
        return self.layers * self.pages_per_slot * self.page_bytes()

    def host_bytes(self) -> int:
        """Cold-tier capacity bytes (all layers; 0 without a host tier)."""
        return self.layers * self.host_pages * self.page_bytes()

    def fingerprint(self) -> tuple:
        return (self.layers, self.heads, self.head_dim, self.slots,
                self.pages_per_slot, self.page_size, self.itemsize,
                self.scale_itemsize, self.host_pages, self.device_pages)


def zero_divisor(spec: TensorSpec, dims: Sequence[DimSharding],
                 machine: MachineSpec, zero_axes: Sequence[str]) -> int:
    """Degree the ZeRO runtime actually divides this weight's moments by.
    MIRRORS compiler/compile.py _zero_moment_pspec: the moments take the
    weight's own layout plus the full data-axis degree on the FIRST
    unsharded dim it divides; a weight with no such dim keeps replicated
    moments (divisor 1), and a weight already sharded over a data axis
    gains nothing."""
    if not zero_axes:
        return 1
    nd = spec.ndim
    dims = list(dims or [])
    dims += [None] * (nd - len(dims))
    used = {a for d in dims for a in _axes_of(d)}
    if used & set(zero_axes):
        return 1
    deg = axis_degree(zero_axes, machine)
    if deg <= 1:
        return 1
    for i in range(nd):
        if not _axes_of(dims[i]) and spec.shape[i] % deg == 0:
            return deg
    return 1


def _axes_of(d: DimSharding) -> tuple:
    if d is None:
        return ()
    return (d,) if isinstance(d, str) else tuple(d)


def dims_degree(dims: Sequence[DimSharding], machine: MachineSpec) -> int:
    deg = 1
    for d in dims or ():
        for a in _axes_of(d):
            deg *= machine.mesh_axes.get(a, 1)
    return deg


def shard_bytes(spec: TensorSpec, dims: Sequence[DimSharding], machine: MachineSpec) -> int:
    return spec.size_bytes // max(1, dims_degree(dims, machine))


def axis_degree(axes, machine: MachineSpec) -> int:
    deg = 1
    for a in axes:
        deg *= machine.mesh_axes.get(a, 1)
    return deg


def _hier_gather_time(full_bytes: float, axes, machine: MachineSpec) -> float:
    """Hierarchical multi-axis all-gather: one ring stage per axis, each
    sending the accumulated shard (k_i - 1) hops at that axis's effective
    bandwidth (reference NetworkedMachineModel's routed multi-hop cost,
    machine_model.cc — here closed-form per torus axis). Axes are staged
    fastest-first (DCN last), which is both the optimal schedule and a
    CANONICAL order — the cost must not depend on set-iteration order of
    the caller (string hashing is per-process randomized).
    Reduces to (k-1)/k * bytes / bw for a single axis."""
    k_total = axis_degree(axes, machine)
    if k_total <= 1:
        return 0.0
    staged = sorted((a for a in axes if machine.mesh_axes.get(a, 1) > 1),
                    key=lambda a: -machine.axis_bw_eff(a))
    shard = full_bytes / k_total
    t = 0.0
    for a in staged:
        k = machine.mesh_axes[a]
        t += (k - 1) * shard / machine.axis_bw_eff(a)
        shard *= k
    return t


def all_gather_time(full_bytes: float, axes, machine: MachineSpec) -> float:
    return _hier_gather_time(full_bytes, axes, machine)


def reduce_scatter_time(bytes_: float, axes, machine: MachineSpec) -> float:
    # ring reduce-scatter moves the same (k-1)/k * bytes as an all-gather,
    # in the opposite direction
    return _hier_gather_time(bytes_, axes, machine)


def all_reduce_time(bytes_: float, axes, machine: MachineSpec) -> float:
    # reduce-scatter down + all-gather up, each hierarchical
    return reduce_scatter_time(bytes_, axes, machine) \
        + all_gather_time(bytes_, axes, machine)


def all_to_all_time(shard_bytes_: float, axes, machine: MachineSpec) -> float:
    k = axis_degree(axes, machine)
    if k <= 1:
        return 0.0
    bw = min(machine.axis_bw_eff(a) for a in axes if machine.mesh_axes.get(a, 1) > 1)
    return (k - 1) / k * shard_bytes_ / bw


def roofline_split(flops: float, hbm_bytes: float, machine: MachineSpec,
                   degree: float = 1, bytes_predivided: bool = False
                   ) -> Tuple[float, float]:
    """The two legs of the per-chip roofline for 1/degree of one training
    step's work over an op: (t_flop, t_mem). fwd+bwd ≈ 3x fwd flops
    (reference simulator models fwd and bwd tasks separately; the 3x is the
    standard dense-training ratio); HBM traffic ≈ 2x the forward bytes.
    When bytes_predivided, hbm_bytes is already the per-device traffic.
    compute_time takes the max; the attribution layer
    (flexflow_tpu/attribution.py) reads both legs to classify each op as
    compute-bound vs bandwidth-bound and derive its MFU ceiling."""
    d = max(1.0, degree)
    eff_flops = machine.flops / machine.mxu_flop_overhead
    t_flop = 3.0 * flops / d / eff_flops
    t_mem = 2.0 * hbm_bytes / (1.0 if bytes_predivided else d) / machine.hbm_bw
    return t_flop, t_mem


def compute_time(flops: float, hbm_bytes: float, machine: MachineSpec,
                 degree: float = 1, bytes_predivided: bool = False) -> float:
    """Roofline on one chip: max of the compute and memory legs (see
    roofline_split)."""
    t_flop, t_mem = roofline_split(flops, hbm_bytes, machine, degree,
                                   bytes_predivided)
    return max(t_flop, t_mem)


def op_roofline(layer, cand, machine: MachineSpec) -> Dict[str, float]:
    """Per-op roofline facts for one (layer, candidate placement): the
    machine-bound minimum time for this op's fwd+bwd work, which leg binds,
    and the MFU ceiling the roofline permits. This is the query ISSUE 7's
    attribution joins against measured per-op times — `mfu_ceiling` is what
    a perfectly-scheduled kernel could reach (1.0 when compute-bound at
    peak, < 1 when HBM bandwidth caps it), so measured_mfu / mfu_ceiling
    isolates scheduling loss from roofline loss."""
    flops, hbm_bytes, degree = cand.flops_bytes(layer, machine)
    t_flop, t_mem = roofline_split(flops, hbm_bytes, machine, degree,
                                   bytes_predivided=True)
    t = max(t_flop, t_mem)
    # flops/s the roofline bound sustains, over the chip's PEAK (not the
    # overhead-derated rate the bound itself uses)
    dev_flops = 3.0 * flops / max(1.0, degree)
    return {
        "flops": flops,
        "device_flops": dev_flops,
        "hbm_bytes": hbm_bytes,
        "degree": degree,
        "roofline_s": t,
        "t_flop_s": t_flop,
        "t_mem_s": t_mem,
        "bound": "bandwidth" if t_mem > t_flop else "compute",
        "mfu_ceiling": (dev_flops / (t * machine.flops)) if t > 0 else 0.0,
    }


def overlapped_step_cost(comp: float, comm: float, machine: MachineSpec) -> float:
    """One layer's contribution under compute/comm overlap (the closed-form
    stand-in for the reference's event-driven concurrent replay,
    simulator.h:785-827): XLA's async collectives + latency-hiding scheduler
    hide collective time behind up to machine.overlap_frac of the consumer's
    pure compute; only the residual serializes. overlap_frac=0 degenerates
    to additive costing. Calibrated by tools/calibrate.py (CALIBRATION.md)."""
    return comp + max(0.0, comm - machine.overlap_frac * comp)


def reshard_time(spec: TensorSpec, src: Sequence[DimSharding],
                 dst: Sequence[DimSharding], machine: MachineSpec) -> float:
    """Cost of moving a tensor from layout src to dst — the price of a
    parallel op (Repartition/Combine/Replicate/AllToAll) on this machine.

    Interned by (tensor geometry, src, dst, machine) — the DP's edge costs
    are the hottest call in the search and structural twins re-price the
    same transitions constantly (search/memo.py, tier 2)."""
    if memo.enabled():
        key = (spec.ndim, spec.size_bytes, memo.freeze_dims(src),
               memo.freeze_dims(dst), memo.machine_fingerprint(machine))
        t = memo.get("reshard", key)
        if t is not memo.MISS:
            return t
        return memo.put("reshard", key, _reshard_time(spec, src, dst, machine))
    return _reshard_time(spec, src, dst, machine)


def _reshard_time(spec: TensorSpec, src: Sequence[DimSharding],
                  dst: Sequence[DimSharding], machine: MachineSpec) -> float:
    nd = spec.ndim
    src = list(src or [None] * nd) + [None] * (nd - len(src or []))
    dst = list(dst or [None] * nd) + [None] * (nd - len(dst or []))
    if [_axes_of(a) for a in src] == [_axes_of(a) for a in dst]:
        return 0.0
    t = 0.0
    moved_axes = set()
    src_all = {a for d in src for a in _axes_of(d)}
    dst_all = {a for d in dst for a in _axes_of(d)}
    for i in range(nd):
        sa, da = set(_axes_of(src[i])), set(_axes_of(dst[i]))
        # axis moved to a different dim → all_to_all over that axis
        for a in sa - da:
            if a in dst_all:
                t += all_to_all_time(shard_bytes(spec, src, machine), (a,), machine)
                moved_axes.add(a)
    # axes fully removed (not present anywhere in dst) → all_gather
    gone = src_all - dst_all - moved_axes
    if gone:
        t += all_gather_time(spec.size_bytes / max(1, dims_degree(
            [None if set(_axes_of(d)) <= gone else d for d in src], machine)),
            tuple(gone), machine)
    # axes newly added where tensor was replicated → local slice (free)
    return t


# ------------------------------------------------------- pipeline costing
def p2p_time(bytes_: float, machine: MachineSpec, axis: str = "pipe") -> float:
    """One neighbor-hop point-to-point transfer (a stage-boundary activation
    or its gradient crossing the pipe axis). Unlike the ring collectives
    there is no (k-1)/k factor: the tensor moves once over one link. The
    pipe axis usually isn't in mesh_axes (stages are disjoint SUB-meshes,
    not an axis of one mesh) — axis_bw falls back to the chip's ICI rate."""
    return bytes_ / machine.axis_bw(axis)


def pipeline_schedule(schedule: str, num_stages: int, num_micro: int):
    """Tick grid of a pipeline schedule: a list of ticks, each a list of
    (stage, phase, microbatch) with phase "F" (forward) or "B" (backward).
    Ops in one tick run concurrently (each stage appears at most once per
    tick); dependencies are F(s,m) after F(s-1,m) and B(s,m) after both
    F(s,m) and B(s+1,m). This grid is the ONE schedule definition shared by
    the runtime executor (parallel/pipeline.py), the event replay
    (search/simulator.py simulate_pipeline) and the bench's measured-bubble
    accounting — schedule semantics cannot drift between pricing and
    execution.

      gpipe: every stage runs all M forwards, then all M backwards (M
             in-flight stashed activations per stage — GPipe, Huang et al.).
      1f1b:  stage s warms up with (S-1-s) forwards then alternates one
             backward / one forward (PipeDream-flush / JaxPP's default);
             at most S in-flight activations, same (S-1)/(M+S-1) bubble.
    """
    S, M = num_stages, num_micro
    order = pipeline_order(schedule, S, M)
    done: Dict[Tuple[str, int, int], int] = {}
    idx = [0] * S
    ticks = []
    while any(idx[s] < len(order[s]) for s in range(S)):
        row = []
        for s in range(S):
            if idx[s] >= len(order[s]):
                continue
            ph, m = order[s][idx[s]]
            if ph == "F":
                ok = s == 0 or done.get(("F", s - 1, m), 10 ** 9) < len(ticks)
            else:
                ok = done.get(("F", s, m), 10 ** 9) < len(ticks) and (
                    s == S - 1
                    or done.get(("B", s + 1, m), 10 ** 9) < len(ticks))
            if ok:
                row.append((s, ph, m))
        if not row:
            raise RuntimeError("pipeline schedule deadlocked "
                               f"({schedule}, S={S}, M={M})")
        for s, ph, m in row:
            done[(ph, s, m)] = len(ticks)
            idx[s] += 1
        ticks.append(row)
    return ticks


def pipeline_order(schedule: str, num_stages: int, num_micro: int):
    """Per-stage op execution order: {stage: [(phase, microbatch), ...]}.
    Each stage is one serial resource (a device group runs one kernel at a
    time); the schedule IS this per-stage order plus the data dependencies
    F(s,m) -> F(s+1,m) -> ... -> B(s+1,m) -> B(s,m)."""
    S, M = num_stages, num_micro
    if schedule not in ("gpipe", "1f1b"):
        raise ValueError(f"unknown pipeline schedule {schedule!r}")
    order: Dict[int, list] = {}
    for s in range(S):
        if schedule == "gpipe":
            ops = [("F", m) for m in range(M)] + [("B", m) for m in range(M)]
        else:
            warm = min(S - 1 - s, M)
            ops = [("F", m) for m in range(warm)]
            nf, nb = warm, 0
            while nb < M:
                if nf < M:
                    ops.append(("F", nf))
                    nf += 1
                ops.append(("B", nb))
                nb += 1
        order[s] = ops
    return order


def pipeline_timeline(schedule: str, num_micro: int,
                      fwd_times: Sequence[float],
                      bwd_times: Sequence[float],
                      p2p: float = 0.0):
    """Event-driven replay of a schedule on per-stage serial timelines:
    op start = max(stage free time, producer finish + p2p hop); returns
    (makespan, {(phase, stage, micro): (start, end)}). This is the
    LogicalTaskgraphBasedSimulator analog for the pipe dimension — stages
    are NOT lockstepped (a tick-grid max would charge 1f1b's F/B
    interleaving for the fwd/bwd duration mismatch that real async
    execution never pays)."""
    S = len(fwd_times)
    order = pipeline_order(schedule, S, num_micro)
    fin: Dict[Tuple[str, int, int], float] = {}
    events: Dict[Tuple[str, int, int], Tuple[float, float]] = {}
    avail = [0.0] * S
    idx = [0] * S
    pending = sum(len(o) for o in order.values())
    while pending:
        progressed = False
        for s in range(S):
            while idx[s] < len(order[s]):
                ph, m = order[s][idx[s]]
                if ph == "F":
                    dep = 0.0 if s == 0 else fin.get(("F", s - 1, m))
                else:
                    up = 0.0 if s == S - 1 else fin.get(("B", s + 1, m))
                    mine = fin.get(("F", s, m))
                    dep = None if (up is None or mine is None) \
                        else max(up, mine)
                if dep is None:
                    break  # producer not scheduled yet; revisit next sweep
                start = max(avail[s], dep + (p2p if dep > 0.0 else 0.0))
                dur = fwd_times[s] if ph == "F" else bwd_times[s]
                fin[(ph, s, m)] = start + dur
                events[(ph, s, m)] = (start, start + dur)
                avail[s] = start + dur
                idx[s] += 1
                pending -= 1
                progressed = True
        if not progressed:
            raise RuntimeError(f"pipeline schedule deadlocked ({schedule})")
    return max(avail), events


def pipeline_span(schedule: str, num_micro: int, fwd_times: Sequence[float],
                  bwd_times: Sequence[float], p2p: float = 0.0) -> float:
    return pipeline_timeline(schedule, num_micro, fwd_times, bwd_times,
                             p2p)[0]


def pipeline_bubble(schedule: str, num_micro: int, fwd_times: Sequence[float],
                    bwd_times: Sequence[float], p2p: float = 0.0) -> float:
    """Idle fraction of the S x span stage-time area under the event-driven
    replay: 1 - total_work / (S * span). For balanced stages this reduces
    to the closed form (S-1)/(M+S-1) for BOTH schedules (1f1b's advantage
    is in-flight activation memory, not bubble)."""
    S = len(fwd_times)
    span = pipeline_span(schedule, num_micro, fwd_times, bwd_times, p2p)
    if span <= 0.0:
        return 0.0
    work = num_micro * sum(fwd_times[s] + bwd_times[s] for s in range(S))
    return max(0.0, 1.0 - work / (S * span))


def pipeline_bubble_fraction(schedule: str, num_stages: int,
                             num_micro: int) -> float:
    """Closed-form bubble of a BALANCED pipeline: (S-1)/(M+S-1) for gpipe
    and (non-interleaved) 1f1b alike — the quick-estimate companion to the
    exact tick-grid pipeline_bubble."""
    S, M = num_stages, num_micro
    if S <= 1 or M <= 0:
        return 0.0
    return (S - 1) / (M + S - 1)


def pipeline_inflight_acts(schedule: str, num_stages: int,
                           num_micro: int) -> int:
    """Peak number of stashed boundary activations a stage holds: M under
    gpipe (all forwards complete before any backward frees), min(S, M)
    under 1f1b (each backward frees its stash before the next forward)."""
    return num_micro if schedule == "gpipe" else min(num_stages, num_micro)


def pipeline_phase_times(stage_costs: Sequence[float]):
    """Per-phase durations of the schedule the EXECUTOR actually runs
    (parallel/pipeline.py), from whole-stage step costs (1x fwd + 2x bwd
    flops, compute_time's 3x convention): the forward slot is c/3; the
    backward slot is a FULL c because it is recompute-based (jax.vjp
    re-runs the stage forward from the stashed input — flash-attention
    style, the price of stashing one input instead of every interior
    activation). The last stage's forward slot is free (loss+grad fuse
    into its backward via value_and_grad, which shares the forward pass —
    no recompute there). Keep this in lockstep with
    PipelinedModel._build_stage_fns or predicted bubbles drift from
    measured ones (tools/bench_pipeline.py asserts 25%)."""
    fwd = [c / 3.0 for c in stage_costs]
    bwd = [float(c) for c in stage_costs]
    fwd[-1] = 0.0
    return fwd, bwd


# --------------------------------------------------------- remat costing
# Per-layer rematerialization policies the DP searches over (ISSUE 12):
# policy -> (recompute_frac, keep_frac).
#   recompute_frac — extra time the backward pays, as a fraction of the
#     op's 3x-roofline step cost. "full" re-runs the layer forward once
#     (= c/3 of the fwd+bwd cost — the same recompute convention
#     pipeline_phase_times charges its recompute-based backward slots);
#     "dots" keeps matmul outputs and re-runs only the cheap elementwise
#     tail (jax.checkpoint_policies.checkpoint_dots), ~a quarter of a
#     forward.
#   keep_frac — fraction of the layer's BACKWARD-stash residency that
#     survives until the backward pass. The DP's live-activation
#     accounting charges a forward value (mult 1) plus a backward stash
#     (mult act_mult-1, normally 1): "none" keeps the whole stash,
#     "dots" roughly half (dot outputs saved, elementwise recomputed),
#     "full" none of it — only the layer INPUT (already charged as the
#     producer's output) is saved.
REMAT_POLICY_SPECS: Dict[str, Tuple[float, float]] = {
    "none": (0.0, 1.0),
    "dots": (1.0 / 12.0, 0.5),
    "full": (1.0 / 3.0, 0.0),
}


def remat_recompute_time(op_time_s: float, policy: str) -> float:
    """Extra backward-pass time a remat policy adds to one op: the
    recompute fraction of its (3x-roofline) step cost."""
    return REMAT_POLICY_SPECS[policy][0] * op_time_s


def remat_act_mult(policy: str, act_mult: float) -> float:
    """Effective live-bytes multiplier for a remat'd layer's outputs: the
    forward value (1) plus the surviving fraction of the backward stash
    (act_mult - 1). none: act_mult unchanged; full: 1 (value only);
    dots: halfway. Inference (act_mult=1) is a fixed point — remat can't
    save memory where no stash exists."""
    return 1.0 + REMAT_POLICY_SPECS[policy][1] * (act_mult - 1.0)


def pipeline_step_time(fwd_times: Sequence[float], bwd_times: Sequence[float],
                       boundary_bytes: Sequence[float], machine: MachineSpec,
                       schedule: str, num_micro: int) -> float:
    """Predicted wall time of ONE pipeline step (= one optimizer update
    over `num_micro` microbatches): the event-driven makespan over
    per-stage per-microbatch fwd/bwd times, plus every boundary crossing
    priced as a neighbor-hop P2P (activation forward + activation-gradient
    backward, once per microbatch per boundary)."""
    t = pipeline_span(schedule, num_micro, list(fwd_times), list(bwd_times))
    t += sum(2.0 * num_micro * p2p_time(b, machine) for b in boundary_bytes)
    return t


def grad_sync_time(weight_specs: Dict[str, TensorSpec],
                   weight_dims: Dict[str, List[DimSharding]],
                   machine: MachineSpec, batch_axes: Sequence[str],
                   zero: bool = False) -> float:
    """Gradient sync over the replica axes of each weight (reference:
    ncclAllReduce fused into the optimizer update, optimizer_kernel.cu:88).
    `zero` prices the ZeRO rewrite instead — reduce-scatter(grads) +
    all-gather(updates); both tensors are param-sized, so on a ring the
    total volume EQUALS the all-reduce's (the ZeRO win is memory, not
    step-time comm — keep the two terms equal or the DP's compute/comm
    overlap split in dp.py drifts from Candidate.op_time's internal sync
    term). Interned by (weight geometry, layouts, machine) — see memo.py."""
    if not weight_specs:
        return 0.0
    if memo.enabled():
        key = (memo.freeze_weight_specs(weight_specs),
               tuple(sorted((w, memo.freeze_dims(d))
                            for w, d in weight_dims.items())),
               tuple(batch_axes), zero, memo.machine_fingerprint(machine))
        t = memo.get("grad_sync", key)
        if t is not memo.MISS:
            return t
        return memo.put("grad_sync", key, _grad_sync_time(
            weight_specs, weight_dims, machine, batch_axes, zero))
    return _grad_sync_time(weight_specs, weight_dims, machine, batch_axes,
                           zero)


def _grad_sync_time(weight_specs, weight_dims, machine, batch_axes,
                    zero=False) -> float:
    t = 0.0
    for w, spec in weight_specs.items():
        dims = weight_dims.get(w, [None] * spec.ndim)
        used = {a for d in dims for a in _axes_of(d)}
        replica_axes = tuple(a for a in batch_axes if a not in used)
        if not replica_axes:
            continue
        b = shard_bytes(spec, dims, machine)
        if zero:
            # grads scatter down at full size, the param-dtype updates
            # gather back up — same ring volume as the fused all-reduce
            t += reduce_scatter_time(b, replica_axes, machine) \
                + all_gather_time(b, replica_axes, machine)
        else:
            t += all_reduce_time(b, replica_axes, machine)
    return t
