"""Elementwise unary/binary ops and cast.

Reference analog: src/ops/element_unary.cc (720 LoC), element_binary.cc (812),
cast.cc (366) + their CUDA kernels. On TPU these are single jnp calls that XLA
fuses into neighbors; no hand-written kernels needed (VPU ops).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from typing import TYPE_CHECKING
if TYPE_CHECKING:
    from flexflow_tpu.core.layer import Layer
from flexflow_tpu.core.tensor import TensorSpec
from flexflow_tpu.dtype import DataType
from flexflow_tpu.ops.op_type import OperatorType, UNARY_OPS, BINARY_OPS
from flexflow_tpu.ops.registry import register_op


_UNARY_FNS = {
    OperatorType.RELU: jax.nn.relu,
    OperatorType.IDENTITY: lambda x: x,
    OperatorType.SIGMOID: jax.nn.sigmoid,
    OperatorType.TANH: jnp.tanh,
    OperatorType.ELU: jax.nn.elu,
    OperatorType.GELU: jax.nn.gelu,
    OperatorType.EXP: jnp.exp,
    OperatorType.LOG: jnp.log,
    OperatorType.SIN: jnp.sin,
    OperatorType.COS: jnp.cos,
    OperatorType.SQRT: jnp.sqrt,
    OperatorType.RSQRT: jax.lax.rsqrt,
    OperatorType.SILU: jax.nn.silu,
    OperatorType.ERF: jax.lax.erf,
}


def _unary_infer(layer: Layer):
    return [layer.inputs[0].spec]


def _unary_lower(layer: Layer, inputs, weights, ctx):
    x = inputs[0]
    t = layer.op_type
    if t is OperatorType.POW:
        return [jnp.power(x, layer.params["exponent"])]
    if t is OperatorType.SCALAR_MULTIPLY:
        return [x * layer.params["scalar"]]
    if t is OperatorType.SCALAR_ADD:
        return [x + layer.params["scalar"]]
    if t is OperatorType.SCALAR_SUB:
        return [x - layer.params["scalar"]]
    if t is OperatorType.SCALAR_TRUE_DIV:
        return [x / layer.params["scalar"]]
    if t is OperatorType.SCALAR_FLOOR_DIV:
        return [jnp.floor_divide(x, layer.params["scalar"])]
    return [_UNARY_FNS[t](x)]


for _t in UNARY_OPS:
    register_op(_t, _unary_infer, _unary_lower)


_BINARY_FNS = {
    OperatorType.EW_ADD: jnp.add,
    OperatorType.EW_SUB: jnp.subtract,
    OperatorType.EW_MUL: jnp.multiply,
    OperatorType.EW_DIV: jnp.divide,
    OperatorType.EW_MAX: jnp.maximum,
    OperatorType.EW_MIN: jnp.minimum,
    OperatorType.EW_EQUAL: jnp.equal,
    OperatorType.EW_GREATER: jnp.greater,
    OperatorType.EW_LESS: jnp.less,
}

_BOOL_OUT = {OperatorType.EW_EQUAL, OperatorType.EW_GREATER, OperatorType.EW_LESS}


def _binary_infer(layer: Layer):
    a, b = layer.inputs[0].spec, layer.inputs[1].spec
    shape = jnp.broadcast_shapes(a.shape, b.shape)
    dtype = DataType.BOOL if layer.op_type in _BOOL_OUT else a.dtype
    return [TensorSpec(shape, dtype)]


def _binary_lower(layer: Layer, inputs, weights, ctx):
    return [_BINARY_FNS[layer.op_type](inputs[0], inputs[1])]


for _t in BINARY_OPS:
    register_op(_t, _binary_infer, _binary_lower)


def _cast_infer(layer: Layer):
    return [layer.inputs[0].spec.with_dtype(DataType.from_any(layer.params["dtype"]))]


def _cast_lower(layer: Layer, inputs, weights, ctx):
    return [inputs[0].astype(DataType.from_any(layer.params["dtype"]).jnp_dtype)]


register_op(OperatorType.CAST, _cast_infer, _cast_lower)


def _noop_infer(layer: Layer):
    return [layer.inputs[0].spec]


register_op(OperatorType.NOOP, _noop_infer, lambda l, i, w, c: [i[0]])
register_op(OperatorType.INPUT, _noop_infer, lambda l, i, w, c: [i[0]])
