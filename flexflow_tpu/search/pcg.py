"""Parallel Computation Graph — the substitution engine's working IR.

Reference analog: `Graph` of `Node{guid, Op*}` (include/flexflow/graph.h:
293-360) on which GraphXfer rewrites operate. Here the PCG is a *clone* of
the model's layer graph (so rewrites never mutate the user's model), where
parallel ops (Repartition/Combine/Replicate/Reduction) are first-class
nodes inserted and removed by rewrites, and compute nodes can carry a
**pin**: the name of the sharding candidate (search/candidates.py) the
rewrite chose for them. Costing a PCG = running the frontier DP
(search/dp.py) with pinned nodes restricted to their pinned candidate.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Tuple

from flexflow_tpu.core.graph import topo_order
from flexflow_tpu.core.layer import Layer
from flexflow_tpu.core.tensor import Tensor
from flexflow_tpu.ops.op_type import PARALLEL_OPS, OperatorType


@dataclasses.dataclass
class PCG:
    """A candidate parallel computation graph: cloned layers + layout pins."""

    layers: List[Layer]
    input_tensors: List[Tensor]
    pins: Dict[str, str] = dataclasses.field(default_factory=dict)  # layer name -> candidate name

    # ------------------------------------------------------------ construction
    @staticmethod
    def from_model(model) -> "PCG":
        return PCG.from_layers(model.layers, model.input_tensors)

    @staticmethod
    def from_layers(layers, input_tensors) -> "PCG":
        tmap: Dict[int, Tensor] = {}
        new_inputs = []
        for t in input_tensors:
            nt = Tensor(t.spec, name=t.name)
            tmap[t.guid] = nt
            new_inputs.append(nt)
        new_layers: List[Layer] = []
        for l in topo_order(layers):
            nl = Layer(l.op_type, l.params, [tmap[t.guid] for t in l.inputs], name=l.name)
            nl.weight_specs = dict(l.weight_specs)
            if hasattr(l, "branches"):  # fork_join sub-graphs (read-only)
                nl.branches = l.branches
            for i, o in enumerate(l.outputs):
                tmap[o.guid] = nl.add_output(o.spec, i, name=o.name)
            new_layers.append(nl)
        return PCG(new_layers, new_inputs)

    def clone(self) -> "PCG":
        g = PCG.from_layers(self.layers, self.input_tensors)
        g.pins = dict(self.pins)
        return g

    # -------------------------------------------------------------- structure
    def consumers(self, tensor: Tensor) -> List[Tuple[Layer, int]]:
        out = []
        for l in self.layers:
            for i, t in enumerate(l.inputs):
                if t.guid == tensor.guid:
                    out.append((l, i))
        return out

    def layer_by_name(self, name: str) -> Layer:
        for l in self.layers:
            if l.name == name:
                return l
        raise KeyError(name)

    def insert_after(self, tensor: Tensor, op_type: OperatorType,
                     params: Dict, name: Optional[str] = None) -> Layer:
        """Insert a (parallel) op consuming `tensor`; every existing consumer
        of `tensor` is rewired to the new op's output. Reference analog:
        parallel-op node insertion in GraphXfer::run (substitution.cc:596)."""
        node = Layer(op_type, params, [tensor], name=name)
        node.add_output(tensor.spec, 0)
        cons = self.consumers(tensor)
        for l, i in cons:
            l.inputs[i] = node.outputs[0]
        # place right after the producer in the list (topo order preserved)
        if tensor.owner is not None:
            idx = self.layers.index(tensor.owner) + 1
        else:
            idx = 0
        self.layers.insert(idx, node)
        return node

    def remove_identity(self, node: Layer):
        """Remove a single-input single-output node, rewiring its consumers
        to its input (parallel-op elimination rules)."""
        assert len(node.inputs) == 1 and len(node.outputs) == 1
        src = node.inputs[0]
        for l, i in self.consumers(node.outputs[0]):
            l.inputs[i] = src
        self.layers.remove(node)
        self.pins.pop(node.name, None)

    # ------------------------------------------------------------------- keys
    def key(self) -> Tuple:
        """Canonical structural identity for visited-set dedup (name-free so
        two applications producing isomorphic graphs collide)."""
        order = topo_order(self.layers)
        idx = {l: i for i, l in enumerate(order)}
        in_idx = {t.guid: i for i, t in enumerate(self.input_tensors)}
        rows = []
        for l in order:
            ins = []
            for t in l.inputs:
                if t.owner is not None and t.owner in idx:
                    ins.append((idx[t.owner], t.owner_idx))
                else:
                    ins.append((-1, in_idx.get(t.guid, -9)))
            rows.append((l.op_type.value, _freeze(l.params), tuple(ins),
                         self.pins.get(l.name)))
        return tuple(rows)

    @property
    def num_parallel_nodes(self) -> int:
        return sum(1 for l in self.layers if l.op_type in PARALLEL_OPS)

    def to_dot(self) -> str:
        from flexflow_tpu.core.graph import to_dot

        ann = {l: f"pin={self.pins[l.name]}" for l in self.layers if l.name in self.pins}
        return to_dot(self.layers, ann)


def _freeze(v):
    if isinstance(v, dict):
        return tuple(sorted((k, _freeze(x)) for k, x in v.items()))
    if isinstance(v, (list, tuple)):
        return tuple(_freeze(x) for x in v)
    if hasattr(v, "tobytes") and hasattr(v, "shape"):  # ndarray constants
        return (tuple(v.shape), str(getattr(v, "dtype", "")), v.tobytes())
    return v
